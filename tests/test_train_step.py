"""Fused learner-step tests.

The key test is the *naive oracle*: the masked/gathered static-shape loss must
equal a literal per-sequence Python transcription of the reference learner's
ragged computation (/root/reference/worker.py:330-346, model.py:89-157) run
sequence by sequence with true lengths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import NetworkConfig, OptimConfig
from r2d2_tpu.learner import create_train_state, make_learner_step, make_loss_fn
from r2d2_tpu.models import init_network
from r2d2_tpu.ops.value import inverse_value_rescale, value_rescale
from r2d2_tpu.replay import ReplaySpec, replay_add, replay_init
from r2d2_tpu.replay.device_replay import replay_sample

from tests.test_replay import A, _fill_blocks, make_spec

OPT = OptimConfig(lr=1e-3, target_net_update_interval=5)


def _net(spec: ReplaySpec, use_double=False, seed=0):
    # 12x12 test frames: Nature convs would shrink to zero, use a small torso
    cfg = NetworkConfig(hidden_dim=spec.hidden_dim, cnn_out_dim=16,
                        use_double=use_double,
                        conv_layers=((8, 4, 2), (16, 3, 1)))
    return init_network(jax.random.PRNGKey(seed), A, cfg,
                        frame_stack=spec.frame_stack,
                        frame_height=spec.frame_height,
                        frame_width=spec.frame_width)


def _filled_replay(spec, rng, n_blocks=3):
    state = replay_init(spec)
    for blk in _fill_blocks(spec, n_blocks, rng):
        state = replay_add(spec, state, blk)
    return state


@pytest.mark.slow
def test_fused_double_unroll_matches_sequential(rng):
    """optim.fused_double_unroll=on (one scan interleaving the online and
    target chains) must reproduce the sequential two-unroll double-DQN
    loss, gradients, and priorities exactly — only the loop structure
    changes (VERDICT r3 #3 forcing mechanism)."""
    import dataclasses

    spec = make_spec(batch_size=6)
    net, _ = _net(spec, use_double=True)
    ts = create_train_state(jax.random.PRNGKey(2), net, OPT)
    # distinct target params so the target chain is actually exercised
    target = net.init(jax.random.PRNGKey(77))
    rs = _filled_replay(spec, rng)
    batch = replay_sample(spec, rs, jax.random.PRNGKey(5))

    losses, grads_all, prios = [], [], []
    for fused in ("off", "on"):
        opt = dataclasses.replace(OPT, fused_double_unroll=fused)
        loss_fn = make_loss_fn(net, spec, opt, use_double=True)
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            ts.params, target, batch)
        losses.append(float(loss))
        grads_all.append(grads)
        prios.append(np.asarray(aux["priorities"]))

    assert losses[0] == losses[1]
    np.testing.assert_array_equal(prios[0], prios[1])
    for a, b in zip(jax.tree_util.tree_leaves(grads_all[0]),
                    jax.tree_util.tree_leaves(grads_all[1])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_learner_step_runs_and_updates(rng):
    spec = make_spec(batch_size=8)
    net, params = _net(spec)
    ts = create_train_state(jax.random.PRNGKey(1), net, OPT)
    rs = _filled_replay(spec, rng)
    tree_before = np.asarray(rs.tree).copy()
    # the step donates its inputs (in-place HBM update) — snapshot first
    params_before = jax.tree_util.tree_map(np.asarray, ts.params)

    step = make_learner_step(net, spec, OPT, use_double=False)
    ts2, rs2, metrics = step(ts, rs)

    assert int(ts2.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["grad_norm"]) > 0
    # params actually moved
    delta = jax.tree_util.tree_reduce(
        lambda acc, x: acc + float(jnp.sum(jnp.abs(x))),
        jax.tree_util.tree_map(lambda a, b: np.asarray(a) - b, ts2.params,
                               params_before), 0.0)
    assert delta > 0
    # priority tree was rewritten by the fused step
    assert not np.allclose(np.asarray(rs2.tree), tree_before)


@pytest.mark.slow
def test_double_dqn_target_sync(rng):
    """Target params stay frozen until step % interval == 0, then hard-sync
    (ref worker.py:375-377)."""
    spec = make_spec(batch_size=8)
    net, _ = _net(spec, use_double=True)
    opt = OptimConfig(lr=1e-3, target_net_update_interval=3)
    ts = create_train_state(jax.random.PRNGKey(1), net, opt)
    rs = _filled_replay(spec, rng)
    step = make_learner_step(net, spec, opt, use_double=True)

    t0 = jax.tree_util.tree_map(np.asarray, ts.target_params)
    for i in range(1, 4):
        ts, rs, _ = step(ts, rs)
        sync = jax.tree_util.tree_all(jax.tree_util.tree_map(
            lambda a, b: np.allclose(np.asarray(a), np.asarray(b)),
            ts.target_params, ts.params))
        if i < 3:
            frozen = jax.tree_util.tree_all(jax.tree_util.tree_map(
                lambda a, b: np.allclose(np.asarray(a), b),
                ts.target_params, t0))
            assert frozen and not sync
        else:
            assert sync


@pytest.mark.slow
def test_loss_decreases_on_fixed_replay(rng):
    """End-to-end training signal: repeated steps on a static buffer must
    drive the TD loss down (the jitted path actually learns)."""
    spec = make_spec(batch_size=16)
    net, _ = _net(spec)
    ts = create_train_state(jax.random.PRNGKey(2), net, OPT)
    rs = _filled_replay(spec, rng, n_blocks=4)
    step = make_learner_step(net, spec, OPT, use_double=False)

    losses = []
    for _ in range(30):
        ts, rs, m = step(ts, rs)
        losses.append(float(m["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7, losses


@pytest.mark.slow
def test_loss_matches_naive_ragged_oracle(rng):
    """Golden parity: static-shape masked loss == per-sequence ragged loop."""
    spec = make_spec(batch_size=6)
    net, params = _net(spec)
    rs = _filled_replay(spec, rng)
    batch = replay_sample(spec, rs, jax.random.PRNGKey(3))

    loss_fn = make_loss_fn(net, spec, OPT, use_double=False)
    loss, aux = loss_fn(params, params, batch)

    # ---- naive oracle ----
    obs = np.asarray(batch.obs, np.float32) / 255.0
    la = np.asarray(batch.last_action)
    K, W = spec.frame_stack, spec.seq_window
    total, num = 0.0, 0
    for b in range(spec.batch_size):
        burn = int(batch.burn_in_steps[b]); learn = int(batch.learning_steps[b])
        fwd = int(batch.forward_steps[b]); seq_len = burn + learn + fwd
        # stack frames then unroll ONLY the true seq_len steps
        stacked = np.stack([obs[b, t : t + K] for t in range(seq_len)])  # (T,K,H,W)
        stacked = stacked.transpose(0, 2, 3, 1)[None]
        onehot = jax.nn.one_hot(la[b, :seq_len], A)[None]
        q, _ = net.apply(params, jnp.asarray(stacked), onehot,
                         batch.hidden[b : b + 1])
        q = np.asarray(q[0])                                   # (seq_len, A)
        # reference slice-then-edge-pad for the t+n outputs (model.py:110-118)
        sel = list(range(burn + spec.forward, seq_len))
        sel += [seq_len - 1] * min(spec.forward - fwd, learn)
        q_next = q[sel].max(axis=1)                            # (learn,)
        r = np.asarray(batch.reward[b, :learn])
        g = np.asarray(batch.gamma[b, :learn])
        tgt = value_rescale(jnp.asarray(r + g * np.asarray(
            inverse_value_rescale(jnp.asarray(q_next)))))
        q_chosen = q[np.arange(burn, burn + learn),
                     np.asarray(batch.action[b, :learn])]
        td = np.asarray(tgt) - q_chosen
        total += float(batch.is_weights[b]) * float((td**2).sum())
        num += learn
    naive_loss = 0.5 * total / num

    assert float(loss) == pytest.approx(naive_loss, rel=2e-4)


@pytest.mark.slow
def test_multi_step_dispatch_matches_single_steps(rng):
    """K fused steps per dispatch (lax.scan) must reproduce K sequential
    single-step dispatches exactly — same RNG chain, same updates."""
    from r2d2_tpu.learner import make_multi_learner_step

    spec = make_spec(batch_size=8)
    net, _ = _net(spec)

    ts_a = create_train_state(jax.random.PRNGKey(5), net, OPT)
    rs_a = _filled_replay(spec, np.random.default_rng(0))
    single = make_learner_step(net, spec, OPT, use_double=False)
    losses_a = []
    for _ in range(4):
        ts_a, rs_a, m = single(ts_a, rs_a)
        losses_a.append(float(m["loss"]))

    ts_b = create_train_state(jax.random.PRNGKey(5), net, OPT)
    rs_b = _filled_replay(spec, np.random.default_rng(0))
    multi = make_multi_learner_step(net, spec, OPT, use_double=False,
                                    steps_per_dispatch=4)
    ts_b, rs_b, m = multi(ts_b, rs_b)
    losses_b = [float(x) for x in np.asarray(m["loss"])]

    np.testing.assert_allclose(losses_a, losses_b, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ts_a.params),
                    jax.tree_util.tree_leaves(ts_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(np.asarray(rs_a.tree), np.asarray(rs_b.tree),
                               rtol=1e-5)


@pytest.mark.slow
def test_long_sequence_window_is_config_change(rng):
    """Long-context scaling (SURVEY §5.7): a 4x longer BPTT window — burn-in
    16, learning 20, n-step 4 (window 40 vs the small specs' 12) — is purely
    a spec change; static shapes keep the same compiled structure (scan body
    compiles once regardless of length)."""
    spec = make_spec(burn_in=16, learning=20, forward=4, block_length=40,
                     seqs_per_block=2, batch_size=4)
    net, _ = _net(spec)
    ts = create_train_state(jax.random.PRNGKey(9), net, OPT)
    rs = _filled_replay(spec, rng, n_blocks=2)
    step = make_learner_step(net, spec, OPT, use_double=False)
    ts, rs, m = step(ts, rs)
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_bf16_loss_parity_with_f32(rng):
    """bf16 numeric-safety gate (VERDICT r2 #3): from identical params and
    data, the bf16 compute policy's losses must track the f32 trajectory
    within tolerance across parameter updates (drift included), not just on
    one step. Learning itself is covered by
    test_loss_decreases_on_fixed_replay."""
    spec = make_spec(batch_size=8)

    def build(bf16: bool):
        cfg = NetworkConfig(hidden_dim=spec.hidden_dim, cnn_out_dim=16,
                            bf16=bf16, conv_layers=((8, 4, 2), (16, 3, 1)))
        return init_network(jax.random.PRNGKey(0), A, cfg,
                            frame_stack=spec.frame_stack,
                            frame_height=spec.frame_height,
                            frame_width=spec.frame_width)[0]

    losses = {}
    for bf16 in (False, True):
        net = build(bf16)
        ts = create_train_state(jax.random.PRNGKey(1), net, OPT)
        rs = _filled_replay(spec, np.random.default_rng(0))
        step = make_learner_step(net, spec, OPT, use_double=False)
        run = []
        for _ in range(15):
            ts, rs, m = step(ts, rs)
            run.append(float(m["loss"]))
        losses[bf16] = run

    # first step: same params, same batch — only the compute dtype differs
    assert losses[True][0] == pytest.approx(losses[False][0], rel=2e-2)
    # whole trajectory: drift through 15 parameter updates stays bounded
    np.testing.assert_allclose(losses[True], losses[False], rtol=5e-2)


@pytest.mark.slow
def test_bf16_and_double_compile(rng):
    spec = make_spec(batch_size=4)
    cfg = NetworkConfig(hidden_dim=spec.hidden_dim, cnn_out_dim=16,
                        use_dueling=True, use_double=True, bf16=True,
                        conv_layers=((8, 4, 2), (16, 3, 1)))
    net, _ = _net(spec)  # f32 net for state creation shapes
    from r2d2_tpu.models import init_network as init2
    net16, _ = init2(jax.random.PRNGKey(0), A, cfg,
                     frame_stack=spec.frame_stack,
                     frame_height=spec.frame_height,
                     frame_width=spec.frame_width)
    ts = create_train_state(jax.random.PRNGKey(1), net16, OPT)
    rs = _filled_replay(spec, rng)
    step = make_learner_step(net16, spec, OPT, use_double=True)
    ts, rs, m = step(ts, rs)
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_pallas_lstm_loss_parity_with_scan(rng):
    """network.pallas_lstm numeric-safety gate (same contract as the bf16
    gate above): from identical params and data, the fused-kernel LSTM
    path's losses must track the lax.scan trajectory within tolerance
    across parameter updates. Runs the kernel in interpret mode on the
    CPU mesh via the debug flag (network.pallas_lstm_interpret)."""
    spec = make_spec(batch_size=8)

    def build(plstm: str):
        cfg = NetworkConfig(hidden_dim=spec.hidden_dim, cnn_out_dim=16,
                            pallas_lstm=plstm, pallas_lstm_interpret=True,
                            conv_layers=((8, 4, 2), (16, 3, 1)))
        return init_network(jax.random.PRNGKey(0), A, cfg,
                            frame_stack=spec.frame_stack,
                            frame_height=spec.frame_height,
                            frame_width=spec.frame_width)[0]

    losses = {}
    for plstm in ("off", "on"):
        net = build(plstm)
        ts = create_train_state(jax.random.PRNGKey(1), net, OPT)
        rs = _filled_replay(spec, np.random.default_rng(0))
        step = make_learner_step(net, spec, OPT, use_double=False)
        run = []
        for _ in range(10):
            ts, rs, m = step(ts, rs)
            run.append(float(m["loss"]))
        losses[plstm] = run

    # f32 config: only the bias-fold addition order and matmul accumulation
    # differ — the first step must agree tightly, the trajectory closely
    assert losses["on"][0] == pytest.approx(losses["off"][0], rel=1e-4)
    np.testing.assert_allclose(losses["on"], losses["off"], rtol=1e-2)


def test_exact_gather_train_step_loss_parity(rng):
    """The padded-storage layout (replay.pallas_exact_gather — the TPU
    default since BENCH r4) must be invisible to TRAINING, not just to
    sampling: from identical params and identically-filled replays, the
    fused step's loss trajectory on padded storage is bit-identical to
    the unpadded spec's (the decode strips the pad before any math)."""
    import dataclasses

    spec = make_spec(batch_size=8)
    spec_pad = dataclasses.replace(spec, exact_gather=True)
    assert spec_pad.stored_frame_width == 128

    net, _ = _net(spec)
    losses = {}
    for label, sp in (("plain", spec), ("padded", spec_pad)):
        ts = create_train_state(jax.random.PRNGKey(3), net, OPT)
        rs = replay_init(sp)
        for blk in _fill_blocks(spec, 3, np.random.default_rng(0)):
            rs = replay_add(sp, rs, blk)
        step = make_learner_step(net, sp, OPT, use_double=False)
        run = []
        for _ in range(3):
            ts, rs, m = step(ts, rs)
            run.append(float(m["loss"]))
        losses[label] = run
    assert losses["padded"] == losses["plain"]
