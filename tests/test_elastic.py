"""Elastic-fleet tests (ISSUE 15): the disaggregated replay service
(service-vs-in-mesh parity, spill demote/promote round-trips, lane
routing provenance, the socket rung), the weight fan-out tree (topology
math, stamp propagation incl. the quant bundle, shm relays, lag), the
membership plane (lease/park/adopt/handoff, elastic supervision), the
join/leave chaos grammar, the replay_service telemetry block + the three
fleet alert rules, config round-trip/validation, the service-routed
Learner — and the slow churn drill (leave 25% of a running fleet,
re-join it, zero learner stalls, shard-routing provenance).
"""

import numpy as np
import pytest

from tests.test_replay import _fill_blocks, make_spec

from r2d2_tpu.config import Config
from r2d2_tpu.fleet.fanout import FanoutTree, ShmFanout, tier_sizes
from r2d2_tpu.fleet.membership import (SLOT_ACTIVE, SLOT_FREE, SLOT_PARKED,
                                       FleetMembership)
from r2d2_tpu.fleet.replay_service import (RemoteReplayProducer,
                                           ReplayService,
                                           ReplayServiceServer, SpillTier)
from r2d2_tpu.replay import replay_add, replay_init, replay_sample
from r2d2_tpu.runtime.weights import InProcWeightStore

import jax


def assert_trees_equal(a, b):
    for (path, la), (_, lb) in zip(
            jax.tree_util.tree_leaves_with_path(a),
            jax.tree_util.tree_leaves_with_path(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                      err_msg=str(path))


# ---------------------------------------------------------------------------
# Replay service: parity, routing, spill.


def test_service_round_robin_parity_with_in_mesh_reference(rng):
    """Service-routed replay is BIT-identical to the in-mesh dp path at
    equal routing: N shards fed round-robin hold exactly the per-shard
    states the dp path's sequential reference construction builds
    (the test_anakin_sharded reference pattern)."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 6, rng)
    svc = ReplayService(spec, 2, route="round_robin")
    for blk in blocks:
        svc.add_block(blk)
    refs = [replay_init(spec), replay_init(spec)]
    for k, blk in enumerate(blocks):
        refs[k % 2] = replay_add(spec, refs[k % 2], blk)
    for shard, ref in zip(svc.shards, refs):
        assert_trees_equal(shard.state, ref)


def test_single_shard_service_is_the_in_mesh_path(rng):
    """One shard, no spill = the plain device ring, bit-for-bit,
    sampling included (program identity at equal keys)."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 3, rng)
    svc = ReplayService(spec, 1)
    ref = replay_init(spec)
    for blk in blocks:
        svc.add_block(blk)
        ref = replay_add(spec, ref, blk)
    assert_trees_equal(svc.shards[0].state, ref)
    key = jax.random.PRNGKey(7)
    batch, shard, snapshot = svc.sample(key)
    assert shard == 0 and snapshot == 3
    assert_trees_equal(batch, replay_sample(spec, ref, key))


def test_cold_spill_sample_parity(rng):
    """With the spill tier COLD (no demotions yet) the service's sample
    path is exactly replay_sample — promotion never perturbs a ring
    that has nothing spilled (the acceptance's parity leg)."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 3, rng)   # < num_blocks: no overwrites
    svc = ReplayService(spec, 1, spill_blocks=8, promote_per_sample=2)
    ref = replay_init(spec)
    for blk in blocks:
        svc.add_block(blk)
        ref = replay_add(spec, ref, blk)
    assert svc.shards[0].spill.occupancy == 0
    key = jax.random.PRNGKey(3)
    batch, _, _ = svc.sample(key)
    assert_trees_equal(batch, replay_sample(spec, ref, key))


def test_spill_demote_promote_round_trip(rng):
    """A block demoted from the device ring re-enters it bit-identical
    on promotion: ring row contents after the promote match the
    original block's fields exactly."""
    spec = make_spec(num_blocks=2)
    blocks = _fill_blocks(spec, 4, rng)
    svc = ReplayService(spec, 1, spill_blocks=8, promote_per_sample=0)
    for blk in blocks:
        svc.add_block(blk)
    shard = svc.shards[0]
    # blocks 0 and 1 were overwritten by 2 and 3 — both pages spilled
    assert shard.spill.occupancy == 2
    assert shard.spill.demotions == 2
    promoted = shard.promote(1)   # LRU: block 0 returns first
    assert promoted == 1
    slot = (shard.ring.ptr - 1) % spec.num_blocks
    np.testing.assert_array_equal(
        np.asarray(shard.state.obs[slot]), np.asarray(blocks[0].obs_row))
    np.testing.assert_array_equal(
        np.asarray(shard.state.action[slot]), np.asarray(blocks[0].action))
    assert shard.spill.promotions == 1
    # the promote overwrote block 2's row, demoting IT in turn
    assert shard.spill.occupancy == 2


def test_spill_capacity_scales_past_device_ring(rng):
    """The acceptance geometry: device ring + spill tier sustain >= 2x
    the device-ring block budget as LIVE capacity."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 12, rng)
    svc = ReplayService(spec, 1, spill_blocks=8)
    for blk in blocks:
        svc.add_block(blk)
    assert svc.device_ring_blocks == 4
    assert svc.live_blocks == 12            # 4 resident + 8 spilled
    assert svc.live_blocks >= 2 * svc.device_ring_blocks


def test_spill_thrash_and_interval_accounting(rng):
    """An undersized spill tier evicts un-promoted pages: the interval
    thrash fraction reads 1.0 and resets on read."""
    spec = make_spec(num_blocks=2)
    blocks = _fill_blocks(spec, 6, rng)
    svc = ReplayService(spec, 1, spill_blocks=1, promote_per_sample=0)
    for blk in blocks:
        svc.add_block(blk)
    block = svc.interval_block()
    assert block["spill"]["demotions"] == 4
    assert block["spill"]["evictions"] == 3
    assert block["spill"]["thrash_frac"] == pytest.approx(0.75)
    assert block["spill"]["occupancy"] == 1
    # interval counters reset; a quiet interval reports thrash None
    block2 = svc.interval_block()
    assert block2["spill"]["demotions"] == 0
    assert block2["spill"]["thrash_frac"] is None
    # cumulative hit-rate: 0 promotions over 3 evictions
    assert block["spill"]["hit_rate"] == 0.0


def test_lane_routing_provenance(rng):
    """route='lane': a block lands in shard (lane % num_shards) — the
    provenance invariant the churn drill checks via the PR-10 stamps;
    unstamped blocks (-1) fall back to round-robin."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 6, rng)
    svc = ReplayService(spec, 2, route="lane")
    for k, blk in enumerate(blocks[:4]):
        stamped = blk.replace(lane=np.asarray(k, np.int32))
        assert svc.add_block(stamped) == k % 2
    for shard in svc.shards:
        lanes = np.asarray(shard.state.lane)
        live = lanes[lanes >= 0]
        assert live.size > 0
        assert np.all(live % 2 == shard.index)
    # unstamped: round-robin fallback advances its own counter
    s1 = svc.add_block(blocks[4])
    s2 = svc.add_block(blocks[5])
    assert {s1, s2} == {0, 1}


def test_accountant_facade(rng):
    """The service exposes the Learner's ring contract: summed
    buffer_steps/total_adds and the live generation stamps."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 4, rng)
    svc = ReplayService(spec, 2)
    assert not svc.all_shards_nonempty
    svc.add_block(blocks[0].replace(weight_version=np.asarray(3, np.int32)))
    assert not svc.all_shards_nonempty      # shard 1 still empty
    for blk in blocks[1:]:
        svc.add_block(blk)
    assert svc.all_shards_nonempty
    assert svc.total_adds == 4
    expected = sum(int(np.asarray(b.learning_steps).sum()) for b in blocks)
    assert svc.buffer_steps == expected
    assert 3 in svc.live_versions()


def test_stale_writeback_guard(rng):
    """The reference worker's ring-pointer staleness guard, rebuilt for
    concurrent (socket) producers: a write-back whose sampled rows were
    overwritten since the sample is DROPPED and counted; one with no
    overlap still lands."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 6, rng)
    svc = ReplayService(spec, 1, promote_per_sample=0)
    for blk in blocks[:4]:
        svc.add_block(blk)
    batch, shard, snap = svc.sample(jax.random.PRNGKey(0))
    # a producer's add lands mid-step, overwriting ring row 0
    svc.add_block(blocks[4])
    tds = np.ones(spec.batch_size, np.float32)
    rows = np.asarray(batch.idxes) // spec.seqs_per_block
    tree_before = np.asarray(svc.shards[0].state.tree).copy()
    svc.update_priorities(shard, batch.idxes, tds, adds_snapshot=snap)
    if 0 in rows:                           # sampled the overwritten row
        assert svc.stale_writebacks == 1
        np.testing.assert_array_equal(
            np.asarray(svc.shards[0].state.tree), tree_before)
    else:                                   # disjoint: update lands
        assert svc.stale_writebacks == 0
        assert not np.array_equal(
            np.asarray(svc.shards[0].state.tree), tree_before)
    # unguarded (in-proc) semantics unchanged: no snapshot, no drop
    batch2, shard2, _ = svc.sample(jax.random.PRNGKey(1))
    svc.update_priorities(shard2, batch2.idxes, tds)
    assert svc.stale_writebacks <= 1


def test_service_socket_rung_round_trip(rng):
    """A remote producer's block routed over TCP lands bit-identical to
    a direct add, and the ack carries the routed shard."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 2, rng)
    svc = ReplayService(spec, 2, route="round_robin")
    ref = ReplayService(spec, 2, route="round_robin")
    server = ReplayServiceServer(svc)
    producer = RemoteReplayProducer(server.host, server.port)
    try:
        shards = [producer.add_block(blk) for blk in blocks]
        assert shards == [0, 1]
        assert server.blocks_received == 2
        for blk in blocks:
            ref.add_block(blk)
        for got, want in zip(svc.shards, ref.shards):
            assert_trees_equal(got.state, want.state)
    finally:
        producer.close()
        server.close()


# ---------------------------------------------------------------------------
# Fan-out tree.


def test_tier_sizes_topology():
    assert tier_sizes(4, 4) == []           # root serves them directly
    assert tier_sizes(16, 4) == [4]
    assert tier_sizes(17, 4) == [5, 2]
    assert tier_sizes(100, 4) == [25, 7, 2]
    with pytest.raises(ValueError):
        tier_sizes(8, 1)


def test_fanout_tree_propagates_and_versions():
    """Publish once at the root; every consumer's leaf endpoint serves
    the tree with the ROOT publish count as its version (staleness
    stamps stay on the learner's clock at any depth)."""
    store = InProcWeightStore({"w": np.zeros(3, np.float32)})
    tree = FanoutTree(store, n_consumers=8, degree=2)
    assert tree.depth == 2                  # 8 -> 4 leaves -> 2 mid
    poll, version, current = tree.endpoints(5)
    first = current()
    assert first is not None and version() == store.publish_count
    store.publish({"w": np.ones(3, np.float32)})
    tree.on_publish()
    fresh = poll()
    np.testing.assert_array_equal(fresh["w"], np.ones(3, np.float32))
    assert version() == store.publish_count == 2
    assert poll() is None                   # unchanged: per-reader gate
    assert tree.stats()["max_lag"] == 0


def test_fanout_quant_bundle_rides_unchanged():
    """The stamped int8 inference bundle (ISSUE 14) propagates through
    relays with dtypes and stamp intact — quantized staleness
    accounting works at any tree depth for free."""
    import dataclasses

    from r2d2_tpu.config import NetworkConfig
    from r2d2_tpu.models.network import NetworkApply, make_inference_bundle
    ncfg = dataclasses.replace(
        NetworkConfig(), hidden_dim=8, cnn_out_dim=16,
        conv_layers=((4, 3, 2),), inference_dtype="int8")
    net = NetworkApply(4, ncfg, 2, 12, 12)
    params = net.init(jax.random.PRNGKey(0))
    bundle = jax.device_get(make_inference_bundle(net, params, stamp=5))
    store = InProcWeightStore({"init": np.zeros(1, np.float32)})
    tree = FanoutTree(store, n_consumers=4, degree=2)
    store.publish(bundle)
    tree.on_publish()
    poll, version, _ = tree.endpoints(3)
    got = poll()
    assert int(np.asarray(got["stamp"])) == 5
    int8_leaves = [leaf for leaf in jax.tree_util.tree_leaves(got["quant"])
                   if np.asarray(leaf).dtype == np.int8]
    assert int8_leaves, "quantized twin lost its int8 leaves in transit"
    assert_trees_equal(got, bundle)


def test_fanout_lag_with_pull_interval():
    """With pull-mode relays (nonzero interval) publishes accumulate as
    LAG until a pump — the fanout_lag alert's signal is real."""
    store = InProcWeightStore({"w": np.zeros(2, np.float32)})
    tree = FanoutTree(store, n_consumers=8, degree=2,
                      pull_interval_s=3600.0)
    for _ in range(3):
        store.publish({"w": np.ones(2, np.float32)})
        tree.on_publish()                   # no-op in pull mode
    assert tree.stats()["max_lag"] >= 3
    tree.pump()
    assert tree.stats()["max_lag"] == 0


def test_shm_fanout_round_trip():
    """Process-mode relays: the root publisher's tree reaches a
    subscriber attached to a LEAF relay segment, publish counts
    aligned (zero lag when pumped per publish)."""
    from r2d2_tpu.runtime.weights import WeightPublisher, WeightSubscriber
    params = {"a": np.arange(6, dtype=np.float32).reshape(2, 3)}
    root = WeightPublisher(params)
    fan = ShmFanout(root.name, params, n_consumers=4, degree=2)
    try:
        fan.pump()
        sub = WeightSubscriber(fan.segment_for(3), params)
        try:
            fresh = {"a": np.full((2, 3), 7.0, np.float32)}
            root.publish(fresh)
            fan.pump()
            got = sub.poll()
            np.testing.assert_array_equal(got["a"], fresh["a"])
            assert fan.stats(root.publish_count)["max_lag"] == 0
        finally:
            sub.close()
    finally:
        fan.close()
        root.close()


# ---------------------------------------------------------------------------
# Membership.


def test_membership_lease_park_adopt():
    m = FleetMembership(4, envs_per_slot=4, num_shards=2)
    assert m.active_slots() == [0, 1, 2, 3]
    m.park(1, reason="died")
    m.park(1, reason="died")                # idempotent
    assert m.leaves == 1
    assert m.state(1) == SLOT_PARKED
    lease = m.lease()                       # longest-parked slot first
    assert lease.slot == 1 and lease.generation == 1
    assert lease.lane_base == 4 and lease.lanes == 4
    assert lease.shard_key == 4 % 2
    assert m.state(1) == SLOT_ACTIVE and m.joins == 1
    m.assert_no_overlap()
    with pytest.raises(RuntimeError):
        m.lease(0)                          # ACTIVE slots are held
    with pytest.raises(RuntimeError):
        m.lease()                           # nothing parked, no spares


def test_membership_spare_slots_and_orphans():
    m = FleetMembership(6, envs_per_slot=1, initial_active=4)
    assert m.state(4) == SLOT_FREE
    lease = m.lease()                       # nothing parked: first spare
    assert lease.slot == 4
    ages = np.array([0.0, 500.0, 1.0, 1.0, 0.0, 0.0])
    assert m.orphaned(ages, horizon_s=100.0) == 1
    snap = m.snapshot(ages, orphan_horizon_s=100.0)
    assert snap["active"] == 5 and snap["free"] == 1
    assert snap["orphaned"] == 1 and snap["joins"] == 1


def test_membership_handoff_preserves_identity():
    """Leave → re-adopt hands the SAME lane range to the joiner (the
    no-overlap guarantee is structural: identity derives from the slot
    index, and the lease table forbids duplicates)."""
    m = FleetMembership(3, envs_per_slot=8)
    before = m.lease_of(2)
    m.park(2)
    after = m.lease(2)
    assert after.lane_base == before.lane_base == 16
    assert list(after.lane_range()) == list(before.lane_range())
    assert after.generation == 1
    m.assert_no_overlap()


def test_elastic_supervision_parks_instead_of_respawning():
    """supervise_workers with a park policy: a dead worker's slot parks
    exactly once (no backoff ladder, no respawn), detached slots are
    skipped entirely."""
    from r2d2_tpu.runtime.feeder import WorkerHealth, supervise_workers

    class Dead:
        def is_alive(self):
            return False

    health = WorkerHealth(3)
    parked = []
    workers = [Dead(), Dead(), Dead()]
    health.detach(2)                        # vacant spare: never scanned
    seen = set()

    def park(i, hung):
        parked.append((i, hung))
        health.detach(i)

    n = supervise_workers(workers, seen, respawn=None, health=health,
                          park=park)
    assert n == 0
    assert parked == [(0, False), (1, False)]
    assert health.restarts == 0             # the ladder never engaged
    # second pass: both slots detached now — nothing double-parks
    supervise_workers(workers, seen, respawn=None, health=health, park=park)
    assert parked == [(0, False), (1, False)]
    health.attach(0)
    assert not health.is_detached(0)


# ---------------------------------------------------------------------------
# Chaos grammar.


def test_join_leave_grammar():
    from r2d2_tpu.tools.chaos import parse_fault_spec, parse_join_spec
    spec = "0:leave@block=3;0:join@t=12.5;1:crash@block=2"
    faults = parse_fault_spec(spec)
    joins = parse_join_spec(spec)
    assert faults[0].kind == "leave" and faults[0].block == 3
    assert faults[1].kind == "crash"
    assert joins[0].kind == "join" and joins[0].t == 12.5
    assert 1 not in joins
    for bad in ("0:leave", "0:leave@block=0", "0:join", "0:join@t=-1",
                "0:join@t=1;0:join@t=2"):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)
            parse_join_spec(bad)


def test_leave_fault_ships_last_block_then_departs():
    """leave@block=N emits block N, fires on_leave, THEN raises
    ChaosLeave — the departing worker's experience is never lost."""
    from r2d2_tpu.tools.chaos import ChaosLeave, FaultSpec, apply_fault
    shipped, left = [], []
    sink = apply_fault(shipped.append, FaultSpec("leave", block=2),
                       on_leave=lambda: left.append(True))
    sink("b1")
    assert shipped == ["b1"] and not left
    with pytest.raises(ChaosLeave):
        sink("b2")
    assert shipped == ["b1", "b2"] and left == [True]


def test_leave_fault_scoped_to_the_original_generation(tmp_path):
    """A joiner adopting a slot (generation > 0) must NOT inherit the
    slot's leave fault — otherwise every adoption departs again N
    blocks later and churn measurements see a permanently-narrowed
    fleet. Crash faults DO re-apply (the breaker drills depend on it)."""
    from r2d2_tpu.runtime.actor_loop import instrument_block_sink
    from r2d2_tpu.tools.chaos import ChaosLeave
    cfg = Config().replace(**{
        "actor.num_actors": 2, "fleet.elastic": True,
        "actor.fault_spec": "0:leave@block=1;1:crash@block=1"})
    got = []
    gen0 = instrument_block_sink(cfg, 0, got.append, generation=0)
    with pytest.raises(ChaosLeave):
        gen0(_dummy_block())
    gen1 = instrument_block_sink(cfg, 0, got.append, generation=1)
    gen1(_dummy_block())                    # adopted worker: no fault
    assert len(got) == 2                    # leave ships its block too
    from r2d2_tpu.tools.chaos import ChaosFault
    crash1 = instrument_block_sink(cfg, 1, got.append, generation=1)
    with pytest.raises(ChaosFault):
        crash1(_dummy_block())              # crash still re-applies


def _dummy_block():
    from r2d2_tpu.replay.structs import Block, empty_block_np
    spec = make_spec()
    return Block(**empty_block_np(spec))


# ---------------------------------------------------------------------------
# Config + telemetry + alerts.


def test_fleet_config_round_trip_and_pre_pr15_dicts():
    cfg = Config().replace(**{
        "fleet.replay_shards": 2, "fleet.spill_blocks": 10,
        "fleet.replay_route": "lane", "fleet.fanout_degree": 4,
        "fleet.max_slots": 8, "fleet.elastic": True,
        "replay.capacity": 8_000,
    })
    again = Config.from_dict(cfg.to_dict())
    assert again.fleet == cfg.fleet
    assert again.fleet.active
    # pre-PR15 serialized configs (no fleet section) load with defaults
    d = Config().to_dict()
    d.pop("fleet")
    legacy = Config.from_dict(d)
    assert legacy.fleet.replay_shards == 0
    assert not legacy.fleet.active
    assert legacy.fleet.resolved_max_slots(4) == 4


@pytest.mark.parametrize("overrides", [
    {"fleet.replay_shards": 2, "replay.placement": "host"},
    {"fleet.replay_shards": 3},                   # 1250 % 3 != 0
    {"fleet.replay_shards": 2, "mesh.dp": 2},
    {"fleet.spill_blocks": 4},                    # spill without service
    {"fleet.fanout_degree": 1},
    {"fleet.max_slots": 1, "actor.num_actors": 2},
    {"fleet.replay_route": "hash"},
    {"fleet.service_transport": "socket"},        # no service
    {"actor.fault_spec": "0:join@t=5"},           # join without elastic
    {"actor.fault_spec": "0:leave@block=2"},      # leave without elastic
    # lane routing with fewer lanes than shards: shard 2 unreachable,
    # the per-shard gate would hold training closed forever
    {"fleet.replay_shards": 5, "fleet.replay_route": "lane",
     "replay.capacity": 400_000, "actor.num_actors": 2},
    {"fleet.elastic": True, "mesh.multihost": True},
    {"fleet.max_slots": 8, "actor.num_actors": 2,
     "mesh.multihost": True},
    {"telemetry.alerts_spill_thrash_frac": 0.0},
    {"telemetry.alerts_fanout_lag": 0.5},
])
def test_fleet_config_validation(overrides):
    with pytest.raises((ValueError, SystemExit)):
        Config().replace(**overrides)


def test_fleet_alert_rules_fire_and_hold():
    from r2d2_tpu.telemetry.alerts import AlertEngine, default_rules
    rules = default_rules(Config().telemetry)
    names = {r.name for r in rules}
    assert {"spill_thrash", "fanout_lag", "orphaned_slot"} <= names
    engine = AlertEngine([r for r in rules if r.name in
                          ("spill_thrash", "fanout_lag", "orphaned_slot")])
    # a record WITHOUT the block leaves every rule inactive
    out = engine.evaluate({"training_steps": 1})
    assert out["fired"] == [] and out["active"] == []
    record = {"replay_service": {
        "spill": {"thrash_frac": 0.9},
        "fanout": {"max_lag": 10},
        "membership": {"orphaned": 1},
    }}
    fired = {a["rule"] for a in engine.evaluate(record)["fired"]}
    assert fired == {"spill_thrash", "fanout_lag", "orphaned_slot"}
    # recovery re-arms
    healthy = {"replay_service": {
        "spill": {"thrash_frac": 0.0},
        "fanout": {"max_lag": 0},
        "membership": {"orphaned": 0},
    }}
    out = engine.evaluate(healthy)
    assert out["active"] == []


def test_record_schema_stability_without_fleet(tmp_path):
    """No provider attached (every legacy run): the record carries no
    replay_service key; attached, the key appears."""
    from r2d2_tpu.runtime.metrics import TrainMetrics
    m = TrainMetrics(0, str(tmp_path))
    rec = m.log(1.0)
    assert "replay_service" not in rec
    m.set_replay_service(lambda: {"membership": {"slots": 2}})
    rec = m.log(1.0)
    assert rec["replay_service"]["membership"]["slots"] == 2
    # a None-returning provider omits the key (quiet interval contract)
    m.set_replay_service(lambda: None)
    assert "replay_service" not in m.log(1.0)


# ---------------------------------------------------------------------------
# Service-routed Learner.


def _svc_config(**extra):
    base = {
        "env.game_name": "Fake",
        "env.frame_height": 12, "env.frame_width": 12, "env.frame_stack": 2,
        "network.hidden_dim": 8, "network.cnn_out_dim": 16,
        "network.conv_layers": ((4, 3, 2),),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 160, "replay.block_length": 20,
        "replay.batch_size": 4, "replay.learning_starts": 40,
        "runtime.save_interval": 0, "runtime.steps_per_dispatch": 1,
        "fleet.replay_shards": 2,
    }
    base.update(extra)
    return Config().replace(**base)


def _learner_blocks(cfg, n, rng):
    from r2d2_tpu.replay.structs import ReplaySpec
    spec = ReplaySpec.from_config(cfg)
    return _fill_blocks(spec, n, rng)


def test_service_learner_trains_and_writes_back(rng, tmp_path):
    """The service-routed Learner: per-shard gating, external-batch
    training on service-sampled batches, priority write-back mutating
    the sampled shard's tree."""
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.learner_loop import Learner
    cfg = _svc_config(**{"runtime.save_dir": str(tmp_path),
                         "fleet.spill_blocks": 4})
    net = NetworkApply(4, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    lr = Learner(cfg, net, 0)
    assert lr.service is not None
    assert lr.replay_state is None
    assert lr.service.num_shards == 2
    assert lr.service.spec.num_blocks == cfg.num_blocks // 2
    blocks = _learner_blocks(cfg, 4, rng)
    lr.ingest(blocks[0])
    assert not lr.ready                     # shard 1 still empty
    for blk in blocks[1:]:
        lr.ingest(blk)
    assert lr.ready
    trees_before = [np.asarray(s.state.tree).copy()
                    for s in lr.service.shards]
    m = lr.step()
    assert "priorities" not in m            # consumed by the write-back
    assert lr.training_steps == 1
    changed = [not np.array_equal(np.asarray(s.state.tree), t0)
               for s, t0 in zip(lr.service.shards, trees_before)]
    assert any(changed)                     # the sampled shard's tree moved
    lr.flush_metrics()
    block = lr.service.interval_block()
    assert block["shards"]["n"] == 2
    assert lr.metrics.buffer_size == lr.service.buffer_steps
    lr.stop_background()


def test_service_learner_rejected_on_device(rng):
    with pytest.raises(ValueError):
        _svc_config(**{"actor.on_device": True})


# ---------------------------------------------------------------------------
# Slow: the churn drill.


@pytest.mark.slow
def test_churn_drill_end_to_end():
    """The ISSUE-15 acceptance drill: 25% of a running thread fleet
    leaves via the grammar fault and re-joins via the join schedule —
    zero learner stalls, no lane overlap, shard contents
    provenance-checked via the PR-10 lane stamps."""
    from r2d2_tpu.tools.chaos import run_churn_drill
    report = run_churn_drill(seconds=35.0)
    assert report["verdict"]["left"], report
    assert report["verdict"]["rejoined"], report
    assert report["verdict"]["zero_learner_stalls"], report
    assert report["verdict"]["no_lane_overlap"], report
    assert report["verdict"]["shards_routed_by_lane"], report
    # the rejoined worker re-runs its slot-keyed leave fault, so the
    # slot may legitimately be parked again at teardown — what must
    # hold is that at least one full leave->adopt cycle completed
    assert report["membership"]["joins"] >= 1
    assert report["shard_lanes"] and all(report["shard_lanes"])
