"""Cost-model & roofline observability tests (ISSUE 9): XLA cost-table
extraction across the step factories (incl. a sharded emulated-mesh
program), named_scope component annotations in the lowered HLO, the
trace→component attribution on the checked-in miniature trace, the
roofline report + analytic golden file, the exact-match costs gate, the
anakin scan's unroll twin, and record-schema stability under the
``telemetry.costmodel_enabled`` kill switch.
"""

import dataclasses
import glob
import json
import os
import shutil

import jax
import numpy as np
import pytest

from r2d2_tpu.config import Config, apex_epsilon
from r2d2_tpu.envs.factory import create_jax_env
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.structs import ReplaySpec
from r2d2_tpu.telemetry import costmodel, traceparse

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")
MINI_TRACE = os.path.join(DATA_DIR, "mini_trace.trace.json.gz")
GOLDEN = os.path.join(DATA_DIR, "roofline_analytic_golden.json")


def gate_cfg(**overrides) -> Config:
    cfg = costmodel.gate_config()
    return cfg.replace(**overrides) if overrides else cfg


def _net_and_spec(cfg):
    env = create_jax_env(cfg.env)
    spec = ReplaySpec.from_config(cfg)
    net = NetworkApply(env.action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    return env, spec, net


def _learner_step_hlo(cfg) -> str:
    from r2d2_tpu.learner.train_step import (create_train_state,
                                             make_learner_step)
    from r2d2_tpu.replay.device_replay import replay_init
    _, spec, net = _net_and_spec(cfg)
    step = make_learner_step(net, spec, cfg.optim, cfg.network.use_double)
    ts = costmodel._sds(jax.eval_shape(
        lambda k: create_train_state(k, net, cfg.optim),
        jax.random.PRNGKey(0)))
    rs = costmodel._sds(jax.eval_shape(lambda: replay_init(spec)))
    return jax.jit(step).lower(ts, rs).compile().as_text()


# ---------------------------------------------------------------------------
# cost-table extraction across step factories


def test_cost_table_core_programs():
    table = costmodel.collect_cost_table(
        gate_cfg(), variants=("learner_step", "replay_add_many",
                              "replay_sample"))
    assert table["schema"] == 1 and table["backend"] == "cpu"
    progs = table["programs"]
    for name in ("learner_step", "replay_add_many", "replay_sample"):
        assert progs[name]["flops"] > 0, name
        assert progs[name]["bytes_accessed"] > 0, name
        assert progs[name]["argument_bytes"] > 0, name
    # the fused step subsumes a sample + tree work: strictly more flops
    assert progs["learner_step"]["flops"] > progs["replay_sample"]["flops"]


def test_cost_table_anakin_program():
    table = costmodel.collect_cost_table(gate_cfg(),
                                         variants=("anakin_act",))
    act = table["programs"]["anakin_act"]
    assert act["flops"] > 0 and act["bytes_accessed"] > 0
    assert act["lanes"] == gate_cfg().actor.anakin_lanes


def test_cost_table_sharded_emulated_mesh():
    # the conftest pins an 8-device virtual CPU platform; the sharded
    # variant builds its dp=2 shard_map program on it
    table = costmodel.collect_cost_table(
        gate_cfg(), variants=("learner_step_sharded", "learner_step_multi"))
    sharded = table["programs"]["learner_step_sharded"]
    assert sharded["flops"] > 0 and sharded["dp"] == 2
    multi = table["programs"]["learner_step_multi"]
    assert multi["flops"] > 0 and multi["steps_per_dispatch"] == 3


def test_cost_table_tp_program():
    table = costmodel.collect_cost_table(gate_cfg(),
                                         variants=("learner_step_tp",))
    tp = table["programs"]["learner_step_tp"]
    assert tp["flops"] > 0 and tp["mp"] == 2


def test_program_cost_is_deterministic():
    cfg = gate_cfg()
    a = costmodel.collect_cost_table(cfg, variants=("replay_sample",))
    b = costmodel.collect_cost_table(cfg, variants=("replay_sample",))
    assert a["programs"] == b["programs"]


# ---------------------------------------------------------------------------
# named_scope component annotations in the lowered HLO


def test_named_scopes_in_learner_hlo():
    # bare-token matching, exactly like traceparse.component_of: under
    # autodiff the scopes ride transform-decorated op_names
    # (jvp(loss)/..., transpose(jvp(loss))/...), so path-delimited
    # tokens would miss the backward ops
    hlo = _learner_step_hlo(gate_cfg())
    for token in ("/torso/", "/lstm/", "/head/", "sum_tree_update",
                  "sum_tree_sample", "replay_sample", "optimizer",
                  "loss", "obs_decode"):
        assert token in hlo, f"component scope {token!r} missing from HLO"


def test_named_scopes_in_fused_dual_hlo():
    # the fused double unroll bypasses the named flax modules — its
    # explicit scopes must keep the program attributable
    hlo = _learner_step_hlo(gate_cfg(**{"optim.fused_double_unroll": "on"}))
    for token in ("jvp(torso)", "jvp(lstm)", "jvp(head)"):
        assert token in hlo, f"fused-dual scope {token!r} missing"


def test_named_scopes_in_anakin_hlo():
    from r2d2_tpu.actor.anakin import init_act_carry, make_anakin_act
    cfg = gate_cfg()
    env, spec, net = _net_and_spec(cfg)
    lanes = cfg.actor.anakin_lanes
    eps = [apex_epsilon(i, lanes, cfg.actor.base_eps, cfg.actor.eps_alpha)
           for i in range(lanes)]
    act = make_anakin_act(env, net, spec, num_lanes=lanes, epsilons=eps,
                          gamma=cfg.optim.gamma, priority=1.0,
                          near_greedy_eps=cfg.actor.near_greedy_eps)
    params = costmodel._sds(jax.eval_shape(net.init, jax.random.PRNGKey(0)))
    carry = costmodel._sds(jax.eval_shape(
        lambda k: init_act_carry(env, spec, lanes, k), jax.random.PRNGKey(1)))
    hlo = act.lower(params, carry,
                    jax.ShapeDtypeStruct((), np.int32)).compile().as_text()
    for token in ("env_step", "env_reset", "emit_blocks", "act_forward"):
        assert token in hlo, f"acting scope {token!r} missing from HLO"


def test_anakin_unroll_twin_bit_identical():
    # the cost model's fully-unrolled acting twin must be the SAME
    # program mathematically: every emitted block field bit-matches
    # (sum_reward compared with equal_nan — NaN is its designed
    # not-reported value)
    from r2d2_tpu.actor.anakin import init_act_carry, make_anakin_act
    cfg = gate_cfg()
    env, spec, net = _net_and_spec(cfg)
    lanes = cfg.actor.anakin_lanes
    eps = [apex_epsilon(i, lanes, cfg.actor.base_eps, cfg.actor.eps_alpha)
           for i in range(lanes)]
    params = net.init(jax.random.PRNGKey(0))

    def run(unroll):
        act = make_anakin_act(env, net, spec, num_lanes=lanes, epsilons=eps,
                              gamma=cfg.optim.gamma, priority=1.0,
                              near_greedy_eps=cfg.actor.near_greedy_eps,
                              unroll=unroll)
        carry = init_act_carry(env, spec, lanes, jax.random.PRNGKey(1))
        return act(params, carry, np.int32(1))[1]

    b1, b2 = run(1), run(spec.block_length)
    for f in b1.__dataclass_fields__:
        x, y = np.asarray(getattr(b1, f)), np.asarray(getattr(b2, f))
        if np.issubdtype(x.dtype, np.floating):
            assert np.array_equal(x, y, equal_nan=True), f
        else:
            assert np.array_equal(x, y), f


# ---------------------------------------------------------------------------
# analytic model + bench parity


def test_flops_parity_with_xla_cost_model():
    # the ISSUE 9 acceptance bar: the unroll twin's XLA flops and
    # bench.model_flops_per_step within 5% (XLA counts a while body
    # once, hence the twin; see the costmodel module docstring)
    import bench
    cfg = gate_cfg()
    table = costmodel.collect_cost_table(cfg, variants=("learner_step",),
                                         unroll_scans=True)
    xla_flops = table["programs"]["learner_step"]["flops"]
    action_dim = table["action_dim"]
    analytic = bench.model_flops_per_step(cfg, action_dim,
                                          cfg.network.use_double)
    ratio = xla_flops / analytic
    assert 0.95 <= ratio <= 1.05, f"parity drifted: {ratio:.4f}"


def test_model_flops_single_source():
    # bench.py delegates to the costmodel count — the two can't drift
    import bench
    cfg = gate_cfg()
    assert bench.model_flops_per_step(cfg, 6, True) == \
        costmodel.model_flops_per_step(cfg, 6, True)
    # double-DQN adds exactly one extra unroll of every matmul
    single = costmodel.model_flops_per_step(cfg, 6, False)
    double = costmodel.model_flops_per_step(cfg, 6, True)
    assert double > single


def test_analytic_component_costs_structure():
    an = costmodel.analytic_component_costs(gate_cfg(), 6)
    comps = an["components"]
    assert set(comps) == {"torso", "lstm", "head", "sum_tree", "replay"}
    for name, c in comps.items():
        assert c["bytes"] > 0, name
        assert c["flops"] >= 0, name
    assert an["total_flops"] > 0
    assert 0 < an["serial_chain"]["share_of_total"] < 1
    # double-DQN, unfused: fwd + bwd + target fwd chain walks
    assert an["serial_chain"]["iterations"] == \
        gate_cfg().sequence.seq_len * 3


def test_analytic_golden_file():
    # deterministic pure math — exact golden comparison. Regenerate
    # deliberately (see tests/data/) when the model changes; a silent
    # drift here is exactly what the costs gate exists to catch.
    with open(GOLDEN) as f:
        golden = json.load(f)
    current = costmodel.analytic_component_costs(gate_cfg(),
                                                 golden["action_dim"])
    assert json.loads(json.dumps(current)) == golden["analytic"]


def test_peak_spec_table():
    v5e = costmodel.peak_spec("TPU v5 lite")
    assert v5e["flops_bf16"] == 197e12 and not v5e["nominal"]
    unknown = costmodel.peak_spec("weird accelerator")
    assert unknown["nominal"] is True


# ---------------------------------------------------------------------------
# traceparse on the checked-in miniature trace


def test_traceparse_mini_trace_attribution():
    s = traceparse.attribute_trace(MINI_TRACE)
    # >= 80% of device time attributed; the rest visible, never dropped
    assert s["attributed_frac"] >= 0.8
    assert s["components"]["unattributed"]["time_us"] == 90.0
    # the host plane's 100 ms python event is excluded from device time,
    # and the "XLA Modules" thread's whole-module enclosing span (1290
    # us under the SAME device pid in the fixture) is not double-counted
    # on top of the per-op "XLA Ops" events
    assert s["total_us"] == 1290.0
    assert not s["host_fallback"]
    for comp in ("torso", "lstm", "head", "sum_tree", "replay",
                 "env_step", "emit_blocks"):
        assert comp in s["components"], comp
    # shares sum to 1 over every component incl. unattributed
    assert sum(c["share"] for c in s["components"].values()) == \
        pytest.approx(1.0, abs=1e-4)
    assert traceparse.format_attribution(s)


def test_traceparse_dir_discovery(tmp_path):
    # the ProfilerCapture layout: plugins/profile/<ts>/*.trace.json.gz
    nested = tmp_path / "plugins" / "profile" / "2026_08_03"
    nested.mkdir(parents=True)
    shutil.copy(MINI_TRACE, nested / "host.trace.json.gz")
    s = traceparse.attribute_trace(str(tmp_path))
    assert s["total_us"] == 1290.0
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(FileNotFoundError):
        traceparse.attribute_trace(str(empty))


def test_traceparse_host_fallback():
    # a capture with no device plane (CPU backend) attributes ALL
    # tracks and says so
    events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "python"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0, "dur": 10,
         "name": "jit(step)/torso/conv"},
    ]
    s = traceparse.attribute_trace(events)
    assert s["host_fallback"] and s["total_us"] == 10.0
    assert s["components"]["torso"]["time_us"] == 10.0


def test_traceparse_excludes_derived_thread_lines():
    # xprof derives whole-module / name-scope / framework-op lines from
    # the same op stream under the SAME device pid — counting them would
    # double- or triple-count every op (the real-capture layout; the
    # checked-in fixture carries the "XLA Modules" case)
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "TensorFlow Name Scope"}},
        {"ph": "M", "pid": 1, "tid": 3, "name": "thread_name",
         "args": {"name": "Steps"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "fusion.1", "args": {"long_name": "jit/torso/conv"}},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 100,
         "name": "torso"},
        {"ph": "X", "pid": 1, "tid": 3, "ts": 0, "dur": 100,
         "name": "step 7"},
    ]
    s = traceparse.attribute_trace(events)
    assert s["total_us"] == 100.0
    assert s["components"]["torso"]["time_us"] == 100.0


def test_component_of_ordering():
    # nested network scopes beat their enclosing acting/loss scopes
    assert traceparse.component_of("jit/act_forward/torso/conv") == "torso"
    assert traceparse.component_of("jit/loss/reduce") == "loss"
    assert traceparse.component_of("jit/act_forward/argmax") == "act_forward"
    assert traceparse.component_of("copy.3") is None


# ---------------------------------------------------------------------------
# roofline report


def test_roofline_report_build():
    from r2d2_tpu.tools.roofline import build_report, format_report
    cfg = gate_cfg()
    report = build_report(cfg, "gate", step_time_ms=5.0,
                          peak=costmodel.peak_spec())
    ls = report["learner_step"]
    assert set(ls["components"]) == {"torso", "lstm", "head", "sum_tree",
                                     "replay"}
    for name, row in ls["components"].items():
        assert row["arithmetic_intensity"] >= 0
        assert row["bound"] in ("compute", "memory"), name
        assert row["pct_of_peak"] is not None
    assert ls["pct_of_peak_total"] > 0
    # acceptance: learner-step total FLOPs within 5% of the bench count
    assert report["parity"]["ratio"] == pytest.approx(1.0, abs=0.05)
    assert report["anakin_act"]["flops_per_env_step"] > 0
    assert "implied_tau_us_upper" in ls["serial_chain"]
    assert "roofline @" in format_report(report)


def test_roofline_cli_artifact(tmp_path):
    from r2d2_tpu.tools import roofline
    out = tmp_path / "ROOFLINE.json"
    assert roofline.main(["--preset", "gate", "--step-time-ms", "5",
                          "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["schema"] == 1 and doc["learner_step"]["measured_ms"] == 5.0


# ---------------------------------------------------------------------------
# the costs regression gate


def test_compare_cost_tables_exact_gate():
    base = {"programs": {"learner_step": {"flops": 100.0, "bytes_accessed":
                                          50.0},
                         "replay_sample": {"flops": 10.0}}}
    ok = costmodel.compare_cost_tables(base, json.loads(json.dumps(base)))
    assert all(r["status"] == "ok" for r in ok)

    # an injected 2x FLOP change fails — in EITHER direction
    doubled = {"programs": {"learner_step": {"flops": 200.0,
                                             "bytes_accessed": 50.0},
                            "replay_sample": {"flops": 10.0}}}
    rows = costmodel.compare_cost_tables(base, doubled)
    changed = [r for r in rows if r["status"] == "CHANGED"]
    assert len(changed) == 1 and changed[0]["metric"] == "flops"
    assert changed[0]["delta_pct"] == 100.0
    halved = {"programs": {"learner_step": {"flops": 50.0,
                                            "bytes_accessed": 50.0},
                           "replay_sample": {"flops": 10.0}}}
    assert any(r["status"] == "CHANGED"
               for r in costmodel.compare_cost_tables(base, halved))

    # a vanished program is a failure too, never a silent pass
    missing = {"programs": {"learner_step": {"flops": 100.0,
                                             "bytes_accessed": 50.0}}}
    rows = costmodel.compare_cost_tables(base, missing)
    assert any(r["status"] == "missing" for r in rows)


def test_regress_gate_fires_on_injected_flops_change(tmp_path,
                                                     monkeypatch, capsys):
    # end-to-end through the regress CLI, with the expensive live
    # recompute stubbed by a fixture table: the baseline snapshots it,
    # the gate passes unchanged, then an injected 2x FLOP change in one
    # step factory fails the run
    from r2d2_tpu.tools import regress
    table = {"schema": 1, "backend": "cpu",
             "programs": {"learner_step": {"flops": 1000.0,
                                           "bytes_accessed": 500.0},
                          "anakin_act": {"flops": 80.0}}}
    current = {"v": json.loads(json.dumps(table))}
    monkeypatch.setattr(
        "r2d2_tpu.telemetry.costmodel.gate_table", lambda: current["v"])
    baseline = tmp_path / "BASELINE.json"
    baseline.write_text(json.dumps({"bench": {}}))
    art_dir = tmp_path / "artifacts"
    art_dir.mkdir()
    (art_dir / "E2E_r99.json").write_text(
        json.dumps({"env_steps_per_sec": 100.0}))
    assert regress.main(["--baseline", str(baseline), "--dir",
                         str(art_dir), "--update"]) == 0
    assert json.loads(baseline.read_text())["costs"] == table

    assert regress.main(["--baseline", str(baseline), "--dir",
                         str(art_dir)]) == 0          # unchanged: passes
    current["v"] = json.loads(json.dumps(table))
    current["v"]["programs"]["anakin_act"]["flops"] *= 2   # injected 2x
    assert regress.main(["--baseline", str(baseline), "--dir",
                         str(art_dir)]) == 1
    assert "CHANGED" in capsys.readouterr().out
    # --skip-costs keeps the bench-only behavior
    assert regress.main(["--baseline", str(baseline), "--dir",
                         str(art_dir), "--skip-costs"]) == 0


# ---------------------------------------------------------------------------
# record wiring + kill switch + config round-trip


def _learner(tmp_path, **overrides):
    from r2d2_tpu.runtime.learner_loop import Learner
    cfg = gate_cfg(**{"runtime.save_dir": str(tmp_path),
                      "runtime.save_interval": 0,
                      "runtime.steps_per_dispatch": 1, **overrides})
    _, _, net = _net_and_spec(cfg)
    return Learner(cfg, net, 0)


def test_costs_block_rides_exactly_one_record(tmp_path):
    learner = _learner(tmp_path)
    learner.flush_metrics()
    record = learner.metrics.log(1.0)
    costs = record["costs"]
    assert set(costs["components"]) == {"torso", "lstm", "head",
                                        "sum_tree", "replay"}
    assert costs["model_flops_per_step"] > 0
    assert costs["serial_chain"]["iterations"] > 0
    # static per config: exactly ONE record carries it
    learner.flush_metrics()
    assert "costs" not in learner.metrics.log(1.0)


def test_costs_killswitch_leaves_records_byte_identical(tmp_path):
    on = _learner(tmp_path / "on")
    off = _learner(tmp_path / "off",
                   **{"telemetry.costmodel_enabled": False})
    on.flush_metrics()
    off.flush_metrics()
    r_on, r_off = on.metrics.log(1.0), off.metrics.log(1.0)
    assert "costs" not in r_off
    # identical schema + content modulo the costs key and wall-clock t
    r_on.pop("costs")
    for r in (r_on, r_off):
        r.pop("t")
    assert json.dumps(r_on, sort_keys=True) == \
        json.dumps(r_off, sort_keys=True)


def test_costmodel_config_roundtrip():
    cfg = Config()
    assert cfg.telemetry.costmodel_enabled is True
    # pre-PR9 serialized configs (no costmodel field) load with default
    d = cfg.to_dict()
    del d["telemetry"]["costmodel_enabled"]
    assert Config.from_dict(d).telemetry.costmodel_enabled is True
    off = cfg.replace(**{"telemetry.costmodel_enabled": False})
    assert Config.from_json(
        off.to_json()).telemetry.costmodel_enabled is False


def test_inspect_costs_panel(tmp_path):
    # the inspector's cost/roofline panel (ISSUE 9 satellite): renders
    # from the record's one-shot costs block + the newest roofline
    # artifact, and digs the block out of the stream's history
    from r2d2_tpu.tools import inspect as inspect_tool
    learner = _learner(tmp_path)
    learner.flush_metrics()
    rec_with = learner.metrics.log(1.0)
    rec_after = learner.metrics.log(1.0)
    from r2d2_tpu.tools.roofline import build_report
    roofline = build_report(gate_cfg(), "gate", step_time_ms=5.0,
                            peak=costmodel.peak_spec("TPU v5 lite"))
    frame = inspect_tool.render_record(rec_after,
                                       costs=rec_with["costs"],
                                       roofline=roofline)
    assert "costs:" in frame and "torso" in frame
    assert "%pk" in frame                       # roofline %-of-peak joined
    # the history digger finds the one record that carried the block
    assert inspect_tool.costs_record([rec_with, rec_after]) \
        == rec_with["costs"]
    assert inspect_tool.costs_record([rec_after]) is None
    # a roofline artifact for a DIFFERENT shape (mtime-discovered, e.g.
    # the gate fixture next to a reference run) is ignored, not joined
    other = json.loads(json.dumps(roofline))
    other["parity"]["model_flops_per_step"] *= 10
    frame = inspect_tool.render_record(rec_after,
                                       costs=rec_with["costs"],
                                       roofline=other)
    assert "different shape" in frame and "%pk" not in frame


@pytest.mark.slow
def test_anakin_profile_at_step_capture(tmp_path):
    # the ISSUE 9 satellite: the fused on-device loop now honors the
    # one-shot runtime.profile_at_step capture trigger — the capture
    # lands where traceparse expects it
    from r2d2_tpu.runtime.anakin_loop import run_anakin_train
    cfg = gate_cfg(**{
        "actor.on_device": True, "actor.anakin_lanes": 2,
        "runtime.save_dir": str(tmp_path), "runtime.save_interval": 0,
        "runtime.steps_per_dispatch": 1, "runtime.log_interval": 2.0,
        "runtime.profile_at_step": 1,
        "replay.learning_starts": 40,
        "telemetry.resources_enabled": False,
    })
    stacks = run_anakin_train(cfg, max_training_steps=3, max_seconds=120)
    assert stacks[0].learner.training_steps >= 1
    traces = glob.glob(os.path.join(str(tmp_path), "xprof", "**",
                                    "*.trace.json.gz"), recursive=True)
    assert traces, "profile_at_step produced no capture in the fused loop"
    # and the capture parses through the component attribution
    s = traceparse.attribute_trace(os.path.join(str(tmp_path), "xprof"))
    assert s["total_us"] >= 0
