"""Learning-dynamics observability tests (ISSUE 5): device-vs-host
histogram parity, ΔQ against an interpreted reference unroll, staleness
stamps end-to-end (queue transports, replay ring wrap, PR4-era blocks),
NaN forensics (one-shot dump, warn/halt policies), record-schema
stability, and a slow e2e slice proving the ``learning`` block lands in
the periodic record with a nonzero sample-age distribution.
"""

import json
import queue as queue_mod

import jax
import numpy as np
import pytest

from r2d2_tpu.config import Config
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.device_replay import replay_add, replay_init, replay_sample
from r2d2_tpu.replay.structs import Block, ReplaySpec, RingAccountant, \
    empty_block_np
from r2d2_tpu.replay.synthetic import make_synthetic_block
from r2d2_tpu.telemetry.histogram import bucket_index, bucket_mid, \
    value_summary
from r2d2_tpu.telemetry.learning import (LearningAggregator, LearningDiag,
                                         delta_q_diag, value_counts)

ACTIONS = 4


def tiny_cfg(**overrides) -> Config:
    cfg = Config().replace(**{
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 400, "replay.block_length": 20,
        "replay.batch_size": 8,
        "replay.pallas_sample_gather": "off",
        "replay.pallas_exact_gather": "off",
    })
    return cfg.replace(**overrides) if overrides else cfg


def tiny_net(cfg: Config) -> NetworkApply:
    return NetworkApply(ACTIONS, cfg.network, cfg.env.frame_stack,
                        cfg.env.frame_height, cfg.env.frame_width)


def stamped_block(spec, rng, version: int):
    blk = make_synthetic_block(spec, rng)
    return blk.replace(
        action=np.asarray(blk.action) % ACTIONS,
        last_action_row=np.asarray(blk.last_action_row) % ACTIONS,
        weight_version=np.asarray(version, np.int32))


def filled_replay(spec, rng, n_blocks=4, start_version=1):
    rs = replay_init(spec)
    for i in range(n_blocks):
        rs = replay_add(spec, rs,
                        stamped_block(spec, rng, start_version + i))
    return rs


# ---------------------------------------------------------------------------
# device-side histograms


def test_value_hist_device_matches_host(rng):
    # bucket midpoints: deterministically inside their bucket under both
    # the host float64 math and the device float32 math
    buckets = rng.integers(1, 63, size=200)
    values = np.asarray([bucket_mid(int(b)) for b in buckets], np.float32)
    counts = np.asarray(jax.jit(value_counts)(values))
    ref = np.zeros(64, np.int64)
    for v in values:
        ref[bucket_index(float(v))] += 1
    np.testing.assert_array_equal(counts, ref)
    assert counts.sum() == 200


def test_value_hist_clamps_and_signs():
    import jax.numpy as jnp
    vals = jnp.asarray([0.0, -0.5, 0.5, 1e12, -1e12, jnp.nan])
    counts = np.asarray(value_counts(vals))
    assert counts.sum() == 6
    assert counts[0] >= 1            # 0 clamps into the bottom bucket
    assert counts[63] >= 3           # overflow + NaN saturate the top
    # sign is dropped: |x| histogrammed
    assert counts[bucket_index(0.5)] == 2


def test_value_hist_mask_excludes():
    import jax.numpy as jnp
    vals = jnp.asarray([[0.5, 0.5], [0.5, 0.5]])
    mask = jnp.asarray([[1.0, 0.0], [1.0, 1.0]])
    assert int(np.asarray(value_counts(vals, mask)).sum()) == 3


def test_value_summary_schema():
    counts = np.zeros(64, np.int64)
    counts[10] = 50
    counts[20] = 50
    s = value_summary(counts)
    assert s["count"] == 100
    assert s["p50"] == pytest.approx(bucket_mid(10), rel=1e-5)
    assert s["p99"] == pytest.approx(bucket_mid(20), rel=1e-5)
    assert value_summary(np.zeros(64)) is None


# ---------------------------------------------------------------------------
# ΔQ vs an interpreted reference unroll


def test_delta_q_matches_interpreted_reference(rng):
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    net = tiny_net(cfg)
    params = net.init(jax.random.PRNGKey(1))
    rs = filled_replay(spec, rng)
    batch = replay_sample(spec, rs, jax.random.PRNGKey(2))

    m = 4
    got = jax.jit(lambda b, r: delta_q_diag(net, spec, params, b, r, m))(
        batch, rs)

    # interpreted reference: per-row python loop, plain net.apply calls
    def q_at(obs_row, la_row, hidden, positions):
        T = la_row.shape[0]
        fsi = np.arange(T)[:, None] + np.arange(spec.frame_stack)[None, :]
        stacked = np.asarray(obs_row)[fsi]            # (T, K, H, W)
        stacked = stacked.transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        la = np.zeros((T, ACTIONS), np.float32)
        valid = np.asarray(la_row) >= 0
        la[np.arange(T)[valid], np.asarray(la_row)[valid]] = 1.0
        q, _ = net.apply(params, stacked[None], la[None], hidden[None])
        return np.asarray(q)[0][positions]            # (L, A)

    L = spec.learning
    dq_s = dq_z = dq_r = 0.0
    total = 0.0
    idxes = np.asarray(batch.idxes)[:m]
    for row in range(m):
        b, s = idxes[row] // spec.seqs_per_block, idxes[row] % spec.seqs_per_block
        seq_start = int(np.asarray(rs.seq_start)[b, s])
        burn = int(np.asarray(batch.burn_in_steps)[row])
        learn = int(np.asarray(batch.learning_steps)[row])
        opos = burn + np.arange(L)
        q_sto = q_at(np.asarray(batch.obs)[row],
                     np.asarray(batch.last_action)[row],
                     np.asarray(batch.hidden)[row], opos)
        q_zer = q_at(np.asarray(batch.obs)[row],
                     np.asarray(batch.last_action)[row],
                     np.zeros((2, spec.hidden_dim), np.float32), opos)
        q_rec = q_at(np.asarray(rs.obs)[b], np.asarray(rs.last_action)[b],
                     np.zeros((2, spec.hidden_dim), np.float32),
                     seq_start + np.arange(L))
        for j in range(L):
            w = 1.0 if j < learn else 0.0
            total += w
            scale_r = np.abs(q_rec[j]).max() + 1e-3
            scale_s = np.abs(q_sto[j]).max() + 1e-3
            dq_s += w * np.linalg.norm(q_sto[j] - q_rec[j]) / scale_r
            dq_z += w * np.linalg.norm(q_zer[j] - q_rec[j]) / scale_r
            dq_r += w * np.linalg.norm(q_rec[j] - q_sto[j]) / scale_s
    ref = np.asarray([dq_s, dq_z, dq_r]) / max(total, 1.0)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-3, atol=2e-4)


def test_delta_q_stored_is_small_when_stored_state_is_true(rng):
    """When the stored hidden IS the state the full-context unroll reaches
    at the window start, the stored strategy must beat the zero strategy
    — the paper's Fig. 4 ordering, reproduced exactly."""
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    net = tiny_net(cfg)
    params = net.init(jax.random.PRNGKey(1))
    rs = filled_replay(spec, rng)

    # overwrite the stored hiddens with the TRUE full-context states:
    # unroll each block row from zero and snapshot at each window start
    def true_hiddens(obs_row, la_row, seq_starts, burn_ins):
        T = la_row.shape[0]
        fsi = np.arange(T)[:, None] + np.arange(spec.frame_stack)[None, :]
        stacked = np.asarray(obs_row)[fsi].transpose(0, 2, 3, 1)\
            .astype(np.float32) / 255.0
        la = np.zeros((T, ACTIONS), np.float32)
        valid = np.asarray(la_row) >= 0
        la[np.arange(T)[valid], np.asarray(la_row)[valid]] = 1.0
        hid = np.zeros((2, spec.hidden_dim), np.float32)
        out = np.zeros((spec.seqs_per_block, 2, spec.hidden_dim), np.float32)
        starts = {int(s) - int(b): i
                  for i, (s, b) in enumerate(zip(seq_starts, burn_ins))}
        for t in range(T):
            if t in starts:
                out[starts[t]] = hid
            _, packed = net.apply(params, stacked[t][None, None],
                                  la[t][None, None], hid[None])
            hid = np.asarray(packed)[0]
        return out

    hid_ring = np.asarray(rs.hidden).copy()
    for b in range(spec.num_blocks):
        hid_ring[b] = true_hiddens(
            np.asarray(rs.obs)[b], np.asarray(rs.last_action)[b],
            np.asarray(rs.seq_start)[b], np.asarray(rs.burn_in_steps)[b])
    rs = rs.replace(hidden=hid_ring)
    batch = replay_sample(spec, rs, jax.random.PRNGKey(3))
    dq_s, dq_z, dq_r = [float(x) for x in
                        delta_q_diag(net, spec, params, batch, rs, 8)]
    assert dq_s < 1e-2, dq_s           # stored+burn-in ≈ the reference
    assert dq_r < 1e-2, dq_r
    assert dq_z > dq_s                 # zero-state discrepancy is larger


# ---------------------------------------------------------------------------
# fused-step integration


def _fused_setup(rng, diag, **cfg_over):
    from r2d2_tpu.learner.train_step import (create_train_state,
                                             make_learner_step)
    cfg = tiny_cfg(**cfg_over)
    spec = ReplaySpec.from_config(cfg)
    net = tiny_net(cfg)
    ts = create_train_state(jax.random.PRNGKey(0), net, cfg.optim)
    rs = filled_replay(spec, rng)
    step = make_learner_step(net, spec, cfg.optim, cfg.network.use_double,
                             diag=diag)
    return cfg, spec, ts, rs, step


def test_fused_step_emits_learning_metrics(rng):
    cfg, spec, ts, rs, step = _fused_setup(
        rng, LearningDiag(interval=1, dq_batch=4))
    ts, rs, m = step(ts, rs)
    valid = int(np.asarray(m["ld/td_hist"]).sum())
    # one histogram entry per VALID learning step of the batch
    assert valid == int(np.asarray(jax.device_get(ts.step)) * 0 +
                        sum(min(spec.learning, l) for l in
                            [spec.learning] * spec.batch_size))
    assert int(np.asarray(m["ld/prio_hist"]).sum()) == spec.batch_size
    assert int(np.asarray(m["ld/q_hist"]).sum()) == valid
    for k in ("ld/grad_norm", "ld/grad_norm_torso", "ld/grad_norm_lstm",
              "ld/grad_norm_head", "ld/target_dist", "ld/delta_q_stored",
              "ld/delta_q_zero", "ld/delta_q_recomputed"):
        assert np.isfinite(float(np.asarray(m[k]))), k
    assert int(m["ld/nonfinite"]) == 0
    assert np.asarray(m["ld/weight_versions"]).shape == (spec.batch_size,)
    assert np.all(np.asarray(m["ld/weight_versions"]) >= 1)
    assert np.asarray(m["ld/batch_idxes"]).shape == (spec.batch_size,)


def test_fused_step_interval_gates_delta_q(rng):
    cfg, spec, ts, rs, step = _fused_setup(
        rng, LearningDiag(interval=2, dq_batch=4))
    ts, rs, m1 = step(ts, rs)     # step 1: off-interval
    ts, rs, m2 = step(ts, rs)     # step 2: interval fires
    assert np.isnan(float(m1["ld/delta_q_stored"]))
    assert np.isnan(float(m1["ld/target_dist"]))
    assert np.isfinite(float(m2["ld/delta_q_stored"]))
    assert np.isfinite(float(m2["ld/target_dist"]))
    # histograms flow EVERY step regardless of the interval
    assert int(np.asarray(m1["ld/td_hist"]).sum()) > 0


def test_fused_step_without_diag_has_no_ld_keys(rng):
    cfg, spec, ts, rs, step = _fused_setup(rng, None)
    ts, rs, m = step(ts, rs)
    assert not any(k.startswith("ld/") for k in m)
    assert {"loss", "mean_abs_td", "mean_q", "grad_norm"} <= set(m)


def test_multi_step_dispatch_stacks_diag(rng):
    from r2d2_tpu.learner.train_step import (create_train_state,
                                             make_multi_learner_step)
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    net = tiny_net(cfg)
    ts = create_train_state(jax.random.PRNGKey(0), net, cfg.optim)
    rs = filled_replay(spec, rng)
    step = make_multi_learner_step(net, spec, cfg.optim,
                                   cfg.network.use_double, 4,
                                   diag=LearningDiag(interval=2, dq_batch=4))
    ts, rs, m = step(ts, rs)
    assert np.asarray(m["ld/td_hist"]).shape == (4, 64)
    dq = np.asarray(m["ld/delta_q_zero"])
    assert dq.shape == (4,)
    # carried step counter drives the cadence inside the scan: steps 2, 4
    assert np.isnan(dq[0]) and np.isnan(dq[2])
    assert np.isfinite(dq[1]) and np.isfinite(dq[3])


def test_sharded_step_diag_is_replicated_and_global(rng):
    from r2d2_tpu.config import MeshConfig
    from r2d2_tpu.learner.train_step import create_train_state
    from r2d2_tpu.parallel import (make_mesh, make_sharded_learner_step,
                                   make_sharded_replay_add,
                                   sharded_replay_init)
    cfg = tiny_cfg(**{"mesh.dp": 2})
    spec = ReplaySpec.from_config(cfg)
    net = tiny_net(cfg)
    ts = create_train_state(jax.random.PRNGKey(0), net, cfg.optim)
    mesh = make_mesh(cfg.mesh)
    rs = sharded_replay_init(spec, mesh)
    add = make_sharded_replay_add(spec, mesh)
    for i in range(4):
        rs = add(rs, stamped_block(spec, rng, i + 1), i % 2)
    step = make_sharded_learner_step(
        net, spec, cfg.optim, cfg.network.use_double, mesh,
        diag=LearningDiag(interval=1, dq_batch=4))
    ts, rs, m = step(ts, rs)
    # histograms psum over shards: GLOBAL batch counts (dp * B sequences)
    assert int(np.asarray(m["ld/prio_hist"]).sum()) == 2 * spec.batch_size
    assert np.isfinite(float(m["ld/delta_q_stored"]))
    assert float(m["ld/version_min"]) >= 1.0
    assert float(m["ld/version_max"]) <= 4.0
    # raw per-sample vectors are omitted on the reduced sharded path
    assert "ld/weight_versions" not in m


def test_external_batch_step_diag_host_mode(rng):
    from r2d2_tpu.learner.train_step import (create_train_state,
                                             make_external_batch_step)
    from r2d2_tpu.replay.host_replay import HostReplay
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    net = tiny_net(cfg)
    ts = create_train_state(jax.random.PRNGKey(0), net, cfg.optim)
    hr = HostReplay(spec, seed=0)
    for i in range(4):
        hr.add(stamped_block(spec, rng, i + 1))
    batch, _ = hr.sample()
    step = make_external_batch_step(net, spec, cfg.optim,
                                    cfg.network.use_double,
                                    diag=LearningDiag(interval=1,
                                                      dq_batch=4))
    ts, m = step(ts, jax.device_put(batch))
    assert int(np.asarray(m["ld/td_hist"]).sum()) > 0
    assert np.all(np.asarray(m["ld/weight_versions"]) >= 1)
    # ΔQ needs the device-resident ring context: NaN in host placement
    assert np.isnan(float(m["ld/delta_q_stored"]))
    assert np.isfinite(float(m["ld/target_dist"]))


# ---------------------------------------------------------------------------
# staleness stamps end-to-end


def test_staleness_stamp_survives_ring_wrap(rng):
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)          # 20 ring rows
    rs = replay_init(spec)
    n = spec.num_blocks
    for i in range(n + 3):                      # wrap by 3
        rs = replay_add(spec, rs, stamped_block(spec, rng, i + 1))
    ring = np.asarray(rs.weight_version)
    # rows 0..2 overwritten by the wrapped adds n+1..n+3
    assert list(ring[:3]) == [n + 1, n + 2, n + 3]
    assert list(ring[3:]) == list(range(4, n + 1))
    batch = replay_sample(spec, rs, jax.random.PRNGKey(0))
    assert set(int(v) for v in np.asarray(batch.weight_version)) <= set(
        range(4, n + 4))


def test_staleness_stamp_survives_queue_transports(rng):
    from r2d2_tpu.runtime.feeder import BlockQueue
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    blk = stamped_block(spec, rng, 37)
    # shm ring (falls back to mp.Queue when the native toolchain is
    # absent — the stamp must survive either backend), mp.Queue, thread
    for q in (BlockQueue(maxsize=4, use_mp=True, shm_spec=spec),
              BlockQueue(maxsize=4, use_mp=True),
              BlockQueue(maxsize=4, use_mp=False)):
        try:
            q.put(blk, timeout=5.0)
            got = q.get(timeout=5.0)
            assert int(np.asarray(got.weight_version)) == 37
            q.put(blk, timeout=5.0)
            q.put(stamped_block(spec, rng, 41), timeout=5.0)
            # mp.Queue's feeder thread makes items poppable asynchronously
            # (qsize can lead get_nowait) — accumulate until both arrive
            import time
            deadline = time.time() + 10.0
            versions = []
            while len(versions) < 2 and time.time() < deadline:
                stacked, k = q.drain_stacked(4)
                if k:
                    versions += [int(v) for v in
                                 np.asarray(stacked.weight_version)]
                else:
                    time.sleep(0.01)
            assert versions == [37, 41]
        finally:
            q.close()


def test_host_replay_carries_stamp(rng):
    from r2d2_tpu.replay.host_replay import HostReplay
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    hr = HostReplay(spec, seed=0)
    for i in range(3):
        hr.add(stamped_block(spec, rng, 10 + i))
    batch, _ = hr.sample()
    assert set(int(v) for v in np.asarray(batch.weight_version)) <= {10, 11, 12}
    assert hr.ring.live_versions() == [10, 11, 12]


def test_pr4_era_block_defaults_to_unknown(rng):
    """A PR4-era record — no weight_version field — must construct, flow
    through replay, and report its age as unknown, not crash."""
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    legacy = {k: v for k, v in empty_block_np(spec).items()
              if k != "weight_version"}
    blk = Block(**legacy)                       # default: -1 = unknown
    assert int(np.asarray(blk.weight_version)) == -1
    rs = replay_init(spec)
    rs = replay_add(spec, rs, blk.replace(
        priority=np.ones((spec.seqs_per_block,), np.float32),
        learning_steps=np.full((spec.seqs_per_block,), spec.learning,
                               np.int32)))
    batch = replay_sample(spec, rs, jax.random.PRNGKey(0))
    assert np.all(np.asarray(batch.weight_version) == -1)
    agg = LearningAggregator(0, ".", "warn", 1e-4)
    agg.on_dispatch({"ld/weight_versions": np.asarray(batch.weight_version)})
    block = agg.flush(1, publish_count=5)
    assert block["sample_age"]["unknown_frac"] == 1.0
    assert "p50" not in block["sample_age"]


def test_instrument_sink_stamps_weight_version(rng):
    from r2d2_tpu.runtime.actor_loop import instrument_block_sink
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    seen = []
    sink = instrument_block_sink(cfg, 0, seen.append,
                                 weight_version=lambda: 9)
    sink(stamped_block(spec, rng, -1))
    assert int(np.asarray(seen[0].weight_version)) == 9


def test_ring_accountant_tracks_versions():
    ring = RingAccountant(3)
    ring.advance(10, 5)
    ring.advance(10, 6)
    assert ring.live_versions() == [5, 6]
    ring.advance(10, 7)
    ring.advance(10, 8)                          # wraps slot 0
    assert ring.live_versions() == [8, 6, 7]
    ring.advance(0)                              # empty block, unstamped
    assert ring.live_versions() == [8, 7]        # slot 1 emptied


def test_weight_service_publish_counts(rng):
    from r2d2_tpu.runtime.weights import (InProcWeightStore, WeightPublisher,
                                          WeightSubscriber)
    params = {"w": np.arange(8, dtype=np.float32)}
    pub = WeightPublisher(params)
    try:
        assert pub.publish_count == 1            # the __init__ publish
        sub = WeightSubscriber(pub.name, params)
        assert sub.publish_count == 0            # nothing adopted yet
        assert sub.poll() is not None
        assert sub.publish_count == 1
        pub.publish(params)
        pub.publish(params)
        assert pub.publish_count == 3
        assert sub.poll() is not None
        assert sub.publish_count == 3
        sub.close()
    finally:
        pub.close()
    store = InProcWeightStore(params)
    assert store.publish_count == 1
    assert store.reader_version(0) == 1          # never polled: init params
    store.publish(params)
    assert store.poll(0) is not None
    assert store.reader_version(0) == 2 == store.publish_count


# ---------------------------------------------------------------------------
# aggregation + NaN forensics


def _fake_dispatch(nonfinite=0, versions=(3, 4), dq=0.25):
    hist = np.zeros(64, np.int64)
    hist[12] = 7
    return {
        "ld/td_hist": hist, "ld/prio_hist": hist, "ld/q_hist": hist,
        "ld/grad_norm": np.float32(1.5),
        "ld/grad_norm_torso": np.float32(0.5),
        "ld/nonfinite": np.int32(nonfinite),
        "ld/weight_versions": np.asarray(versions, np.int32),
        "ld/batch_idxes": np.asarray([1, 2], np.int32),
        "ld/target_dist": np.float32(0.1),
        "ld/delta_q_stored": np.float32(dq),
        "ld/delta_q_zero": np.float32(2 * dq),
        "ld/delta_q_recomputed": np.float32(dq),
    }


def test_aggregator_builds_learning_block(tmp_path):
    agg = LearningAggregator(0, str(tmp_path), "warn", 1e-4)
    agg.on_dispatch(_fake_dispatch())
    agg.on_dispatch(_fake_dispatch(dq=np.nan))
    block = agg.flush(10, publish_count=6,
                      occupancy_versions=[2, 5, -1])
    assert block["td_abs"]["count"] == 14       # two dispatches merged
    assert block["grad_norm"]["global"]["mean"] == 1.5
    assert block["grad_norm"]["torso"]["mean"] == 0.5
    assert block["delta_q"]["stored"] == 0.25   # last FINITE value
    assert block["target_param_dist"] == pytest.approx(0.1)
    age = block["sample_age"]
    assert age["p50"] == 2.5 and age["max"] == 3  # pub 6 - versions {3,4}
    assert age["unknown_frac"] == 0.0
    rage = block["replay_age"]
    assert rage["max"] == 4 and rage["slots"] == 2
    assert rage["unknown_slots"] == 1
    assert block["nonfinite_steps"] == 0
    # flush consumed the interval
    assert agg.flush(11) is None


def test_aggregator_handles_multi_step_stacked_rows(tmp_path):
    agg = LearningAggregator(0, str(tmp_path), "warn", 1e-4)
    d = _fake_dispatch()
    # (K, 64) histograms and (K, B) versions, as the k-step scan stacks
    d["ld/td_hist"] = np.stack([d["ld/td_hist"]] * 3)
    d["ld/weight_versions"] = np.asarray([[3, 4], [5, 6], [7, 8]], np.int32)
    agg.on_dispatch(d)
    block = agg.flush(3, publish_count=10)
    assert block["td_abs"]["count"] == 21
    assert block["sample_age"]["max"] == 7      # oldest = version 3


def test_nan_dump_fires_exactly_once(tmp_path):
    agg = LearningAggregator(0, str(tmp_path), "warn", 1e-4)
    agg.on_dispatch(_fake_dispatch(nonfinite=1))
    block = agg.flush(5, publish_count=6)
    assert block["nonfinite_steps"] == 1
    path = tmp_path / "nan_dump_player0.json"
    assert path.exists()
    dump = json.loads(path.read_text())
    assert dump["step"] == 5 and dump["lr"] == 1e-4
    assert dump["last_batch_idxes"] == [1, 2]
    assert "td_abs_counts" in dump["histograms"]
    stamp = path.stat().st_mtime_ns
    # a second poisoned interval must NOT rewrite the dump
    agg.on_dispatch(_fake_dispatch(nonfinite=1))
    agg.flush(6, publish_count=7)
    assert path.stat().st_mtime_ns == stamp
    assert agg.nan_dumped


def test_nan_policy_halt_raises_after_dump(tmp_path):
    agg = LearningAggregator(1, str(tmp_path), "halt", 1e-4)
    agg.on_dispatch(_fake_dispatch(nonfinite=1))
    with pytest.raises(RuntimeError, match="nan_policy=halt"):
        agg.flush(5, publish_count=6)
    assert (tmp_path / "nan_dump_player1.json").exists()


# ---------------------------------------------------------------------------
# config + record schema


def test_config_roundtrips_learning_fields():
    cfg = tiny_cfg(**{"telemetry.learning_enabled": False,
                      "telemetry.learning_interval": 77,
                      "telemetry.learning_dq_batch": 9,
                      "telemetry.nan_policy": "halt"})
    back = Config.from_json(cfg.to_json())
    assert back.telemetry.learning_enabled is False
    assert back.telemetry.learning_interval == 77
    assert back.telemetry.learning_dq_batch == 9
    assert back.telemetry.nan_policy == "halt"


def test_pre_pr5_config_dict_loads_with_defaults():
    d = Config().to_dict()
    # a PR4-era checkpoint config: telemetry section without the new keys
    for k in ("learning_enabled", "learning_interval", "learning_dq_batch",
              "nan_policy"):
        del d["telemetry"][k]
    cfg = Config.from_dict(d)
    assert cfg.telemetry.learning_enabled is True
    assert cfg.telemetry.nan_policy == "warn"
    assert LearningDiag.from_config(cfg) is not None


def test_learning_diag_gating():
    assert LearningDiag.from_config(
        tiny_cfg(**{"telemetry.learning_enabled": False})) is None
    assert LearningDiag.from_config(
        tiny_cfg(**{"telemetry.enabled": False})) is None
    d = LearningDiag.from_config(tiny_cfg())
    assert d == LearningDiag(interval=200, dq_batch=16)


def test_config_validates_learning_fields():
    with pytest.raises(ValueError, match="learning_interval"):
        tiny_cfg(**{"telemetry.learning_interval": 0})
    with pytest.raises(ValueError, match="nan_policy"):
        tiny_cfg(**{"telemetry.nan_policy": "explode"})


def test_record_schema_learning_block(tmp_path):
    from r2d2_tpu.runtime.metrics import TrainMetrics
    m = TrainMetrics(0, str(tmp_path))
    m.set_learning({"delta_q": {"stored": 0.1}})
    record = m.log(1.0)
    assert record["learning"]["delta_q"]["stored"] == 0.1
    # PR2/3/4 keys unaffected (schema stability)
    for key in ("buffer_size", "env_steps", "training_steps", "loss",
                "ingest_blocks_total", "ingest_drains", "actor_restarts",
                "actor_parked_slots", "heartbeat_age_max_s"):
        assert key in record, key
    # consumed on emission; absent when nothing was set
    record2 = m.log(1.0)
    assert "learning" not in record2
    # and the block round-trips the JSONL stream
    from r2d2_tpu.tools.logparse import learning_series, parse_jsonl
    records = parse_jsonl(str(tmp_path / "metrics_player0.jsonl"))
    series = learning_series(records)
    assert series["delta_q_stored"] == [0.1]


def test_plot_cli_learning_mode(tmp_path):
    import os
    recs = [{"t": float(i), "training_steps": i * 10,
             "learning": {
                 "delta_q": {"stored": 0.1 + i * 0.01, "zero": 0.5,
                             "recomputed": 0.1},
                 "sample_age": {"p50": 2.0, "p95": 5.0, "max": 9,
                                "unknown_frac": 0.0},
                 "grad_norm": {"global": {"mean": 1.0, "max": 2.0}},
                 "td_abs": {"count": 10, "p50": 0.1, "p95": 0.3,
                            "p99": 0.5},
             }} for i in range(6)]
    with open(tmp_path / "metrics_player0.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    out = str(tmp_path / "learning.png")
    from r2d2_tpu.cli.plot import main
    main(["--learning", "--file_path", str(tmp_path), "--out", out])
    assert os.path.getsize(out) > 1000


def test_render_learning_panel():
    from r2d2_tpu.tools.inspect import render_record
    frame = render_record({
        "t": 10.0, "env_steps": 100, "training_steps": 5, "buffer_size": 50,
        "learning": {
            "delta_q": {"stored": 0.12, "zero": 0.5, "recomputed": 0.11},
            "td_abs": {"count": 10, "p50": 0.1, "p95": 0.2, "p99": 0.3},
            "grad_norm": {"global": {"mean": 1.0, "max": 2.0}},
            "sample_age": {"p50": 2.0, "p95": 4.0, "max": 6,
                           "unknown_frac": 0.0},
            "replay_age": {"p50": 1.0, "p95": 3.0, "max": 5, "slots": 4,
                           "unknown_slots": 0},
            "nonfinite_steps": 1,
        }})
    assert "dQ stored=0.12" in frame.replace("0.120000", "0.12")
    assert "sample-age p50=2" in frame
    assert "NON-FINITE" in frame


# ---------------------------------------------------------------------------
# slow e2e slice: the learning block lands end-to-end


@pytest.mark.slow
def test_e2e_learning_block_and_kill_switch(tmp_path):
    from r2d2_tpu.runtime.orchestrator import train
    from tests.test_runtime import tiny_config

    cfg = tiny_config(tmp_path, **{
        "runtime.save_interval": 0,
        "runtime.log_interval": 1.0,
        "runtime.weight_publish_interval": 1,
        "telemetry.learning_interval": 5,
        "telemetry.learning_dq_batch": 4,
    })
    records = []
    stacks = train(cfg, max_training_steps=30, max_seconds=180,
                   actor_mode="thread", log_fn=records.append)
    assert stacks[0].learner.training_steps >= 30
    blocks = [r["learning"] for r in records if r.get("learning")]
    assert blocks, "no learning block in any record"
    # ΔQ fired at the 5-step cadence inside the run
    dq = [b["delta_q"] for b in blocks if b.get("delta_q")]
    assert dq and all(
        np.isfinite(d[k]) for d in dq
        for k in ("stored", "zero", "recomputed")), dq
    # histograms + grad norms present
    assert any(b.get("td_abs") for b in blocks)
    assert any(b.get("grad_norm", {}).get("global") for b in blocks)
    # NONZERO sample-age distribution: publishes advanced past the
    # generation stamps of replayed experience
    ages = [b["sample_age"] for b in blocks if b.get("sample_age")]
    assert ages, "no sample ages aggregated"
    assert max(a.get("max", 0) for a in ages) > 0
    assert all(a.get("unknown_frac", 1.0) < 1.0 for a in ages)
    # occupancy ages ride along
    assert any(b.get("replay_age") for b in blocks)

    # kill switch: same system, learning_enabled=false -> no block at all
    cfg_off = tiny_config(tmp_path / "off", **{
        "runtime.save_interval": 0,
        "runtime.log_interval": 1.0,
        "telemetry.learning_enabled": False,
    })
    records_off = []
    train(cfg_off, max_training_steps=10, max_seconds=120,
          actor_mode="thread", log_fn=records_off.append)
    assert records_off
    assert all("learning" not in r for r in records_off)
    assert not (tmp_path / "off" / "nan_dump_player0.json").exists()
