"""Crash-recovery plane tests (ISSUE 18): durable replay snapshot
bit-parity (service shards with/without spill, the plain in-mesh cut),
the atomic manifest commit + corruption probe, the async SnapshotWriter's
latest-wins contract, producer reconnect + unacked-tail replay across a
service bounce (cumulative-ack idempotence), eager-connect construction
failures + the bounded dial ladder, resume determinism (the restored
learner's next-step loss equals the uninterrupted twin's, on BOTH the
plain and service paths), the learner supervisor's crash-loop breaker /
clean-exit / resume-chain policies (fake process, no spawn cost),
checkpoint retention GC, and the kill-switch schema contract (no
``recovery`` record block, no snapshot files, inert alert rules when
``runtime.snapshot_interval == 0``). Slow tier: the two SIGKILL drills
from tools/chaos.py end-to-end.
"""

import json
import os
import socket as socket_mod
import threading
import time

import numpy as np
import pytest

from tests.test_elastic import assert_trees_equal
from tests.test_replay import _fill_blocks, make_spec
from tests.test_runtime import tiny_config
from tests.test_service_ingest import _ring_equal, _spill_equal, _svc_cfg

import jax

from r2d2_tpu.config import Config
from r2d2_tpu.fleet.replay_service import (RemoteReplayProducer,
                                           ReplayService,
                                           ReplayServiceServer)
from r2d2_tpu.replay import replay_add, replay_init
from r2d2_tpu.replay.snapshot import (SnapshotWriter, capture_plain,
                                      load_snapshot, read_manifest,
                                      restore_plain, snapshot_paths,
                                      write_snapshot)
from r2d2_tpu.replay.structs import RingAccountant


def _recovery_cfg(tmp_path, **extra):
    """tiny_config shrunk to the 12x12/hidden-8 geometry _fill_blocks
    synthesizes, with the snapshot plane armed (manual cadence: the
    interval is large so tests drive snapshot_replay() explicitly)."""
    base = {
        "env.frame_height": 12, "env.frame_width": 12,
        "network.hidden_dim": 8,
        "runtime.snapshot_interval": 100_000,
        "runtime.save_interval": 0,
    }
    base.update(extra)
    return tiny_config(tmp_path, **base)


def _make_net(cfg):
    from r2d2_tpu.models.network import NetworkApply
    return NetworkApply(4, cfg.network, cfg.env.frame_stack,
                        cfg.env.frame_height, cfg.env.frame_width)


# ---------------------------------------------------------------------------
# Snapshot round-trip: bit-parity restore.


@pytest.mark.parametrize("spill", [0, 3])
@pytest.mark.parametrize("route", ["round_robin", "lane"])
def test_service_snapshot_roundtrip_bit_parity(rng, tmp_path, route, spill):
    """Capture → disk → restore of a wrapped, spilled service is
    BIT-identical: every shard's ReplayState (tree, rings, stamps),
    ring accountant, spill pages + priority heap, residency table and
    the route cursors — and a same-key sample from the restored service
    returns the identical batch from the identical shard."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 14, rng)     # wraps each 4-slot shard
    svc = ReplayService(spec, 2, spill_blocks=spill, route=route)
    try:
        for blk in blocks:
            svc.add_block(blk)
        snap = svc.snapshot_state(14, extra={"marker": 7})
        meta = write_snapshot(snap, str(tmp_path), 0)
        assert meta["kind"] == "service"
        assert meta["total_adds"] == svc.total_adds == 14
        loaded = load_snapshot(str(tmp_path), 0)
        assert loaded is not None
        assert loaded["extra"]["marker"] == 7

        svc2 = ReplayService(spec, 2, spill_blocks=spill, route=route)
        try:
            svc2.restore_state(loaded)
            assert svc2.total_adds == svc.total_adds
            assert svc2.buffer_steps == svc.buffer_steps
            for got, exp in zip(svc2.shards, svc.shards):
                assert_trees_equal(got.state, exp.state)
                _ring_equal(got, exp)
                _spill_equal(got, exp)
            # behavioral parity: the restored route cursors + trees draw
            # the SAME batch from the SAME shard under the same key
            key = jax.random.PRNGKey(3)
            batch, shard, adds = svc.sample(key)
            batch2, shard2, adds2 = svc2.sample(key)
            assert shard == shard2 and adds == adds2
            assert_trees_equal(batch, batch2)
        finally:
            svc2.close()
    finally:
        svc.close()


def test_plain_snapshot_roundtrip_bit_parity(rng, tmp_path):
    """The replay_shards=0 learner's cut: one ReplayState + its
    RingAccountant mirror survive the disk round-trip bit-exactly,
    restored onto a freshly-initialized state/ring pair."""
    spec = make_spec(num_blocks=3)
    state = replay_init(spec)
    ring = RingAccountant(spec.num_blocks)
    for blk in _fill_blocks(spec, 5, rng):   # wraps the 3-slot ring
        state = replay_add(spec, state, blk)
        ring.advance(int(np.asarray(blk.learning_steps).sum()))
    snap = capture_plain(spec, state, ring, step=42,
                         extra={"env_steps": 99})
    write_snapshot(snap, str(tmp_path), 1)
    loaded = load_snapshot(str(tmp_path), 1)
    assert loaded is not None and loaded["kind"] == "plain"
    assert loaded["step"] == 42 and loaded["extra"]["env_steps"] == 99

    ring2 = RingAccountant(spec.num_blocks)
    state2 = restore_plain(spec, replay_init(spec), ring2, loaded)
    assert_trees_equal(state2, state)
    assert ring2.ptr == ring.ptr
    assert ring2.total_adds == ring.total_adds == 5
    assert ring2.buffer_steps == ring.buffer_steps
    assert ring2.slot_steps == ring.slot_steps
    assert ring2.slot_versions == ring.slot_versions


def test_snapshot_spec_mismatch_refused(rng, tmp_path):
    """A snapshot from a different replay geometry is refused loudly —
    restoring it bitwise into mismatched rings would corrupt sampling."""
    spec = make_spec(num_blocks=3)
    state = replay_init(spec)
    ring = RingAccountant(spec.num_blocks)
    snap = capture_plain(spec, state, ring, step=0)
    other = make_spec(num_blocks=3, batch_size=8)
    with pytest.raises(ValueError, match="spec mismatch"):
        restore_plain(other, replay_init(other),
                      RingAccountant(other.num_blocks), snap)


def test_manifest_commit_atomic_and_corruption_probe(rng, tmp_path):
    """The manifest rename is the commit point: a committed snapshot
    leaves no .tmp litter, read_manifest() is the cheap probe (kind /
    step / total_adds / payload size), and a payload whose size no
    longer matches the manifest (torn write, partial copy) makes
    load_snapshot return None instead of restoring garbage."""
    spec = make_spec(num_blocks=3)
    state = replay_init(spec)
    ring = RingAccountant(spec.num_blocks)
    for blk in _fill_blocks(spec, 2, rng):
        state = replay_add(spec, state, blk)
        ring.advance(int(np.asarray(blk.learning_steps).sum()))
    write_snapshot(capture_plain(spec, state, ring, 7), str(tmp_path), 0)
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]
    man = read_manifest(str(tmp_path), 0)
    assert man is not None
    assert man["kind"] == "plain" and man["step"] == 7
    assert man["total_adds"] == 2 and man["payload_bytes"] > 0
    assert read_manifest(str(tmp_path), 3) is None   # absent player

    payload, _manifest = snapshot_paths(str(tmp_path), 0)
    with open(payload, "rb") as f:
        data = f.read()
    with open(payload, "wb") as f:
        f.write(data[: len(data) // 2])              # torn payload
    assert load_snapshot(str(tmp_path), 0) is None


def test_snapshot_writer_async_latest_wins(rng, tmp_path):
    """The writer never queues more than one pending cut (latest wins,
    replaced cuts counted as dropped), every submitted cut is accounted
    as written-or-dropped after drain, and write_now is synchronous."""
    spec = make_spec(num_blocks=3)
    state = replay_init(spec)
    ring = RingAccountant(spec.num_blocks)
    w = SnapshotWriter(str(tmp_path), 0)
    n = 6
    for step in range(n):
        w.submit(capture_plain(spec, state, ring, step))
    assert w.drain(10.0)
    deadline = time.monotonic() + 10.0
    while w.count + w.dropped < n and time.monotonic() < deadline:
        time.sleep(0.01)
    assert w.count + w.dropped == n and w.count >= 1
    meta = w.write_now(capture_plain(spec, state, ring, 99))
    assert meta["step"] == 99
    assert read_manifest(str(tmp_path), 0)["step"] == 99
    assert w.last_meta["step"] == 99
    w.stop()
    w.stop()                                         # idempotent


# ---------------------------------------------------------------------------
# Service bounce: producer reconnect + unacked-tail replay.


def test_service_bounce_mid_window_ack_replay_idempotent(rng):
    """Kill the service with a window frame unacked (every data ack
    dropped), restore a successor FROM ITS SNAPSHOT on the same port:
    the producer redials on the ladder, replays the unacked tail in seq
    order, and every block sent is eventually acked. The replayed frame
    the dead service already committed lands again as a benign ring
    overwrite (counted adds, never a crash): restored 2 + replayed 2 +
    new 2 = 6 committed adds for 4 producer-sent blocks."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 4, rng)
    svc1 = ReplayService(spec, 2, ingest_batch_blocks=2)
    server1 = ReplayServiceServer(svc1, drop_ack_every=1)
    port = server1.port
    producer = RemoteReplayProducer(
        server1.host, port, window=4, connect_retries=60,
        backoff_base_s=0.05, backoff_max_s=0.25)
    svc2 = server2 = None
    try:
        producer.add_blocks(blocks[:2])          # committed; ack dropped
        deadline = time.monotonic() + 5.0
        while svc1.total_adds < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert svc1.total_adds == 2 and producer.inflight == 1
        snap = svc1.snapshot_state(2)
        server1.close()                          # SIGKILL stand-in
        svc1.close()

        svc2 = ReplayService(spec, 2, ingest_batch_blocks=2)
        svc2.restore_state(snap)
        server2 = ReplayServiceServer(svc2, "127.0.0.1", port)
        producer.add_blocks(blocks[2:])
        acked = producer.flush()
        assert acked == 4 and producer.inflight == 0
        assert producer.reconnects >= 1
        assert producer.blocks_resent >= 2       # the unacked tail
        assert svc2.total_adds == 6
        assert server2.blocks_received == 4
    finally:
        producer.close()
        server1.close()
        if server2 is not None:
            server2.close()
        if svc2 is not None:
            svc2.close()


def _dead_port() -> int:
    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_eager_connect_raises_at_construction():
    """A misaddressed producer/policy channel fails where it is BUILT —
    today a dead replay-service address surfaced only at the first add,
    a thousand steps into multihost bring-up."""
    port = _dead_port()
    with pytest.raises(OSError):
        RemoteReplayProducer("127.0.0.1", port, dial_timeout=0.5)
    from r2d2_tpu.serve.transport import SocketChannel
    with pytest.raises(OSError):
        SocketChannel("127.0.0.1", port, dial_timeout=0.5,
                      eager_connect=True)
    # eager_connect=False keeps the legacy lazy dial (no raise here)
    SocketChannel("127.0.0.1", port, dial_timeout=0.5)


def test_connect_retry_ladder_covers_late_binding_server():
    """Order-insensitive bring-up: a producer constructed BEFORE its
    server binds rides the bounded backoff ladder to a live connection
    instead of dying on the first refusal."""
    port = _dead_port()
    accepted = threading.Event()

    def _bind_late():
        time.sleep(0.3)
        srv = socket_mod.create_server(("127.0.0.1", port))
        conn, _ = srv.accept()
        accepted.set()
        conn.close()
        srv.close()

    t = threading.Thread(target=_bind_late, daemon=True)
    t.start()
    producer = RemoteReplayProducer(
        "127.0.0.1", port, dial_timeout=0.5, connect_retries=20,
        backoff_base_s=0.05, backoff_max_s=0.2)
    try:
        assert accepted.wait(5.0)
    finally:
        producer.close()
        t.join(5.0)


# ---------------------------------------------------------------------------
# Learner snapshot cycle + resume determinism.


def test_learner_plain_resume_determinism(rng, tmp_path):
    """checkpoint + replay snapshot → a restored plain-path learner is
    the uninterrupted twin: bit-identical replay state/ring, the carried
    train key (which resume_training_state deliberately does NOT
    checkpoint) round-trips through the snapshot, and the next step's
    loss matches exactly."""
    from r2d2_tpu.runtime.learner_loop import Learner
    cfg = _recovery_cfg(tmp_path)
    net = _make_net(cfg)
    lr = Learner(cfg, net, 0)
    try:
        for blk in _fill_blocks(lr.spec, 6, rng):
            lr.ingest(blk)
        assert lr.ready
        lr.step()
        ckpt = lr.save(1)
        lr.snapshot_replay()
        assert lr._snap_writer.drain(10.0)
        deadline = time.monotonic() + 10.0
        while lr._snap_writer.count < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        man = read_manifest(str(tmp_path), 0)
        assert man["total_adds"] == lr.ring.total_adds == 6
        ref_state = jax.device_get(lr.replay_state)
        twin_loss = np.asarray(jax.device_get(lr.step()["loss"]))

        resumed = Learner(cfg.replace(**{"runtime.resume": ckpt}), net, 0)
        try:
            assert resumed._restores == 1
            assert resumed._restored_blocks == 6
            assert resumed.ring.total_adds == 6
            assert resumed.ring.ptr == lr.ring.ptr
            assert_trees_equal(jax.device_get(resumed.replay_state),
                               ref_state)
            rec = resumed.recovery_block()
            assert rec["restores"] == 1 and rec["restored_blocks"] == 6
            got = np.asarray(jax.device_get(resumed.step()["loss"]))
            np.testing.assert_array_equal(twin_loss, got)
        finally:
            resumed.stop_background()
    finally:
        lr.stop_background()


def test_learner_service_resume_determinism(rng, tmp_path):
    """Same contract on the service path: the snapshot carries every
    shard + the service sample key, so the restored learner draws the
    same batch and lands the same next-step loss as the twin."""
    from r2d2_tpu.runtime.learner_loop import Learner
    cfg = _svc_cfg(tmp_path, **{"runtime.snapshot_interval": 100_000})
    net = _make_net(cfg)
    lr = Learner(cfg, net, 0)
    try:
        from r2d2_tpu.replay.structs import ReplaySpec
        for blk in _fill_blocks(ReplaySpec.from_config(cfg), 4, rng):
            lr.ingest(blk)
        assert lr.ready
        lr.step()
        ckpt = lr.save(1)
        lr.snapshot_replay()
        assert lr._snap_writer.drain(10.0)
        deadline = time.monotonic() + 10.0
        while lr._snap_writer.count < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        twin_loss = np.asarray(jax.device_get(lr.step()["loss"]))

        resumed = Learner(cfg.replace(**{"runtime.resume": ckpt}), net, 0)
        try:
            assert resumed._restores == 1
            assert resumed.service.total_adds == 4
            got = np.asarray(jax.device_get(resumed.step()["loss"]))
            np.testing.assert_array_equal(twin_loss, got)
        finally:
            resumed.stop_background()
    finally:
        lr.stop_background()


def test_learner_no_snapshot_resume_is_checkpoint_only(rng, tmp_path):
    """Resume with no snapshot on disk stays the pre-PR18 behavior: a
    silent params/opt-state-only restore, empty replay, no restores
    counted — an old checkpoint dir must keep working."""
    from r2d2_tpu.runtime.learner_loop import Learner
    cfg = _recovery_cfg(tmp_path)
    net = _make_net(cfg)
    lr = Learner(cfg, net, 0)
    try:
        ckpt = lr.save(1)
    finally:
        lr.stop_background()
    resumed = Learner(cfg.replace(**{"runtime.resume": ckpt}), net, 0)
    try:
        assert resumed._restores == 0
        assert resumed.ring.total_adds == 0
    finally:
        resumed.stop_background()


# ---------------------------------------------------------------------------
# Supervisor policies (fake child process — no spawn cost).


class _FakeProc:
    def __init__(self, exitcodes, calls, target=None, args=(), name=""):
        self.exitcode = exitcodes.pop(0) if exitcodes else 0
        self.pid = 4242
        calls.append(args)

    def start(self):
        pass

    def is_alive(self):
        return False

    def join(self, timeout=None):
        pass

    def terminate(self):
        pass

    def kill(self):
        pass


class _FakeCtx:
    def __init__(self, exitcodes, calls):
        self._exitcodes, self._calls = exitcodes, calls

    def Process(self, target=None, args=(), name=""):
        return _FakeProc(self._exitcodes, self._calls,
                         target=target, args=args, name=name)


def _sup_cfg(tmp_path, **extra):
    base = {
        "env.game_name": "Fake",
        "runtime.save_dir": str(tmp_path),
        "runtime.restart_backoff_base_s": 0.01,
        "runtime.restart_backoff_max_s": 0.02,
        "runtime.max_restarts_per_window": 2,
        "runtime.restart_window_s": 600.0,
    }
    base.update(extra)
    return Config().replace(**base)


def _patch_ctx(monkeypatch, exitcodes):
    import multiprocessing
    calls = []
    ctx = _FakeCtx(list(exitcodes), calls)
    monkeypatch.setattr(multiprocessing, "get_context",
                        lambda method=None: ctx)
    return calls


def test_supervisor_clean_exit_no_relaunch(tmp_path, monkeypatch):
    """Exit code 0 = the run completed; the supervisor must NOT relaunch
    (a clean stop is not a crash)."""
    from r2d2_tpu.runtime.supervisor import supervise_train
    calls = _patch_ctx(monkeypatch, [0])
    assert supervise_train(_sup_cfg(tmp_path)) == 0
    assert len(calls) == 1
    assert calls[0][0]["runtime"]["resume"] == ""


def test_supervisor_resume_chain(tmp_path, monkeypatch):
    """A crashed child is relaunched FROM THE NEWEST CHECKPOINT: the
    second incarnation's config carries runtime.resume pointed at it
    (and pretrain cleared), and the restart ordinal is threaded
    through."""
    from r2d2_tpu.runtime.supervisor import supervise_train
    os.makedirs(tmp_path / "Fake7_player0")
    calls = _patch_ctx(monkeypatch, [1, 0])
    assert supervise_train(_sup_cfg(tmp_path)) == 1
    assert len(calls) == 2
    assert calls[0][0]["runtime"]["resume"] == ""
    assert calls[1][0]["runtime"]["resume"].endswith("Fake7_player0")
    assert calls[1][0]["runtime"]["pretrain"] == ""
    assert calls[1][4] == 1                       # restart ordinal


def test_supervisor_crash_loop_breaker(tmp_path, monkeypatch):
    """max_restarts_per_window failures inside the window park the slot:
    the supervisor raises ONE loud error instead of relaunching a doomed
    run forever (the actor fleet's WorkerHealth policy, reused)."""
    from r2d2_tpu.runtime.supervisor import supervise_train
    calls = _patch_ctx(monkeypatch, [1, 1, 1, 1, 1])
    with pytest.raises(RuntimeError, match="crash-loop breaker"):
        supervise_train(_sup_cfg(tmp_path))
    assert len(calls) == 3                        # 2 relaunches, then trip


def test_supervisor_refuses_multihost(tmp_path, monkeypatch):
    from r2d2_tpu.runtime.supervisor import supervise_train
    _patch_ctx(monkeypatch, [0])
    cfg = _sup_cfg(tmp_path, **{"mesh.multihost": True,
                                "mesh.num_processes": 2})
    with pytest.raises(NotImplementedError, match="auto_resume"):
        supervise_train(cfg)


# ---------------------------------------------------------------------------
# Retention GC.


def test_prune_checkpoints_retention(tmp_path):
    """keep=K deletes all but the newest K checkpoint dirs + their
    .config.json sidecars; keep<=0 keeps everything; the rolling replay
    snapshot pair is never touched."""
    from r2d2_tpu.runtime.checkpoint import (latest_checkpoint,
                                             prune_checkpoints)
    for i in (1, 2, 3, 10):
        d = tmp_path / f"Fake{i}_player0"
        os.makedirs(d)
        with open(str(d) + ".config.json", "w") as f:
            f.write("{}")
    os.makedirs(tmp_path / "Fake9_player1")       # other player: untouched
    for name in ("replay_player0.npz", "replay_player0.json"):
        with open(tmp_path / name, "w") as f:
            f.write("x")

    assert prune_checkpoints(str(tmp_path), "Fake", 0, 0) == []
    pruned = prune_checkpoints(str(tmp_path), "Fake", 0, 2)
    assert [os.path.basename(p) for p in pruned] == [
        "Fake1_player0", "Fake2_player0"]
    left = sorted(p for p in os.listdir(tmp_path) if "player0" in p
                  and not p.endswith((".npz", ".json")))
    assert left == ["Fake10_player0", "Fake3_player0"]
    assert not os.path.exists(tmp_path / "Fake1_player0.config.json")
    assert os.path.exists(tmp_path / "Fake10_player0.config.json")
    assert os.path.exists(tmp_path / "Fake9_player1")
    assert os.path.exists(tmp_path / "replay_player0.npz")
    assert latest_checkpoint(str(tmp_path), "Fake", 0).endswith(
        "Fake10_player0")


# ---------------------------------------------------------------------------
# Kill-switch contract: plane off = byte-identical records, inert rules.


def test_record_schema_stable_with_plane_off(rng, tmp_path):
    """runtime.snapshot_interval=0: no SnapshotWriter, no snapshot files,
    recovery_block() is None and the periodic record carries NO
    'recovery' key — the schema is byte-identical to pre-PR18 runs."""
    from r2d2_tpu.runtime.learner_loop import Learner
    cfg = _recovery_cfg(tmp_path, **{"runtime.snapshot_interval": 0})
    net = _make_net(cfg)
    lr = Learner(cfg, net, 0)
    try:
        assert lr._snap_writer is None
        assert lr.recovery_block() is None
        for blk in _fill_blocks(lr.spec, 6, rng):
            lr.ingest(blk)
        lr.step()
        lr.metrics.set_recovery(lr.recovery_block)
        rec = lr.metrics.log(1.0)
        assert "recovery" not in rec
        assert json.dumps(rec)                    # still serializable
        assert read_manifest(str(tmp_path), 0) is None
    finally:
        lr.stop_background()


def test_recovery_alert_rules_inert_without_block():
    """snapshot_stale / recovery_loop evaluate to 'no data' on records
    without the recovery block (plane off) and fire on real breaches."""
    from r2d2_tpu.telemetry.alerts import AlertEngine, default_rules
    tcfg = Config().telemetry
    eng = AlertEngine(default_rules(tcfg))
    out = eng.evaluate({"training_steps": 5})
    assert "snapshot_stale" not in eng.active
    assert "recovery_loop" not in eng.active
    assert not any(a["rule"] in ("snapshot_stale", "recovery_loop")
                   for a in out["fired"])
    out = eng.evaluate({
        "training_steps": 6,
        "recovery": {"snapshot": {"age_s": tcfg.alerts_snapshot_stale_s + 1},
                     "supervisor": {"restarts": 3}},
    })
    fired = {a["rule"] for a in out["fired"]}
    assert {"snapshot_stale", "recovery_loop"} <= fired


def test_snapshot_interval_rejects_host_placement(tmp_path):
    with pytest.raises(ValueError, match="snapshot_interval"):
        Config().replace(**{"replay.placement": "host",
                            "runtime.snapshot_interval": 10})


# ---------------------------------------------------------------------------
# Kill drills (slow tier): SIGKILL mid-run, assert auto-recovery.


@pytest.mark.slow
def test_kill_learner_drill_end_to_end():
    """SIGKILL the supervised learner child mid-run: the supervisor
    relaunches from the newest checkpoint + replay snapshot, training
    resumes past the kill point, loss is bounded by the snapshot
    interval, and the actor fleet neither breaker-trips nor parks."""
    from r2d2_tpu.tools.chaos import run_kill_learner_drill
    report = run_kill_learner_drill(seconds=240.0)
    assert all(report["verdict"].values()), report


@pytest.mark.slow
def test_kill_replay_service_drill_end_to_end():
    """SIGKILL the standalone replay service mid-ingest: the producer
    reconnects and replays its unacked tail into the restarted service,
    which restores from its last snapshot — every sent block acked,
    committed-block loss bounded by the snapshot interval + window."""
    from r2d2_tpu.tools.chaos import run_kill_replay_service_drill
    report = run_kill_replay_service_drill(seconds=180.0)
    assert all(report["verdict"].values()), report
