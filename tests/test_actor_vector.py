"""Vectorized actor pipeline tests: SyncVectorEnv semantics, batched-policy
parity against the scalar policy, the ε-ladder spread, block emission from
the vector actor loop, and the end-to-end thread/process integrations.
"""

import dataclasses

import jax
import numpy as np
import pytest

from r2d2_tpu.actor.policy import ActorPolicy, BatchedActorPolicy
from r2d2_tpu.config import Config, apex_epsilon, vector_lane_epsilons
from r2d2_tpu.envs.fake import FakeR2D2Env
from r2d2_tpu.envs.vector import SyncVectorEnv, make_vector_env
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.runtime.actor_loop import run_actor, run_vector_actor


def small_cfg(**overrides) -> Config:
    cfg = Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.actor_update_interval": 50,
    })
    return cfg.replace(**overrides) if overrides else cfg


def small_net(cfg: Config, action_dim: int = 6) -> NetworkApply:
    return NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                        cfg.env.frame_height, cfg.env.frame_width)


# ---- SyncVectorEnv -------------------------------------------------------


def test_vector_env_autoreset_and_accounting():
    """Done lanes report the TERMINAL obs in the stacked array, carry the
    new episode's initial obs + episode accounting in info, and restart
    their counters; other lanes are untouched."""
    ep = 5
    envs = [FakeR2D2Env(action_dim=4, episode_len=ep, height=12, width=12,
                        seed=s) for s in (0, 1)]
    venv = SyncVectorEnv(envs)
    obs = venv.reset()
    assert obs.shape == (2, 12, 12) and obs.dtype == np.uint8

    # lane 0 plays the oracle (reward 1 per step), lane 1 a fixed action
    oracle = [int(envs[0]._schedule[t]) for t in range(ep)]
    for t in range(ep):
        obs, rewards, dones, infos = venv.step([oracle[t], 3])
        if t < ep - 1:
            assert not dones.any()
            assert infos[0] == {} and infos[1] == {}
    assert dones.all()
    for i in range(2):
        assert infos[i]["episode_steps"] == ep
        # terminal obs is the env's t=ep frame, NOT the reset frame
        fresh = FakeR2D2Env(action_dim=4, episode_len=ep, height=12,
                            width=12, seed=i)
        fresh.reset()
        for t in range(ep):
            terminal = fresh.step(oracle[t] if i == 0 else 3)[0]
        np.testing.assert_array_equal(obs[i], terminal)
        # auto-reset already restarted the lane: reset_obs == a fresh reset
        np.testing.assert_array_equal(infos[i]["reset_obs"], fresh.reset())
    assert infos[0]["episode_return"] == float(ep)   # oracle lane
    assert (venv._episode_steps == 0).all()          # accounting restarted

    # without auto_reset the lane stays terminal until reset_lane
    venv2 = SyncVectorEnv([FakeR2D2Env(episode_len=2, height=12, width=12)],
                          auto_reset=False)
    venv2.reset()
    venv2.step([0])
    _, _, dones, infos = venv2.step([0])
    assert dones[0] and "reset_obs" not in infos[0]
    assert venv2.reset_lane(0).shape == (12, 12)
    venv.close()
    venv2.close()


def test_vector_env_validation_and_close():
    class StubEnv:
        class action_space:
            n = 3
        closed = False
        def reset(self):
            return np.zeros((4, 4), np.uint8)
        def step(self, a):
            return np.zeros((4, 4), np.uint8), 0.0, False, {}
        def close(self):
            self.closed = True

    with pytest.raises(ValueError, match="at least one"):
        SyncVectorEnv([])
    envs = [StubEnv(), StubEnv()]
    venv = SyncVectorEnv(envs)
    venv.reset()
    with pytest.raises(ValueError, match="actions"):
        venv.step([0])                       # wrong lane count
    venv.close()
    assert all(e.closed for e in envs)


def test_make_vector_env_per_lane_seeds():
    cfg = small_cfg()
    venv = make_vector_env(cfg.env, 3, seed=40)
    try:
        seeds = [e.unwrapped.seed for e in venv.envs]
        assert seeds == [40, 41, 42]
        obs = venv.reset()
        assert obs.shape == (3, 24, 24)
        # distinct seeds ⇒ distinct target schedules
        schedules = [e.unwrapped._schedule for e in venv.envs]
        assert not np.array_equal(schedules[0], schedules[1])
    finally:
        venv.close()


# ---- BatchedActorPolicy parity ------------------------------------------


def test_batched_policy_parity_vs_scalar_lanes():
    """N lanes through the batched (N, 1) forward vs N independent
    ActorPolicy instances at the same seeds, greedy path: actions and the
    per-step rng streams are bit-identical; Q/hidden match to ≤ 2e-6 (the
    XLA:CPU gemm tiles differently at batch N vs 1, a measured ~1-ulp
    effect — see BatchedActorPolicy's docstring — so full bit-identity of
    the float outputs is not achievable without giving up the batching)."""
    n = 3
    cfg = small_cfg()
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))
    seeds = [11, 22, 33]
    envs = [FakeR2D2Env(episode_len=200, height=24, width=24, seed=s)
            for s in seeds]
    scalars = [ActorPolicy(net, params, 0.0, seed=s) for s in seeds]
    batched = BatchedActorPolicy(net, params, [0.0] * n, seeds=seeds)

    for i, env in enumerate(envs):
        obs = env.reset()
        scalars[i].observe_reset(obs)
        batched.observe_reset_lane(i, obs)

    for t in range(12):
        # bootstrap BEFORE acting: both sides at the same pre-step state
        s_boot = [p.bootstrap_q() for p in scalars]
        v_boot = batched.bootstrap_q()
        b_actions, b_q, b_hidden = batched.act()
        next_obs = []
        for i, p in enumerate(scalars):
            a, q, h = p.act()
            assert int(b_actions[i]) == a, (t, i)
            np.testing.assert_allclose(b_q[i], q, atol=2e-6, rtol=0)
            np.testing.assert_allclose(b_hidden[i], h, atol=2e-6, rtol=0)
            np.testing.assert_allclose(v_boot[i], s_boot[i], atol=2e-6,
                                       rtol=0)
            obs, _, _, _ = envs[i].step(a)
            p.observe(obs, a)
            next_obs.append(obs)
        batched.observe(np.stack(next_obs), b_actions)

    # per-lane reset leaves the other lanes' state untouched
    before = batched.hidden.copy()
    batched.observe_reset_lane(1, envs[1].reset())
    assert (batched.hidden[1] == 0).all()
    np.testing.assert_array_equal(batched.hidden[0], before[0])
    np.testing.assert_array_equal(batched.hidden[2], before[2])


def test_batched_policy_eps_ladder_distribution():
    """Lane ε really drives per-lane exploration: deviation-from-greedy
    frequency tracks ε_i * (1 - 1/A) per lane (one uniform draw per lane
    per step, integer draw only on exploration — the scalar act() order)."""
    cfg = small_cfg().replace(**{
        "env.frame_height": 12, "env.frame_width": 12,
        "network.hidden_dim": 8, "network.cnn_out_dim": 16,
        "network.conv_layers": ((4, 3, 2),)})
    net = small_net(cfg, action_dim=4)
    params = net.init(jax.random.PRNGKey(1))
    eps = [0.0, 0.5, 1.0]
    pol = BatchedActorPolicy(net, params, eps, seeds=[1, 2, 3])
    obs = np.random.default_rng(0).integers(0, 255, (3, 12, 12), np.uint8)
    for i in range(3):
        pol.observe_reset_lane(i, obs[i])

    steps = 400
    deviations = np.zeros(3)
    for _ in range(steps):
        actions, q, _ = pol.act()
        deviations += actions != np.argmax(q, axis=-1)
    frac = deviations / steps
    expect = np.asarray(eps) * (1 - 1 / 4)
    assert frac[0] == 0.0
    np.testing.assert_allclose(frac[1:], expect[1:], atol=0.08)


def test_vector_lane_epsilons_match_global_ladder():
    """Worker-sliced lane ε's concatenate to exactly the Ape-X ladder over
    num_actors * envs_per_actor total lanes."""
    cfg = Config().replace(**{"actor.num_actors": 3,
                              "actor.envs_per_actor": 4})
    ladder = []
    for a in range(3):
        ladder.extend(vector_lane_epsilons(a, cfg.actor))
    want = [apex_epsilon(i, 12, cfg.actor.base_eps, cfg.actor.eps_alpha)
            for i in range(12)]
    assert ladder == want


def test_vector_lane_epsilons_multihost_fleet():
    """Multihost spawners pass the GLOBAL worker index + fleet size
    (parallel/multihost.py: gidx = rank * num_actors + i, total =
    nprocs * num_actors): the per-worker slices must tile the global
    ladder, and a global index passed WITHOUT the fleet size — the bug
    class where rank > 0 extrapolated past the ladder — is rejected."""
    # 2 hosts x 2 local workers x 3 lanes = a 12-lane global ladder
    cfg = Config().replace(**{"actor.num_actors": 2,
                              "actor.envs_per_actor": 3})
    ladder = []
    for rank in range(2):
        for i in range(2):
            gidx = rank * 2 + i
            ladder.extend(vector_lane_epsilons(gidx, cfg.actor,
                                               total_actors=4))
    want = [apex_epsilon(i, 12, cfg.actor.base_eps, cfg.actor.eps_alpha)
            for i in range(12)]
    assert ladder == want
    with pytest.raises(ValueError, match="total_actors"):
        vector_lane_epsilons(2, cfg.actor)   # rank-1 gidx, no fleet size


# ---- run_vector_actor ----------------------------------------------------


def _collect_blocks(cfg, n_lanes, max_env_steps, seed=7, eps=0.0,
                    episode_len=120):
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))
    envs = [FakeR2D2Env(episode_len=episode_len, height=24, width=24,
                        seed=seed + l) for l in range(n_lanes)]
    venv = SyncVectorEnv(envs)
    policy = BatchedActorPolicy(net, params, [eps] * n_lanes,
                                seeds=[seed + l for l in range(n_lanes)])
    blocks = []
    steps = run_vector_actor(cfg, venv, policy, blocks.append, lambda: None,
                             lambda: False, max_env_steps=max_env_steps)
    return steps, blocks


def test_vector_loop_n1_matches_scalar_loop_blocks():
    """The strongest integration parity: at one lane, run_vector_actor
    emits the same block stream as run_actor (greedy, same seed) — integer
    fields bit-identical, float fields within the batched-gemm ulp noise."""
    cfg = small_cfg()
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))

    env = FakeR2D2Env(episode_len=120, height=24, width=24, seed=7)
    policy = ActorPolicy(net, params, 0.0, seed=7)
    scalar_blocks = []
    scalar_steps = run_actor(cfg, env, policy, scalar_blocks.append,
                             lambda: None, lambda: False, max_env_steps=100)

    steps, blocks = _collect_blocks(cfg, 1, 100)
    assert steps == scalar_steps == 100
    assert len(blocks) == len(scalar_blocks) == 5
    exact_fields = {"action", "last_action_row", "obs_row", "seq_start",
                    "burn_in_steps", "learning_steps", "forward_steps",
                    "num_sequences"}
    for a, b in zip(scalar_blocks, blocks):
        for f in dataclasses.fields(a):
            if getattr(a, f.name) is None or getattr(b, f.name) is None:
                # trailing-defaulted leaves (trace_ms): absent on both
                # streams in an untraced run — that IS the parity
                assert getattr(a, f.name) is getattr(b, f.name), f.name
                continue
            x = np.asarray(getattr(a, f.name))
            y = np.asarray(getattr(b, f.name))
            if f.name in exact_fields:
                np.testing.assert_array_equal(x, y, err_msg=f.name)
            else:
                np.testing.assert_allclose(y, x, atol=3e-6, rtol=0,
                                           equal_nan=True, err_msg=f.name)


def test_vector_loop_block_emission_counts():
    """Per-lane episode accounting under episodes shorter than a block:
    every 15-step episode closes its own block (no bootstrap), nothing
    leaks across lanes, and partial tails stay unflushed."""
    cfg = small_cfg()
    # 100 steps/lane, episode_len 15 < block_length 20: 6 complete episodes
    # per lane (90 steps) + a 10-step tail that must NOT emit
    steps, blocks = _collect_blocks(cfg, 4, 400, episode_len=15)
    assert steps == 400
    assert len(blocks) == 4 * 6
    for blk in blocks:
        assert int(blk.num_sequences) == 3             # ceil(15/5)
        assert int(blk.learning_steps[:3].sum()) == 15
        assert not np.isnan(float(blk.sum_reward))     # eps=0 ⇒ near-greedy

    # exploring lanes (ε above the near-greedy threshold) report NaN return
    _, noisy = _collect_blocks(cfg, 2, 60, eps=0.4, episode_len=15)
    assert noisy and all(np.isnan(float(b.sum_reward)) for b in noisy)


def test_vector_loop_truncation_resets_lane():
    """actor.max_episode_steps truncates a lane mid-episode: the block is
    closed without bootstrap and the lane restarts (reset_lane path)."""
    cfg = small_cfg(**{"actor.max_episode_steps": 10})
    steps, blocks = _collect_blocks(cfg, 2, 40, episode_len=120)
    assert steps == 40
    # each lane truncates at 10 steps -> 2 blocks per lane over 20 steps
    assert len(blocks) == 4
    for blk in blocks:
        assert int(blk.num_sequences) == 2             # ceil(10/5)


def test_config_validation():
    with pytest.raises(ValueError, match="envs_per_actor"):
        Config().replace(**{"actor.envs_per_actor": 0})
    with pytest.raises(ValueError, match="multiplayer"):
        Config().replace(**{"multiplayer.enabled": True,
                            "actor.envs_per_actor": 2})
    # the knob round-trips through dict/json like every config field
    cfg = Config().replace(**{"actor.envs_per_actor": 8})
    assert Config.from_json(cfg.to_json()).actor.envs_per_actor == 8


# ---- end-to-end integration ---------------------------------------------


@pytest.mark.slow
def test_end_to_end_vector_thread_mode(tmp_path):
    """Thread-mode orchestrator with envs_per_actor=2: vector actors feed
    the real learner through the standard queue; training proceeds."""
    from r2d2_tpu.runtime.orchestrator import train

    cfg = small_cfg(**{
        "actor.num_actors": 2, "actor.envs_per_actor": 2,
        "runtime.save_dir": str(tmp_path), "runtime.save_interval": 0,
        "runtime.log_interval": 0.2, "runtime.steps_per_dispatch": 1})
    stacks = train(cfg, max_training_steps=8, max_seconds=300,
                   actor_mode="thread")
    learner = stacks[0].learner
    assert learner.training_steps >= 8
    assert learner.env_steps >= cfg.replay.learning_starts


@pytest.mark.slow
def test_e2e_bench_phase(tmp_path):
    """The driver-facing throughput artifact: actor sweep cells + the
    process-mode actors+learner run, both speeds present and nonzero."""
    from r2d2_tpu.tools.e2e_bench import run_actor_sweep, run_e2e

    tiny = {
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "runtime.save_dir": str(tmp_path), "runtime.log_interval": 0.5,
    }
    sweep = run_actor_sweep([1, 2], seconds=1.0, overrides=tiny)
    assert [c["envs_per_actor"] for c in sweep["cells"]] == [1, 2]
    assert all(c["env_steps_per_sec"] > 0 for c in sweep["cells"])
    assert sweep["cells"][0]["speedup_vs_scalar"] == 1.0

    # 40 s window: spawned-actor bring-up (jax import + env construction)
    # alone can eat ~20 s on a loaded 2-core host, leaving a shorter
    # window with zero blocks emitted — a timing flake, not a product
    # signal
    out = run_e2e(seconds=40.0, envs_per_actor=2, num_actors=1,
                  overrides=tiny)
    assert out["total_env_steps"] >= tiny["replay.learning_starts"]
    assert out["total_train_steps"] > 0
    # the two logged speeds of the reference (worker.py:222,229)
    assert out["env_steps_per_sec_overall"] > 0
    assert out["learner_seq_updates_per_sec"] >= 0
