"""On-device acting path tests (ISSUE 6): jitted-env parity against the
host envs, auto-reset/episode-accounting semantics, device block assembly
parity with the host LocalBuffer sink, replay-state identity through the
fused scan, config round-trip/validation, the orchestrator kill switch,
and (slow) the gridworld learnability slice under the fused loop.
"""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.actor.anakin import (ActCarry, emit_blocks, init_act_carry,
                                   make_anakin_act)
from r2d2_tpu.actor.local_buffer import LocalBuffer
from r2d2_tpu.config import Config, apex_epsilon
from r2d2_tpu.envs.factory import create_env, create_jax_env
from r2d2_tpu.envs.fake import FakeR2D2Env
from r2d2_tpu.envs.jax_env import HostJaxEnv, JaxFakeEnv, JaxGridWorld
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.structs import ReplaySpec


def small_cfg(**overrides) -> Config:
    cfg = Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 12, "env.frame_width": 12, "env.frame_stack": 2,
        "env.episode_len": 40,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2),),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.on_device": True, "actor.anakin_lanes": 3,
        "runtime.save_interval": 0,
    })
    return cfg.replace(**overrides) if overrides else cfg


def small_net(cfg: Config, action_dim: int = 6) -> NetworkApply:
    return NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                        cfg.env.frame_height, cfg.env.frame_width)


# ---- jitted env vs host env parity --------------------------------------


def test_jax_fake_env_matches_host_step_for_step():
    """The jitted fake env is a PORT of envs/fake.py: driven with the
    HOST env's schedule and the same actions, obs/reward/done agree
    exactly for a full episode plus the terminal frame."""
    host = FakeR2D2Env(height=24, width=24, episode_len=30, seed=7)
    jenv = JaxFakeEnv(episode_len=30, height=24, width=24)
    state = jenv.state_from_schedule(host._schedule)
    step = jax.jit(jenv.step)

    obs_h = host.reset()
    np.testing.assert_array_equal(
        obs_h, np.asarray(jenv._obs(state["schedule"], state["t"])))
    rng = np.random.default_rng(0)
    for t in range(30):
        a = int(rng.integers(6))
        oh, rh, dh, _ = host.step(a)
        state, oj, rj, dj = step(state, np.int32(a), jax.random.PRNGKey(t))
        np.testing.assert_array_equal(oh, np.asarray(oj), err_msg=f"t={t}")
        assert rh == float(rj) and dh == bool(dj), f"t={t}"
    assert bool(dj)   # fixed-length episode ends exactly at episode_len


def test_jax_fake_env_reward_follows_schedule():
    jenv = JaxFakeEnv(episode_len=8, height=12, width=12)
    state, _ = jax.jit(jenv.reset)(jax.random.PRNGKey(0))
    sched = np.asarray(state["schedule"])
    step = jax.jit(jenv.step)
    for t in range(8):
        # playing the schedule's target always pays +1
        state, _, r, _ = step(state, np.int32(sched[t]),
                              jax.random.PRNGKey(t))
        assert float(r) == 1.0


def test_grid_env_semantics():
    """Reward fires exactly on stepping onto the goal; the agent respawns
    off-goal; the goal stays fixed; episodes end at episode_len; frames
    render the two cells at distinct intensities."""
    env = JaxGridWorld(size=4, episode_len=10, height=16, width=16)
    state, obs = jax.jit(env.reset)(jax.random.PRNGKey(2))
    obs = np.asarray(obs)
    assert set(np.unique(obs)) <= {16, 128, 255}
    assert (obs == 255).sum() == 4 * 4   # one agent cell (4x4 px)
    assert (obs == 128).sum() == 4 * 4   # one goal cell
    step = jax.jit(env.step)
    goal = np.asarray(state["goal"]).copy()
    total = 0.0
    for t in range(10):
        pos = np.asarray(state["pos"])
        # drive straight at the goal: move along the first differing axis
        if pos[0] != goal[0]:
            a = 0 if pos[0] > goal[0] else 1
        elif pos[1] != goal[1]:
            a = 2 if pos[1] > goal[1] else 3
        else:  # pragma: no cover - respawn guarantees off-goal
            a = 4
        state, obs, r, d = step(state, np.int32(a), jax.random.PRNGKey(t))
        total += float(r)
        np.testing.assert_array_equal(np.asarray(state["goal"]), goal)
        # after a goal hit the agent respawns AWAY from the goal
        assert not np.array_equal(np.asarray(state["pos"]), goal)
        assert bool(d) == (t == 9)
    assert total >= 1.0   # goal-seeking collects reward within one episode


def test_host_adapter_and_factory_registration():
    cfg = small_cfg(**{"env.game_name": "Grid", "env.grid_size": 4})
    env = create_env(cfg.env, seed=0)
    assert isinstance(env, HostJaxEnv)
    assert env.action_space.n == 5
    obs = env.reset()
    assert obs.shape == (12, 12) and obs.dtype == np.uint8
    obs2, r, d, info = env.step(1)
    assert obs2.shape == (12, 12) and isinstance(r, float) and not d
    env.close()

    # "JaxFake" resolves the jitted fake behind the adapter; plain "Fake"
    # keeps the host numpy env (legacy path unchanged)
    jf = create_env(dataclasses.replace(cfg.env, game_name="JaxFake"), seed=0)
    assert isinstance(jf, HostJaxEnv) and jf.action_space.n == 6
    fk = create_env(dataclasses.replace(cfg.env, game_name="Fake"), seed=0)
    assert isinstance(fk.unwrapped, FakeR2D2Env)

    assert isinstance(create_jax_env(cfg.env), JaxGridWorld)
    assert isinstance(
        create_jax_env(dataclasses.replace(cfg.env, game_name="Fake")),
        JaxFakeEnv)
    with pytest.raises(ValueError, match="no pure-JAX"):
        create_jax_env(dataclasses.replace(cfg.env, game_name="Vizdoom"))


# ---- config knobs --------------------------------------------------------


def test_config_roundtrip_and_pre_pr6_dicts():
    cfg = small_cfg(**{"actor.anakin_lanes": 5,
                       "actor.anakin_scans_per_train": 2,
                       "actor.anakin_priority": 0.5})
    again = Config.from_dict(json.loads(cfg.to_json()))
    assert again.actor.on_device and again.actor.anakin_lanes == 5
    assert again.actor.anakin_scans_per_train == 2
    assert again.actor.anakin_priority == 0.5
    assert again.env.episode_len == 40 and again.env.grid_size == 6

    # a pre-PR6 checkpoint config (no anakin/env knobs) loads with defaults
    d = Config().to_dict()
    for key in ("on_device", "anakin_lanes", "anakin_scans_per_train",
                "anakin_priority"):
        d["actor"].pop(key)
    d["env"].pop("episode_len")
    d["env"].pop("grid_size")
    old = Config.from_dict(d)
    assert old.actor.on_device is False
    assert old.actor.anakin_lanes == 64
    assert old.env.episode_len == 120 and old.env.grid_size == 6


def test_config_validates_on_device_preconditions():
    with pytest.raises(ValueError, match="multiple of"):
        small_cfg(**{"env.episode_len": 30})       # 30 % 20 != 0
    with pytest.raises(ValueError, match="num_blocks"):
        small_cfg(**{"actor.anakin_lanes": 41})    # > 800/20 blocks
    with pytest.raises(ValueError, match="placement"):
        small_cfg(**{"replay.placement": "host"})
    with pytest.raises(ValueError, match="anakin_priority"):
        small_cfg(**{"actor.anakin_priority": 0.0})
    with pytest.raises(ValueError, match="anakin_scans_per_train"):
        small_cfg(**{"actor.anakin_scans_per_train": 0})
    # the same knobs are unconstrained while on_device is off
    off = small_cfg(**{"actor.on_device": False, "env.episode_len": 30})
    assert not off.actor.on_device


# ---- device block assembly vs the host LocalBuffer sink ------------------


def _drive_parity(spec: ReplaySpec, n_segments: int, ep_blocks: int,
                  num_lanes: int = 2, seed: int
                  = 0, td_priority: bool = False):
    """Feed IDENTICAL synthetic transition streams to the host LocalBuffer
    (add/finish per lane) and the device assembler (emit_blocks per
    segment, tails carried), returning (host_blocks[lane][seg],
    device_blocks[seg], terminals[seg]). ``td_priority`` feeds BOTH
    sides the same synthetic Q streams (per-step rows + the segment-end
    bootstrap) and runs the device assembler in priority="td" mode."""
    rng = np.random.default_rng(seed)
    n, l_seg = num_lanes, spec.block_length
    h = w = spec.frame_height
    a_dim, hid = 6, spec.hidden_dim
    gamma = 0.997

    lbs = [LocalBuffer(spec, a_dim, gamma) for _ in range(n)]
    init_obs = rng.integers(0, 255, (n, h, w)).astype(np.uint8)
    for i in range(n):
        lbs[i].reset(init_obs[i])
    stack, b = spec.frame_stack, spec.burn_in
    tails = (
        np.zeros((n, stack + b, h, w), np.uint8),
        np.full((n, b + 1), -1, np.int32),
        np.zeros((n, b + 1, 2, hid), np.float32),
        np.zeros((n,), np.int32),
    )
    tails[0][:, b:] = np.repeat(init_obs[:, None], stack, axis=1)
    ep_ret = np.zeros((n,), np.float32)

    host_blocks = [[] for _ in range(n)]
    dev_blocks, terminals = [], []
    for seg in range(n_segments):
        obs = rng.integers(0, 255, (n, l_seg, h, w)).astype(np.uint8)
        actions = rng.integers(0, a_dim, (n, l_seg)).astype(np.int32)
        rewards = rng.normal(size=(n, l_seg)).astype(np.float32)
        hiddens = rng.normal(size=(n, l_seg, 2, hid)).astype(np.float32)
        terminal = np.full((n,), ((seg + 1) % ep_blocks) == 0)
        reset_obs = rng.integers(0, 255, (n, h, w)).astype(np.uint8)
        ep_ret = ep_ret + rewards.sum(axis=1)
        qs = rng.normal(size=(n, l_seg, a_dim)).astype(np.float32)
        q_boot = rng.normal(size=(n, a_dim)).astype(np.float32)

        for i in range(n):
            for t in range(l_seg):
                lbs[i].add(int(actions[i, t]), float(rewards[i, t]),
                           obs[i, t],
                           qs[i, t] if td_priority
                           else np.zeros(a_dim, np.float32),
                           hiddens[i, t])
            if terminal[i]:
                host_blocks[i].append(lbs[i].finish(None))
                lbs[i].reset(reset_obs[i])
            else:
                host_blocks[i].append(lbs[i].finish(
                    q_boot[i] if td_priority
                    else np.zeros(a_dim, np.float32)))

        blocks, tails = emit_blocks(
            spec, gamma, "td" if td_priority else 1.0,
            *[jnp.asarray(x) for x in tails],
            jnp.asarray(obs), jnp.asarray(actions), jnp.asarray(rewards),
            jnp.asarray(hiddens), jnp.asarray(terminal),
            jnp.asarray(ep_ret), jnp.ones(n, bool), jnp.asarray(reset_obs),
            seg + 100,
            # the act builder zeroes the bootstrap on terminal lanes
            # (LocalBuffer.finish(None)); the direct driver does it here
            q_seg=jnp.asarray(qs),
            q_boot=jnp.asarray(np.where(terminal[:, None], 0.0, q_boot)
                               .astype(np.float32)))
        tails = [np.asarray(x) for x in tails]
        dev_blocks.append(jax.tree_util.tree_map(np.asarray, blocks))
        terminals.append(terminal)
        ep_ret = np.where(terminal, 0.0, ep_ret).astype(np.float32)
    return host_blocks, dev_blocks, terminals


def test_block_layout_parity_with_host_sink():
    """Every field of every device-assembled block matches the host
    LocalBuffer's, across segments spanning burn-in carry AND episode
    resets — except priority (deliberately a constant stamp)."""
    cfg = small_cfg()
    spec = ReplaySpec.from_config(cfg)
    host_blocks, dev_blocks, terminals = _drive_parity(
        spec, n_segments=4, ep_blocks=2)   # episode = 2 blocks

    for seg in range(4):
        for i in range(2):
            hb = host_blocks[i][seg]
            db = jax.tree_util.tree_map(lambda x: x[i], dev_blocks[seg])
            np.testing.assert_array_equal(db.obs_row, hb.obs_row)
            np.testing.assert_array_equal(db.last_action_row,
                                          hb.last_action_row)
            np.testing.assert_array_equal(db.action, hb.action)
            np.testing.assert_array_equal(db.hidden, hb.hidden)
            np.testing.assert_allclose(db.reward, hb.reward, atol=2e-5)
            np.testing.assert_allclose(db.gamma, hb.gamma, atol=2e-6)
            np.testing.assert_array_equal(db.burn_in_steps,
                                          hb.burn_in_steps)
            np.testing.assert_array_equal(db.learning_steps,
                                          hb.learning_steps)
            np.testing.assert_array_equal(db.forward_steps,
                                          hb.forward_steps)
            np.testing.assert_array_equal(db.seq_start, hb.seq_start)
            assert int(db.num_sequences) == int(hb.num_sequences)
            assert int(db.weight_version) == seg + 100
            assert (db.priority == 1.0).all()   # the constant stamp
            if terminals[seg][i]:
                np.testing.assert_allclose(float(db.sum_reward),
                                           float(hb.sum_reward), rtol=1e-5)
            else:
                assert np.isnan(float(db.sum_reward))
                assert np.isnan(float(hb.sum_reward))


def test_emit_blocks_zero_burn_in():
    """burn_in=0 collapses the carry buffers to their minimal shapes —
    the degenerate layout must still match the host assembler."""
    cfg = small_cfg(**{"sequence.burn_in_steps": 0})
    spec = ReplaySpec.from_config(cfg)
    host_blocks, dev_blocks, _ = _drive_parity(spec, n_segments=2,
                                               ep_blocks=2)
    for seg in range(2):
        for i in range(2):
            hb = host_blocks[i][seg]
            db = jax.tree_util.tree_map(lambda x: x[i], dev_blocks[seg])
            np.testing.assert_array_equal(db.obs_row, hb.obs_row)
            np.testing.assert_array_equal(db.burn_in_steps,
                                          hb.burn_in_steps)
            np.testing.assert_allclose(db.reward, hb.reward, atol=2e-5)


# ---- TD initial-priority mode (ISSUE 8 satellite) ------------------------


def test_emit_blocks_td_priority_matches_local_buffer():
    """priority="td": the in-graph n-step TD seeding reproduces the host
    assembler's initial_priorities + eta-mix per sequence, across
    segments spanning burn-in carry AND episode resets — while every
    other field stays parity-exact."""
    cfg = small_cfg()
    spec = ReplaySpec.from_config(cfg)
    host_blocks, dev_blocks, _ = _drive_parity(
        spec, n_segments=4, ep_blocks=2, td_priority=True)
    for seg in range(4):
        for i in range(2):
            hb = host_blocks[i][seg]
            db = jax.tree_util.tree_map(lambda x: x[i], dev_blocks[seg])
            np.testing.assert_allclose(db.priority, hb.priority,
                                       atol=2e-4, rtol=1e-4)
            np.testing.assert_array_equal(db.obs_row, hb.obs_row)
            np.testing.assert_allclose(db.reward, hb.reward, atol=2e-5)
    # the estimates actually rank: not one constant stamp
    prios = np.concatenate([np.asarray(b.priority).reshape(-1)
                            for b in dev_blocks])
    assert np.unique(np.round(prios, 5)).size > 1


def test_act_scan_td_priority_only_changes_priorities():
    """The td-mode acting program draws the SAME RNG chain as the
    constant-stamp program (the extra bootstrap forward is
    deterministic), so from identical carries every emitted field
    matches except the priorities — which become varying, finite,
    non-negative TD estimates."""
    cfg = small_cfg()
    n = 3
    env, spec, net, params, act_const, _ = _make_act(cfg, n)
    eps = [apex_epsilon(i, n, cfg.actor.base_eps, cfg.actor.eps_alpha)
           for i in range(n)]
    act_td = make_anakin_act(
        env, net, spec, num_lanes=n, epsilons=eps, gamma=cfg.optim.gamma,
        priority="td", near_greedy_eps=cfg.actor.near_greedy_eps,
        priority_eta=cfg.optim.priority_eta)
    carry_c = init_act_carry(env, spec, n, jax.random.PRNGKey(1))
    carry_t = init_act_carry(env, spec, n, jax.random.PRNGKey(1))
    for wv in (1, 2):    # segment 2 crosses the episode boundary
        carry_c, blocks_c, _ = act_const(params, carry_c, np.int32(wv))
        carry_t, blocks_t, _ = act_td(params, carry_t, np.int32(wv))
        for name in blocks_c.__dataclass_fields__:
            a = np.asarray(getattr(blocks_c, name))
            b = np.asarray(getattr(blocks_t, name))
            if name == "priority":
                assert (a == cfg.actor.anakin_priority).all()
                assert np.isfinite(b).all() and (b >= 0).all()
                assert np.unique(np.round(b, 6)).size > 1
            else:
                np.testing.assert_array_equal(a, b, err_msg=name)


def test_td_priority_config_knob():
    cfg = small_cfg(**{"actor.anakin_priority": "td"})
    again = Config.from_dict(json.loads(cfg.to_json()))
    assert again.actor.anakin_priority == "td"
    with pytest.raises(ValueError, match="anakin_priority"):
        small_cfg(**{"actor.anakin_priority": "tdx"})
    # CLI coercion of the union knob: numeric -> float, "td" -> str
    from r2d2_tpu.config import parse_overrides
    assert parse_overrides(
        Config(), ["--actor.anakin_priority=td"]
    ).actor.anakin_priority == "td"
    assert parse_overrides(
        Config(), ["--actor.anakin_priority=0.5"]
    ).actor.anakin_priority == 0.5


# ---- the fused acting scan ----------------------------------------------


def _make_act(cfg: Config, num_lanes: int):
    env = create_jax_env(cfg.env)
    spec = ReplaySpec.from_config(cfg)
    net = small_net(cfg, env.action_dim)
    params = net.init(jax.random.PRNGKey(0))
    eps = [apex_epsilon(i, num_lanes, cfg.actor.base_eps,
                        cfg.actor.eps_alpha) for i in range(num_lanes)]
    act = make_anakin_act(env, net, spec, num_lanes=num_lanes,
                          epsilons=eps, gamma=cfg.optim.gamma,
                          priority=cfg.actor.anakin_priority,
                          near_greedy_eps=cfg.actor.near_greedy_eps)
    carry = init_act_carry(env, spec, num_lanes, jax.random.PRNGKey(1))
    return env, spec, net, params, act, carry


def test_act_scan_emits_full_blocks_and_autoresets():
    """One acting segment per block: shapes, full sequence slots, stamped
    weight_version; at the episode-boundary segment every lane reports
    done exactly once, the carry resets (zero hidden / null last action /
    duplicated reset frames / zero burn-in), and mid-episode segments
    carry burn-in forward — the envs/vector.py auto-reset semantics."""
    cfg = small_cfg()            # episode_len 40 = 2 blocks of 20
    n = 3
    env, spec, net, params, act, carry = _make_act(cfg, n)

    # segment 1: mid-episode (no lane done)
    carry, blocks, stats = act(params, carry, np.int32(4))
    assert blocks.obs_row.shape == (n, spec.obs_row_len, 12, 12)
    assert (np.asarray(blocks.num_sequences) == spec.seqs_per_block).all()
    assert (np.asarray(blocks.learning_steps) == spec.learning).all()
    assert (np.asarray(blocks.weight_version) == 4).all()
    assert (np.asarray(blocks.priority) == cfg.actor.anakin_priority).all()
    assert int(stats["episodes"]) == 0
    assert (np.asarray(carry.burn0)
            == min(spec.block_length, spec.burn_in)).all()
    # gamma tail bootstraps (no termination): strictly positive
    assert (np.asarray(blocks.gamma) > 0).all()

    # segment 2: ends the episode in every lane
    carry, blocks, stats = act(params, carry, np.int32(5))
    assert int(stats["episodes"]) == n
    assert (np.asarray(carry.burn0) == 0).all()
    assert (np.asarray(carry.hidden) == 0).all()
    assert (np.asarray(carry.last_action) == -1).all()
    # terminal gamma tail: the last forward window is zeroed
    g = np.asarray(blocks.gamma)
    assert (g[:, -1, -1] == 0).all()
    # frame stack restarted with the new episode's duplicated initial obs
    cs = np.asarray(carry.cur_stack)
    for k in range(1, spec.frame_stack):
        np.testing.assert_array_equal(cs[:, 0], cs[:, k])
    # the new episode's burn-in tail holds those same frames
    np.testing.assert_array_equal(
        np.asarray(carry.tail_frames)[:, spec.burn_in:], cs)


def test_act_scan_replay_state_identity_with_sequential_adds():
    """Ring-writing one fused segment's N stacked blocks via
    replay_add_many equals N sequential replay_add calls — the device
    path reuses the parity-exact ingestion primitive, asserted end to
    end here."""
    from r2d2_tpu.replay.device_replay import (replay_add, replay_add_many,
                                               replay_init)
    cfg = small_cfg()
    n = 3
    env, spec, net, params, act, carry = _make_act(cfg, n)
    carry, blocks, _ = act(params, carry, np.int32(1))

    many = replay_add_many(spec, replay_init(spec), blocks)
    seq = replay_init(spec)
    for i in range(n):
        one = jax.tree_util.tree_map(lambda x: np.asarray(x)[i], blocks)
        from r2d2_tpu.replay.structs import Block
        seq = replay_add(spec, seq, Block(**{
            f.name: getattr(one, f.name)
            for f in dataclasses.fields(Block)}))
    for name in many.__dataclass_fields__:
        np.testing.assert_array_equal(
            np.asarray(getattr(many, name)), np.asarray(getattr(seq, name)),
            err_msg=name)


def test_act_scan_near_greedy_report_filter():
    """Only lanes at ε <= near_greedy_eps report episode returns (the
    host loop's filtering), and the per-segment stats aggregate exactly
    those lanes."""
    cfg = small_cfg()
    n = 4
    env, spec, net, params, act, carry = _make_act(cfg, n)
    eps = [apex_epsilon(i, n, cfg.actor.base_eps, cfg.actor.eps_alpha)
           for i in range(n)]
    reporting = sum(e <= cfg.actor.near_greedy_eps for e in eps)
    assert 0 < reporting < n     # the ladder straddles the threshold
    carry, blocks, _ = act(params, carry, np.int32(1))     # mid-episode
    carry, blocks, stats = act(params, carry, np.int32(1))  # boundary
    assert int(stats["episodes"]) == n
    assert int(stats["reported_episodes"]) == reporting
    sr = np.asarray(blocks.sum_reward)
    assert np.isfinite(sr).sum() == reporting
    finite_sum = float(np.nansum(np.where(np.isfinite(sr), sr, 0.0)))
    np.testing.assert_allclose(float(stats["reported_return_sum"]),
                               finite_sum, rtol=1e-5)


# ---- the fused act+train loop -------------------------------------------


def test_anakin_loop_trains_end_to_end(tmp_path):
    """The colocated loop: acting segments fill device replay, the gate
    opens, train steps run, metrics/records flow — all in-process with
    zero host actors."""
    from r2d2_tpu.runtime.orchestrator import train
    cfg = small_cfg(**{
        "replay.capacity": 400, "replay.learning_starts": 60,
        "actor.anakin_lanes": 2, "env.episode_len": 20,
        "replay.block_length": 10, "replay.batch_size": 4,
        "runtime.save_dir": str(tmp_path), "runtime.log_interval": 0.2,
    })
    records = []
    stacks = train(cfg, max_training_steps=6, max_seconds=120,
                   log_fn=records.append)
    lr = stacks[0].learner
    assert lr.training_steps >= 6
    assert lr.env_steps >= cfg.replay.learning_starts
    assert lr.ring.buffer_steps > 0
    # records are emitted at log-interval boundaries (the final partial
    # interval flushes metrics without a record, like the host loop)
    assert records and records[-1]["buffer_size"] > 0
    assert any(r["training_steps"] >= 1 for r in records)


def test_on_device_kill_switch_routes_and_legacy_untouched(monkeypatch):
    """actor.on_device=False (the default) never touches the anakin loop;
    True delegates before any fleet/queue/weight-service construction."""
    from r2d2_tpu.runtime import anakin_loop, orchestrator
    assert Config().actor.on_device is False

    sentinel = object()
    called = {}

    def fake_run(cfg, **kw):
        called["cfg"] = cfg
        return sentinel

    monkeypatch.setattr(anakin_loop, "run_anakin_train", fake_run)
    out = orchestrator.train(small_cfg(), max_training_steps=1)
    assert out is sentinel and called["cfg"].actor.on_device

    # off: the delegation must NOT fire (legacy path runs; bound to a
    # trivially short thread-mode run)
    def boom(cfg, **kw):  # pragma: no cover - failure path
        raise AssertionError("anakin loop reached with on_device=False")

    monkeypatch.setattr(anakin_loop, "run_anakin_train", boom)
    cfg_off = small_cfg(**{"actor.on_device": False,
                           "actor.num_actors": 1,
                           "replay.learning_starts": 40})
    stacks = orchestrator.train(cfg_off, max_training_steps=1,
                                max_seconds=25, actor_mode="thread")
    assert stacks[0].learner.training_steps >= 0


# ---- learnability (slow) -------------------------------------------------

GRID_TRAIN_STEPS = 2000


def _grid_cfg(save_dir: str) -> Config:
    return Config().replace(**{
        "env.game_name": "Grid", "env.grid_size": 5,
        "env.frame_height": 20, "env.frame_width": 20,
        "env.frame_stack": 2, "env.episode_len": 40,
        "network.hidden_dim": 32, "network.cnn_out_dim": 64,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 32_000, "replay.block_length": 40,
        "replay.batch_size": 16, "replay.learning_starts": 2_000,
        "replay.max_env_steps_per_train_step": 16.0,
        "actor.on_device": True, "actor.anakin_lanes": 32,
        "optim.lr": 1e-3, "optim.gamma": 0.99,
        "runtime.save_interval": 0, "runtime.log_interval": 8.0,
        "runtime.save_dir": save_dir,
    })


def _grid_train(save_dir: str) -> dict:
    from r2d2_tpu.runtime.anakin_loop import run_anakin_train
    records = []
    stacks = run_anakin_train(_grid_cfg(save_dir),
                              max_training_steps=GRID_TRAIN_STEPS,
                              max_seconds=600, log_fn=records.append)
    returns = [r["avg_episode_return"] for r in records
               if r.get("avg_episode_return") is not None]
    return {"training_steps": int(stacks[0].learner.training_steps),
            "returns": returns}


@pytest.mark.slow
def test_grid_learnability_under_fused_loop(tmp_path):
    """The jitted gridworld visibly LEARNS under the fused act+train
    loop: the near-greedy lanes' behavior return grows several-fold from
    the first logged interval to the last (measured 0.09 -> 1.15 over
    2000 steps on the 2-core container; asserted with wide margins).
    Runs in a subprocess on a plain single-device CPU backend — the
    suite's 8-virtual-device pin triples single-core wall time."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-4000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["training_steps"] >= GRID_TRAIN_STEPS
    returns = result["returns"]
    assert len(returns) >= 2, returns
    early, late = returns[0], returns[-1]
    assert late >= max(3.0 * early, early + 0.3), returns


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from r2d2_tpu.utils.platform import pin_platform
    pin_platform()
    print(json.dumps(_grid_train(sys.argv[1])))
