"""Environment-layer tests: fake env determinism, wrapper stack, factory
gating, and the ViZDoom pure logic (DELTA expansion, action vectors, shaped
reward, game args) — hermetic, no engine (SURVEY §4)."""

import numpy as np
import pytest

from r2d2_tpu.config import EnvConfig
from r2d2_tpu.envs import FakeR2D2Env, create_env
from r2d2_tpu.envs.vizdoom_defs import (
    MULTI_REWARD_SCENARIOS,
    SCENARIOS,
    build_action_vector,
    expand_buttons,
    host_game_args,
    join_game_args,
    shaped_multiplayer_reward,
)
from r2d2_tpu.envs.wrappers import ClipReward, GymnasiumAdapter, WarpFrame


def test_fake_env_deterministic_and_learnable():
    e1, e2 = FakeR2D2Env(seed=3), FakeR2D2Env(seed=3)
    o1, o2 = e1.reset(), e2.reset()
    np.testing.assert_array_equal(o1, o2)
    r_total = 0.0
    for t in range(e1.episode_len):
        target = int(e1._schedule[e1.t])
        obs, r, done, _ = e1.step(target)      # oracle policy gets reward 1
        r_total += r
    assert done and r_total == e1.episode_len


def test_fake_env_wrapped_by_factory():
    cfg = EnvConfig(game_name="Fake", frame_height=84, frame_width=84)
    env = create_env(cfg, clip_rewards=True, seed=0)
    obs = env.reset()
    assert obs.shape == (84, 84) and obs.dtype == np.uint8
    obs, r, done, info = env.step(env.action_space.sample())
    assert -1.0 <= r <= 1.0


def test_warpframe_grayscale_resize():
    class RGBEnv:
        class action_space:
            n = 2
        def reset(self):
            return np.full((120, 160, 3), 100, np.uint8)
        def step(self, a):
            return np.full((120, 160, 3), 200, np.uint8), 5.0, False, {}
        def close(self):
            pass

    env = WarpFrame(RGBEnv(), 84, 84)
    obs = env.reset()
    assert obs.shape == (84, 84) and obs.dtype == np.uint8
    assert abs(int(obs.mean()) - 100) <= 2   # gray of uniform gray
    obs, r, done, _ = env.step(0)
    assert r == 5.0 and abs(int(obs.mean()) - 200) <= 2


def test_numpy_fallback_matches_cv2_at_atari_ratios(monkeypatch):
    """The cv2-less WarpFrame fallback on REAL Atari geometry — 210x160 ->
    84x84, non-integer ratios 2.5 and 1.9047 (VERDICT r4 weak #5): the
    area resample must track cv2's INTER_AREA within fixed-point rounding,
    so a cv2-less host trains on observations the reference's
    preprocessing (ref environment.py:71-75) would also produce."""
    cv2 = pytest.importorskip("cv2")
    from r2d2_tpu.envs import wrappers as W

    rng = np.random.default_rng(0)
    for _ in range(3):
        frame = rng.integers(0, 256, (210, 160), np.uint8)
        want = cv2.resize(frame, (84, 84), interpolation=cv2.INTER_AREA)
        monkeypatch.setattr(W, "_HAS_CV2", False)
        monkeypatch.setattr(W, "_warned_fallback", True)
        got = W._resize(frame, 84, 84)
        monkeypatch.setattr(W, "_HAS_CV2", True)
        diff = np.abs(got.astype(np.int32) - want.astype(np.int32))
        assert diff.max() <= 1, diff.max()

    # full RGB pipeline (gray coefficients differ only in fixed-point too)
    rgb = rng.integers(0, 256, (210, 160, 3), np.uint8)
    want = cv2.resize(cv2.cvtColor(rgb, cv2.COLOR_RGB2GRAY), (84, 84),
                      interpolation=cv2.INTER_AREA)
    monkeypatch.setattr(W, "_HAS_CV2", False)
    got = W._resize(W._to_gray(rgb), 84, 84)
    diff = np.abs(got.astype(np.int32) - want.astype(np.int32))
    assert diff.max() <= 2, diff.max()


def test_cv2less_fallback_warns_once(monkeypatch):
    """A cv2-less deployment must be told loudly — once — that WarpFrame
    is not bit-identical to the reference preprocessing (VERDICT r4)."""
    from r2d2_tpu.envs import wrappers as W
    monkeypatch.setattr(W, "_HAS_CV2", False)
    monkeypatch.setattr(W, "_warned_fallback", False)
    frame = np.zeros((210, 160), np.uint8)
    with pytest.warns(UserWarning, match="numpy area-resample fallback"):
        W._resize(frame, 84, 84)
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")      # a second warning would raise
        W._resize(frame, 84, 84)


def test_clip_reward():
    class E:
        class action_space:
            n = 1
        def step(self, a):
            return None, -3.7, False, {}
    assert ClipReward(E()).step(0)[1] == -1.0


def test_gymnasium_adapter_5tuple():
    class G5:
        class action_space:
            n = 1
        def reset(self):
            return "obs", {"info": 1}
        def step(self, a):
            return "obs", 1.0, False, True, {}
    env = GymnasiumAdapter(G5())
    assert env.reset() == "obs"
    obs, r, done, info = env.step(0)
    assert done is True   # truncated folds into done


# ---- ViZDoom pure logic (ref base_gym_env.py:114-154,190-214) ----


def test_scenario_registry_complete():
    assert len(SCENARIOS) == 14
    assert SCENARIOS["VizdoomBasic-v0"] == "basic.cfg"
    assert SCENARIOS["VizdoomBasicDeathmatch-v0"] == "multi.cfg"
    assert SCENARIOS["VizdoomSingleDeathmatch-v0"] == "multi_single.cfg"
    assert MULTI_REWARD_SCENARIOS == ("multi_single.cfg",)


def test_delta_button_expansion():
    names, nd = expand_buttons(["ATTACK", "TURN_LEFT_RIGHT_DELTA", "MOVE_LEFT"])
    assert nd == 1
    assert names == ["ATTACK", "TURN_LEFT_RIGHT_DELTA_POS_0",
                     "TURN_LEFT_RIGHT_DELTA_NEG_0", "MOVE_LEFT"]


@pytest.mark.parametrize("buttons,action,expected", [
    # no deltas: plain one-hot (ref base_gym_env.py:153-154)
    (["ATTACK", "MOVE_LEFT"], 1, [0, 1]),
    # delta POS at expanded idx 1 → +1 in engine slot 1
    (["ATTACK", "TURN_DELTA", "MOVE"], 1, [0, 1, 0]),
    # delta NEG at expanded idx 2 → -1 in engine slot 1
    (["ATTACK", "TURN_DELTA", "MOVE"], 2, [0, -1, 0]),
    # expanded MOVE shifted by one: expanded idx 3 → engine slot 2
    (["ATTACK", "TURN_DELTA", "MOVE"], 3, [0, 0, 1]),
])
def test_action_vectors(buttons, action, expected):
    names, nd = expand_buttons(buttons)
    assert build_action_vector(action, names, nd) == expected


def test_shaped_multiplayer_reward_cases():
    cfg = EnvConfig()
    # (health, hits, ammo, frags)
    base = (100, 0, 50, 0)
    assert shaped_multiplayer_reward(base, (80, 0, 50, 0), cfg) == -20.0
    assert shaped_multiplayer_reward(base, (0, 0, 50, 0), cfg) == -100.0
    assert shaped_multiplayer_reward(base, (100, 0, 49, 0), cfg) == -5.0
    assert shaped_multiplayer_reward(base, (100, 1, 50, 0), cfg) == 25.0
    assert shaped_multiplayer_reward(base, (100, 0, 50, 1), cfg) == 100.0
    # combo: hit + ammo spent
    assert shaped_multiplayer_reward(base, (100, 1, 49, 0), cfg) == 20.0


def test_compose_render_image():
    """Render composition (ref base_gym_env.py:242-297) as pure numpy: panel
    stacking order, depth tiling, label recoloring, terminal black frame."""
    from r2d2_tpu.envs.vizdoom_defs import compose_render_image

    h, w = 6, 8
    screen = np.full((h, w, 3), 10, np.uint8)
    depth = np.full((h, w), 77, np.uint8)
    labels_buffer = np.zeros((h, w), np.uint8)
    labels_buffer[2, 3] = 9
    palette = np.arange(256 * 3, dtype=np.uint8).reshape(256, 3)
    automap = np.full((h, w, 3), 200, np.uint8)

    img = compose_render_image(
        (h, w, 3), screen=screen, depth=depth, labels_buffer=labels_buffer,
        labels=[(300, 9)], automap=automap, label_colors=palette)
    assert img.shape == (h, 4 * w, 3)
    np.testing.assert_array_equal(img[:, :w], screen)          # panel 1
    assert (img[:, w:2 * w] == 77).all()                       # depth tiled
    np.testing.assert_array_equal(img[2, 2 * w + 3],
                                  palette[300 % 256])          # label color
    assert (img[0, 2 * w:3 * w] == 0).all()                    # mask bg black
    np.testing.assert_array_equal(img[:, 3 * w:], automap)     # panel 4

    # screen-only: no extra panels
    assert compose_render_image((h, w, 3), screen=screen).shape == (h, w, 3)
    # terminal state: black image sized for the enabled panel count
    black = compose_render_image((h, w, 3), n_panels=4)
    assert black.shape == (h, 4 * w, 3) and not black.any()


def test_game_args():
    h = host_game_args(2, 5060)
    assert "-host 2" in h and "-port 5060" in h and "-deathmatch" in h
    assert "+sv_forcerespawn 1" in h and "+viz_nocheat 1" in h
    assert join_game_args("127.0.0.1", 5061) == "-join 127.0.0.1 -port 5061"


def test_vizdoom_gated_import():
    cfg = EnvConfig(game_name="Vizdoom", env_type="Basic-v0")
    with pytest.raises(ImportError, match="vizdoom"):
        create_env(cfg)


# ---- gymnasium-backend conformance (the ALE path, ref environment.py:82-93)
# ale_py is not installable in this build environment (no network installs);
# a registered RGB stub drives the identical factory branch — real gymnasium
# registry, real make(), adapter, WarpFrame, ClipReward. The tests below it
# run the true engines whenever ale_py / vizdoom become importable.
# Re-checked 2026-07-29 (round 3): `import ale_py` / `import vizdoom` still
# raise ModuleNotFoundError, no vendored wheels in the image, and installs
# remain policy-forbidden (no network). gymnasium 1.2.2 itself is present,
# so the stub-driven factory branch is the live coverage.


def _register_stub_ale():
    gymnasium = pytest.importorskip("gymnasium")
    from gymnasium import spaces

    class StubALE(gymnasium.Env):
        """210x160 RGB Atari-shaped env with out-of-range rewards."""

        action_space = spaces.Discrete(4)
        observation_space = spaces.Box(0, 255, (210, 160, 3), np.uint8)

        def __init__(self, frameskip: int = 1):
            self.frameskip = frameskip
            self._t = 0

        def reset(self, *, seed=None, options=None):
            self._t = 0
            return np.full((210, 160, 3), 100, np.uint8), {}

        def step(self, action):
            self._t += 1
            obs = np.full((210, 160, 3), 50 + 10 * self._t, np.uint8)
            return obs, 2.5, self._t >= 10, False, {}

    if "StubALE-v5" not in gymnasium.registry:
        gymnasium.register(id="StubALE-v5",
                           entry_point=lambda **kw: StubALE(**kw))
    return gymnasium


def test_gymnasium_backend_conformance():
    _register_stub_ale()
    cfg = EnvConfig(game_name="StubALE", env_type="-v5",
                    frame_height=84, frame_width=84)
    env = create_env(cfg, clip_rewards=True, seed=0)
    obs = env.reset()
    assert obs.shape == (84, 84) and obs.dtype == np.uint8
    steps = 0
    done = False
    while not done:
        obs, r, done, _ = env.step(env.action_space.sample())
        steps += 1
        assert obs.shape == (84, 84)
        assert r == 1.0          # 2.5 clipped (training path)
    assert steps == 10
    env.close()

    # eval path: rewards unclipped (ref test.py:97 clip_rewards=False)
    env = create_env(cfg, clip_rewards=False, seed=0)
    env.reset()
    assert env.step(0)[1] == 2.5
    env.close()


def test_gymnasium_frameskip_passthrough():
    gymnasium = _register_stub_ale()
    cfg = EnvConfig(game_name="StubALE", env_type="-v5", frame_skip=4)
    env = create_env(cfg, clip_rewards=False)
    # the factory forwards frame_skip as the backend's native frameskip
    # (ref environment.py:83 passes frame_skip into gym.make)
    inner = env
    while hasattr(inner, "env"):
        inner = inner.env
    inner = getattr(inner, "unwrapped", inner)
    assert inner.frameskip == 4
    env.close()


def test_real_ale_boxing_episode():
    """Runs the true ALE backend when ale_py is importable (not installable
    in this build env — documented in README); skipped otherwise."""
    pytest.importorskip("ale_py")
    cfg = EnvConfig(game_name="ALE/Boxing", env_type="-v5")
    env = create_env(cfg, clip_rewards=False, seed=0)
    obs = env.reset()
    assert obs.shape == (84, 84) and obs.dtype == np.uint8
    for _ in range(20):
        obs, r, done, _ = env.step(env.action_space.sample())
        assert obs.shape == (84, 84)
        if done:
            env.reset()
    env.close()


def test_real_vizdoom_basic_episode():
    """Runs the true ViZDoom engine when vizdoom is importable; skipped
    otherwise (the env shell's pure logic is tested above either way)."""
    pytest.importorskip("vizdoom")
    cfg = EnvConfig(game_name="Vizdoom", env_type="Basic-v0")
    env = create_env(cfg, clip_rewards=False, seed=0)
    obs = env.reset()
    assert obs.shape == (84, 84) and obs.dtype == np.uint8
    for _ in range(10):
        obs, r, done, _ = env.step(env.action_space.sample())
        if done:
            env.reset()
    env.close()
