"""Environment-layer tests: fake env determinism, wrapper stack, factory
gating, and the ViZDoom pure logic (DELTA expansion, action vectors, shaped
reward, game args) — hermetic, no engine (SURVEY §4)."""

import numpy as np
import pytest

from r2d2_tpu.config import EnvConfig
from r2d2_tpu.envs import FakeR2D2Env, create_env
from r2d2_tpu.envs.vizdoom_defs import (
    MULTI_REWARD_SCENARIOS,
    SCENARIOS,
    build_action_vector,
    expand_buttons,
    host_game_args,
    join_game_args,
    shaped_multiplayer_reward,
)
from r2d2_tpu.envs.wrappers import ClipReward, GymnasiumAdapter, WarpFrame


def test_fake_env_deterministic_and_learnable():
    e1, e2 = FakeR2D2Env(seed=3), FakeR2D2Env(seed=3)
    o1, o2 = e1.reset(), e2.reset()
    np.testing.assert_array_equal(o1, o2)
    r_total = 0.0
    for t in range(e1.episode_len):
        target = int(e1._schedule[e1.t])
        obs, r, done, _ = e1.step(target)      # oracle policy gets reward 1
        r_total += r
    assert done and r_total == e1.episode_len


def test_fake_env_wrapped_by_factory():
    cfg = EnvConfig(game_name="Fake", frame_height=84, frame_width=84)
    env = create_env(cfg, clip_rewards=True, seed=0)
    obs = env.reset()
    assert obs.shape == (84, 84) and obs.dtype == np.uint8
    obs, r, done, info = env.step(env.action_space.sample())
    assert -1.0 <= r <= 1.0


def test_warpframe_grayscale_resize():
    class RGBEnv:
        class action_space:
            n = 2
        def reset(self):
            return np.full((120, 160, 3), 100, np.uint8)
        def step(self, a):
            return np.full((120, 160, 3), 200, np.uint8), 5.0, False, {}
        def close(self):
            pass

    env = WarpFrame(RGBEnv(), 84, 84)
    obs = env.reset()
    assert obs.shape == (84, 84) and obs.dtype == np.uint8
    assert abs(int(obs.mean()) - 100) <= 2   # gray of uniform gray
    obs, r, done, _ = env.step(0)
    assert r == 5.0 and abs(int(obs.mean()) - 200) <= 2


def test_clip_reward():
    class E:
        class action_space:
            n = 1
        def step(self, a):
            return None, -3.7, False, {}
    assert ClipReward(E()).step(0)[1] == -1.0


def test_gymnasium_adapter_5tuple():
    class G5:
        class action_space:
            n = 1
        def reset(self):
            return "obs", {"info": 1}
        def step(self, a):
            return "obs", 1.0, False, True, {}
    env = GymnasiumAdapter(G5())
    assert env.reset() == "obs"
    obs, r, done, info = env.step(0)
    assert done is True   # truncated folds into done


# ---- ViZDoom pure logic (ref base_gym_env.py:114-154,190-214) ----


def test_scenario_registry_complete():
    assert len(SCENARIOS) == 14
    assert SCENARIOS["VizdoomBasic-v0"] == "basic.cfg"
    assert SCENARIOS["VizdoomBasicDeathmatch-v0"] == "multi.cfg"
    assert SCENARIOS["VizdoomSingleDeathmatch-v0"] == "multi_single.cfg"
    assert MULTI_REWARD_SCENARIOS == ("multi_single.cfg",)


def test_delta_button_expansion():
    names, nd = expand_buttons(["ATTACK", "TURN_LEFT_RIGHT_DELTA", "MOVE_LEFT"])
    assert nd == 1
    assert names == ["ATTACK", "TURN_LEFT_RIGHT_DELTA_POS_0",
                     "TURN_LEFT_RIGHT_DELTA_NEG_0", "MOVE_LEFT"]


@pytest.mark.parametrize("buttons,action,expected", [
    # no deltas: plain one-hot (ref base_gym_env.py:153-154)
    (["ATTACK", "MOVE_LEFT"], 1, [0, 1]),
    # delta POS at expanded idx 1 → +1 in engine slot 1
    (["ATTACK", "TURN_DELTA", "MOVE"], 1, [0, 1, 0]),
    # delta NEG at expanded idx 2 → -1 in engine slot 1
    (["ATTACK", "TURN_DELTA", "MOVE"], 2, [0, -1, 0]),
    # expanded MOVE shifted by one: expanded idx 3 → engine slot 2
    (["ATTACK", "TURN_DELTA", "MOVE"], 3, [0, 0, 1]),
])
def test_action_vectors(buttons, action, expected):
    names, nd = expand_buttons(buttons)
    assert build_action_vector(action, names, nd) == expected


def test_shaped_multiplayer_reward_cases():
    cfg = EnvConfig()
    # (health, hits, ammo, frags)
    base = (100, 0, 50, 0)
    assert shaped_multiplayer_reward(base, (80, 0, 50, 0), cfg) == -20.0
    assert shaped_multiplayer_reward(base, (0, 0, 50, 0), cfg) == -100.0
    assert shaped_multiplayer_reward(base, (100, 0, 49, 0), cfg) == -5.0
    assert shaped_multiplayer_reward(base, (100, 1, 50, 0), cfg) == 25.0
    assert shaped_multiplayer_reward(base, (100, 0, 50, 1), cfg) == 100.0
    # combo: hit + ammo spent
    assert shaped_multiplayer_reward(base, (100, 1, 49, 0), cfg) == 20.0


def test_game_args():
    h = host_game_args(2, 5060)
    assert "-host 2" in h and "-port 5060" in h and "-deathmatch" in h
    assert "+sv_forcerespawn 1" in h and "+viz_nocheat 1" in h
    assert join_game_args("127.0.0.1", 5061) == "-join 127.0.0.1 -port 5061"


def test_vizdoom_gated_import():
    cfg = EnvConfig(game_name="Vizdoom", env_type="Basic-v0")
    with pytest.raises(ImportError, match="vizdoom"):
        create_env(cfg)
