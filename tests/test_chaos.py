"""Worker-health subsystem tests driven by the deterministic fault-injection
harness (tools/chaos.py): heartbeats, hang watchdog, restart backoff, the
crash-loop circuit breaker, ingest stall detection, and the end-to-end chaos
slices where REAL killed/wedged workers exercise all of it (the failure
handling the reference lacks entirely, SURVEY §5.3).
"""

import threading
import time

import numpy as np
import pytest

from r2d2_tpu.config import Config
from r2d2_tpu.runtime.feeder import (
    BlockQueue, HeartbeatBoard, IngestStallDetector, WorkerHealth,
    supervise_workers)
from r2d2_tpu.tools.chaos import (
    ChaosFault, FaultSpec, apply_fault, parse_fault_spec)

from tests.test_runtime import tiny_config

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# fault-spec grammar


def test_parse_fault_spec_grammar():
    faults = parse_fault_spec("1:crash@block=3;2:hang@block=5;0:slowx4")
    assert faults[1] == FaultSpec("crash", block=3)
    assert faults[2] == FaultSpec("hang", block=5)
    assert faults[0] == FaultSpec("slow", factor=4.0)
    assert parse_fault_spec("0:slow@factor=2.5")[0].factor == 2.5
    assert parse_fault_spec("") == {}
    assert parse_fault_spec(" 1:crash@block=1 ; ")[1].block == 1


@pytest.mark.parametrize("bad", [
    "nocolon", "x:crash@block=1", "-1:crash@block=1", "0:boom",
    "0:crash", "0:crash@block=0", "0:crash@block=x", "0:hang",
    "0:slow", "0:slow@factor=1.0", "0:slowxfast",
    "0:crash@block=1;0:hang@block=2",          # duplicate slot
])
def test_parse_fault_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_fault_spec(bad)


def test_config_validates_fault_spec():
    cfg = Config()
    cfg.replace(**{"actor.fault_spec": "1:crash@block=2"})   # in range: ok
    with pytest.raises(ValueError, match="outside the fleet"):
        cfg.replace(**{"actor.fault_spec": "7:crash@block=2"})
    with pytest.raises(ValueError, match="unknown kind"):
        cfg.replace(**{"actor.fault_spec": "0:explode"})
    with pytest.raises(ValueError, match="hang_timeout_s"):
        cfg.replace(**{"runtime.hang_timeout_s": -1.0})
    with pytest.raises(ValueError, match="supervise_interval_s"):
        cfg.replace(**{"runtime.supervise_interval_s": 0.0})


def test_apply_fault_crash_and_slow():
    emitted = []
    crash = apply_fault(emitted.append, FaultSpec("crash", block=3))
    crash("a"); crash("b")
    with pytest.raises(ChaosFault):
        crash("c")
    assert emitted == ["a", "b"]          # block 3 died with the block in hand

    got = []
    slow = apply_fault(got.append, FaultSpec("slow", factor=3.0))
    slow("x")                              # first emit: no interval yet
    time.sleep(0.05)
    t0 = time.monotonic()
    slow("y")                              # sleeps ~2x the 0.05s interval
    assert time.monotonic() - t0 >= 0.08
    assert got == ["x", "y"]


# ---------------------------------------------------------------------------
# heartbeat board


def test_heartbeat_board_beat_touch_reset():
    board = HeartbeatBoard(3)
    try:
        assert board.counts().tolist() == [0.0, 0.0, 0.0]
        board.beat(1)
        board.beat(1)
        assert board.count(1) == 2
        assert board.age(1) < 1.0
        # touch: liveness without progress
        board._ensure()[2, 1] = time.time() - 50.0
        assert board.age(2) > 49.0
        board.touch(2)
        assert board.age(2) < 1.0 and board.count(2) == 0
        board.reset_slot(1)
        assert board.count(1) == 0 and board.age(1) < 1.0
    finally:
        board.close()


def test_heartbeat_board_crosses_pickle_boundary():
    """The spawn-mode contract: the pickled handle attaches to the SAME
    region (one writer's beats visible to the other side)."""
    import pickle

    board = HeartbeatBoard(2)
    attached = pickle.loads(pickle.dumps(board))
    try:
        attached.beat(0)
        assert board.count(0) == 1
        board.beat(0)
        assert attached.count(0) == 2
    finally:
        attached.close()
        board.close()


def test_put_patient_beats_while_parked():
    """A producer parked under back-pressure keeps publishing liveness —
    back-pressure must never read as a hang to the watchdog."""
    q = BlockQueue(maxsize=1, use_mp=False)
    q.put("a")                             # full
    beats = []
    t = threading.Thread(
        target=lambda: q.put_patient("b", should_stop=lambda: False,
                                     poll=0.05, beat=lambda: beats.append(1)))
    t.start()
    time.sleep(0.3)
    assert t.is_alive() and len(beats) >= 3   # parked, still beating
    assert q.drain(max_items=1) == ["a"]
    t.join(timeout=5.0)
    assert q.drain() == ["b"]


# ---------------------------------------------------------------------------
# restart backoff + circuit breaker (WorkerHealth policy, deterministic time)


def test_backoff_ladder_is_exponential_and_capped():
    h = WorkerHealth(1, backoff_base_s=2.0, backoff_max_s=5.0,
                     restart_window_s=100.0)
    h.on_failure(0, now=10.0)
    assert h.respawn_due(0, now=10.0)          # first failure: immediate
    h.on_failure(0, now=20.0)
    assert not h.respawn_due(0, now=21.0)      # 2nd: base backoff (2s)
    assert h.respawn_due(0, now=22.1)
    h.on_failure(0, now=30.0)
    assert not h.respawn_due(0, now=33.0)      # 3rd: 2*base (4s)
    assert h.respawn_due(0, now=34.1)
    h.on_failure(0, now=40.0)
    assert h.respawn_due(0, now=45.1)          # 4th: capped at max (5s), not 8
    assert not h.respawn_due(0, now=44.9)
    # window expiry resets the ladder: a failure long after the last one
    # respawns immediately again
    h.on_failure(0, now=500.0)
    assert h.respawn_due(0, now=500.0)


def test_breaker_parks_slot_after_window_budget():
    h = WorkerHealth(2, backoff_base_s=0.0, max_restarts_per_window=2,
                     restart_window_s=100.0)
    h.on_failure(0, now=1.0)
    h.on_failure(0, now=2.0)
    assert not h.is_parked(0)
    h.on_failure(0, now=3.0)                   # 3rd failure in window: trip
    assert h.is_parked(0)
    assert not h.respawn_due(0, now=999.0)     # parked = parked forever
    assert not h.is_parked(1)                  # per-slot
    snap = h.snapshot()
    assert snap["actor_breaker_trips"] == 1
    assert snap["actor_parked_slots"] == 1


def test_breaker_disabled_by_zero():
    h = WorkerHealth(1, backoff_base_s=0.0, max_restarts_per_window=0)
    for k in range(20):
        h.on_failure(0, now=float(k))
    assert not h.is_parked(0)


# ---------------------------------------------------------------------------
# supervise_workers: hang watchdog, backoff, breaker integration


class StubWorker:
    def __init__(self, alive=True, ignore_terminate=False):
        self.alive = alive
        self.terminated = self.killed = False
        self._ignore = ignore_terminate
        self.health_cancel = threading.Event()

    def is_alive(self):
        return self.alive

    def terminate(self):
        self.terminated = True
        if not self._ignore:
            self.alive = False

    def kill(self):
        self.killed = True
        self.alive = False

    def join(self, timeout=None):
        pass


def _stale_board(n, slot, age, count=1):
    board = HeartbeatBoard(n)
    arr = board._ensure()
    arr[slot] = (count, time.time() - age)
    return board


def test_watchdog_kills_and_respawns_hung_worker():
    """Alive-but-silent worker: killed (terminate), counted as a hang,
    replaced through the normal respawn path."""
    board = _stale_board(1, 0, age=100.0)
    try:
        h = WorkerHealth(1, board, hang_timeout_s=5.0, hang_spawn_grace_s=5.0)
        hung = StubWorker(alive=True)
        workers, seen, spawned = [hung], set(), []

        def respawn(i):
            board.reset_slot(i)
            spawned.append(i)
            return StubWorker(alive=True)

        assert supervise_workers(workers, seen, respawn=respawn, health=h) == 1
        assert hung.terminated and not hung.alive
        assert h.hangs_detected == 1 and h.restarts == 1
        assert spawned == [0]
        # the fresh incarnation (board just reset) is NOT hung
        assert supervise_workers(workers, seen, respawn=respawn, health=h) == 0
        assert h.hangs_detected == 1
    finally:
        board.close()


def test_watchdog_escalates_to_kill_and_flags_threads():
    board = _stale_board(2, 0, age=100.0)
    board._ensure()[1] = (1, time.time() - 100.0)
    try:
        h = WorkerHealth(2, board, hang_timeout_s=5.0, hang_spawn_grace_s=5.0)
        stubborn = StubWorker(alive=True, ignore_terminate=True)

        class ThreadStub:                      # no terminate/kill surface
            health_cancel = threading.Event()

            def is_alive(self):
                return True

        threadlike = ThreadStub()
        workers, seen = [stubborn, threadlike], set()
        supervise_workers(workers, seen, respawn=lambda i: None, health=h)
        assert stubborn.terminated and stubborn.killed     # escalation
        assert threadlike.health_cancel.is_set()           # flagged
        assert threadlike.is_alive()                       # ...but unkillable
        assert h.hangs_detected == 2
    finally:
        board.close()


def test_watchdog_spawn_grace_covers_bringup():
    """Before the incarnation's FIRST beat the (longer) spawn grace
    applies — slow process bring-up is not a hang; after a beat the regular
    timeout takes over."""
    board = _stale_board(1, 0, age=10.0, count=0)    # 10s old, never beat
    try:
        h = WorkerHealth(1, board, hang_timeout_s=5.0,
                         hang_spawn_grace_s=60.0)
        assert not h.check_hung(0, time.time())      # inside spawn grace
        board._ensure()[0, 0] = 1.0                  # first beat happened
        assert h.check_hung(0, time.time())          # now 5s rule applies
    finally:
        board.close()


def test_supervise_workers_backoff_defers_respawn():
    h = WorkerHealth(1, backoff_base_s=0.3, backoff_max_s=5.0)
    workers, seen = [StubWorker(alive=False)], set()
    respawn = lambda i: StubWorker(alive=True)
    assert supervise_workers(workers, seen, respawn=respawn, health=h) == 1
    workers[0].alive = False                       # dies again immediately
    # 2nd failure: recorded once, respawn deferred by the 0.3s backoff
    assert supervise_workers(workers, seen, respawn=respawn, health=h) == 0
    assert supervise_workers(workers, seen, respawn=respawn, health=h) == 0
    assert len(h._windows[0]) == 2                 # corpse counted ONCE
    time.sleep(0.35)
    assert supervise_workers(workers, seen, respawn=respawn, health=h) == 1
    assert h.restarts == 2


def test_supervise_workers_parked_slot_stays_down():
    h = WorkerHealth(2, backoff_base_s=0.0, max_restarts_per_window=1)
    workers = [StubWorker(alive=False), StubWorker(alive=True)]
    seen = set()
    respawn_calls = []

    def respawn(i):
        respawn_calls.append(i)
        return StubWorker(alive=False)             # crash-loop: dies at once

    for _ in range(4):
        supervise_workers(workers, seen, respawn=respawn, health=h)
    assert h.is_parked(0) and h.breaker_trips == 1
    n = len(respawn_calls)
    supervise_workers(workers, seen, respawn=respawn, health=h)
    assert len(respawn_calls) == n                 # parked: no more respawns


# ---------------------------------------------------------------------------
# ingest stall detector


def test_stall_detector_one_shot_and_rearm():
    det = IngestStallDetector(timeout_s=10.0)
    dumps = []
    diag = lambda: dumps.append(1) or {"x": 1}
    assert not det.check(5, 2, False, now=0.0, diagnostics=diag)
    assert not det.check(5, 2, False, now=9.0, diagnostics=diag)
    assert det.check(5, 2, False, now=11.0, diagnostics=diag)      # fires
    assert not det.check(5, 2, False, now=50.0, diagnostics=diag)  # one-shot
    assert det.dumps == 1 and len(dumps) == 1
    # progress re-arms; a NEW stall episode fires again
    assert not det.check(6, 2, False, now=51.0, diagnostics=diag)
    assert det.check(6, 2, False, now=62.0, diagnostics=diag)
    assert det.dumps == 2


def test_stall_detector_ignores_limiter_pause_and_dead_fleet():
    det = IngestStallDetector(timeout_s=10.0)
    assert not det.check(5, 2, False, now=0.0)
    # rate-limiter pause: deliberate, clock restarts at unpause
    assert not det.check(5, 2, True, now=20.0)
    assert not det.check(5, 2, False, now=25.0)
    assert not det.check(5, 2, False, now=34.0)    # only 9s since unpause
    assert det.check(5, 2, False, now=36.0)
    # zero alive workers: the supervisor story, not a silent stall
    det2 = IngestStallDetector(timeout_s=10.0)
    assert not det2.check(5, 0, False, now=0.0)
    assert not det2.check(5, 0, False, now=100.0)
    # disabled
    det3 = IngestStallDetector(timeout_s=0.0)
    assert not det3.check(5, 2, False, now=0.0)
    assert not det3.check(5, 2, False, now=1000.0)


def test_metrics_record_carries_health_counters(tmp_path):
    from r2d2_tpu.runtime.metrics import TrainMetrics

    m = TrainMetrics(player_idx=0, log_dir=str(tmp_path))
    rec = m.log(1.0)
    assert rec["actor_restarts"] == 0 and rec["actor_hangs_detected"] == 0
    m.set_actor_health({"actor_restarts": 3, "actor_hangs_detected": 1,
                        "actor_breaker_trips": 1, "actor_parked_slots": 1,
                        "shm_slots_recovered": 2, "ingest_stall_dumps": 1,
                        "heartbeat_age_max_s": 4.2})
    rec = m.log(1.0)
    assert rec["actor_restarts"] == 3 and rec["actor_hangs_detected"] == 1
    assert rec["actor_breaker_trips"] == 1 and rec["actor_parked_slots"] == 1
    assert rec["shm_slots_recovered"] == 2 and rec["heartbeat_age_max_s"] == 4.2


# ---------------------------------------------------------------------------
# PlayerStack integration (no training loop needed)


def test_playerstack_close_escalates_to_kill(tmp_path):
    """Satellite: a terminate-ignoring child must be kill()ed by close(),
    never leaked as a zombie."""
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.orchestrator import PlayerStack

    cfg = tiny_config(tmp_path)
    probe = create_env(cfg.env)
    stack = PlayerStack(cfg, 0, probe.action_space.n)
    probe.close()
    stubborn = StubWorker(alive=True, ignore_terminate=True)
    polite = StubWorker(alive=True)
    stack.processes = [stubborn, polite]
    stack.close()
    assert stubborn.terminated and stubborn.killed and not stubborn.alive
    assert polite.terminated and not polite.killed


def test_learner_save_final_on_stop(tmp_path):
    """Satellite: save_final writes exactly one extra checkpoint when (and
    only when) training advanced past the last periodic save."""
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.learner_loop import Learner

    cfg = tiny_config(tmp_path)
    probe = create_env(cfg.env)
    net = NetworkApply(probe.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    probe.close()
    learner = Learner(cfg, net)
    assert learner.save_final() is None            # nothing trained yet
    learner._host_step = 7                         # mid-interval stop point
    path = learner.save_final()
    assert path is not None
    assert learner.save_final() is None            # already covered
    # disabled checkpointing: never writes
    learner2 = Learner(cfg.replace(**{"runtime.save_interval": 0}), net)
    learner2._host_step = 7
    assert learner2.save_final() is None


def test_thread_actors_publish_heartbeats_scalar_and_vector(tmp_path):
    """Heartbeat parity: scalar and vectorized thread actors both publish
    per-slot progress through the same board (process mode is asserted by
    the slow end-to-end chaos test)."""
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.orchestrator import PlayerStack

    for overrides in ({}, {"actor.num_actors": 1, "actor.envs_per_actor": 4}):
        cfg = tiny_config(tmp_path, **overrides)
        probe = create_env(cfg.env)
        stack = PlayerStack(cfg, 0, probe.action_space.n)
        probe.close()
        stop = threading.Event()
        stack.start_actors_threads(stop)
        try:
            deadline = time.time() + 90.0
            while (time.time() < deadline
                   and not (stack.heartbeats.counts() > 0).all()):
                stack.queue.drain(64)      # keep the queue from backing up
                time.sleep(0.05)
            counts = stack.heartbeats.counts()
            assert (counts > 0).all(), counts
            assert stack.heartbeats.ages().max() < 60.0
        finally:
            stop.set()
            stack.close()


# ---------------------------------------------------------------------------
# end-to-end chaos slices (real misbehaving workers)


@pytest.mark.slow
def test_warmup_crash_is_supervised(tmp_path):
    """Satellite: an actor that dies BEFORE learning_starts is respawned by
    the warm-up loop's supervision (it used to run unsupervised and wedge
    until the deadline)."""
    from r2d2_tpu.runtime.orchestrator import train

    cfg = tiny_config(tmp_path, **{
        "actor.num_actors": 2,
        # slot 1 dies on its FIRST emit, every incarnation — without
        # warm-up supervision half the fleet would stay down for good
        "actor.fault_spec": "1:crash@block=1",
        "runtime.save_interval": 0,
        "runtime.supervise_interval_s": 0.2,
        "runtime.restart_backoff_base_s": 0.1,
        "runtime.restart_backoff_max_s": 0.5,
        "runtime.max_restarts_per_window": 0,
    })
    stacks = train(cfg, max_training_steps=3, max_seconds=240,
                   actor_mode="thread")
    st = stacks[0]
    assert st.learner.training_steps >= 3          # warm-up completed
    assert st.health.restarts >= 1                 # ...under supervision


@pytest.mark.slow
def test_thread_crash_loop_trips_breaker_training_degrades(tmp_path):
    """Crash-loop → breaker parks the slot; training continues degraded on
    the healthy actor; counters land in the emitted metrics record."""
    from r2d2_tpu.runtime.orchestrator import train

    records = []
    cfg = tiny_config(tmp_path, **{
        "actor.num_actors": 2,
        "actor.fault_spec": "1:crash@block=1",
        "runtime.save_interval": 0, "runtime.log_interval": 0.5,
        "runtime.supervise_interval_s": 0.2,
        "runtime.restart_backoff_base_s": 0.05,
        "runtime.restart_backoff_max_s": 0.2,
        "runtime.max_restarts_per_window": 2,
        "runtime.restart_window_s": 300.0,
    })
    stacks = train(cfg, max_training_steps=10**9, max_seconds=45,
                   actor_mode="thread", log_fn=records.append)
    st = stacks[0]
    assert st.health.breaker_trips >= 1
    assert st.health.is_parked(1)
    assert st.health.restarts >= 2                  # backed-off respawns ran
    assert st.learner.training_steps > 0            # degraded, not dead
    last = records[-1]
    assert last["actor_breaker_trips"] >= 1
    assert last["actor_parked_slots"] == 1
    assert last["actor_restarts"] >= 2


@pytest.mark.slow
def test_process_hang_watchdog_end_to_end(tmp_path):
    """ACCEPTANCE: a hang (not a crash) injected into one process-mode
    actor — the watchdog detects it within hang_timeout_s, kills and
    respawns the worker with backoff, the shm ring keeps feeding (slot
    reclamation pass scheduled + ingestion continues), learner training
    steps advance throughout, and the hang/restart counters are visible in
    the emitted metrics records."""
    from r2d2_tpu.runtime.orchestrator import train

    records = []
    cfg = tiny_config(tmp_path, **{
        "actor.num_actors": 2,
        "actor.fault_spec": "1:hang@block=1",       # wedges on its 1st emit
        "runtime.save_interval": 0, "runtime.log_interval": 1.0,
        "runtime.supervise_interval_s": 0.5,
        "runtime.hang_timeout_s": 3.0,
        "runtime.hang_spawn_grace_s": 150.0,
        "runtime.restart_backoff_base_s": 0.5,
        "runtime.restart_backoff_max_s": 2.0,
        "runtime.max_restarts_per_window": 0,
    })
    stacks = train(cfg, max_training_steps=10**9, max_seconds=60,
                   actor_mode="process", log_fn=records.append)
    st = stacks[0]
    # watchdog saw the wedged worker and killed it; supervision respawned
    assert st.health.hangs_detected >= 1
    assert st.health.restarts >= 1
    # the kill routed through ring-slot reclamation scheduling
    assert st._ring_recovery._last_death > 0
    # the healthy actor's heartbeats flowed the whole time (process-mode
    # heartbeat parity)
    assert st.heartbeats.counts()[0] > 0
    # training ran throughout
    assert st.learner.training_steps > 0
    hang_recs = [r for r in records if r["actor_hangs_detected"] >= 1]
    assert hang_recs, "hang counter never reached the metrics records"
    first, last = hang_recs[0], records[-1]
    assert last["actor_restarts"] >= 1
    # the learner kept ingesting and training AFTER the hang was handled
    assert last["env_steps"] > first["env_steps"]
    assert last["training_steps"] > first["training_steps"]


@pytest.mark.slow
def test_chaos_harness_thread_mode(tmp_path):
    """tools/chaos.run_chaos (the soak's chaos phase): one healthy, one
    crash-looping (→ breaker), one hanging (→ watchdog) actor; the report
    must carry a full PASS verdict."""
    from r2d2_tpu.tools.chaos import run_chaos

    out = run_chaos(seconds=45.0, actor_mode="thread", config_overrides={
        "runtime.save_dir": str(tmp_path),
        "runtime.hang_timeout_s": 3.0,
        "runtime.hang_spawn_grace_s": 60.0,
        "runtime.restart_backoff_base_s": 0.1,
        "runtime.restart_backoff_max_s": 0.5,
    })
    assert out["verdict"]["trained_through_faults"], out
    assert out["verdict"]["hang_detected"], out
    assert out["verdict"]["breaker_parked_crash_loop"], out
    assert out["verdict"]["restarts_happened"], out
    assert out["heartbeat_counts"][0] > 0          # healthy slot progressed
    assert out["records"][-1]["actor_parked_slots"] >= 1
