"""Quantized inference plane tests (ISSUE 14): per-channel int8
quantize/dequantize round-trip bounds, greedy-action agreement vs the
f32 twin on the fixture net, publish-time bundle round-trips through
both weight stores (staleness stamps included), serve/local/anakin
switching through the ONE shared forward, the in-graph accuracy probe +
quant record block + quant_divergence rule, kill-switch schema
stability, pre-PR14 config round-trips, the costmodel's serve-bucket and
weight-bytes rows, and (slow) int8 gridworld learnability."""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import Config
from r2d2_tpu.models.network import (NetworkApply, initial_hidden,
                                     is_quant_bundle, make_inference_bundle,
                                     param_tree_bytes, quantize_leaf_int8,
                                     quantize_params,
                                     quantized_inference_apply)


def small_cfg(**overrides) -> Config:
    cfg = Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "env.episode_len": 40,
        "network.hidden_dim": 32, "network.cnn_out_dim": 64,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "runtime.save_interval": 0,
    })
    return cfg.replace(**overrides) if overrides else cfg


def small_net(cfg: Config, action_dim: int = 6) -> NetworkApply:
    return NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                        cfg.env.frame_height, cfg.env.frame_width)


def _inputs(cfg, n, seed=0, hidden_scale=0.0):
    rng = np.random.default_rng(seed)
    obs = rng.random((n, cfg.env.frame_height, cfg.env.frame_width,
                      cfg.env.frame_stack)).astype(np.float32)
    la = rng.integers(0, 6, n).astype(np.int32)
    hid = (rng.standard_normal((n, 2, cfg.network.hidden_dim))
           .astype(np.float32) * hidden_scale)
    return obs, la, hid


# ---------------------------------------------------------------------------
# quantize / dequantize math


def test_int8_round_trip_bound(rng):
    """Per-element reconstruction error of the per-channel symmetric
    scheme is bounded by scale/2 (round-to-nearest of w/scale)."""
    from r2d2_tpu.models.network import dequantize_leaf
    w = rng.standard_normal((7, 5, 3, 16)).astype(np.float32) * \
        rng.random(16).astype(np.float32)          # per-channel ranges
    leaf = jax.device_get(quantize_leaf_int8(w))
    assert leaf["q"].dtype == np.int8
    assert leaf["scale"].shape == (1, 1, 1, 16)    # one scale per out chan
    deq = np.asarray(dequantize_leaf(leaf, jnp.float32))
    bound = 0.5 * leaf["scale"] + 1e-7
    assert np.all(np.abs(deq - w) <= bound)


def test_int8_zero_channel_is_stable(rng):
    """An all-zero output channel must not divide by zero (scale floor)
    and must reconstruct exactly zero."""
    from r2d2_tpu.models.network import dequantize_leaf
    w = rng.standard_normal((4, 8)).astype(np.float32)
    w[:, 3] = 0.0
    leaf = quantize_leaf_int8(w)
    deq = np.asarray(dequantize_leaf(leaf, jnp.float32))
    assert np.all(np.isfinite(deq))
    assert np.all(deq[:, 3] == 0.0)


def test_quantize_params_modes():
    cfg = small_cfg()
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))
    # bf16: every float leaf halves
    b16 = quantize_params(params, "bf16")
    for leaf in jax.tree_util.tree_leaves(b16):
        assert leaf.dtype == jnp.bfloat16
    # int8: kernels (ndim >= 2) become {q, scale}; biases stay f32
    q8 = quantize_params(params, "int8")
    conv = q8["params"]["torso"]["Conv_0"]
    assert conv["kernel"]["q"].dtype == jnp.int8
    assert conv["bias"].dtype == jnp.float32
    lstm = q8["params"]["lstm"]
    assert lstm["recurrent_kernel"]["q"].dtype == jnp.int8
    assert lstm["bias"].dtype == jnp.float32
    # identity at f32
    assert quantize_params(params, "f32") is params
    # the byte cut the whole plane exists for
    assert param_tree_bytes(params) / param_tree_bytes(q8) >= 3.0
    assert abs(param_tree_bytes(params) / param_tree_bytes(b16) - 2.0) < 0.1


# ---------------------------------------------------------------------------
# the shared forward: f32 identity + quant agreement + the probe


def test_forward_f32_identical_to_module_apply():
    """inference_dtype='f32' leaves the shared forward the EXACT
    pre-PR14 program: same signature, outputs equal to a direct module
    apply."""
    from r2d2_tpu.actor.policy import make_forward_fn
    cfg = small_cfg()
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))
    obs, la, hid = _inputs(cfg, 4)
    fwd = make_forward_fn(net)                     # config default = f32
    a, q, h = fwd(params, obs, la, hid)
    la_1h = jax.nn.one_hot(la, 6, dtype=jnp.float32)[:, None]
    q_ref, h_ref = net.module.apply(params, obs[:, None], la_1h, hid)
    # allclose, not equal: the eager reference apply and the jitted
    # forward fuse differently on XLA:CPU (~1 ulp — the PR1 batched-
    # policy numerics note); actions are bit-identical regardless
    np.testing.assert_allclose(np.asarray(q), np.asarray(q_ref[:, 0]),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_array_equal(np.asarray(a),
                                  np.argmax(np.asarray(q_ref[:, 0]), -1))


@pytest.mark.parametrize("mode", ["int8", "bf16"])
def test_quant_forward_agreement(mode):
    """Greedy-action agreement vs the f32 twin on the fixture net: >=
    0.99 over states with any real Q margin, and every residual
    disagreement is a TIE FLIP — the f32 top-2 gap there is within the
    measured |ΔQ| (a random-init net's Q spread is ~1e-3, so counting
    coin-flip ties against the guard would test tie-breaking, not
    quantization; the trained-net line is the slow gridworld test +
    the live agree gauge)."""
    from r2d2_tpu.actor.policy import make_forward_fn
    cfg = small_cfg(**{"network.inference_dtype": mode})
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(1))
    bundle = make_inference_bundle(net, params, 1)
    qfwd = make_forward_fn(net)
    ffwd = make_forward_fn(net, "f32")
    agree = total = 0
    dq_max = qscale = 0.0
    for seed in range(4):
        obs, la, hid = _inputs(cfg, 64, seed=seed, hidden_scale=0.1)
        a_q, q_q, _, _probe = qfwd(bundle, obs, la, hid, np.int32(1),
                                   np.int32(64))
        a_f, q_f, _ = ffwd(params, obs, la, hid)
        a_q, a_f = np.asarray(a_q), np.asarray(a_f)
        q_f = np.asarray(q_f)
        dq = float(np.max(np.abs(np.asarray(q_q) - q_f)))
        dq_max = max(dq_max, dq)
        qscale = max(qscale, float(np.max(np.abs(q_f))))
        top2 = np.sort(q_f, axis=-1)
        margin = top2[:, -1] - top2[:, -2]          # f32 top-2 gap
        clear = margin > 2.0 * dq                   # not a tie flip
        agree += int(np.sum((a_q == a_f)[clear]))
        total += int(np.sum(clear))
        # disagreements only ever happen inside the tie band
        assert np.all((a_q == a_f) | ~clear)
    assert total >= 128, total                      # the mask kept most
    assert agree / total >= 0.99, (agree, total)
    assert dq_max <= 0.05 * max(qscale, 1e-3), (dq_max, qscale)


def test_quant_forward_f32_carry():
    """The quantized forward's recurrent state is f32 end to end: the
    returned packed hidden is f32, and feeding it back for many steps
    tracks the f32 twin's hidden closely (quantization error stays
    per-step, never compounding into the carry)."""
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(2))
    bundle = make_inference_bundle(net, params, 1)
    obs, la, _ = _inputs(cfg, 2)
    h_q = h_f = initial_hidden(2, cfg.network.hidden_dim)
    la_1h = jax.nn.one_hot(la, 6, dtype=jnp.float32)[:, None]
    for step in range(20):
        o = jnp.asarray(np.roll(obs, step, axis=1))[:, None]
        q_q, h_q = quantized_inference_apply(net, bundle["quant"], o,
                                             la_1h, h_q)
        q_f, h_f = net.module.apply(params, o, la_1h, h_f)
        assert np.asarray(h_q).dtype == np.float32
    gap = float(np.max(np.abs(np.asarray(h_q) - np.asarray(h_f))))
    assert gap < 0.05, gap


def test_probe_cadence():
    """The lax.cond probe fires exactly on tick % interval == 0 and
    reports sane numbers; probe_interval=0 compiles it out (flag always
    zero)."""
    from r2d2_tpu.actor.policy import make_forward_fn
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    bundle = make_inference_bundle(net, net.init(jax.random.PRNGKey(0)), 1)
    obs, la, hid = _inputs(cfg, 8)
    fwd = make_forward_fn(net, probe_interval=4)
    for tick, expect in ((0, 1.0), (1, 0.0), (3, 0.0), (4, 1.0), (8, 1.0)):
        _, _, _, (dq, agree, probed) = fwd(bundle, obs, la, hid,
                                           np.int32(tick), np.int32(8))
        assert float(probed) == expect, tick
        if expect:
            assert 0.0 <= float(agree) <= 1.0
            assert float(dq) >= 0.0
    noprobe = make_forward_fn(net, probe_interval=0)
    _, _, _, (dq, agree, probed) = noprobe(bundle, obs, la, hid,
                                           np.int32(0), np.int32(8))
    assert float(probed) == 0.0


def test_probe_masks_padding_rows():
    """The server pads under-filled dispatches to pow2 buckets with
    degenerate zero rows; the probe's agreement/|dQ| must come from the
    first `live` rows only — a tie flip on the fixed pad input must
    neither fire nor dilute quant_divergence."""
    from r2d2_tpu.actor.policy import make_forward_fn
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    bundle = make_inference_bundle(net, net.init(jax.random.PRNGKey(0)), 1)
    obs, la, hid = _inputs(cfg, 8)
    obs[5:] = 0.0          # "padding": rows >= live are degenerate
    la[5:] = -1
    hid[5:] = 0.0
    fwd = make_forward_fn(net, probe_interval=1)
    _, _, _, (dq_live, agree_live, _p) = fwd(bundle, obs, la, hid,
                                             np.int32(0), np.int32(5))
    _, _, _, (dq_all, agree_all, _p2) = fwd(bundle, obs, la, hid,
                                            np.int32(0), np.int32(8))
    # masked stats must equal recomputing over the first 5 rows alone
    obs5, la5, hid5 = obs[:5], la[:5], hid[:5]
    _, _, _, (dq_ref, agree_ref, _p3) = fwd(bundle, obs5, la5, hid5,
                                            np.int32(0), np.int32(5))
    assert abs(float(agree_live) - float(agree_ref)) < 1e-6
    assert abs(float(dq_live) - float(dq_ref)) < 1e-5
    # and live < N genuinely excludes the tail (dq over all rows can
    # only be >= the masked value)
    assert float(dq_all) >= float(dq_live) - 1e-7


# ---------------------------------------------------------------------------
# publish-time bundle through the weight plumbing


def test_bundle_structure_and_stamp():
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))
    bundle = make_inference_bundle(net, params, 7)
    assert is_quant_bundle(bundle) and not is_quant_bundle(params)
    assert int(np.asarray(bundle["stamp"])) == 7
    # f32: the published tree IS the raw params (byte-identical plumbing)
    f32net = small_net(small_cfg())
    assert make_inference_bundle(f32net, params, 7) is params


def test_publish_preparer_identity_at_f32():
    from r2d2_tpu.runtime.weights import make_publish_preparer, wrap_publish
    net = small_net(small_cfg())
    assert make_publish_preparer(net) is None
    sentinel = object()
    assert wrap_publish(sentinel, None, lambda: 0) is sentinel


def test_inproc_store_bundle_round_trip():
    """Thread-mode plumbing: the wrapped publish builds one stamped
    bundle per publication; readers adopt the twin with the matching
    publish count (the staleness-stamp contract)."""
    from r2d2_tpu.runtime.weights import (InProcWeightStore,
                                          make_publish_preparer,
                                          wrap_publish)
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    p0 = net.init(jax.random.PRNGKey(0))
    prep = make_publish_preparer(net)
    store = InProcWeightStore(prep(p0, 1))
    publish = wrap_publish(store.publish, prep, lambda: store.publish_count)
    got = store.poll("r")
    assert int(np.asarray(got["stamp"])) == 1 == store.reader_version("r")
    p1 = net.init(jax.random.PRNGKey(1))
    publish(p1)
    got = store.poll("r")
    assert int(np.asarray(got["stamp"])) == 2 == store.reader_version("r")
    # the adopted twin IS the publish-time quantization of p1
    ref = jax.device_get(make_inference_bundle(net, p1, 2))
    np.testing.assert_array_equal(
        np.asarray(got["quant"]["params"]["head"]["adv_out"]["kernel"]["q"]),
        np.asarray(ref["quant"]["params"]["head"]["adv_out"]["kernel"]["q"]))


def test_store_current_fresh_after_reader_consumed():
    """The respawn contract: a dead actor's slot has already consumed
    the store version (poll -> None), so a respawned thread policy is
    constructed from store.current(), which must hand back the LIVE
    published tree and mark the version adopted (the staleness stamp
    matches the tree the policy actually holds)."""
    from r2d2_tpu.runtime.weights import (InProcWeightStore,
                                          make_publish_preparer,
                                          wrap_publish)
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    prep = make_publish_preparer(net)
    store = InProcWeightStore(prep(net.init(jax.random.PRNGKey(0)), 1))
    publish = wrap_publish(store.publish, prep,
                           lambda: store.publish_count)
    publish(net.init(jax.random.PRNGKey(1)))       # publication 2
    assert store.poll(3) is not None               # reader 3 adopts v2
    assert store.poll(3) is None                   # the respawn's view
    cur = store.current(reader_id=3)
    assert int(np.asarray(cur["stamp"])) == 2      # live tree, not init
    assert store.reader_version(3) == store.publish_count


def test_shm_publisher_bundle_round_trip():
    """Process-mode plumbing: the int8 twin survives the shm segment's
    f32 payload EXACTLY (int8 values are small integers, lossless in
    f32), scales and stamps included."""
    from r2d2_tpu.runtime.weights import (WeightPublisher, WeightSubscriber,
                                          make_publish_preparer,
                                          wrap_publish)
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    p0 = net.init(jax.random.PRNGKey(0))
    prep = make_publish_preparer(net)
    pub = WeightPublisher(prep(p0, 1))
    try:
        template = jax.device_get(prep(net.init(jax.random.PRNGKey(9)), 0))
        sub = WeightSubscriber(pub.name, template)
        publish = wrap_publish(pub.publish, prep,
                               lambda: pub.publish_count)
        p1 = net.init(jax.random.PRNGKey(1))
        publish(p1)
        got = sub.poll()
        assert got is not None
        assert int(np.asarray(got["stamp"])) == 2 == sub.publish_count
        # reference through the SAME jitted preparer publish used (the
        # eager twin differs by ~1 ulp in the scale division)
        ref = jax.device_get(prep(p1, 2))
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(ref)):
            assert np.asarray(a).dtype == np.asarray(b).dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        sub.close()
    finally:
        pub.close()


# ---------------------------------------------------------------------------
# policies / server / anakin switch together


def test_actor_policy_int8_probes_and_stamps():
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.telemetry import QuantStats
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))
    stats = QuantStats("int8", probe_interval=2)
    pol = ActorPolicy(net, params, epsilon=0.0, seed=0,
                      quant_stats=stats, quant_probe_interval=2)
    rng = np.random.default_rng(0)
    pol.observe_reset(rng.integers(0, 255, (24, 24), np.uint8))
    for _ in range(6):
        a, q, _ = pol.act()
        pol.observe(rng.integers(0, 255, (24, 24), np.uint8), a)
    block = stats.interval_block()
    assert block["dtype"] == "int8"
    assert block["probes"] == 3            # ticks 0, 2, 4
    assert block["agree_frac"] is not None
    # update with a published bundle records the twin's stamp
    pol.update_params(jax.device_get(make_inference_bundle(net, params, 5)))
    assert stats.interval_block()["publish_stamp"] == 5


def test_actor_policy_int8_tracks_f32_actions():
    """A greedy int8 policy and its f32 twin, stepped through the same
    observation stream, pick the same actions nearly always (the
    fixture-net agreement line, end to end through the policy state)."""
    from r2d2_tpu.actor.policy import ActorPolicy
    cfg32 = small_cfg()
    cfg8 = small_cfg(**{"network.inference_dtype": "int8"})
    params = small_net(cfg32).init(jax.random.PRNGKey(1))
    p32 = ActorPolicy(small_net(cfg32), params, epsilon=0.0, seed=0)
    p8 = ActorPolicy(small_net(cfg8), params, epsilon=0.0, seed=0)
    rng = np.random.default_rng(0)
    obs0 = rng.integers(0, 255, (24, 24), np.uint8)
    p32.observe_reset(obs0)
    p8.observe_reset(obs0)
    match = 0
    for _ in range(40):
        a32, _, _ = p32.act()
        a8, _, _ = p8.act()
        match += int(a32 == a8)
        nxt = rng.integers(0, 255, (24, 24), np.uint8)
        # drive BOTH with the f32 stream so state stays comparable
        p32.observe(nxt, a32)
        p8.observe(nxt, a32)
    assert match >= 39, match


def test_batched_policy_int8_runs():
    from r2d2_tpu.actor.policy import BatchedActorPolicy
    from r2d2_tpu.telemetry import QuantStats
    cfg = small_cfg(**{"network.inference_dtype": "int8"})
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))
    stats = QuantStats("int8", probe_interval=1)
    pol = BatchedActorPolicy(net, params, [0.1, 0.2], seeds=[0, 1],
                             quant_stats=stats, quant_probe_interval=1)
    rng = np.random.default_rng(0)
    for lane in range(2):
        pol.observe_reset_lane(lane, rng.integers(0, 255, (24, 24),
                                                  np.uint8))
    actions, q, hidden = pol.act()
    assert actions.shape == (2,) and q.shape == (2, 6)
    assert hidden.dtype == np.float32
    block = stats.interval_block()
    assert block["probes"] == 1 and block["lanes_probed"] == 2


def test_server_int8_matches_local_quant_policy():
    """Served int8 inference is the SAME program local int8 policies
    run: at ε=0 and equal state the served action/Q stream is
    bit-identical to the local quant policy's."""
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.serve import InprocEndpoint, PolicyServer, RemotePolicy
    from r2d2_tpu.telemetry import QuantStats
    cfg = small_cfg(**{"network.inference_dtype": "int8",
                       "serve.max_batch": 2, "serve.deadline_ms": 1.0,
                       "telemetry.quant_probe_interval": 1})
    net = small_net(cfg)
    params = net.init(jax.random.PRNGKey(0))
    stats = QuantStats("int8", 1)
    ep = InprocEndpoint()
    srv = PolicyServer(cfg, net, params, endpoint=ep,
                       quant_stats=stats).start()
    try:
        local = ActorPolicy(net, params, epsilon=0.0, seed=0)
        remote = RemotePolicy(ep.connect(), net.action_dim, 0.0, seed=0,
                              client_id=0)
        rng = np.random.default_rng(0)
        obs0 = rng.integers(0, 255, (24, 24), np.uint8)
        local.observe_reset(obs0)
        remote.observe_reset(obs0)
        for _ in range(8):
            al, ql, _ = local.act()
            ar, qr, _ = remote.act()
            assert al == ar
            np.testing.assert_array_equal(np.asarray(ql), np.asarray(qr))
            nxt = rng.integers(0, 255, (24, 24), np.uint8)
            local.observe(nxt, al)
            remote.observe(nxt, al)
        # the server's dispatch loop fed the shared QuantStats
        assert stats.interval_block()["probes"] >= 1
    finally:
        srv.stop()


def test_anakin_core_quant_probe_and_blocks():
    """The acting scan switches to the quantized forward with the knob
    and its per-segment probe lands in the stats dict; at f32 the stats
    carry no quant keys (the program is the pre-PR14 one)."""
    from r2d2_tpu.actor.anakin import init_act_carry, make_anakin_act
    from r2d2_tpu.envs.factory import create_jax_env
    from r2d2_tpu.replay.structs import ReplaySpec
    base = {"env.frame_height": 12, "env.frame_width": 12,
            "network.hidden_dim": 16, "network.cnn_out_dim": 32,
            "network.conv_layers": ((8, 4, 2),),
            "replay.block_length": 20, "env.episode_len": 40,
            "actor.on_device": True, "actor.anakin_lanes": 3}
    cfg8 = small_cfg(**dict(base, **{"network.inference_dtype": "int8"}))
    env = create_jax_env(cfg8.env)
    net = small_net(cfg8)
    spec = ReplaySpec.from_config(cfg8)
    act = make_anakin_act(env, net, spec, num_lanes=3,
                          epsilons=[0.1, 0.2, 0.3], gamma=0.99,
                          priority=1.0, near_greedy_eps=0.5)
    params = net.init(jax.random.PRNGKey(0))
    bundle = make_inference_bundle(net, params, 1)
    carry = init_act_carry(env, spec, 3, jax.random.PRNGKey(1))
    carry, blocks, stats = act(bundle, carry, np.int32(1))
    assert "quant_dq" in stats and "quant_agree" in stats
    assert 0.0 <= float(stats["quant_agree"]) <= 1.0
    assert float(stats["quant_dq"]) >= 0.0
    assert np.isfinite(np.asarray(blocks.reward)).all()
    assert np.asarray(blocks.obs_row).shape[0] == 3

    # the probe honors its kill switch: off, the f32 twin never enters
    # the quantized program's stats (telemetry.quant_probe_interval = 0)
    act_np = make_anakin_act(env, net, spec, num_lanes=3,
                             epsilons=[0.1, 0.2, 0.3], gamma=0.99,
                             priority=1.0, near_greedy_eps=0.5,
                             quant_probe=False)
    carry_np = init_act_carry(env, spec, 3, jax.random.PRNGKey(1))
    _, _, stats_np = act_np(bundle, carry_np, np.int32(1))
    assert "quant_dq" not in stats_np

    cfg32 = small_cfg(**base)
    env32 = create_jax_env(cfg32.env)
    net32 = small_net(cfg32)
    act32 = make_anakin_act(env32, net32, spec, num_lanes=3,
                            epsilons=[0.1, 0.2, 0.3], gamma=0.99,
                            priority=1.0, near_greedy_eps=0.5)
    carry32 = init_act_carry(env32, spec, 3, jax.random.PRNGKey(1))
    _, _, stats32 = act32(params, carry32, np.int32(1))
    assert "quant_dq" not in stats32


# ---------------------------------------------------------------------------
# record block, alert rule, schema stability, config


def test_quant_stats_interval_semantics():
    from r2d2_tpu.telemetry import QuantStats
    s = QuantStats("bf16", 64)
    empty = s.interval_block()
    assert empty["dtype"] == "bf16" and empty["probes"] == 0
    assert empty["agree_frac"] is None and empty["dq_max"] is None
    s.on_probe(0.02, 1.0, lanes=3)
    s.on_probe(0.5, 0.5, lanes=1)
    b = s.interval_block()
    assert b["probes"] == 2 and b["lanes_probed"] == 4
    assert abs(b["agree_frac"] - 3.5 / 4) < 1e-6
    assert b["agree_min"] == 0.5 and b["dq_max"] == 0.5
    # consumed: the next interval starts clean
    assert s.interval_block()["probes"] == 0


def test_quant_divergence_rule():
    from r2d2_tpu.telemetry import AlertEngine, default_rules
    cfg = small_cfg()
    engine = AlertEngine(default_rules(cfg.telemetry))
    assert any(r.name == "quant_divergence" for r in engine.rules)

    def rec(agree):
        return {"quant": {"dtype": "int8", "agree_frac": agree}}

    assert engine.evaluate(rec(0.999))["fired"] == []
    out = engine.evaluate(rec(0.5))
    assert [a["rule"] for a in out["fired"]] == ["quant_divergence"]
    # a probe-free interval (None) HOLDS the breach — no refire either
    held = engine.evaluate(rec(None))
    assert held["fired"] == [] and "quant_divergence" in held["active"]
    # recovery re-arms, next breach fires again
    assert engine.evaluate(rec(0.99))["fired"] == []
    assert len(engine.evaluate(rec(0.1))["fired"]) == 1


def test_record_schema_stable_without_quant(tmp_path):
    """No provider attached (every f32 run): the record carries no
    'quant' key — byte-identical to the PR13 schema."""
    from r2d2_tpu.runtime.metrics import TrainMetrics
    from r2d2_tpu.telemetry import QuantStats
    m = TrainMetrics(0, str(tmp_path))
    record = m.log(1.0)
    assert "quant" not in record
    m2 = TrainMetrics(1, str(tmp_path))
    m2.set_quant(QuantStats("int8", 8).interval_block)
    record2 = m2.log(1.0)
    assert record2["quant"]["dtype"] == "int8"


def test_config_round_trip_and_validation():
    # pre-PR14 dicts (no inference_dtype / quant knobs) load with defaults
    d = Config().to_dict()
    for key in ("inference_dtype",):
        d["network"].pop(key)
    d["telemetry"].pop("quant_probe_interval")
    d["telemetry"].pop("alerts_quant_agreement")
    cfg = Config.from_dict(d)
    assert cfg.network.inference_dtype == "f32"
    assert cfg.telemetry.quant_probe_interval == 256
    # full round-trip with the knob on
    cfg8 = small_cfg(**{"network.inference_dtype": "int8"})
    assert Config.from_json(cfg8.to_json()).network.inference_dtype == "int8"
    with pytest.raises(ValueError, match="inference_dtype"):
        small_cfg(**{"network.inference_dtype": "fp8"})
    with pytest.raises(ValueError, match="quant_probe_interval"):
        small_cfg(**{"telemetry.quant_probe_interval": -1})
    with pytest.raises(ValueError, match="alerts_quant_agreement"):
        small_cfg(**{"telemetry.alerts_quant_agreement": 0.0})


def test_costmodel_quant_and_serve_rows():
    """The costmodel satellite: the serve micro-batched forward's pow2
    buckets are tabled, and the acting-forward weight-bytes rows show
    the >= 3x int8 cut the acceptance names."""
    from r2d2_tpu.serve.server import serve_buckets
    from r2d2_tpu.telemetry.costmodel import collect_cost_table, gate_config
    cfg = gate_config()
    table = collect_cost_table(cfg, variants=("serve_forward",
                                              "quant_forward"))
    progs = table["programs"]
    for b in serve_buckets(cfg.serve.max_batch):
        row = progs[f"serve_forward_b{b}"]
        assert row["batch"] == b and row.get("flops", 0) > 0
    wb = {m: progs[f"acting_forward_{m}"]["weight_bytes"]
          for m in ("f32", "bf16", "int8")}
    assert wb["f32"] / wb["int8"] >= 3.0
    assert wb["f32"] / wb["bf16"] >= 1.9
    for m in ("f32", "bf16", "int8"):
        assert progs[f"acting_forward_{m}"].get("flops", 0) > 0


# ---------------------------------------------------------------------------
# learnability (slow): int8 acting still trains


GRID_TRAIN_STEPS = 2000


def _grid_cfg(save_dir: str) -> Config:
    return Config().replace(**{
        "env.game_name": "Grid", "env.grid_size": 5,
        "env.frame_height": 20, "env.frame_width": 20,
        "env.frame_stack": 2, "env.episode_len": 40,
        "network.hidden_dim": 32, "network.cnn_out_dim": 64,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "network.inference_dtype": "int8",
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 32_000, "replay.block_length": 40,
        "replay.batch_size": 16, "replay.learning_starts": 2_000,
        "replay.max_env_steps_per_train_step": 16.0,
        "actor.on_device": True, "actor.anakin_lanes": 32,
        "optim.lr": 1e-3, "optim.gamma": 0.99,
        "runtime.save_interval": 0, "runtime.log_interval": 8.0,
        "runtime.save_dir": save_dir,
    })


def _grid_train(save_dir: str) -> dict:
    from r2d2_tpu.runtime.anakin_loop import run_anakin_train
    records = []
    stacks = run_anakin_train(_grid_cfg(save_dir),
                              max_training_steps=GRID_TRAIN_STEPS,
                              max_seconds=600, log_fn=records.append)
    returns = [r["avg_episode_return"] for r in records
               if r.get("avg_episode_return") is not None]
    quant = [r["quant"] for r in records if r.get("quant")]
    return {"training_steps": int(stacks[0].learner.training_steps),
            "returns": returns,
            "agree": [q.get("agree_frac") for q in quant
                      if q.get("agree_frac") is not None]}


@pytest.mark.slow
def test_grid_learnability_int8_acting(tmp_path):
    """The gridworld still visibly LEARNS when every acting forward is
    int8 (the learner stays f32): multi-fold return growth from the
    first logged interval to the last, with the live agreement gauge
    confirming the quantized policy tracked its f32 twin throughout —
    the acceptance's end-to-end quality line. Runs in a subprocess on a
    plain single-device CPU backend (the anakin learnability recipe)."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-4000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["training_steps"] >= GRID_TRAIN_STEPS
    returns = result["returns"]
    assert len(returns) >= 2, returns
    early, late = returns[0], returns[-1]
    assert late >= max(3.0 * early, early + 0.3), returns
    assert result["agree"], "no quant probes reached the records"
    assert np.mean(result["agree"]) >= 0.9, result["agree"]


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from r2d2_tpu.utils.platform import pin_platform
    pin_platform()
    print(json.dumps(_grid_train(sys.argv[1])))
