"""Tools tests: log parsing, genetic search mechanics (mock fitness), and the
plot CLI on a synthetic reference-format log."""

import json
import os

import numpy as np
import pytest

from r2d2_tpu.config import Config, GENETIC_SEARCH_SPACE
from r2d2_tpu.tools.genetic import (
    genome_to_config, mutate, run_search, sample_genome)
from r2d2_tpu.tools.logparse import parse_log


def _write_reference_style_log(path, n=12):
    """Emit exactly the reference's log line format (ref worker.py:220-234)."""
    with open(path, "w") as f:
        for i in range(n):
            f.write(f"buffer size: {1000 + i * 100}\n")
            f.write(f"buffer update speed: {50.0}/s\n")
            f.write(f"number of environment steps: {i * 1000}\n")
            if i % 2 == 0:
                f.write(f"average episode return: {float(i):.4f}\n")
            f.write(f"number of training steps: {i * 10}\n")
            f.write("training speed: 0.5/s\n")
            if i > 0:
                f.write(f"loss: {1.0 / (i + 1):.4f}\n")


def test_parse_reference_log(tmp_path):
    path = str(tmp_path / "train_player0.log")
    _write_reference_style_log(path)
    log = parse_log(path)
    assert len(log.buffer_sizes) == 12
    assert len(log.returns) == 6 and log.returns[0] == 0.0
    assert len(log.losses) == 11
    assert log.return_counts[1] == 3  # third interval (0-based count after 3 'buffer size' lines)
    assert log.env_steps[-1] == 11000


def test_plot_cli(tmp_path):
    _write_reference_style_log(str(tmp_path / "train_player0.log"))
    _write_reference_style_log(str(tmp_path / "train_player1.log"))
    out = str(tmp_path / "curves.png")
    from r2d2_tpu.cli.plot import main
    main(["--file_path", str(tmp_path), "--show_all", "--loss_interpolation",
          "--out", out])
    assert os.path.getsize(out) > 1000


def test_genome_sampling_always_valid():
    """Every sampled/mutated genome must construct a valid Config (the
    layout-safe space contract)."""
    rng = np.random.default_rng(0)
    base = Config()
    for _ in range(50):
        g = sample_genome(rng)
        g = mutate(rng, g, rate=0.5)
        cfg = genome_to_config(base, g)      # __post_init__ validates
        assert cfg.replay.block_length % cfg.sequence.learning_steps == 0
        assert isinstance(cfg.network.hidden_dim, int)
        assert isinstance(cfg.network.use_dueling, bool)


def test_run_search_improves_mock_fitness():
    """GA must climb a simple deterministic objective (closer lr to 3e-4 and
    bigger hidden_dim is better)."""
    def fitness(cfg: Config) -> float:
        return (-abs(np.log10(cfg.optim.lr) - np.log10(3e-4))
                + cfg.network.hidden_dim / 1024.0)

    history = run_search(fitness, population=8, generations=5, seed=1)
    first_best = history[0].best[1]
    last_best = history[-1].best[1]
    assert last_best >= first_best
    # elitism: best fitness is monotonically non-decreasing
    bests = [h.best[1] for h in history]
    assert all(b2 >= b1 - 1e-9 for b1, b2 in zip(bests, bests[1:]))
