"""Tools tests: log parsing, genetic search mechanics (mock fitness), and the
plot CLI on a synthetic reference-format log."""

import json
import os

import numpy as np
import pytest

from r2d2_tpu.config import Config, GENETIC_SEARCH_SPACE
from r2d2_tpu.tools.genetic import (
    genome_to_config, mutate, run_search, sample_genome)
from r2d2_tpu.tools.logparse import parse_log


def _write_reference_style_log(path, n=12):
    """Emit exactly the reference's log line format (ref worker.py:220-234)."""
    with open(path, "w") as f:
        for i in range(n):
            f.write(f"buffer size: {1000 + i * 100}\n")
            f.write(f"buffer update speed: {50.0}/s\n")
            f.write(f"number of environment steps: {i * 1000}\n")
            if i % 2 == 0:
                f.write(f"average episode return: {float(i):.4f}\n")
            f.write(f"number of training steps: {i * 10}\n")
            f.write("training speed: 0.5/s\n")
            if i > 0:
                f.write(f"loss: {1.0 / (i + 1):.4f}\n")


def test_parse_reference_log(tmp_path):
    path = str(tmp_path / "train_player0.log")
    _write_reference_style_log(path)
    log = parse_log(path)
    assert len(log.buffer_sizes) == 12
    assert len(log.returns) == 6 and log.returns[0] == 0.0
    assert len(log.losses) == 11
    assert log.return_counts[1] == 3  # third interval (0-based count after 3 'buffer size' lines)
    assert log.env_steps[-1] == 11000


def test_plot_cli(tmp_path):
    _write_reference_style_log(str(tmp_path / "train_player0.log"))
    _write_reference_style_log(str(tmp_path / "train_player1.log"))
    out = str(tmp_path / "curves.png")
    from r2d2_tpu.cli.plot import main
    main(["--file_path", str(tmp_path), "--show_all", "--loss_interpolation",
          "--out", out])
    assert os.path.getsize(out) > 1000


def test_genome_sampling_always_valid():
    """Every sampled/mutated genome must construct a valid Config (the
    layout-safe space contract)."""
    rng = np.random.default_rng(0)
    base = Config()
    for _ in range(50):
        g = sample_genome(rng)
        g = mutate(rng, g, rate=0.5)
        cfg = genome_to_config(base, g)      # __post_init__ validates
        assert cfg.replay.block_length % cfg.sequence.learning_steps == 0
        assert isinstance(cfg.network.hidden_dim, int)
        assert isinstance(cfg.network.use_dueling, bool)


def test_slice_eval_pins_rate_limiter(monkeypatch):
    """Round-3 review: genetic fitness slices with the rate limiter off
    score scheduler noise (PERF.md measured 25-86 return on identical
    invocations). make_slice_eval must pin the collect:learn ratio unless
    the genome/base config already sets one."""
    from types import SimpleNamespace

    from r2d2_tpu.cli.genetic import make_slice_eval
    from r2d2_tpu.runtime import orchestrator as orch_mod

    captured = []

    def fake_train(cfg, **kwargs):
        captured.append(cfg)
        return [SimpleNamespace(
            metrics=SimpleNamespace(num_episodes=0, episode_reward=0.0))]

    monkeypatch.setattr(orch_mod, "train", fake_train)
    ev = make_slice_eval([], slice_steps=10, slice_seconds=10.0,
                         slice_ratio=2.0)
    ev(Config())                                   # default ratio 0 -> pinned
    assert captured[-1].replay.max_env_steps_per_train_step == 2.0
    explicit = Config().replace(
        **{"replay.max_env_steps_per_train_step": 1.5})
    ev(explicit)                                   # explicit value preserved
    assert captured[-1].replay.max_env_steps_per_train_step == 1.5
    ev0 = make_slice_eval([], 10, 10.0, slice_ratio=0.0)
    ev0(Config())                                  # 0 disables the pin
    assert captured[-1].replay.max_env_steps_per_train_step == 0.0
    # an EXPLICIT user 0 (free-run request) wins over the pin even though
    # it equals the dataclass default
    ev_user = make_slice_eval(
        ["--replay.max_env_steps_per_train_step=0"], 10, 10.0,
        slice_ratio=2.0)
    ev_user(Config())
    assert captured[-1].replay.max_env_steps_per_train_step == 0.0


def test_sync_eval_rejects_sub_one_ratio_and_bounds_wall_clock(tmp_path):
    """Round-4 review: sync collection IS the ratio schedule, so a <1
    effective ratio must be rejected up front (not silently score every
    genome -inf); and --slice-seconds must bound each sync genome (a
    timed-out genome scores -inf instead of stalling the generation)."""
    from r2d2_tpu.cli.genetic import make_sync_eval

    from tests.test_runtime import tiny_config

    with pytest.raises(ValueError, match="ratio >= 1"):
        make_sync_eval([], slice_steps=10, slice_ratio=0.0)

    # host placement breaks the bit-reproducibility contract: rejected
    from r2d2_tpu.tools.sync_train import sync_train
    host_cfg = tiny_config(tmp_path).replace(
        **{"replay.placement": "host",
           "replay.max_env_steps_per_train_step": 2.0})
    with pytest.raises(ValueError, match="placement='device'"):
        sync_train(host_cfg, 5, 0.4)

    ev = make_sync_eval([], slice_steps=10_000, slice_ratio=2.0,
                        max_seconds=0.5)
    assert np.isneginf(ev(tiny_config(tmp_path)))   # timed out -> -inf


@pytest.mark.slow
def test_identical_genome_scores_identically_in_sync_mode(tmp_path):
    """VERDICT r3 #6 'done' criterion (strengthened): two evaluations of
    the identical genome don't just land within tolerance — the default
    sync fitness mode is bit-reproducible, so they are EQUAL."""
    from r2d2_tpu.cli.genetic import make_sync_eval

    from tests.test_runtime import tiny_config

    cfg = tiny_config(tmp_path)
    ev = make_sync_eval([], slice_steps=30, slice_ratio=2.0)
    a, b = ev(cfg), ev(cfg)
    assert np.isfinite(a) and np.isfinite(b)
    assert a == b


def test_invalid_genome_scores_neg_inf_instead_of_crashing():
    """A user-overridden base can make sampled genomes invalid (e.g.
    block_length=20 vs the space's learning_steps=16): the search must
    score them -inf, not die at Config construction."""
    base = Config().replace(**{"replay.block_length": 20,
                               "replay.capacity": 800,
                               "sequence.learning_steps": 5,
                               "sequence.burn_in_steps": 4})
    seen = []

    def fitness(cfg: Config) -> float:
        seen.append(cfg)
        return float(cfg.optim.lr)

    history = run_search(fitness, base=base, population=8, generations=2,
                         seed=3)
    flat = [f for h in history for f in h.fitnesses]
    assert any(np.isneginf(f) for f in flat)       # invalid genomes scored
    assert any(np.isfinite(f) for f in flat)       # valid ones still ran
    assert seen                                    # eval_fn saw valid configs

    # an ALL-invalid generation (base conflicts with the whole space) must
    # fail loudly, not return a never-evaluated 'best' genome
    space = {"sequence.learning_steps": {"choices": (16,)}}   # 20 % 16 != 0
    with pytest.raises(ValueError, match="every genome"):
        run_search(fitness, base=base, population=4, generations=1,
                   seed=0, space=space)


def test_summarize_trace_aggregates_chrome_events(tmp_path):
    """summarize_trace: per-plane totals/counts from a Chrome trace, sorted
    by total span; device_plane picks the accelerator pid."""
    import gzip

    from r2d2_tpu.tools.profile_step import (
        device_plane, format_summary, summarize_trace)

    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 2, "name": "fusion.1", "dur": 100.0, "ts": 0},
        {"ph": "X", "pid": 2, "name": "fusion.1", "dur": 50.0, "ts": 1},
        {"ph": "X", "pid": 2, "name": "copy.2", "dur": 30.0, "ts": 2},
        {"ph": "X", "pid": 1, "name": "PjitFunction(step)", "dur": 10.0,
         "ts": 0},
    ]
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    summary = summarize_trace(str(tmp_path))
    assert summary["/device:TPU:0"][0] == ("fusion.1", 150.0, 2)
    assert summary["/device:TPU:0"][1] == ("copy.2", 30.0, 1)
    assert summary["/host:CPU"] == [("PjitFunction(step)", 10.0, 1)]
    plane, rows = device_plane(summary)
    assert plane == "/device:TPU:0" and rows[0][0] == "fusion.1"
    text = format_summary(summary, steps=2)
    assert "fusion.1" in text and "/device:TPU:0" in text

    with pytest.raises(FileNotFoundError):
        summarize_trace(str(tmp_path / "absent"))


@pytest.mark.slow
def test_profile_capture_end_to_end(tmp_path):
    """capture_step_trace profiles real fused steps at a tiny config and
    the summary contains the jitted step dispatch."""
    from r2d2_tpu.tools.profile_step import (
        capture_step_trace, summarize_trace, traced_step_count)

    from tests.test_runtime import tiny_config

    cfg = tiny_config(tmp_path)
    out = capture_step_trace(cfg, steps=3, out_dir=str(tmp_path / "trace"))
    # steps rounds UP to whole dispatches and is recorded alongside the
    # trace so re-analysis divides by what actually ran
    assert traced_step_count(out) == 3   # k=1 in tiny_config
    summary = summarize_trace(out)
    all_names = [n for rows in summary.values() for n, _, _ in rows]
    assert any("step" in n for n in all_names), all_names


def test_run_search_improves_mock_fitness():
    """GA must climb a simple deterministic objective (closer lr to 3e-4 and
    bigger hidden_dim is better)."""
    def fitness(cfg: Config) -> float:
        return (-abs(np.log10(cfg.optim.lr) - np.log10(3e-4))
                + cfg.network.hidden_dim / 1024.0)

    history = run_search(fitness, population=8, generations=5, seed=1)
    first_best = history[0].best[1]
    last_best = history[-1].best[1]
    assert last_best >= first_best
    # elitism: best fitness is monotonically non-decreasing
    bests = [h.best[1] for h in history]
    assert all(b2 >= b1 - 1e-9 for b1, b2 in zip(bests, bests[1:]))


def test_chip_checks_refuses_cpu_backend():
    """The on-chip pallas gate must refuse loudly on CPU (kernels do not
    lower there) instead of failing kernel-by-kernel."""
    from r2d2_tpu.tools.chip_checks import run_chip_checks
    assert run_chip_checks() == 2


@pytest.mark.slow
def test_soak_smoke_contract(tmp_path):
    """The production-soak CLI (VERDICT r4 #3) at toy scale: fill+wrap the
    ring, train with interleaved ingestion, checkpoint on cadence, emit
    the one-line JSON contract."""
    import json
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "r2d2_tpu.cli.soak", "--seconds=6",
         "--capacity=200", "--checkpoint-interval=3",
         f"--save-dir={tmp_path}",
         "--override", "env.frame_height=24",
         "--override", "env.frame_width=24",
         "--override", "env.frame_stack=2",
         "--override", "network.hidden_dim=32",
         "--override", "network.cnn_out_dim=32",
         "--override", "network.conv_layers=[[8,4,2],[16,3,1]]",
         "--override", "replay.block_length=20",
         "--override", "sequence.burn_in_steps=4",
         "--override", "sequence.learning_steps=5",
         "--override", "sequence.forward_steps=3",
         "--override", "replay.batch_size=8"],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "soak"
    # OBSERVED wrap evidence from the replay state itself: the buffer is
    # full (capacity learning-steps) and the write pointer came back
    # around the ring after num_blocks + wrap_extra adds
    assert out["buffer_steps_after_fill"] == 200    # == capacity
    assert 0 < out["block_ptr_after_fill"] < out["num_blocks"]
    assert out["ring_laps_fill"] > 1.0
    assert out["ring_laps_train"] > 0           # ingestion during training
    assert out["train_steps"] > 0
    assert out["steps_per_sec_mean"] > 0
    assert len(out["checkpoint_save_s"]) >= 1   # cadence fired
    assert all(np.isfinite(x) for x in out["losses_sampled"])
