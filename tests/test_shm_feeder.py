"""Native shared-memory experience transport (runtime/shm_feeder.py +
native/shm_ring.cc): serialization round-trip, FIFO/capacity semantics,
multi-producer correctness, and pickled cross-handle attach.
"""

import queue as queue_mod
import threading

import numpy as np
import pytest

from tests.test_replay import _fill_blocks, make_spec

pytest.importorskip("r2d2_tpu.native")  # C++ toolchain required

from r2d2_tpu.runtime.shm_feeder import ShmBlockRing


@pytest.fixture
def spec():
    return make_spec()


def blocks_equal(a, b):
    for name in a.__dataclass_fields__:
        va, vb = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        np.testing.assert_array_equal(va, vb, err_msg=name)


def test_roundtrip_preserves_every_field(spec):
    rng = np.random.default_rng(0)
    ring = ShmBlockRing(spec, maxsize=4)
    try:
        for blk in _fill_blocks(spec, 3, rng):
            ring.put(blk, timeout=1.0)
            got = ring.get_nowait()
            blocks_equal(blk, got)
    finally:
        ring.close()


def test_fifo_capacity_and_empty(spec):
    rng = np.random.default_rng(1)
    blocks = _fill_blocks(spec, 4, rng)
    ring = ShmBlockRing(spec, maxsize=3)
    try:
        for blk in blocks[:3]:
            ring.put(blk, timeout=1.0)
        with pytest.raises(queue_mod.Full):
            ring.put(blocks[3], timeout=0.05)
        assert ring.qsize() == 3
        # FIFO order out
        for blk in blocks[:3]:
            blocks_equal(blk, ring.get(timeout=1.0))
        with pytest.raises(queue_mod.Empty):
            ring.get_nowait()
    finally:
        ring.close()


def test_multi_producer_all_blocks_arrive(spec):
    """4 producer threads x 8 blocks through a 4-slot ring: every block
    arrives exactly once (MPMC reservation correctness under contention).
    Identified by the reward field's unique first element."""
    rng = np.random.default_rng(2)
    all_blocks = _fill_blocks(spec, 32, rng)
    for i, blk in enumerate(all_blocks):
        blk.reward[0, 0] = float(i)
    ring = ShmBlockRing(spec, maxsize=4)
    try:
        def producer(chunk):
            for blk in chunk:
                ring.put(blk, timeout=30.0)

        threads = [threading.Thread(target=producer,
                                    args=(all_blocks[i * 8:(i + 1) * 8],))
                   for i in range(4)]
        for t in threads:
            t.start()
        seen = set()
        for _ in range(32):
            blk = ring.get(timeout=30.0)
            seen.add(int(np.asarray(blk.reward)[0, 0]))
        for t in threads:
            t.join(timeout=5.0)
        assert seen == set(range(32))
    finally:
        ring.close()


def test_recover_stalled_frees_wedged_slot(spec):
    """A producer dying between reserve and commit must not wedge the ring
    forever: recover_stalled (supervisor-invoked after reaping the dead
    process) skips the stale reserved-uncommitted head slot."""
    rng = np.random.default_rng(4)
    blocks = _fill_blocks(spec, 2, rng)
    ring = ShmBlockRing(spec, maxsize=2)
    try:
        lib = ring._ensure()
        # simulate the crash: reserve without commit (slot 0 now wedged)
        assert int(lib.ring_reserve_push(ring._base)) == 0
        ring.put(blocks[0], timeout=1.0)     # slot 1 commits normally
        with pytest.raises(queue_mod.Empty):
            ring.get_nowait()                # head wedged -> nothing pops
        assert ring.recover_stalled(stale_ms=0) == 1
        blocks_equal(blocks[0], ring.get(timeout=1.0))   # flowing again
        ring.put(blocks[1], timeout=1.0)     # the freed slot is reusable
        blocks_equal(blocks[1], ring.get_nowait())
        # live-writer protection: a fresh reservation is NOT reclaimed
        # under a non-zero grace
        assert int(lib.ring_reserve_push(ring._base)) >= 0
        assert ring.recover_stalled(stale_ms=60_000) == 0
    finally:
        ring.close()


def test_pickled_handle_attaches_to_same_ring(spec):
    """The pickled handle (what spawned actors receive) reaches the same
    region: a block put through the copy comes out of the original."""
    import pickle

    rng = np.random.default_rng(3)
    blk = _fill_blocks(spec, 1, rng)[0]
    ring = ShmBlockRing(spec, maxsize=2)
    try:
        handle = pickle.loads(pickle.dumps(ring))
        assert handle.name == ring.name
        handle.put(blk, timeout=1.0)
        blocks_equal(blk, ring.get(timeout=1.0))
        handle.close()   # non-owner: must NOT unlink
        ring.put(blk, timeout=1.0)   # region still alive
        blocks_equal(blk, ring.get_nowait())
    finally:
        ring.close()
