"""Test harness: force an 8-device virtual CPU platform so every sharding/
multi-chip test runs hermetically (no TPU required), per SURVEY.md §4."""

import os

# The shell may pre-set JAX_PLATFORMS to the TPU platform, and a pytest
# plugin imports jax before this conftest runs — so pin the platform through
# jax.config (effective until the first backend initialization) as well as
# the environment, unconditionally.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", (
    "test suite must run on the virtual CPU mesh; a backend was initialized "
    "on another platform before conftest could pin it")
assert len(jax.devices()) >= 8

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
