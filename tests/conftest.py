"""Test harness: force an 8-device virtual CPU platform so every sharding/
multi-chip test runs hermetically (no TPU required), per SURVEY.md §4."""

# The shell may pre-set JAX_PLATFORMS to the TPU platform, and a pytest
# plugin imports jax before this conftest runs — pin_cpu_platform covers
# both routes (env vars + jax.config before first backend init).
from r2d2_tpu.utils.platform import pin_cpu_platform

pin_cpu_platform(8)

import jax

assert jax.devices()[0].platform == "cpu", (
    "test suite must run on the virtual CPU mesh; a backend was initialized "
    "on another platform before conftest could pin it")
assert len(jax.devices()) >= 8

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
