"""Test harness: force an 8-device virtual CPU platform so every sharding/
multi-chip test runs hermetically (no TPU required), per SURVEY.md §4."""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
