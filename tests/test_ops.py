import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.ops import (
    inverse_value_rescale,
    initial_priorities,
    mixed_td_errors_masked,
    mixed_td_errors_ragged,
    n_step_gamma,
    n_step_return,
    tree_init,
    tree_sample,
    tree_update,
    value_rescale,
)
from r2d2_tpu.ops.sum_tree import (
    tree_init_np,
    tree_num_layers,
    tree_sample_np,
    tree_update_np,
)


class TestValueRescale:
    def test_round_trip(self):
        x = jnp.linspace(-50.0, 50.0, 101)
        np.testing.assert_allclose(
            inverse_value_rescale(value_rescale(x)), x, atol=1e-3, rtol=1e-4
        )

    def test_zero_fixed_point(self):
        assert float(value_rescale(jnp.array(0.0))) == 0.0
        assert float(inverse_value_rescale(jnp.array(0.0))) == 0.0

    def test_odd_symmetry(self):
        x = jnp.array([0.5, 3.0, 17.0])
        np.testing.assert_allclose(value_rescale(-x), -value_rescale(x), rtol=1e-6)


class TestNStepReturn:
    def test_vs_brute_force(self, rng):
        rewards = rng.normal(size=37).astype(np.float32)
        gamma, n = 0.997, 5
        got = n_step_return(rewards, gamma, n)
        padded = np.concatenate([rewards, np.zeros(n - 1)])
        want = np.array(
            [sum(gamma**i * padded[t + i] for i in range(n)) for t in range(37)]
        )
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_short_block(self):
        # block shorter than the horizon
        got = n_step_return(np.array([1.0, 2.0]), 0.5, 5)
        np.testing.assert_allclose(got, [1.0 + 0.5 * 2.0, 2.0], rtol=1e-6)

    def test_gamma_terminal_zeros_tail(self):
        g = n_step_gamma(size=12, gamma=0.9, n=5, bootstrap=False)
        np.testing.assert_allclose(g[:7], 0.9**5, rtol=1e-6)
        np.testing.assert_allclose(g[7:], 0.0)

    def test_gamma_bootstrap_shortens_tail(self):
        g = n_step_gamma(size=12, gamma=0.9, n=5, bootstrap=True)
        np.testing.assert_allclose(g[:7], 0.9**5, rtol=1e-6)
        np.testing.assert_allclose(g[7:], [0.9**k for k in range(5, 0, -1)], rtol=1e-6)

    def test_gamma_tiny_block(self):
        g = n_step_gamma(size=3, gamma=0.9, n=5, bootstrap=True)
        np.testing.assert_allclose(g, [0.9**3, 0.9**2, 0.9**1], rtol=1e-6)


class TestInitialPriorities:
    def test_vs_brute_force(self, rng):
        size, n, actions_dim = 23, 5, 6
        q = rng.normal(size=(size + 1, actions_dim)).astype(np.float32)
        actions = rng.integers(0, actions_dim, size)
        rewards = rng.normal(size=size).astype(np.float32)
        gammas = n_step_gamma(size, 0.99, n, bootstrap=True)
        got = initial_priorities(q, actions, rewards, gammas, n)
        for t in range(size):
            boot_row = min(t + n, size)
            want = abs(rewards[t] + gammas[t] * q[boot_row].max() - q[t, actions[t]])
            assert got[t] == pytest.approx(want, rel=1e-5)


class TestMixedTD:
    def test_masked_matches_ragged(self, rng):
        B, L = 16, 10
        steps = rng.integers(1, L + 1, size=B)
        dense = rng.uniform(0.01, 2.0, size=(B, L)).astype(np.float32)
        mask = (np.arange(L)[None, :] < steps[:, None]).astype(np.float32)
        flat = np.concatenate([dense[i, : steps[i]] for i in range(B)])
        want = mixed_td_errors_ragged(flat, steps)
        got = np.asarray(mixed_td_errors_masked(jnp.asarray(dense), jnp.asarray(mask)))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_eta_mix(self):
        td = jnp.array([[1.0, 3.0]])
        mask = jnp.ones((1, 2))
        got = float(mixed_td_errors_masked(td, mask, eta=0.9)[0])
        assert got == pytest.approx(0.9 * 3.0 + 0.1 * 2.0)


class TestSumTree:
    def test_num_layers(self):
        assert tree_num_layers(1) == 1
        assert tree_num_layers(2) == 2
        assert tree_num_layers(3) == 3
        assert tree_num_layers(4) == 3
        assert tree_num_layers(50_000) == 17

    def test_update_total_and_leaves(self, rng):
        capacity = 64
        L, tree = tree_init(capacity)
        td = rng.uniform(0.1, 2.0, size=capacity).astype(np.float32)
        tree = tree_update(L, tree, 0.9, jnp.asarray(td), jnp.arange(capacity))
        leaves = np.asarray(tree[2 ** (L - 1) - 1 :])[:capacity]
        np.testing.assert_allclose(leaves, td**0.9, rtol=1e-5)
        assert float(tree[0]) == pytest.approx((td**0.9).sum(), rel=1e-4)

    def test_alpha_zero_keeps_zero_priority(self):
        L, tree = tree_init(8)
        tree = tree_update(
            L, tree, 0.0, jnp.array([0.0, 1.0, 2.0]), jnp.array([0, 1, 2])
        )
        leaves = np.asarray(tree[2 ** (L - 1) - 1 :])
        np.testing.assert_allclose(leaves[:3], [0.0, 1.0, 1.0])

    def test_partial_update_preserves_rest(self, rng):
        L, tree = tree_init(32)
        tree = tree_update(L, tree, 1.0, jnp.ones(32), jnp.arange(32))
        tree = tree_update(L, tree, 1.0, jnp.array([5.0]), jnp.array([7]))
        assert float(tree[0]) == pytest.approx(31 + 5.0, rel=1e-5)

    def test_sample_matches_numpy_semantics(self, rng):
        capacity = 128
        td = rng.uniform(0.1, 3.0, size=capacity)
        L, jtree = tree_init(capacity)
        jtree = tree_update(L, jtree, 0.9, jnp.asarray(td), jnp.arange(capacity))
        Ln, ntree = tree_init_np(capacity)
        tree_update_np(Ln, ntree, 0.9, td, np.arange(capacity))
        assert L == Ln
        np.testing.assert_allclose(np.asarray(jtree), ntree, rtol=1e-4)

        idx, w = tree_sample(L, jtree, 0.6, 64, jax.random.PRNGKey(0))
        idx = np.asarray(idx)
        assert idx.min() >= 0 and idx.max() < capacity
        w = np.asarray(w)
        # (p/min_p)^-beta: highest weight 1.0 at the sampled min-priority leaf
        assert np.all(w <= 1.0 + 1e-6) and w.max() == pytest.approx(1.0)

    def test_sampling_is_proportional(self, rng):
        capacity = 16
        prio = np.zeros(capacity)
        prio[3] = 1.0
        prio[10] = 3.0
        L, tree = tree_init(capacity)
        tree = tree_update(L, tree, 1.0, jnp.asarray(prio), jnp.arange(capacity))
        counts = np.zeros(capacity)
        for s in range(20):
            idx, _ = tree_sample(L, tree, 0.6, 64, jax.random.PRNGKey(s))
            np.add.at(counts, np.asarray(idx), 1)
        assert counts[3] + counts[10] == counts.sum()
        assert counts[10] / counts[3] == pytest.approx(3.0, rel=0.15)

    def test_partially_filled_tree_never_samples_padding(self):
        # Regression: with f32 prefix sums, the top stratum could round up to
        # exactly p_sum and descend into a zero-priority padding leaf (NaN
        # weights, out-of-range index). 50k leaves, only 20k filled.
        capacity, filled = 50_000, 20_000
        L, tree = tree_init(capacity)
        tree = tree_update(L, tree, 0.9, jnp.ones(filled), jnp.arange(filled))
        for s in range(5):
            idx, w = tree_sample(L, tree, 0.6, 128, jax.random.PRNGKey(s))
            assert int(jnp.max(idx)) < filled
            assert bool(jnp.all(jnp.isfinite(w)))

    def test_stratified_covers_strata(self):
        capacity = 64
        L, tree = tree_init(capacity)
        tree = tree_update(L, tree, 1.0, jnp.ones(capacity), jnp.arange(capacity))
        idx, w = tree_sample(L, tree, 0.6, capacity, jax.random.PRNGKey(1))
        # uniform priorities + stratification => every leaf sampled exactly once
        assert sorted(np.asarray(idx).tolist()) == list(range(capacity))
        np.testing.assert_allclose(np.asarray(w), 1.0, rtol=1e-5)
