"""Sharded-anakin composition tests (ISSUE 8): the fused act+train loop
across a dp-wide (emulated) mesh — replay-state identity against the
per-shard sequential reference, per-shard RNG independence, the global
ε-ladder layout, the relaxed mesh validation + config round-trip, the
composed loop end to end with the per-shard telemetry block, the
shard_imbalance alert rule, and (slow) the gridworld learnability slice
under dp=2.
"""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from r2d2_tpu.config import Config, apex_epsilon
from r2d2_tpu.envs.factory import create_jax_env
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.structs import ReplaySpec


def sharded_cfg(**overrides) -> Config:
    cfg = Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 12, "env.frame_width": 12, "env.frame_stack": 2,
        "env.episode_len": 40,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2),),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.on_device": True, "actor.anakin_lanes": 4,
        "mesh.dp": 2,
        "runtime.save_interval": 0,
    })
    return cfg.replace(**overrides) if overrides else cfg


def _build_sharded(cfg: Config):
    from r2d2_tpu.parallel import (init_sharded_act_carry, make_mesh,
                                   make_sharded_anakin_act,
                                   sharded_replay_init)
    spec = ReplaySpec.from_config(cfg)
    env = create_jax_env(cfg.env)
    net = NetworkApply(env.action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    params = net.init(jax.random.PRNGKey(0))
    mesh = make_mesh(cfg.mesh)
    n = cfg.actor.anakin_lanes
    eps = [apex_epsilon(i, n, cfg.actor.base_eps, cfg.actor.eps_alpha)
           for i in range(n)]
    act = make_sharded_anakin_act(
        env, net, spec, mesh=mesh, num_lanes=n, epsilons=eps,
        gamma=cfg.optim.gamma, priority=cfg.actor.anakin_priority,
        near_greedy_eps=cfg.actor.near_greedy_eps)
    key = jax.random.PRNGKey(1)
    carry = init_sharded_act_carry(env, spec, n, mesh, key)
    replay = sharded_replay_init(spec, mesh)
    return env, spec, net, params, mesh, eps, act, carry, replay, key


# ---- config: the relaxed mesh check --------------------------------------


def test_config_accepts_dp_mesh_and_roundtrips():
    """on_device + mesh.dp>1 is now a VALID pairing (the PR6 loop
    rejected any non-1x1 mesh); the knobs round-trip through JSON."""
    cfg = sharded_cfg()
    assert cfg.actor.on_device and cfg.mesh.dp == 2
    again = Config.from_dict(json.loads(cfg.to_json()))
    assert again.actor.on_device and again.mesh.dp == 2
    assert again.actor.anakin_lanes == 4


def test_config_validates_lane_shard_contracts():
    # divisibility at CONFIG time, not trace time
    with pytest.raises(ValueError, match="divisible by mesh.dp"):
        sharded_cfg(**{"actor.anakin_lanes": 5})
    # the scatter-alias bound is per SHARD under a dp mesh: 80 lanes /
    # dp=2 = 40 per shard == num_blocks passes, 41 per shard fails
    ok = sharded_cfg(**{"actor.anakin_lanes": 80})
    assert ok.actor.anakin_lanes // ok.mesh.dp == ok.num_blocks
    with pytest.raises(ValueError, match="num_blocks"):
        sharded_cfg(**{"actor.anakin_lanes": 82})
    # model parallelism stays rejected, naming the knob to flip
    with pytest.raises(ValueError, match="data-parallel"):
        sharded_cfg(**{"mesh.mp": 2, "mesh.dp": 1})


def test_loop_validates_resolved_dp_contracts():
    """mesh.dp=-1 resolves at runtime — the loop re-checks divisibility
    against the resolved width with the knob named in the error."""
    from r2d2_tpu.runtime.anakin_loop import run_anakin_train
    cfg = sharded_cfg(**{"mesh.dp": -1, "actor.anakin_lanes": 9})
    if 9 % len(jax.devices()) == 0:   # pragma: no cover - 8-device suite
        pytest.skip("9 lanes divide evenly across this device count")
    with pytest.raises(ValueError, match="resolved mesh.dp"):
        run_anakin_train(cfg, max_training_steps=1, max_seconds=5)


# ---- replay-state identity + RNG independence ----------------------------


def test_sharded_replay_identity_with_per_shard_sequential_adds():
    """The ONE sharded dispatch (act + local ring-write per shard) lands
    bit-identical replay contents to the reference construction: each
    shard's lane group run through the single-mesh act path (same
    fold_in(key, shard) chain, same GLOBAL ε-ladder slice) with its
    blocks added sequentially to a standalone replay state."""
    from r2d2_tpu.actor.anakin import init_act_carry, make_anakin_act
    from r2d2_tpu.replay.device_replay import replay_add_many, replay_init
    cfg = sharded_cfg()
    (env, spec, net, params, mesh, eps, act, carry, replay,
     key) = _build_sharded(cfg)
    dp, n = 2, cfg.actor.anakin_lanes
    lps = n // dp
    n_segments = 3     # spans an episode boundary (40 = 2 x 20)
    for seg in range(n_segments):
        carry, replay, stats = act(params, carry, replay,
                                   np.int32(seg + 1))
    glob = jax.device_get(replay)

    for s in range(dp):
        act1 = make_anakin_act(
            env, net, spec, num_lanes=lps,
            epsilons=eps[s * lps:(s + 1) * lps], gamma=cfg.optim.gamma,
            priority=cfg.actor.anakin_priority,
            near_greedy_eps=cfg.actor.near_greedy_eps,
            # the shard's slice of the GLOBAL ladder carries its global
            # lane-provenance stamps (ISSUE 10)
            lane_base=s * lps)
        c1 = init_act_carry(env, spec, lps, jax.random.fold_in(key, s))
        ref = replay_init(spec)
        for seg in range(n_segments):
            c1, blocks, _ = act1(params, c1, np.int32(seg + 1))
            ref = replay_add_many(spec, ref, blocks)
        ref = jax.device_get(ref)
        for name in glob.__dataclass_fields__:
            np.testing.assert_array_equal(
                np.asarray(getattr(glob, name))[s],
                np.asarray(getattr(ref, name)),
                err_msg=f"shard {s} field {name}")


def test_per_shard_rng_independence():
    """Shards explore independently: with identical lane counts and the
    same params, the two shards' stored experience must differ — env
    schedules (obs rows) AND action streams (fold_in(key, shard) chains,
    not one chain replicated)."""
    cfg = sharded_cfg()
    (env, spec, net, params, mesh, eps, act, carry, replay,
     key) = _build_sharded(cfg)
    carry, replay, _ = act(params, carry, replay, np.int32(1))
    glob = jax.device_get(replay)
    obs = np.asarray(glob.obs)
    actions = np.asarray(glob.action)
    lps = cfg.actor.anakin_lanes // 2
    assert not np.array_equal(obs[0, :lps], obs[1, :lps])
    assert not np.array_equal(actions[0, :lps], actions[1, :lps])
    # and lanes WITHIN a shard differ too (per-lane env keys)
    assert not np.array_equal(obs[0, 0], obs[0, 1])


# ---- global ε-ladder layout ----------------------------------------------


def test_epsilon_ladder_spans_global_lanes():
    """The Ape-X ladder covers the GLOBAL lane count: with 4 lanes over
    2 shards, the two near-greedy lanes (ε <= near_greedy_eps) are BOTH
    in shard 1 — a per-shard ladder would put one reporter in each
    shard. Asserted through the per-shard reported-episode counts at the
    episode-boundary segment."""
    cfg = sharded_cfg()
    n = cfg.actor.anakin_lanes
    eps = [apex_epsilon(i, n, cfg.actor.base_eps, cfg.actor.eps_alpha)
           for i in range(n)]
    report = [e <= cfg.actor.near_greedy_eps for e in eps]
    assert report == [False, False, True, True]   # the global layout
    (env, spec, net, params, mesh, _, act, carry, replay,
     key) = _build_sharded(cfg)
    carry, replay, _ = act(params, carry, replay, np.int32(1))
    carry, replay, stats = act(params, carry, replay, np.int32(2))
    stats = jax.device_get(stats)
    assert stats["episodes"].tolist() == [2, 2]
    assert stats["reported_episodes"].tolist() == [0, 2]
    assert float(stats["reported_return_sum"][0]) == 0.0
    assert stats["env_steps"].tolist() == [40, 40]


# ---- the composed loop ---------------------------------------------------


def test_sharded_anakin_loop_trains_end_to_end(tmp_path):
    """The composed path through orchestrator.train: per-shard acting
    fills the dp-sharded replay, the gate opens, the dp-sharded learner
    trains, and the records carry the per-shard anakin block with a
    balanced imbalance ratio."""
    from r2d2_tpu.runtime.orchestrator import train
    cfg = sharded_cfg(**{
        "replay.capacity": 400, "replay.learning_starts": 60,
        "actor.anakin_lanes": 4, "env.episode_len": 20,
        "replay.block_length": 10, "replay.batch_size": 4,
        "runtime.save_dir": str(tmp_path), "runtime.log_interval": 0.2,
    })
    records = []
    stacks = train(cfg, max_training_steps=6, max_seconds=180,
                   log_fn=records.append)
    lr = stacks[0].learner
    assert lr.training_steps >= 6
    assert lr.mesh is not None and lr.mesh.shape["dp"] == 2
    assert lr.env_steps >= cfg.replay.learning_starts
    an = next((r["anakin"] for r in records if r.get("anakin")), None)
    assert an is not None
    assert an["dp"] == 2 and an["lanes_per_shard"] == 2
    assert len(an["shard_env_steps"]) == 2
    assert an["shard_imbalance"] == 1.0   # lockstep lane groups
    # the sentinel saw the block and stayed quiet
    alerts = [a["rule"] for r in records
              for a in (r.get("alerts") or {}).get("fired") or []]
    assert "shard_imbalance" not in alerts


def test_dp1_loop_emits_single_shard_anakin_block(tmp_path):
    """The 1x1-mesh fused loop reports the same block shape with one
    row, so inspectors and the alert rule read both compositions."""
    from r2d2_tpu.runtime.anakin_loop import run_anakin_train
    cfg = sharded_cfg(**{
        "mesh.dp": 1,
        "replay.capacity": 400, "replay.learning_starts": 60,
        "actor.anakin_lanes": 2, "env.episode_len": 20,
        "replay.block_length": 10, "replay.batch_size": 4,
        "runtime.save_dir": str(tmp_path), "runtime.log_interval": 0.2,
    })
    records = []
    run_anakin_train(cfg, max_training_steps=4, max_seconds=120,
                     log_fn=records.append)
    an = next((r["anakin"] for r in records if r.get("anakin")), None)
    assert an is not None and an["dp"] == 1
    assert len(an["shard_env_steps"]) == 1
    assert an["shard_imbalance"] == 1.0


# ---- the shard_imbalance alert rule --------------------------------------


def test_shard_imbalance_alert_rule():
    from r2d2_tpu.telemetry.alerts import AlertEngine, default_rules
    t = Config().telemetry
    eng = AlertEngine(default_rules(t))
    by_name = {r.name: r for r in eng.rules}
    rule = by_name["shard_imbalance"]
    assert rule.path == ("anakin", "shard_imbalance")
    assert rule.bound == t.alerts_shard_imbalance
    # balanced interval: quiet; no block at all (host runs): quiet
    assert eng.evaluate({"anakin": {"shard_imbalance": 1.0}})["fired"] == []
    assert eng.evaluate({})["fired"] == []
    # a skewed interval fires once, then holds while the skew persists
    out = eng.evaluate({"anakin": {"shard_imbalance": 2.0}})
    assert [a["rule"] for a in out["fired"]] == ["shard_imbalance"]
    out = eng.evaluate({"anakin": {"shard_imbalance": 2.0}})
    assert out["fired"] == [] and "shard_imbalance" in out["active"]


def test_shard_imbalance_knob_validated():
    with pytest.raises(ValueError, match="alerts_shard_imbalance"):
        Config().replace(**{"telemetry.alerts_shard_imbalance": 1.0})
    cfg = Config().replace(**{"telemetry.alerts_shard_imbalance": 2.5})
    again = Config.from_dict(json.loads(cfg.to_json()))
    assert again.telemetry.alerts_shard_imbalance == 2.5
    # pre-PR8 serialized configs load with the default
    d = Config().to_dict()
    d["telemetry"].pop("alerts_shard_imbalance")
    assert Config.from_dict(d).telemetry.alerts_shard_imbalance == 1.5


# ---- learnability under the sharded composition (slow) -------------------

GRID_TRAIN_STEPS = 2000


def _grid_cfg(save_dir: str) -> Config:
    return Config().replace(**{
        "env.game_name": "Grid", "env.grid_size": 5,
        "env.frame_height": 20, "env.frame_width": 20,
        "env.frame_stack": 2, "env.episode_len": 40,
        "network.hidden_dim": 32, "network.cnn_out_dim": 64,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 32_000, "replay.block_length": 40,
        "replay.batch_size": 16, "replay.learning_starts": 2_000,
        "replay.max_env_steps_per_train_step": 16.0,
        "actor.on_device": True, "actor.anakin_lanes": 32,
        "mesh.dp": 2,
        "optim.lr": 1e-3, "optim.gamma": 0.99,
        "runtime.save_interval": 0, "runtime.log_interval": 8.0,
        "runtime.save_dir": save_dir,
    })


def _grid_train(save_dir: str) -> dict:
    from r2d2_tpu.runtime.anakin_loop import run_anakin_train
    records = []
    stacks = run_anakin_train(_grid_cfg(save_dir),
                              max_training_steps=GRID_TRAIN_STEPS,
                              max_seconds=600, log_fn=records.append)
    returns = [r["avg_episode_return"] for r in records
               if r.get("avg_episode_return") is not None]
    return {"training_steps": int(stacks[0].learner.training_steps),
            "returns": returns}


@pytest.mark.slow
def test_grid_learnability_under_sharded_loop(tmp_path):
    """The jitted gridworld still LEARNS when the fused loop is sharded
    dp=2: per-shard exploration feeding per-shard replay trains one
    (replicated) policy whose behavior return grows several-fold.
    Subprocess on a 2-device CPU platform (the dp=2 mesh, no more — the
    suite's 8-device pin triples single-core wall time)."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-4000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["training_steps"] >= GRID_TRAIN_STEPS
    returns = result["returns"]
    assert len(returns) >= 2, returns
    early, late = returns[0], returns[-1]
    assert late >= max(3.0 * early, early + 0.3), returns


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from r2d2_tpu.utils.platform import pin_platform
    pin_platform()
    print(json.dumps(_grid_train(sys.argv[1])))
