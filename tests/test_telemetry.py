"""Telemetry subsystem tests (ISSUE 4): histogram percentiles and merge,
span tracer ring semantics, cross-process board aggregation, the
aggregated TrainMetrics record (including PR-2/3 schema stability and the
logparse round-trip), profiler capture lifecycle, and a slow end-to-end
slice proving the whole pipeline emits fleet-wide stage percentiles.
"""

import json
import os
import pickle
import threading
import time

import numpy as np
import pytest

from r2d2_tpu.telemetry import (NBUCKETS, NULL_TELEMETRY, STAGES,
                                LogHistogram, ProfilerCapture, SpanTracer,
                                StageTimers, Telemetry, TelemetryBoard,
                                bucket_bounds, bucket_index, bucket_mid,
                                chrome_trace_events, percentile, summarize)
from r2d2_tpu.tools.logparse import parse_jsonl, parse_log


# ---------------------------------------------------------------------------
# histograms

def test_bucket_index_monotonic_and_bounded():
    durations = [1e-9, 1e-7, 1e-6, 1e-5, 1e-3, 0.1, 1.0, 10.0, 99.0, 1e4]
    idx = [bucket_index(d) for d in durations]
    assert idx == sorted(idx)
    assert all(0 <= i < NBUCKETS for i in idx)
    assert bucket_index(0.0) == 0
    assert bucket_index(1e9) == NBUCKETS - 1


def test_bucket_value_inside_bounds():
    for i in (0, 1, 17, NBUCKETS - 1):
        lo, hi = bucket_bounds(i)
        assert lo < bucket_mid(i) < hi
        # a duration at the midpoint maps back into its own bucket
        assert bucket_index(bucket_mid(i)) == i


def test_percentile_known_distribution():
    h = LogHistogram()
    # 90 fast observations at ~1 ms, 10 slow at ~1 s: P50 must report the
    # fast mode, P99 the slow tail — the exact property interval means hide
    for _ in range(90):
        h.add(1e-3)
    for _ in range(10):
        h.add(1.0)
    p50, p99 = h.percentile(0.50), h.percentile(0.99)
    assert 0.5e-3 < p50 < 2e-3
    assert 0.5 < p99 < 2.0
    assert h.total == 100


def test_percentile_resolution_is_bucket_bounded():
    # one observation: every percentile reports its bucket midpoint, and
    # the midpoint is within one bucket's growth factor (~33%) of truth
    h = LogHistogram()
    h.add(0.0123)
    lo, hi = bucket_bounds(bucket_index(0.0123))
    assert lo <= h.percentile(0.5) <= hi
    assert hi / lo < 1.4


def test_histogram_merge_equals_combined():
    rng = np.random.default_rng(0)
    a, b, both = LogHistogram(), LogHistogram(), LogHistogram()
    for d in rng.uniform(1e-5, 1e-2, 200):
        a.add(d), both.add(d)
    for d in rng.uniform(1e-3, 1.0, 300):
        b.add(d), both.add(d)
    merged = a.merge(b)
    np.testing.assert_array_equal(merged.counts, both.counts)
    for q in (0.5, 0.95, 0.99):
        assert merged.percentile(q) == both.percentile(q)


def test_empty_histogram():
    h = LogHistogram()
    assert h.percentile(0.5) is None
    assert h.summarize() is None
    assert summarize(np.zeros(NBUCKETS, np.int64)) is None


def test_summarize_schema():
    h = LogHistogram()
    h.add(0.01)
    s = h.summarize()
    assert set(s) == {"count", "p50_ms", "p95_ms", "p99_ms"}
    assert s["count"] == 1
    assert s["p50_ms"] == s["p99_ms"]
    assert 5.0 < s["p50_ms"] < 20.0          # ms units


# ---------------------------------------------------------------------------
# stage timers

def test_stage_timers_take_is_per_interval():
    st = StageTimers()
    st.observe("actor/env_step", 1e-3)
    st.observe("actor/env_step", 2e-3)
    st.observe("ingest/commit", 0.1)
    first = st.take()
    assert first.sum() == 3
    assert first[STAGES.index("actor/env_step")].sum() == 2
    # nothing new -> empty interval; cumulative stays monotonic
    assert st.take().sum() == 0
    st.observe("ingest/commit", 0.2)
    assert st.take().sum() == 1
    assert st.cumulative().sum() == 4


def test_stage_timers_unknown_stage_raises():
    with pytest.raises(KeyError):
        StageTimers().observe("actor/definitely_not_a_stage", 1.0)


def test_stage_timers_thread_safety():
    st = StageTimers()

    def worker():
        for _ in range(500):
            st.observe("actor/forward", 1e-4)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert st.cumulative().sum() == 2000


# ---------------------------------------------------------------------------
# span tracer

def test_span_tracer_records_and_drains():
    tr = SpanTracer(ring_size=64)
    tr.record("a", 1.0, 1.5, {"k": 1})
    tr.record("b", 2.0, 2.25)
    events = tr.drain()
    assert [e["name"] for e in events] == ["a", "b"]
    assert events[0]["dur"] == pytest.approx(0.5)
    assert events[0]["tags"] == {"k": 1}
    assert "tid" in events[0]
    assert tr.drain() == []          # drained


def test_span_tracer_ring_drops_oldest():
    tr = SpanTracer(ring_size=16)
    for i in range(40):
        tr.record(f"s{i}", float(i), float(i) + 0.1)
    events = tr.drain()
    assert len(events) == 16
    assert events[-1]["name"] == "s39"   # newest survives
    assert tr.dropped == 40 - 16


def test_span_tracer_disabled_is_noop():
    tr = SpanTracer(ring_size=16, enabled=False)
    tr.record("a", 0.0, 1.0)
    with tr.span("b"):
        pass
    assert tr.drain() == []


def test_span_context_manager_records_on_raise():
    tr = SpanTracer(ring_size=16)
    with pytest.raises(RuntimeError):
        with tr.span("boom", slot=3):
            raise RuntimeError("x")
    (ev,) = tr.drain()
    assert ev["name"] == "boom" and ev["tags"] == {"slot": 3}


def test_span_tracer_prunes_dead_thread_rings():
    tr = SpanTracer(ring_size=16)
    for i in range(3):
        t = threading.Thread(target=lambda i=i: tr.record(
            f"w{i}", float(i), float(i) + 0.1))
        t.start()
        t.join()
    assert len(tr._rings) == 3
    events = tr.drain()
    assert len(events) == 3
    # drained rings of dead threads are pruned — a crash-looping soak's
    # respawned workers must not grow the registry without bound
    assert tr._rings == []


def test_span_tracer_multi_thread_rings():
    tr = SpanTracer(ring_size=64)

    def worker(i):
        tr.record(f"w{i}", float(i), float(i) + 0.1)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tr.record("main", 10.0, 10.1)
    events = tr.drain()
    assert {e["name"] for e in events} == {"w0", "w1", "w2", "main"}
    assert len({e["tid"] for e in events}) == 4


# ---------------------------------------------------------------------------
# cross-process board

def test_board_publish_read_roundtrip_via_pickle():
    board = TelemetryBoard(2)
    try:
        attached = pickle.loads(pickle.dumps(board))   # the spawn path
        counts = np.zeros((len(STAGES), NBUCKETS), np.int64)
        counts[STAGES.index("actor/forward"), 10] = 7
        attached.publish(1, counts)
        table = board.read()
        assert table.shape == (2, len(STAGES), NBUCKETS)
        assert table[1, STAGES.index("actor/forward"), 10] == 7
        assert table[0].sum() == 0
        attached.close()
    finally:
        board.close()


def test_board_take_deltas_interval_and_slot_reset():
    board = TelemetryBoard(2)
    try:
        row = np.zeros((len(STAGES), NBUCKETS), np.int64)
        fwd = STAGES.index("actor/forward")
        row[fwd, 5] = 10
        board.publish(0, row)
        d1 = board.take_deltas()
        assert d1[fwd, 5] == 10
        # cumulative grows by 5 -> next interval sees exactly the 5
        row[fwd, 5] = 15
        board.publish(0, row)
        assert board.take_deltas()[fwd, 5] == 5
        # respawn: slot restarts from zero, then publishes 3 — the reset
        # detection must take the fresh cumulative as the delta, never a
        # clipped negative
        board.reset_slot(0)
        row2 = np.zeros_like(row)
        row2[fwd, 5] = 3
        board.publish(0, row2)
        assert board.take_deltas()[fwd, 5] == 3
    finally:
        board.close()


def test_telemetry_facade_merges_local_and_board():
    board = TelemetryBoard(1)
    try:
        worker = Telemetry(name="worker", board=pickle.loads(
            pickle.dumps(board)), slot=0)
        worker.observe("actor/env_step", 1e-3)
        worker.observe("actor/env_step", 1e-3)
        worker.flush()
        agg = Telemetry(name="agg")
        agg.attach_board(board)
        agg.observe("learner/train_dispatch", 0.05)
        summary = agg.interval_summary()
        assert summary["actor/env_step"]["count"] == 2
        assert summary["learner/train_dispatch"]["count"] == 1
        # interval consumed: a second take with no new data is empty
        assert agg.interval_summary() == {}
    finally:
        board.close()


def test_null_telemetry_is_inert():
    NULL_TELEMETRY.observe("actor/env_step", 1.0)
    NULL_TELEMETRY.record_span("x", 0.0, 1.0)
    with NULL_TELEMETRY.span("y"):
        pass
    assert NULL_TELEMETRY.interval_summary() == {}
    assert not NULL_TELEMETRY.enabled


def test_telemetry_drain_thread_flushes_spans_and_board(tmp_path):
    board = TelemetryBoard(1)
    try:
        worker = Telemetry(name="w", board=board, slot=0,
                           flush_interval_s=0.05)
        path = str(tmp_path / "spans_w.jsonl")
        worker.start_drain(path)
        worker.observe("actor/block_emit", 0.01)
        worker.record_span("actor/block_emit", 1.0, 1.01)
        time.sleep(0.3)
        worker.close()
        events = parse_jsonl(path)
        assert any(e["name"] == "actor/block_emit" for e in events)
        assert events[0]["pid"] == "w"
        assert board.read().sum() == 1
    finally:
        board.close()


# ---------------------------------------------------------------------------
# chrome-trace export

def test_chrome_trace_events_schema():
    tr = SpanTracer(ring_size=16)
    tr.record("stage/a", 1.0, 1.5, {"slot": 0})
    events = chrome_trace_events(tr.drain(), pid="actor-0", pid_index=3)
    x = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert len(x) == 1
    assert x[0]["ts"] == pytest.approx(1.0e6)
    assert x[0]["dur"] == pytest.approx(0.5e6)
    assert x[0]["pid"] == 3
    assert {m["name"] for m in meta} == {"process_name", "thread_name"}


def test_export_chrome_trace_merges_files(tmp_path):
    from r2d2_tpu.tools.inspect import export_chrome_trace
    for proc in ("p0_a0", "player0"):
        with open(tmp_path / f"spans_{proc}.jsonl", "w") as f:
            for i in range(3):
                f.write(json.dumps({
                    "name": "actor/block_emit", "ts": 100.0 + i,
                    "dur": 0.5, "tid": "t", "pid": proc}) + "\n")
    out = str(tmp_path / "trace.json")
    n = export_chrome_trace(str(tmp_path), out)
    assert n == 6
    trace = json.load(open(out))
    x = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(x) == 6
    assert len({e["pid"] for e in x}) == 2   # one pid row per process


# ---------------------------------------------------------------------------
# TrainMetrics aggregation + schema stability + logparse round-trip

# Every key PR 2 (ingestion observability) and PR 3 (worker health) added
# to the periodic record — the aggregation refactor must not lose one.
PR23_RECORD_KEYS = {
    # base
    "t", "buffer_size", "buffer_speed", "env_steps", "avg_episode_return",
    "training_steps", "training_speed", "loss", "dropped_priority_updates",
    # PR 2: ingestion observability
    "ingest_blocks_total", "ingest_drains", "ingest_blocks_per_drain",
    "ingest_drain_latency_ms", "ingest_queue_depth", "ingest_pause_time",
    # PR 3: worker health
    "actor_restarts", "actor_hangs_detected", "actor_breaker_trips",
    "actor_parked_slots", "shm_slots_recovered", "ingest_stall_dumps",
    "heartbeat_age_max_s",
}


def _metrics(tmp_path, **kwargs):
    from r2d2_tpu.runtime.metrics import TrainMetrics
    return TrainMetrics(0, str(tmp_path), **kwargs)


def test_record_schema_stability_with_telemetry(tmp_path):
    m = _metrics(tmp_path)
    tele = Telemetry(name="t")
    m.set_telemetry(tele)
    tele.observe("learner/train_dispatch", 0.02)
    m.on_block(20, 1.5)
    m.on_train_step(0.5)
    record = m.log(10.0)
    missing = PR23_RECORD_KEYS - set(record)
    assert not missing, f"aggregation refactor dropped keys: {missing}"
    assert "stages" in record and "telemetry_dropped_spans" in record
    assert record["stages"]["learner/train_dispatch"]["count"] == 1


def test_record_omits_stages_when_disabled(tmp_path):
    m = _metrics(tmp_path)     # default telemetry attr is NULL
    record = m.log(10.0)
    assert "stages" not in record
    assert "telemetry_dropped_spans" not in record
    assert PR23_RECORD_KEYS <= set(record)


def test_jsonl_roundtrip_of_aggregated_record(tmp_path):
    m = _metrics(tmp_path)
    tele = Telemetry(name="t")
    m.set_telemetry(tele)
    for _ in range(5):
        tele.observe("actor/env_step", 1e-3)
    tele.observe("ingest/commit", 0.2)
    m.on_block(20, 2.0)
    written = m.log(5.0)
    tele.observe("actor/env_step", 1e-3)
    written2 = m.log(5.0)
    records = parse_jsonl(str(tmp_path / "metrics_player0.jsonl"))
    assert len(records) == 2
    assert records[0] == json.loads(json.dumps(written))
    assert records[1]["stages"]["actor/env_step"]["count"] == 1
    assert records[0]["stages"]["ingest/commit"]["p99_ms"] > \
        records[0]["stages"]["actor/env_step"]["p99_ms"]
    assert json.loads(json.dumps(written2)) == records[1]
    # the human log alongside still parses with the reference parser
    parsed = parse_log(str(tmp_path / "train_player0.log"))
    assert len(parsed.buffer_sizes) == 2


def test_parse_jsonl_skips_partial_trailing_line(tmp_path):
    path = tmp_path / "m.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"a": 1}) + "\n")
        f.write('{"a": 2, "tr')          # writer mid-append
    assert parse_jsonl(str(path)) == [{"a": 1}]


def test_metrics_fresh_run_truncates_resume_appends(tmp_path):
    m1 = _metrics(tmp_path)
    m1.log(1.0)
    m1.log(1.0)
    # resume: both the human log and the JSONL keep their history
    m2 = _metrics(tmp_path, resume=True)
    m2.log(1.0)
    assert len(parse_jsonl(str(tmp_path / "metrics_player0.jsonl"))) == 3
    assert len(parse_log(str(tmp_path / "train_player0.log")).buffer_sizes) == 3
    # fresh: both truncate
    m3 = _metrics(tmp_path)
    m3.log(1.0)
    assert len(parse_jsonl(str(tmp_path / "metrics_player0.jsonl"))) == 1
    assert len(parse_log(str(tmp_path / "train_player0.log")).buffer_sizes) == 1


def test_put_patient_observes_queue_wait():
    import queue

    from r2d2_tpu.runtime.feeder import put_patient
    q = queue.Queue(maxsize=4)
    tele = Telemetry(name="t")
    assert put_patient(q, "block", should_stop=lambda: False,
                       telemetry=tele)
    summary = tele.interval_summary()
    assert summary["actor/queue_put"]["count"] == 1


# ---------------------------------------------------------------------------
# config

def test_config_missing_telemetry_section_defaults():
    from r2d2_tpu.config import Config
    d = Config().to_dict()
    d.pop("telemetry")
    cfg = Config.from_dict(d)                # pre-telemetry checkpoint
    assert cfg.telemetry.enabled is True
    assert Config.from_json(Config().to_json()).telemetry.ring_size == 4096


def test_config_validates_telemetry_fields():
    from r2d2_tpu.config import Config
    with pytest.raises(ValueError, match="ring_size"):
        Config().replace(**{"telemetry.ring_size": 2})
    with pytest.raises(ValueError, match="flush_interval_s"):
        Config().replace(**{"telemetry.flush_interval_s": 0.0})
    with pytest.raises(ValueError, match="profile_at_step"):
        Config().replace(**{"runtime.profile_at_step": -1})


# ---------------------------------------------------------------------------
# profiler capture lifecycle (monkeypatched jax.profiler: the state
# machine is what satellite 2 fixes — no real trace needed)

class _FakeProfiler:
    def __init__(self):
        self.starts = 0
        self.stops = 0
        self.active = False

    def start_trace(self, out_dir):
        if self.active:
            raise RuntimeError("trace already active")
        self.active = True
        self.starts += 1

    def stop_trace(self):
        if not self.active:
            raise RuntimeError("no trace active")
        self.active = False
        self.stops += 1


@pytest.fixture
def fake_profiler(monkeypatch):
    import jax
    fake = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    return fake


def test_profiler_capture_stop_is_idempotent(fake_profiler):
    cap = ProfilerCapture()
    cap.stop()                       # no capture: must not touch jax
    assert fake_profiler.stops == 0
    assert cap.start("/tmp/x")
    assert not cap.start("/tmp/y")   # second start refused, no state harm
    cap.stop()
    cap.stop()                       # the old double-stop path: now a no-op
    assert fake_profiler.starts == 1
    assert fake_profiler.stops == 1
    assert cap.captures == 1


def test_profiler_capture_poll_bounds_window(fake_profiler):
    cap = ProfilerCapture()
    cap.start("/tmp/x", duration_s=10.0)
    t0 = time.time()
    assert not cap.poll(t0 + 5.0)
    assert cap.active
    assert cap.poll(t0 + 11.0)
    assert not cap.active
    assert not cap.poll(t0 + 12.0)   # already stopped


def test_profiler_trace_contextmanager_stops_on_raise(fake_profiler):
    from r2d2_tpu.telemetry.profiler import trace
    with pytest.raises(RuntimeError, match="boom"):
        with trace("/tmp/x"):
            assert fake_profiler.active
            raise RuntimeError("boom")
    assert not fake_profiler.active
    assert fake_profiler.stops == 1


# ---------------------------------------------------------------------------
# inspector rendering

def test_render_record_includes_stage_table():
    from r2d2_tpu.tools.inspect import render_record
    record = {"t": 12.0, "env_steps": 100, "training_steps": 4,
              "buffer_size": 80, "buffer_speed": 10.0,
              "training_speed": 0.4, "loss": 0.1,
              "ingest_blocks_total": 5, "ingest_queue_depth": 0,
              "ingest_pause_time": 0.0, "actor_restarts": 1,
              "stages": {"actor/forward": {"count": 3, "p50_ms": 1.0,
                                           "p95_ms": 2.0, "p99_ms": 3.0}}}
    frame = render_record(record, [{"rank": 1, "t": 11.0,
                                    "stages": {"x": {}}}])
    assert "actor/forward" in frame
    assert "p99 ms" in frame
    assert "restarts=1" in frame
    # host rows render as the per-rank fleet panel (ISSUE 12 replaced
    # the one-line "host rank r: N stages" summary)
    assert "per-rank" in frame and "rank 1" in frame


def test_render_record_without_telemetry():
    from r2d2_tpu.tools.inspect import render_record
    frame = render_record({"t": 1.0})
    assert "telemetry.enabled" in frame


# ---------------------------------------------------------------------------
# end-to-end slice (slow): the full pipeline emits fleet-wide stage
# percentiles, spans export to a loadable Chrome trace, and
# runtime.profile_at_step triggers a mid-run capture

@pytest.mark.slow
def test_e2e_thread_telemetry_and_midrun_capture(tmp_path):
    import glob

    from r2d2_tpu.runtime.orchestrator import train
    from r2d2_tpu.tools.inspect import export_chrome_trace
    from tests.test_runtime import tiny_config

    cfg = tiny_config(tmp_path, **{
        "runtime.profile_at_step": 5,
        "runtime.save_interval": 0,
        "runtime.log_interval": 1.0,
        "telemetry.flush_interval_s": 0.3,
    })
    records = []
    stacks = train(cfg, max_training_steps=25, max_seconds=180,
                   actor_mode="thread", log_fn=records.append)
    assert stacks[0].learner.training_steps >= 25
    stages = set()
    for r in records:
        stages |= set(r.get("stages") or {})
    # the acceptance bar: >= 6 distinct pipeline stages aggregated into
    # the per-interval record
    assert len(stages) >= 6, f"only {sorted(stages)}"
    assert {"actor/forward", "actor/env_step", "actor/block_emit",
            "learner/train_dispatch"} <= stages
    for name in stages:
        for r in records:
            if name in (r.get("stages") or {}):
                assert {"count", "p50_ms", "p95_ms", "p99_ms"} <= set(
                    r["stages"][name])
    # spans drained to disk and export to a valid Chrome trace
    out = str(tmp_path / "trace.json")
    assert export_chrome_trace(str(tmp_path), out) > 0
    trace = json.load(open(out))
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])
    # the mid-run capture fired (profile_at_step=5 < 25 steps)
    assert glob.glob(str(tmp_path / "xprof" / "**" / "*.trace.json.gz"),
                     recursive=True) or \
        glob.glob(str(tmp_path / "xprof" / "**" / "*.xplane.pb"),
                  recursive=True)


@pytest.mark.slow
def test_e2e_telemetry_kill_switch(tmp_path):
    from r2d2_tpu.runtime.orchestrator import train
    from tests.test_runtime import tiny_config

    cfg = tiny_config(tmp_path, **{
        "telemetry.enabled": False,
        "runtime.save_interval": 0,
        "runtime.log_interval": 1.0,
    })
    records = []
    train(cfg, max_training_steps=10, max_seconds=120,
          actor_mode="thread", log_fn=records.append)
    assert records
    assert all("stages" not in r for r in records)
    assert not list(tmp_path.glob("spans_*.jsonl"))
