"""Batched + pipelined replay ingestion (ISSUE 2): replay_add_many parity
with K sequential adds (including ring wrap and the dp-sharded round-robin),
stacked feeder drains (shm ring + fallback backends), the learner's ingest
pipeline (commit-time accounting, rate-limiter semantics, drain-burst knob),
and the ingestion observability counters.
"""

import contextlib
import queue as queue_mod
import time

import jax
import numpy as np
import pytest

from tests.test_replay import _fill_blocks, make_spec

from r2d2_tpu.config import Config, MeshConfig
from r2d2_tpu.replay import (
    Block, HostReplay, replay_add, replay_add_many, replay_init)
from r2d2_tpu.runtime.feeder import BlockQueue
from r2d2_tpu.runtime.metrics import TrainMetrics


def stack_blocks(blocks) -> Block:
    """np.stack every leaf — the reference stacking the transports'
    drain_stacked fast paths are checked against."""
    return jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *blocks)


def assert_trees_equal(a, b):
    for (path, la), (_, lb) in zip(
            jax.tree_util.tree_leaves_with_path(a),
            jax.tree_util.tree_leaves_with_path(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                      err_msg=str(path))


# interpret = eager tracing via jax.disable_jit(): the acceptance criterion
# wants add_many parity to hold both compiled and uncompiled
MODES = ("compiled", "interpret")


def mode_ctx(mode):
    return jax.disable_jit() if mode == "interpret" else (
        contextlib.nullcontext())


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("exact_gather", [False, True])
def test_add_many_matches_sequential_adds_with_wrap(rng, mode, exact_gather):
    """replay_add_many(K) == K sequential replay_add — ring rows, tree
    leaves, pointer — across a wrap of a 4-slot ring, padded storage
    (exact_gather) included."""
    spec = make_spec(num_blocks=4, exact_gather=exact_gather)
    blocks = _fill_blocks(spec, 6, rng)   # wraps: 6 adds over 4 slots
    with mode_ctx(mode):
        seq = replay_init(spec)
        for blk in blocks:
            seq = replay_add(spec, seq, blk)
        many = replay_init(spec)
        many = replay_add_many(spec, many, stack_blocks(blocks[:3]))
        many = replay_add_many(spec, many, stack_blocks(blocks[3:5]))
        # a K=1 stacked batch and a plain add interoperate on one state
        many = replay_add_many(spec, many, stack_blocks(blocks[5:6]))
    assert_trees_equal(seq, many)
    assert int(many.block_ptr) == 6 % 4


def test_add_many_exact_ring_fill(rng):
    """K == num_blocks is the largest legal batch (all rows distinct)."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 4, rng)
    seq = replay_init(spec)
    for blk in blocks:
        seq = replay_add(spec, seq, blk)
    many = replay_add_many(spec, replay_init(spec), stack_blocks(blocks))
    assert_trees_equal(seq, many)
    assert int(many.block_ptr) == 0


def test_add_many_rejects_aliasing_batch(rng):
    """K > num_blocks would scatter twice into one ring row (undefined
    order) — refused at trace time with the config hint."""
    spec = make_spec(num_blocks=2)
    blocks = _fill_blocks(spec, 3, rng)
    with pytest.raises(ValueError, match="ingest_batch_blocks"):
        replay_add_many(spec, replay_init(spec), stack_blocks(blocks))


@pytest.mark.parametrize("mode", MODES)
def test_sharded_add_many_matches_round_robin(rng, mode):
    """One add_many dispatch == K sequential sharded adds round-robining
    from the same start shard, including per-shard ring wrap (7 blocks
    over dp=4 shards of 3 rows each) and a start_shard mid-cycle."""
    from r2d2_tpu.parallel import (
        make_mesh, make_sharded_replay_add, make_sharded_replay_add_many,
        sharded_replay_init)

    spec = make_spec(num_blocks=3)
    mesh = make_mesh(MeshConfig(dp=4))
    blocks = _fill_blocks(spec, 7, rng)
    with mode_ctx(mode):
        add1 = make_sharded_replay_add(spec, mesh)
        addk = make_sharded_replay_add_many(spec, mesh)
        seq = sharded_replay_init(spec, mesh)
        shard = 2
        for blk in blocks:
            seq = add1(seq, blk, shard)
            shard = (shard + 1) % 4
        many = sharded_replay_init(spec, mesh)
        many = addk(many, stack_blocks(blocks[:5]), 2)
        many = addk(many, stack_blocks(blocks[5:]), (2 + 5) % 4)
    assert_trees_equal(seq, many)


def test_blockqueue_stacked_drain_fallback(rng):
    """The queue.Queue / mp.Queue fallback stacks per-block pops into the
    same contract the shm fast path returns."""
    spec = make_spec()
    blocks = _fill_blocks(spec, 5, rng)
    q = BlockQueue(use_mp=False)
    for blk in blocks:
        q.put(blk)
    stacked, k = q.drain_stacked(3)
    assert k == 3
    assert_trees_equal(stacked, stack_blocks(blocks[:3]))
    stacked, k = q.drain_stacked(16)     # partial tail drain
    assert k == 2
    assert_trees_equal(stacked, stack_blocks(blocks[3:]))
    assert q.drain_stacked(4) == (None, 0)


def test_shm_ring_stacked_drain(rng):
    """Stacked drain straight from the shm ring slots: field-for-field
    equal to the per-block pops, FIFO order, partial tail, empty case."""
    pytest.importorskip("r2d2_tpu.native")
    from r2d2_tpu.runtime.shm_feeder import ShmBlockRing

    spec = make_spec()
    blocks = _fill_blocks(spec, 5, rng)
    ring = ShmBlockRing(spec, maxsize=8)
    try:
        for blk in blocks:
            ring.put(blk, timeout=1.0)
        stacked, k = ring.drain_stacked(3)
        assert k == 3
        assert_trees_equal(stacked, stack_blocks(blocks[:3]))
        # each leaf is one contiguous array, device_put-ready
        assert all(np.asarray(x).flags["C_CONTIGUOUS"]
                   for x in jax.tree_util.tree_leaves(stacked))
        stacked, k = ring.drain_stacked(16)
        assert k == 2
        assert_trees_equal(stacked, stack_blocks(blocks[3:]))
        assert ring.drain_stacked(4) == (None, 0)
        # ring still usable after stacked drains
        ring.put(blocks[0], timeout=1.0)
        got = ring.get_nowait()
        assert_trees_equal(got, blocks[0])
    finally:
        ring.close()


# -- learner pipeline --

LEARNER_OVERRIDES = {
    "env.game_name": "Fake",
    "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
    "network.hidden_dim": 16, "network.cnn_out_dim": 32,
    "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
    "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
    "sequence.forward_steps": 3,
    "replay.capacity": 800, "replay.block_length": 20,
    "replay.batch_size": 8, "replay.learning_starts": 100,
    "runtime.save_interval": 0, "runtime.steps_per_dispatch": 1,
}


def make_learner(tmp_path, **extra):
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.learner_loop import Learner

    ov = dict(LEARNER_OVERRIDES)
    ov["runtime.save_dir"] = str(tmp_path)
    ov.update(extra)
    cfg = Config().replace(**ov)
    net = NetworkApply(4, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    return cfg, Learner(cfg, net)


def fill_learner_blocks(learner, n, rng):
    from r2d2_tpu.actor.local_buffer import LocalBuffer

    spec = learner.spec
    buf = LocalBuffer(spec, 4, gamma=0.9)
    buf.reset(np.zeros((spec.frame_height, spec.frame_width), np.uint8))
    out = []
    for _ in range(n):
        for t in range(spec.block_length):
            buf.add(t % 4, float(t % 3),
                    np.full((spec.frame_height, spec.frame_width),
                            t % 250, np.uint8),
                    rng.normal(size=4).astype(np.float32),
                    rng.normal(size=(2, spec.hidden_dim)).astype(np.float32))
        out.append(buf.finish(last_qval=np.ones(4, np.float32)))
    return out


def drain_until(learner, q, want, timeout=30.0):
    n = 0
    deadline = time.time() + timeout
    while n < want and time.time() < deadline:
        n += learner.drain(q)
        time.sleep(0.01)
    return n


def test_learner_pipelined_commit_accounting(tmp_path, rng):
    """The stager+commit path must leave the learner in the identical
    accounting state the synchronous path produces: env_steps, ring
    pointer (mirroring the compiled pointer), buffer steps, and the
    ingestion counters the observability record reads."""
    cfg, learner = make_learner(tmp_path, **{
        "replay.ingest_batch_blocks": 4})
    try:
        assert learner._ingest_k == 4
        q = BlockQueue(use_mp=False)
        for blk in fill_learner_blocks(learner, 10, rng):
            q.put(blk)
        assert drain_until(learner, q, 10) == 10
        spec = learner.spec
        assert learner.env_steps == 10 * spec.block_length
        assert learner.ring.ptr == 10 % spec.num_blocks
        assert int(learner.replay_state.block_ptr) == learner.ring.ptr
        assert learner.ring.buffer_steps == 10 * spec.block_length
        assert learner.metrics.ingest_blocks_total == 10
        assert learner.ready
        learner.step()            # the committed ring must be trainable
        learner.flush_metrics()
        assert learner.training_steps == 1
        record = learner.metrics.log(1.0)
        assert record["ingest_blocks_per_drain"] is not None
        assert record["ingest_drain_latency_ms"] is not None
        assert record["ingest_queue_depth"] == 0
    finally:
        learner.stop_background()


def test_learner_pipelined_matches_legacy_replay_state(tmp_path, rng):
    """Same blocks through the pipelined path and the legacy path yield
    byte-identical replay state."""
    cfg_p, pipelined = make_learner(tmp_path / "p", **{
        "replay.ingest_batch_blocks": 3})
    cfg_l, legacy = make_learner(tmp_path / "l", **{
        "replay.ingest_batch_blocks": 1})
    try:
        blocks = fill_learner_blocks(legacy, 7, rng)
        qp, ql = BlockQueue(use_mp=False), BlockQueue(use_mp=False)
        for blk in blocks:
            qp.put(blk)
            ql.put(blk)
        assert drain_until(pipelined, qp, 7) == 7
        assert legacy.drain(ql) == 7
        assert_trees_equal(pipelined.replay_state, legacy.replay_state)
        assert pipelined.env_steps == legacy.env_steps
    finally:
        pipelined.stop_background()
        legacy.stop_background()


def test_learner_pipelined_sharded_matches_legacy(tmp_path, rng):
    """dp-sharded pipelined ingestion: the stager's AOT-compiled
    make_sharded_replay_add_many commits must leave the identical sharded
    replay state as the legacy per-block round-robin, and never compile on
    the commit path (the cache holds every pow2 bucket after startup)."""
    cfg_p, pipelined = make_learner(tmp_path / "p", **{
        "mesh.dp": 2, "replay.ingest_batch_blocks": 3})
    cfg_l, legacy = make_learner(tmp_path / "l", **{
        "mesh.dp": 2, "replay.ingest_batch_blocks": 1})
    try:
        blocks = fill_learner_blocks(legacy, 7, rng)
        qp, ql = BlockQueue(use_mp=False), BlockQueue(use_mp=False)
        for blk in blocks:
            qp.put(blk)
            ql.put(blk)
        assert drain_until(pipelined, qp, 7, timeout=60.0) == 7
        assert legacy.drain(ql) == 7
        assert_trees_equal(pipelined.replay_state, legacy.replay_state)
        assert pipelined._next_shard == legacy._next_shard
        assert {1, 2} <= set(pipelined._add_many_cache)  # pow2 precompile
    finally:
        pipelined.stop_background()
        legacy.stop_background()


def test_rate_limiter_backpressures_pipelined_stager(tmp_path, rng):
    """With the collect:learn limiter engaged and no training running, the
    stager must stop pulling from the feeder (blocks stay queued =
    actor back-pressure) once committed + staged steps reach the budget —
    within one staging batch of the synchronous trigger point."""
    cfg, learner = make_learner(tmp_path, **{
        "replay.ingest_batch_blocks": 2,
        "replay.learning_starts": 100,
        "replay.max_env_steps_per_train_step": 20.0})
    try:
        q = BlockQueue(use_mp=False)
        blocks = fill_learner_blocks(learner, 12, rng)
        for blk in blocks:
            q.put(blk)
        # budget with zero training steps: learning_starts + ratio * 1
        # = 120 steps = 6 blocks; the pipeline may hold up to 2 staged
        # batches (4 blocks) beyond the committed ones
        drain_until(learner, q, 12, timeout=3.0)
        time.sleep(0.5)      # give the stager time to overrun, if it would
        learner.drain(q)
        committed = learner.env_steps // learner.spec.block_length
        with learner._staged_lock:
            staged = learner._staged_env_steps // learner.spec.block_length
        assert committed >= 6                  # reached the budget
        assert committed + staged <= 6 + 2 * 2  # bounded overrun
        assert learner.ingestion_paused
        # pause time is being accounted for the observability record
        learner.metrics.on_ingest_pause(0.0)   # flush helper is thread-side
    finally:
        learner.stop_background()


def test_drain_burst_knob_shared_by_default(tmp_path, rng):
    """Legacy drain's default burst is replay.drain_max_blocks (the one
    knob the training loop AND the warm-up loop inherit), overridable per
    call."""
    cfg, learner = make_learner(tmp_path, **{
        "replay.ingest_batch_blocks": 1, "replay.drain_max_blocks": 3})
    try:
        q = BlockQueue(use_mp=False)
        for blk in fill_learner_blocks(learner, 8, rng):
            q.put(blk)
        assert learner.drain(q) == 3          # cfg default
        assert learner.drain(q, max_items=4) == 4   # explicit override
        assert learner.drain(q) == 1
    finally:
        learner.stop_background()


def test_host_sample_vectorized_gather_matches_loop(rng):
    """The batched fancy-index gather must return exactly what the removed
    per-row python slice loop returned."""
    spec = make_spec()
    host = HostReplay(spec, seed=0, use_native=False)
    for blk in _fill_blocks(spec, 3, rng):
        host.add(blk)
    batch, _ = host.sample()
    idx = np.asarray(batch.idxes, np.int64)
    b, s = idx // spec.seqs_per_block, idx % spec.seqs_per_block
    start = host.seq_start[b, s] - host.burn_in_steps[b, s]
    obs_len = spec.seq_window + spec.frame_stack - 1
    for i in range(spec.batch_size):
        t0 = int(start[i])
        np.testing.assert_array_equal(
            batch.obs[i], host.obs[b[i], t0:t0 + obs_len])
        np.testing.assert_array_equal(
            batch.last_action[i],
            host.last_action[b[i], t0:t0 + spec.seq_window])
    assert batch.obs.dtype == np.uint8
    assert batch.last_action.dtype == np.int32


def test_config_ingest_knob_validation():
    cfg = Config().replace(**{"env.game_name": "Fake"})
    assert cfg.replay.resolved_ingest_batch_blocks() == 1   # auto on CPU
    assert cfg.replay.drain_max_blocks == 32
    with pytest.raises(ValueError, match="ingest_batch_blocks"):
        cfg.replace(**{"replay.ingest_batch_blocks": 0})
    with pytest.raises(ValueError, match="must be <= num_blocks"):
        cfg.replace(**{"replay.ingest_batch_blocks": cfg.num_blocks + 1})
    with pytest.raises(ValueError, match="drain_max_blocks"):
        cfg.replace(**{"replay.drain_max_blocks": 0})
    # explicit K round-trips the config serialization
    k = cfg.replace(**{"replay.ingest_batch_blocks": 4})
    assert Config.from_json(k.to_json()).replay.ingest_batch_blocks == 4


def test_metrics_ingest_record_resets_per_interval(tmp_path):
    m = TrainMetrics(0, str(tmp_path))
    m.on_ingest_drain(4, 0.002)
    m.on_ingest_drain(2, 0.004)
    m.on_ingest_pause(0.5)
    m.set_ingest_queue_depth(1)
    rec = m.log(1.0)
    assert rec["ingest_drains"] == 2
    assert rec["ingest_blocks_per_drain"] == 3.0
    assert rec["ingest_drain_latency_ms"] == 3.0
    assert rec["ingest_pause_time"] == 0.5
    assert rec["ingest_queue_depth"] == 1
    assert rec["ingest_blocks_total"] == 6
    rec2 = m.log(1.0)    # interval accumulators reset, cumulative stays
    assert rec2["ingest_drains"] == 0
    assert rec2["ingest_blocks_per_drain"] is None
    assert rec2["ingest_pause_time"] == 0.0
    assert rec2["ingest_blocks_total"] == 6
