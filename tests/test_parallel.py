"""Multi-chip tests on the virtual 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8, SURVEY §4): the dp-sharded fused step
must compile, keep params replicated bit-identically, and agree with
single-chip training given equivalent data.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import NetworkConfig, OptimConfig
from r2d2_tpu.learner import create_train_state
from r2d2_tpu.models import init_network
from r2d2_tpu.parallel import (
    make_mesh,
    make_sharded_learner_step,
    sharded_buffer_steps,
    sharded_replay_init,
)
from r2d2_tpu.parallel.sharded import make_sharded_replay_add
from r2d2_tpu.config import MeshConfig

from tests.test_replay import A, _fill_blocks, make_spec
from tests.test_train_step import OPT, _net


@pytest.fixture(scope="module")
def mesh4():
    assert len(jax.devices()) >= 4, "conftest should provide 8 CPU devices"
    return make_mesh(MeshConfig(dp=4))


def test_mesh_shapes(mesh4):
    assert mesh4.shape == {"dp": 4, "mp": 1}


@pytest.mark.slow
def test_sharded_step_replicated_params(mesh4, rng):
    """One sharded step: params stay bit-identical on every chip (the pmean'd
    update is the determinism contract from SURVEY §4)."""
    spec = make_spec(batch_size=8)
    net, _ = _net(spec)
    ts = create_train_state(jax.random.PRNGKey(1), net, OPT)
    rs = sharded_replay_init(spec, mesh4)

    add = make_sharded_replay_add(spec, mesh4)
    blocks = _fill_blocks(spec, 8, rng)
    for i, blk in enumerate(blocks):
        rs = add(rs, blk, i % 4)
    assert sharded_buffer_steps(rs) == 8 * spec.block_length
    # round-robin placed two blocks per shard
    per_shard = np.asarray(rs.learning_steps).sum(axis=(1, 2))
    np.testing.assert_array_equal(per_shard, [2 * spec.block_length] * 4)

    step = make_sharded_learner_step(net, spec, OPT, use_double=True, mesh=mesh4)
    ts2, rs2, m = step(ts, rs)
    assert np.isfinite(float(m["loss"]))
    assert int(ts2.step) == 1

    # per-device param copies must be bitwise identical
    some_leaf = jax.tree_util.tree_leaves(ts2.params)[0]
    shards = [np.asarray(s.data) for s in some_leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


@pytest.mark.slow
def test_sharded_matches_single_chip_exactly(mesh4, rng):
    """A dp=1 mesh must reproduce the single-chip fused step exactly — same
    sample stream (both fold_in shard index 0), same updates, same metrics.
    This pins the sharded path to the golden single-chip semantics."""
    from r2d2_tpu.learner import make_learner_step

    spec = make_spec(batch_size=8)
    net, _ = _net(spec)
    mesh1 = make_mesh(MeshConfig(dp=1))

    blocks = _fill_blocks(spec, 3, rng)

    # single chip
    from r2d2_tpu.replay import replay_add, replay_init
    ts_a = create_train_state(jax.random.PRNGKey(7), net, OPT)
    rs_a = replay_init(spec)
    for blk in blocks:
        rs_a = replay_add(spec, rs_a, blk)
    step_a = make_learner_step(net, spec, OPT, use_double=False)

    # dp=1 sharded
    ts_b = create_train_state(jax.random.PRNGKey(7), net, OPT)
    rs_b = sharded_replay_init(spec, mesh1)
    add = make_sharded_replay_add(spec, mesh1)
    for blk in blocks:
        rs_b = add(rs_b, blk, 0)
    step_b = make_sharded_learner_step(net, spec, OPT, use_double=False,
                                       mesh=mesh1)

    for _ in range(3):
        ts_a, rs_a, m_a = step_a(ts_a, rs_a)
        ts_b, rs_b, m_b = step_b(ts_b, rs_b)
        np.testing.assert_allclose(float(m_a["loss"]), float(m_b["loss"]),
                                   rtol=1e-5)

    leaves_a = jax.tree_util.tree_leaves(ts_a.params)
    leaves_b = jax.tree_util.tree_leaves(ts_b.params)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(np.asarray(rs_a.tree),
                               np.asarray(rs_b.tree)[0], rtol=1e-5)


@pytest.mark.slow
def test_device_replay_mp_matches_manual_dp(rng):
    """VERDICT r3 #4: mesh.mp>1 under the fused device-replay step (the
    GSPMD formulation) must match the manual shard_map dp path — same RNG
    chain (fold_in by shard index), same grad mean, same target schedule —
    while genuinely feature-sharding the wide params over mp. Checked
    dp=2 x mp=2 vs dp=2 x mp=1 over multiple steps."""
    from r2d2_tpu.parallel.tensor_parallel import state_shardings

    spec = make_spec(batch_size=8)
    net, _ = _net(spec)
    blocks = _fill_blocks(spec, 4, rng)

    def run(mesh, mp_shard, steps=3):
        ts = create_train_state(jax.random.PRNGKey(7), net, OPT)
        if mp_shard:
            ts = jax.device_put(
                ts, state_shardings(ts, mesh, min_shard_width=8))
        rs = sharded_replay_init(spec, mesh)
        add = make_sharded_replay_add(spec, mesh)
        for i, blk in enumerate(blocks):
            rs = add(rs, blk, i % mesh.shape["dp"])
        step = make_sharded_learner_step(net, spec, OPT, use_double=True,
                                         mesh=mesh)
        losses = []
        for _ in range(steps):
            ts, rs, m = step(ts, rs)
            losses.append(float(m["loss"]))
        return ts, rs, losses

    ts_a, rs_a, losses_a = run(make_mesh(MeshConfig(dp=2, mp=1)), False)
    ts_b, rs_b, losses_b = run(make_mesh(MeshConfig(dp=2, mp=2)), True)

    np.testing.assert_allclose(losses_a, losses_b, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ts_a.params),
                    jax.tree_util.tree_leaves(ts_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    # priorities wrote back identically into the dp-sharded trees
    np.testing.assert_allclose(np.asarray(rs_a.tree), np.asarray(rs_b.tree),
                               rtol=1e-5)
    # wide params are genuinely sharded across mp
    sharded = [l for l in jax.tree_util.tree_leaves(ts_b.params)
               if l.ndim >= 1
               and l.addressable_shards[0].data.shape[-1] != l.shape[-1]]
    assert sharded, "no param leaf sharded over mp"


@pytest.mark.slow
def test_sharded_multi_step_matches_single_steps(mesh4, rng):
    """K scanned sharded steps per dispatch == K single-step dispatches:
    same RNG chain, same params, same trees, metrics stacked (K,). This is
    the dp-mesh analog of the single-chip steps_per_dispatch equivalence."""
    spec = make_spec(batch_size=8)
    net, _ = _net(spec)
    blocks = _fill_blocks(spec, 8, rng)
    add = make_sharded_replay_add(spec, mesh4)

    def prep():
        ts = create_train_state(jax.random.PRNGKey(3), net, OPT)
        rs = sharded_replay_init(spec, mesh4)
        for i, blk in enumerate(blocks):
            rs = add(rs, blk, i % 4)
        return ts, rs

    k = 3
    step1 = make_sharded_learner_step(net, spec, OPT, use_double=True,
                                      mesh=mesh4)
    stepk = make_sharded_learner_step(net, spec, OPT, use_double=True,
                                      mesh=mesh4, steps_per_dispatch=k)

    ts_a, rs_a = prep()
    losses_a = []
    for _ in range(k):
        ts_a, rs_a, m = step1(ts_a, rs_a)
        losses_a.append(float(m["loss"]))

    ts_b, rs_b = prep()
    ts_b, rs_b, m_b = stepk(ts_b, rs_b)

    assert np.asarray(m_b["loss"]).shape == (k,)
    np.testing.assert_allclose(losses_a, np.asarray(m_b["loss"]), rtol=1e-5)
    assert int(ts_b.step) == k
    for a, b in zip(jax.tree_util.tree_leaves(ts_a.params),
                    jax.tree_util.tree_leaves(ts_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(np.asarray(rs_a.tree), np.asarray(rs_b.tree),
                               rtol=1e-5)


@pytest.mark.slow
def test_tensor_parallel_matches_unsharded(rng):
    """TP over the 'mp' axis (parallel/tensor_parallel.py): the SAME train
    step jitted under feature-sharded params must (a) actually shard the
    wide kernels across mp devices and (b) reproduce the unsharded step's
    training trajectory (GSPMD may reorder reductions -> allclose, not
    bit-equal). dp=2 x mp=2 exercises both axes together."""
    from r2d2_tpu.learner.train_step import make_external_batch_step
    from r2d2_tpu.parallel.tensor_parallel import (
        leaf_partition_spec, make_tp_external_batch_step)
    from r2d2_tpu.replay import replay_add, replay_init
    from r2d2_tpu.replay.device_replay import replay_sample

    spec = make_spec(batch_size=8)
    net, _ = _net(spec)
    mesh = make_mesh(MeshConfig(dp=2, mp=2))
    # test-scale net (4H=32): lower the rule's min shard width so the LSTM
    # projections actually shard at mp=2
    msw = 8

    rs = replay_init(spec)
    for blk in _fill_blocks(spec, 3, rng):
        rs = replay_add(spec, rs, blk)
    batches = [replay_sample(spec, rs, jax.random.PRNGKey(s))
               for s in range(3)]

    step_a = make_external_batch_step(net, spec, OPT, use_double=True)
    ts_a = create_train_state(jax.random.PRNGKey(5), net, OPT)
    losses_a = []
    for b in batches:
        ts_a, m = step_a(ts_a, b)
        losses_a.append(float(m["loss"]))

    step_b, place_state, place_batch = make_tp_external_batch_step(
        net, spec, OPT, use_double=True, mesh=mesh, min_shard_width=msw)
    ts_b = place_state(create_train_state(jax.random.PRNGKey(5), net, OPT))

    # the wide kernels must REALLY be split over mp: a sharded leaf's
    # addressable shards have half the feature dim each
    from jax.sharding import PartitionSpec as P
    wide = [leaf for leaf in jax.tree_util.tree_leaves(ts_b.params)
            if leaf.ndim >= 1
            and leaf_partition_spec(leaf.shape, 2, msw) != P()]
    assert wide, "no param leaf was sharded over mp"
    sharded_leaf = max(wide, key=lambda l: l.size)
    shard_shape = sharded_leaf.addressable_shards[0].data.shape
    assert shard_shape[-1] == sharded_leaf.shape[-1] // 2

    losses_b = []
    for b in batches:
        ts_b, m = step_b(ts_b, place_batch(b))
        losses_b.append(float(m["loss"]))

    np.testing.assert_allclose(losses_a, losses_b, rtol=2e-4)
    for a, b in zip(jax.tree_util.tree_leaves(ts_a.params),
                    jax.tree_util.tree_leaves(ts_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_sequence_parallel_lstm_exact(rng):
    """The pipelined time-sharded LSTM (parallel/sequence_parallel.py) must
    be BIT-EXACT vs the in-chip scan: same cell function, same step order —
    chunking the window over 'sp' and microbatching the batch changes the
    schedule, never the math. 4 stages x 4 microbatches over T=12, B=8."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from r2d2_tpu.models.network import HoistedLSTM
    from r2d2_tpu.parallel.sequence_parallel import make_sp_lstm

    B, T, D, H = 8, 12, 10, 8
    key = jax.random.PRNGKey(11)
    xs = jax.random.normal(key, (B, T, D))
    c0 = jax.random.normal(jax.random.fold_in(key, 1), (B, H))
    h0 = jax.random.normal(jax.random.fold_in(key, 2), (B, H))

    lstm = HoistedLSTM(features=H)
    params = lstm.init(jax.random.PRNGKey(3), (c0, h0), xs)
    (c_ref, h_ref), out_ref = lstm.apply(params, (c0, h0), xs)

    p = params["params"]
    x_proj = xs @ p["input_proj"]["kernel"]          # the hoisted matmul
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    sp = make_sp_lstm(mesh, microbatches=4)
    out_sp, final = sp(p["recurrent_kernel"], p["bias"], x_proj,
                       jnp.stack([c0, h0]))

    np.testing.assert_array_equal(np.asarray(out_sp), np.asarray(out_ref))
    np.testing.assert_array_equal(np.asarray(final[0]), np.asarray(c_ref))
    np.testing.assert_array_equal(np.asarray(final[1]), np.asarray(h_ref))

    # divisibility contract is validated loudly
    with pytest.raises(ValueError, match="not divisible"):
        sp(p["recurrent_kernel"], p["bias"], x_proj[:, :10],
           jnp.stack([c0, h0]))


@pytest.mark.slow
def test_eight_device_full_mesh_compiles(rng):
    """The full 8-device dryrun the driver will exercise via
    __graft_entry__.dryrun_multichip."""
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_multihost_loopback_dryrun():
    """Two separate jax.distributed controller processes over a loopback
    coordinator run one fused dp-sharded step on a global mesh spanning both
    (SURVEY §5.8 DCN bring-up — multi-controller SPMD, the path a real
    multi-host pod takes)."""
    from r2d2_tpu.parallel.multihost_dryrun import launch
    launch(num_processes=2, devices_per_process=4, timeout=280.0)


def test_local_actor_fleet_supervision():
    """The multihost trainer's per-host supervision (LocalActorFleet):
    restarts dead threads with a logged count, never lets a failing spawn
    escape into the lockstep loop (it would abandon peers mid-collective),
    and honors the off-switch and the stop event."""
    import threading

    from r2d2_tpu.parallel.multihost import LocalActorFleet

    def make_spawn(fail_on=()):
        def spawn(i):
            if i in fail_on:
                raise RuntimeError("env creation failed")
            t = threading.Thread(target=lambda: None)
            t.start()
            return t
        return spawn

    stop = threading.Event()
    fleet = LocalActorFleet(make_spawn(), 3, restart_dead=True, stop=stop)
    for t in fleet.threads:
        t.join()
    assert fleet.supervise() == 3           # all finished -> all restarted

    # a failing respawn is swallowed (logged), others still restart
    fleet._spawn = make_spawn(fail_on={1})
    for t in fleet.threads:
        t.join()
    assert fleet.supervise() == 2

    # stop set -> no restarts; off-switch -> no restarts
    stop.set()
    assert fleet.supervise() == 0
    stop2 = threading.Event()
    fleet2 = LocalActorFleet(make_spawn(), 1, restart_dead=False, stop=stop2)
    fleet2.threads[0].join()
    assert fleet2.supervise() == 0
    fleet.join(timeout=1.0)
    fleet2.join(timeout=1.0)


@pytest.mark.slow
def test_multihost_lockstep_training(tmp_path):
    """The full rank-aware trainer (parallel/multihost.py): two controller
    processes, each owning its own actors and feeding only its local replay
    shards, train in lockstep to the step budget — per-worker asserts check
    replicated params stay bit-identical across each process's shards, and
    rank 0's checkpoints must be restorable from an ordinary single-process
    job afterwards."""
    from r2d2_tpu.parallel.multihost import launch_demo
    from r2d2_tpu.runtime.checkpoint import list_checkpoints, restore_checkpoint

    save_dir = str(tmp_path / "mh")
    launch_demo(num_processes=2, devices_per_process=2, save_dir=save_dir,
                max_steps=8, timeout=280.0)
    ckpts = list_checkpoints(save_dir, "Fake", player=0)
    assert ckpts, "rank 0 wrote no checkpoints"
    ck = restore_checkpoint(ckpts[-1][1])
    assert int(ck["step"]) == 8
    assert int(ck["env_steps"]) > 0
    # rank 0's metrics stream exists with the reference-format log
    assert (tmp_path / "mh" / "train_player0.log").exists()

    # rank-consistent resume: every controller restores the same checkpoint
    # and the pod continues to the new (cumulative) budget
    launch_demo(num_processes=2, devices_per_process=2, save_dir=save_dir,
                max_steps=12, timeout=280.0, resume=ckpts[-1][1])
    ck2 = restore_checkpoint(list_checkpoints(save_dir, "Fake", 0)[-1][1])
    assert int(ck2["step"]) == 12
    assert int(ck2["env_steps"]) > int(ck["env_steps"])


@pytest.mark.slow
def test_multihost_lockstep_tensor_parallel(tmp_path):
    """Pod-scale tensor parallelism: two controllers over a dp=2 x mp=2
    mesh — GSPMD learner step + GSPMD lockstep ingest, wide params
    genuinely feature-sharded over mp (asserted in-worker), cross-host
    param digests still bit-identical, rank-0 checkpoints restorable."""
    from r2d2_tpu.parallel.multihost import launch_demo
    from r2d2_tpu.runtime.checkpoint import list_checkpoints, restore_checkpoint

    save_dir = str(tmp_path / "mh_tp")
    launch_demo(num_processes=2, devices_per_process=2, save_dir=save_dir,
                max_steps=8, timeout=280.0, mp=2)
    ckpts = list_checkpoints(save_dir, "Fake", player=0)
    assert ckpts, "rank 0 wrote no checkpoints"
    ck = restore_checkpoint(ckpts[-1][1])
    assert int(ck["step"]) == 8
    assert int(ck["env_steps"]) > 0

    # rank-consistent TP resume: every controller restores the same
    # checkpoint, re-shards it over mp, and the pod continues in lockstep
    launch_demo(num_processes=2, devices_per_process=2, save_dir=save_dir,
                max_steps=12, timeout=280.0, mp=2, resume=ckpts[-1][1])
    ck2 = restore_checkpoint(list_checkpoints(save_dir, "Fake", 0)[-1][1])
    assert int(ck2["step"]) == 12
    assert int(ck2["env_steps"]) > int(ck["env_steps"])


@pytest.mark.slow
def test_multihost_lockstep_process_actors(tmp_path):
    """VERDICT r3 #8: the lockstep trainer with SPAWNED-PROCESS actor
    fleets — each controller hosts CPU-pinned actor processes fed through
    the shm-ring/mp queue transport — still trains to budget with
    bit-identical cross-host params (launch_demo's digest check) and
    rank-0 checkpoints."""
    from r2d2_tpu.parallel.multihost import launch_demo
    from r2d2_tpu.runtime.checkpoint import list_checkpoints, restore_checkpoint

    save_dir = str(tmp_path / "mh_proc")
    launch_demo(num_processes=2, devices_per_process=2, save_dir=save_dir,
                max_steps=8, timeout=280.0, actor_mode="process")
    ckpts = list_checkpoints(save_dir, "Fake", player=0)
    assert ckpts, "rank 0 wrote no checkpoints"
    ck = restore_checkpoint(ckpts[-1][1])
    assert int(ck["step"]) == 8
    assert int(ck["env_steps"]) > 0


def test_multiplayer_env_args_wiring():
    """The shared host/join helper (MultiplayerConfig.env_args, ref
    train.py:33-38): player 0 hosts on port(actor_idx), every other player
    joins the same port; disabled = no hosting. The factory threads the
    resolved wiring into the env (the fake records it)."""
    from r2d2_tpu.config import Config, MultiplayerConfig
    from r2d2_tpu.envs.factory import create_env

    mpc = MultiplayerConfig(enabled=True, num_players=3, base_port=7000)
    assert mpc.env_args(0, 2) == dict(is_host=True, port=7002)
    assert mpc.env_args(1, 2) == dict(is_host=False, port=7002)
    assert mpc.env_args(2, 0) == dict(is_host=False, port=7000)
    off = MultiplayerConfig(enabled=False, base_port=7000)
    assert off.env_args(0, 5) == dict(is_host=False, port=7000)

    cfg = Config().replace(**{"env.game_name": "Fake"})
    env = create_env(cfg.env, num_players=3, name="p1a2",
                     **mpc.env_args(1, 2))
    w = env.unwrapped.multiplayer_wiring
    assert w == dict(is_host=False, port=7002, num_players=3, name="p1a2")
    env.close()

    # population bound: a player_id outside the population fails loudly
    with pytest.raises(ValueError, match="player_id"):
        Config().replace(**{"multiplayer.enabled": True,
                            "multiplayer.num_players": 2,
                            "multiplayer.player_id": 2})


@pytest.mark.slow
def test_multiplayer_per_player_jobs_loopback(tmp_path):
    """Multiplayer at pod scale (README): TWO INDEPENDENT multihost jobs —
    one per player — run concurrently, coupled only through the game
    engine's host/join sockets (recorded hermetically by the fake env).
    Player 0's job is itself 2 lockstep controllers x 1 actor
    (digest-verified by launch_demo); player 1's job is a single
    controller x 2 actors — the SAME total fan-out (2), which the
    composition requires: game index = global actor index, so every
    hosted game must have exactly one joiner per other player. Asserts:
    both jobs train to budget, player 0's actors HOST games at
    base_port+global_idx, player 1's actors JOIN the same two ports, and
    the two jobs' logs/checkpoints land under per-player names without
    colliding."""
    from concurrent.futures import ThreadPoolExecutor

    from r2d2_tpu.parallel.multihost import launch_demo
    from r2d2_tpu.runtime.checkpoint import list_checkpoints, restore_checkpoint

    d0 = str(tmp_path / "p0")
    d1 = str(tmp_path / "p1")
    with ThreadPoolExecutor(2) as ex:
        f0 = ex.submit(launch_demo, 2, 2, d0, 8, 420.0, "", "thread", 1,
                       0, 2, 1)   # player 0: two controllers x 1 actor
        f1 = ex.submit(launch_demo, 1, 2, d1, 8, 420.0, "", "thread", 1,
                       1, 2, 2)   # player 1: one controller x 2 actors
        dig0, dig1 = f0.result(), f1.result()

    # player 0's actors host; global index = rank * n_local + i drives the
    # game port, so rank 0 hosts game 0 and rank 1 hosts game 1
    base = 5060
    for rank, rec in enumerate(dig0):
        assert rec["player_id"] == 0
        (w,) = rec["actor_wiring"]
        assert w["is_host"] is True and w["port"] == base + rank
        assert w["num_players"] == 2
    # player 1's two actors join games 0 and 1 — one joiner per hosted game
    (rec1,) = dig1
    assert rec1["player_id"] == 1
    ports = [w["port"] for w in rec1["actor_wiring"]]
    assert ports == [base, base + 1]
    assert all(w["is_host"] is False for w in rec1["actor_wiring"])

    # per-player artifacts: player-keyed logs and checkpoints
    import os
    assert os.path.exists(os.path.join(d0, "train_player0.log"))
    assert os.path.exists(os.path.join(d1, "train_player1.log"))
    ck0 = list_checkpoints(d0, "Fake", player=0)
    ck1 = list_checkpoints(d1, "Fake", player=1)
    assert ck0 and ck1
    assert int(restore_checkpoint(ck0[-1][1])["step"]) == 8
    assert int(restore_checkpoint(ck1[-1][1])["step"]) == 8


@pytest.mark.slow
def test_multihost_lockstep_host_replay(tmp_path):
    """Host replay placement under the lockstep trainer (the last
    placement combination that used to raise): per-process CPU HostReplay
    + the consensus psum program + the GSPMD external-batch step, trained
    to budget with bit-identical cross-host params (launch_demo's digest
    check) and rank-0 checkpoints; plus the same under a dp x mp mesh
    (params genuinely feature-sharded, asserted in-worker)."""
    from r2d2_tpu.parallel.multihost import launch_demo
    from r2d2_tpu.runtime.checkpoint import list_checkpoints, restore_checkpoint

    save_dir = str(tmp_path / "mh_host")
    launch_demo(num_processes=2, devices_per_process=2, save_dir=save_dir,
                max_steps=8, timeout=280.0, placement="host")
    ckpts = list_checkpoints(save_dir, "Fake", player=0)
    assert ckpts, "rank 0 wrote no checkpoints"
    ck = restore_checkpoint(ckpts[-1][1])
    assert int(ck["step"]) == 8
    assert int(ck["env_steps"]) > 0

    launch_demo(num_processes=2, devices_per_process=2,
                save_dir=str(tmp_path / "mh_host_tp"),
                max_steps=8, timeout=280.0, placement="host", mp=2)


@pytest.mark.slow
def test_multihost_chaos_process_actor_kill_recovers(tmp_path, monkeypatch):
    """Chaos test (VERDICT r4 #8): SIGKILL a process-mode actor child
    mid-run under the lockstep multihost trainer with the shm block ring.
    The per-host fleet must detect the corpse, reclaim its ring slot
    (RingRecoveryScheduler), and respawn onto the LIVE ring — and training
    must still finish with bit-identical cross-host params (digest check
    inside launch_demo)."""
    import glob
    import json
    import os

    from r2d2_tpu.parallel.multihost import launch_demo

    monkeypatch.setenv("R2D2_MH_CHAOS_KILL_ACTOR", "5")
    save_dir = str(tmp_path / "mh_chaos")
    launch_demo(num_processes=2, devices_per_process=2, save_dir=save_dir,
                max_steps=8, timeout=280.0, actor_mode="process")
    markers = glob.glob(os.path.join(save_dir, "chaos_kill_r*.json"))
    assert len(markers) == 2, markers          # every rank ran the chaos
    for m in sorted(markers):
        rec = json.loads(open(m).read())
        assert rec["victim_exitcode"] not in (0, None)   # SIGKILLed corpse
        assert rec["restarted"] >= 1, rec      # supervision respawned it
