"""System-level learnability proof (VERDICT r2 #2).

The reference's only acceptance test is the Atari Boxing learning curve
(/root/reference/README.md:38-40) — unreproducible here while the game
engines cannot be installed. This is its hermetic stand-in: train the real
policy → LocalBuffer → replay → fused-learner pipeline on the
deterministic FakeR2D2Env (the target action is visible in every frame, so
the oracle return is episode_len=120 and a uniform-random policy expects
episode_len/action_dim=20) and assert the greedy policy's evaluation
return lands a large multiple above random.

Collection and training run in a DETERMINISTIC synchronous loop — exactly
``max_env_steps_per_train_step`` env steps per learner step, no threads —
because the result must be a red/green CI signal: with free-running actor
threads the collect:learn interleaving (and so the learning outcome)
swings with host scheduling — measured round 3, the same config scored
returns anywhere in 25-86 across identical invocations. The threaded and
process orchestrations are covered by the e2e tests in test_runtime.py;
this test pins the *algorithm*. It executes in a subprocess on a plain
single-device CPU backend (the suite's 8-virtual-device pin triples the
wall time on one core for no extra coverage).

Budget calibration (round 3, single CPU core): 4000 learner steps at
gamma=0.99, collect ratio 2.0, trains in ~2 minutes; the run is bit-
reproducible given the seeds. gamma=0.99 over the default 0.997 shortens
the credit-assignment horizon to match the env's reactive reward.
"""

import json
import os
import subprocess
import sys

try:                              # the __main__ subprocess has no pytest dep
    import pytest
    pytestmark = pytest.mark.slow     # ~2-4 min subprocess (VERDICT r3 #5)
except ImportError:               # pragma: no cover
    pass

RANDOM_EXPECTATION = 120 / 6      # episode_len / action_dim
ORACLE = 120.0                    # +1 every step
TRAIN_STEPS = 4000
COLLECT_EPS = 0.4                 # behavior-policy exploration
EVAL_SEEDS = (123, 456, 789)


def learn_config(save_dir: str):
    from r2d2_tpu.config import Config
    return Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 32, "env.frame_width": 32, "env.frame_stack": 2,
        "network.hidden_dim": 32, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 4000, "replay.block_length": 20,
        "replay.batch_size": 16, "replay.learning_starts": 500,
        # pin the collect:learn ratio so the result does not depend on how
        # the host schedules actor threads vs the learner (measured round
        # 3: unthrottled, the same config swings 25-86 return depending on
        # scheduling balance alone)
        "replay.max_env_steps_per_train_step": 2.0,
        "actor.num_actors": 2, "actor.actor_update_interval": 50,
        "optim.lr": 1e-3, "optim.gamma": 0.99,
        "runtime.save_dir": save_dir, "runtime.save_interval": 0,
        "runtime.weight_publish_interval": 5,
        "runtime.log_interval": 30.0,
    })


def _train_and_eval(save_dir: str) -> dict:
    # the shared deterministic loop (r2d2_tpu/tools/sync_train.py) — also
    # the genetic search's sync fitness mode, so the acceptance proof and
    # genome selection run the identical algorithm
    from r2d2_tpu.tools.sync_train import greedy_return, sync_train

    cfg = learn_config(save_dir)
    net, learner = sync_train(cfg, TRAIN_STEPS, COLLECT_EPS, seed=0)
    returns = [greedy_return(net, learner.train_state.params, cfg.env, seed)
               for seed in EVAL_SEEDS]
    return {"training_steps": int(learner.training_steps), "returns": returns}


def test_full_system_improves_policy(tmp_path):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=1100)
    assert proc.returncode == 0, proc.stderr[-4000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["training_steps"] >= TRAIN_STEPS

    returns = result["returns"]
    mean_return = sum(returns) / len(returns)
    # every seed clears 2x random; the mean clears 3x
    assert min(returns) >= 2.0 * RANDOM_EXPECTATION, returns
    assert mean_return >= 3.0 * RANDOM_EXPECTATION, returns
    assert mean_return <= ORACLE


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # Route the JAX_PLATFORMS=cpu pin through jax.config BEFORE any backend
    # discovery: with a wedged remote-TPU tunnel the env var alone does not
    # stop the accelerator plugin from hanging discovery.
    from r2d2_tpu.utils.platform import pin_platform
    pin_platform()
    print(json.dumps(_train_and_eval(sys.argv[1])))
