"""System-level learnability proof (VERDICT r2 #2).

The reference's only acceptance test is the Atari Boxing learning curve
(/root/reference/README.md:38-40) — unreproducible here while the game
engines cannot be installed. This is its hermetic stand-in: train the full
actor→replay→learner loop on the deterministic FakeR2D2Env (the target
action is visible in every frame, so the oracle return is episode_len=120
and a uniform-random policy expects episode_len/action_dim=20) and assert
the greedy policy's evaluation return lands a large multiple above random.

The training run executes in a subprocess on a plain single-device CPU
backend: under the suite's 8-virtual-device pin (conftest.py) the same
budget takes ~3x the wall time on one physical core for no extra coverage —
the virtual mesh matters for the sharding tests, not this one.

Budget calibration (round 3, single CPU core): 2400 learner steps at
gamma=0.99 trains in ~2 minutes and reaches returns of 79-86 across seeds
(~4x random); the 3x assertion leaves margin. gamma=0.99 over the default
0.997 shortens the credit-assignment horizon to match the env's reactive
reward — with 0.997 the same budget only reaches ~2.8x.
"""

import json
import os
import subprocess
import sys

RANDOM_EXPECTATION = 120 / 6      # episode_len / action_dim
ORACLE = 120.0                    # +1 every step
TRAIN_STEPS = 2400
EVAL_SEEDS = (123, 456, 789)


def learn_config(save_dir: str):
    from r2d2_tpu.config import Config
    return Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 32, "env.frame_width": 32, "env.frame_stack": 2,
        "network.hidden_dim": 32, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 4000, "replay.block_length": 20,
        "replay.batch_size": 16, "replay.learning_starts": 500,
        "actor.num_actors": 2, "actor.actor_update_interval": 50,
        "optim.lr": 1e-3, "optim.gamma": 0.99,
        "runtime.save_dir": save_dir, "runtime.save_interval": 0,
        "runtime.steps_per_dispatch": 8,
        "runtime.weight_publish_interval": 5,
        "runtime.log_interval": 30.0,
    })


def greedy_return(net, params, env_cfg, seed: int) -> float:
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.envs.factory import create_env
    env = create_env(env_cfg, seed=seed)
    policy = ActorPolicy(net, params, epsilon=0.0, seed=seed)
    obs = env.reset()
    policy.observe_reset(obs)
    total, done = 0.0, False
    while not done:
        action, _, _ = policy.act()
        obs, reward, done, _ = env.step(action)
        policy.observe(obs, action)
        total += reward
    env.close()
    return total


def _train_and_eval(save_dir: str) -> dict:
    from r2d2_tpu.runtime.orchestrator import train
    cfg = learn_config(save_dir)
    stacks = train(cfg, max_training_steps=TRAIN_STEPS, max_seconds=900,
                   actor_mode="thread")
    learner = stacks[0].learner
    returns = [greedy_return(stacks[0].net, learner.train_state.params,
                             cfg.env, seed) for seed in EVAL_SEEDS]
    return {"training_steps": int(learner.training_steps), "returns": returns}


def test_full_system_improves_policy(tmp_path):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=1100)
    assert proc.returncode == 0, proc.stderr[-4000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["training_steps"] >= TRAIN_STEPS

    returns = result["returns"]
    mean_return = sum(returns) / len(returns)
    # every seed clears 2x random; the mean clears 3x
    assert min(returns) >= 2.0 * RANDOM_EXPECTATION, returns
    assert mean_return >= 3.0 * RANDOM_EXPECTATION, returns
    assert mean_return <= ORACLE


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(json.dumps(_train_and_eval(sys.argv[1])))
