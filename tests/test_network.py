"""Network semantics tests (SURVEY.md §4: golden-value + invariant checks).

Verifies the static-shape unroll reproduces the reference's sequence
semantics (/root/reference/model.py:48-157) without pack/pad:
  * step-by-step unroll == whole-sequence unroll (causality);
  * dueling identity q = v + a - mean(a) ⇒ mean-advantage invariance;
  * gather-index math matches a naive ragged python reference;
  * padded suffix steps never affect gathered valid outputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import NetworkConfig
from r2d2_tpu.models import init_network, initial_hidden
from r2d2_tpu.ops.indexing import (
    frame_stack_indices,
    learning_step_mask,
    online_q_positions,
    target_q_positions,
)

A = 6


@pytest.fixture(scope="module")
def small_net():
    cfg = NetworkConfig(hidden_dim=32, cnn_out_dim=64)
    spec, params = init_network(
        jax.random.PRNGKey(0), A, cfg, frame_stack=2, frame_height=36, frame_width=36
    )
    return spec, params


def _rand_inputs(key, batch, seq, hw=36, stack=2):
    k1, k2 = jax.random.split(key)
    obs = jax.random.uniform(k1, (batch, seq, hw, hw, stack))
    la = jax.nn.one_hot(
        jax.random.randint(k2, (batch, seq), 0, A), A, dtype=jnp.float32
    )
    return obs, la


def test_unroll_matches_stepwise(small_net):
    """T-step unroll == T single steps threading hidden state: the actor's
    `step` and the learner's sequence pass are the same program."""
    spec, params = small_net
    obs, la = _rand_inputs(jax.random.PRNGKey(1), 2, 5)
    hidden = initial_hidden(2, spec.config.hidden_dim)

    q_full, h_full = spec.apply(params, obs, la, hidden)

    h = hidden
    qs = []
    for t in range(5):
        q_t, h = spec.apply(params, obs[:, t : t + 1], la[:, t : t + 1], h)
        qs.append(q_t[:, 0])
    q_step = jnp.stack(qs, axis=1)

    np.testing.assert_allclose(np.asarray(q_full), np.asarray(q_step), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_full), np.asarray(h), atol=1e-5)


def test_dual_sequence_q_matches_two_applies(small_net):
    """The fused double-DQN unroll (one scan interleaving both recurrent
    chains — models/network.py dual_sequence_q) must match two separate
    net.apply calls EXACTLY: the per-chain op sequence is unchanged, only
    the loop structure differs."""
    from r2d2_tpu.models.network import dual_sequence_q

    spec, params_a = small_net
    params_b = spec.init(jax.random.PRNGKey(9))       # a distinct target net
    obs, la = _rand_inputs(jax.random.PRNGKey(3), 3, 7)
    hid_a = initial_hidden(3, spec.config.hidden_dim)
    hid_b = jnp.ones_like(hid_a) * 0.1

    q_a_ref, _ = spec.apply(params_a, obs, la, hid_a)
    q_b_ref, _ = spec.apply(params_b, obs, la, hid_b)
    q_a, q_b = dual_sequence_q(spec, params_a, params_b, obs, la,
                               hid_a, hid_b)
    np.testing.assert_array_equal(np.asarray(q_a), np.asarray(q_a_ref))
    np.testing.assert_array_equal(np.asarray(q_b), np.asarray(q_b_ref))


def test_padding_suffix_does_not_affect_prefix(small_net):
    """Causality: garbage past a sequence's true end leaves the valid prefix
    bit-identical — this is what licenses fixed-window unrolls over ragged
    sequences (replacing ref model.py:103-108 pack_padded_sequence)."""
    spec, params = small_net
    obs, la = _rand_inputs(jax.random.PRNGKey(2), 1, 6)
    hidden = initial_hidden(1, spec.config.hidden_dim)

    q_a, _ = spec.apply(params, obs, la, hidden)

    obs_b = obs.at[:, 4:].set(0.12345)
    la_b = la.at[:, 4:].set(0.0)
    q_b, _ = spec.apply(params, obs_b, la_b, hidden)

    np.testing.assert_allclose(np.asarray(q_a[:, :4]), np.asarray(q_b[:, :4]), atol=1e-6)


def test_dueling_mean_advantage_invariance(small_net):
    """Adding a constant to all advantages must not change Q (the mean
    baseline subtracts it) — the dueling identity of ref model.py:61."""
    spec, params = small_net
    obs, la = _rand_inputs(jax.random.PRNGKey(3), 2, 1)
    hidden = initial_hidden(2, spec.config.hidden_dim)

    q, _ = spec.apply(params, obs, la, hidden)

    shifted = jax.tree_util.tree_map(lambda x: x, params)
    bias_path = shifted["params"]["head"]["adv_out"]["bias"]
    shifted["params"]["head"]["adv_out"]["bias"] = bias_path + 3.7
    q_shift, _ = spec.apply(shifted, obs, la, hidden)

    np.testing.assert_allclose(np.asarray(q), np.asarray(q_shift), atol=1e-4)


@pytest.mark.slow
def test_hoisted_lstm_matches_flax_optimized_cell():
    """HoistedLSTM (input projection outside the scan) must reproduce
    nn.OptimizedLSTMCell exactly given the same weights: map flax's
    per-gate i{comp}/h{comp} params onto the concatenated [i,f,g,o] layout
    and compare the full unrolled outputs and final carry."""
    import flax.linen as nn

    from r2d2_tpu.models.network import HoistedLSTM

    B, T, D, H = 3, 11, 10, 8
    key = jax.random.PRNGKey(42)
    xs = jax.random.normal(key, (B, T, D))
    c0 = jax.random.normal(jax.random.fold_in(key, 1), (B, H))
    h0 = jax.random.normal(jax.random.fold_in(key, 2), (B, H))

    cell = nn.OptimizedLSTMCell(features=H)
    cell_params = cell.init(jax.random.PRNGKey(0), (c0, h0), xs[:, 0])

    scan_cell = nn.scan(
        nn.OptimizedLSTMCell, variable_broadcast="params",
        split_rngs={"params": False}, in_axes=1, out_axes=1)(features=H)
    (c_ref, h_ref), out_ref = scan_cell.apply(cell_params, (c0, h0), xs)

    p = cell_params["params"]
    gates = ["i", "f", "g", "o"]
    hoisted_params = {"params": {
        "input_proj": {"kernel": jnp.concatenate(
            [p[f"i{g}"]["kernel"] for g in gates], axis=1)},
        "recurrent_kernel": jnp.concatenate(
            [p[f"h{g}"]["kernel"] for g in gates], axis=1),
        "bias": jnp.concatenate([p[f"h{g}"]["bias"] for g in gates]),
    }}
    lstm = HoistedLSTM(features=H)
    (c_got, h_got), out_got = lstm.apply(hoisted_params, (c0, h0), xs)

    np.testing.assert_allclose(np.asarray(out_got), np.asarray(out_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(c_got), np.asarray(c_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(h_got), np.asarray(h_ref),
                               rtol=1e-5, atol=1e-6)

    # scan_unroll is a schedule knob, not a math change — bitwise-identical
    # outputs for any unroll factor (incl. one that doesn't divide T=11)
    for unroll in (4, 11):
        (c_u, h_u), out_u = HoistedLSTM(features=H, unroll=unroll).apply(
            hoisted_params, (c0, h0), xs)
        np.testing.assert_array_equal(np.asarray(out_u), np.asarray(out_got))
        np.testing.assert_array_equal(np.asarray(c_u), np.asarray(c_got))
        np.testing.assert_array_equal(np.asarray(h_u), np.asarray(h_got))


def test_non_dueling_head():
    cfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32, use_dueling=False)
    spec, params = init_network(
        jax.random.PRNGKey(0), A, cfg, frame_stack=2, frame_height=36, frame_width=36
    )
    obs, la = _rand_inputs(jax.random.PRNGKey(4), 1, 2)
    q, h = spec.apply(params, obs, la, initial_hidden(1, 16))
    assert q.shape == (1, 2, A)
    assert h.shape == (1, 2, 16)


@pytest.mark.slow
def test_bf16_policy_runs_f32_outputs():
    cfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32, bf16=True)
    spec, params = init_network(
        jax.random.PRNGKey(0), A, cfg, frame_stack=2, frame_height=36, frame_width=36
    )
    obs, la = _rand_inputs(jax.random.PRNGKey(5), 1, 3)
    q, h = spec.apply(params, obs, la, initial_hidden(1, 16))
    assert q.dtype == jnp.float32 and h.dtype == jnp.float32
    # params stay f32 (mixed-precision policy, not a cast-down of weights)
    assert params["params"]["torso"]["Conv_0"]["kernel"].dtype == jnp.float32


# ---------------------------------------------------------------------------
# Gather-index semantics vs naive ragged reference
# ---------------------------------------------------------------------------


def _naive_target_positions(burn_in, learning, forward, fwd_max):
    """Literal transcription of the reference's slice-then-edge-pad loop
    (ref model.py:110-118), producing explicit output positions."""
    seq_len = burn_in + learning + forward
    start = burn_in + fwd_max
    positions = list(range(start, seq_len))
    pad = min(fwd_max - forward, learning)
    positions += [seq_len - 1] * pad
    return positions  # length == learning


@pytest.mark.parametrize(
    "burn_in,learning,forward",
    [
        (4, 10, 5),   # full window mid-episode
        (0, 10, 5),   # episode start, no burn-in yet
        (4, 10, 1),   # near episode end: forward shortened
        (4, 3, 1),    # final ragged tail: slice is empty, all edge-pad
        (2, 1, 1),    # single learning step
    ],
)
def test_target_positions_match_reference_semantics(burn_in, learning, forward):
    fwd_max, learn_max = 5, 10
    pos = target_q_positions(
        jnp.array([burn_in]), jnp.array([learning]), jnp.array([forward]),
        learn_max, fwd_max,
    )[0]
    naive = _naive_target_positions(burn_in, learning, forward, fwd_max)
    assert len(naive) == learning
    np.testing.assert_array_equal(np.asarray(pos[:learning]), np.asarray(naive))


def test_online_positions_and_mask():
    pos = online_q_positions(jnp.array([4, 0]), 10)
    np.testing.assert_array_equal(np.asarray(pos[0]), np.arange(4, 14))
    np.testing.assert_array_equal(np.asarray(pos[1]), np.arange(0, 10))
    mask = learning_step_mask(jnp.array([3, 10]), 10)
    assert mask[0].sum() == 3 and mask[1].sum() == 10
    assert mask[0, 2] == 1.0 and mask[0, 3] == 0.0


@pytest.mark.slow
def test_space_to_depth_is_exact(rng):
    """network.space_to_depth rewrites the first conv as the SAME linear
    map over a 2x2 space-to-depth input: with the standard conv's weights
    re-indexed into the transformed layout, outputs must match to float
    tolerance (same sums, different association order)."""
    from r2d2_tpu.models.network import ConvTorso

    B, H, W, C = 4, 84, 84, 4
    layers = ((32, 8, 4), (64, 4, 2), (64, 3, 1))
    x = jnp.asarray(rng.uniform(0, 1, (B, H, W, C)), jnp.float32)

    std = ConvTorso(64, layers, jnp.float32)
    p_std = std.init(jax.random.PRNGKey(0), x)
    want = std.apply(p_std, x)

    # remap conv1: w2[ph, pw, (dh*2+dw)*C + c, o] = w[2ph+dh, 2pw+dw, c, o]
    w = p_std["params"]["Conv_0"]["kernel"]            # (8, 8, C, 32)
    w2 = (w.reshape(4, 2, 4, 2, C, 32)
           .transpose(0, 2, 1, 3, 4, 5)
           .reshape(4, 4, 4 * C, 32))
    p_s2d = jax.tree_util.tree_map(lambda v: v, p_std)
    p_s2d["params"]["Conv_0"]["kernel"] = w2

    s2d = ConvTorso(64, layers, jnp.float32, space_to_depth=True)
    got = s2d.apply(p_s2d, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    # shape contract: param layout differs, output does not
    init_shapes = jax.tree_util.tree_map(
        lambda v: v.shape, s2d.init(jax.random.PRNGKey(1), x))
    assert init_shapes["params"]["Conv_0"]["kernel"] == (4, 4, 16, 32)
    assert got.shape == want.shape

    # full-network parity through the config knob: a standard-layout
    # checkpoint migrated by convert_params_space_to_depth must produce
    # identical Q-values from the s2d network
    from r2d2_tpu.models.network import (
        NetworkApply, convert_params_space_to_depth)
    base_cfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32)
    net_off = NetworkApply(4, base_cfg, 4, 84, 84)
    params_off = net_off.init(jax.random.PRNGKey(2))
    obs = jnp.asarray(rng.uniform(0, 1, (2, 3, 84, 84, 4)), jnp.float32)
    la = jnp.zeros((2, 3, 4), jnp.float32)
    from r2d2_tpu.models import initial_hidden
    q_off, _ = net_off.apply(params_off, obs, la, initial_hidden(2, 16))

    cfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32, space_to_depth="on")
    net = NetworkApply(4, cfg, 4, 84, 84)
    params_on = convert_params_space_to_depth(params_off, frame_stack=4)
    q_on, _ = net.apply(params_on, obs, la, initial_hidden(2, 16))
    np.testing.assert_allclose(np.asarray(q_on), np.asarray(q_off),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="already converted"):
        convert_params_space_to_depth(params_on, frame_stack=4)
    with pytest.raises(ValueError, match="space_to_depth"):
        NetworkApply(4, cfg, 4, 83, 84)
    # "auto" is rejected: a layout-changing knob must resolve identically
    # on every host (review finding — heterogeneous-backend param trees)
    with pytest.raises(ValueError, match="auto"):
        NetworkApply(4, NetworkConfig(space_to_depth="auto"), 4, 84, 84)


def test_actor_policy_forces_f32_under_bf16(rng):
    """Actors infer on host CPUs where bf16 is emulated: given a learner
    net with the bf16 policy forced on, ActorPolicy must rebuild itself
    f32 — and the learner's params (f32 storage under either policy) must
    drive it unchanged."""
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.models.network import NetworkApply

    cfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32, bf16="on",
                        conv_layers=((8, 4, 2), (16, 3, 1)))
    net = NetworkApply(4, cfg, 2, 20, 20)
    assert net.config.bf16 is True          # forced on, resolved concrete
    params = net.init(jax.random.PRNGKey(0))
    # params are f32 storage even under the bf16 compute policy
    for leaf in jax.tree_util.tree_leaves(params):
        assert leaf.dtype == jnp.float32

    policy = ActorPolicy(net, params, epsilon=0.0, seed=0)
    assert policy.net.config.bf16 is False  # rebuilt f32 for CPU inference
    policy.observe_reset(np.asarray(rng.integers(0, 255, (20, 20)), np.uint8))
    action, q, hidden = policy.act()
    assert 0 <= int(action) < 4
    assert np.asarray(q).dtype == np.float32
    assert np.isfinite(np.asarray(q)).all()


def test_frame_stack_indices():
    idx = frame_stack_indices(5, 4)
    assert idx.shape == (5, 4)
    np.testing.assert_array_equal(np.asarray(idx[0]), [0, 1, 2, 3])
    np.testing.assert_array_equal(np.asarray(idx[4]), [4, 5, 6, 7])
