"""Runtime integration tests: weight service, metrics log format, checkpoint
round-trip, and the hermetic end-to-end training slice on the fake env
(SURVEY §4 — the multi-process/system behavior the reference never tests).
"""

import os
import re

import jax
import numpy as np
import pytest

from r2d2_tpu.config import Config
from r2d2_tpu.models import init_network
from r2d2_tpu.runtime.checkpoint import (
    list_checkpoints, load_pretrain, restore_checkpoint, save_checkpoint)
from r2d2_tpu.runtime.metrics import TrainMetrics
from r2d2_tpu.runtime.orchestrator import train
from r2d2_tpu.runtime.weights import (
    InProcWeightStore, WeightPublisher, WeightSubscriber)


def tiny_config(tmp_path, **overrides) -> Config:
    cfg = Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.num_actors": 2, "actor.actor_update_interval": 50,
        "optim.lr": 1e-3,
        "runtime.save_dir": str(tmp_path), "runtime.save_interval": 50,
        "runtime.log_interval": 0.2, "runtime.weight_publish_interval": 5,
        # per-step dispatch: these tests assert per-step cadences (publish,
        # checkpoint, step counts); the production default is 16
        "runtime.steps_per_dispatch": 1,
    })
    return cfg.replace(**overrides) if overrides else cfg


@pytest.fixture
def small_params():
    from r2d2_tpu.config import NetworkConfig
    _, params = init_network(
        jax.random.PRNGKey(0), 4,
        NetworkConfig(hidden_dim=8, cnn_out_dim=16,
                      conv_layers=((4, 3, 2),)),
        frame_stack=2, frame_height=12, frame_width=12)
    return params


def test_weight_shm_roundtrip(small_params):
    """Publisher → shm → subscriber returns the identical pytree; repeated
    polls without a publish return None (version gate)."""
    pub = WeightPublisher(small_params)
    try:
        sub = WeightSubscriber(pub.name, small_params)
        got = sub.poll()
        assert got is not None
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
            small_params, got)
        assert sub.poll() is None
        bumped = jax.tree_util.tree_map(lambda x: x + 1.0, small_params)
        pub.publish(bumped)
        got2 = sub.poll()
        leaves = jax.tree_util.tree_leaves(got2)
        orig = jax.tree_util.tree_leaves(small_params)
        np.testing.assert_allclose(np.asarray(leaves[0]),
                                   np.asarray(orig[0]) + 1.0)
        sub.close()
    finally:
        pub.close()


def test_weight_shm_checksum_path(small_params, monkeypatch):
    """The non-TSO validation path (VERDICT r4 #6): with _NEEDS_CHECKSUM
    forced on, (a) the roundtrip still works (crc written + validated), and
    (b) a payload corrupted AFTER the version settled — the torn-read shape
    a weakly-ordered host can produce — is rejected instead of returned."""
    from r2d2_tpu.runtime import weights as W
    monkeypatch.setattr(W, "_NEEDS_CHECKSUM", True)
    pub = WeightPublisher(small_params)
    try:
        sub = WeightSubscriber(pub.name, small_params)
        got = sub.poll()
        assert got is not None
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                    np.asarray(b)),
            small_params, got)
        # simulate a torn publish: bump the version to a NEW even value
        # (so the version gate alone would accept) but corrupt the payload
        # relative to the stored crc
        bumped = jax.tree_util.tree_map(lambda x: x + 1.0, small_params)
        pub.publish(bumped)
        pub._payload[0] += 123.0
        assert sub.poll() is None          # crc mismatch -> rejected
        # a clean re-publish recovers
        pub.publish(bumped)
        assert sub.poll() is not None
        sub.close()
    finally:
        pub.close()


def test_inproc_store_per_reader_versions(small_params):
    store = InProcWeightStore(small_params)
    assert store.poll(0) is not None
    assert store.poll(0) is None
    assert store.poll(1) is not None  # second reader still sees v1
    store.publish(small_params)
    assert store.poll(0) is not None


def test_metrics_reference_log_format(tmp_path):
    """Emitted keys must match the reference's exact strings so its plot.py
    parses our logs (ref worker.py:220-234, plot.py:33-48)."""
    m = TrainMetrics(player_idx=0, log_dir=str(tmp_path))
    m.set_buffer_size(1234)
    m.on_block(20, episode_return=7.5)
    m.on_train_step(0.25)
    m.on_train_step(0.35)
    m.log(20.0)
    text = (tmp_path / "train_player0.log").read_text()
    assert re.search(r"^buffer size: 1234$", text, re.M)
    assert re.search(r"^buffer update speed: .*/s$", text, re.M)
    assert re.search(r"^number of environment steps: 20$", text, re.M)
    assert re.search(r"^average episode return: 7\.5000$", text, re.M)
    assert re.search(r"^number of training steps: 2$", text, re.M)
    assert re.search(r"^training speed: .*/s$", text, re.M)
    assert re.search(r"^loss: 0\.3000$", text, re.M)


def test_checkpoint_roundtrip_and_pretrain(tmp_path, small_params):
    import optax
    opt_state = optax.adam(1e-4).init(small_params)
    path = save_checkpoint(str(tmp_path), "Fake", 3, 0, small_params,
                           opt_state, small_params, step=300, env_steps=9000)
    assert os.path.isdir(path)
    restored = restore_checkpoint(path)
    assert int(restored["step"]) == 300 and int(restored["env_steps"]) == 9000
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        small_params, restored["params"])
    warm = load_pretrain(path, small_params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        small_params, warm)
    assert list_checkpoints(str(tmp_path), "Fake", 0) == [(3, path)]


@pytest.mark.slow
def test_full_resume_continues_exactly(tmp_path):
    """Train K steps → checkpoint → resume → continued run matches the
    uninterrupted run bit-for-bit (params AND opt_state restored; the
    reference can only warm-start weights, worker.py:260-261)."""
    import numpy.random as npr
    from r2d2_tpu.config import NetworkConfig, OptimConfig
    from r2d2_tpu.learner import create_train_state
    from r2d2_tpu.learner.train_step import make_external_batch_step
    from r2d2_tpu.replay import replay_add, replay_init
    from r2d2_tpu.replay.device_replay import replay_sample
    from r2d2_tpu.runtime.checkpoint import (
        resume_training_state, save_checkpoint)
    from tests.test_replay import A, _fill_blocks, make_spec

    rng = npr.default_rng(0)
    spec = make_spec(batch_size=8)
    ncfg = NetworkConfig(hidden_dim=spec.hidden_dim, cnn_out_dim=16,
                         conv_layers=((8, 4, 2), (16, 3, 1)))
    net, _ = init_network(jax.random.PRNGKey(0), A, ncfg,
                          frame_stack=spec.frame_stack,
                          frame_height=spec.frame_height,
                          frame_width=spec.frame_width)
    opt = OptimConfig(lr=1e-3)
    rs = replay_init(spec)
    for blk in _fill_blocks(spec, 3, rng):
        rs = replay_add(spec, rs, blk)
    batch = replay_sample(spec, rs, jax.random.PRNGKey(7))
    step = make_external_batch_step(net, spec, opt, use_double=False)

    ts = create_train_state(jax.random.PRNGKey(1), net, opt)
    for _ in range(3):
        ts, _m = step(ts, batch)
    path = save_checkpoint(str(tmp_path), "Fake", 1, 0, ts.params,
                           ts.opt_state, ts.target_params, int(ts.step),
                           env_steps=123)
    for _ in range(3):
        ts, _m = step(ts, batch)          # uninterrupted continuation

    # resume into a DIFFERENTLY-seeded fresh state: everything must come
    # from the checkpoint, nothing from the fresh init
    ts2 = create_train_state(jax.random.PRNGKey(99), net, opt)
    ts2, env_steps = resume_training_state(path, ts2)
    assert env_steps == 123
    assert int(ts2.step) == 3
    for _ in range(3):
        ts2, _m = step(ts2, batch)

    assert int(ts.step) == int(ts2.step) == 6
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        ts.params, ts2.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        ts.opt_state, ts2.opt_state)


def test_learner_resume_wiring(tmp_path):
    """cfg.runtime.resume restores step/env_steps into the Learner; resume
    and pretrain are mutually exclusive."""
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.learner_loop import Learner

    cfg = tiny_config(tmp_path)
    net = NetworkApply(4, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    learner = Learner(cfg, net)
    path = learner.save(2)
    learner.env_steps = 0  # save() recorded env_steps=0

    cfg2 = cfg.replace(**{"runtime.resume": path})
    resumed = Learner(cfg2, net)
    assert resumed.training_steps == int(learner.train_state.step)
    assert resumed.env_steps == 0

    with pytest.raises(ValueError, match="mutually exclusive"):
        Learner(cfg.replace(**{"runtime.resume": path,
                               "runtime.pretrain": path}), net)


def test_supervisor_restarts_dead_actor(tmp_path):
    """PlayerStack.supervise respawns dead actor threads (failure handling
    the reference lacks entirely, SURVEY §5.3)."""
    import threading
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.orchestrator import PlayerStack

    cfg = tiny_config(tmp_path)
    probe = create_env(cfg.env)
    stack = PlayerStack(cfg, 0, probe.action_space.n)
    stop = threading.Event()
    stack.start_actors_threads(stop)
    try:
        assert all(t.is_alive() for t in stack.threads)
        # simulate a crashed actor: a thread that already finished
        dead = threading.Thread(target=lambda: None)
        dead.start(); dead.join()
        stack.threads[0] = dead
        assert stack.supervise() == 1
        assert stack.threads[0].is_alive()
        # stop requested: no restart
        stack.threads[0] = dead
        stop.set()
        assert stack.supervise() == 0
    finally:
        stop.set()
        stack.close()


def test_supervisor_disabled_by_config(tmp_path):
    """runtime.restart_dead_actors=False disables RESPAWNING: the health
    scan still runs (hang detection, failure accounting) but a dead
    worker stays dead."""
    import threading
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.orchestrator import PlayerStack

    cfg = tiny_config(tmp_path, **{"runtime.restart_dead_actors": False})
    probe = create_env(cfg.env)
    stack = PlayerStack(cfg, 0, probe.action_space.n)
    stop = threading.Event()
    stack.start_actors_threads(stop)
    try:
        dead = threading.Thread(target=lambda: None)
        dead.start(); dead.join()
        stack.threads[0] = dead
        assert stack.supervise() == 0
        assert not stack.threads[0].is_alive()
    finally:
        stop.set()
        stack.close()


def test_ring_recovery_runs_with_restarts_disabled(tmp_path):
    """Round-3 advisor: with runtime.restart_dead_actors=False a producer
    dying between reserve and commit must STILL trigger shm-slot
    reclamation — otherwise the wedged head slot starves the learner even
    though other actors are alive."""
    import threading
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.orchestrator import PlayerStack

    cfg = tiny_config(tmp_path, **{"runtime.restart_dead_actors": False})
    probe = create_env(cfg.env)
    stack = PlayerStack(cfg, 0, probe.action_space.n)
    probe.close()
    stack._stop = threading.Event()

    class DeadProc:
        def is_alive(self):
            return False

    class StubQueue:
        recoveries = 0

        def recover_stalled(self):
            self.recoveries += 1
            return 1

    stack.processes = [DeadProc()]
    stack.queue = StubQueue()
    try:
        sched = stack._ring_recovery
        assert stack.supervise() == 0            # no restart...
        assert sched._after is not None          # ...but recovery scheduled
        sched._after = 0.0                       # skip the 6s slot grace
        assert stack.supervise() == 0
        assert stack.queue.recoveries == 1
        # the death was < 6s ago: a follow-up pass re-arms (the slot may
        # not have been stale for the pass that just ran)
        assert sched._after is not None
        sched._last_death = 0.0                  # grace has long passed
        sched._after = 0.0
        assert stack.supervise() == 0
        assert stack.queue.recoveries == 2
        assert sched._after is None              # disarmed
        # the same permanently-dead process must not reschedule every tick
        assert stack.supervise() == 0
        assert sched._after is None
        assert stack.queue.recoveries == 2
    finally:
        # release the stack's process-wide state (shm boards, span drain,
        # the compile monitor's logger hook) — the stubs aren't closeable
        stack.processes = []
        stack.queue = None
        stack.close()


def test_thread_actor_envs_closed_on_stop(tmp_path, monkeypatch):
    """Round-3 advisor: actor thread exit (clean stop or crash) must close
    its env — a respawn creates a fresh one, so an unclosed predecessor
    leaks fds/engine handles per restart."""
    import threading
    from r2d2_tpu.envs import factory as factory_mod
    from r2d2_tpu.runtime import orchestrator as orch_mod

    closed = []
    real_create = factory_mod.create_env

    def tracking_create(*args, **kwargs):
        env = real_create(*args, **kwargs)
        orig_close = env.close
        env.close = lambda: (closed.append(env), orig_close())[1]
        return env

    monkeypatch.setattr(orch_mod, "create_env", tracking_create)
    cfg = tiny_config(tmp_path)
    probe = factory_mod.create_env(cfg.env)
    stack = orch_mod.PlayerStack(cfg, 0, probe.action_space.n)
    probe.close()
    stop = threading.Event()
    stack.start_actors_threads(stop)
    n = cfg.actor.num_actors
    assert len(stack.threads) == n
    stop.set()
    stack.close()
    assert len(closed) == n


@pytest.mark.slow
def test_pretrain_auto_migrates_space_to_depth(tmp_path):
    """Round-3 advisor: warm-starting a space_to_depth network from a
    standard-layout checkpoint must auto-migrate (exact rewrite) instead of
    dying with the generic mismatch error; the reverse direction refuses
    loudly."""
    import jax.numpy as jnp
    from r2d2_tpu.config import NetworkConfig
    from r2d2_tpu.models import initial_hidden
    from r2d2_tpu.models.network import NetworkApply

    base_cfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32)
    net_off = NetworkApply(4, base_cfg, 4, 84, 84)
    params_off = net_off.init(jax.random.PRNGKey(2))
    path = save_checkpoint(str(tmp_path), "Fake", 1, 0, params_off,
                           {"dummy": np.zeros(1)}, params_off, 0, 0)

    s2d_cfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32,
                            space_to_depth="on")
    net_on = NetworkApply(4, s2d_cfg, 4, 84, 84)
    template_on = net_on.init(jax.random.PRNGKey(3))
    migrated = load_pretrain(path, template_on)

    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.uniform(0, 1, (2, 3, 84, 84, 4)), jnp.float32)
    la = jnp.zeros((2, 3, 4), jnp.float32)
    q_off, _ = net_off.apply(params_off, obs, la, initial_hidden(2, 16))
    q_on, _ = net_on.apply(migrated, obs, la, initial_hidden(2, 16))
    np.testing.assert_allclose(np.asarray(q_on), np.asarray(q_off),
                               rtol=1e-5, atol=1e-5)

    # reverse direction (s2d checkpoint -> standard net): loud refusal
    params_on = net_on.init(jax.random.PRNGKey(4))
    path_on = save_checkpoint(str(tmp_path), "FakeS2d", 1, 0, params_on,
                              {"dummy": np.zeros(1)}, params_on, 0, 0)
    with pytest.raises(ValueError, match="space_to_depth=off"):
        load_pretrain(path_on, net_off.init(jax.random.PRNGKey(5)))

    # unrelated shape mismatch: named param in the error, no migration
    wide = NetworkApply(4, NetworkConfig(hidden_dim=32, cnn_out_dim=32), 4, 84, 84)
    with pytest.raises(ValueError, match="architecture mismatch"):
        load_pretrain(path, wide.init(jax.random.PRNGKey(6)))


@pytest.mark.slow
def test_end_to_end_training_slice(tmp_path):
    """The minimum end-to-end slice (SURVEY §7.3): thread actors on the fake
    env feed the device replay; the fused learner trains; checkpoints, logs,
    and weight publication all happen."""
    cfg = tiny_config(tmp_path)
    stacks = train(cfg, max_training_steps=15, max_seconds=300,
                   actor_mode="thread")
    learner = stacks[0].learner
    assert int(learner.train_state.step) >= 15
    assert learner.env_steps >= cfg.replay.learning_starts
    # step-0 checkpoint written (ref worker.py:311)
    assert any(idx == 0 for idx, _ in list_checkpoints(str(tmp_path), "Fake", 0))
    log = (tmp_path / "train_player0.log")
    assert log.exists()


def test_put_patient_blocks_until_space_and_honors_stop():
    """The patient put survives back-pressure (a full queue) until space
    appears, and gives up promptly when the stop signal fires."""
    import threading
    import time as time_mod

    from r2d2_tpu.runtime.feeder import BlockQueue

    q = BlockQueue(maxsize=1, use_mp=False)
    assert q.put_patient("a", should_stop=lambda: False, poll=0.05)

    # full queue: put_patient parks until a consumer drains
    done = []
    t = threading.Thread(
        target=lambda: done.append(
            q.put_patient("b", should_stop=lambda: False, poll=0.05)))
    t.start()
    time_mod.sleep(0.2)
    assert t.is_alive() and not done          # parked, not failed
    # drain exactly one: a full drain races the just-woken producer, which
    # can slip "b" in between two get_nowait calls
    assert q.drain(max_items=1) == ["a"]
    t.join(timeout=5.0)
    assert done == [True] and q.drain() == ["b"]

    # full queue + stop: returns False instead of blocking forever
    q.put_patient("c", should_stop=lambda: False, poll=0.05)
    t0 = time_mod.time()
    assert q.put_patient("d", should_stop=lambda: True, poll=0.05) is False
    assert time_mod.time() - t0 < 1.0


def test_rate_limiter_pauses_and_resumes_ingestion(tmp_path):
    """replay.max_env_steps_per_train_step pins the collect:learn ratio:
    ingestion pauses once env_steps exceed learning_starts + ratio *
    train_steps and resumes as training advances (Reverb-style rate
    limiting; the reference's actors free-run, worker.py:528)."""
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.feeder import BlockQueue
    from r2d2_tpu.runtime.learner_loop import Learner

    from tests.test_replay import _fill_blocks

    # frame/hidden dims matched to test_replay's synthetic block driver
    cfg = tiny_config(tmp_path, **{
        "replay.max_env_steps_per_train_step": 2.0,
        "env.frame_height": 12, "env.frame_width": 12,
        "network.hidden_dim": 8})
    probe = create_env(cfg.env)
    net = NetworkApply(probe.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    probe.close()
    learner = Learner(cfg, net)

    rng = np.random.default_rng(0)
    q = BlockQueue(use_mp=False)
    for blk in _fill_blocks(learner.spec, 12, rng):
        q.put(blk)

    # pre-training budget = learning_starts(100) + 2.0*1: 20-step blocks
    # ingest until env_steps reaches 120, then pause
    ingested = 0
    while learner.drain(q, max_items=1):
        ingested += 1
    assert learner.env_steps == 120 and ingested == 6
    assert learner.ingestion_paused
    assert learner.drain(q) == 0          # still parked

    # training advances -> budget moves -> ingestion resumes
    learner._host_step = 50               # budget = 100 + 2.0*50 = 200
    assert not learner.ingestion_paused
    while learner.drain(q, max_items=1):
        ingested += 1
    assert learner.env_steps == 200 and ingested == 10
    assert learner.ingestion_paused


@pytest.mark.slow
def test_dropped_priority_writebacks_are_counted(tmp_path):
    """Round-3 review: under write-back queue backpressure the host-mode
    learner drops priority updates (degrading PER toward uniform) — that
    must be observable: TrainMetrics.dropped_priority_updates increments
    and the JSONL record carries it."""
    import queue as queue_mod
    import threading

    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.feeder import BlockQueue
    from r2d2_tpu.runtime.learner_loop import Learner

    from tests.test_replay import _fill_blocks

    cfg = tiny_config(tmp_path, **{
        "replay.placement": "host", "runtime.save_interval": 0,
        "env.frame_height": 12, "env.frame_width": 12,
        "network.hidden_dim": 8})
    probe = create_env(cfg.env)
    net = NetworkApply(probe.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    probe.close()
    learner = Learner(cfg, net)

    q = BlockQueue(use_mp=False)
    for blk in _fill_blocks(learner.spec, 6, np.random.default_rng(0)):
        q.put(blk)
    while learner.drain(q, max_items=1):
        pass
    assert learner.ready

    # Saturate the write-back path: stall the consumer inside
    # update_priorities and shrink the queue to one slot, so the second or
    # third step's put_nowait hits Full and the drop must be counted.
    release = threading.Event()
    orig_update = learner.host_replay.update_priorities

    def stalled_update(*args, **kwargs):
        release.wait(timeout=60)
        return orig_update(*args, **kwargs)

    learner.host_replay.update_priorities = stalled_update
    learner._writeback_q = queue_mod.Queue(maxsize=1)
    try:
        for _ in range(4):
            learner.step()
        assert learner.metrics.dropped_priority_updates >= 1
        rec = learner.metrics.log(1.0)
        assert (rec["dropped_priority_updates"]
                == learner.metrics.dropped_priority_updates)
    finally:
        release.set()
        learner.stop_background()


def test_rate_limiter_survives_resume(tmp_path):
    """Regression (round-3 review): the limiter budget must be measured
    from the process's starting point. A resumed run restores large
    cumulative env/train counters while its replay ring restarts empty —
    an absolute budget comparison would pause ingestion forever and
    training could never reach learning_starts again."""
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.feeder import BlockQueue
    from r2d2_tpu.runtime.learner_loop import Learner

    from tests.test_replay import _fill_blocks

    cfg = tiny_config(tmp_path, **{
        "replay.max_env_steps_per_train_step": 2.0,
        "env.frame_height": 12, "env.frame_width": 12,
        "network.hidden_dim": 8})
    probe = create_env(cfg.env)
    net = NetworkApply(probe.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    probe.close()

    first = Learner(cfg, net)
    first.env_steps = 9_999            # steady-state cumulative counter
    ckpt = first.save(7)

    resumed = Learner(cfg.replace(**{"runtime.resume": ckpt}), net)
    assert resumed.env_steps == 9_999
    assert not resumed.ingestion_paused   # empty ring: must accept data

    q = BlockQueue(use_mp=False)
    rng = np.random.default_rng(0)
    for blk in _fill_blocks(resumed.spec, 8, rng):
        q.put(blk)
    ingested = 0
    while resumed.drain(q, max_items=1):
        ingested += 1
    # fresh budget from the resume point: learning_starts(100)+2.0 -> 6
    # blocks of 20 steps, then pause — training can start
    assert ingested == 6 and resumed.ready
    assert resumed.ingestion_paused


def test_rate_limiter_never_pauses_before_dp_gate_opens(tmp_path):
    """Regression (round-3 review): under a dp mesh the ready gate also
    waits for one block per shard. The limiter must not pause ingestion
    while that gate is closed — the budget can be exhausted after shard 0's
    block, and pausing there would starve shard 1 forever (drain() returns
    0, ready stays False, training never starts: livelock)."""
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.feeder import BlockQueue
    from r2d2_tpu.runtime.learner_loop import Learner

    from tests.test_replay import _fill_blocks

    # one 20-step block already exceeds budget = learning_starts(10) + 2.0
    cfg = tiny_config(tmp_path, **{
        "mesh.dp": 2, "replay.learning_starts": 10,
        "replay.max_env_steps_per_train_step": 2.0,
        "env.frame_height": 12, "env.frame_width": 12,
        "network.hidden_dim": 8})
    probe = create_env(cfg.env)
    net = NetworkApply(probe.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    probe.close()
    learner = Learner(cfg, net)

    q = BlockQueue(use_mp=False)
    for blk in _fill_blocks(learner.spec, 2, np.random.default_rng(0)):
        q.put(blk)

    assert learner.drain(q, max_items=1) == 1    # shard 0 filled
    assert not learner.ready                     # shard 1 still empty
    assert not learner.ingestion_paused          # must keep accepting
    assert learner.drain(q, max_items=1) == 1    # shard 1 filled
    assert learner.ready                         # training can start
    assert learner.ingestion_paused              # NOW the ratio applies


@pytest.mark.slow
def test_end_to_end_process_mode(tmp_path):
    """The production actor topology (VERDICT r2 #4): spawned actor
    processes feeding the learner over the native shm block ring with
    shared-memory weight subscription (the reference's deployed mode is Ray
    actors over plasma, worker.py:502-591 + train.py:36-43). Asserts the
    learner trains from process-produced blocks and that close() leaves no
    orphan processes."""
    import time as time_mod

    cfg = tiny_config(tmp_path, **{"runtime.save_interval": 0})
    stacks = train(cfg, max_training_steps=10, max_seconds=600,
                   actor_mode="process")
    learner = stacks[0].learner
    assert learner.training_steps >= 10
    # blocks crossed the process boundary and filled the buffer — through
    # the native shm ring when the toolchain is present (default transport)
    assert learner.env_steps >= cfg.replay.learning_starts
    try:
        from r2d2_tpu.native import ring_lib
        ring_lib()   # probes the actual native build, not just the import
        native_ok = True
    except Exception:
        native_ok = False
    if native_ok:
        from r2d2_tpu.runtime.shm_feeder import ShmBlockRing
        assert isinstance(stacks[0].queue._q, ShmBlockRing)
    procs = stacks[0].processes
    assert len(procs) == cfg.actor.num_actors
    deadline = time_mod.time() + 10.0
    while any(p.is_alive() for p in procs) and time_mod.time() < deadline:
        time_mod.sleep(0.1)
    assert not any(p.is_alive() for p in procs), "orphan actor processes"
    # shm weight segment was unlinked by close()
    assert stacks[0].publisher is not None


@pytest.mark.slow
def test_end_to_end_mesh_dp2(tmp_path):
    """mesh.dp=2 routes the production Learner onto the shard_map step and
    the dp-sharded replay (SURVEY §5.8): thread actors feed blocks
    round-robin across shards, gradients pmean over the mesh, and the
    orchestrator loop never knows the difference."""
    cfg = tiny_config(tmp_path, **{"mesh.dp": 2, "runtime.save_interval": 0})
    stacks = train(cfg, max_training_steps=6, max_seconds=300,
                   actor_mode="thread")
    learner = stacks[0].learner
    assert learner.mesh is not None and learner.mesh.shape["dp"] == 2
    assert learner.training_steps >= 6
    # the replay ring really is sharded: leading dp axis
    assert learner.replay_state.obs.shape[0] == 2
    assert int(learner.replay_state.learning_steps[0].sum()) > 0
    assert int(learner.replay_state.learning_steps[1].sum()) > 0
    for leaf in jax.tree_util.tree_leaves(learner.train_state.params):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.slow
def test_end_to_end_host_placement(tmp_path):
    """The reference-style architecture (replay.placement="host"): CPU ring +
    native sum tree + prefetch/write-back threads, external-batch device
    step."""
    cfg = tiny_config(tmp_path, **{"replay.placement": "host",
                                   "runtime.save_interval": 0})
    stacks = train(cfg, max_training_steps=10, max_seconds=300,
                   actor_mode="thread")
    learner = stacks[0].learner
    assert learner.host_mode
    assert learner.training_steps >= 10
    assert len(learner.host_replay) >= cfg.replay.learning_starts
    # close() (already run by train()) must have joined the pipeline threads
    assert not any(t.is_alive() for t in learner._bg_threads)
    assert not learner._bg_threads


@pytest.mark.slow
def test_end_to_end_host_placement_tensor_parallel(tmp_path):
    """mesh.mp=2 with replay.placement='host' routes the production Learner
    onto the tensor-parallel external-batch step: wide params genuinely
    sharded over mp, batches placed over dp, training proceeds through the
    full orchestrator."""
    cfg = tiny_config(tmp_path, **{
        "replay.placement": "host", "mesh.mp": 2, "mesh.dp": 2,
        "runtime.save_interval": 0})
    stacks = train(cfg, max_training_steps=6, max_seconds=300,
                   actor_mode="thread")
    learner = stacks[0].learner
    assert learner.host_mode and learner.training_steps >= 6
    # at least one param leaf must really be feature-sharded across mp
    sharded = [l for l in jax.tree_util.tree_leaves(learner.train_state.params)
               if l.ndim >= 1
               and l.addressable_shards[0].data.shape[-1] != l.shape[-1]]
    assert sharded, "no param leaf sharded over mp"
    for leaf in jax.tree_util.tree_leaves(learner.train_state.params):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.slow
def test_end_to_end_device_placement_tensor_parallel(tmp_path):
    """VERDICT r3 #4: mesh.mp=2 with the DEFAULT device-replay placement —
    the fused sample-in-HBM step runs with wide params genuinely
    feature-sharded over mp (GSPMD) and replay dp-sharded, through the full
    orchestrator. Model sharding is a mesh-axis change on the flagship
    path."""
    cfg = tiny_config(tmp_path, **{
        "mesh.mp": 2, "mesh.dp": 2, "runtime.save_interval": 0})
    stacks = train(cfg, max_training_steps=6, max_seconds=300,
                   actor_mode="thread")
    learner = stacks[0].learner
    assert not learner.host_mode and learner.training_steps >= 6
    sharded = [l for l in jax.tree_util.tree_leaves(learner.train_state.params)
               if l.ndim >= 1
               and l.addressable_shards[0].data.shape[-1] != l.shape[-1]]
    assert sharded, "no param leaf sharded over mp"
    for leaf in jax.tree_util.tree_leaves(learner.train_state.params):
        assert np.isfinite(np.asarray(leaf)).all()
    # replay stayed dp-sharded
    assert learner.replay_state.tree.sharding.spec[0] == "dp"


@pytest.mark.slow
def test_sigterm_maps_to_clean_stop(tmp_path):
    """An external SIGTERM lands on the stop-event path (wedge avoidance:
    TPU-holding runs must never be hard-killed mid-dispatch) and the previous
    handler is restored afterwards."""
    import signal
    import threading
    import time as time_mod

    cfg = tiny_config(tmp_path, **{"runtime.save_interval": 0})
    prev = signal.getsignal(signal.SIGTERM)
    timer = threading.Timer(
        2.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
    timer.start()
    t0 = time_mod.time()
    try:
        train(cfg, max_training_steps=10**9, max_seconds=60.0,
              actor_mode="thread")
    finally:
        timer.cancel()
    assert time_mod.time() - t0 < 55.0, "signal did not stop the run"
    assert signal.getsignal(signal.SIGTERM) is prev


@pytest.mark.slow
def test_multi_step_dispatch_end_to_end(tmp_path):
    """steps_per_dispatch > 1 trains in K-step dispatches."""
    cfg = tiny_config(tmp_path, **{"runtime.steps_per_dispatch": 4,
                                   "runtime.save_interval": 0})
    stacks = train(cfg, max_training_steps=8, max_seconds=300,
                   actor_mode="thread")
    assert stacks[0].learner.training_steps in (8, 12)  # multiple of k=4
