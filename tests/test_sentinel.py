"""Resource/compilation observability + alerting sentinel tests (ISSUE 7):
the declarative rule engine's semantics, retrace detection on a real
shape-churning jit, the resource monitor (device stats, buffer
attribution, board RSS aggregation, OOM forensics), record-schema
stability for PR4/5-era readers, the sentinel/regress CLIs, and the
chaos-driven e2e slices proving injected faults raise the right alerts.
"""

import json
import logging
import os
import time

import numpy as np
import pytest

from r2d2_tpu.config import Config
from r2d2_tpu.telemetry.alerts import (AlertEngine, AlertRule, default_rules,
                                       record_value)

from tests.test_runtime import tiny_config
from tests.test_telemetry import PR23_RECORD_KEYS


def _engine(*rules, **kwargs):
    return AlertEngine(rules, **kwargs)


# ---------------------------------------------------------------------------
# rule / engine units


def test_alert_rule_validation():
    with pytest.raises(ValueError, match="unknown kind"):
        AlertRule("x", "spike", ("a",), 1.0)
    with pytest.raises(ValueError, match="window"):
        AlertRule("x", "drop", ("a",), 0.5, window=1)


def test_record_value_walks_paths():
    rec = {"a": {"b": {"c": 3}}, "flat": 1.5, "none": None,
           "s": "str", "l": [1]}
    assert record_value(rec, ("a", "b", "c")) == 3.0
    assert record_value(rec, ("flat",)) == 1.5
    assert record_value(rec, ("a", "missing")) is None
    assert record_value(rec, ("none",)) is None
    assert record_value(rec, ("s",)) is None
    assert record_value(rec, ("l",)) is None
    assert record_value(rec, ("flat", "deeper")) is None


def test_threshold_rule_edge_and_rearm():
    eng = _engine(AlertRule("hot", "threshold", ("v",), 10.0))
    assert eng.evaluate({"v": 5})["fired"] == []
    fired = eng.evaluate({"v": 12})["fired"]
    assert [a["rule"] for a in fired] == ["hot"]
    # persistent condition: active, but no re-fire
    out = eng.evaluate({"v": 15})
    assert out["fired"] == [] and out["active"] == ["hot"]
    # recovery re-arms, next crossing fires again
    assert eng.evaluate({"v": 5})["active"] == []
    assert [a["rule"] for a in eng.evaluate({"v": 11})["fired"]] == ["hot"]
    assert eng.fired_total == 2


def test_threshold_below_direction():
    eng = _engine(AlertRule("low", "threshold", ("v",), 0.05, below=True))
    assert eng.evaluate({"v": 0.5})["fired"] == []
    assert [a["rule"] for a in eng.evaluate({"v": 0.01})["fired"]] == ["low"]


def test_counter_rule_zero_baseline_then_edge():
    eng = _engine(AlertRule("c", "counter", ("n",), 1.0))
    # healthy counter at zero: nothing to report
    assert eng.evaluate({"n": 0})["fired"] == []
    assert eng.evaluate({"n": 0})["fired"] == []
    fired = eng.evaluate({"n": 1})["fired"]
    assert fired and fired[0]["delta"] == 1.0
    # pure edge semantics: one increment fires exactly once
    assert eng.evaluate({"n": 1})["fired"] == []
    # a missing record key holds the baseline, it doesn't reset it
    assert eng.evaluate({})["fired"] == []
    assert eng.evaluate({"n": 3})["fired"][0]["delta"] == 2.0


def test_counter_rule_first_record_already_carries_events():
    # events BEFORE the first log boundary (a warm-up hang) still alert:
    # the baseline is zero, not the first observation
    eng = _engine(AlertRule("c", "counter", ("n",), 1.0))
    fired = eng.evaluate({"n": 2})["fired"]
    assert fired and fired[0]["delta"] == 2.0
    assert eng.evaluate({"n": 2})["fired"] == []      # still exactly once


def test_drop_rule_fires_on_collapse_with_baseline():
    eng = _engine(AlertRule("tp", "drop", ("v",), 0.5, window=3))
    for _ in range(3):
        assert eng.evaluate({"v": 100.0})["fired"] == []
    fired = eng.evaluate({"v": 30.0})["fired"]
    assert fired and fired[0]["rule"] == "tp"
    assert fired[0]["baseline"] == pytest.approx(100.0)
    # recovery clears without a new fire
    assert eng.evaluate({"v": 90.0})["active"] == []


def test_drop_rule_warmup_zeros_never_arm():
    eng = _engine(AlertRule("tp", "drop", ("v",), 0.5, window=2))
    # zeros (warm-up / paused intervals) never enter the median, so the
    # rule cannot arm off a dead baseline and then fire on recovery
    for _ in range(5):
        assert eng.evaluate({"v": 0.0})["fired"] == []
    assert eng.evaluate({"v": 50.0})["fired"] == []   # first healthy obs
    assert eng.evaluate({"v": 60.0})["fired"] == []
    assert eng.evaluate({"v": 10.0})["fired"]         # now a real collapse


def test_growth_rule():
    eng = _engine(AlertRule("age", "growth", ("v",), 4.0, window=2))
    for v in (10.0, 12.0):
        assert eng.evaluate({"v": v})["fired"] == []
    assert eng.evaluate({"v": 20.0})["fired"] == []   # 20 < 4 x 11
    # window now [12, 20] -> baseline 16; 70 > 4 x 16 fires
    assert [a["rule"] for a in eng.evaluate({"v": 70.0})["fired"]] == ["age"]


def test_missing_data_holds_level_state():
    eng = _engine(AlertRule("hot", "threshold", ("v",), 10.0))
    eng.evaluate({"v": 12})
    # a record without the key (training pause, pre-PR7 reader) must not
    # read as recovery — otherwise the next sighting would re-fire
    out = eng.evaluate({})
    assert out["active"] == ["hot"] and out["fired"] == []
    assert eng.evaluate({"v": 12})["fired"] == []


def test_default_rules_parameterized_and_unique():
    t = Config().telemetry
    rules = default_rules(t)
    names = [r.name for r in rules]
    assert len(set(names)) == len(names)
    by_name = {r.name: r for r in rules}
    assert by_name["retrace_storm"].bound == float(t.alerts_retrace_storm)
    assert by_name["hbm_headroom"].below
    assert by_name["hbm_headroom"].path == ("resources",
                                            "hbm_headroom_frac_min")
    assert by_name["actor_stall"].kind == "counter"
    assert by_name["env_throughput_drop"].window == t.alerts_window


def test_engine_rejects_duplicate_rule_names():
    with pytest.raises(ValueError, match="duplicate"):
        _engine(AlertRule("a", "threshold", ("v",), 1.0),
                AlertRule("a", "counter", ("w",), 1.0))


def test_engine_jsonl_truncate_and_resume(tmp_path):
    path = str(tmp_path / "alerts_player0.jsonl")
    eng = _engine(AlertRule("hot", "threshold", ("v",), 1.0),
                  jsonl_path=path)
    eng.evaluate({"v": 2, "t": 1.0, "training_steps": 7, "env_steps": 70})
    rows = [json.loads(l) for l in open(path)]
    assert rows[0]["rule"] == "hot" and rows[0]["training_steps"] == 7
    # resume appends to the stream, fresh truncates (TrainMetrics contract)
    eng2 = _engine(AlertRule("hot", "threshold", ("v",), 1.0),
                   jsonl_path=path, resume=True)
    eng2.evaluate({"v": 2})
    assert len(open(path).readlines()) == 2
    _engine(AlertRule("hot", "threshold", ("v",), 1.0), jsonl_path=path)
    assert open(path).read() == ""


# ---------------------------------------------------------------------------
# compile / retrace telemetry


def _pxla_logger_state():
    logger = logging.getLogger("jax._src.interpreters.pxla")
    return (logger.level, logger.propagate, list(logger.handlers))


def test_compile_monitor_retrace_detection():
    """The detector on an intentionally shape-churning jit: post-warm
    compiles of a KNOWN fn with NEW avals are retraces (flagged with the
    offending avals); a new fn after warm-up is a late compile, not a
    retrace."""
    import jax
    import jax.numpy as jnp

    from r2d2_tpu.telemetry.compile import CompileMonitor, active_monitor

    before = _pxla_logger_state()
    mon = CompileMonitor().install()
    try:
        assert active_monitor() is mon

        def churner(x):
            return x * 2.0 + 1.0

        f = jax.jit(churner)
        f(jnp.ones((4,)))                  # warm-up compile
        assert mon.totals()["retraces_total"] == 0
        mon.mark_warm()
        f(jnp.ones((4,)))                  # cache hit: no event
        f(jnp.ones((8,)))                  # retrace 1
        f(jnp.ones((16,)))                 # retrace 2
        totals = mon.totals()
        assert totals["retraces_total"] == 2
        assert totals["compiles_total"] >= 3
        assert "churner" in totals["last_retrace"]["fn"]
        assert "16" in totals["last_retrace"]["avals"]
        # a NEW function post-warm is a late first compile, not a retrace
        g = jax.jit(lambda x: x - 1.0)
        g(jnp.ones((4,)))
        totals = mon.totals()
        assert totals["late_compiles"] >= 1
        assert totals["retraces_total"] == 2
    finally:
        mon.uninstall()
    assert active_monitor() is None
    assert _pxla_logger_state() == before     # logger restored exactly


def test_compile_monitor_interval_summary_consumes():
    from r2d2_tpu.telemetry.compile import CompileMonitor
    mon = CompileMonitor()
    mon._on_backend_compile(1.5)
    mon._on_compile("f", "f32[4]")
    s1 = mon.interval_summary()
    assert s1["compiles"] == 1 and s1["compile_time_s"] == 1.5
    s2 = mon.interval_summary()
    assert s2["compiles"] == 0 and s2["compiles_total"] == 1


def test_compile_monitor_single_active_slot():
    from r2d2_tpu.telemetry.compile import CompileMonitor, active_monitor
    a = CompileMonitor().install()
    b = CompileMonitor().install()     # displaces a (install deactivates)
    try:
        assert active_monitor() is b
        b._on_compile("f", "f32[1]")
        assert a.traced_compiles == 0 and b.traced_compiles == 1
    finally:
        b.uninstall()
    assert active_monitor() is None


def test_retrace_event_counting_via_signatures():
    from r2d2_tpu.telemetry.compile import CompileMonitor
    mon = CompileMonitor()
    mon._on_compile("f", "f32[4]")
    mon.mark_warm()
    mon._on_compile("f", "f32[4]")     # same avals: not a retrace
    assert mon.retraces == 0
    mon._on_compile("f", "f32[8]")
    assert mon.retraces == 1
    mon._on_compile("g", "f32[4]")     # new fn post-warm: late, no retrace
    assert mon.retraces == 1 and mon.late_compiles == 1
    assert mon.functions_seen() == {"f": 2, "g": 1}


def test_aot_coverage_report():
    from r2d2_tpu.telemetry.compile import aot_coverage
    cov = aot_coverage([1, 2, 4, 8], [1, 2, 8, 16])
    assert cov["missing"] == [4]
    assert cov["extra"] == [16]
    assert cov["expected"] == [1, 2, 4, 8]


# ---------------------------------------------------------------------------
# resource monitor


def test_device_memory_stats_backend_optional():
    from r2d2_tpu.telemetry.resources import SUMMARY_KEYS, device_memory_stats

    class Raises:
        def memory_stats(self):
            raise RuntimeError("unimplemented")

    class Reports:
        def memory_stats(self):
            return {"bytes_in_use": 7.0, "bytes_limit": 100,
                    "allocs": "not-a-number", "other": 3}

    assert device_memory_stats(Raises()) == {}
    full = device_memory_stats(Reports())
    assert full == {"bytes_in_use": 7, "bytes_limit": 100, "other": 3}
    assert device_memory_stats(Reports(), keys=SUMMARY_KEYS) == {
        "bytes_in_use": 7, "bytes_limit": 100}


def test_pytree_nbytes():
    from r2d2_tpu.telemetry.resources import pytree_nbytes
    tree = {"a": np.zeros((4, 4), np.float32), "b": [np.zeros(8, np.int64)],
            "c": "not-an-array"}
    assert pytree_nbytes(tree) == 4 * 4 * 4 + 8 * 8


def test_host_usage_reports_this_process():
    from r2d2_tpu.telemetry.resources import host_usage
    u = host_usage()
    assert u["rss_bytes"] > 0
    assert u["cpu_s"] > 0
    assert u["threads"] >= 1


def test_buffer_registry_semantics():
    from r2d2_tpu.telemetry.resources import BufferRegistry
    reg = BufferRegistry()
    reg.register("p0/ring", 100)
    reg.register("p0/params", 50)
    reg.register("p0/ring", 120)          # re-register overwrites
    assert reg.snapshot() == {"p0/ring": 120, "p0/params": 50}
    assert reg.total() == 170
    reg.unregister("p0/params")
    reg.unregister("never-registered")    # no-op, not an error
    assert reg.total() == 120
    reg.clear()
    assert reg.snapshot() == {}


def _stats_fn(in_use, limit=1000):
    return lambda d: {"bytes_in_use": in_use, "bytes_limit": limit,
                      "peak_bytes_in_use": in_use}


def test_resource_monitor_block_and_running_peak(tmp_path):
    from r2d2_tpu.telemetry.resources import BufferRegistry, ResourceMonitor
    reg = BufferRegistry()
    reg.register("p0/ring", 640)
    mon = ResourceMonitor(0, str(tmp_path), interval_s=0.0, registry=reg,
                          headroom_warn_frac=0.0,
                          stats_fn=_stats_fn(400))
    mon.sample()
    block = mon.block()
    dev = block["devices"][0]
    assert dev["bytes_in_use"] == 400 and dev["headroom_frac"] == 0.6
    assert block["hbm_headroom_frac_min"] == 0.6
    assert block["buffers"] == {"p0/ring": 640}
    assert block["buffers_total"] == 640
    assert block["host"]["rss_bytes"] > 0
    # host-side running peak survives an allocator whose own peak resets
    mon._stats_fn = _stats_fn(250)
    mon.sample()
    assert mon.block()["devices"][0]["peak_seen"] == 400


def test_resource_monitor_maybe_sample_cadence(tmp_path):
    from r2d2_tpu.telemetry.resources import ResourceMonitor
    mon = ResourceMonitor(0, str(tmp_path), interval_s=60.0,
                          stats_fn=_stats_fn(1))
    assert mon.maybe_sample(now=1000.0)
    assert not mon.maybe_sample(now=1030.0)     # inside the interval
    assert mon.maybe_sample(now=1061.0)


def test_resource_monitor_forensics_dump_one_shot(tmp_path):
    from r2d2_tpu.telemetry.resources import ResourceMonitor
    mon = ResourceMonitor(3, str(tmp_path), interval_s=0.0,
                          headroom_warn_frac=0.10,
                          stats_fn=_stats_fn(970))    # 3% headroom
    mon.sample()
    path = tmp_path / "resource_dump_player3.json"
    assert path.exists()
    dump = json.loads(path.read_text())
    assert "headroom" in dump["reason"]
    assert dump["devices"][0]["bytes_in_use"] == 970
    # one-shot latch (the nan_dump pattern): later samples don't rewrite
    mtime = path.stat().st_mtime
    mon.sample()
    assert mon.dump() is None
    assert path.stat().st_mtime == mtime


def test_board_gauges_publish_read_and_reset():
    from r2d2_tpu.telemetry import TelemetryBoard
    board = TelemetryBoard(3)
    try:
        board.publish_gauges(0, 100 << 20, 5000)
        board.publish_gauges(2, 50 << 20, 1000)
        g = board.read_gauges()
        assert g.shape == (3, 2)
        assert g[0, 0] == 100 << 20 and g[2, 1] == 1000
        assert g[1, 0] == 0
        # a respawned slot starts clean
        board.reset_slot(0)
        assert board.read_gauges()[0, 0] == 0
        # gauges don't disturb the histogram table (layout check)
        assert board.read().sum() == 0
    finally:
        board.close()
    assert board.read_gauges() is None      # live-only, unlike histograms


def test_resource_monitor_board_rss_aggregation(tmp_path):
    """Board RSS/CPU aggregation: per-slot gauges land in the block;
    cpu%% is differenced across samples, and a respawned slot's counter
    reset reads as the fresh value, not a negative rate."""
    from r2d2_tpu.telemetry import TelemetryBoard
    from r2d2_tpu.telemetry.resources import ResourceMonitor
    board = TelemetryBoard(2)
    try:
        mon = ResourceMonitor(0, str(tmp_path), interval_s=0.0, board=board,
                              stats_fn=lambda d: {})
        board.publish_gauges(0, 100 << 20, 1000)
        board.publish_gauges(1, 200 << 20, 4000)
        mon.sample(now=10.0)
        slots = mon.block()["actor_slots"]
        assert slots["rss_bytes"] == [100 << 20, 200 << 20]
        assert slots["cpu_pct"] == [None, None]      # no delta yet
        board.publish_gauges(0, 110 << 20, 3000)     # +2s cpu over 10s
        board.publish_gauges(1, 200 << 20, 1000)     # respawn: counter reset
        mon.sample(now=20.0)
        slots = mon.block()["actor_slots"]
        assert slots["cpu_pct"][0] == pytest.approx(20.0)
        assert slots["cpu_pct"][1] == pytest.approx(10.0)   # fresh value
    finally:
        board.close()


def test_telemetry_flush_publishes_resource_gauges():
    from r2d2_tpu.telemetry import Telemetry, TelemetryBoard
    board = TelemetryBoard(2)
    try:
        tele = Telemetry(name="w", board=board, slot=1,
                         resource_gauges=True)
        tele.observe("actor/env_step", 1e-3)
        tele.flush()
        g = board.read_gauges()
        assert g[1, 0] > 0 and g[1, 1] > 0          # rss, cpu_ms
        assert g[0, 0] == 0
    finally:
        board.close()


# ---------------------------------------------------------------------------
# record schema stability + config round-trip


def test_record_schema_identical_without_pillar(tmp_path):
    """telemetry.resources_enabled=False (or simply nothing attached):
    the record must be byte-identical to the PR4/5/6 schema — no
    'resources', no 'alerts', every pre-PR7 key intact."""
    from r2d2_tpu.runtime.metrics import TrainMetrics
    m = TrainMetrics(0, str(tmp_path))
    m.on_block(20, 1.0)
    m.on_train_step(0.5)
    record = m.log(2.0)
    assert "resources" not in record and "alerts" not in record
    assert PR23_RECORD_KEYS <= set(record)
    # what a PR4/5-era reader would parse from the stream
    from r2d2_tpu.tools.logparse import parse_jsonl
    rows = parse_jsonl(str(tmp_path / "metrics_player0.jsonl"))
    assert set(rows[0]) == set(record)


def test_record_carries_resources_then_alerts_see_them(tmp_path):
    """The resources block is assembled BEFORE the alert pass, so a
    machine-side rule (hbm_headroom) fires off the same record it rides
    in — and the firing lands in alerts_player{p}.jsonl."""
    from r2d2_tpu.runtime.metrics import TrainMetrics
    from r2d2_tpu.telemetry.resources import ResourceMonitor
    m = TrainMetrics(0, str(tmp_path))
    mon = ResourceMonitor(0, str(tmp_path), interval_s=0.0,
                          headroom_warn_frac=0.0,
                          stats_fn=_stats_fn(980))    # 2% headroom
    m.set_resources(mon.block)
    path = str(tmp_path / "alerts_player0.jsonl")
    m.set_sentinel(AlertEngine(default_rules(Config().telemetry),
                               jsonl_path=path))
    record = m.log(2.0)
    assert record["resources"]["hbm_headroom_frac_min"] == pytest.approx(
        0.02)
    assert "hbm_headroom" in [a["rule"] for a in record["alerts"]["fired"]]
    rows = [json.loads(l) for l in open(path)]
    assert rows[0]["rule"] == "hbm_headroom"
    assert rows[0]["severity"] == "crit"


def test_config_pre_pr7_dict_round_trips():
    cfg = Config()
    d = cfg.to_dict()
    tel = d["telemetry"]
    for k in list(tel):
        if k.startswith(("resources_", "alerts_", "compile_")):
            del tel[k]                     # a PR6-era checkpoint config
    restored = Config.from_dict(d)
    assert restored.telemetry.resources_enabled
    assert restored.telemetry.alerts_window == cfg.telemetry.alerts_window
    # full modern round-trip preserves overrides
    cfg2 = cfg.replace(**{"telemetry.alerts_retrace_storm": 7,
                          "telemetry.resources_interval_s": 3.0})
    assert Config.from_dict(
        cfg2.to_dict()).telemetry.alerts_retrace_storm == 7


@pytest.mark.parametrize("knob,value,match", [
    ("telemetry.resources_interval_s", 0.0, "resources_interval_s"),
    ("telemetry.resources_headroom_warn_frac", 1.5, "headroom_warn_frac"),
    ("telemetry.alerts_window", 1, "alerts_window"),
    ("telemetry.alerts_throughput_drop_frac", 0.0, "throughput_drop_frac"),
    ("telemetry.alerts_staleness_growth_factor", 1.0, "staleness_growth"),
    ("telemetry.alerts_hbm_headroom_frac", -0.1, "hbm_headroom_frac"),
    ("telemetry.alerts_retrace_storm", 0, "retrace_storm"),
])
def test_config_validates_pillar_knobs(knob, value, match):
    with pytest.raises(ValueError, match=match):
        Config().replace(**{knob: value})


# ---------------------------------------------------------------------------
# logparse + inspector


def test_alerts_series_partial_line_tolerance(tmp_path):
    from r2d2_tpu.tools.logparse import alerts_series
    path = tmp_path / "alerts_player0.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"t": 1.0, "training_steps": 5, "env_steps": 50,
                            "rule": "hot", "severity": "crit",
                            "value": 12.0, "bound": 10.0}) + "\n")
        f.write('{"t": 2.0, "rule": "tr')          # writer mid-append
    s = alerts_series(str(path))
    assert s["rule"] == ["hot"] and s["t"] == [1.0]
    assert s["severity"] == ["crit"] and s["bound"] == [10.0]


def test_resources_series_aligned_on_carrying_records():
    from r2d2_tpu.tools.logparse import resources_series
    records = [
        {"t": 1.0},                                 # pre-PR7 record: skipped
        {"t": 2.0, "training_steps": 10, "resources": {
            "devices": [{"id": 0, "bytes_in_use": 100},
                        {"id": 1, "bytes_in_use": 50}],
            "hbm_headroom_frac_min": 0.4,
            "host": {"rss_bytes": 777, "cpu_pct": 55.0},
            "buffers_total": 640,
            "compile": {"compiles_total": 3, "compile_time_s_total": 1.5,
                        "retraces_total": 1}},
         "alerts": {"active": ["hbm_headroom"], "fired": []}},
    ]
    s = resources_series(records)
    assert s["t"] == [2.0]
    assert s["bytes_in_use"] == [150]
    assert s["hbm_headroom"] == [0.4]
    assert s["host_rss"] == [777]
    assert s["retraces"] == [1]
    assert s["alerts_active"] == [1]


def test_render_record_anakin_mode_and_panels():
    from r2d2_tpu.tools.inspect import render_record
    record = {"t": 10.0, "env_steps": 1000, "training_steps": 50,
              "buffer_size": 500, "buffer_speed": 100.0,
              "training_speed": 5.0,
              "stages": {"actor/act_scan":
                         {"count": 5, "p50_ms": 1.0, "p95_ms": 2.0,
                          "p99_ms": 3.0}},
              "actor_restarts": 3,     # stale default keys must NOT render
              "resources": {"devices": [], "host": {"rss_bytes": 1 << 30},
                            "buffers": {"p0/anakin_carry": 1 << 20},
                            "buffers_total": 1 << 20},
              "alerts": {"active": [], "fired": []}}
    frame = render_record(record)
    assert "on-device (anakin" in frame
    assert "health:" not in frame              # no fleet panel on anakin
    assert "actor/act_scan" in frame
    assert "anakin_carry" in frame
    assert "alerts: none active" in frame
    # a fleet record still renders its health panel
    fleet = dict(record)
    del fleet["stages"]
    frame2 = render_record(fleet)
    assert "health: restarts=3" in frame2


def test_render_alerts_fired():
    from r2d2_tpu.tools.inspect import render_alerts
    out = render_alerts({"active": ["retrace_storm"],
                         "fired": [{"rule": "retrace_storm",
                                    "severity": "crit", "value": 5.0,
                                    "bound": 3.0}]})
    assert "ACTIVE: retrace_storm" in out
    assert "FIRED CRIT retrace_storm" in out


# ---------------------------------------------------------------------------
# sentinel CLI


def test_sentinel_replay_exit_codes(tmp_path):
    from r2d2_tpu.tools.sentinel import main
    path = tmp_path / "metrics_player0.jsonl"
    clean = [{"t": float(i), "buffer_speed": 100.0, "training_speed": 5.0}
             for i in range(4)]
    with open(path, "w") as f:
        for r in clean:
            f.write(json.dumps(r) + "\n")
    assert main(["--dir", str(tmp_path)]) == 0
    # a NaN record makes the replay exit nonzero (crit rule fired)
    with open(path, "a") as f:
        f.write(json.dumps({"t": 9.0, "learning":
                            {"nonfinite_steps": 2}}) + "\n")
    assert main(["--dir", str(tmp_path)]) == 1
    assert main(["--dir", str(tmp_path / "nowhere")]) == 2


def test_sentinel_replay_detects_throughput_collapse(tmp_path):
    from r2d2_tpu.tools.sentinel import build_engine, replay_stream
    records = [{"buffer_speed": 100.0 + i} for i in range(8)]
    records.append({"buffer_speed": 10.0})          # collapse vs median
    engine = build_engine()
    summary = replay_stream(records, engine, emit=lambda s: None)
    assert summary["by_rule"] == {"env_throughput_drop": 1}
    assert summary["crit"] == 1


def test_sentinel_override_changes_bounds(tmp_path):
    from r2d2_tpu.tools.sentinel import build_engine
    eng = build_engine({"telemetry.alerts_retrace_storm": 9})
    assert {r.name: r for r in eng.rules}["retrace_storm"].bound == 9.0


# ---------------------------------------------------------------------------
# regress gate


def _fake_artifact(env=1000.0, ratio=1.05):
    return {"metric": "e2e_throughput",
            "e2e_resources_ab": {
                "resources_on": {"env_steps_per_sec": env,
                                 "learner_steps_per_sec": env / 100.0,
                                 "seconds": 30.0},
                "env_steps_ratio": ratio,
                "env_steps_per_sec_cells": {"on": [env, env]},
                "config": {"replay.capacity": 1}}}


def test_regress_extracts_watched_metrics():
    from r2d2_tpu.tools.regress import extract_metrics
    m = extract_metrics(_fake_artifact())
    assert m["e2e_resources_ab.resources_on.env_steps_per_sec"] == 1000.0
    assert m["e2e_resources_ab.env_steps_ratio"] == 1.05
    assert not any("seconds" in k for k in m)       # unwatched scalar
    assert not any("cells" in k for k in m)         # lists skipped
    assert not any("config" in k for k in m)        # config skipped
    # stale last-good re-emissions never become gates
    assert extract_metrics({"value": 5.0, "stale": True}) == {}


def test_regress_gate_passes_unmodified_fails_20pct_drop(tmp_path):
    """ACCEPTANCE: the gate passes against a baseline snapshotted from
    the same artifacts, and fails on a synthetic 20% throughput
    regression fixture."""
    from r2d2_tpu.tools.regress import main
    base = tmp_path / "BASELINE.json"
    base.write_text(json.dumps({"metric": "x"}))
    art = tmp_path / "E2E_r99.json"
    art.write_text(json.dumps(_fake_artifact(env=1000.0)))
    argv = ["--baseline", str(base), "--dir", str(tmp_path)]
    assert main(argv + ["--update"]) == 0
    assert main(argv) == 0                           # unmodified tree
    # synthetic 20% throughput regression: must fail
    art.write_text(json.dumps(_fake_artifact(env=800.0)))
    assert main(argv) == 1
    # recovery + improvement: passes (higher is never a failure)
    art.write_text(json.dumps(_fake_artifact(env=1400.0)))
    assert main(argv) == 0
    # a vanished metric fails too (the silent way out)
    art.write_text(json.dumps({"metric": "x"}))
    assert main(argv) == 1


def test_regress_tolerance_table():
    from r2d2_tpu.tools.regress import metric_tolerance
    assert metric_tolerance("a.env_steps_ratio") == 0.10   # medians: tight
    assert metric_tolerance("a.env_steps_per_sec") == 0.15
    assert metric_tolerance("a.b.speedup_vs_scalar") == 0.15
    assert metric_tolerance("whatever", override=0.3) == 0.3


def test_regress_no_bench_section_is_usage_error(tmp_path):
    from r2d2_tpu.tools.regress import main
    base = tmp_path / "BASELINE.json"
    base.write_text(json.dumps({"metric": "x"}))
    assert main(["--baseline", str(base), "--dir", str(tmp_path)]) == 2
    assert main(["--baseline", str(tmp_path / "none.json")]) == 2


# ---------------------------------------------------------------------------
# e2e slices


def test_retrace_storm_alert_end_to_end(tmp_path):
    """ACCEPTANCE (retrace storm): an induced post-warm-up retrace storm
    — one jitted fn recompiled at churning shapes — lands in the record's
    compile block and fires the retrace_storm alert into
    alerts_player{p}.jsonl exactly once while the storm lasts."""
    import jax
    import jax.numpy as jnp

    from r2d2_tpu.runtime.metrics import TrainMetrics
    from r2d2_tpu.telemetry.compile import CompileMonitor
    from r2d2_tpu.telemetry.resources import ResourceMonitor

    mon = CompileMonitor().install()
    try:
        def stormy(x):
            return jnp.tanh(x) * 3.0

        f = jax.jit(stormy)
        f(jnp.ones((2,)))
        mon.mark_warm()

        m = TrainMetrics(0, str(tmp_path))
        res = ResourceMonitor(0, str(tmp_path), interval_s=0.0,
                              compile_monitor=mon, stats_fn=lambda d: {})
        m.set_resources(res.block)
        path = str(tmp_path / "alerts_player0.jsonl")
        m.set_sentinel(AlertEngine(default_rules(Config().telemetry),
                                   jsonl_path=path))
        record = m.log(1.0)                        # healthy interval
        assert record["alerts"]["fired"] == []

        for n in (3, 5, 7, 9):                     # the storm: 4 retraces
            f(jnp.ones((n,)))
        record = m.log(1.0)
        assert record["resources"]["compile"]["retraces_interval"] >= 3
        assert "retrace_storm" in [a["rule"]
                                   for a in record["alerts"]["fired"]]
        assert "stormy" in record["resources"]["compile"][
            "last_retrace"]["fn"]
        # storm continues: still active, but only ONE fired line so far
        f(jnp.ones((11,)))
        f(jnp.ones((13,)))
        f(jnp.ones((15,)))
        record = m.log(1.0)
        assert "retrace_storm" in record["alerts"]["active"]
        rows = [json.loads(l) for l in open(path)]
        assert [r["rule"] for r in rows] == ["retrace_storm"]
    finally:
        mon.uninstall()


@pytest.mark.slow
def test_chaos_hang_raises_actor_stall_alert_exactly_once(tmp_path):
    """ACCEPTANCE (chaos slice): a hang injected into one process-mode
    actor (``1:hang@block=1``) — the watchdog detects it, the hang
    counter reaches the periodic record, and the sentinel fires the
    ``actor_stall`` alert into alerts_player0.jsonl EXACTLY once (counter
    edge semantics: one hang, one alert). The resources block flows in
    the same run — per-actor-slot RSS aggregated off the telemetry board
    from real worker processes."""
    from r2d2_tpu.runtime.orchestrator import train

    records = []
    cfg = tiny_config(tmp_path, **{
        "actor.num_actors": 2,
        # wedges on its 1st emit — during warm-up, BEFORE the first
        # periodic record, which therefore already carries the count;
        # the zero-baseline counter semantics make that an edge too
        "actor.fault_spec": "1:hang@block=1",
        "runtime.save_interval": 0, "runtime.log_interval": 1.0,
        "runtime.supervise_interval_s": 0.5,
        "runtime.hang_timeout_s": 3.0,
        "runtime.hang_spawn_grace_s": 150.0,
        "runtime.restart_backoff_base_s": 0.5,
        "runtime.restart_backoff_max_s": 2.0,
        # one detection, no respawn loop: the respawned slot would hang
        # again and fire a SECOND legitimate stall alert
        "runtime.restart_dead_actors": False,
        "telemetry.resources_interval_s": 1.0,
    })
    stacks = train(cfg, max_training_steps=10**9, max_seconds=60,
                   actor_mode="process", log_fn=records.append)
    st = stacks[0]
    assert st.health.hangs_detected == 1
    hang_recs = [r for r in records if r["actor_hangs_detected"] >= 1]
    assert hang_recs, "hang counter never reached the metrics records"
    # the alert stream: actor_stall exactly once
    rows = [json.loads(l)
            for l in open(os.path.join(str(tmp_path),
                                       "alerts_player0.jsonl"))]
    stalls = [r for r in rows if r["rule"] == "actor_stall"]
    assert len(stalls) == 1, rows
    assert stalls[0]["severity"] == "crit"
    assert stalls[0]["delta"] == 1.0
    # machine-side evidence from the same run: resources block with the
    # board-aggregated per-slot RSS of the real actor processes
    withres = [r for r in records if r.get("resources")]
    assert withres
    slot_rss = [r["resources"].get("actor_slots", {}).get("rss_bytes")
                for r in withres]
    assert any(rss and max(rss) > 0 for rss in slot_rss), \
        "actor-slot RSS never aggregated off the board"
