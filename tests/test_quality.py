"""Policy-quality observability plane tests (ISSUE 20): the calibration
join vs a per-row python reference, the QualityStats interval/aggregation
semantics, shadow scoring that NEVER mutates live serving state, the
gated canary promotion state machine (stage/refuse/promote/rollback +
persistence across a process restart), record-schema stability under the
kill switch, pre-PR20 config round-trips, and the three quality alert
rules (in-run + their tower twins)."""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from r2d2_tpu.config import Config
from r2d2_tpu.telemetry.quality import (QualityLedger, QualityStats,
                                        calibration_join,
                                        make_calibration_feed)


def small_cfg(**overrides) -> Config:
    cfg = Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "runtime.save_interval": 0,
    })
    return cfg.replace(**overrides) if overrides else cfg


# ---------------------------------------------------------------------------
# Q-calibration: the join math vs a per-row python reference


def test_calibration_join_matches_reference(rng):
    """calibration_join's vectorized window math equals the obvious
    per-row python loop for every (t, n_steps) combination, including
    windows that shorten at the episode tail."""
    T, A = 17, 6
    qvals = rng.standard_normal((T + 1, A)).astype(np.float32)
    rewards = rng.standard_normal(T).astype(np.float32)
    for n_steps in (1, 3, 5, T + 4):       # incl. n > T (all-tail windows)
        gamma = 0.97
        pred, realized = calibration_join(qvals, rewards, gamma, n_steps)
        assert pred.shape == realized.shape == (T,)
        maxq = qvals.astype(np.float64).max(axis=1)
        for t in range(T):
            m = min(max(n_steps, 1), T - t)
            ref = sum(gamma ** i * float(rewards[t + i]) for i in range(m))
            ref += gamma ** m * maxq[t + m]
            assert abs(realized[t] - ref) < 1e-9, (t, n_steps)
            assert pred[t] == maxq[t]


def test_calibration_join_terminal_bootstrap(rng):
    """A zero bootstrap row (LocalBuffer's termination convention) makes
    the tail window a pure discounted reward sum — no explicit terminal
    flag needed."""
    T = 5
    qvals = rng.standard_normal((T + 1, 4)).astype(np.float32)
    qvals[-1] = 0.0
    rewards = np.ones(T, np.float32)
    gamma = 0.5
    _, realized = calibration_join(qvals, rewards, gamma, n_steps=T)
    ref = sum(gamma ** i for i in range(T))     # bootstrap term is 0
    assert abs(realized[0] - ref) < 1e-9
    with pytest.raises(ValueError, match="qvals rows"):
        calibration_join(qvals[:-1], rewards, gamma, 3)


def test_calibration_feed_sampling_and_stamp():
    """The LocalBuffer tap samples every Nth finished block and joins
    the feeding actor's adopted publish stamp onto the signal."""
    stats = QualityStats()
    stamps = iter(range(10, 20))
    feed = make_calibration_feed(stats, gamma=0.99, n_steps=3,
                                 sample_every=2,
                                 stamp_fn=lambda: next(stamps))
    q = np.ones((6, 4), np.float32)
    r = np.zeros(5, np.float32)
    for _ in range(5):
        feed(q, r)
    block = stats.interval_block()["calibration"]
    # blocks 2 and 4 of 5 sampled, 5 rows each
    assert block["samples"] == 10 == block["samples_total"]
    assert block["stamp"] == 11                 # second stamp_fn draw
    # gap = pred - realized = 1 - gamma^m (m shortens at the tail:
    # 3,3,3,2,1 over the 5 rows); the abs max is the full-window row
    gaps = [1.0 - 0.99 ** m for m in (3, 3, 3, 2, 1)]
    assert abs(block["gap_mean"] - np.mean(gaps)) < 1e-9
    assert abs(block["gap_abs_max"] - max(gaps)) < 1e-9


# ---------------------------------------------------------------------------
# QualityStats: interval consumption + per-scenario eval aggregation


def test_quality_stats_interval_semantics():
    s = QualityStats()
    empty = s.interval_block()
    assert empty["calibration"]["samples"] == 0
    assert empty["calibration"]["gap_mean"] is None
    assert empty["shadow"]["divergence"] is None    # None HOLDS the rules
    assert empty["promotion"]["state"] == "idle"
    s.on_calibration(4, 2.0, 0.9)
    s.on_shadow(8, 6, dq_max=0.5)
    s.on_shadow(2, 2, dropped=3)
    b = s.interval_block()
    assert b["calibration"]["samples"] == 4
    assert abs(b["calibration"]["gap_mean"] - 0.5) < 1e-9
    sh = b["shadow"]
    assert sh["requests"] == 10 and sh["dropped"] == 3
    assert abs(sh["agree_frac"] - 0.8) < 1e-9
    assert abs(sh["divergence"] - 0.2) < 1e-9
    assert sh["dq_max"] == 0.5 and sh["mirrored_total"] == 10
    # consumed: next interval is clean, but cumulative totals persist
    b2 = s.interval_block()
    assert b2["shadow"]["requests"] == 0
    assert b2["calibration"]["samples_total"] == 4
    assert b2["shadow"]["mirrored_total"] == 10


def test_quality_stats_eval_aggregation_and_lineage():
    """Per-scenario rows aggregate episode-weighted; the eval snapshot
    PERSISTS across intervals (the drop rule needs a value series) and
    carries checkpoint lineage."""
    s = QualityStats()
    rows = [{"scenario": "eps0", "episodes": 3, "mean_return": 10.0},
            {"scenario": "eps5", "episodes": 1, "mean_return": 2.0}]
    s.on_eval(rows, step=700, publish_stamp=9, parent_stamp=4)
    for _ in range(2):                          # persists across intervals
        ev = s.interval_block()["eval"]
        assert ev["evals_total"] == 1
        assert abs(ev["mean_return"] - 8.0) < 1e-9   # (3*10 + 1*2) / 4
        assert ev["checkpoint_step"] == 700
        assert ev["publish_stamp"] == 9 and ev["parent_stamp"] == 4
        assert [r["scenario"] for r in ev["scenarios"]] == ["eps0", "eps5"]


# ---------------------------------------------------------------------------
# shadow scoring: mirrored traffic never touches the live path


def _req(req_id, kind=None):
    from r2d2_tpu.serve.transport import KIND_STEP, Request
    return Request(client_id=1, req_id=req_id,
                   kind=KIND_STEP if kind is None else kind,
                   op_seq=req_id, reply_to=f"ring-{req_id}")


def _rep(req_id, q):
    from r2d2_tpu.serve.transport import Reply
    return Reply(req_id=req_id, action=int(np.argmax(q)),
                 q=np.asarray(q, np.float32))


class _EchoChannel:
    """A candidate channel that records what it was asked and answers
    with a fixed q-vector — enough to prove the scorer sends COPIES."""

    def __init__(self, q):
        self.q = np.asarray(q, np.float32)
        self.seen = []

    def request_many(self, reqs, timeout=None):
        self.seen.extend(reqs)
        return {r.req_id: _rep(r.req_id, self.q) for r in reqs}


def test_shadow_scorer_never_mutates_live():
    """The mirror side effects stop at the scorer: live Request/Reply
    objects are unchanged field-for-field, the candidate sees COPIES
    with reply_to stripped, and candidate replies are never handed
    back toward clients (divergence is observable only via stats)."""
    from r2d2_tpu.fleet.promotion import ShadowScorer
    stats = QualityStats()
    live_q = [0.1, 0.9, 0.0]
    cand = _EchoChannel([0.9, 0.1, 0.0])        # argmax flipped: diverges
    scorer = ShadowScorer(cand, stats, sample_rate=1.0, seed=0)
    reqs = [_req(i) for i in range(6)]
    replies = {r.req_id: _rep(r.req_id, live_q) for r in reqs}
    frozen = {rid: dataclasses.replace(rep) for rid, rep in replies.items()}
    scorer.mirror(reqs, replies)
    assert scorer.process_pending() == 6
    # live replies bit-unchanged, and still the LIVE policy's answers
    for rid, rep in replies.items():
        assert rep.action == frozen[rid].action == 1
        np.testing.assert_array_equal(rep.q, frozen[rid].q)
    # the candidate was driven with copies: reply_to stripped, live
    # request objects untouched
    assert all(c.reply_to == "" for c in cand.seen)
    assert all(r.reply_to == f"ring-{r.req_id}" for r in reqs)
    assert scorer.divergence() == 1.0
    assert stats.interval_block()["shadow"]["divergence"] == 1.0


def test_shadow_scorer_sampling_drops_and_errors():
    from r2d2_tpu.fleet.promotion import ShadowScorer
    from r2d2_tpu.serve.transport import KIND_BOOTSTRAP, STATUS_EXPIRED

    stats = QualityStats()

    class _Boom:
        def request_many(self, reqs, timeout=None):
            raise RuntimeError("candidate down")

    # non-step and non-OK live pairs never enqueue
    scorer = ShadowScorer(_Boom(), stats, sample_rate=1.0)
    bad_rep = _rep(0, [1.0, 0.0])
    bad_rep.status = STATUS_EXPIRED
    scorer.mirror([_req(0), _req(1, kind=KIND_BOOTSTRAP)],
                  {0: bad_rep, 1: _rep(1, [1.0, 0.0])})
    assert scorer.mirrored == 0
    # overflow of the bounded queue is counted, never blocks
    scorer2 = ShadowScorer(_Boom(), stats, sample_rate=1.0, max_queue=2)
    reqs = [_req(i) for i in range(5)]
    scorer2.mirror(reqs, {r.req_id: _rep(r.req_id, [1.0, 0.0])
                          for r in reqs})
    assert scorer2.dropped == 3 and scorer2.mirrored == 5
    # a dead candidate is an error count, not an exception on the drain
    assert scorer2.process_pending() == 0
    assert scorer2.errors == 1
    assert stats.interval_block()["shadow"]["dropped"] == 3


# ---------------------------------------------------------------------------
# promotion state machine


def _tree(seed, shape=(3, 2)):
    rng = np.random.default_rng(seed)
    return {"params": {"head": rng.standard_normal(shape)
                       .astype(np.float32)}}


def _trees_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    return (ta == tb and len(la) == len(lb)
            and all(np.asarray(x).dtype == np.asarray(y).dtype
                    and np.array_equal(np.asarray(x), np.asarray(y))
                    for x, y in zip(la, lb)))


def _promo(tmp_path, n_consumers=8, degree=2):
    from r2d2_tpu.fleet.fanout import FanoutTree
    from r2d2_tpu.fleet.promotion import PromotionManager
    from r2d2_tpu.runtime.weights import InProcWeightStore
    cfg = small_cfg()
    live = _tree(0)
    store = InProcWeightStore(live)
    fanout = FanoutTree(store, n_consumers=n_consumers, degree=degree)
    fanout.pump()
    mgr = PromotionManager(cfg.fleet, store, fanout=fanout,
                           save_dir=str(tmp_path))
    return cfg, live, store, fanout, mgr


def test_promotion_refuse_promote_rollback_round_trip(tmp_path):
    """The full lifecycle on real store + fan-out plumbing: a refused
    canary never touches the root, a promotion is ONE root publish that
    every consumer adopts, and rollback restores the previous bundle
    bit-identically."""
    cfg, live, store, fanout, mgr = _promo(tmp_path)
    corrupt, healthy = _tree(1), _tree(2)

    staged = mgr.stage(corrupt, stamp=77)
    assert staged["previous_stamp"] == 1        # construction publication
    assert staged["canary_consumers"] == [6, 7]     # high-slot leaf relay
    # canary scoped: covered slots read the candidate, others the live
    for c in range(8):
        cur = fanout.endpoints(c)[2]()
        assert _trees_equal(cur, corrupt if c >= 6 else live), c
    ok, gates = mgr.decide(candidate_return=1.0, live_return=1.0,
                           shadow_divergence=0.9, shadow_requests=64)
    assert not ok and not gates["shadow"]["ok"] and gates["eval_return"]["ok"]
    mgr.refuse(gates)
    assert store.publish_count == 1 and mgr.root_publishes == 0
    for c in range(8):                          # canary cleared to live
        assert _trees_equal(fanout.endpoints(c)[2](), live)

    mgr.stage(healthy, stamp=88)
    ok, _ = mgr.decide(candidate_return=1.0, live_return=1.0,
                       calibration_gap=0.5, shadow_divergence=0.0,
                       shadow_requests=64)
    assert ok
    before = (store.publish_count, mgr.root_publishes)
    assert mgr.promote() == 88
    assert (store.publish_count, mgr.root_publishes) == (before[0] + 1,
                                                         before[1] + 1)
    for c in range(8):                          # fleet-wide adoption
        assert _trees_equal(fanout.endpoints(c)[2](), healthy)

    assert mgr.rollback() == 1                  # the retained stamp
    assert _trees_equal(store.current(), live)
    for c in range(8):
        assert _trees_equal(fanout.endpoints(c)[2](), live)
    blk = mgr.block()
    assert blk["state"] == "rolled_back"
    assert (blk["promotions"], blk["rollbacks"], blk["refusals"]) == (1, 1, 1)


def test_promotion_gates_fail_closed():
    """Eval and shadow gates refuse on MISSING evidence; the calibration
    gate fails open with no stream but bounds a present one."""
    from r2d2_tpu.fleet.promotion import PromotionManager
    from r2d2_tpu.runtime.weights import InProcWeightStore
    cfg = small_cfg()
    mgr = PromotionManager(cfg.fleet, InProcWeightStore(_tree(0)))
    ok, gates = mgr.decide(candidate_return=None, live_return=1.0,
                           shadow_divergence=0.0, shadow_requests=64)
    assert not ok and not gates["eval_return"]["ok"]
    ok, gates = mgr.decide(candidate_return=1.0, live_return=1.0,
                           shadow_divergence=None, shadow_requests=0)
    assert not ok and not gates["shadow"]["ok"]
    ok, gates = mgr.decide(candidate_return=1.0, live_return=1.0,
                           calibration_gap=None, shadow_divergence=0.0,
                           shadow_requests=cfg.fleet.promotion_min_shadow)
    assert ok and gates["calibration"]["ok"]
    ok, gates = mgr.decide(
        candidate_return=1.0, live_return=1.0,
        calibration_gap=cfg.fleet.promotion_calibration_bound + 1,
        shadow_divergence=0.0,
        shadow_requests=cfg.fleet.promotion_min_shadow)
    assert not ok and not gates["calibration"]["ok"]
    # the tolerance band: slightly-worse passes, clearly-worse refuses
    tol = cfg.fleet.promotion_return_tolerance
    assert mgr.decide(candidate_return=1.0 - tol / 2, live_return=1.0,
                      shadow_divergence=0.0, shadow_requests=64)[0]
    assert not mgr.decide(candidate_return=1.0 - 2 * tol, live_return=1.0,
                          shadow_divergence=0.0, shadow_requests=64)[0]


def test_promotion_state_guards(tmp_path):
    from r2d2_tpu.fleet.promotion import PromotionManager
    from r2d2_tpu.runtime.weights import InProcWeightStore
    cfg = small_cfg()
    mgr = PromotionManager(cfg.fleet, InProcWeightStore(_tree(0)))
    with pytest.raises(RuntimeError, match="no staged candidate"):
        mgr.promote()
    with pytest.raises(RuntimeError, match="no staged candidate"):
        mgr.refuse()
    with pytest.raises(RuntimeError, match="nothing retained"):
        mgr.rollback()
    mgr.stage(_tree(1))
    with pytest.raises(RuntimeError, match="already staged"):
        mgr.stage(_tree(2))


def test_promotion_persists_across_restart(tmp_path):
    """The retained previous bundle + counters survive the process: a
    FRESH manager on the same save_dir can still roll back
    bit-identically after a promote-then-crash."""
    from r2d2_tpu.fleet.promotion import PromotionManager
    from r2d2_tpu.runtime.weights import InProcWeightStore
    cfg, live = small_cfg(), _tree(0)
    store = InProcWeightStore(live)
    mgr = PromotionManager(cfg.fleet, store, save_dir=str(tmp_path))
    mgr.stage(_tree(2), stamp=55)
    assert mgr.promote() == 55

    mgr2 = PromotionManager(cfg.fleet, store, save_dir=str(tmp_path))
    assert mgr2.state == "promoted" and mgr2.promotions == 1
    blk = mgr2.block()
    assert blk["candidate_stamp"] == 55 and blk["previous_stamp"] == 1
    assert mgr2.rollback() == 1
    assert _trees_equal(store.current(), live)


# ---------------------------------------------------------------------------
# record schema + ledger stream + config


def test_record_schema_stable_without_quality(tmp_path):
    """No provider attached (quality_enabled off, the default): the
    record carries no 'quality' key — byte-identical to the PR-19
    schema — and no ledger file exists."""
    from r2d2_tpu.runtime.metrics import TrainMetrics
    m = TrainMetrics(0, str(tmp_path))
    record = m.log(1.0)
    assert "quality" not in record
    assert "quality" not in json.dumps(record)
    assert not list(tmp_path.glob("quality_player*.jsonl"))
    m2 = TrainMetrics(1, str(tmp_path))
    stats = QualityStats()
    m2.set_quality(QualityLedger(stats, str(tmp_path), 1).interval_block)
    record2 = m2.log(1.0)
    assert set(record2["quality"]) == {"calibration", "eval", "shadow",
                                       "promotion"}


def test_quality_ledger_rows(tmp_path):
    """One ledger row per interval: the proc identity header + clock
    anchor (the tower's join key), the quality block, and top-level
    lineage — and the sentinel's engine evaluates the stream directly."""
    from r2d2_tpu.tools.sentinel import build_engine, replay_stream
    stats = QualityStats()
    ledger = QualityLedger(stats, str(tmp_path), 0)
    stats.on_eval([{"scenario": "eps0", "episodes": 2, "mean_return": 7.0}],
                  step=300, publish_stamp=5, parent_stamp=2)
    stats.on_shadow(40, 10)                     # divergence 0.75: crit
    ledger.interval_block()
    rows = [json.loads(line) for line in
            open(os.path.join(str(tmp_path), "quality_player0.jsonl"))]
    assert len(rows) == 1 and ledger.write_errors == 0
    row = rows[0]
    assert row["proc"]["plane"] == "quality" and "t" in row
    assert "clock_anchor" in row["proc"]        # the tower's join key
    assert row["lineage"] == {"step": 300, "publish_stamp": 5,
                              "parent_stamp": 2}
    fired = []
    summary = replay_stream(rows, build_engine(),
                            emit=lambda line: fired.append(line))
    assert summary["crit"] == 1                 # canary_divergence
    assert any("canary_divergence" in line for line in fired)


def test_config_round_trip_pre_pr20():
    # pre-PR20 dicts (no quality/promotion knobs) load with defaults
    d = Config().to_dict()
    for key in ("quality_enabled", "quality_eval_interval_s",
                "quality_eval_rounds", "quality_eval_clients",
                "quality_calib_sample_every", "alerts_quality_regression",
                "alerts_canary_divergence", "alerts_promotion_stall_s"):
        d["telemetry"].pop(key)
    d["serve"].pop("shadow_sample_rate")
    for key in ("promotion_return_tolerance", "promotion_calibration_bound",
                "promotion_divergence_bound", "promotion_min_shadow",
                "promotion_canary_frac"):
        d["fleet"].pop(key)
    cfg = Config.from_dict(d)
    assert cfg.telemetry.quality_enabled is False
    assert cfg.serve.shadow_sample_rate == 0.0
    assert cfg.fleet.promotion_canary_frac == 0.25
    # full round-trip with the plane on
    cfg_on = small_cfg(**{"telemetry.quality_enabled": True,
                          "serve.shadow_sample_rate": 0.5,
                          "fleet.promotion_min_shadow": 8})
    back = Config.from_json(cfg_on.to_json())
    assert back.telemetry.quality_enabled is True
    assert back.serve.shadow_sample_rate == 0.5
    assert back.fleet.promotion_min_shadow == 8
    with pytest.raises(ValueError, match="shadow_sample_rate"):
        small_cfg(**{"serve.shadow_sample_rate": 1.5})
    with pytest.raises(ValueError, match="quality_calib_sample_every"):
        small_cfg(**{"telemetry.quality_calib_sample_every": 0})


# ---------------------------------------------------------------------------
# alert rules: in-run + tower twins


def test_quality_alert_rules_fire_and_rearm():
    from r2d2_tpu.telemetry import AlertEngine, default_rules
    cfg = small_cfg()
    engine = AlertEngine(default_rules(cfg.telemetry))
    names = {r.name for r in engine.rules}
    assert {"quality_regression", "canary_divergence",
            "promotion_stall"} <= names

    def rec(div=None, age=None):
        return {"quality": {"shadow": {"divergence": div},
                            "promotion": {"age_s": age}}}

    # canary_divergence: crit on the bound, EDGE-fired exactly once
    assert engine.evaluate(rec(div=0.1))["fired"] == []
    fired = engine.evaluate(rec(div=0.9))["fired"]
    assert [a["rule"] for a in fired] == ["canary_divergence"]
    assert fired[0]["severity"] == "crit"
    # a shadow-free interval (None) HOLDS the breach — no refire
    held = engine.evaluate(rec(div=None))
    assert held["fired"] == [] and "canary_divergence" in held["active"]
    # recovery re-arms; the next breach fires again
    assert engine.evaluate(rec(div=0.0))["fired"] == []
    assert len(engine.evaluate(rec(div=0.9))["fired"]) == 1
    # promotion_stall rides the canary age (None outside canary = inert)
    stall = engine.evaluate(
        rec(age=cfg.telemetry.alerts_promotion_stall_s + 1))["fired"]
    assert [a["rule"] for a in stall] == ["promotion_stall"]

    # quality_regression: eval mean_return collapsing below the window
    # baseline fraction
    eng2 = AlertEngine(default_rules(cfg.telemetry))
    for _ in range(cfg.telemetry.alerts_window):
        assert all(a["rule"] != "quality_regression" for a in eng2.evaluate(
            {"quality": {"eval": {"mean_return": 10.0}}})["fired"])
    out = eng2.evaluate({"quality": {"eval": {"mean_return": 1.0}}})
    assert any(a["rule"] == "quality_regression" for a in out["fired"])


def test_tower_quality_twins():
    """The tower watches the same three signals via its derived
    worst-case join over quality_player*.jsonl rows."""
    from r2d2_tpu.telemetry.tower import TowerCollector, tower_rules
    cfg = small_cfg()
    names = {r.name for r in tower_rules(cfg)}
    assert {"tower_quality_regression", "tower_canary_divergence",
            "tower_promotion_stall"} <= names

    def qrow(ret, div, age):
        return {"quality": {"eval": {"mean_return": ret},
                            "shadow": {"divergence": div},
                            "promotion": {"age_s": age}}}

    derived = TowerCollector.derive(
        {"learner": [], "quality": [qrow(5.0, 0.1, 10.0),
                                    qrow(2.0, 0.6, 900.0)]})
    # worst-case across players: min return, max divergence, max age
    assert derived["quality_eval_return"] == 2.0
    assert derived["canary_divergence"] == 0.6
    assert derived["promotion_age_s"] == 900.0
    # no quality plane: none of the keys appear (rules stay inert)
    empty = TowerCollector.derive({"learner": [], "quality": []})
    assert not {"quality_eval_return", "canary_divergence",
                "promotion_age_s"} & set(empty)
