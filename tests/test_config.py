import pytest

from r2d2_tpu.config import Config, apex_epsilon, parse_overrides


def test_defaults_match_reference():
    cfg = Config()
    assert cfg.sequence.seq_len == 55
    assert cfg.replay.capacity == 500_000
    assert cfg.seqs_per_block == 40
    assert cfg.num_blocks == 1250
    assert cfg.num_sequences == 50_000
    assert cfg.env.obs_shape == (4, 84, 84)


def test_replace_dotted():
    cfg = Config().replace(**{"replay.capacity": 4000, "actor.num_actors": 8})
    assert cfg.replay.capacity == 4000
    assert cfg.actor.num_actors == 8
    # untouched sections preserved
    assert cfg.optim.lr == 1e-4


def test_parse_overrides_types():
    cfg = parse_overrides(
        Config(),
        ["--optim.lr=0.001", "--network.use_double=true", "--replay.batch_size=32"],
    )
    assert cfg.optim.lr == pytest.approx(1e-3)
    assert cfg.network.use_double is True
    assert cfg.replay.batch_size == 32


def test_parse_overrides_rejects_unknown():
    with pytest.raises(SystemExit):
        parse_overrides(Config(), ["--nope.lr=1"])
    with pytest.raises(SystemExit):
        parse_overrides(Config(), ["--optim.nope=1"])


def test_apex_epsilon_ladder():
    # eps_i = 0.4 ** (1 + 7*i/(N-1)): ref train.py:16-18
    n = 10
    eps = [apex_epsilon(i, n, 0.4, 7.0) for i in range(n)]
    assert eps[0] == pytest.approx(0.4)
    assert eps[-1] == pytest.approx(0.4**8)
    assert all(a > b for a, b in zip(eps, eps[1:]))
    assert apex_epsilon(0, 1, 0.4, 7.0) == pytest.approx(0.4)


def test_invalid_sizes_rejected():
    with pytest.raises(ValueError):
        Config().replace(**{"sequence.learning_steps": 15})  # 400 % 15 != 0
    with pytest.raises(ValueError):
        Config().replace(**{"replay.capacity": 500_100})


def test_bad_numeric_override_is_friendly():
    with pytest.raises(SystemExit):
        parse_overrides(Config(), ["--replay.batch_size=abc"])
    with pytest.raises(SystemExit):
        # python-tuple syntax is rejected with the triple-syntax hint
        parse_overrides(Config(), ["--network.conv_layers=((16,4,2),)"])


def test_conv_layers_cli_override():
    """Conv pyramids are CLI-settable as ';'-joined triples — needed to run
    small-frame configs (the Nature pyramid shrinks a 32x32 frame to 0) from
    the command line."""
    cfg = parse_overrides(Config(), ["--network.conv_layers=8,4,2;16,3,1"])
    assert cfg.network.conv_layers == ((8, 4, 2), (16, 3, 1))
    with pytest.raises(SystemExit):
        parse_overrides(Config(), ["--network.conv_layers=8,4;16,3,1"])
