"""Central policy inference service (ISSUE 13): micro-batcher
deadline/fill semantics, state-cache lease/evict/reconnect, local-vs-
server action parity, the transport ladder (in-proc + shm + socket),
serving record schema + serve_* alert rules, kill-switch schema
stability, chaos client faults, and the e2e/chaos slow slices."""

import queue
import threading
import time

import numpy as np
import pytest

from r2d2_tpu.config import Config

pytestmark = []


def small_cfg(**over):
    base = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "serve.max_batch": 4, "serve.deadline_ms": 2.0,
        "runtime.save_interval": 0,
    }
    base.update(over)
    return Config().replace(**base)


def tiny_net(cfg, action_dim=4):
    import jax

    from r2d2_tpu.models.network import NetworkApply
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    return net, net.init(jax.random.PRNGKey(0))


def make_server(cfg=None, **server_kw):
    from r2d2_tpu.serve import InprocEndpoint, PolicyServer
    cfg = cfg or small_cfg()
    net, params = tiny_net(cfg)
    ep = InprocEndpoint()
    srv = PolicyServer(cfg, net, params, endpoint=ep, **server_kw).start()
    return cfg, net, params, ep, srv


def rand_obs(rng, cfg):
    return rng.integers(0, 255, (cfg.env.frame_height,
                                 cfg.env.frame_width), np.uint8)


# ---------------------------------------------------------------------------
# micro-batcher semantics


def _pending(t_recv=None):
    from r2d2_tpu.serve import Request
    req = Request(client_id=0, req_id=0)
    req.t_recv = time.monotonic() if t_recv is None else t_recv
    return (req, lambda reply: None)


def test_collect_batch_dispatches_on_fill():
    from r2d2_tpu.serve import collect_batch
    inbox = queue.Queue()
    for _ in range(5):
        inbox.put(_pending())
    first = inbox.get()
    t0 = time.monotonic()
    batch = collect_batch(inbox, first, max_batch=4, deadline_s=10.0)
    # fills to max_batch immediately — never waits out a long deadline
    assert len(batch) == 4
    assert time.monotonic() - t0 < 1.0
    assert inbox.qsize() == 1                      # one left behind


def test_collect_batch_dispatches_on_deadline():
    from r2d2_tpu.serve import collect_batch
    inbox = queue.Queue()
    first = _pending()
    t0 = time.monotonic()
    batch = collect_batch(inbox, first, max_batch=8, deadline_s=0.08)
    elapsed = time.monotonic() - t0
    # a lone request goes out once the OLDEST (itself) ages out
    assert len(batch) == 1
    assert 0.04 <= elapsed < 2.0


def test_collect_batch_deadline_measured_from_arrival():
    from r2d2_tpu.serve import collect_batch
    inbox = queue.Queue()
    # the first request already waited its deadline out in the queue:
    # dispatch must be immediate, not deadline-from-now
    first = _pending(t_recv=time.monotonic() - 1.0)
    t0 = time.monotonic()
    batch = collect_batch(inbox, first, max_batch=8, deadline_s=0.5)
    assert len(batch) == 1
    assert time.monotonic() - t0 < 0.2


def test_collect_batch_early_dispatch_at_expected():
    """Once every connected client is represented (expected), the
    batcher stops WAITING — but still drains an immediately-pending
    burst up to max_batch."""
    from r2d2_tpu.serve import collect_batch
    inbox = queue.Queue()
    inbox.put(_pending())
    first = inbox.get()
    t0 = time.monotonic()
    batch = collect_batch(inbox, first, max_batch=8, deadline_s=5.0,
                          expected=1)
    assert len(batch) == 1
    assert time.monotonic() - t0 < 0.5              # no deadline wait
    # burst backlog: expected=2 reached, the rest drain without waiting
    for _ in range(5):
        inbox.put(_pending())
    first = inbox.get()
    t0 = time.monotonic()
    batch = collect_batch(inbox, first, max_batch=8, deadline_s=5.0,
                          expected=2)
    assert len(batch) == 5                          # 1 + all 4 pending
    assert time.monotonic() - t0 < 0.5


def test_serve_buckets():
    from r2d2_tpu.serve import serve_buckets
    assert serve_buckets(1) == [1]
    assert serve_buckets(8) == [1, 2, 4, 8]
    assert serve_buckets(12) == [1, 2, 4, 8, 12]


# ---------------------------------------------------------------------------
# state cache


def test_state_cache_lease_reconnect_evict():
    from r2d2_tpu.serve import StateCache
    c = StateCache(slots=4, shards=2, frame_hw=(8, 8), frame_stack=2,
                   hidden_dim=4, lease_timeout_s=10.0)
    slot, fresh = c.lease(7, now=0.0)
    assert fresh and c.connects == 1
    c.hidden[slot, 0, 0] = 3.5                      # mark the state
    again, fresh2 = c.lease(7, now=1.0)
    assert again == slot and not fresh2             # renewal, state kept
    assert c.release(7, now=2.0)
    # reconnect inside the lease window: SAME slot, state retained
    back, fresh3 = c.lease(7, now=5.0)
    assert back == slot and not fresh3
    assert c.hidden[slot, 0, 0] == 3.5
    assert c.reconnects == 1
    # disconnected past the timeout: swept, slot resets
    c.release(7, now=6.0)
    assert c.sweep(now=20.0) == 1
    assert c.evictions == 1
    slot2, fresh4 = c.lease(7, now=21.0)
    assert fresh4 and c.hidden[slot2].sum() == 0.0


def test_state_cache_full_shard_evicts_stalest():
    from r2d2_tpu.serve import StateCache
    c = StateCache(slots=4, shards=2, frame_hw=(8, 8), frame_stack=2,
                   hidden_dim=4, lease_timeout_s=1e9)
    # shard 0 owns even client ids (id % shards); fill its 2 slots
    c.lease(0, now=0.0)
    c.lease(2, now=1.0)
    c.release(0, now=2.0)                           # disconnected, stalest
    s4, fresh = c.lease(4, now=3.0)                 # full shard: evict
    assert fresh and c.evictions == 1
    # the disconnected lease went first; the connected one survived
    assert c.lease(2, now=4.0)[1] is False
    assert c.lease(0, now=5.0)[1] is True           # evicted = fresh again


def test_state_cache_mutation_parity_with_local_policy():
    """observe_reset / observe on a cache slot reproduce ActorPolicy's
    frame-stack math bit-for-bit."""
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.serve import StateCache
    cfg = small_cfg()
    net, params = tiny_net(cfg)
    local = ActorPolicy(net, params, 0.0, seed=0)
    c = StateCache(slots=2, shards=1, frame_hw=(24, 24), frame_stack=2,
                   hidden_dim=16)
    slot, _ = c.lease(0)
    rng = np.random.default_rng(0)
    obs = rand_obs(rng, cfg)
    local.observe_reset(obs)
    c.reset_slot(slot, obs)
    np.testing.assert_array_equal(c.stacked[slot], local.stacked)
    for t in range(3):
        nxt = rand_obs(rng, cfg)
        local.observe(nxt, t)
        c.observe(slot, nxt, t)
        np.testing.assert_array_equal(c.stacked[slot], local.stacked)
        assert c.last_action[slot] == local.last_action


# ---------------------------------------------------------------------------
# local-vs-server parity


def test_scalar_action_parity_exact():
    """At equal seeds and ε the served actor's action/Q/hidden stream is
    BIT-IDENTICAL to the local one's: the server runs the same shared
    forward program (make_forward_fn) on the same state math."""
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.serve import RemotePolicy
    cfg, net, params, ep, srv = make_server()
    try:
        local = ActorPolicy(net, params, 0.4, seed=7)
        remote = RemotePolicy(ep.connect(), net.action_dim, 0.4, seed=7)
        rng = np.random.default_rng(1)
        obs = rand_obs(rng, cfg)
        local.observe_reset(obs)
        remote.observe_reset(obs)
        for t in range(30):
            a1, q1, h1 = local.act()
            a2, q2, h2 = remote.act()
            assert a1 == a2
            np.testing.assert_array_equal(q1, q2)
            np.testing.assert_array_equal(h1, h2)
            if t == 10:
                np.testing.assert_array_equal(local.bootstrap_q(),
                                              remote.bootstrap_q())
            nxt = rand_obs(rng, cfg)
            local.observe(nxt, a1)
            remote.observe(nxt, a2)
        assert remote.weight_version == 0           # no weight service
    finally:
        srv.stop()


def test_vector_action_parity_exact():
    """N=4 lanes: the pipelined lanes fill one bucket-4 micro-batch —
    the identical (4, 1) program BatchedActorPolicy runs locally."""
    from r2d2_tpu.actor.policy import BatchedActorPolicy
    from r2d2_tpu.serve import RemoteBatchedPolicy
    cfg, net, params, ep, srv = make_server()
    try:
        eps = [0.4, 0.2, 0.1, 0.05]
        seeds = [3, 4, 5, 6]
        local = BatchedActorPolicy(net, params, eps, seeds)
        remote = RemoteBatchedPolicy(ep.connect(), net.action_dim, eps,
                                     seeds, client_base=0)
        rng = np.random.default_rng(2)
        for i in range(4):
            obs = rand_obs(rng, cfg)
            local.observe_reset_lane(i, obs)
            remote.observe_reset_lane(i, obs)
        for t in range(10):
            a1, q1, h1 = local.act()
            a2, q2, h2 = remote.act()
            np.testing.assert_array_equal(a1, a2)
            np.testing.assert_array_equal(q1, q2)
            np.testing.assert_array_equal(h1, h2)
            if t == 4:
                np.testing.assert_array_equal(local.bootstrap_q(),
                                              remote.bootstrap_q())
            nxt = np.stack([rand_obs(rng, cfg) for _ in range(4)])
            local.observe(nxt, a1)
            remote.observe(nxt, a2)
    finally:
        srv.stop()


def test_run_actor_block_stream_parity():
    """The whole loop: run_actor with a local policy vs a RemotePolicy
    on identically-seeded envs emits IDENTICAL blocks."""
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.actor_loop import make_actor_policy, run_actor
    cfg = small_cfg()
    cfg_srv = small_cfg(**{"actor.inference": "server"})
    _, net, params, ep, srv = make_server(cfg_srv)
    blocks = {"local": [], "server": []}
    try:
        for mode, c in (("local", cfg), ("server", cfg_srv)):
            env = create_env(c.env, seed=11)
            channel = ep.connect() if mode == "server" else None
            policy, run_loop = make_actor_policy(
                c, net, params, 0, seed=5, epsilon=0.3,
                serve_channel=channel)
            run_loop(c, env, policy, blocks[mode].append,
                     lambda: None, lambda: False, max_env_steps=60)
    finally:
        srv.stop()
    assert len(blocks["local"]) == len(blocks["server"]) > 0
    for lb, sb in zip(blocks["local"], blocks["server"]):
        for field in ("obs_row", "last_action_row", "hidden", "action",
                      "reward", "gamma", "priority", "learning_steps"):
            np.testing.assert_array_equal(
                np.asarray(getattr(lb, field)),
                np.asarray(getattr(sb, field)), err_msg=field)


def test_bootstrap_does_not_advance_state():
    from r2d2_tpu.serve import RemotePolicy
    cfg, net, params, ep, srv = make_server()
    try:
        remote = RemotePolicy(ep.connect(), net.action_dim, 0.0, seed=0)
        rng = np.random.default_rng(3)
        remote.observe_reset(rand_obs(rng, cfg))
        q1 = remote.bootstrap_q()
        q2 = remote.bootstrap_q()
        np.testing.assert_array_equal(q1, q2)       # no hidden advance
        _, q3, _ = remote.step()
        np.testing.assert_array_equal(q1, q3)       # first step: same state
        _, q4, _ = remote.step()                    # now hidden advanced
        assert not np.array_equal(q3, q4)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# batching under load + weight sync


def test_pipelined_lanes_fill_micro_batch():
    from r2d2_tpu.serve import RemoteBatchedPolicy
    cfg = small_cfg(**{"serve.max_batch": 8, "serve.deadline_ms": 50.0})
    _, net, params, ep, srv = make_server(cfg)
    try:
        remote = RemoteBatchedPolicy(ep.connect(), net.action_dim,
                                     [0.1] * 8, list(range(8)))
        rng = np.random.default_rng(4)
        for i in range(8):
            remote.observe_reset_lane(i, rand_obs(rng, cfg))
        for _ in range(5):
            remote.act()
        block = srv.stats.interval_block()
        assert block["batch"]["fill_mean"] > 4      # 8 lanes coalesce
        assert block["clients"]["active"] == 8
    finally:
        srv.stop()


def test_weight_sync_and_version_stamp():
    from r2d2_tpu.runtime.weights import InProcWeightStore
    from r2d2_tpu.serve import RemotePolicy
    cfg = small_cfg(**{"serve.weight_poll_interval_s": 0.01})
    net, params = tiny_net(cfg)
    store = InProcWeightStore(params)
    from r2d2_tpu.serve import InprocEndpoint, PolicyServer
    ep = InprocEndpoint()
    srv = PolicyServer(cfg, net, params, endpoint=ep,
                       weight_poll=lambda: store.poll("serve"),
                       weight_version=lambda: store.reader_version(
                           "serve")).start()
    try:
        remote = RemotePolicy(ep.connect(), net.action_dim, 0.0, seed=0)
        rng = np.random.default_rng(5)
        remote.observe_reset(rand_obs(rng, cfg))
        _, q_before, _ = remote.step()
        import jax
        new_params = jax.tree_util.tree_map(lambda x: x * 2.0, params)
        store.publish(new_params)
        deadline = time.monotonic() + 10.0
        while remote.weight_version < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
            remote.bootstrap_q()                    # no state advance
        assert remote.weight_version == 2           # stamped from replies
        q_after = remote.bootstrap_q()
        assert not np.array_equal(q_before, q_after)
    finally:
        srv.stop()


def test_expired_request_dropped_without_state_touch():
    from r2d2_tpu.serve import Reply, Request
    from r2d2_tpu.serve.transport import STATUS_EXPIRED
    cfg = small_cfg(**{"serve.request_ttl_s": 0.5})
    _, net, params, ep, srv = make_server(cfg)
    try:
        got = []
        event = threading.Event()
        # aged on the SERVER-side arrival stamp (t_recv — comparable
        # across hosts, unlike the client's t_submit monotonic): push
        # straight into the inbox with an old arrival time, the shape of
        # a backlog queued against a dead server
        req = Request(client_id=9, req_id=1, t_submit=time.monotonic())
        req.t_recv = time.monotonic() - 10.0
        ep.inbox.put((req, lambda r: (got.append(r), event.set())))
        assert event.wait(5.0)
        assert got[0].status == STATUS_EXPIRED
        assert srv.cache.leased_slots == 0          # state untouched
        assert isinstance(got[0], Reply)
    finally:
        srv.stop()


def test_duplicate_op_replays_cached_reply():
    """Idempotent RPC: a retried copy of an already-applied op (client
    timed out, reply lost) must NOT re-roll the frame stack or
    re-advance the hidden — the server replays the cached result."""
    from r2d2_tpu.serve import KIND_STEP, Request
    cfg, net, params, ep, srv = make_server()
    try:
        rng = np.random.default_rng(11)
        obs = rand_obs(rng, cfg)
        frame = rand_obs(rng, cfg)

        def ask(req):
            got = []
            event = threading.Event()
            ep.submit(req, lambda r: (got.append(r), event.set()))
            assert event.wait(5.0)
            return got[0]

        first = Request(client_id=5, req_id=100, kind=KIND_STEP, op_seq=1,
                        t_submit=time.monotonic(), reset_obs=obs)
        r1 = ask(first)
        # the retry: fresh req_id, SAME op_seq, same payload
        dup = Request(client_id=5, req_id=101, kind=KIND_STEP, op_seq=1,
                      t_submit=time.monotonic(), reset_obs=obs)
        r2 = ask(dup)
        assert r2.action == r1.action
        np.testing.assert_array_equal(r2.q, r1.q)
        np.testing.assert_array_equal(r2.hidden, r1.hidden)  # no advance
        # the NEXT logical op advances normally
        nxt = Request(client_id=5, req_id=102, kind=KIND_STEP, op_seq=2,
                      t_submit=time.monotonic(), obs=frame, action=r1.action)
        r3 = ask(nxt)
        assert not np.array_equal(r3.hidden, r1.hidden)
        # a stale copy OLDER than the applied horizon is never re-applied
        from r2d2_tpu.serve.transport import STATUS_EXPIRED
        stale = Request(client_id=5, req_id=103, kind=KIND_STEP, op_seq=1,
                        t_submit=time.monotonic(), reset_obs=obs)
        r4 = ask(stale)
        assert r4.status == STATUS_EXPIRED
        slot = srv.cache._leases[5 % srv.cache.shards][5]
        np.testing.assert_array_equal(srv.cache.hidden[slot],
                                      np.asarray(r3.hidden))
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# transports


def _native_available():
    try:
        from r2d2_tpu.native import ring_lib
        ring_lib()
        return True
    except Exception:
        return False


def test_shm_transport_roundtrip():
    if not _native_available():
        pytest.skip("native shm ring toolchain unavailable")
    from r2d2_tpu.serve import (InprocEndpoint, PolicyServer,
                                RemotePolicy, ShmServeChannel,
                                ShmServeTransport)
    cfg = small_cfg()
    net, params = tiny_net(cfg)
    ep = InprocEndpoint()
    transport = ShmServeTransport(
        ep.submit, (cfg.env.frame_height, cfg.env.frame_width),
        net.action_dim, cfg.network.hidden_dim, request_slots=16)
    srv = PolicyServer(cfg, net, params, endpoint=ep).start()
    try:
        channel = ShmServeChannel(transport.request_ring, net.action_dim,
                                  cfg.network.hidden_dim, reply_slots=4)
        remote = RemotePolicy(channel, net.action_dim, 0.0, seed=0,
                              client_id=3)
        rng = np.random.default_rng(6)
        remote.observe_reset(rand_obs(rng, cfg))
        a, q, h = remote.act()
        assert 0 <= a < net.action_dim
        assert q.shape == (net.action_dim,)
        assert h.shape == (2, cfg.network.hidden_dim)
        remote.close()
    finally:
        srv.stop()
        transport.close()


def test_shm_transport_full_stream_parity():
    if not _native_available():
        pytest.skip("native shm ring toolchain unavailable")
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.serve import (InprocEndpoint, PolicyServer,
                                RemotePolicy, ShmServeChannel,
                                ShmServeTransport)
    cfg = small_cfg()
    net, params = tiny_net(cfg)
    ep = InprocEndpoint()
    transport = ShmServeTransport(
        ep.submit, (cfg.env.frame_height, cfg.env.frame_width),
        net.action_dim, cfg.network.hidden_dim, request_slots=16)
    srv = PolicyServer(cfg, net, params, endpoint=ep).start()
    try:
        channel = ShmServeChannel(transport.request_ring, net.action_dim,
                                  cfg.network.hidden_dim, reply_slots=4)
        remote = RemotePolicy(channel, net.action_dim, 0.3, seed=9)
        local = ActorPolicy(net, params, 0.3, seed=9)
        rng = np.random.default_rng(7)
        obs = rand_obs(rng, cfg)
        local.observe_reset(obs)
        remote.observe_reset(obs)
        for _ in range(10):
            a1, q1, _ = local.act()
            a2, q2, _ = remote.act()
            assert a1 == a2
            np.testing.assert_array_equal(q1, q2)
            nxt = rand_obs(rng, cfg)
            local.observe(nxt, a1)
            remote.observe(nxt, a2)
        remote.close()
    finally:
        srv.stop()
        transport.close()


def test_socket_transport_roundtrip():
    from r2d2_tpu.serve import (InprocEndpoint, PolicyServer, RemotePolicy,
                                SocketChannel, SocketServerTransport)
    cfg = small_cfg()
    net, params = tiny_net(cfg)
    ep = InprocEndpoint()
    transport = SocketServerTransport(ep.submit, "127.0.0.1", 0)
    srv = PolicyServer(cfg, net, params, endpoint=ep).start()
    try:
        channel = SocketChannel(transport.host, transport.port)
        remote = RemotePolicy(channel, net.action_dim, 0.0, seed=0)
        rng = np.random.default_rng(8)
        remote.observe_reset(rand_obs(rng, cfg))
        a1, q1, _ = remote.act()
        a2, q2, _ = remote.act()
        assert q1.shape == q2.shape == (net.action_dim,)
        assert not np.array_equal(q1, q2)           # hidden advanced
        remote.close()
    finally:
        srv.stop()
        transport.close()


def test_server_restart_reconnect_inproc():
    """A dead server makes requests time out (backoff ladder, eventually
    ServeUnavailable); a replacement on the SAME endpoint picks the
    retried requests up — the chaos drill's mechanism, unit-sized."""
    from r2d2_tpu.serve import (PolicyServer, RemotePolicy, ServeUnavailable)
    cfg = small_cfg(**{"serve.request_timeout_s": 0.15,
                       "serve.request_ttl_s": 0.3})
    _, net, params, ep, srv = make_server(cfg)
    remote = RemotePolicy(ep.connect(), net.action_dim, 0.0, seed=0,
                          timeout_s=0.15, max_retry_s=1.0,
                          backoff_base_s=0.05, backoff_max_s=0.1)
    rng = np.random.default_rng(9)
    remote.observe_reset(rand_obs(rng, cfg))
    remote.step()
    srv.stop()
    with pytest.raises(ServeUnavailable):
        remote.step()
    assert remote.timeouts >= 1 and remote.reconnects >= 1
    srv2 = PolicyServer(cfg, net, params, endpoint=ep).start()
    try:
        remote.max_retry_s = 30.0
        remote.observe_reset(rand_obs(rng, cfg))    # resync state
        a, q, h = remote.step()
        assert q.shape == (net.action_dim,)
    finally:
        srv2.stop()


# ---------------------------------------------------------------------------
# serving record schema + alert rules


def test_serving_stats_interval_block_schema_and_consumption():
    from r2d2_tpu.serve import ServingStats
    s = ServingStats()
    assert s.interval_block() is None               # no traffic: no block
    s.on_requests(3)
    s.on_replies(3)
    s.on_request_latency(0.004)
    s.on_batch(3, hit_full=False, hit_deadline=True, starved=False)
    s.on_clients(connects=2, disconnects=1)
    s.active_clients = 2
    block = s.interval_block(deadline_ms=5.0, max_batch=32)
    assert block["requests"] == 3
    assert block["latency"]["count"] == 1
    assert block["batch"]["fill_mean"] == 3.0
    assert block["batch"]["deadline_frac"] == 1.0
    assert block["clients"] == {"active": 2, "connects": 2,
                                "reconnects": 0, "disconnects": 1,
                                "evictions": 0}
    assert block["deadline_ms"] == 5.0 and block["max_batch"] == 32
    assert s.interval_block() is None               # consumed
    s.on_clients(disconnects=1)
    s.on_requests(1)
    block2 = s.interval_block()
    assert block2["clients"]["disconnects"] == 2    # cumulative counter


def _record_with_serving(p99_ms=None, starved=None, disconnects=0):
    serving = {"latency": {"p99_ms": p99_ms},
               "batch": {"starved_frac": starved},
               "clients": {"disconnects": disconnects}}
    return {"t": 1.0, "buffer_speed": 100.0, "training_speed": 1.0,
            "serving": serving}


def test_serve_alert_rules_fire_and_rearm():
    from r2d2_tpu.telemetry.alerts import AlertEngine, default_rules
    engine = AlertEngine(default_rules(Config().telemetry))
    # healthy: nothing
    out = engine.evaluate(_record_with_serving(p99_ms=5.0))
    assert not out["fired"]
    # outage-shaped latency: SLO fires once, stays active, then re-arms
    out = engine.evaluate(_record_with_serving(p99_ms=5000.0))
    assert [a["rule"] for a in out["fired"]] == ["serve_latency_slo"]
    out = engine.evaluate(_record_with_serving(p99_ms=6000.0))
    assert not out["fired"]                         # level: edge only
    out = engine.evaluate(_record_with_serving(p99_ms=4.0))
    assert "serve_latency_slo" not in out["active"]
    out = engine.evaluate(_record_with_serving(p99_ms=5000.0))
    assert [a["rule"] for a in out["fired"]] == ["serve_latency_slo"]
    # starvation threshold (fires, then clears on a healthy interval)
    out = engine.evaluate(_record_with_serving(p99_ms=5.0, starved=0.99))
    assert [a["rule"] for a in out["fired"]] == ["serve_batch_starvation"]
    out = engine.evaluate(_record_with_serving(p99_ms=5.0, starved=0.1))
    assert "serve_batch_starvation" not in out["active"]
    # churn counter: cumulative jump >= bound fires once
    out = engine.evaluate(_record_with_serving(p99_ms=5.0, disconnects=4))
    assert [a["rule"] for a in out["fired"]] == ["serve_client_churn"]
    out = engine.evaluate(_record_with_serving(p99_ms=5.0, disconnects=4))
    assert not out["fired"]
    # a record WITHOUT the serving block neither fires nor re-activates
    # any serve rule (record_value -> None leaves level rules holding
    # their — here inactive — state)
    out = engine.evaluate({"t": 2.0, "buffer_speed": 100.0})
    assert not out["fired"]
    assert not any(r.startswith("serve") for r in out["active"])


def test_record_schema_identical_without_serving(tmp_path):
    """actor.inference='local' (nothing attached): the record must be
    byte-identical to the PR-11 schema — no 'serving' key, every
    pre-PR13 key intact (the kill-switch acceptance)."""
    from r2d2_tpu.runtime.metrics import TrainMetrics
    from tests.test_telemetry import PR23_RECORD_KEYS
    m = TrainMetrics(0, str(tmp_path))
    m.on_block(20, 1.0)
    m.on_train_step(0.5)
    record = m.log(2.0)
    assert "serving" not in record
    assert PR23_RECORD_KEYS <= set(record)
    from r2d2_tpu.tools.logparse import parse_jsonl
    rows = parse_jsonl(str(tmp_path / "metrics_player0.jsonl"))
    assert set(rows[0]) == set(record)


def test_record_serving_block_and_provider_contract(tmp_path):
    from r2d2_tpu.runtime.metrics import TrainMetrics
    from r2d2_tpu.serve import ServingStats
    m = TrainMetrics(0, str(tmp_path))
    stats = ServingStats()
    m.set_serving(stats.interval_block)
    record = m.log(2.0)
    assert "serving" not in record                  # no traffic: omitted
    stats.on_requests(2)
    stats.on_replies(2)
    stats.on_request_latency(0.002)
    record = m.log(2.0)
    assert record["serving"]["requests"] == 2
    from r2d2_tpu.tools.logparse import serve_series
    series = serve_series([record])
    assert series["requests"] == [2]
    assert series["latency_p99_ms"][0] is not None


def test_inspect_serving_panel():
    from r2d2_tpu.tools.inspect import render_serving
    block = {"requests": 10, "replies": 10, "expired": 0, "timeouts": 1,
             "latency": {"count": 10, "p50_ms": 2.0, "p95_ms": 5.0,
                         "p99_ms": 9.0},
             "batch": {"count": 5, "fill_mean": 2.0, "full_frac": 0.0,
                       "deadline_frac": 1.0, "starved_frac": 0.2},
             "clients": {"active": 2, "connects": 2, "reconnects": 1,
                         "disconnects": 1, "evictions": 0},
             "deadline_ms": 5.0, "max_batch": 8}
    panel = render_serving(block)
    assert "serving: 10 req" in panel
    assert "p99=9" in panel.replace(".0000", "").replace(".000", "")
    assert "reconnects=1" in panel


# ---------------------------------------------------------------------------
# chaos: client faults + config plumbing


def test_fault_grammar_disconnect():
    from r2d2_tpu.tools.chaos import parse_fault_spec
    faults = parse_fault_spec("0:disconnect@req=5;1:slowx2")
    assert faults[0].kind == "disconnect" and faults[0].block == 5
    with pytest.raises(ValueError):
        parse_fault_spec("0:disconnect")            # needs @req=N
    with pytest.raises(ValueError):
        parse_fault_spec("0:disconnect@req=0")
    # config validation: disconnect requires served inference
    with pytest.raises(ValueError, match="inference"):
        small_cfg(**{"actor.fault_spec": "0:disconnect@req=5"})
    cfg = small_cfg(**{"actor.fault_spec": "0:disconnect@req=5",
                       "actor.inference": "server"})
    assert cfg.actor.inference == "server"


def test_chaos_channel_disconnect_state_survives():
    """disconnect@req=N drops the serve connection every Nth request;
    the lease-retention window means the action stream STILL matches the
    uninterrupted local policy's exactly."""
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.serve import RemotePolicy
    from r2d2_tpu.tools.chaos import parse_fault_spec, wrap_channel
    cfg, net, params, ep, srv = make_server()
    try:
        fault = parse_fault_spec("0:disconnect@req=4")[0]
        channel = wrap_channel(ep.connect(), fault)
        remote = RemotePolicy(channel, net.action_dim, 0.25, seed=13)
        local = ActorPolicy(net, params, 0.25, seed=13)
        rng = np.random.default_rng(10)
        obs = rand_obs(rng, cfg)
        local.observe_reset(obs)
        remote.observe_reset(obs)
        for _ in range(12):
            a1, q1, _ = local.act()
            a2, q2, _ = remote.act()
            assert a1 == a2
            np.testing.assert_array_equal(q1, q2)
            nxt = rand_obs(rng, cfg)
            local.observe(nxt, a1)
            remote.observe(nxt, a2)
        assert channel.disconnects_injected >= 2
        deadline = time.monotonic() + 5.0
        while srv.cache.reconnects < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.cache.reconnects >= 2            # lease resumed each time
    finally:
        srv.stop()


def test_config_roundtrip_and_validation():
    cfg = small_cfg(**{"actor.inference": "server", "serve.max_batch": 16,
                       "serve.transport": "socket"})
    again = Config.from_dict(cfg.to_dict())
    assert again.serve.max_batch == 16
    assert again.actor.inference == "server"
    # pre-PR13 config dicts (no serve section / inference field) load
    d = cfg.to_dict()
    del d["serve"]
    del d["actor"]["inference"]
    old = Config.from_dict(d)
    # absent section/field take defaults: serve defaults, local inference
    assert old.serve.max_batch == 32 and old.actor.inference == "local"
    with pytest.raises(ValueError, match="inference"):
        small_cfg(**{"actor.inference": "remote"})
    with pytest.raises(ValueError, match="divisible"):
        small_cfg(**{"serve.state_slots": 10, "serve.state_shards": 4})
    with pytest.raises(ValueError, match="state_slots"):
        small_cfg(**{"actor.inference": "server", "actor.num_actors": 2,
                     "actor.envs_per_actor": 16, "serve.state_slots": 8,
                     "serve.state_shards": 1})
    with pytest.raises(ValueError, match="on_device"):
        small_cfg(**{"actor.inference": "server", "actor.on_device": True,
                     "env.episode_len": 20, "actor.anakin_lanes": 4})
    with pytest.raises(ValueError, match="transport"):
        small_cfg(**{"serve.transport": "pigeon"})


def test_serve_stages_registered():
    from r2d2_tpu.telemetry import STAGES
    for stage in ("serve/enqueue", "serve/batch_wait", "serve/forward",
                  "serve/reply"):
        assert stage in STAGES


# ---------------------------------------------------------------------------
# e2e slices


def test_serve_e2e_thread_mini(tmp_path):
    """Fast e2e: thread actors act through the in-proc server into the
    real learner; the periodic record carries the serving block and
    training advances."""
    from r2d2_tpu.runtime.orchestrator import train
    cfg = small_cfg(**{
        "actor.num_actors": 2, "actor.inference": "server",
        "runtime.log_interval": 1.0,
        "runtime.steps_per_dispatch": 1,
        "runtime.save_dir": str(tmp_path),
    })
    records = []
    stacks = train(cfg, max_training_steps=2, max_seconds=120,
                   actor_mode="thread", log_fn=records.append)
    lr = stacks[0].learner
    assert lr.training_steps >= 2
    serving = [r["serving"] for r in records if r.get("serving")]
    assert serving, "no serving block in any record"
    sb = serving[-1]
    assert sb["replies"] > 0
    assert sb["clients"]["active"] == 2
    assert sb["latency"]["p99_ms"] is not None
    # the serve stages flowed through the canonical telemetry
    stages = {}
    for r in records:
        stages.update(r.get("stages") or {})
    assert "serve/forward" in stages


@pytest.mark.slow
def test_serve_e2e_process_shm(tmp_path):
    """Slow e2e: PROCESS actors reach the learner-process server over
    the shm request/reply rings and training advances — the full
    transport ladder under the real orchestrator."""
    from r2d2_tpu.runtime.orchestrator import train
    cfg = small_cfg(**{
        "actor.num_actors": 1, "actor.envs_per_actor": 4,
        "actor.inference": "server",
        "runtime.log_interval": 2.0,
        "runtime.steps_per_dispatch": 1,
        "runtime.save_dir": str(tmp_path),
    })
    records = []
    stacks = train(cfg, max_training_steps=3, max_seconds=240,
                   actor_mode="process", log_fn=records.append)
    assert stacks[0].learner.training_steps >= 3
    serving = [r["serving"] for r in records if r.get("serving")]
    assert serving and serving[-1]["batch"]["fill_mean"] > 1


@pytest.mark.slow
def test_serve_chaos_server_restart_drill():
    """The acceptance drill: kill the server mid-training — the learner
    never stalls, serve_latency_slo fires during the outage and re-arms,
    clients reconnect and resume."""
    from r2d2_tpu.tools.chaos import run_serve_chaos
    report = run_serve_chaos(seconds=45.0, outage_s=6.0)
    assert report["verdict"]["no_learner_stall"], report
    assert report["verdict"]["slo_fired"], report
    assert report["verdict"]["slo_rearmed"], report
    assert report["verdict"]["clients_resumed"], report


@pytest.mark.slow
def test_evaluate_as_a_service(tmp_path):
    """cli/evaluate --serve: checkpoint rollouts through the in-proc
    server match the direct path's contract (finite mean return)."""
    from r2d2_tpu.cli.evaluate import evaluate_checkpoint
    from r2d2_tpu.runtime.checkpoint import save_checkpoint
    cfg = small_cfg(**{"runtime.save_dir": str(tmp_path)})
    net, params = tiny_net(cfg, action_dim=6)
    ckpt = save_checkpoint(str(tmp_path), "Fake", 1, 0, params,
                           {"none": np.zeros(1)}, params, step=7,
                           env_steps=140, config_json=cfg.to_json())
    mean_direct, step, env_steps = evaluate_checkpoint(
        cfg, ckpt, rounds=2, seed=0)
    mean_served, step2, _ = evaluate_checkpoint(
        cfg, ckpt, rounds=4, seed=0, serve=True, serve_clients=2)
    assert step == step2 == 7
    assert np.isfinite(mean_direct) and np.isfinite(mean_served)


# ---------------------------------------------------------------------------
# sharded serving fleet (ISSUE 17): shard routing, admission/brownout,
# elastic grow/shrink, kill-one-of-N failover


def fleet_cfg(**over):
    base = {"serve.servers": 2, "serve.max_servers": 2,
            "serve.state_shards": 8, "serve.state_slots": 512}
    base.update(over)
    return small_cfg(**base)


def make_fleet(cfg=None, **fleet_kw):
    from r2d2_tpu.serve import ServerFleet, ServingStats
    cfg = cfg or fleet_cfg()
    net, params = tiny_net(cfg)
    stats = fleet_kw.pop("stats", None) or ServingStats()
    fleet = ServerFleet(cfg, net, params, stats=stats, **fleet_kw)
    return cfg, net, params, stats, fleet


def test_collect_batch_drains_backlog_past_deadline():
    """The deadline bounds WAITING, not backlog drain: a first request
    that aged out while the server was mid-forward must still dispatch
    with everything already queued, not as a batch of one (the
    degenerate fill-1 regime the fleet bench exposed)."""
    from r2d2_tpu.serve import collect_batch
    inbox = queue.Queue()
    for _ in range(3):
        inbox.put(_pending())
    stale = _pending(t_recv=time.monotonic() - 1.0)
    batch = collect_batch(inbox, stale, max_batch=8, deadline_s=0.005)
    assert len(batch) == 4


def test_contiguous_partition():
    from r2d2_tpu.serve import contiguous_partition
    parts = contiguous_partition(8, [0, 2])
    assert parts == {0: [0, 1, 2, 3], 2: [4, 5, 6, 7]}
    # remainder shards go to the leading servers, coverage is exact
    parts = contiguous_partition(10, [1, 3, 4])
    got = sorted(s for shards in parts.values() for s in shards)
    assert got == list(range(10))
    assert [len(parts[s]) for s in (1, 3, 4)] == [4, 3, 3]
    with pytest.raises(ValueError):
        contiguous_partition(4, [])


def test_shard_map_wire_versioning():
    from r2d2_tpu.serve import ShardMap
    m = ShardMap(4, [0, 0, 1, 1])
    assert m.server_for(0) == 0 and m.server_for(2) == 1
    assert m.server_for(6) == 1          # client_id % total_shards
    wire = m.to_wire()
    other = ShardMap(4, [0, 0, 0, 0])
    other.version = 0
    assert other.apply_wire(wire)
    assert other.assignment() == m.assignment()
    # stale or equal versions are ignored
    assert not other.apply_wire(wire)
    assert not other.apply_wire((0, (1, 1, 1, 1)))
    v = m.update([1, 1, 0, 0])
    assert v == m.version and m.server_for(0) == 1


def test_state_cache_shard_handoff_roundtrip():
    """detach_shard -> import_shard moves a client's recurrent state
    bit-exactly; the donor then MISROUTES the moved client."""
    from r2d2_tpu.serve.state_cache import MisroutedClient, StateCache
    a = StateCache(64, 4, (24, 24), 2, 16, owned_shards=[0, 1, 2, 3],
                   total_shards=8)
    b = StateCache(64, 4, (24, 24), 2, 16, owned_shards=[4, 5, 6, 7],
                   total_shards=8)
    slot, fresh = a.lease(1)             # client 1 -> shard 1
    assert fresh
    a.hidden[slot] = 7.25
    a.last_action[slot] = 3
    state = a.detach_shard(1)
    b.import_shard(state)
    assert 1 not in a.owned_shards and 1 in b.owned_shards
    with pytest.raises(MisroutedClient):
        a.lease(1)
    slot_b, fresh_b = b.lease(1)
    assert not fresh_b                   # retained state, not a reset
    assert float(b.hidden[slot_b].ravel()[0]) == 7.25
    assert int(b.last_action[slot_b]) == 3


def test_routing_channel_reroutes_on_misroute():
    """A client holding a STALE map gets MISROUTED + the true map from
    the wrong server and re-aims within the same call."""
    from r2d2_tpu.serve import RemotePolicy, RoutingChannel, ShardMap
    cfg, net, params, stats, fleet = make_fleet()
    try:
        stale = ShardMap(8, [1] * 8)     # everything -> server 1: wrong
        stale.version = 0                # any fleet wire wins
        chan = RoutingChannel(
            {s: ep.connect() for s, ep in enumerate(fleet.endpoints)},
            stale)
        pol = RemotePolicy(chan, net.action_dim, 0.0, client_id=0,
                           timeout_s=5.0)
        rng = np.random.default_rng(0)
        pol.observe_reset(rand_obs(rng, cfg))
        action, q, _ = pol.act()
        assert chan.reroutes >= 1
        assert (chan.shard_map.assignment()
                == fleet.shard_map.assignment())
        assert np.isfinite(q).all()
    finally:
        fleet.stop()


def test_fleet_parity_with_single_server():
    """Served inference through a 2-server fleet is bit-identical to
    the single-server path at equal seeds/eps: same per-client streams,
    only the routing differs."""
    from r2d2_tpu.serve import RemoteBatchedPolicy
    cfg, net, params, stats, fleet = make_fleet()
    single_cfg = small_cfg(**{"serve.state_shards": 8,
                              "serve.state_slots": 512})
    _, _, _, ep, srv = make_server(single_cfg)
    try:
        streams = {}
        for tag, channel in (("fleet", fleet.connect()),
                             ("single", ep.connect())):
            pol = RemoteBatchedPolicy(channel, net.action_dim,
                                      [0.0] * 4, [0, 1, 2, 3],
                                      client_base=2, timeout_s=5.0)
            rng = np.random.default_rng(7)
            for i in range(4):
                pol.observe_reset_lane(i, rand_obs(rng, cfg))
            acts, qs = [], []
            for _ in range(6):
                a, q, _ = pol.act()
                acts.append(a.copy())
                qs.append(np.asarray(q).copy())
                pol.observe(np.stack([rand_obs(rng, cfg)
                                      for _ in range(4)]), a)
            streams[tag] = (np.stack(acts), np.stack(qs))
        np.testing.assert_array_equal(streams["fleet"][0],
                                      streams["single"][0])
        np.testing.assert_array_equal(streams["fleet"][1],
                                      streams["single"][1])
        block = fleet.interval_block()
        rows = block["servers"]["rows"]
        assert len(rows) == 2            # both servers took traffic
        assert all(r["requests"] > 0 for r in rows.values())
    finally:
        fleet.stop()
        srv.stop()


def test_fleet_kill_failover_stream_parity():
    """Kill one of two servers mid-stream: the survivor adopts the
    orphaned shards, clients re-route on the bounced map, and the
    action stream stays bit-identical to an undisturbed single-server
    run of the same seeds."""
    from r2d2_tpu.serve import RemoteBatchedPolicy
    cfg, net, params, stats, fleet = make_fleet()
    single_cfg = small_cfg(**{"serve.state_shards": 8,
                              "serve.state_slots": 512})
    _, _, _, ep, srv = make_server(single_cfg)
    try:
        def run(channel, fleet_to_kill=None):
            pol = RemoteBatchedPolicy(channel, net.action_dim,
                                      [0.0] * 4, [0, 1, 2, 3],
                                      client_base=2, timeout_s=5.0)
            rng = np.random.default_rng(11)
            for i in range(4):
                pol.observe_reset_lane(i, rand_obs(rng, cfg))
            acts = []
            for t in range(8):
                if t == 4 and fleet_to_kill is not None:
                    victim = max(fleet_to_kill.servers)
                    fleet_to_kill.kill_server(victim)
                    deadline = time.time() + 10.0
                    while (fleet_to_kill.supervise() == 0
                           and time.time() < deadline):
                        time.sleep(0.02)
                a, _, _ = pol.act()
                acts.append(a.copy())
                pol.observe(np.stack([rand_obs(rng, cfg)
                                      for _ in range(4)]), a)
            return np.stack(acts)

        v0 = fleet.shard_map.version
        fleet_stream = run(fleet.connect(), fleet_to_kill=fleet)
        single_stream = run(ep.connect())
        np.testing.assert_array_equal(fleet_stream, single_stream)
        assert len(fleet.servers) == 1
        survivor = next(iter(fleet.servers.values()))
        assert sorted(survivor.cache.owned_shards) == list(range(8))
        assert fleet.shard_map.version > v0
    finally:
        fleet.stop()
        srv.stop()


def test_fleet_grow_shrink_reslices():
    """grow_server splits the shard range onto the joiner with a
    lease handoff; shrink_server rehomes them back — clients keep
    streaming across both re-slices."""
    from r2d2_tpu.serve import RemoteBatchedPolicy
    cfg, net, params, stats, fleet = make_fleet(
        cfg=fleet_cfg(**{"serve.servers": 1, "serve.max_servers": 2}))
    try:
        pol = RemoteBatchedPolicy(fleet.connect(), net.action_dim,
                                  [0.0] * 4, [0, 1, 2, 3],
                                  timeout_s=5.0)
        rng = np.random.default_rng(3)
        for i in range(4):
            pol.observe_reset_lane(i, rand_obs(rng, cfg))
        pol.act()
        slot = fleet.grow_server()
        assert len(fleet.servers) == 2
        per = [sorted(s.cache.owned_shards)
               for s in fleet.servers.values()]
        assert sorted(sum(per, [])) == list(range(8))
        assert all(len(p) == 4 for p in per)
        a_grow, _, _ = pol.act()         # streams through the re-slice
        pol.observe(np.stack([rand_obs(rng, cfg) for _ in range(4)]),
                    a_grow)
        assert fleet.shrink_server(slot) == slot
        assert len(fleet.servers) == 1
        survivor = next(iter(fleet.servers.values()))
        assert sorted(survivor.cache.owned_shards) == list(range(8))
        a_shrink, _, _ = pol.act()
        assert a_shrink.shape == (4,)
    finally:
        fleet.stop()


def test_admission_shed_retry_and_stats():
    """Overload a 1-wide fleet past its queue bound: the overflow is
    shed with STATUS_RETRY, clients absorb the retries (no failures),
    and the serving block's admission counters account for it."""
    from r2d2_tpu.serve import RemoteBatchedPolicy
    cfg = fleet_cfg(**{"serve.servers": 1, "serve.max_servers": 1,
                       "serve.state_shards": 8, "serve.max_batch": 2,
                       "serve.queue_depth_bound": 1,
                       "serve.deadline_ms": 1.0})
    cfg2, net, params, stats, fleet = make_fleet(cfg=cfg)
    try:
        pol = RemoteBatchedPolicy(fleet.connect(), net.action_dim,
                                  [0.0] * 8, list(range(8)),
                                  timeout_s=5.0)
        rng = np.random.default_rng(5)
        for i in range(8):
            pol.observe_reset_lane(i, rand_obs(rng, cfg))
        for t in range(6):               # 8 lanes vs batch 2, bound 1
            a, _, _ = pol.act()
            pol.observe(np.stack([rand_obs(rng, cfg)
                                  for _ in range(8)]), a)
        assert pol.shed_retries > 0
        block = fleet.interval_block()
        adm = block["admission"]
        assert adm["shed"] > 0 and adm["shed_frac"] > 0
        assert adm["admitted_latency"]["p99_ms"] is not None
    finally:
        fleet.stop()


def test_serve_brownout_alert_fires_and_rearms():
    from r2d2_tpu.telemetry.alerts import AlertEngine, default_rules
    engine = AlertEngine(default_rules(Config().telemetry))

    def rec(shed_frac):
        serving = {"latency": {"p99_ms": 5.0},
                   "admission": {"shed_frac": shed_frac}}
        return {"t": 1.0, "buffer_speed": 100.0, "training_speed": 1.0,
                "serving": serving}

    out = engine.evaluate(rec(0.0))
    assert not out["fired"]
    out = engine.evaluate(rec(0.5))
    assert [a["rule"] for a in out["fired"]] == ["serve_brownout"]
    out = engine.evaluate(rec(0.6))
    assert not out["fired"]              # level rule: edge only
    out = engine.evaluate(rec(0.01))
    assert "serve_brownout" not in out["active"]
    out = engine.evaluate(rec(0.5))
    assert [a["rule"] for a in out["fired"]] == ["serve_brownout"]


def test_admission_block_gated_off_single_server():
    """Kill switch: serve.servers=1 + queue_depth_bound=0 emits the
    PR-16 serving schema exactly — no 'admission', no 'servers' key."""
    from r2d2_tpu.serve import RemoteBatchedPolicy, ServingStats
    stats = ServingStats()
    cfg, net, params, ep, srv = make_server(stats=stats)
    try:
        pol = RemoteBatchedPolicy(ep.connect(), net.action_dim,
                                  [0.0] * 2, [0, 1], timeout_s=5.0)
        rng = np.random.default_rng(0)
        for i in range(2):
            pol.observe_reset_lane(i, rand_obs(rng, cfg))
        pol.act()
        block = stats.interval_block()
        assert "admission" not in block
        assert "servers" not in block
    finally:
        srv.stop()


def test_fleet_config_validation():
    with pytest.raises(ValueError, match="state_shards"):
        fleet_cfg(**{"serve.servers": 9})
    with pytest.raises(ValueError, match="max_servers"):
        fleet_cfg(**{"serve.max_servers": 1})
    with pytest.raises(ValueError, match="transport"):
        fleet_cfg(**{"serve.transport": "shm"})
    with pytest.raises(ValueError, match="queue_depth_bound"):
        small_cfg(**{"serve.queue_depth_bound": -1})
    cfg = fleet_cfg(**{"serve.queue_depth_bound": 16})
    assert cfg.serve.servers == 2


def test_membership_lease_server_roundtrip():
    """The socket lease API (cli/join.py's dial): join/leave/info round
    trips, handler errors surface as refusals, unknown ops list the
    vocabulary."""
    from r2d2_tpu.fleet import MembershipServer, lease_call
    calls = []

    def join(slot=None):
        calls.append(("join", slot))
        return {"slot": 3 if slot is None else int(slot),
                "generation": 1, "lane_base": 0, "lanes": 4}

    def leave(slot):
        if int(slot) == 9:
            raise RuntimeError("slot 9 is not ACTIVE")
        return {"slot": int(slot)}

    ms = MembershipServer({"join": join, "leave": leave,
                           "info": lambda: {"actors": 2}})
    try:
        got = lease_call(ms.host, ms.port, "join")
        assert got["slot"] == 3 and got["ok"]
        got = lease_call(ms.host, ms.port, "join", slot=1)
        assert got["slot"] == 1
        assert lease_call(ms.host, ms.port, "info")["actors"] == 2
        with pytest.raises(RuntimeError, match="not ACTIVE"):
            lease_call(ms.host, ms.port, "leave", slot=9)
        with pytest.raises(RuntimeError, match="join"):
            lease_call(ms.host, ms.port, "nonsense")
        assert calls[0] == ("join", None)
    finally:
        ms.close()
