"""Pallas kernel tests.

Interpret mode runs on the suite's CPU mesh; the compiled-lowering gate
(test_stack_frames_pallas_compiled_on_tpu) runs the real Mosaic pipeline in
a subprocess with the CPU pin stripped, and skips when no TPU is attached —
so lowering regressions (like BENCH_r02's unsupported uint8 cast, which
interpret mode cannot catch) surface in any TPU-attached pytest run instead
of only in the driver bench."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.ops.pallas_kernels import (
    gather_rows_pallas, gather_rows_reference, resolve_pallas_obs_decode,
    stack_frames_pallas, stack_frames_reference)


def test_stack_frames_pallas_matches_reference(rng):
    B, T, K, H, W = 3, 7, 4, 12, 16
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1 + 2, H, W)),
                      jnp.uint8)  # +2: row longer than the window, like replay
    want = np.asarray(stack_frames_reference(obs, T, K))
    got = np.asarray(stack_frames_pallas(obs, T, K, True))
    assert got.shape == (B, T, H, W, K)
    # kernel multiplies by 1/255 (one VPU op) vs the reference's divide —
    # identical up to one ulp
    np.testing.assert_allclose(got, want, rtol=2e-7)
    assert got.dtype == np.float32
    assert got.max() <= 1.0 and got.min() >= 0.0


def test_gather_rows_exact_matches_reference(rng):
    """The exact-read async-copy gather (interpret mode) returns the same
    windows as the vmapped dynamic-slice twin."""
    from r2d2_tpu.ops.pallas_kernels import gather_rows_exact_pallas
    ring = jnp.asarray(rng.integers(0, 255, (8, 50, 16, 16)), jnp.uint8)
    bi = jnp.asarray(rng.integers(0, 8, (6,)), jnp.int32)
    st = jnp.asarray(rng.integers(0, 40, (6,)), jnp.int32)
    got = gather_rows_exact_pallas(ring, bi, st, 10, True)
    want = gather_rows_reference(ring, bi, st, 10)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stack_frames_out_height_strips_padding(rng):
    """out_height (exact-gather padded storage) strips the sublane pad in
    both decode twins, matching an unpadded decode exactly."""
    B, T, K, H, W = 2, 5, 3, 12, 16
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1, H, W)), jnp.uint8)
    obs_pad = jnp.pad(obs, ((0, 0), (0, 0), (0, 4), (0, 0)))  # H 12 -> 16
    want = np.asarray(stack_frames_reference(obs, T, K))
    got_ref = np.asarray(stack_frames_reference(obs_pad, T, K, out_height=H))
    got_pl = np.asarray(stack_frames_pallas(obs_pad, T, K, True,
                                            out_height=H))
    np.testing.assert_array_equal(got_ref, want)
    np.testing.assert_allclose(got_pl, want, rtol=2e-7)


def test_stack_frames_out_width_strips_padding(rng):
    """out_width (exact-gather lane-tile padding, 84x84 -> 96x128 at
    reference scale) strips the lane pad in BOTH pallas kernels (planar
    and nhwc) and the reference twin, matching an unpadded decode
    exactly."""
    from r2d2_tpu.ops.pallas_kernels import stack_frames_pallas_nhwc
    B, T, K, H, W = 2, 5, 3, 12, 12
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1, H, W)), jnp.uint8)
    obs_pad = jnp.pad(obs, ((0, 0), (0, 0), (0, 4), (0, 6)))  # -> (16, 18)
    want = np.asarray(stack_frames_reference(obs, T, K))
    got_ref = np.asarray(stack_frames_reference(obs_pad, T, K,
                                                out_height=H, out_width=W))
    got_pl = np.asarray(stack_frames_pallas(obs_pad, T, K, True,
                                            out_height=H, out_width=W))
    got_nhwc = np.asarray(stack_frames_pallas_nhwc(obs_pad, T, K, True,
                                                   out_height=H, out_width=W))
    np.testing.assert_array_equal(got_ref, want)
    np.testing.assert_allclose(got_pl, want, rtol=2e-7)
    np.testing.assert_allclose(got_nhwc, want, rtol=2e-7)
    assert got_pl.shape == got_nhwc.shape == (B, T, H, W, K)


def test_stack_frames_nhwc_matches_reference(rng):
    """The NHWC-emitting decode (K interleaved into the lane dim in-kernel,
    no post-kernel transpose) matches the reference twin — including with
    a padded storage height and bf16 output."""
    from r2d2_tpu.ops.pallas_kernels import stack_frames_pallas_nhwc
    B, T, K, H, W = 3, 6, 4, 12, 16
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1 + 2, H, W)),
                      jnp.uint8)
    want = np.asarray(stack_frames_reference(obs, T, K))
    got = np.asarray(stack_frames_pallas_nhwc(obs, T, K, True))
    assert got.shape == (B, T, H, W, K)
    np.testing.assert_allclose(got, want, rtol=2e-7)

    obs_pad = jnp.pad(obs, ((0, 0), (0, 0), (0, 4), (0, 0)))
    got_pad = np.asarray(stack_frames_pallas_nhwc(obs_pad, T, K, True,
                                                  out_height=H))
    np.testing.assert_allclose(got_pad, want, rtol=2e-7)

    want_bf16 = np.asarray(stack_frames_reference(obs, T, K,
                                                  out_dtype=jnp.bfloat16))
    got_bf16 = np.asarray(stack_frames_pallas_nhwc(obs, T, K, True,
                                                   out_dtype=jnp.bfloat16))
    np.testing.assert_array_equal(got_bf16, want_bf16)


def test_stack_frames_bf16_output(rng):
    """out_dtype=bf16 (the bf16-policy decode): both twins normalize in f32
    and round ONCE at the end, so kernel and reference agree bit-exactly
    and match an explicit f32->bf16 cast of the f32 result."""
    B, T, K, H, W = 2, 5, 3, 12, 16
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1, H, W)), jnp.uint8)
    ref_f32 = stack_frames_reference(obs, T, K)
    ref_bf16 = np.asarray(stack_frames_reference(obs, T, K,
                                                 out_dtype=jnp.bfloat16))
    got = np.asarray(stack_frames_pallas(obs, T, K, True,
                                         out_dtype=jnp.bfloat16))
    assert got.dtype == jnp.bfloat16 and ref_bf16.dtype == jnp.bfloat16
    np.testing.assert_array_equal(got, ref_bf16)
    np.testing.assert_array_equal(
        ref_bf16, np.asarray(ref_f32.astype(jnp.bfloat16)))


def test_stack_frames_reference_window_semantics(rng):
    """out[b, t, :, :, k] must be frame t+k (the learner-side obs_idx gather,
    ref worker.py:310,330)."""
    B, T, K, H, W = 1, 4, 2, 6, 6
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1, H, W)), jnp.uint8)
    out = np.asarray(stack_frames_reference(obs, T, K))
    for t in range(T):
        for k in range(K):
            np.testing.assert_allclose(
                out[0, t, :, :, k], np.asarray(obs[0, t + k], np.float32) / 255.0)


def test_gather_rows_pallas_matches_reference(rng):
    """Scalar-prefetch row gather (the replay-sample obs slice): interpret
    mode vs the vmapped dynamic-slice twin, including repeated rows and
    window starts at both row edges."""
    N, R, H, W = 5, 20, 12, 16
    WIN = 7
    ring = jnp.asarray(rng.integers(0, 255, (N, R, H, W)), jnp.uint8)
    block_idx = jnp.asarray([0, 3, 3, 4, 2, 0], jnp.int32)
    start = jnp.asarray([0, 5, 13, R - WIN, 1, 0], jnp.int32)
    want = np.asarray(gather_rows_reference(ring, block_idx, start, WIN))
    got = np.asarray(gather_rows_pallas(ring, block_idx, start, WIN, True))
    np.testing.assert_array_equal(got, want)
    assert got.dtype == np.uint8


def test_resolve_pallas_obs_decode():
    assert resolve_pallas_obs_decode("on") is True
    assert resolve_pallas_obs_decode("off") is False
    # the suite runs on the pinned CPU mesh, so auto resolves to the gather path
    assert resolve_pallas_obs_decode("auto") is False
    # legacy bool configs pass through
    assert resolve_pallas_obs_decode(True) is True
    with pytest.raises(ValueError):
        resolve_pallas_obs_decode("maybe")


_COMPILED_CHECK = """
import sys
import jax
if jax.default_backend() != "tpu":
    print("NOTPU")
    sys.exit(0)
import numpy as np
import jax.numpy as jnp
from r2d2_tpu.ops.pallas_kernels import stack_frames_pallas, stack_frames_reference
rng = np.random.default_rng(0)
obs = jnp.asarray(rng.integers(0, 255, (4, 58, 84, 84)).astype(np.uint8))
got = stack_frames_pallas(obs, 55, 4)          # interpret=False: real Mosaic
want = stack_frames_reference(obs, 55, 4)
np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-7)
from r2d2_tpu.ops.pallas_kernels import gather_rows_pallas, gather_rows_reference
ring = jnp.asarray(rng.integers(0, 255, (8, 412, 84, 84)).astype(np.uint8))
bi = jnp.asarray(rng.integers(0, 8, (16,)).astype(np.int32))
st = jnp.asarray(rng.integers(0, 412 - 58, (16,)).astype(np.int32))
got = gather_rows_pallas(ring, bi, st, 58)     # compiled scalar-prefetch path
want = gather_rows_reference(ring, bi, st, 58)
np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
print("OK")
"""


@pytest.mark.slow
def test_stack_frames_pallas_compiled_on_tpu():
    """Compiled-mode gate (VERDICT r2 #6): real Mosaic lowering at the bench's
    production shape, in a subprocess free of the suite's CPU-platform pin."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    # Stage 1: bounded discovery probe. Backend discovery can HANG (not
    # fail) when the remote-TPU tunnel was wedged by an earlier hard-killed
    # process — probing first caps that case at 90s instead of spending the
    # full compile budget (420s measured, round 4) before skipping.
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            env=env, capture_output=True, text=True, timeout=90)
    except subprocess.TimeoutExpired:
        pytest.skip("backend discovery hung (wedged remote-TPU tunnel?); "
                    "compiled lowering not testable")
    if probe.returncode != 0 or probe.stdout.strip() != "tpu":
        pytest.skip("no TPU backend attached; compiled lowering not testable")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _COMPILED_CHECK], env=env,
            capture_output=True, text=True, timeout=420)
    except subprocess.TimeoutExpired:
        pytest.skip("backend discovery hung (wedged remote-TPU tunnel?); "
                    "compiled lowering not testable")
    out = proc.stdout.strip().splitlines()
    if proc.returncode == 0 and out and out[-1] == "NOTPU":
        pytest.skip("no TPU backend attached; compiled lowering not testable")
    assert proc.returncode == 0, (
        f"compiled pallas check failed (rc={proc.returncode}):\n{proc.stderr[-4000:]}")
    assert out and out[-1] == "OK"


# ---------------------------------------------------------------------------
# Fused LSTM time-scan (ops/pallas_lstm.py)


def _lstm_inputs(rng, T=7, B=8, H=128, dtype=jnp.float32):
    xpb = jnp.asarray(rng.standard_normal((T, B, 4 * H)), dtype)
    wh = jnp.asarray(rng.standard_normal((H, 4 * H)) * 0.1, dtype)
    c0 = jnp.asarray(rng.standard_normal((B, H)), dtype)
    h0 = jnp.asarray(rng.standard_normal((B, H)), dtype)
    return xpb, wh, c0, h0


def test_lstm_scan_pallas_forward_matches_reference(rng):
    """f32 interpret-mode forward is bit-exact vs the lax.scan twin (the
    kernel's f32 carry + f32 gate math reproduce the scan exactly when
    nothing is rounded)."""
    from r2d2_tpu.ops.pallas_lstm import (lstm_scan_pallas,
                                          lstm_scan_reference)
    args = _lstm_inputs(rng)
    hs_r, (cf_r, hf_r) = lstm_scan_reference(*args)
    hs_p, (cf_p, hf_p) = lstm_scan_pallas(*args, interpret=True)
    np.testing.assert_array_equal(np.asarray(hs_p), np.asarray(hs_r))
    np.testing.assert_array_equal(np.asarray(cf_p), np.asarray(cf_r))
    np.testing.assert_array_equal(np.asarray(hf_p), np.asarray(hf_r))


def test_lstm_scan_pallas_grads_match_reference(rng):
    """custom-VJP backward kernel vs jax.grad of the scan twin, for every
    input — including the final-carry cotangents (the loss reads c_fin and
    h_fin so dcfin/dhfin are non-zero)."""
    from r2d2_tpu.ops.pallas_lstm import (lstm_scan_pallas,
                                          lstm_scan_reference)
    args = _lstm_inputs(rng)
    T, B, H = args[0].shape[0], args[0].shape[1], args[1].shape[0]
    w = jnp.asarray(rng.standard_normal((T, B, H)), jnp.float32)

    def loss(fn, args):
        hs, (c, h) = fn(*args)
        return jnp.sum(hs * w) + jnp.sum(c * 1.3) + jnp.sum(h * 0.7)

    g_ref = jax.grad(lambda a: loss(lstm_scan_reference, a))(args)
    g_pal = jax.grad(lambda a: loss(
        lambda *a: lstm_scan_pallas(*a, interpret=True), a))(args)
    for name, a, b in zip(("dxpb", "dwh", "dc0", "dh0"), g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-6, rtol=2e-6, err_msg=name)


def test_lstm_scan_pallas_unused_carry_grads(rng):
    """When the loss ignores the final carry JAX feeds zero cotangents for
    it; the kernel must still produce the right dxpb/dwh."""
    from r2d2_tpu.ops.pallas_lstm import (lstm_scan_pallas,
                                          lstm_scan_reference)
    args = _lstm_inputs(rng, T=4, B=8, H=128)

    def loss(fn, args):
        hs, _ = fn(*args)
        return jnp.sum(hs ** 2)

    g_ref = jax.grad(lambda a: loss(lstm_scan_reference, a))(args)
    g_pal = jax.grad(lambda a: loss(
        lambda *a: lstm_scan_pallas(*a, interpret=True), a))(args)
    for name, a, b in zip(("dxpb", "dwh", "dc0", "dh0"), g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-6, rtol=2e-6, err_msg=name)


def test_hoisted_lstm_pallas_path_matches_scan(rng):
    """HoistedLSTM(use_pallas=True) plumbing — bias folding, axis swaps,
    carry order — against the default scan path, same params. The bias
    fold changes one f32 addition order, hence allclose not array_equal."""
    from r2d2_tpu.models.network import HoistedLSTM
    B, T, D, H = 4, 6, 48, 128
    xs = jnp.asarray(rng.standard_normal((B, T, D)), jnp.float32)
    carry = (jnp.asarray(rng.standard_normal((B, H)), jnp.float32),
             jnp.asarray(rng.standard_normal((B, H)), jnp.float32))
    scan_cell = HoistedLSTM(features=H)
    params = scan_cell.init(jax.random.PRNGKey(0), carry, xs)
    # make the bias nonzero so the fold is actually exercised
    params = jax.tree_util.tree_map(lambda x: x, params)
    params["params"]["bias"] = jnp.asarray(
        rng.standard_normal((4 * H,)) * 0.1, jnp.float32)
    (c_s, h_s), out_s = scan_cell.apply(params, carry, xs)
    pallas_cell = HoistedLSTM(features=H, use_pallas=True,
                              pallas_interpret=True)
    (c_p, h_p), out_p = pallas_cell.apply(params, carry, xs)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_s),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(c_p), np.asarray(c_s),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h_p), np.asarray(h_s),
                               atol=1e-5, rtol=1e-5)


def test_hoisted_lstm_pallas_single_step_falls_back(rng):
    """T=1 (the actor's step shape) must stay on the scan path — the
    pallas kernel is a sequence fusion, not a step dispatch."""
    from r2d2_tpu.models.network import HoistedLSTM
    B, D, H = 4, 48, 128
    xs = jnp.asarray(rng.standard_normal((B, 1, D)), jnp.float32)
    carry = (jnp.zeros((B, H)), jnp.zeros((B, H)))
    cell = HoistedLSTM(features=H, use_pallas=True, pallas_interpret=False)
    params = cell.init(jax.random.PRNGKey(0), carry, xs)
    # pallas_interpret=False would fail to compile on CPU if the kernel
    # were (wrongly) taken; succeeding proves the fallback
    (_, _), out = cell.apply(params, carry, xs)
    assert out.shape == (B, 1, H)


def test_lstm_scan_pallas_bf16_tracks_reference(rng):
    """bf16 interpret-mode pass of both kernels (the dtype the chip runs
    under the shipped policy): forward within bf16 tolerance of the f32
    reference, and the custom-VJP pipeline produces finite, same-scale
    grads for every input. Catches dtype-specific kernel bugs (bad casts,
    f32-only ops) before the on-chip A/B."""
    from r2d2_tpu.ops.pallas_lstm import (lstm_scan_pallas,
                                          lstm_scan_reference)
    f32args = _lstm_inputs(rng, T=5, B=8, H=128)
    args = tuple(a.astype(jnp.bfloat16) for a in f32args)
    hs_r, (cf_r, hf_r) = lstm_scan_reference(*f32args)
    hs_p, (cf_p, hf_p) = lstm_scan_pallas(*args, interpret=True)
    assert hs_p.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(hs_p, np.float32),
                               np.asarray(hs_r), atol=0.03, rtol=0.03)
    np.testing.assert_allclose(np.asarray(cf_p, np.float32),
                               np.asarray(cf_r), atol=0.05, rtol=0.05)

    def loss(a):
        hs, (c, h) = lstm_scan_pallas(*a, interpret=True)
        return (jnp.sum(hs.astype(jnp.float32) ** 2)
                + jnp.sum(c.astype(jnp.float32))
                + jnp.sum(h.astype(jnp.float32)))

    g_pal = jax.grad(loss)(args)

    def loss_ref(a):
        hs, (c, h) = lstm_scan_reference(*a)
        return jnp.sum(hs ** 2) + jnp.sum(c) + jnp.sum(h)

    g_ref = jax.grad(loss_ref)(f32args)
    for name, a, b in zip(("dxpb", "dwh", "dc0", "dh0"), g_pal, g_ref):
        a = np.asarray(a, np.float32)
        b = np.asarray(b)
        assert np.isfinite(a).all(), name
        assert a.dtype == np.float32 and a.shape == b.shape
        # same magnitude ballpark (bf16 rounding both in the kernel and in
        # the bf16 reference chain rules out elementwise equality)
        denom = max(np.abs(b).max(), 1e-3)
        assert np.abs(a - b).max() / denom < 0.25, name


@pytest.mark.slow
def test_lstm_scan_pallas_block_t_matches_reference(rng):
    """block_t > 1 (several timesteps per grid iteration) must be exactly
    the same computation: bit-exact f32 forward across block boundaries,
    grads to f32 epsilon — including the in-block h_prev recomputation
    (o*tanh(c)) and the block-boundary carry handoff."""
    from r2d2_tpu.ops.pallas_lstm import (lstm_scan_pallas,
                                          lstm_scan_reference)
    args = _lstm_inputs(rng, T=10, B=8, H=128)
    hs_r, (cf_r, hf_r) = lstm_scan_reference(*args)
    w = jnp.asarray(rng.standard_normal(hs_r.shape), jnp.float32)

    def loss(fn, a):
        hs, (c, h) = fn(*a)
        return jnp.sum(hs * w) + jnp.sum(c * 1.3) + jnp.sum(h * 0.7)

    g_ref = jax.grad(lambda a: loss(lstm_scan_reference, a))(args)
    for bt in (2, 5, 10):
        hs_p, (cf_p, hf_p) = lstm_scan_pallas(*args, interpret=True,
                                              block_t=bt)
        np.testing.assert_array_equal(np.asarray(hs_p), np.asarray(hs_r),
                                      err_msg=f"block_t={bt}")
        np.testing.assert_array_equal(np.asarray(cf_p), np.asarray(cf_r))
        g_pal = jax.grad(lambda a: loss(
            lambda *x: lstm_scan_pallas(*x, interpret=True, block_t=bt),
            a))(args)
        for name, a, b in zip(("dxpb", "dwh", "dc0", "dh0"), g_ref, g_pal):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-6, rtol=3e-6,
                                       err_msg=f"{name} block_t={bt}")


def test_lstm_scan_pallas_block_t_must_divide(rng):
    from r2d2_tpu.ops.pallas_lstm import lstm_scan_pallas
    args = _lstm_inputs(rng, T=7, B=8, H=128)
    with pytest.raises(ValueError, match="divide"):
        lstm_scan_pallas(*args, interpret=True, block_t=3)
