"""Pallas kernel tests (interpret mode on the CPU mesh — the real lowering
runs on TPU; bench.py compares both paths there)."""

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.ops.pallas_kernels import (
    stack_frames_pallas, stack_frames_reference)


def test_stack_frames_pallas_matches_reference(rng):
    B, T, K, H, W = 3, 7, 4, 12, 16
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1 + 2, H, W)),
                      jnp.uint8)  # +2: row longer than the window, like replay
    want = np.asarray(stack_frames_reference(obs, T, K))
    got = np.asarray(stack_frames_pallas(obs, T, K, True))
    assert got.shape == (B, T, H, W, K)
    # kernel multiplies by 1/255 (one VPU op) vs the reference's divide —
    # identical up to one ulp
    np.testing.assert_allclose(got, want, rtol=2e-7)
    assert got.dtype == np.float32
    assert got.max() <= 1.0 and got.min() >= 0.0


def test_stack_frames_reference_window_semantics(rng):
    """out[b, t, :, :, k] must be frame t+k (the learner-side obs_idx gather,
    ref worker.py:310,330)."""
    B, T, K, H, W = 1, 4, 2, 6, 6
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1, H, W)), jnp.uint8)
    out = np.asarray(stack_frames_reference(obs, T, K))
    for t in range(T):
        for k in range(K):
            np.testing.assert_allclose(
                out[0, t, :, :, k], np.asarray(obs[0, t + k], np.float32) / 255.0)
