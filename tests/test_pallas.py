"""Pallas kernel tests.

Interpret mode runs on the suite's CPU mesh; the compiled-lowering gate
(test_stack_frames_pallas_compiled_on_tpu) runs the real Mosaic pipeline in
a subprocess with the CPU pin stripped, and skips when no TPU is attached —
so lowering regressions (like BENCH_r02's unsupported uint8 cast, which
interpret mode cannot catch) surface in any TPU-attached pytest run instead
of only in the driver bench."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.ops.pallas_kernels import (
    resolve_pallas_obs_decode, stack_frames_pallas, stack_frames_reference)


def test_stack_frames_pallas_matches_reference(rng):
    B, T, K, H, W = 3, 7, 4, 12, 16
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1 + 2, H, W)),
                      jnp.uint8)  # +2: row longer than the window, like replay
    want = np.asarray(stack_frames_reference(obs, T, K))
    got = np.asarray(stack_frames_pallas(obs, T, K, True))
    assert got.shape == (B, T, H, W, K)
    # kernel multiplies by 1/255 (one VPU op) vs the reference's divide —
    # identical up to one ulp
    np.testing.assert_allclose(got, want, rtol=2e-7)
    assert got.dtype == np.float32
    assert got.max() <= 1.0 and got.min() >= 0.0


def test_stack_frames_reference_window_semantics(rng):
    """out[b, t, :, :, k] must be frame t+k (the learner-side obs_idx gather,
    ref worker.py:310,330)."""
    B, T, K, H, W = 1, 4, 2, 6, 6
    obs = jnp.asarray(rng.integers(0, 255, (B, T + K - 1, H, W)), jnp.uint8)
    out = np.asarray(stack_frames_reference(obs, T, K))
    for t in range(T):
        for k in range(K):
            np.testing.assert_allclose(
                out[0, t, :, :, k], np.asarray(obs[0, t + k], np.float32) / 255.0)


def test_resolve_pallas_obs_decode():
    assert resolve_pallas_obs_decode("on") is True
    assert resolve_pallas_obs_decode("off") is False
    # the suite runs on the pinned CPU mesh, so auto resolves to the gather path
    assert resolve_pallas_obs_decode("auto") is False
    # legacy bool configs pass through
    assert resolve_pallas_obs_decode(True) is True
    with pytest.raises(ValueError):
        resolve_pallas_obs_decode("maybe")


_COMPILED_CHECK = """
import sys
import jax
if jax.default_backend() != "tpu":
    print("NOTPU")
    sys.exit(0)
import numpy as np
import jax.numpy as jnp
from r2d2_tpu.ops.pallas_kernels import stack_frames_pallas, stack_frames_reference
rng = np.random.default_rng(0)
obs = jnp.asarray(rng.integers(0, 255, (4, 58, 84, 84)).astype(np.uint8))
got = stack_frames_pallas(obs, 55, 4)          # interpret=False: real Mosaic
want = stack_frames_reference(obs, 55, 4)
np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-7)
print("OK")
"""


def test_stack_frames_pallas_compiled_on_tpu():
    """Compiled-mode gate (VERDICT r2 #6): real Mosaic lowering at the bench's
    production shape, in a subprocess free of the suite's CPU-platform pin."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    proc = subprocess.run(
        [sys.executable, "-c", _COMPILED_CHECK], env=env,
        capture_output=True, text=True, timeout=600)
    out = proc.stdout.strip().splitlines()
    if proc.returncode == 0 and out and out[-1] == "NOTPU":
        pytest.skip("no TPU backend attached; compiled lowering not testable")
    assert proc.returncode == 0, (
        f"compiled pallas check failed (rc={proc.returncode}):\n{proc.stderr[-4000:]}")
    assert out and out[-1] == "OK"
