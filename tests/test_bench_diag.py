"""bench.py diagnostics tests (VERDICT r2 #5).

BENCH_r02 n=1 died with a raw traceback when the wedged remote-TPU tunnel
surfaced at the *first dispatch*, after init's jax.devices() guard had
passed. These tests run bench.py as a subprocess on the CPU backend in its
smoke configuration and assert (a) a simulated backend failure at first
dispatch produces the actionable guidance message with rc=1, and (b) the
happy path still emits the one-line JSON contract the driver parses.
"""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _run_bench(extra_env):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update({"JAX_PLATFORMS": "cpu", "R2D2_BENCH_SMOKE": "1"})
    env.update(extra_env)
    return subprocess.run([sys.executable, BENCH], env=env,
                          capture_output=True, text=True, timeout=600)


def test_simulated_dispatch_failure_prints_guidance():
    proc = _run_bench({"R2D2_BENCH_SIMULATE_DISPATCH_FAILURE": "1"})
    assert proc.returncode == 1
    assert "first compile+dispatch FAILED" in proc.stderr
    assert "JAX_PLATFORMS" in proc.stderr          # the actionable guidance
    assert "retry later" in proc.stderr
    assert "Traceback" not in proc.stderr          # no raw traceback


def test_smoke_bench_emits_json_contract():
    proc = _run_bench({})
    assert proc.returncode == 0, proc.stderr[-4000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "learner_sequence_updates_per_sec_per_chip"
    assert out["unit"] == "sequences/s"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    assert out["matrix"]["f32_spd1"] == out["value"]
