"""bench.py diagnostics + resilience tests (VERDICT r2 #5, r3 #1).

BENCH_r02 n=1 died with a raw traceback when the wedged remote-TPU tunnel
surfaced at the *first dispatch*, after init's jax.devices() guard had
passed; BENCH_r03 was lost entirely when discovery HUNG at driver time.
These tests run bench.py as a subprocess on the CPU backend in its smoke
configuration and assert:
  (a) a simulated backend failure with no last-good cache produces the
      actionable guidance message with rc=1 (no raw traceback);
  (b) the happy path still emits the one-line JSON contract;
  (c) a backend failure WITH a last-good cache degrades to that
      measurement flagged "stale": true with rc=0 (the round keeps a
      number);
  (d) the retry loop around backend discovery also reaches the stale
      fallback when discovery itself fails repeatedly;
  (e) a successful run records the last-good cache for future rounds.
"""

import json
import os
import subprocess
import sys

import pytest

# every test here runs bench.py as a subprocess (jax import + smoke train
# per run): slow tier (VERDICT r3 #5)
pytestmark = pytest.mark.slow

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")

FAKE_CACHE = {
    "recorded_at": "2026-01-01T00:00:00Z",
    "output": {
        "metric": "learner_sequence_updates_per_sec_per_chip",
        "value": 11314.0, "unit": "sequences/s", "vs_baseline": 17.68,
        "platform": "tpu", "device_kind": "TPU v5 lite",
        # pre-round-5 cache shape: matrix without cell_status — the stale
        # path must synthesize statuses so old caches stay self-describing
        "matrix": {"bf16_spd16": 11314.0, "f32_spd4": None},
    },
}


def _run_bench(extra_env, timeout=600):
    import tempfile
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update({"JAX_PLATFORMS": "cpu", "R2D2_BENCH_SMOKE": "1",
                "R2D2_BENCH_BACKOFF": "0",
                # isolate the partial-snapshot file from concurrent benches
                "R2D2_BENCH_PARTIAL": os.path.join(
                    tempfile.mkdtemp(prefix="bench_partial_"),
                    "partial.json")})
    env.update(extra_env)
    return subprocess.run([sys.executable, BENCH], env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_simulated_dispatch_failure_prints_guidance(tmp_path):
    proc = _run_bench({
        "R2D2_BENCH_SIMULATE_DISPATCH_FAILURE": "1",
        "R2D2_BENCH_CACHE": str(tmp_path / "absent.json")})
    assert proc.returncode == 1
    assert "first compile+dispatch FAILED" in proc.stderr
    assert "JAX_PLATFORMS" in proc.stderr          # the actionable guidance
    assert "retry later" in proc.stderr
    assert "no last-good cache" in proc.stderr
    assert "Traceback" not in proc.stderr          # no raw traceback


def test_smoke_bench_emits_json_contract(tmp_path):
    proc = _run_bench({"R2D2_BENCH_CACHE": str(tmp_path / "cache.json")})
    assert proc.returncode == 0, proc.stderr[-4000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "learner_sequence_updates_per_sec_per_chip"
    assert out["unit"] == "sequences/s"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    assert out["matrix"]["f32_spd1"] == out["value"]
    assert "stale" not in out
    # the matrix is self-describing (VERDICT r4 #5): every cell carries a
    # status, and null cells name WHY they are null
    assert out["cell_status"]["f32_spd1"] in ("ok", "ok-reused")
    for k, v in out["matrix"].items():
        if v is None:
            assert out["cell_status"][k].startswith(
                ("skipped:", "not-run", "failed:", "mosaic-reject")), (
                k, out["cell_status"][k])
    # smoke CPU results are NOT cached (the cache carries the TPU number)
    assert not (tmp_path / "cache.json").exists()


def test_dispatch_failure_falls_back_to_stale_cache(tmp_path):
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps(FAKE_CACHE))
    proc = _run_bench({"R2D2_BENCH_SIMULATE_DISPATCH_FAILURE": "1",
                       "R2D2_BENCH_CACHE": str(cache)})
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["stale"] is True
    assert out["value"] == FAKE_CACHE["output"]["value"]
    assert out["stale_recorded_at"] == FAKE_CACHE["recorded_at"]
    assert "rc=42" in out["stale_reason"]          # the diagnosed-failure code
    # statuses synthesized for a pre-round-5 cache (value -> ok, null ->
    # unknown) so even a stale artifact is self-describing
    assert out["cell_status"] == {"bf16_spd16": "ok", "f32_spd4": "unknown"}


def test_genuine_crash_is_not_masked_by_stale_cache(tmp_path):
    # Only DIAGNOSED backend failures degrade to the cache; a code crash
    # must stay a loud nonzero exit or regressions hide behind old numbers.
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps(FAKE_CACHE))
    proc = _run_bench({"R2D2_BENCH_SIMULATE_CRASH": "1",
                       "R2D2_BENCH_CACHE": str(cache)})
    assert proc.returncode == 1
    assert "NOT masking" in proc.stderr
    assert not proc.stdout.strip()                 # no JSON emitted


def test_discovery_retry_then_stale_cache(tmp_path):
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps(FAKE_CACHE))
    proc = _run_bench({"JAX_PLATFORMS": "bogus_backend",
                       "R2D2_BENCH_ATTEMPTS": "2",
                       "R2D2_BENCH_CACHE": str(cache)})
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert proc.stderr.count("backend probe failed") == 2   # both attempts
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["stale"] is True
    assert "discovery failed 2x" in out["stale_reason"]


def test_child_deadline_falls_back_to_stale_cache(tmp_path):
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps(FAKE_CACHE))
    proc = _run_bench({"R2D2_BENCH_CHILD_TIMEOUT": "3",
                       "R2D2_BENCH_CACHE": str(cache)})
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["stale"] is True
    assert "deadline" in out["stale_reason"]


def test_supervisor_sigterm_unwinds_child_and_emits_stale(tmp_path):
    # A driver timeout SIGTERMs the supervisor mid-measurement; it must
    # unwind the (TPU-holding) child and still print a stale JSON line.
    import signal
    import time
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps(FAKE_CACHE))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update({"JAX_PLATFORMS": "cpu", "R2D2_BENCH_SMOKE": "1",
                "R2D2_BENCH_BACKOFF": "0",
                "R2D2_BENCH_CACHE": str(cache),
                "R2D2_BENCH_PARTIAL": str(tmp_path / "partial.json")})
    proc = subprocess.Popen([sys.executable, BENCH], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    # wait past the probe phase (the handler installs after it), then TERM
    deadline = time.time() + 120
    while time.time() < deadline:
        time.sleep(1)
        line = proc.stderr.readline()
        if "backend probe ok" in line:
            break
    time.sleep(3)
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=120)
    assert proc.returncode == 0, err[-4000:]
    result = json.loads(out.strip().splitlines()[-1])
    assert result["stale"] is True
    assert "signal" in result["stale_reason"]


def test_successful_run_records_cache(tmp_path):
    cache = tmp_path / "cache.json"
    proc = _run_bench({"R2D2_BENCH_CACHE": str(cache),
                       "R2D2_BENCH_FORCE_CACHE": "1"})
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    saved = json.loads(cache.read_text())
    assert saved["output"] == out
    assert saved["recorded_at"]


def test_mid_run_wedge_emits_partial_results(tmp_path):
    """A wedge AFTER cells have been measured must surface THIS run's
    fresh partial results (flagged partial=true), not last round's stale
    cache — a round-4 wedge in an optional late cell would otherwise have
    discarded nine fresh cells."""
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps(FAKE_CACHE))
    proc = _run_bench({"R2D2_BENCH_SIMULATE_HANG": "1",
                       "R2D2_BENCH_CHILD_TIMEOUT": "120",
                       "R2D2_BENCH_CACHE": str(cache),
                       "R2D2_BENCH_PARTIAL": str(tmp_path / "partial.json")},
                      timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out.get("partial") is True
    assert "deadline" in out["partial_reason"]
    assert out["matrix"]["f32_spd1"] is not None      # the measured cell
    assert out["value"] == out["matrix"][out["measured_config"]]
    assert "stale" not in out                         # fresh, not cached
    # the wedge triggered the resume pass (the CPU "backend" still answers
    # after a simulated hang): the rerun child must carry the measured cell
    # instead of re-paying its compile+timing window (VERDICT r4 #5)
    assert "re-running missing cells only" in proc.stderr
    assert "[f32_spd1] carried" in proc.stderr
    assert out["cell_status"]["f32_spd1"] in ("ok", "ok-reused", "carried")
    # smoke runs are not cache-worthy: the old cache must survive intact
    assert json.loads(cache.read_text()) == FAKE_CACHE


def test_partial_results_refresh_cache_when_forced(tmp_path):
    """The cacheable-partial branch: a partial carrying the headline cell
    may replace the older cache as the next fallback (gated to TPU +
    default-cell-measured in production; R2D2_BENCH_FORCE_CACHE exercises
    it here)."""
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps(FAKE_CACHE))
    proc = _run_bench({"R2D2_BENCH_SIMULATE_HANG": "1",
                       "R2D2_BENCH_CHILD_TIMEOUT": "120",
                       "R2D2_BENCH_FORCE_CACHE": "1",
                       "R2D2_BENCH_CACHE": str(cache),
                       "R2D2_BENCH_PARTIAL": str(tmp_path / "partial.json")},
                      timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out.get("partial") is True
    saved = json.loads(cache.read_text())
    assert saved["output"] == out            # fresh partial replaced the
    assert saved["output"]["partial"] is True  # 2026-01-01 FAKE_CACHE entry


def test_anomalous_default_cell_does_not_elect_headline():
    """assemble_output must not headline a value its own status says to
    disregard (code-review r5): an anomaly-flagged default cell falls back
    to the best clean cell."""
    import importlib.util
    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    ctx = {"default_label": "bf16_spd16", "batch_size": 128,
           "flops_per_step": 1e9, "peak": 0, "platform": "tpu",
           "device_kind": "fake"}
    matrix = {"f32_spd1": 6900.0, "bf16_spd16": 245.0}
    status = {"f32_spd1": "ok", "bf16_spd16": "anomaly"}
    out = bench.assemble_output({}, matrix, ctx, status)
    assert out["measured_config"] == "f32_spd1"
    assert out["value"] == 6900.0
    assert out["cell_status"]["bf16_spd16"] == "anomaly"
    # with a clean default the default cell elects as before
    status["bf16_spd16"] = "ok"
    matrix["bf16_spd16"] = 11290.0
    out = bench.assemble_output({}, matrix, ctx, status)
    assert out["measured_config"] == "bf16_spd16"


def test_resume_child_carries_partial_cells(tmp_path):
    """The R2D2_BENCH_RESUME child must seed already-measured cells from
    the partial snapshot (status 'carried') and skip their compile+timing
    windows entirely — run directly in child mode with a crafted partial."""
    import tempfile
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps({
        "results": {"xla_decode": 99.0},
        "matrix": {"f32_spd1": 99.0},
        "cell_status": {"f32_spd1": "ok"},
        "ctx": {}}))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update({"JAX_PLATFORMS": "cpu", "R2D2_BENCH_SMOKE": "1",
                "R2D2_BENCH_CHILD": "1", "R2D2_BENCH_RESUME": "1",
                "R2D2_BENCH_PARTIAL": str(partial)})
    proc = subprocess.run([sys.executable, BENCH], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["matrix"]["f32_spd1"] == 99.0        # carried, not re-run
    assert out["cell_status"]["f32_spd1"] == "carried"
    assert out["value"] == 99.0
    assert "[f32_spd1] carried" in proc.stderr
    assert "[xla_decode] carried" in proc.stderr    # results side too
    # no timing window ran: the carried run must not print a measured rate
    assert "train steps/s" not in proc.stderr
