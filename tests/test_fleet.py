"""Fleet observability tests (ISSUE 12): lockstep psum-row gauges on the
emulated mesh, FleetAggregator merge/skew math vs per-rank references,
the four fleet alert rules (incl. once-per-breach edge semantics),
host-row rotation, the clock-aligned cross-host trace merge on the
checked-in two-rank fixture, sentinel host streams, and record-schema /
psum-shape stability under the ``telemetry.fleet_enabled`` kill switch.

Single-process emulated meshes throughout (this container's CPU backend
lacks multiprocess collectives — known since PR 3); the loopback
two-process straggler A/B is the slow-marked test at the bottom.
"""

import json
import os

import jax
import numpy as np
import pytest

from r2d2_tpu.config import Config, MeshConfig
from r2d2_tpu.parallel.mesh import make_mesh
from r2d2_tpu.telemetry import AlertEngine, default_rules
from r2d2_tpu.telemetry.fleet import (FLEET_INFO_KEYS, FleetAggregator,
                                      RotatingJsonlWriter,
                                      host_row_path, merge_stage_counts,
                                      mesh_row_ranks, rank_first_rows,
                                      read_last_jsonl_row,
                                      stage_counts_dict,
                                      summarize_stage_counts)
from r2d2_tpu.tools.logparse import fleet_series, parse_jsonl

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "fleet_two_rank")

BASE_CFG = {
    "env.game_name": "Fake",
    "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
    "network.hidden_dim": 16, "network.cnn_out_dim": 32,
    "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
    "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
    "sequence.forward_steps": 3,
    "replay.capacity": 800, "replay.block_length": 20,
    "replay.batch_size": 4, "replay.learning_starts": 60,
    "actor.num_actors": 1,
    "runtime.save_interval": 0, "runtime.log_interval": 1.0,
    "runtime.weight_publish_interval": 2,
    "runtime.steps_per_dispatch": 1,
}


# ---------------------------------------------------------------------------
# Widened lockstep programs (emulated mesh, single controller)


def _spec():
    from r2d2_tpu.replay.structs import ReplaySpec
    return ReplaySpec.from_config(Config().replace(**BASE_CFG))


def _times(mesh, values):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.device_put(np.asarray(values, np.float32),
                          NamedSharding(mesh, P("dp")))


def test_lockstep_ingest_fleet_gauges():
    """The widened ingest returns the all-gathered step-time/env tables,
    the sum/max/min reductions, and the one-hot argmax straggler row —
    replicated, off the same dispatch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from r2d2_tpu.parallel.multihost import HostFeed, make_lockstep_ingest
    from r2d2_tpu.parallel.sharded import sharded_replay_init

    spec = _spec()
    mesh = make_mesh(MeshConfig(dp=4))
    rs = sharded_replay_init(spec, mesh)
    cum = jax.device_put(np.zeros((4,), np.int32),
                         NamedSharding(mesh, P("dp")))
    feed = HostFeed(spec, mesh)
    ing = make_lockstep_ingest(spec, mesh, fleet=True)
    rs, cum, info = ing(rs, cum, *feed.build(None, 0),
                        _times(mesh, [0.1, 0.4, 0.2, 0.3]))
    got = jax.device_get(info)
    np.testing.assert_allclose(got["step_times"], [0.1, 0.4, 0.2, 0.3],
                               rtol=1e-6)
    assert abs(float(got["step_time_sum"]) - 1.0) < 1e-6
    assert abs(float(got["step_time_max"]) - 0.4) < 1e-6
    assert abs(float(got["step_time_min"]) - 0.1) < 1e-6
    assert int(got["straggler_shard"]) == 1
    np.testing.assert_array_equal(got["env_steps_shards"], [0, 0, 0, 0])
    # every widened key is declared (the loop strips them by this list)
    assert set(FLEET_INFO_KEYS) <= set(got)


def test_lockstep_ingest_kill_switch_shape_identity():
    """fleet=False compiles the exact PR-10 program: 5 operands, the
    4-key info dict, no gauge outputs."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from r2d2_tpu.parallel.multihost import HostFeed, make_lockstep_ingest
    from r2d2_tpu.parallel.sharded import sharded_replay_init

    spec = _spec()
    mesh = make_mesh(MeshConfig(dp=2))
    rs = sharded_replay_init(spec, mesh)
    cum = jax.device_put(np.zeros((2,), np.int32),
                         NamedSharding(mesh, P("dp")))
    feed = HostFeed(spec, mesh)
    ing = make_lockstep_ingest(spec, mesh, fleet=False)
    _, _, info = ing(rs, cum, *feed.build(None, 0))
    assert sorted(jax.device_get(info).keys()) == [
        "buffer_steps", "env_steps", "filled_shards", "stop"]


def test_lockstep_consensus_fleet_rows():
    """The widened consensus gathers the raw (dp, 5) row table alongside
    the psum — per-rank step times and env steps readable on every rank;
    fleet=False keeps the PR-10 4-column psum."""
    from r2d2_tpu.parallel.multihost import make_lockstep_consensus

    mesh = make_mesh(MeshConfig(dp=2))
    con = make_lockstep_consensus(mesh, fleet=True)
    info = con(10, 20, True, 0, step_time_s=0.25)
    assert info["buffer_steps"] == 10 and info["env_steps"] == 20
    assert info["ready_procs"] == 1 and info["stop"] == 0
    # single process owns both rows; only the first carries data
    np.testing.assert_allclose(info["step_times"], [0.25, 0.0], atol=1e-6)
    assert abs(info["step_time_max"] - 0.25) < 1e-6
    assert info["straggler_shard"] == 0
    np.testing.assert_array_equal(info["env_steps_shards"], [20, 0])

    con0 = make_lockstep_consensus(mesh, fleet=False)
    assert sorted(con0(10, 20, True, 0).keys()) == [
        "buffer_steps", "env_steps", "ready_procs", "stop"]


def test_gspmd_lockstep_ingest_fleet_gauges():
    """The mp>1 (GSPMD) formulation returns the same widened contract."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from r2d2_tpu.parallel.multihost import (HostFeed,
                                             make_lockstep_ingest)
    from r2d2_tpu.parallel.sharded import sharded_replay_init

    spec = _spec()
    mesh = make_mesh(MeshConfig(dp=2, mp=2))
    rs = sharded_replay_init(spec, mesh)
    cum = jax.device_put(np.zeros((2,), np.int32),
                         NamedSharding(mesh, P("dp")))
    feed = HostFeed(spec, mesh)
    ing = make_lockstep_ingest(spec, mesh, fleet=True)
    _, _, info = ing(rs, cum, *feed.build(None, 0),
                     _times(mesh, [0.3, 0.1]))
    got = jax.device_get(info)
    np.testing.assert_allclose(got["step_times"], [0.3, 0.1], rtol=1e-6)
    assert int(got["straggler_shard"]) == 0
    assert abs(float(got["step_time_max"]) - 0.3) < 1e-6


def test_mesh_row_ranks_and_first_rows():
    mesh = make_mesh(MeshConfig(dp=4))
    ranks = mesh_row_ranks(mesh)
    assert ranks == [0, 0, 0, 0]          # single controller owns all rows
    assert rank_first_rows(ranks, 1) == [0]
    assert rank_first_rows([0, 0, 1, 1], 2) == [0, 2]
    with pytest.raises(ValueError, match="own no dp rows"):
        rank_first_rows([0, 0], 2)


# ---------------------------------------------------------------------------
# Stage-histogram merge parity


def test_stage_counts_merge_parity():
    """Rank 0's merge must equal the elementwise sum of the per-rank
    references — the PR-4 mergeability contract, through the JSON row
    round-trip."""
    from r2d2_tpu.telemetry import STAGES
    from r2d2_tpu.telemetry.core import summarize_matrix
    from r2d2_tpu.telemetry.histogram import NBUCKETS

    rng = np.random.default_rng(7)
    mats = [rng.integers(0, 20, size=(len(STAGES), NBUCKETS)).astype(
        np.int64) for _ in range(3)]
    # rows travel as JSON (host rows on the shared filesystem)
    dicts = [json.loads(json.dumps(stage_counts_dict(m))) for m in mats]
    merged = merge_stage_counts(dicts)
    ref = summarize_matrix(sum(mats))
    assert summarize_stage_counts(merged) == ref
    # sparse rows merge too: a rank missing a stage contributes nothing
    partial = merge_stage_counts([dicts[0], {}])
    assert summarize_stage_counts(partial) == summarize_matrix(mats[0])


# ---------------------------------------------------------------------------
# FleetAggregator skew/argmax math + the straggler acceptance fixture


def _feed_two_rank(agg, factor, iters=10, base=0.01, env_fast=100,
                   env_slow=100):
    """Synthetic two-rank lockstep: rank 1's step time is ``factor`` x
    rank 0's (the chaos slowxF shape); env counters advance per rank."""
    for i in range(1, iters + 1):
        times = np.array([base, base * factor], np.float64)
        agg.on_collective({
            "step_times": times,
            "step_time_sum": times.sum(),
            "step_time_max": times.max(),
            "step_time_min": times.min(),
            "env_steps_shards": np.array([env_fast * i, env_slow * i]),
            "straggler_shard": int(np.argmax(times)),
        }, wait_s=base * (factor - 1.0))
        agg.on_step(step_s=base * factor)   # lockstep: all run at F x base


def test_fleet_aggregator_names_injected_straggler():
    """The acceptance shape, fixture-replayed: chaos ``slowx4`` on rank 1
    -> the fleet block names rank 1 as the straggler with skew ~ F, and
    the lockstep wait fraction shows the fast rank blocked."""
    from r2d2_tpu.tools.chaos import parse_fault_spec

    factor = parse_fault_spec("1:slowx4")[1].factor
    agg = FleetAggregator(rank=0, nprocs=2, row_ranks=[0, 1],
                          save_dir=None)
    _feed_two_rank(agg, factor)
    block = agg.flush(now=1000.0)
    st = block["step_time"]
    assert st["straggler_rank"] == 1
    assert st["straggler_shard"] == 1              # the in-graph one-hot
    np.testing.assert_allclose(st["per_rank_ms"], [10.0, 40.0], rtol=1e-3)
    assert abs(st["skew"] - factor) < 0.05
    # the LAST collective's in-band psum/pmax/pmin gauges surface too
    ib = st["in_band_ms"]
    assert abs(ib["max"] - 40.0) < 1e-6 and abs(ib["min"] - 10.0) < 1e-6
    assert abs(ib["sum"] - 50.0) < 1e-6
    ls = block["lockstep"]
    # this rank stepped at F x base but spent (F-1) x base in the psum
    assert abs(ls["wait_frac"] - (factor - 1.0) / factor) < 0.01
    assert block["env_steps"]["divergence"] == 1.0
    # flush resets the interval; a fresh healthy interval reads balanced
    _feed_two_rank(agg, 1.0)
    block2 = agg.flush(now=1001.0)
    assert abs(block2["step_time"]["skew"] - 1.0) < 1e-6
    assert block2["step_time"]["per_rank_ms"][1] < 11.0


def test_fleet_aggregator_env_divergence_and_multirow_collapse():
    """Per-rank env accounting: a rank owning several dp rows sums them;
    interval deltas (not cumulative totals) drive the divergence ratio."""
    agg = FleetAggregator(rank=0, nprocs=2, row_ranks=[0, 0, 1, 1],
                          save_dir=None)
    for i, env in enumerate(([100, 100, 50, 50], [200, 200, 60, 60])):
        agg.on_collective({
            "step_times": np.full((4,), 0.01),
            "env_steps_shards": np.asarray(env),
        }, wait_s=0.001)
        agg.on_step(step_s=0.01)
        block = agg.flush(now=float(i))
    assert block["env_steps"]["per_rank"] == [400, 120]
    # interval deltas: rank0 +200, rank1 +20 -> 10x divergence
    assert block["env_steps"]["interval"] == [200, 20]
    assert abs(block["env_steps"]["divergence"] - 10.0) < 1e-6


def test_fleet_aggregator_host_row_fixture_replay():
    """Rank-0 flush over the checked-in two-rank fixture: rank 1's row
    ages off its wall stamp, its stage counts merge into the fleet
    stages view, and an absent rank is reported (not false-aged)."""
    agg = FleetAggregator(rank=0, nprocs=2, row_ranks=[0, 1],
                          save_dir=FIXTURE)
    _feed_two_rank(agg, 2.0, iters=3)
    local = {"learner/train_dispatch": [0] * 64}
    local["learner/train_dispatch"][40] = 5
    block = agg.flush(now=1012.6 + 100.0, local_stage_counts=local)
    hr = block["host_rows"]
    assert hr["absent_ranks"] == []
    assert abs(hr["ages_s"][1] - 100.0) < 1e-6      # now - rank1 wall
    assert hr["max_age_s"] == hr["ages_s"][1]
    # fixture rank 1 counts merged with the local matrix
    assert block["stages"]["actor/env_step"]["count"] == 400
    assert block["stages"]["learner/train_dispatch"]["count"] == 5
    assert block["stages"]["lockstep/dispatch"]["count"] == 40

    # a rank that never wrote a row: absent, never a fake age
    agg3 = FleetAggregator(rank=0, nprocs=3, row_ranks=[0, 1, 2],
                           save_dir=FIXTURE)
    _feed_two_rank(agg3, 1.0, iters=1)   # tables too short for 3 ranks: ok
    block3 = agg3.flush(now=2000.0)
    assert block3["host_rows"]["absent_ranks"] == [2]
    assert block3["host_rows"]["ages_s"][2] is None


# ---------------------------------------------------------------------------
# The four fleet alert rules


def _engine():
    return AlertEngine(default_rules(Config().telemetry))


def test_fleet_rules_present_and_parameterized():
    t = Config().replace(**{
        "telemetry.alerts_rank_straggler": 3.0,
        "telemetry.alerts_missing_rank_age_s": 60.0}).telemetry
    by_name = {r.name: r for r in default_rules(t)}
    assert by_name["rank_straggler"].path == ("fleet", "step_time", "skew")
    assert by_name["rank_straggler"].bound == 3.0
    assert by_name["lockstep_wait_frac"].path == (
        "fleet", "lockstep", "wait_frac")
    assert by_name["fleet_desync"].path == (
        "fleet", "env_steps", "divergence")
    assert by_name["missing_rank"].path == (
        "fleet", "host_rows", "max_age_s")
    assert by_name["missing_rank"].bound == 60.0
    assert by_name["missing_rank"].severity == "crit"


def _fleet_record(skew=1.0, wait=0.1, div=1.0, age=1.0):
    return {"fleet": {"step_time": {"skew": skew},
                      "lockstep": {"wait_frac": wait},
                      "env_steps": {"divergence": div},
                      "host_rows": {"max_age_s": age}}}


def test_rank_straggler_fires_exactly_once_per_breach():
    """The acceptance's edge contract: a sustained breach fires ONE
    alert, recovery re-arms, the next breach fires again."""
    eng = _engine()
    fired = []
    for rec in (_fleet_record(), _fleet_record(skew=4.0),
                _fleet_record(skew=4.2), _fleet_record(skew=1.1),
                _fleet_record(skew=5.0)):
        fired += [a["rule"] for a in eng.evaluate(rec)["fired"]]
    assert fired.count("rank_straggler") == 2
    # records with no fleet block (single-host runs) never activate it
    eng2 = _engine()
    out = eng2.evaluate({"buffer_speed": 10.0})
    assert "rank_straggler" not in out["active"]


def test_other_fleet_rules_fire_on_their_metrics():
    eng = _engine()
    out = eng.evaluate(_fleet_record(wait=0.9, div=10.0, age=500.0))
    names = {a["rule"] for a in out["fired"]}
    assert {"lockstep_wait_frac", "fleet_desync", "missing_rank"} <= names
    sev = {a["rule"]: a["severity"] for a in out["fired"]}
    assert sev["missing_rank"] == "crit"


# ---------------------------------------------------------------------------
# Host-row rotation


def test_rotating_writer_wraps_and_stays_parseable(tmp_path):
    path = str(tmp_path / "telemetry_host1.jsonl")
    w = RotatingJsonlWriter(path, max_bytes=600)
    for i in range(50):
        w.write({"rank": 1, "i": i, "pad": "x" * 40})
    assert w.rotations >= 1
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 600 + 80     # at most one row over
    live = parse_jsonl(path)
    prev = parse_jsonl(path + ".1")
    # no gaps across the rotation boundary, newest row in the live file
    seen = [r["i"] for r in prev + live]
    assert seen == sorted(seen) and seen[-1] == 49
    # partial trailing line (writer mid-append) stays tolerated
    with open(path, "a") as f:
        f.write('{"rank": 1, "i": 99')
    assert parse_jsonl(path)[-1]["i"] == seen[-1]
    assert read_last_jsonl_row(path)["i"] == seen[-1]
    # readers racing the rotation instant fall back to the .1 generation
    # (rotation also happens BEFORE the exceeding write, so the live
    # file normally always holds the newest row)
    os.remove(path)
    assert read_last_jsonl_row(path)["i"] == prev[-1]["i"]

    # fresh (non-resume) construction truncates live AND rotated files
    RotatingJsonlWriter(path, max_bytes=600)
    assert os.path.getsize(path) == 0 and not os.path.exists(path + ".1")


def test_rotating_writer_resume_appends(tmp_path):
    path = str(tmp_path / "telemetry_host1.jsonl")
    RotatingJsonlWriter(path).write({"i": 0})
    w = RotatingJsonlWriter(path, resume=True)
    w.write({"i": 1})
    assert [r["i"] for r in parse_jsonl(path)] == [0, 1]


def test_rotation_default_on_and_validated():
    cfg = Config()
    assert cfg.telemetry.fleet_host_row_max_bytes == 16 * 2**20
    with pytest.raises(ValueError, match="fleet_host_row_max_bytes"):
        Config().replace(**{"telemetry.fleet_host_row_max_bytes": -1})


# ---------------------------------------------------------------------------
# Cross-host trace merge on the checked-in fixture


def test_trace_merge_aligns_two_rank_fixture(tmp_path):
    """The fixture's rank-1 clock runs 2.5 s ahead (its anchor says so);
    after the merge both ranks' 'lockstep/it5' spans — the same true
    instant — land at the same trace timestamp, on per-rank tracks."""
    from r2d2_tpu.tools.inspect import (export_chrome_trace,
                                        fleet_clock_offsets)

    offsets, actors_per_rank = fleet_clock_offsets(FIXTURE)
    assert abs(offsets[1] - 2.5) < 1e-6 and offsets[0] == 0.0
    assert actors_per_rank == 1

    out = str(tmp_path / "trace.json")
    n = export_chrome_trace(FIXTURE, out)
    assert n == 4
    trace = json.load(open(out))["traceEvents"]
    pids = {e["args"]["name"]: e["pid"] for e in trace
            if e.get("name") == "process_name"}
    assert any(name.startswith("rank0/") for name in pids)
    assert any(name.startswith("rank1/") for name in pids)
    its = [e for e in trace if e.get("name") == "lockstep/it5"]
    assert len(its) == 2
    assert abs(its[0]["ts"] - its[1]["ts"]) < 1.0    # µs, aligned
    assert its[0]["pid"] != its[1]["pid"]            # separate tracks


def test_span_file_rank_mapping():
    from r2d2_tpu.tools.inspect import _span_file_rank
    assert _span_file_rank("spans_host3.jsonl", None) == 3
    assert _span_file_rank("spans_p0_a5.jsonl", 2) == 2
    assert _span_file_rank("spans_p0_a5.jsonl", None) is None
    assert _span_file_rank("spans_learner.jsonl", 2) is None


# ---------------------------------------------------------------------------
# Sentinel host-row / host-alert streams + logparse/plot series


def test_sentinel_host_rank_stream(tmp_path, capsys):
    """--host-rank replays a rank's host rows through the same engine:
    the fleet rules see the row's own fleet block."""
    from r2d2_tpu.tools import sentinel

    d = tmp_path / "run"
    d.mkdir()
    rows = [_fleet_record(), _fleet_record(wait=0.95)]
    for i, r in enumerate(rows):
        r.update({"t": float(i), "rank": 1})
    with open(d / "telemetry_host1.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    rc = sentinel.main(["--dir", str(d), "--host-rank", "1"])
    out = capsys.readouterr().out
    assert "lockstep_wait_frac" in out
    assert rc == 0                                   # warn, not crit


def test_sentinel_resume_after_shrink_rotation_vs_truncation(tmp_path):
    """A followed stream that shrank because of size-cap rotation must
    keep the engine (same run!) and surface the rotated generation's
    unread tail; a genuine truncation resets."""
    from r2d2_tpu.tools.sentinel import resume_after_shrink

    path = str(tmp_path / "telemetry_host1.jsonl")
    # rotation: 5 rows moved to .1, live file restarted with 1 row
    with open(path + ".1", "w") as f:
        for i in range(5):
            f.write(json.dumps({"i": i}) + "\n")
    with open(path, "w") as f:
        f.write(json.dumps({"i": 5}) + "\n")
    rotation, backlog = resume_after_shrink(path, seen=3)
    assert rotation and [r["i"] for r in backlog] == [3, 4]
    # all rotated rows already seen: rotation, empty backlog
    rotation, backlog = resume_after_shrink(path, seen=5)
    assert rotation and backlog == []
    # truncation: no rotated generation (or one shorter than seen)
    os.remove(path + ".1")
    rotation, backlog = resume_after_shrink(path, seen=3)
    assert not rotation and backlog == []


def test_sentinel_alerts_stream(tmp_path, capsys):
    from r2d2_tpu.tools import sentinel

    path = tmp_path / "alerts_host1.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"t": 1.0, "rule": "missing_rank",
                            "severity": "crit", "value": 300.0}) + "\n")
        f.write(json.dumps({"t": 2.0, "rule": "rank_straggler",
                            "severity": "warn", "value": 4.0}) + "\n")
    rc = sentinel.main(["--alerts-stream", str(path)])
    out = capsys.readouterr().out
    assert rc == 1 and "missing_rank" in out and "rank_straggler" in out
    assert sentinel.main(["--alerts-stream",
                          str(tmp_path / "nope.jsonl")]) == 2


def test_fleet_series_extraction():
    records = [
        {"t": 1.0, "training_steps": 5},              # no block: skipped
        {"t": 2.0, "training_steps": 10,
         "fleet": {"lockstep": {"wait_frac": 0.4},
                   "step_time": {"skew": 3.96, "straggler_rank": 1,
                                 "mean_ms": 620.0, "max_ms": 990.0,
                                 "per_rank_ms": [250.0, 990.0]},
                   "env_steps": {"divergence": 1.5},
                   "host_rows": {"max_age_s": 2.0}}},
    ]
    s = fleet_series(records)
    assert s["t"] == [2.0]
    assert s["wait_frac"] == [0.4]
    assert s["skew"] == [3.96] and s["straggler_rank"] == [1]
    assert s["per_rank_ms"] == [[250.0, 990.0]]
    assert s["divergence"] == [1.5] and s["max_age_s"] == [2.0]


def test_inspect_fleet_panels_render():
    from r2d2_tpu.tools.inspect import (render_fleet, render_host_rows,
                                        render_record)

    rows = parse_jsonl(os.path.join(FIXTURE, "telemetry_host0.jsonl")) \
        + parse_jsonl(os.path.join(FIXTURE, "telemetry_host1.jsonl"))
    panel = render_fleet(rows[0]["fleet"])
    assert "straggler=rank 1" in panel and "skew=3.96" in panel
    per_rank = render_host_rows(rows)
    assert "rank 0" in per_rank and "rank 1" in per_rank
    assert "wait=40%" in per_rank        # rank 0's row view
    # the full record path renders the fleet panel + per-rank lines
    frame = render_record({"t": 1.0, "fleet": rows[0]["fleet"]},
                          host_rows=rows)
    assert "fleet: 2 rank(s)" in frame and "per-rank" in frame


# ---------------------------------------------------------------------------
# Config round-trip + schema stability


def test_pre_pr12_config_dicts_round_trip():
    d = Config().to_dict()
    for key in list(d["telemetry"]):
        if key.startswith("fleet_") or key in (
                "alerts_rank_straggler", "alerts_lockstep_wait_frac",
                "alerts_fleet_desync", "alerts_missing_rank_age_s"):
            del d["telemetry"][key]
    cfg = Config.from_dict(d)
    assert cfg.telemetry.fleet_enabled is True
    assert cfg.telemetry.alerts_rank_straggler == 2.0
    for bad, val in (("alerts_rank_straggler", 1.0),
                     ("alerts_lockstep_wait_frac", 0.0),
                     ("alerts_fleet_desync", 1.0),
                     ("alerts_missing_rank_age_s", 0.0)):
        with pytest.raises(ValueError, match=bad):
            Config().replace(**{f"telemetry.{bad}": val})


def test_record_schema_stable_without_fleet(tmp_path):
    """TrainMetrics: no set_fleet call (single-host runs, or the kill
    switch) -> no 'fleet' key; one call -> exactly one record carries
    it, then it is consumed."""
    from r2d2_tpu.runtime.metrics import TrainMetrics

    m = TrainMetrics(0, str(tmp_path))
    rec = m.log(1.0)
    assert "fleet" not in rec
    m.set_fleet({"ranks": 2})
    assert m.log(1.0)["fleet"] == {"ranks": 2}
    assert "fleet" not in m.log(1.0)


# ---------------------------------------------------------------------------
# Slow e2e slices: the real lockstep loop (single controller), and the
# two-process loopback straggler A/B (needs multiprocess collectives).


@pytest.mark.slow
def test_fleet_e2e_single_controller(tmp_path):
    """The full lockstep trainer as one controller over an emulated dp=2
    mesh: records carry a live fleet block (wait fraction, gauge tables,
    host-row section), rank 0 writes its anchored host row, and the
    trace export aligns without error."""
    from r2d2_tpu.parallel.multihost import train_multihost
    from r2d2_tpu.tools.inspect import export_chrome_trace

    d = str(tmp_path / "mh")
    cfg = Config().replace(**dict(
        BASE_CFG, **{"mesh.dp": 2, "runtime.save_dir": d}))
    records = []
    out = train_multihost(cfg, max_training_steps=6, max_seconds=180,
                          actor_mode="thread", log_fn=records.append)
    assert out["step"] >= 6
    fleet = [r["fleet"] for r in records if r.get("fleet")]
    assert fleet, "no fleet block reached the records"
    fb = fleet[-1]
    assert fb["ranks"] == 1 and fb["lockstep"]["dispatches"] > 0
    assert fb["lockstep"]["wait_frac"] is not None
    assert fb["step_time"]["per_rank_ms"]
    rows = parse_jsonl(os.path.join(d, "telemetry_host0.jsonl"))
    assert rows and rows[-1]["clock_anchor"]["it"] == 1
    assert rows[-1]["stage_counts"]
    n = export_chrome_trace(d, str(tmp_path / "trace.json"))
    assert n > 0


@pytest.mark.slow
def test_fleet_e2e_kill_switch_schema(tmp_path):
    """fleet_enabled=false through the real loop: records byte-free of
    the fleet key, no rank-0 host row, the PR-10 file set."""
    from r2d2_tpu.parallel.multihost import train_multihost

    d = str(tmp_path / "mh_off")
    os.makedirs(d)
    # a previous fleet-on run's stale rank-0 host row must be cleaned
    # up, not rendered as if it belonged to this run
    stale = os.path.join(d, "telemetry_host0.jsonl")
    with open(stale, "w") as f:
        f.write(json.dumps({"rank": 0, "clock_anchor": {"wall": 1.0}})
                + "\n")
    cfg = Config().replace(**dict(
        BASE_CFG, **{"mesh.dp": 2, "runtime.save_dir": d,
                     "telemetry.fleet_enabled": False}))
    records = []
    train_multihost(cfg, max_training_steps=4, max_seconds=180,
                    actor_mode="thread", log_fn=records.append)
    assert records and not any("fleet" in r for r in records)
    assert not os.path.exists(stale)


@pytest.mark.slow
def test_fleet_loopback_two_rank_straggler(tmp_path, monkeypatch):
    """The loopback two-process A/B (the acceptance's first path where
    the backend allows): chaos slowx3 injected on rank 1's loop — rank
    0's fleet block must name rank 1 as the straggler, and the
    rank_straggler firing must land in alerts_player0.jsonl. Requires
    multiprocess collectives (fails on backends without them — the
    known PR-3 limitation; the fixture-replay tests above are the
    container-portable acceptance)."""
    from r2d2_tpu.parallel.multihost import launch_demo

    monkeypatch.setenv("R2D2_MH_CHAOS_STRAGGLER", "1:slowx3")
    save_dir = str(tmp_path / "mh_straggler")
    launch_demo(num_processes=2, devices_per_process=2, save_dir=save_dir,
                max_steps=8, timeout=280.0)
    records = parse_jsonl(os.path.join(save_dir, "metrics_player0.jsonl"))
    fleet = [r["fleet"] for r in records if r.get("fleet")]
    assert fleet, "rank 0 logged no fleet block"
    skews = [f["step_time"]["skew"] for f in fleet
             if f.get("step_time", {}).get("skew")]
    assert skews and max(skews) > 1.5
    stragglers = {f["step_time"].get("straggler_rank") for f in fleet
                  if f.get("step_time")}
    assert 1 in stragglers
    # rank 1 wrote its own anchored, alert-bearing host row
    rows = parse_jsonl(os.path.join(save_dir, "telemetry_host1.jsonl"))
    assert rows and rows[-1].get("clock_anchor")
