"""Evaluation CLI + multiplayer population training (hermetic)."""

import numpy as np
import pytest

from r2d2_tpu.runtime.checkpoint import list_checkpoints
from r2d2_tpu.runtime.orchestrator import train

from tests.test_runtime import tiny_config


@pytest.mark.slow
def test_multiplayer_population_two_stacks(tmp_path):
    """multiplayer.enabled trains num_players complete stacks concurrently
    (ref train.py:28-45) — each with its own learner, buffer, and log."""
    cfg = tiny_config(tmp_path, **{
        "multiplayer.enabled": True, "multiplayer.num_players": 2,
        "actor.num_actors": 1,
        "replay.learning_starts": 60,
    })
    stacks = train(cfg, max_training_steps=3, max_seconds=240,
                   actor_mode="thread")
    assert len(stacks) == 2
    for p, st in enumerate(stacks):
        assert int(st.learner.train_state.step) >= 3
        assert (tmp_path / f"train_player{p}.log").exists()
    # the two populations trained independently (different sampled data)
    import jax
    a = jax.tree_util.tree_leaves(stacks[0].learner.train_state.params)[0]
    b = jax.tree_util.tree_leaves(stacks[1].learner.train_state.params)[0]
    assert not np.allclose(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_multiplayer_play_runs_evaluators_concurrently(tmp_path, monkeypatch):
    """--play with N checkpoints must run N evaluators simultaneously (the
    host stays alive while joiners connect — ref test.py:129-144). A barrier
    inside env.reset can only be passed if both evaluators are live at once;
    a sequential loop deadlocks it (BrokenBarrierError after timeout)."""
    import threading

    from r2d2_tpu.envs import factory as factory_mod
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.learner_loop import Learner

    cfg = tiny_config(tmp_path)
    probe = create_env(cfg.env)
    net = NetworkApply(probe.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    probe.close()
    learner = Learner(cfg, net)
    ckpt_a = learner.save(1)
    ckpt_b = learner.save(2)

    barrier = threading.Barrier(2)
    real_create = factory_mod.create_env

    def synced_create(env_cfg, **kw):
        env = real_create(env_cfg, **kw)
        orig_reset = env.reset
        armed = [True]

        def reset(*a, **k):
            if armed[0]:
                armed[0] = False
                barrier.wait(timeout=60)   # both evaluators or bust
            return orig_reset(*a, **k)

        env.reset = reset
        return env

    monkeypatch.setattr(factory_mod, "create_env", synced_create)

    from r2d2_tpu.cli.evaluate import main
    main(["--play", ckpt_a, ckpt_b, "--rounds", "1"])
    assert barrier.n_waiting == 0


def test_multiplayer_play_host_death_surfaces_and_closes_joiner(
        tmp_path, monkeypatch):
    """Host-death path (VERDICT r2 #7): the host evaluator fails, the joiner
    is blocked mid-reset waiting for a game that will never exist. The CLI
    must surface the host's error as SystemExit within the grace window
    (not hang), and must close the abandoned joiner's env so no engine
    process leaks."""
    import threading
    import time as time_mod

    from r2d2_tpu.envs import factory as factory_mod
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.learner_loop import Learner

    cfg = tiny_config(tmp_path)
    probe = create_env(cfg.env)
    net = NetworkApply(probe.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    probe.close()
    learner = Learner(cfg, net)
    ckpt_a = learner.save(1)
    ckpt_b = learner.save(2)

    real_create = factory_mod.create_env
    release = threading.Event()
    joiner_env = []

    def faulty_create(env_cfg, **kw):
        if kw.get("is_host"):
            raise RuntimeError("host engine failed to start")
        env = real_create(env_cfg, **kw)
        joiner_env.append(env)
        orig_reset, orig_close = env.reset, env.close

        def reset(*a, **k):
            release.wait(timeout=30)   # joiner parked on the dead host
            return orig_reset(*a, **k)

        def close():
            release.set()              # closing the env unblocks the joiner
            env.closed = True
            return orig_close()

        env.reset = reset
        env.close = close
        return env

    monkeypatch.setattr(factory_mod, "create_env", faulty_create)

    from r2d2_tpu.cli.evaluate import main
    t0 = time_mod.time()
    with pytest.raises(SystemExit, match="host engine failed to start"):
        main(["--play", ckpt_a, ckpt_b, "--rounds", "1",
              "--grace-window", "2", "--straggler-window", "5"])
    assert time_mod.time() - t0 < 25.0, "CLI hung past the grace window"
    assert joiner_env and getattr(joiner_env[0], "closed", False), (
        "abandoned joiner's env was not closed")


@pytest.mark.slow
def test_evaluate_checkpoint_sweep(tmp_path):
    cfg = tiny_config(tmp_path, **{"replay.learning_starts": 60,
                                   "runtime.save_interval": 2})
    train(cfg, max_training_steps=4, max_seconds=240, actor_mode="thread")
    ckpts = list_checkpoints(str(tmp_path), "Fake", 0)
    assert len(ckpts) >= 2

    from r2d2_tpu.cli.evaluate import evaluate_checkpoint
    mean_ret, step, env_steps = evaluate_checkpoint(cfg, ckpts[-1][1], rounds=2)
    assert np.isfinite(mean_ret)
    assert step >= 0 and env_steps >= 0

    # the full CLI sweep path: thread-pool evaluation + curve plot
    from r2d2_tpu.cli.evaluate import main
    out = str(tmp_path / "eval_curve.png")
    main(["--rounds", "1", "--workers", "2", "--out", out,
          "--env.game_name=Fake", "--env.frame_height=24",
          "--env.frame_width=24", "--env.frame_stack=2",
          "--network.hidden_dim=16", "--network.cnn_out_dim=32",
          f"--runtime.save_dir={tmp_path}"])
    import os
    assert os.path.getsize(out) > 1000
