"""Batched/pipelined ReplayService data-plane tests (ISSUE 16): grouped
multi-shard ingest bit-parity with the sequential path (ring wrap, spill
demotion mid-group, lane routing, staleness stamps), the AOT chunk plan,
the windowed socket rung's cumulative acks under chaos-grammar ack drops,
spilled-page priority write-backs (ROADMAP 4a), priority-aware async
spill prefetch, the service-mode sample stager vs the legacy step,
producer-pump wiring (parallel/multihost.run_replay_producer), the
batched frame writer, the new fleet knobs' round-trip/validation, and
the ingest_backlog alert rule.
"""

import threading

import numpy as np
import pytest

from tests.test_elastic import assert_trees_equal
from tests.test_replay import _fill_blocks, make_spec

from r2d2_tpu.config import Config
from r2d2_tpu.fleet.replay_service import (RemoteReplayProducer,
                                           ReplayProducerPump, ReplayService,
                                           ReplayServiceServer, SpillTier)
from r2d2_tpu.tools.chaos import parse_fault_spec

import jax


def _ring_equal(a, b):
    assert a.ring.ptr == b.ring.ptr
    assert a.ring.total_adds == b.ring.total_adds
    assert a.ring.buffer_steps == b.ring.buffer_steps
    assert a.ring.slot_steps == b.ring.slot_steps
    assert a.ring.slot_versions == b.ring.slot_versions


def _spill_equal(a, b):
    assert a.spill.occupancy == b.spill.occupancy
    assert list(a.spill._pages.keys()) == list(b.spill._pages.keys())
    for pid in a.spill._pages:
        (ba, la, va), (bb, lb, vb) = a.spill._pages[pid], b.spill._pages[pid]
        assert (la, va) == (lb, vb)
        assert_trees_equal(ba, bb)
    assert a._demote_ids == b._demote_ids


# ---------------------------------------------------------------------------
# Grouped ingest: bit-parity with the sequential path.


@pytest.mark.parametrize("spill", [0, 3])
@pytest.mark.parametrize("route", ["round_robin", "lane"])
def test_grouped_ingest_bit_parity(rng, spill, route):
    """add_blocks at ingest_batch_blocks=4 is BIT-identical to the same
    blocks through sequential add_block calls — routed shards, ring
    state (incl. wrap + weight_version/lane stamps), accountant, spill
    demotion order, and the write-back routing table all match. Lane
    blocks include unstamped (-1) ones so the round-robin fallback
    counter is exercised inside a group."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 11, rng)
    stamped = []
    for k, blk in enumerate(blocks):
        lane = k % 3 if (route == "lane" and k % 4 != 3) else -1
        stamped.append(blk.replace(
            lane=np.asarray(lane, np.int32),
            weight_version=np.asarray(k, np.int32)))
    svc = ReplayService(spec, 2, spill_blocks=spill, route=route,
                        ingest_batch_blocks=4)
    ref = ReplayService(spec, 2, spill_blocks=spill, route=route)
    routed = svc.add_blocks(stamped)
    want = [ref.add_block(b) for b in stamped]
    assert routed == want
    for got, exp in zip(svc.shards, ref.shards):
        assert_trees_equal(got.state, exp.state)
        _ring_equal(got, exp)
        _spill_equal(got, exp)
    iv = svc.interval_block()["ingest"]
    assert iv["blocks"] == 11 and iv["dispatches"] < 11
    assert "ingest" not in ref.interval_block()


def test_grouped_ingest_chunk_plan_and_aot_coverage(rng):
    """The chunk rule (group size while enough blocks remain, largest
    pow2 on the tail, 1 via the per-block jit) and the AOT plan that
    covers it: 11 blocks into one shard at group 4 = chunks 4+4+2+1."""
    spec = make_spec(num_blocks=8)
    svc = ReplayService(spec, 1, ingest_batch_blocks=4)
    cov = svc.aot_chunk_coverage()
    assert cov == {"expected": [2, 4], "compiled": [2, 4],
                   "complete": True}
    blocks = _fill_blocks(spec, 11, rng)
    svc.add_blocks(blocks)
    iv = svc.interval_block()["ingest"]
    assert iv["blocks"] == 11 and iv["dispatches"] == 4
    assert iv["blocks_per_dispatch"] == round(11 / 4, 2)
    svc.note_backlog(100)
    assert svc.interval_block()["ingest"]["backlog"] == 100


def test_default_knobs_keep_pr15_record_schema(rng):
    """Off-defaults = PR 15 byte-identity: add_blocks routes through the
    sequential path and the telemetry block carries neither the ingest
    sub-block nor the spill prefetch keys."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 3, rng)
    svc = ReplayService(spec, 2)
    ref = ReplayService(spec, 2)
    assert svc.add_blocks(blocks) == [ref.add_block(b) for b in blocks]
    for got, exp in zip(svc.shards, ref.shards):
        assert_trees_equal(got.state, exp.state)
    block = svc.interval_block()
    assert "ingest" not in block
    assert "prefetch" not in block["spill"]


# ---------------------------------------------------------------------------
# Windowed socket rung: pipelined frames, cumulative acks, drop healing.


def test_windowed_socket_cumulative_acks_under_drops(rng):
    """The chaos grammar's drop_ack@every=N injection against the
    windowed producer: every block still lands exactly once (cumulative
    acks absorb the dropped ones), flush reaps the full window, and the
    server's shard states match a direct grouped ingest."""
    fault = parse_fault_spec("0:drop_ack@every=2")[0]
    assert fault.kind == "drop_ack" and fault.block == 2
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 12, rng)
    svc = ReplayService(spec, 2, ingest_batch_blocks=4)
    ref = ReplayService(spec, 2, ingest_batch_blocks=4)
    server = ReplayServiceServer(svc, drop_ack_every=fault.block)
    producer = RemoteReplayProducer(server.host, server.port, window=3)
    try:
        for i in range(0, 12, 4):
            producer.add_blocks(blocks[i:i + 4])
        acked = producer.flush()
        assert acked == 12 and producer.inflight == 0
        assert producer.frames_sent == 3
        assert server.blocks_received == 12
        assert server.acks_dropped >= 1
        stats = server.interval_stats()
        assert stats["blocks"] == 12 and stats["frames"] == 3
        assert stats["window_max"] >= 1 and stats["acks_dropped"] >= 1
        assert stats["blocks_total"] == 12
        # reset-on-read except the lifetime total
        assert server.interval_stats()["blocks"] == 0
        for i in range(0, 12, 4):
            ref.add_blocks(blocks[i:i + 4])
        for got, exp in zip(svc.shards, ref.shards):
            assert_trees_equal(got.state, exp.state)
    finally:
        producer.close()
        server.close()


def test_window_stall_heals_via_flush_probe(rng):
    """EVERY data ack dropped + window 1 (each send must reap an ack
    before returning): the producer's recv times out, sends a flush
    probe — always acked — and the cumulative ack covers the stalled
    frame. No deadlock, no duplicate delivery."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 2, rng)
    svc = ReplayService(spec, 1, ingest_batch_blocks=2)
    server = ReplayServiceServer(svc, drop_ack_every=1)
    producer = RemoteReplayProducer(server.host, server.port,
                                    dial_timeout=0.5, window=1)
    try:
        producer.add_blocks(blocks, timeout=0.5)
        assert producer.blocks_acked == 2 and producer.inflight == 0
        assert server.blocks_received == 2
        assert server.acks_dropped == 1
    finally:
        producer.close()
        server.close()


def test_producer_pump_and_run_replay_producer(rng):
    """The producer-only-host wiring end-to-end: blocks emitted into a
    BlockQueue reach a remote service via stacked windowed frames
    (parallel/multihost.run_replay_producer), landing bit-identical to
    local sequential adds."""
    from r2d2_tpu.parallel.multihost import run_replay_producer
    from r2d2_tpu.runtime.feeder import BlockQueue
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 6, rng)
    svc = ReplayService(spec, 2, ingest_batch_blocks=4)
    ref = ReplayService(spec, 2)
    server = ReplayServiceServer(svc)
    queue = BlockQueue(use_mp=False)
    for blk in blocks:
        queue.put(blk)
    stop = threading.Event()
    stop.set()                      # drain-then-exit
    try:
        stats = run_replay_producer(queue, server.host, server.port,
                                    window=2, group=4, stop=stop)
        assert stats["blocks_sent"] == 6
        assert stats["blocks_acked"] == 6
        assert server.blocks_received == 6
        for blk in blocks:
            ref.add_block(blk)
        for got, exp in zip(svc.shards, ref.shards):
            assert_trees_equal(got.state, exp.state)
    finally:
        server.close()


def test_feeder_drain_groups(rng):
    """drain_groups turns a deep backlog into window-sized stacked
    frames in arrival order."""
    from r2d2_tpu.runtime.feeder import BlockQueue
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 5, rng)
    queue = BlockQueue(use_mp=False)
    for blk in blocks:
        queue.put(blk)
    groups = queue.drain_groups(group=2, max_groups=4)
    assert [k for _, k in groups] == [2, 2, 1]
    got = np.concatenate([np.asarray(g.priority).reshape(k, -1)
                          for g, k in groups])
    want = np.stack([np.asarray(b.priority) for b in blocks])
    np.testing.assert_array_equal(got, want.reshape(5, -1))
    assert queue.drain_groups(group=2) == []


def test_send_frames_wire_identity():
    """The batched frame writer's bytes are indistinguishable from N
    send_frame calls — the receiver reads them back one by one."""
    import socket

    from r2d2_tpu.serve.transport import recv_frame, send_frames
    a, b = socket.socketpair()
    try:
        objs = [("addw", 1, 0, 2, {"x": np.arange(3)}), ("flushw", 2), "z"]
        send_frames(a, objs, threading.Lock())
        for want in objs:
            got = recv_frame(b)
            if isinstance(want, tuple):
                assert got[:-1] == want[:-1] if isinstance(
                    want[-1], dict) else got == want
                if isinstance(want[-1], dict):
                    np.testing.assert_array_equal(got[-1]["x"],
                                                  want[-1]["x"])
            else:
                assert got == want
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# Spilled-page write-backs (ROADMAP 4a) + priority-aware prefetch.


def test_stale_writeback_routes_to_spilled_pages(rng):
    """With the spill tier retaining evicted blocks, a write-back whose
    sampled rows were overwritten routes those rows' |TD| into the
    demoted pages' priority arrays; the fresh rows land through the
    same-shape padded update, identically to applying them alone."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 6, rng)
    svc = ReplayService(spec, 1, spill_blocks=4, promote_per_sample=0)
    ref = ReplayService(spec, 1, spill_blocks=4, promote_per_sample=0)
    for blk in blocks[:4]:
        svc.add_block(blk)
        ref.add_block(blk)
    snap = svc.shards[0].ring.total_adds
    for blk in blocks[4:]:          # overwrite rows 0 and 1, demoting them
        svc.add_block(blk)
        ref.add_block(blk)
    spb = spec.seqs_per_block
    idxes = np.asarray([0 * spb + 2, 1 * spb + 1, 2 * spb, 3 * spb + 3],
                       np.int32)
    tds = np.asarray([5.0, 7.0, 1.5, 2.5], np.float32)
    svc.update_priorities(0, idxes, tds, adds_snapshot=snap)
    assert svc.spilled_writebacks == 2 and svc.stale_rows_dropped == 0
    assert svc.stale_writebacks == 0
    for slot, seq, td in ((0, 2, 5.0), (1, 1, 7.0)):
        pid = svc.shards[0]._demote_ids[slot]
        page_block = svc.shards[0].spill._pages[pid][0]
        assert float(np.asarray(page_block.priority)[seq]) == td
        assert svc.shards[0].spill._prio[pid] >= td
    # fresh rows == applying them alone on the reference
    ref.shards[0].update_priorities(idxes[2:], tds[2:])
    assert_trees_equal(svc.shards[0].state, ref.shards[0].state)
    assert svc.shards[0].spill.writebacks == 2


def test_stale_writeback_whole_drop_without_spill(rng):
    """spill_blocks=0 keeps the PR-14/15 semantics exactly: any stale
    row drops the WHOLE batch (there is no page to route to)."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 5, rng)
    svc = ReplayService(spec, 1, promote_per_sample=0)
    for blk in blocks[:4]:
        svc.add_block(blk)
    snap = svc.shards[0].ring.total_adds
    svc.add_block(blocks[4])        # overwrites row 0
    tree_before = np.asarray(svc.shards[0].state.tree).copy()
    spb = spec.seqs_per_block
    svc.update_priorities(0, np.asarray([0, 2 * spb], np.int32),
                          np.asarray([9.0, 9.0], np.float32),
                          adds_snapshot=snap)
    assert svc.stale_writebacks == 1 and svc.spilled_writebacks == 0
    np.testing.assert_array_equal(np.asarray(svc.shards[0].state.tree),
                                  tree_before)


def test_promote_best_order_and_writeback_reorder(rng):
    """promote_best pops pages in stored-priority order, and a
    write_back re-orders the heap (lazy deletion: the stale entry is
    skipped). Eviction stays LRU regardless of priority."""
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 4, rng)
    tier = SpillTier(4)
    prios = [1.0, 5.0, 3.0, 2.0]
    pids = []
    for blk, p in zip(blocks, prios):
        prio = np.full_like(np.asarray(blk.priority), p)
        pids.append(tier.demote(blk.replace(priority=prio), 5, -1))
    assert pids == [1, 2, 3, 4]
    first = tier.promote_best()
    assert float(np.max(np.asarray(first[0].priority))) == 5.0
    # raise the lowest page above everything else
    assert tier.write_back(pids[0], 0, 9.0)
    second = tier.promote_best()
    assert float(np.max(np.asarray(second[0].priority))) == 9.0
    assert not tier.write_back(pids[1], 0, 1.0)     # already promoted
    # LRU eviction removes the page from the heap's reachable set
    small = SpillTier(1)
    small.demote(blocks[0].replace(
        priority=np.full_like(np.asarray(blocks[0].priority), 8.0)), 5, -1)
    small.demote(blocks[1].replace(
        priority=np.full_like(np.asarray(blocks[1].priority), 2.0)), 5, -1)
    assert small.evictions == 1
    best = small.promote_best()
    assert float(np.max(np.asarray(best[0].priority))) == 2.0
    assert small.promote_best() is None


def test_spill_prefetch_moves_promotion_off_sample_path(rng):
    """spill_prefetch=True: sample() performs NO inline promotion (the
    batch is exactly replay_sample); the write-back kicks the async
    pass, which promotes the highest-priority page."""
    from r2d2_tpu.replay import replay_sample
    spec = make_spec(num_blocks=4)
    blocks = _fill_blocks(spec, 6, rng)
    svc = ReplayService(spec, 1, spill_blocks=4, promote_per_sample=1,
                        spill_prefetch=True)
    try:
        for blk in blocks:
            svc.add_block(blk)
        assert svc.shards[0].spill.occupancy == 2
        state_before = svc.shards[0].state
        key = jax.random.PRNGKey(3)
        batch, shard, snap = svc.sample(key)
        assert svc.shards[0].spill.occupancy == 2   # promotion skipped
        assert_trees_equal(batch, replay_sample(spec, state_before, key))
        best_prio = max(svc.shards[0].spill._prio.values())
        svc.update_priorities(shard, batch.idxes,
                              np.zeros(spec.batch_size, np.float32))
        svc.drain_prefetch()
        assert svc.shards[0].spill.promotions == 1  # async pass ran
        # each promotion's ring re-entry demotes the overwritten
        # resident, so occupancy cycles rather than shrinking — and the
        # page that came back was the heap's best (it is gone from the
        # tier's priority map)
        assert svc.shards[0].spill.occupancy == 2
        assert best_prio not in svc.shards[0].spill._prio.values()
        block = svc.interval_block()
        assert block["spill"]["prefetch"] is True
        assert block["spill"]["prefetch_promotions"] == 1
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# Service-mode sample staging (learner pipeline parity).


def _svc_cfg(tmp_path, **extra):
    base = {
        "env.game_name": "Fake",
        "env.frame_height": 12, "env.frame_width": 12, "env.frame_stack": 2,
        "network.hidden_dim": 8, "network.cnn_out_dim": 16,
        "network.conv_layers": ((4, 3, 2),),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 160, "replay.block_length": 20,
        "replay.batch_size": 4, "replay.learning_starts": 40,
        "runtime.save_interval": 0, "runtime.steps_per_dispatch": 1,
        "runtime.save_dir": str(tmp_path),
        "fleet.replay_shards": 2,
    }
    base.update(extra)
    return Config().replace(**base)


@pytest.mark.slow
def test_service_stager_matches_legacy_step(rng, tmp_path):
    """fleet.sample_staging: the staged learner's first step trains on
    the SAME batch the legacy step draws (same service key sequence,
    same priorities) and its grouped write-back lands the identical
    replay state; further steps keep training and shutdown joins the
    stager threads."""
    import time

    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.learner_loop import Learner
    cfg_sync = _svc_cfg(tmp_path / "sync")
    cfg_staged = _svc_cfg(tmp_path / "staged",
                          **{"fleet.sample_staging": True})
    net = NetworkApply(4, cfg_sync.network, cfg_sync.env.frame_stack,
                       cfg_sync.env.frame_height, cfg_sync.env.frame_width)
    sync, staged = Learner(cfg_sync, net, 0), Learner(cfg_staged, net, 0)
    try:
        from r2d2_tpu.replay.structs import ReplaySpec
        blocks = _fill_blocks(ReplaySpec.from_config(cfg_sync), 4, rng)
        for blk in blocks:
            sync.ingest(blk)
            staged.ingest(blk)
        assert sync.ready and staged.ready
        m_sync = sync.step()
        m_staged = staged.step()
        np.testing.assert_allclose(float(m_staged["loss"]),
                                   float(m_sync["loss"]), rtol=1e-6)
        want = [np.asarray(s.state.tree) for s in sync.service.shards]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            got = [np.asarray(s.state.tree) for s in staged.service.shards]
            if all(np.array_equal(g, w) for g, w in zip(got, want)):
                break
            time.sleep(0.01)
        else:
            raise AssertionError("staged write-back never matched the "
                                 "legacy step's replay state")
        for _ in range(3):
            staged.step()
        assert staged.training_steps == 4
        assert staged.service.stale_writebacks == 0
    finally:
        sync.stop_background()
        staged.stop_background()
        assert not any(t.is_alive() for t in staged._svc_threads)


# ---------------------------------------------------------------------------
# Config + alert plumbing.


def test_ingest_config_round_trip():
    cfg = Config().replace(**{
        "fleet.replay_shards": 2, "fleet.ingest_batch_blocks": 8,
        "fleet.spill_blocks": 10, "fleet.spill_prefetch": True,
        "fleet.sample_staging": True,
        "fleet.service_transport": "socket", "fleet.socket_window": 4,
        "replay.capacity": 8_000,
    })
    again = Config.from_dict(cfg.to_dict())
    assert again.fleet == cfg.fleet
    # pre-PR16 serialized configs (no new keys) load with off defaults
    d = Config().to_dict()
    for key in ("ingest_batch_blocks", "socket_window", "spill_prefetch",
                "sample_staging"):
        d["fleet"].pop(key, None)
    legacy = Config.from_dict(d)
    assert legacy.fleet.ingest_batch_blocks == 1
    assert legacy.fleet.socket_window == 1
    assert not legacy.fleet.spill_prefetch
    assert not legacy.fleet.sample_staging


@pytest.mark.parametrize("overrides", [
    {"fleet.ingest_batch_blocks": 0},
    {"fleet.ingest_batch_blocks": 4},             # no service
    {"fleet.socket_window": 0},
    {"fleet.socket_window": 2},                   # transport not socket
    {"fleet.replay_shards": 2, "fleet.socket_window": 2,
     "replay.capacity": 8_000},                   # still not socket
    {"fleet.spill_prefetch": True},               # no spill tier
    {"fleet.sample_staging": True},               # no service
    {"telemetry.alerts_ingest_backlog": 0.0},
])
def test_ingest_config_validation(overrides):
    with pytest.raises((ValueError, SystemExit)):
        Config().replace(**overrides)


def test_ingest_backlog_alert_rule():
    from r2d2_tpu.telemetry.alerts import AlertEngine, default_rules
    rules = [r for r in default_rules(Config().telemetry)
             if r.name == "ingest_backlog"]
    assert len(rules) == 1
    rule = rules[0]
    assert rule.path == ("replay_service", "ingest", "backlog")
    assert rule.bound == Config().telemetry.alerts_ingest_backlog
    engine = AlertEngine(rules)
    quiet = engine.evaluate({"replay_service": {"ingest": {"backlog": 3}}})
    assert quiet["fired"] == []
    hot = engine.evaluate({"replay_service": {"ingest": {"backlog": 500}}})
    assert {a["rule"] for a in hot["fired"]} == {"ingest_backlog"}
