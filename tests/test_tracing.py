"""Cross-plane distributed tracing + control tower (ISSUE 19): hop-stamp
propagation on the serving path (in-proc / shm / socket), experience
lineage from emission through ring wrap, spill demote/promote and
snapshot restore, the record's ``trace`` block + kill-switch schema
identity, the per-tier replay telemetry (ROADMAP 4d), and the tower's
cross-plane join, derived signals, rule set, CLI, and Perfetto merge."""

import json
import os
import pickle
import time

import numpy as np
import pytest

from r2d2_tpu.config import Config
from tests.test_replay import _fill_blocks, make_spec
from tests.test_serve import (_native_available, rand_obs, small_cfg,
                              tiny_net)
from tests.test_telemetry import PR23_RECORD_KEYS


def _stamp(block, ms):
    return block.replace(trace_ms=np.asarray(ms, np.int32))


# ---------------------------------------------------------------------------
# primitives: stamps, hops, interval aggregators


def test_now_ms_hop_ms_wrap_and_untraced():
    from r2d2_tpu.telemetry.tracing import UNTRACED, hop_ms, now_ms
    t = now_ms()
    assert 0 <= t < 2 ** 31
    assert hop_ms(100, 350) == 250.0
    # a wrap mid-hop stays non-negative (mod-2^31 difference)
    assert hop_ms(2 ** 31 - 5, 3) == 8.0
    assert hop_ms(UNTRACED, t) is None
    assert hop_ms(t, UNTRACED) is None


def test_request_trace_and_proc_header_shape():
    from r2d2_tpu.telemetry.tracing import new_request_trace, proc_header
    tr = new_request_trace(42)
    assert tr["id"] == 42 and tr["t_submit_wall"] > 0
    head = proc_header("serve")
    assert head["plane"] == "serve" and head["pid"] == os.getpid()
    assert {"wall", "mono"} <= set(head["clock_anchor"])
    assert "lease" not in head
    assert proc_header("replay_service", lease=7)["lease"] == 7


def test_experience_trace_interval_semantics():
    from r2d2_tpu.telemetry.tracing import (EXPERIENCE_HOPS,
                                            ExperienceTrace, now_ms)
    tr = ExperienceTrace()
    assert tr.on_sample([]) is None
    assert tr.interval_block() is None          # empty interval: no block
    emit = now_ms() - 120
    token = tr.on_sample([(emit, emit + 40), (emit, emit + 60)])
    assert token is not None and token[1:] == [emit, emit]
    tr.on_train(token)
    tr.on_train(None)                           # untraced batch: no-op
    block = tr.interval_block()
    assert block["sampled"] == 2
    e2e = block["e2e_experience_latency"]
    assert e2e["count"] == 2 and e2e["p95_ms"] > 0
    assert set(block["hops"]) == set(EXPERIENCE_HOPS)
    # the block CONSUMES the interval (TrainMetrics provider contract)
    assert tr.interval_block() is None


def test_serve_trace_interval_semantics():
    from r2d2_tpu.telemetry.tracing import SERVE_HOPS, ServeTrace
    tr = ServeTrace()
    assert tr.interval_block() is None
    tr.on_request({"t_submit_wall": 5.0, "t_send_wall": 5.001,
                   "t_recv_wall": 5.004}, queue_wait_s=0.002)
    tr.on_batch(forward_s=0.003, reply_s=0.001)
    block = tr.interval_block()
    assert block["requests"] == 1
    assert set(block["hops"]) == set(SERVE_HOPS)
    assert block["hops"]["transit"]["count"] == 1
    assert tr.interval_block() is None


# ---------------------------------------------------------------------------
# experience lineage: the Block leaf, ring mirrors, spill, snapshots


def test_block_trace_leaf_absent_by_default(rng):
    import jax
    blk = _fill_blocks(make_spec(), 1, rng)[0]
    base = jax.tree_util.tree_leaves(blk)
    stamped = _stamp(blk, 1234)
    assert len(jax.tree_util.tree_leaves(stamped)) == len(base) + 1
    # stripping restores the EXACT untraced structure (wire identity)
    stripped = stamped.replace(trace_ms=None)
    assert (jax.tree_util.tree_structure(stripped)
            == jax.tree_util.tree_structure(blk))


def test_wire_frame_fields_omit_untraced(rng):
    from r2d2_tpu.fleet.replay_service import _block_fields
    blk = _fill_blocks(make_spec(), 1, rng)[0]
    assert "trace_ms" not in _block_fields(blk)
    fields = _block_fields(_stamp(blk, 77))
    assert int(fields["trace_ms"]) == 77


def test_shard_add_strips_stamp_and_mirrors_it(rng):
    import jax
    from r2d2_tpu.fleet.replay_service import ReplayShard
    spec = make_spec()
    blk = _fill_blocks(spec, 1, rng)[0]
    traced, plain = ReplayShard(spec, 0), ReplayShard(spec, 0)
    slot = traced.add(_stamp(blk, 9001))
    plain.add(blk)
    # device state is BIT-IDENTICAL to the untraced add: the stamp
    # never reaches the jitted ring
    for a, b in zip(jax.tree_util.tree_leaves(traced.state),
                    jax.tree_util.tree_leaves(plain.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert traced.ring.slot_trace[slot] == 9001
    assert traced.ring.slot_ingest_ms[slot] >= 0
    assert plain.ring.slot_trace[slot] == -1
    assert plain.ring.slot_ingest_ms[slot] == -1


def test_lineage_through_ring_wrap(rng):
    from r2d2_tpu.fleet.replay_service import ReplayShard
    spec = make_spec()
    shard = ReplayShard(spec, 0)
    blk = _fill_blocks(spec, 1, rng)[0]
    for i in range(2 * spec.num_blocks):
        shard.add(_stamp(blk, 3000 + i))
    # the ring wrapped once: every slot mirrors its SECOND occupant
    assert (set(shard.ring.slot_trace)
            == {3000 + i for i in range(spec.num_blocks, 2 * spec.num_blocks)})


def test_trace_lookup_filters_untraced_rows(rng):
    import jax
    from r2d2_tpu.fleet.replay_service import ReplayService
    spec = make_spec()
    svc = ReplayService(spec, 1)
    for i, blk in enumerate(_fill_blocks(spec, spec.num_blocks, rng)):
        svc.add_block(_stamp(blk, 100 + i) if i % 2 == 0 else blk)
    batch, shard, _snap = svc.sample(jax.random.PRNGKey(0))
    pairs = svc.trace_lookup(shard, np.asarray(batch.idxes))
    even = {100 + i for i in range(0, spec.num_blocks, 2)}
    assert pairs, "a full ring with half its slots traced must yield pairs"
    for emit, ingest in pairs:
        assert emit in even and ingest >= 0


def test_lineage_rides_spill_demote_promote(rng):
    from r2d2_tpu.fleet.replay_service import ReplayShard
    spec = make_spec()
    shard = ReplayShard(spec, 0, spill_blocks=4)
    blk = _fill_blocks(spec, 1, rng)[0]
    for i in range(spec.num_blocks):
        shard.add(_stamp(blk, 500 + i))
    for i in range(2):                     # overwrites demote slots 0, 1
        shard.add(_stamp(blk, 600 + i))
    assert shard.spill.occupancy == 2
    assert 500 not in shard.ring.slot_trace
    assert shard.promote(2) == 2
    # the promoted pages re-enter the ring carrying their ORIGINAL emit
    # stamp (the retained block rides demote -> promote intact)
    assert {500, 501} <= set(shard.ring.slot_trace)


def test_lineage_survives_snapshot_restore(rng):
    from r2d2_tpu.fleet.replay_service import ReplayService
    spec = make_spec()
    svc = ReplayService(spec, 2)
    for i, blk in enumerate(_fill_blocks(spec, 6, rng)):
        svc.add_block(_stamp(blk, 800 + i))
    snap = svc.snapshot_state(step=3)
    restored = ReplayService(spec, 2)
    restored.restore_state(snap)
    for a, b in zip(svc.shards, restored.shards):
        assert list(a.ring.slot_trace) == list(b.ring.slot_trace)
        assert list(a.ring.slot_ingest_ms) == list(b.ring.slot_ingest_ms)


def test_experience_trace_end_to_end_via_service(rng):
    import jax
    from r2d2_tpu.telemetry.tracing import (EXPERIENCE_HOPS,
                                            ExperienceTrace, now_ms)
    from r2d2_tpu.fleet.replay_service import ReplayService
    spec = make_spec()
    svc = ReplayService(spec, 1)
    emit = now_ms()
    for blk in _fill_blocks(spec, spec.num_blocks, rng):
        svc.add_block(_stamp(blk, emit))
    batch, shard, _ = svc.sample(jax.random.PRNGKey(1))
    pairs = svc.trace_lookup(shard, np.asarray(batch.idxes))
    assert len(pairs) == spec.batch_size   # fully traced run: every row
    tr = ExperienceTrace()
    token = tr.on_sample(pairs)
    tr.on_train(token)
    block = tr.interval_block()
    assert block["sampled"] == spec.batch_size
    assert block["e2e_experience_latency"]["count"] == spec.batch_size
    assert set(block["hops"]) == set(EXPERIENCE_HOPS)


# ---------------------------------------------------------------------------
# record schema, config knobs, in-run rules, per-tier telemetry


def test_record_trace_block_provider_contract(tmp_path):
    from r2d2_tpu.runtime.metrics import TrainMetrics
    m = TrainMetrics(0, str(tmp_path))
    payload = {"sampled": 3,
               "e2e_experience_latency": {"count": 3, "p50_ms": 40.0,
                                          "p95_ms": 90.0, "p99_ms": 95.0}}
    m.set_tracing(lambda: payload)
    record = m.log(1.0)
    assert record["trace"] == payload
    m.set_tracing(lambda: None)            # quiet interval: key omitted
    assert "trace" not in m.log(1.0)


def test_record_schema_identical_with_tracing_off(tmp_path):
    from r2d2_tpu.runtime.metrics import TrainMetrics
    record = TrainMetrics(0, str(tmp_path)).log(1.0)
    assert PR23_RECORD_KEYS <= set(record)
    assert "trace" not in record


def test_config_tracing_knobs_and_validation():
    t = Config().telemetry
    assert t.tracing_enabled is False      # kill switch default: OFF
    assert t.trace_sample_every == 16
    assert t.tower_enabled is True
    assert t.alerts_spill_promotion_ms == 60_000.0
    assert t.alerts_e2e_latency_growth == 4.0
    cfg = Config().replace(**{"telemetry.tracing_enabled": True,
                              "telemetry.trace_sample_every": 4})
    assert cfg.telemetry.tracing_enabled
    assert cfg.telemetry.trace_sample_every == 4
    with pytest.raises(ValueError):
        Config().replace(**{"telemetry.trace_sample_every": 0})
    with pytest.raises(ValueError):
        Config().replace(**{"telemetry.alerts_e2e_latency_growth": 1.0})


def test_in_run_tracing_alert_rules():
    from r2d2_tpu.telemetry.alerts import AlertEngine, default_rules
    cfg = Config().replace(**{"telemetry.alerts_window": 2})
    engine = AlertEngine(default_rules(cfg.telemetry))

    def rec(e2e, promo):
        return {"trace": {"e2e_experience_latency": {"p95_ms": e2e}},
                "replay_service": {"spill": {"promotion_latency":
                                             {"p95_ms": promo}}}}

    fired = engine.evaluate(rec(100.0, 70_000.0))["fired"]
    assert any(a["rule"] == "spill_promotion_latency"
               and a["severity"] == "warn" for a in fired)
    engine.evaluate(rec(100.0, 1.0))       # fills the growth window
    fired = engine.evaluate(rec(1000.0, 1.0))["fired"]
    assert any(a["rule"] == "e2e_latency_growth" for a in fired)


def test_tier_stats_interval_block_gated(rng):
    from r2d2_tpu.fleet.replay_service import ReplayService
    spec = make_spec()
    svc = ReplayService(spec, 1, spill_blocks=4, tier_stats=True)
    for blk in _fill_blocks(spec, spec.num_blocks + 2, rng):
        svc.add_block(blk)
    assert svc.shards[0].promote(1) == 1
    spill = svc.interval_block()["spill"]
    promo = spill["promotion_latency"]
    assert promo is not None and promo["count"] >= 1
    assert promo["p95_ms"] >= 0
    tiers = spill["tiers"]
    assert tiers["device_bytes"] > 0 and tiers["spill_page_bytes"] > 0
    assert tiers["spill_bytes"] == (spill["occupancy"]
                                    * tiers["spill_page_bytes"])
    # gated OFF (the default): the PR-15 spill block is byte-identical
    legacy = ReplayService(spec, 1, spill_blocks=4)
    for blk in _fill_blocks(spec, 2, rng):
        legacy.add_block(blk)
    legacy_spill = legacy.interval_block()["spill"]
    assert "promotion_latency" not in legacy_spill
    assert "tiers" not in legacy_spill


# ---------------------------------------------------------------------------
# serving-path hop propagation: in-proc, wire identity, socket, shm


def _traced_server(cfg=None):
    from r2d2_tpu.serve import InprocEndpoint, PolicyServer
    from r2d2_tpu.serve.server import ServingStats
    from r2d2_tpu.telemetry.tracing import ServeTrace
    cfg = cfg or small_cfg()
    net, params = tiny_net(cfg)
    stats = ServingStats()
    stats.trace = ServeTrace()
    ep = InprocEndpoint()
    srv = PolicyServer(cfg, net, params, endpoint=ep, stats=stats).start()
    return cfg, net, ep, srv, stats


def test_inproc_traced_exchange_records_hops():
    from r2d2_tpu.serve import RemotePolicy
    from r2d2_tpu.telemetry.tracing import SERVE_HOPS
    cfg, net, ep, srv, stats = _traced_server()
    try:
        remote = RemotePolicy(ep.connect(), net.action_dim, 0.0, seed=0,
                              trace_every=1)
        rng = np.random.default_rng(3)
        remote.observe_reset(rand_obs(rng, cfg))
        for _ in range(3):
            remote.act()
        block = stats.interval_block()
        trace = block["trace"]
        assert trace["requests"] >= 3
        hops = trace["hops"]
        assert set(hops) <= set(SERVE_HOPS)
        # client stamps (submit/send), endpoint stamps receive, server
        # stamps the batch: the full decomposition on one process
        assert {"route", "transit", "queue_wait",
                "forward", "reply"} <= set(hops)
    finally:
        srv.stop()


def test_untraced_requests_and_layout_byte_identical():
    from r2d2_tpu.serve import RemotePolicy, Request
    from r2d2_tpu.serve.transport import request_layout
    from r2d2_tpu.telemetry.tracing import new_request_trace
    base = pickle.dumps(Request(client_id=1, req_id=2))
    assert pickle.dumps(Request(client_id=1, req_id=2)) == base
    traced = Request(client_id=1, req_id=2)
    traced.trace = new_request_trace(2)
    assert pickle.dumps(traced) != base    # the trace rides __dict__
    # the shm slot layout only grows stamp fields when ASKED, at the END
    plain = request_layout(8, 8)
    assert plain == request_layout(8, 8, tracing=False)
    grown = request_layout(8, 8, tracing=True)
    assert grown[:len(plain)] == plain
    assert [f[0] for f in grown[len(plain):]] == ["t_submit_wall",
                                                  "t_send_wall"]
    # client gating: trace_every=0 (the default) never attaches
    cfg, net, ep, srv, _stats = _traced_server()
    captured = []
    orig, orig_many = ep.submit, ep.submit_many
    ep.submit = lambda req, cb: (captured.append(req), orig(req, cb))[1]
    ep.submit_many = lambda items: (
        captured.extend(req for req, _cb in items), orig_many(items))[1]
    try:
        rng = np.random.default_rng(4)
        remote = RemotePolicy(ep.connect(), net.action_dim, 0.0, seed=0)
        remote.observe_reset(rand_obs(rng, cfg))
        remote.act()
        assert captured and all(not hasattr(r, "trace") for r in captured)
        traced_remote = RemotePolicy(ep.connect(), net.action_dim, 0.0,
                                     seed=0, client_id=1, trace_every=1)
        traced_remote.observe_reset(rand_obs(rng, cfg))
        traced_remote.act()
        stamps = [r.trace for r in captured if hasattr(r, "trace")]
        assert stamps and {"t_submit_wall", "t_send_wall",
                           "t_recv_wall"} <= set(stamps[0])
    finally:
        srv.stop()


def test_socket_transport_carries_trace():
    from r2d2_tpu.serve import (InprocEndpoint, PolicyServer, RemotePolicy,
                                SocketChannel, SocketServerTransport)
    from r2d2_tpu.serve.server import ServingStats
    from r2d2_tpu.telemetry.tracing import ServeTrace
    cfg = small_cfg()
    net, params = tiny_net(cfg)
    stats = ServingStats()
    stats.trace = ServeTrace()
    ep = InprocEndpoint()
    transport = SocketServerTransport(ep.submit, "127.0.0.1", 0)
    srv = PolicyServer(cfg, net, params, endpoint=ep, stats=stats).start()
    try:
        channel = SocketChannel(transport.host, transport.port)
        remote = RemotePolicy(channel, net.action_dim, 0.0, seed=0,
                              trace_every=1)
        rng = np.random.default_rng(5)
        remote.observe_reset(rand_obs(rng, cfg))
        remote.act()
        remote.act()
        trace = stats.interval_block()["trace"]
        assert trace["requests"] >= 2
        # transit = client send stamp -> server-side receive stamp,
        # measured ACROSS the socket hop
        assert trace["hops"]["transit"]["count"] >= 2
        remote.close()
    finally:
        srv.stop()
        transport.close()


def test_shm_transport_carries_trace():
    if not _native_available():
        pytest.skip("native shm ring toolchain unavailable")
    from r2d2_tpu.serve import (InprocEndpoint, PolicyServer, RemotePolicy,
                                ShmServeChannel, ShmServeTransport)
    from r2d2_tpu.serve.server import ServingStats
    from r2d2_tpu.telemetry.tracing import ServeTrace
    cfg = small_cfg()
    net, params = tiny_net(cfg)
    stats = ServingStats()
    stats.trace = ServeTrace()
    ep = InprocEndpoint()
    transport = ShmServeTransport(
        ep.submit, (cfg.env.frame_height, cfg.env.frame_width),
        net.action_dim, cfg.network.hidden_dim, request_slots=16,
        tracing=True)
    srv = PolicyServer(cfg, net, params, endpoint=ep, stats=stats).start()
    try:
        channel = ShmServeChannel(transport.request_ring, net.action_dim,
                                  cfg.network.hidden_dim, reply_slots=4)
        remote = RemotePolicy(channel, net.action_dim, 0.0, seed=0,
                              client_id=3, trace_every=1)
        rng = np.random.default_rng(6)
        remote.observe_reset(rand_obs(rng, cfg))
        remote.act()
        trace = stats.interval_block()["trace"]
        assert trace["requests"] >= 1
        assert trace["hops"]["transit"]["count"] >= 1
        remote.close()
    finally:
        srv.stop()
        transport.close()


def test_shm_block_ring_traced_layout_roundtrip():
    if not _native_available():
        pytest.skip("native shm ring toolchain unavailable")
    from r2d2_tpu.runtime.shm_feeder import ShmBlockRing, block_layout
    from r2d2_tpu.telemetry.tracing import UNTRACED
    spec = make_spec()
    # kill switch: the traced layout only differs by the trailing field
    plain = block_layout(spec)
    traced = block_layout(spec, tracing=True)
    assert traced[:-1] == plain
    assert traced[-1][0] == "trace_ms"
    rng = np.random.default_rng(11)
    a, b, c = _fill_blocks(spec, 3, rng)
    ring = ShmBlockRing(spec, maxsize=8, tracing=True)
    try:
        ring.put(_stamp(a, 4321), timeout=1.0)
        ring.put(b, timeout=1.0)                 # unstamped on a traced ring
        ring.put(_stamp(c, UNTRACED), timeout=1.0)
        # per-block pop carries the stamp (and -1 for the unstamped put)
        got = ring.get_nowait()
        assert int(np.asarray(got.trace_ms)) == 4321
        # the stager's path: one stacked drain, stamps ride the K axis
        stacked, k = ring.drain_stacked(4)
        assert k == 2
        assert np.asarray(stacked.trace_ms).tolist() == [-1, UNTRACED]
        # pickled handles re-attach with the traced layout
        clone = pickle.loads(pickle.dumps(ring))
        assert clone.tracing and clone.slot_bytes == ring.slot_bytes
    finally:
        ring.close()
    off = ShmBlockRing(spec, maxsize=8)
    try:
        assert off.slot_bytes < ring.slot_bytes  # no hidden traced bytes
        off.put(a, timeout=1.0)
        assert off.get_nowait().trace_ms is None
    finally:
        off.close()


# ---------------------------------------------------------------------------
# the control tower: join, derived signals, rules, CLI, Perfetto merge


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def _learner_row(t=10.0, e2e_p95=250.0):
    return {"t": t, "env_steps": 1000, "training_steps": 50,
            "trace": {"sampled": 4,
                      "e2e_experience_latency": {"count": 4, "p50_ms": 120.0,
                                                 "p95_ms": e2e_p95,
                                                 "p99_ms": 300.0}}}


def _serve_row(t=10.0, shed=3, offset=None):
    anchor = {"wall": 100.0, "mono": 2.0}
    if offset is not None:
        anchor["offset_est"] = offset
    return {"t": t, "batches": 5,
            "proc": {"plane": "serve", "pid": 11, "clock_anchor": anchor},
            "serving": {"requests": 40, "admission": {"shed": shed}}}


def _service_row(t=10.0, backlog=6, promo_p95=70_000.0, offset=0.25):
    return {"t": t,
            "proc": {"plane": "replay_service", "pid": 12,
                     "clock_anchor": {"wall": 100.0, "mono": 0.5,
                                      "offset_est": offset}},
            "replay_service": {
                "shards": {"n": 1, "fill_min": 0.5, "fill_max": 0.5},
                "ingest": {"backlog": backlog},
                "spill": {"occupancy": 2, "capacity": 4,
                          "promotion_latency": {"count": 2, "p50_ms": 100.0,
                                                "p95_ms": promo_p95,
                                                "p99_ms": promo_p95}}}}


@pytest.mark.tower
def test_tower_rules_table():
    from r2d2_tpu.telemetry.tower import tower_rules
    from r2d2_tpu.tools.tower import main
    rules = {r.name: r for r in tower_rules(Config())}
    assert set(rules) == {"tower_e2e_latency_growth",
                          "tower_shed_while_backlog",
                          "tower_spill_promotion_latency",
                          "tower_plane_silent",
                          "tower_quality_regression",
                          "tower_canary_divergence",
                          "tower_promotion_stall"}
    for r in rules.values():
        assert r.path[0] == "derived"      # tower rules read the JOIN
    assert rules["tower_shed_while_backlog"].severity == "crit"
    assert rules["tower_plane_silent"].severity == "crit"
    assert rules["tower_e2e_latency_growth"].bound == 4.0
    assert rules["tower_spill_promotion_latency"].bound == 60_000.0
    assert main(["--rules"]) == 0


@pytest.mark.tower
def test_tower_derive_and_clock_are_cross_plane():
    from r2d2_tpu.telemetry.tower import TowerCollector
    planes = {"learner": [_learner_row()], "serve": _serve_row(offset=-0.5),
              "replay_service": [_service_row()], "hosts": []}
    derived = TowerCollector.derive(planes, {"learner": 1.0, "serve": 200.0})
    assert derived["e2e_p95_ms"] == 250.0
    assert derived["spill_promotion_p95_ms"] == 70_000.0
    assert derived["ingest_backlog"] == 6
    assert derived["serve_shed"] == 3
    assert derived["shed_while_backlog"] == 1.0
    assert derived["stalest_plane_age_s"] == 200.0
    clock = TowerCollector.clock(planes)
    assert clock["offsets"] == {"serve": -0.5, "replay_service/0": 0.25}
    assert {"serve", "replay_service/0"} <= set(clock["anchors"])
    # one healthy plane missing its counterpart: no correlation signal
    healthy = TowerCollector.derive({"learner": [_learner_row()],
                                     "serve": None,
                                     "replay_service": [], "hosts": []})
    assert "shed_while_backlog" not in healthy


@pytest.mark.tower
def test_tower_snapshot_joins_streams_and_fires(tmp_path):
    from r2d2_tpu.telemetry.tower import TowerCollector, render_tower
    _write_jsonl(tmp_path / "metrics_player0.jsonl", [_learner_row()])
    _write_jsonl(tmp_path / "serve_metrics.jsonl", [_serve_row()])
    _write_jsonl(tmp_path / "service_metrics_p0.jsonl", [_service_row()])
    collector = TowerCollector(str(tmp_path), Config())
    record = collector.snapshot()
    assert record["planes"]["learner"][0]["env_steps"] == 1000
    assert record["planes"]["serve"]["batches"] == 5
    fired = {a["rule"]: a for a in record["alerts"]["fired"]}
    assert fired["tower_shed_while_backlog"]["severity"] == "crit"
    assert fired["tower_spill_promotion_latency"]["severity"] == "warn"
    assert record["clock"]["offsets"]["replay_service/0"] == 0.25
    frame = render_tower(record)
    assert "SHED-WHILE-BACKLOG" in frame and "clock offsets" in frame


@pytest.mark.tower
def test_tower_replay_index_aligns_unequal_streams(tmp_path):
    from r2d2_tpu.telemetry.tower import TowerCollector
    _write_jsonl(tmp_path / "metrics_player0.jsonl",
                 [_learner_row(t=10.0 * (i + 1)) for i in range(3)])
    _write_jsonl(tmp_path / "serve_metrics.jsonl",
                 [_serve_row(t=10.0), _serve_row(t=20.0, shed=9)])
    records = TowerCollector(str(tmp_path), Config()).replay()
    assert len(records) == 3               # depth = the longest stream
    # the shorter serve stream HOLDS its final row (its last state)
    assert records[2]["planes"]["serve"]["serving"]["admission"]["shed"] == 9
    assert all("alerts" in r for r in records)
    assert records[0]["planes"]["learner"][0]["t"] == 10.0
    assert records[2]["planes"]["learner"][0]["t"] == 30.0


@pytest.mark.tower
def test_tower_cli_exit_codes_and_kill_switch(tmp_path, capsys):
    from r2d2_tpu.tools.tower import main
    _write_jsonl(tmp_path / "metrics_player0.jsonl", [_learner_row()])
    _write_jsonl(tmp_path / "serve_metrics.jsonl", [_serve_row()])
    _write_jsonl(tmp_path / "service_metrics_p0.jsonl", [_service_row()])
    # crit fired (shed-while-backlog) -> exit 1, firings printed
    assert main(["--dir", str(tmp_path)]) == 1
    assert "tower_shed_while_backlog" in capsys.readouterr().out
    # kill switch: no reads, exit 0
    assert main(["--dir", str(tmp_path), "--override",
                 "telemetry.tower_enabled=false"]) == 0
    assert "tower disabled" in capsys.readouterr().out
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["--dir", str(empty)]) == 2


@pytest.mark.tower
def test_export_trace_merges_planes_on_anchored_clocks(tmp_path):
    from r2d2_tpu.tools.inspect import (export_chrome_trace,
                                        plane_clock_offsets)
    span = {"name": "work", "ts": 100.0, "dur": 0.5, "tid": "main"}
    _write_jsonl(tmp_path / "spans_player0.jsonl",
                 [{**span, "pid": "player0"}])
    _write_jsonl(tmp_path / "spans_serve.jsonl", [{**span, "pid": "serve"}])
    _write_jsonl(tmp_path / "spans_replay_service.jsonl",
                 [{**span, "pid": "replay_service"}])
    _write_jsonl(tmp_path / "serve_metrics.jsonl", [_serve_row(offset=0.5)])
    _write_jsonl(tmp_path / "service_metrics_p0.jsonl",
                 [_service_row(offset=-0.25)])
    assert plane_clock_offsets(str(tmp_path)) == {
        "spans_serve.jsonl": 0.5, "spans_replay_service.jsonl": -0.25}
    out = tmp_path / "trace.json"
    assert export_chrome_trace(str(tmp_path), str(out)) == 3
    events = json.loads(out.read_text())["traceEvents"]
    name_of = {ev["pid"]: ev["args"]["name"] for ev in events
               if ev["ph"] == "M" and ev["name"] == "process_name"}
    # ONE timeline spanning >= 3 processes (the acceptance criterion)
    assert {"player0", "serve", "replay_service"} <= set(name_of.values())
    ts = {name_of[ev["pid"]]: ev["ts"] for ev in events if ev["ph"] == "X"}
    assert ts["player0"] == pytest.approx(100.0 * 1e6)
    # each plane's spans shift onto the learner clock by its offset_est
    assert ts["serve"] == pytest.approx((100.0 - 0.5) * 1e6)
    assert ts["replay_service"] == pytest.approx((100.0 + 0.25) * 1e6)


@pytest.mark.tower
def test_sentinel_stream_replays_plane_rows(tmp_path, capsys):
    from r2d2_tpu.tools.sentinel import main
    path = tmp_path / "service_metrics_p0.jsonl"
    _write_jsonl(path, [
        {"t": 5.0 * (i + 1),
         "replay_service": {"spill": {"promotion_latency":
                                      {"count": 1, "p50_ms": 1.0,
                                       "p95_ms": 70_000.0,
                                       "p99_ms": 70_000.0}}}}
        for i in range(2)])
    assert main(["--stream", str(path)]) == 0     # warn fired, no crit
    assert "spill_promotion_latency" in capsys.readouterr().out
    assert main(["--stream", str(tmp_path / "missing.jsonl")]) == 2
