"""Native C++ sum tree vs the numpy oracle (SURVEY §2.1 native parity)."""

import numpy as np
import pytest

from r2d2_tpu.ops.sum_tree import tree_init_np, tree_sample_np, tree_update_np

native = pytest.importorskip("r2d2_tpu.native")


def test_native_matches_numpy_oracle(rng):
    cap = 100
    nt = native.NativeSumTree(cap)
    layers, tree = tree_init_np(cap)
    assert nt.num_layers == layers

    for _ in range(5):
        n = 17
        idx = rng.choice(cap, n, replace=False).astype(np.int64)
        td = rng.uniform(0, 3, n)
        td[rng.random(n) < 0.2] = 0.0
        nt.update(0.9, td, idx)
        tree_update_np(layers, tree, 0.9, td, idx)
        assert nt.total == pytest.approx(tree[0], rel=1e-12)

    # identical jitter stream -> identical samples and weights
    seed = 123
    idx_c, w_c = nt.sample(0.6, 32, np.random.default_rng(seed))
    # numpy twin draws uniform(0, interval) per stratum; the native API takes
    # jitter in [0,1) scaled internally — replicate its exact computation
    jitter = np.random.default_rng(seed).uniform(0.0, 1.0, 32)
    p_sum = tree[0]
    interval = p_sum / 32
    prefix = np.minimum((np.arange(32) + jitter) * interval,
                        p_sum * (1 - 1e-12))
    node = np.zeros(32, np.int64)
    for _ in range(layers - 1):
        left, right = tree[2 * node + 1], tree[2 * node + 2]
        go_left = (prefix < left) | (right <= 0.0)
        node = np.where(go_left, 2 * node + 1, 2 * node + 2)
        prefix = np.where(go_left, np.minimum(prefix, left * (1 - 1e-12)),
                          prefix - left)
    leaves = node - (2 ** (layers - 1) - 1)
    np.testing.assert_array_equal(idx_c, leaves)
    p = tree[node]
    np.testing.assert_allclose(w_c, (p / p.min()) ** -0.6, rtol=1e-12)


def test_native_alpha_zero_keeps_zero_priority(rng):
    """alpha=0 must still give p=0 for td=0 (PER-off path,
    ref priority_tree.py:17)."""
    nt = native.NativeSumTree(8)
    nt.update(0.0, np.array([0.0, 2.0]), np.array([0, 1], np.int64))
    assert nt.total == pytest.approx(1.0)  # only the nonzero td got 0^0->1


def test_host_replay_uses_native(rng):
    from r2d2_tpu.replay import HostReplay
    from tests.test_replay import make_spec, _fill_blocks

    spec = make_spec()
    host = HostReplay(spec, seed=0, use_native=True)
    assert host._native is not None, "native tree should load here"
    for blk in _fill_blocks(spec, 3, rng):
        host.add(blk)
    batch, ptr = host.sample()
    assert np.isfinite(batch.is_weights).all()
    assert (np.asarray(batch.learning_steps) > 0).all()
    host.update_priorities(batch.idxes, np.abs(rng.normal(size=spec.batch_size)) + 0.1, ptr)
