"""Replay pipeline tests: LocalBuffer block assembly → device/host replay
add/sample/update, checked against the reference's ragged semantics
(/root/reference/worker.py:395-492, 85-209) via hand-computed expectations
and brute-force oracles (SURVEY.md §4).
"""

import jax
import numpy as np
import pytest

from r2d2_tpu.actor.local_buffer import LocalBuffer
from r2d2_tpu.replay import (
    HostReplay,
    ReplaySpec,
    replay_add,
    replay_init,
    replay_sample,
)
from r2d2_tpu.replay.device_replay import replay_size

A = 4  # action dim
HD = 8  # hidden dim


def make_spec(**kw) -> ReplaySpec:
    base = dict(
        num_blocks=8, seqs_per_block=4, block_length=20, burn_in=4,
        learning=5, forward=3, frame_stack=2, frame_height=12, frame_width=12,
        hidden_dim=HD, batch_size=16, prio_exponent=0.9, is_exponent=0.6,
    )
    base.update(kw)
    return ReplaySpec(**base)


def drive(buf: LocalBuffer, rng, n_steps: int, start_t: int = 0):
    """Push n_steps synthetic transitions; returns the per-step records."""
    recs = []
    for i in range(n_steps):
        t = start_t + i
        obs = np.full((12, 12), t % 250, np.uint8)
        q = rng.normal(size=A).astype(np.float32)
        hidden = rng.normal(size=(2, HD)).astype(np.float32)
        action = t % A
        reward = float(t % 3)
        buf.add(action, reward, obs, q, hidden)
        recs.append((action, reward, obs, q, hidden))
    return recs


def test_local_buffer_full_block_metadata(rng):
    """Full 20-step block with bootstrap: the reference's per-sequence
    burn-in/learning/forward formulas (ref worker.py:468-471)."""
    spec = make_spec()
    buf = LocalBuffer(spec, A, gamma=0.9)
    buf.reset(np.zeros((12, 12), np.uint8))
    drive(buf, rng, 20)
    blk = buf.finish(last_qval=np.ones(A, np.float32))

    assert int(blk.num_sequences) == 4
    np.testing.assert_array_equal(blk.burn_in_steps, [0, 4, 4, 4])
    np.testing.assert_array_equal(blk.learning_steps, [5, 5, 5, 5])
    np.testing.assert_array_equal(blk.forward_steps, [3, 3, 3, 1])
    np.testing.assert_array_equal(blk.seq_start, [0, 5, 10, 15])
    assert np.isnan(float(blk.sum_reward))  # not an episode end
    assert buf.curr_burn_in == 4  # burn-in carried to next block

    # n-step gamma: full window gamma^3 until the bootstrap-shortened tail
    g = blk.gamma.reshape(-1)[:20]
    np.testing.assert_allclose(g[:17], 0.9**3, rtol=1e-6)
    np.testing.assert_allclose(g[17:20], [0.9**3, 0.9**2, 0.9**1], rtol=1e-6)

    # n-step reward vs brute force (ref worker.py:463-466)
    rewards = np.array([t % 3 for t in range(20)], float)
    want = [sum(0.9**i * (rewards[t + i] if t + i < 20 else 0.0) for i in range(3))
            for t in range(20)]
    np.testing.assert_allclose(blk.reward.reshape(-1)[:20], want, rtol=1e-5)


def test_first_block_hidden_at_window_start(rng):
    """Episode-start blocks: the stored hidden must be the state at the
    sequence's WINDOW start (seq_start - burn_in), not s*learning steps in.
    The reference stores the latter (worker.py:459), handing the learner a
    state that already consumed the burn-in it is about to replay — a
    deliberate divergence fixed here."""
    spec = make_spec()
    buf = LocalBuffer(spec, A, gamma=0.9)
    buf.reset(np.zeros((12, 12), np.uint8))
    recs = drive(buf, rng, 20)
    blk = buf.finish(last_qval=np.ones(A, np.float32))

    # s=0: window start 0 -> initial zero state
    np.testing.assert_array_equal(blk.hidden[0], 0.0)
    # s=1: burn_in=min(5,4)=4, seq_start=5 -> window start 1 -> state after
    # step 1 = recs[0]'s hidden
    np.testing.assert_allclose(blk.hidden[1], recs[0][4], rtol=1e-6)
    # s=2: burn_in=4, seq_start=10 -> window start 6 -> recs[5]'s hidden
    np.testing.assert_allclose(blk.hidden[2], recs[5][4], rtol=1e-6)


def test_local_buffer_episode_end_and_carry(rng):
    """Partial block at episode end: zeroed gamma tail, episode return
    reported, next episode restarts burn-in at 0 (ref worker.py:445-456)."""
    spec = make_spec()
    buf = LocalBuffer(spec, A, gamma=0.9)
    buf.reset(np.zeros((12, 12), np.uint8))
    drive(buf, rng, 13)
    blk = buf.finish(last_qval=None)

    assert int(blk.num_sequences) == 3
    np.testing.assert_array_equal(blk.learning_steps[:3], [5, 5, 3])
    np.testing.assert_array_equal(blk.forward_steps[:3], [3, 3, 1])
    # terminal: last min(size, forward)=3 effective gammas are zero
    flat_gamma = blk.gamma.reshape(-1)
    np.testing.assert_allclose(flat_gamma[10:13], 0.0, atol=0)
    expected_return = sum(t % 3 for t in range(13))
    assert float(blk.sum_reward) == pytest.approx(expected_return)
    # empty 4th slot must be unsamplable
    assert blk.priority[3] == 0.0 and blk.learning_steps[3] == 0


def test_local_buffer_cross_block_hidden_alignment(rng):
    """Second block: hidden snapshot s=0 is the state before the *window*
    (burn-in start), i.e. the hidden captured burn_in steps before seq_start
    (the stored-state strategy, ref worker.py:459 + SURVEY §5.7)."""
    spec = make_spec()
    buf = LocalBuffer(spec, A, gamma=0.9)
    buf.reset(np.zeros((12, 12), np.uint8))
    recs1 = drive(buf, rng, 20)
    buf.finish(last_qval=np.ones(A, np.float32))
    recs2 = drive(buf, rng, 20, start_t=20)
    blk2 = buf.finish(last_qval=np.ones(A, np.float32))

    assert blk2.burn_in_steps[0] == 4
    # Window position 0 of block2/seq0 replays global step 17 (1-based):
    # its input hidden is the state after step 16 = recs1[15]'s hidden, and
    # its stacked obs is frames from steps 15,16 → obs_row[0] is step 15's
    # frame = recs1[14]'s obs (obs_row[0:stack] = steps 15,16).
    np.testing.assert_allclose(blk2.hidden[0], recs1[15][4], rtol=1e-6)
    np.testing.assert_array_equal(blk2.obs_row[0], recs1[14][2])
    np.testing.assert_array_equal(blk2.obs_row[1], recs1[15][2])
    # last_action at window position 0 is the action taken at step 16
    assert blk2.last_action_row[0] == recs1[15][0]


def _fill_blocks(spec, n, rng, gamma=0.9):
    buf = LocalBuffer(spec, A, gamma=gamma)
    buf.reset(np.zeros((12, 12), np.uint8))
    blocks = []
    t = 0
    for _ in range(n):
        drive(buf, rng, spec.block_length, start_t=t)
        t += spec.block_length
        blocks.append(buf.finish(last_qval=rng.normal(size=A).astype(np.float32)))
    return blocks


def test_exact_gather_padded_storage_is_transparent(rng):
    """spec.exact_gather pads the stored frame to the uint8 (32, 128)
    tile (12x12 -> 32x128 here; 84x84 -> 96x128 at reference scale; both
    minor dims must be tile-aligned for the async-copy DMA — BENCH r4);
    the padding must be invisible end-to-end: the same blocks + same
    sample keys yield batches whose unpadded rows and every other field
    are IDENTICAL to the unpadded spec's, and the decoded observation
    (out_height/out_width strip the pad) matches exactly."""
    from r2d2_tpu.ops.pallas_kernels import stack_frames_reference

    spec = make_spec()
    spec_pad = make_spec(exact_gather=True)
    assert spec_pad.stored_frame_height == 32 and spec.frame_height == 12
    assert spec_pad.stored_frame_width == 128 and spec.frame_width == 12

    blocks = _fill_blocks(spec, 3, rng)
    state, state_pad = replay_init(spec), replay_init(spec_pad)
    assert state_pad.obs.shape[2:] == (32, 128)
    for blk in blocks:
        state = replay_add(spec, state, blk)
        state_pad = replay_add(spec_pad, state_pad, blk)

    key = jax.random.PRNGKey(0)
    batch = replay_sample(spec, state, key)
    batch_pad = replay_sample(spec_pad, state_pad, key)

    np.testing.assert_array_equal(np.asarray(batch.idxes),
                                  np.asarray(batch_pad.idxes))
    np.testing.assert_array_equal(np.asarray(batch.obs),
                                  np.asarray(batch_pad.obs)[:, :, :12, :12])
    assert (np.asarray(batch_pad.obs)[:, :, 12:, :] == 0).all()
    assert (np.asarray(batch_pad.obs)[:, :, :, 12:] == 0).all()
    np.testing.assert_array_equal(np.asarray(batch.last_action),
                                  np.asarray(batch_pad.last_action))

    dec = stack_frames_reference(batch.obs, spec.seq_window,
                                 spec.frame_stack, out_height=12)
    dec_pad = stack_frames_reference(batch_pad.obs, spec.seq_window,
                                     spec.frame_stack, out_height=12,
                                     out_width=12)
    assert dec_pad.shape == dec.shape
    np.testing.assert_array_equal(np.asarray(dec), np.asarray(dec_pad))


def test_device_replay_add_sample_consistency(rng):
    """Jitted sample must return exactly the stored windows: cross-check every
    sampled field against direct numpy indexing of the ring state."""
    spec = make_spec()
    state = replay_init(spec)
    for blk in _fill_blocks(spec, 3, rng):
        state = replay_add(spec, state, blk)

    assert int(state.block_ptr) == 3
    assert int(replay_size(state)) == 3 * spec.block_length

    batch = replay_sample(spec, state, jax.random.PRNGKey(0))
    obs_np = np.asarray(state.obs)
    la_np = np.asarray(state.last_action)

    idxes = np.asarray(batch.idxes)
    assert (idxes < 3 * spec.seqs_per_block).all()
    assert (np.asarray(batch.learning_steps) > 0).all()
    w = np.asarray(batch.is_weights)
    assert np.isfinite(w).all() and (w > 0).all() and w.max() == pytest.approx(1.0)

    for i in range(spec.batch_size):
        b, s = idxes[i] // spec.seqs_per_block, idxes[i] % spec.seqs_per_block
        burn = int(np.asarray(state.burn_in_steps)[b, s])
        start = int(np.asarray(state.seq_start)[b, s]) - burn
        assert start >= 0
        win = spec.seq_window
        np.testing.assert_array_equal(
            np.asarray(batch.obs)[i], obs_np[b, start : start + win + spec.frame_stack - 1])
        np.testing.assert_array_equal(
            np.asarray(batch.last_action)[i], la_np[b, start : start + win])
        np.testing.assert_allclose(
            np.asarray(batch.hidden)[i], np.asarray(state.hidden)[b, s])


def test_device_replay_ring_overwrite(rng):
    """Wrapping the ring replaces old priorities — slots from the overwritten
    block must reflect the new block's data (ref worker.py:96-102)."""
    spec = make_spec(num_blocks=2)
    state = replay_init(spec)
    blocks = _fill_blocks(spec, 3, rng)
    state = replay_add(spec, state, blocks[0])
    tree_after_b0 = np.asarray(state.tree).copy()
    state = replay_add(spec, state, blocks[1])
    state = replay_add(spec, state, blocks[2])  # overwrites ring slot 0
    assert int(state.block_ptr) == 1
    leaves = np.asarray(state.tree)[2**spec.tree_layers // 2 - 1 :]
    want = np.asarray(blocks[2].priority) ** spec.prio_exponent
    np.testing.assert_allclose(leaves[: spec.seqs_per_block], want, rtol=1e-5)
    assert not np.allclose(leaves[: spec.seqs_per_block],
                           tree_after_b0[2**spec.tree_layers // 2 - 1 :][: spec.seqs_per_block])


def test_ring_accountant_mirrors_device_pointer(rng):
    """RingAccountant (the single host-side ring authority) must advance
    with the identical wrap rule as the compiled pointer in
    ReplayState.block_ptr — the invariant that makes the Learner's host
    mirror safe (it never reads the device pointer)."""
    from r2d2_tpu.replay.structs import RingAccountant

    spec = make_spec(num_blocks=3)
    state = replay_init(spec)
    ring = RingAccountant(spec.num_blocks)
    for blk in _fill_blocks(spec, 7, rng):   # wraps the 3-slot ring twice
        state = replay_add(spec, state, blk)
        ring.advance(int(np.asarray(blk.learning_steps).sum()))
        assert ring.ptr == int(state.block_ptr)
        assert ring.buffer_steps == int(replay_size(state))
    assert ring.total_adds == 7


def test_sample_distribution_follows_priorities(rng):
    """Stratified sampling must draw high-priority sequences more often."""
    spec = make_spec(batch_size=64)
    state = replay_init(spec)
    blocks = _fill_blocks(spec, 2, rng)
    # block 0: tiny priorities; block 1: large
    b0 = blocks[0].replace(priority=np.full(spec.seqs_per_block, 0.01, np.float32))
    b1 = blocks[1].replace(priority=np.full(spec.seqs_per_block, 1.0, np.float32))
    state = replay_add(spec, state, b0)
    state = replay_add(spec, state, b1)
    batch = replay_sample(spec, state, jax.random.PRNGKey(1))
    frac_b1 = (np.asarray(batch.idxes) >= spec.seqs_per_block).mean()
    assert frac_b1 > 0.9


def test_host_replay_matches_contract_and_staleness_guard(rng):
    spec = make_spec()
    host = HostReplay(spec, seed=0, use_native=False)
    blocks = _fill_blocks(spec, 3, rng)
    for blk in blocks:
        host.add(blk)
    assert len(host) == 3 * spec.block_length

    batch, snapshot = host.sample()
    assert snapshot == 3
    assert batch.obs.shape == (
        spec.batch_size, spec.seq_window + spec.frame_stack - 1, 12, 12)

    # advance the ring over block 0, then write back stale priorities:
    # leaves of block 0 must keep the *new* block's priorities
    for blk in _fill_blocks(spec, 6, rng):
        host.add(blk)  # ptr: 3..8 -> wraps, overwrites block 0
    leaf0 = 2**host.tree_layers // 2 - 1
    before = host.tree[leaf0 : leaf0 + spec.seqs_per_block].copy()
    host.update_priorities(batch.idxes, np.full(spec.batch_size, 99.0), snapshot)
    after = host.tree[leaf0 : leaf0 + spec.seqs_per_block]
    np.testing.assert_array_equal(before, after)


def test_host_replay_guard_survives_full_ring_lap(rng):
    """Exactly num_blocks adds between sample and write-back returns the ring
    pointer to its snapshot value — the reference's pointer-equality guard
    (worker.py:196-206) would apply every stale update; the monotonic
    add-counter guard must drop them all."""
    spec = make_spec()
    host = HostReplay(spec, seed=0, use_native=False)
    for blk in _fill_blocks(spec, 3, rng):
        host.add(blk)
    batch, snapshot = host.sample()
    for blk in _fill_blocks(spec, spec.num_blocks, rng):  # full lap
        host.add(blk)
    assert host.ring.ptr == 3  # pointer is back where it was
    tree_before = host.tree.copy()
    host.update_priorities(batch.idxes, np.full(spec.batch_size, 99.0), snapshot)
    np.testing.assert_array_equal(host.tree, tree_before)


def test_device_host_same_layout(rng):
    """Device and host replay must store identical bytes for the same blocks
    (the feeder can switch placement without re-encoding)."""
    spec = make_spec()
    blocks = _fill_blocks(spec, 2, rng)
    state = replay_init(spec)
    host = HostReplay(spec, use_native=False)
    for blk in blocks:
        state = replay_add(spec, state, blk)
        host.add(blk)
    np.testing.assert_array_equal(np.asarray(state.obs), host.obs)
    np.testing.assert_array_equal(np.asarray(state.last_action), host.last_action)
    np.testing.assert_allclose(np.asarray(state.reward), host.reward, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(state.seq_start), host.seq_start)


def test_device_ring_bytes_matches_allocation():
    """The capacity guard's estimate must be exact for what replay_init
    actually allocates (VERDICT r4 #3: refuse with numbers, don't OOM)."""
    for kw in ({}, {"exact_gather": True}):
        spec = make_spec(**kw)
        state = replay_init(spec)
        allocated = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
        # block_ptr (one i32 scalar) is the only array outside the estimate
        assert allocated - spec.device_ring_bytes == 4, kw


def test_replay_init_refuses_oversized_ring(monkeypatch):
    """A ring larger than the device's reported HBM must fail fast with a
    clear message (before allocating anything), not OOM mid-init."""
    from r2d2_tpu.replay import device_replay

    class FakeTpu:
        platform = "tpu"

        def memory_stats(self):
            return {"bytes_limit": 1 << 30}

    monkeypatch.setattr(device_replay.jax, "devices", lambda: [FakeTpu()])
    big = make_spec(num_blocks=4000, frame_height=84, frame_width=84,
                    exact_gather=True)
    assert big.device_ring_bytes > (1 << 30)
    with pytest.raises(ValueError, match="OOM at replay_init"):
        replay_init(big)
    # the refusal names the exact_gather escape hatch with its real size
    with pytest.raises(ValueError, match="pallas_exact_gather"):
        replay_init(big)


def test_replay_init_warns_on_large_padded_ring(monkeypatch):
    """exact_gather's 1.74x storage pad on a multi-GiB ring warns once at
    replay_init (ADVICE r4) — without allocating here (guard called
    directly)."""
    from r2d2_tpu.replay.device_replay import _guard_device_capacity

    big = make_spec(num_blocks=8000, frame_height=84, frame_width=84,
                    exact_gather=True)
    assert big.device_ring_bytes > (2 << 30)
    with pytest.warns(UserWarning, match="pads stored frames 84x84"):
        _guard_device_capacity(big)
