"""Replay & data-pathology observability tests (ISSUE 10): device-vs-host
sum-tree leaf-histogram parity, the per-slot sample-count ring across
wrap and batched overwrite, eviction lifetimes against a sequential
reference, lane-provenance stamps end-to-end (queue transports, ring
wrap, the anakin paths, PR5-era blocks), the aggregator + new alert
rules, kill-switch record-schema stability for PR4–PR9 readers, and a
slow e2e slice proving the ``replay_diag`` block lands with a nonzero
never-sampled-before-eviction fraction.
"""

import jax
import numpy as np
import pytest

from r2d2_tpu.config import Config
from r2d2_tpu.replay.device_replay import (replay_add, replay_add_many,
                                           replay_init, replay_sample)
from r2d2_tpu.replay.structs import Block, ReplaySpec, empty_block_np
from r2d2_tpu.replay.synthetic import make_synthetic_block
from r2d2_tpu.telemetry.histogram import bucket_index, bucket_mid
from r2d2_tpu.telemetry.replaydiag import (ReplayDiag, ReplayDiagAggregator,
                                           derive_evictions, derive_lanes,
                                           derive_tree_stats, lane_counts,
                                           merge_shard_moments,
                                           tree_health_moments)

ACTIONS = 4


def tiny_cfg(**overrides) -> Config:
    cfg = Config().replace(**{
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 400, "replay.block_length": 20,
        "replay.batch_size": 8,
        "replay.pallas_sample_gather": "off",
        "replay.pallas_exact_gather": "off",
    })
    return cfg.replace(**overrides) if overrides else cfg


def tiny_net(cfg: Config):
    from r2d2_tpu.models.network import NetworkApply
    return NetworkApply(ACTIONS, cfg.network, cfg.env.frame_stack,
                        cfg.env.frame_height, cfg.env.frame_width)


def lane_block(spec, rng, lane: int, priority=None):
    blk = make_synthetic_block(spec, rng)
    fields = dict(
        action=np.asarray(blk.action) % ACTIONS,
        last_action_row=np.asarray(blk.last_action_row) % ACTIONS,
        lane=np.asarray(lane, np.int32))
    if priority is not None:
        fields["priority"] = np.full(
            (spec.seqs_per_block,), priority, np.float32)
    return blk.replace(**fields)


# ---------------------------------------------------------------------------
# sum-tree health: device-vs-host parity + derived indicators


def test_tree_health_device_matches_host_twin(rng):
    """Fill the jitted device replay and the HostReplay numpy twin with
    the SAME blocks (bucket-midpoint priorities, alpha=1 so leaves equal
    the stamps) — leaf histogram and moments must agree."""
    from r2d2_tpu.replay.host_replay import HostReplay
    cfg = tiny_cfg(**{"replay.prio_exponent": 1.0})
    spec = ReplaySpec.from_config(cfg)
    assert spec.replay_diag
    rs = replay_init(spec)
    hr = HostReplay(spec, seed=0, use_native=False)
    for i in range(6):
        blk = lane_block(spec, rng, i,
                         priority=bucket_mid(int(rng.integers(20, 60))))
        rs = replay_add(spec, rs, blk)
        hr.add(blk)
    moments, hist = jax.jit(
        lambda t: tree_health_moments(t, spec.tree_layers))(rs.tree)
    host = hr.diag_raw()
    np.testing.assert_array_equal(np.asarray(hist), host["leaf_hist"])
    np.testing.assert_allclose(np.asarray(moments),
                               host["tree_moments"], rtol=1e-5)
    # derived indicators agree too (the numbers the alert rules watch)
    dev = derive_tree_stats(np.asarray(moments), np.asarray(hist))
    hst = derive_tree_stats(host["tree_moments"], host["leaf_hist"])
    assert dev["active_leaves"] == hst["active_leaves"] == \
        6 * spec.seqs_per_block
    assert dev["ess_frac"] == pytest.approx(hst["ess_frac"], rel=1e-4)
    assert dev["frac_at_max"] == pytest.approx(hst["frac_at_max"],
                                               rel=1e-4)


def test_tree_health_collapse_indicators():
    """A hand-built leaf layout: 3 live leaves [1, 1, 8] → ESS, max/mean
    and at-max computed against the closed forms."""
    import jax.numpy as jnp
    from r2d2_tpu.ops.sum_tree import tree_init, tree_update
    layers, tree = tree_init(4)
    tree = tree_update(layers, tree, 1.0,
                       jnp.asarray([1.0, 1.0, 8.0]),
                       jnp.asarray([0, 1, 2]))
    moments, hist = tree_health_moments(tree, layers)
    stats = derive_tree_stats(np.asarray(moments), np.asarray(hist))
    assert stats["active_leaves"] == 3
    # ESS = (10)^2 / 66 (rounded to the block's 2-decimal precision)
    assert stats["ess"] == pytest.approx(100 / 66.0, abs=5e-3)
    assert stats["max_mean_ratio"] == pytest.approx(8 / (10 / 3), rel=1e-3)
    assert stats["frac_at_max"] == pytest.approx(1 / 3, rel=1e-4)
    assert sum(stats["leaf_hist_counts"]) == 3
    # empty / off-interval snapshots derive to None
    assert derive_tree_stats(np.full(5, np.nan)) is None
    assert derive_tree_stats(np.zeros(5)) is None


def test_value_counts_np_matches_device_and_scalar(rng):
    """The vectorized host bucketize (histogram.value_counts_np) agrees
    with BOTH the scalar bucket_index loop and the device scatter over
    bucket-midpoint-safe values."""
    from r2d2_tpu.telemetry.histogram import value_counts, value_counts_np
    buckets = rng.integers(1, 63, size=300)
    values = np.asarray([bucket_mid(int(b)) for b in buckets], np.float64)
    fast = value_counts_np(values)
    ref = np.zeros(64, np.int64)
    for v in values:
        ref[bucket_index(float(v))] += 1
    np.testing.assert_array_equal(fast, ref)
    np.testing.assert_array_equal(
        fast, np.asarray(value_counts(values.astype(np.float32))))
    # mask + clamp semantics match the device helper
    vals = np.asarray([0.0, 0.5, 1e12, np.nan])
    np.testing.assert_array_equal(
        value_counts_np(vals, mask=[1, 1, 1, 1]),
        np.asarray(value_counts(np.asarray(vals, np.float32))))
    assert value_counts_np(vals, mask=[0, 1, 0, 0]).sum() == 1


def test_merge_shard_moments_counts_at_global_max():
    # shard 0 max 2.0 (3 at max), shard 1 max 8.0 (2 at max): merged
    # at-max counts only shard 1's
    merged = merge_shard_moments(np.asarray(
        [[10, 12.0, 20.0, 2.0, 3], [10, 20.0, 70.0, 8.0, 2]]))
    assert merged[0] == 20 and merged[3] == 8.0 and merged[4] == 2
    stats = derive_tree_stats(merged)
    assert stats["frac_at_max"] == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# sample-lifetime accounting


def test_sample_count_ring_and_eviction_lifetimes(rng):
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)          # 20 ring rows
    rs = replay_init(spec)
    for i in range(spec.num_blocks):
        rs = replay_add(spec, rs, lane_block(spec, rng, i))
    # sample a few batches: counts accumulate at the sampled blocks
    from r2d2_tpu.telemetry.replaydiag import fused_replay_diag
    rdiag = ReplayDiag(interval=1, lanes=spec.num_blocks)
    for s in range(3):
        batch = replay_sample(spec, rs, jax.random.PRNGKey(s))
        rs, _ = jax.jit(
            lambda r, b: fused_replay_diag(spec, rdiag, 1, r, b))(rs, batch)
    counts = np.asarray(rs.sample_count)
    assert counts.sum() == 3 * spec.batch_size
    # wrap: overwrite the first 4 rows → their lifetimes accumulate and
    # their counts reset
    expected_life = counts[:4].sum()
    expected_never = int(np.sum(counts[:4] == 0))
    for i in range(4):
        rs = replay_add(spec, rs, lane_block(spec, rng, 50 + i))
    ev = np.asarray(rs.evict_stats)
    assert ev[0] == 4                            # evicted slots
    assert ev[1] == expected_never               # never sampled
    assert ev[2] == expected_life                # lifetime sum
    assert ev[3] == 4 * spec.num_blocks          # age = one full lap each
    assert np.all(np.asarray(rs.sample_count)[:4] == 0)
    assert int(np.asarray(rs.add_count)) == spec.num_blocks + 4
    # the snapshot READS AND RESETS the accumulators (per-interval
    # deltas — no f32 counter ever holds a run-length total)
    batch = replay_sample(spec, rs, jax.random.PRNGKey(9))
    rs, rd = jax.jit(
        lambda r, b: fused_replay_diag(spec, rdiag, 1, r, b))(rs, batch)
    assert np.asarray(rd["rd/evict_stats"])[0] == 4     # emitted delta
    assert np.all(np.asarray(rs.evict_stats) == 0)      # state reset


def test_add_many_eviction_parity_with_sequential(rng):
    """replay_add_many(K) must leave the SAME diagnostic state as K
    sequential replay_add calls — the eviction read-before-update order
    and birth stamps included."""
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    blocks = [lane_block(spec, rng, i) for i in range(spec.num_blocks + 5)]

    rs_a = replay_init(spec)
    for blk in blocks[:spec.num_blocks]:
        rs_a = replay_add(spec, rs_a, blk)
    # mark a few LIVE rows sampled so the wrap evicts nonzero lifetimes
    rs_a = rs_a.replace(sample_count=rs_a.sample_count.at[:3].add(2))
    rs_b = jax.tree_util.tree_map(lambda x: x.copy(), rs_a)
    for blk in blocks[spec.num_blocks:]:
        rs_a = replay_add(spec, rs_a, blk)
    stacked = jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]),
        *blocks[spec.num_blocks:])
    rs_b = replay_add_many(spec, rs_b, stacked)
    for name in ("sample_count", "added_at", "add_count", "evict_stats",
                 "evict_life_hist", "lane"):
        np.testing.assert_allclose(
            np.asarray(getattr(rs_a, name)),
            np.asarray(getattr(rs_b, name)), err_msg=name)
    ev = np.asarray(rs_a.evict_stats)
    assert ev[0] == 5 and ev[2] > 0              # lifetimes recorded


def test_host_replay_eviction_twin(rng):
    from r2d2_tpu.replay.host_replay import HostReplay
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    hr = HostReplay(spec, seed=0, use_native=False)
    for i in range(spec.num_blocks):
        hr.add(lane_block(spec, rng, i))
    for _ in range(3):
        hr.sample()
    sampled_counts = hr.sample_count.copy()
    for i in range(4):
        hr.add(lane_block(spec, rng, 90 + i))
    raw = hr.diag_raw()
    ev = raw["evict_stats"]
    assert ev[0] == 4
    assert ev[1] == float(np.sum(sampled_counts[:4] == 0))
    assert ev[2] == float(sampled_counts[:4].sum())
    block = derive_evictions(ev, raw["evict_life_hist"])
    assert block["evicted"] == 4
    assert 0.0 <= block["never_sampled_frac"] <= 1.0
    # read-and-reset, like the device snapshot: the next reading is a
    # fresh delta window
    assert hr.diag_raw()["evict_stats"][0] == 0


# ---------------------------------------------------------------------------
# lane provenance end-to-end


def test_lane_stamp_survives_ring_wrap_and_sampling(rng):
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    rs = replay_init(spec)
    n = spec.num_blocks
    for i in range(n + 3):
        rs = replay_add(spec, rs, lane_block(spec, rng, i % 7))
    ring = np.asarray(rs.lane)
    assert list(ring[:3]) == [(n + i) % 7 for i in range(3)]
    batch = replay_sample(spec, rs, jax.random.PRNGKey(0))
    assert set(int(v) for v in np.asarray(batch.lane)) <= set(range(7))


def test_lane_stamp_survives_queue_transports(rng):
    from r2d2_tpu.runtime.feeder import BlockQueue
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    blk = lane_block(spec, rng, 11)
    for q in (BlockQueue(maxsize=4, use_mp=True, shm_spec=spec),
              BlockQueue(maxsize=4, use_mp=True),
              BlockQueue(maxsize=4, use_mp=False)):
        try:
            q.put(blk, timeout=5.0)
            got = q.get(timeout=5.0)
            assert int(np.asarray(got.lane)) == 11
            q.put(blk, timeout=5.0)
            q.put(lane_block(spec, rng, 13), timeout=5.0)
            import time
            deadline = time.time() + 10.0
            lanes = []
            while len(lanes) < 2 and time.time() < deadline:
                stacked, k = q.drain_stacked(4)
                if k:
                    lanes += [int(v) for v in np.asarray(stacked.lane)]
                else:
                    time.sleep(0.01)
            assert lanes == [11, 13]
        finally:
            q.close()


def test_instrument_sink_offsets_lane_base(rng):
    from r2d2_tpu.runtime.actor_loop import instrument_block_sink
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    seen = []
    sink = instrument_block_sink(cfg, 0, seen.append, lane_base=32)
    # run loops stamp the RELATIVE lane; the sink offsets it
    sink(lane_block(spec, rng, 3))
    # an UNstamped block (-1) stays unknown — never fabricated into the
    # worker's first lane
    sink(make_synthetic_block(spec, rng))
    assert int(np.asarray(seen[0].lane)) == 35
    assert int(np.asarray(seen[1].lane)) == -1


def test_pr5_era_block_defaults_to_unknown_lane(rng):
    """A PR5-era record — no lane field — must construct, flow through
    replay, and report lane unknown (the small-fix satellite)."""
    cfg = tiny_cfg()
    spec = ReplaySpec.from_config(cfg)
    legacy = {k: v for k, v in empty_block_np(spec).items() if k != "lane"}
    blk = Block(**legacy)
    assert int(np.asarray(blk.lane)) == -1
    rs = replay_init(spec)
    rs = replay_add(spec, rs, blk.replace(
        priority=np.ones((spec.seqs_per_block,), np.float32),
        learning_steps=np.full((spec.seqs_per_block,), spec.learning,
                               np.int32)))
    batch = replay_sample(spec, rs, jax.random.PRNGKey(0))
    assert np.all(np.asarray(batch.lane) == -1)
    counts = np.asarray(lane_counts(batch.lane, 4))
    assert counts[-1] == spec.batch_size         # all unknown
    lanes = derive_lanes(counts, 4)
    assert lanes["unknown_frac"] == 1.0


def test_anakin_blocks_carry_global_lanes():
    from r2d2_tpu.actor.anakin import init_act_carry, make_anakin_act
    from r2d2_tpu.envs.factory import create_jax_env
    from r2d2_tpu.models.network import NetworkApply
    cfg = tiny_cfg(**{
        "env.game_name": "Fake", "env.frame_height": 8, "env.frame_width": 8,
        "env.episode_len": 20,
        "network.conv_layers": ((4, 4, 4),), "network.cnn_out_dim": 16,
    })
    spec = ReplaySpec.from_config(cfg)
    env = create_jax_env(cfg.env)
    net = NetworkApply(env.action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    params = net.init(jax.random.PRNGKey(0))
    act = make_anakin_act(env, net, spec, num_lanes=4, epsilons=[0.4] * 4,
                          gamma=0.997, priority=1.0, near_greedy_eps=0.02)
    carry = init_act_carry(env, spec, 4, jax.random.PRNGKey(1))
    _, blocks, _ = act(params, carry, np.int32(1))
    assert list(np.asarray(blocks.lane)) == [0, 1, 2, 3]


def test_sharded_anakin_lane_stamps_span_global_ladder():
    from r2d2_tpu.config import MeshConfig
    from r2d2_tpu.envs.factory import create_jax_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.parallel import (init_sharded_act_carry, make_mesh,
                                   make_sharded_anakin_act,
                                   sharded_replay_init)
    cfg = tiny_cfg(**{
        "env.game_name": "Fake", "env.frame_height": 8, "env.frame_width": 8,
        "env.episode_len": 20,
        "network.conv_layers": ((4, 4, 4),), "network.cnn_out_dim": 16,
    })
    spec = ReplaySpec.from_config(cfg)
    env = create_jax_env(cfg.env)
    net = NetworkApply(env.action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    params = net.init(jax.random.PRNGKey(0))
    mesh = make_mesh(MeshConfig(dp=2))
    act = make_sharded_anakin_act(env, net, spec, mesh=mesh, num_lanes=4,
                                  epsilons=[0.4] * 4, gamma=0.997,
                                  priority=1.0, near_greedy_eps=0.02)
    carry = init_sharded_act_carry(env, spec, 4, mesh, jax.random.PRNGKey(2))
    rs = sharded_replay_init(spec, mesh)
    carry, rs, _ = act(params, carry, rs, np.int32(1))
    ring = np.asarray(rs.lane)                  # (dp, N)
    assert list(ring[0][:2]) == [0, 1]          # shard 0: ladder slice 0-1
    assert list(ring[1][:2]) == [2, 3]          # shard 1: ladder slice 2-3
    # per-shard sample-count rings exist and start clean
    assert np.asarray(rs.sample_count).shape == (2, spec.num_blocks)


# ---------------------------------------------------------------------------
# fused-step integration + sharded views


def _fused_setup(rng, rdiag, **cfg_over):
    from r2d2_tpu.learner.train_step import (create_train_state,
                                             make_learner_step)
    cfg = tiny_cfg(**cfg_over)
    spec = ReplaySpec.from_config(cfg)
    net = tiny_net(cfg)
    ts = create_train_state(jax.random.PRNGKey(0), net, cfg.optim)
    rs = replay_init(spec)
    for i in range(4):
        rs = replay_add(spec, rs, lane_block(spec, rng, i))
    step = make_learner_step(net, spec, cfg.optim, cfg.network.use_double,
                             rdiag=rdiag)
    return cfg, spec, ts, rs, step


def test_fused_step_emits_replay_metrics(rng):
    cfg, spec, ts, rs, step = _fused_setup(
        rng, ReplayDiag(interval=1, lanes=8))
    ts, rs, m = step(ts, rs)
    assert np.asarray(m["rd/lane_counts"]).shape == (9,)
    assert int(np.asarray(m["rd/lane_counts"]).sum()) == spec.batch_size
    moments = np.asarray(m["rd/tree_moments"])
    assert moments[0] == 4 * spec.seqs_per_block        # active leaves
    assert int(np.asarray(m["rd/leaf_hist"]).sum()) == int(moments[0])
    assert np.all(np.isfinite(np.asarray(m["rd/evict_stats"])))
    # the sample-count ring advanced at the sampled blocks
    assert int(np.asarray(rs.sample_count).sum()) == spec.batch_size


def test_fused_step_interval_gates_snapshot(rng):
    cfg, spec, ts, rs, step = _fused_setup(
        rng, ReplayDiag(interval=2, lanes=8))
    ts, rs, m1 = step(ts, rs)
    ts, rs, m2 = step(ts, rs)
    assert np.isnan(np.asarray(m1["rd/tree_moments"])).all()
    assert np.isfinite(np.asarray(m2["rd/tree_moments"])).all()
    # lane counts + sample counting flow EVERY step
    assert int(np.asarray(m1["rd/lane_counts"]).sum()) == spec.batch_size
    assert int(np.asarray(rs.sample_count).sum()) == 2 * spec.batch_size


def test_fused_step_without_rdiag_has_no_rd_keys(rng):
    cfg, spec, ts, rs, step = _fused_setup(rng, None)
    ts, rs, m = step(ts, rs)
    assert not any(k.startswith("rd/") for k in m)


def test_kill_switch_compiles_without_diag_state(rng):
    """spec.replay_diag=False: replay_init allocates no diagnostic
    state, the sampled batch still carries the always-on lane stamp, and
    the config resolution follows the kill switches."""
    cfg = tiny_cfg(**{"telemetry.replay_diag_enabled": False})
    spec = ReplaySpec.from_config(cfg)
    assert not spec.replay_diag
    rs = replay_init(spec)
    assert rs.sample_count is None and rs.evict_stats is None
    assert rs.lane is not None
    rs = replay_add(spec, rs, lane_block(rng=rng, spec=spec, lane=2))
    batch = replay_sample(spec, rs, jax.random.PRNGKey(0))
    assert int(np.asarray(batch.lane)[0]) in (-1, 2)
    assert ReplayDiag.from_config(cfg) is None
    assert ReplayDiag.from_config(
        tiny_cfg(**{"telemetry.enabled": False})) is None
    d = ReplayDiag.from_config(tiny_cfg(**{"actor.num_actors": 3,
                                           "actor.envs_per_actor": 4}))
    assert d == ReplayDiag(interval=50, lanes=12)
    # multihost fleets stamp GLOBAL lane indices across every process's
    # workers — the bincount must span process_count * local lanes
    assert ReplayDiag.from_config(
        tiny_cfg(**{"actor.num_actors": 3, "actor.envs_per_actor": 4,
                    "mesh.multihost": True,
                    "mesh.num_processes": 2})).lanes == 24
    assert ReplayDiag.from_config(
        tiny_cfg(**{"env.episode_len": 20, "actor.on_device": True,
                    "actor.anakin_lanes": 20})).lanes == 20


def test_sharded_step_emits_per_shard_and_merged_views(rng):
    from r2d2_tpu.learner.train_step import create_train_state
    from r2d2_tpu.parallel import (make_mesh, make_sharded_learner_step,
                                   make_sharded_replay_add,
                                   sharded_replay_init)
    cfg = tiny_cfg(**{"mesh.dp": 2})
    spec = ReplaySpec.from_config(cfg)
    net = tiny_net(cfg)
    ts = create_train_state(jax.random.PRNGKey(0), net, cfg.optim)
    mesh = make_mesh(cfg.mesh)
    rs = sharded_replay_init(spec, mesh)
    add = make_sharded_replay_add(spec, mesh)
    for i in range(4):
        rs = add(rs, lane_block(spec, rng, i), i % 2)
    step = make_sharded_learner_step(
        net, spec, cfg.optim, cfg.network.use_double, mesh,
        rdiag=ReplayDiag(interval=1, lanes=8))
    ts, rs, m = step(ts, rs)
    sm = np.asarray(m["rd/shard_tree_moments"])
    assert sm.shape == (2, 5)
    assert np.all(sm[:, 0] == 2 * spec.seqs_per_block)   # 2 blocks/shard
    assert np.asarray(m["rd/shard_leaf_hist"]).shape == (2, 64)
    # global lane composition psums over shards: dp * batch sequences
    assert int(np.asarray(m["rd/lane_counts"]).sum()) == 2 * spec.batch_size
    # the aggregator builds per-shard rows + a merged tree view from it
    agg = ReplayDiagAggregator(lanes=8)
    agg.on_dispatch(m)
    block = agg.flush()
    assert len(block["shards"]) == 2
    assert block["tree"]["active_leaves"] == 4 * spec.seqs_per_block
    assert block["lanes"]["sampled_sequences"] == 2 * spec.batch_size


# ---------------------------------------------------------------------------
# aggregation + derived blocks


def _fake_dispatch(interval_fired=True, lanes=4):
    moments = (np.asarray([10.0, 5.0, 3.0, 1.5, 2.0], np.float32)
               if interval_fired else np.full(5, np.nan, np.float32))
    hist = np.zeros(64, np.int32)
    if interval_fired:
        hist[30] = 10
    ev = (np.asarray([6.0, 3.0, 9.0, 60.0, 1.2], np.float32)
          if interval_fired else np.full(5, np.nan, np.float32))
    lc = np.zeros(lanes + 1, np.int32)
    lc[0] = 5
    lc[1] = 2
    lc[lanes] = 1
    return {"rd/tree_moments": moments, "rd/leaf_hist": hist,
            "rd/evict_stats": ev, "rd/evict_life_hist": hist.copy(),
            "rd/lane_counts": lc}


def test_aggregator_builds_replay_diag_block():
    agg = ReplayDiagAggregator(lanes=4)
    agg.on_dispatch(_fake_dispatch(interval_fired=True))
    agg.on_dispatch(_fake_dispatch(interval_fired=False))
    block = agg.flush()
    # snapshot keys take the newest FIRING (the NaN dispatch is skipped)
    assert block["tree"]["active_leaves"] == 10
    assert block["tree"]["ess_frac"] == pytest.approx(25 / 30.0, rel=1e-3)
    ev = block["evictions"]
    assert ev["evicted"] == 6 and ev["never_sampled"] == 3
    assert ev["never_sampled_frac"] == 0.5
    assert ev["mean_age_blocks"] == 10.0
    # lane counts SUM across the interval's dispatches
    lanes = block["lanes"]
    assert lanes["sampled_sequences"] == 16
    assert lanes["active_lanes"] == 2
    assert lanes["starved_frac"] == 0.5
    assert lanes["unknown_frac"] == pytest.approx(2 / 16)
    assert lanes["counts"] == [10, 4, 0, 0]
    # flush consumed the interval; eviction totals INTEGRATE across
    # flushes (the device accumulators are read-and-reset deltas, so no
    # f32 counter ever holds a run-length total)
    assert agg.flush() is None
    agg.on_dispatch(_fake_dispatch(interval_fired=True))
    block2 = agg.flush()
    assert block2["evictions"]["evicted"] == 12
    assert block2["evictions"]["never_sampled"] == 6
    assert block2["evictions"]["interval"] == {
        "evicted": 6, "never_sampled": 3, "never_sampled_frac": 0.5}


def test_aggregator_handles_multi_step_stacked_rows():
    agg = ReplayDiagAggregator(lanes=4)
    d1 = _fake_dispatch(True)
    d2 = _fake_dispatch(False)
    stacked = {k: np.stack([d1[k], d2[k]]) for k in d1}
    agg.on_dispatch(stacked)
    block = agg.flush()
    assert block["tree"]["active_leaves"] == 10    # row 0 is the firing
    assert block["lanes"]["sampled_sequences"] == 16


def test_aggregator_host_stats_substitute():
    agg = ReplayDiagAggregator(lanes=4)
    d = _fake_dispatch(False)
    d.pop("rd/tree_moments"), d.pop("rd/leaf_hist")
    d.pop("rd/evict_stats"), d.pop("rd/evict_life_hist")
    agg.on_dispatch(d)                   # host placement: lane counts only
    host = {"tree_moments": np.asarray([4.0, 2.0, 1.0, 0.5, 1.0]),
            "leaf_hist": np.zeros(64, np.int64),
            "evict_stats": np.asarray([2.0, 1.0, 3.0, 10.0, 0.5]),
            "evict_life_hist": np.zeros(64, np.int64)}
    block = agg.flush(host_stats=host)
    assert block["tree"]["active_leaves"] == 4
    assert block["evictions"]["never_sampled_frac"] == 0.5
    assert block["lanes"]["sampled_sequences"] == 8


# ---------------------------------------------------------------------------
# alert rules + sentinel


def _rd_record(ess_frac=0.5, frac_at_max=0.1, never_frac=None,
               starved=0.0):
    rd = {"tree": {"ess_frac": ess_frac, "frac_at_max": frac_at_max},
          "lanes": {"starved_frac": starved}}
    if never_frac is not None:
        # the growth rule watches THIS interval's fraction (the
        # cumulative one's change decays as 1/t)
        rd["evictions"] = {"never_sampled_frac": never_frac,
                           "interval": {"evicted": 10,
                                        "never_sampled_frac": never_frac}}
    return {"replay_diag": rd}


def test_alert_rules_fire_on_replay_pathologies():
    from r2d2_tpu.telemetry import AlertEngine, default_rules
    cfg = tiny_cfg()
    engine = AlertEngine(default_rules(cfg.telemetry))
    names = {r.name for r in engine.rules}
    assert {"priority_collapse", "priority_saturation",
            "never_sampled_growth", "lane_starvation"} <= names
    # healthy record: nothing fires
    block = engine.evaluate(_rd_record())
    assert not block["fired"]
    # ESS collapse + saturation + starvation fire on their edges
    block = engine.evaluate(_rd_record(ess_frac=0.01, frac_at_max=0.9,
                                       starved=0.8))
    fired = {a["rule"] for a in block["fired"]}
    assert {"priority_collapse", "priority_saturation",
            "lane_starvation"} <= fired
    # growth rule: healthy window, then a 4x jump
    engine2 = AlertEngine(default_rules(cfg.telemetry))
    for _ in range(cfg.telemetry.alerts_window):
        assert not engine2.evaluate(_rd_record(never_frac=0.1))["fired"]
    block = engine2.evaluate(_rd_record(never_frac=0.4))
    assert [a["rule"] for a in block["fired"]] == ["never_sampled_growth"]


def test_sentinel_rules_listing_includes_replay_rules(capsys):
    from r2d2_tpu.tools.sentinel import main
    assert main(["--rules"]) == 0
    out = capsys.readouterr().out
    for name in ("priority_collapse", "priority_saturation",
                 "never_sampled_growth", "lane_starvation"):
        assert name in out, name
    assert "replay_diag.tree.ess_frac" in out


# ---------------------------------------------------------------------------
# config round-trip + record schema stability


def test_config_roundtrips_replay_diag_fields():
    cfg = tiny_cfg(**{"telemetry.replay_diag_enabled": False,
                      "telemetry.replay_diag_interval": 77,
                      "telemetry.alerts_replay_ess_frac": 0.1,
                      "telemetry.alerts_lane_starved_frac": 0.9})
    back = Config.from_json(cfg.to_json())
    assert back.telemetry.replay_diag_enabled is False
    assert back.telemetry.replay_diag_interval == 77
    assert back.telemetry.alerts_replay_ess_frac == 0.1
    assert back.telemetry.alerts_lane_starved_frac == 0.9


def test_pre_pr10_config_dict_loads_with_defaults():
    d = Config().to_dict()
    # a PR9-era checkpoint config: telemetry section without the new keys
    for k in ("replay_diag_enabled", "replay_diag_interval",
              "alerts_replay_ess_frac", "alerts_priority_saturation",
              "alerts_never_sampled_growth", "alerts_lane_starved_frac"):
        del d["telemetry"][k]
    cfg = Config.from_dict(d)
    assert cfg.telemetry.replay_diag_enabled is True
    assert cfg.telemetry.replay_diag_interval == 50
    assert ReplayDiag.from_config(cfg) is not None


def test_config_validates_replay_diag_fields():
    with pytest.raises(ValueError, match="replay_diag_interval"):
        tiny_cfg(**{"telemetry.replay_diag_interval": 0})
    with pytest.raises(ValueError, match="alerts_replay_ess_frac"):
        tiny_cfg(**{"telemetry.alerts_replay_ess_frac": 1.5})
    with pytest.raises(ValueError, match="alerts_never_sampled_growth"):
        tiny_cfg(**{"telemetry.alerts_never_sampled_growth": 1.0})


def test_record_schema_replay_diag_block(tmp_path):
    from r2d2_tpu.runtime.metrics import TrainMetrics
    m = TrainMetrics(0, str(tmp_path))
    m.set_replay_diag({"tree": {"ess_frac": 0.4}})
    record = m.log(1.0)
    assert record["replay_diag"]["tree"]["ess_frac"] == 0.4
    # PR2..PR9 reader keys unaffected (schema stability)
    for key in ("buffer_size", "env_steps", "training_steps", "loss",
                "ingest_blocks_total", "ingest_drains", "actor_restarts",
                "actor_parked_slots", "heartbeat_age_max_s",
                "dropped_priority_updates"):
        assert key in record, key
    # consumed on emission; absent when nothing was set (the kill-switch
    # schema: records byte-identical to PR9)
    record2 = m.log(1.0)
    assert "replay_diag" not in record2
    # and the block round-trips the JSONL stream into the plot series
    from r2d2_tpu.tools.logparse import parse_jsonl, replay_diag_series
    records = parse_jsonl(str(tmp_path / "metrics_player0.jsonl"))
    series = replay_diag_series(records)
    assert series["ess_frac"] == [0.4]


def test_render_replay_diag_panel():
    from r2d2_tpu.tools.inspect import render_record
    frame = render_record({
        "t": 10.0, "env_steps": 100, "training_steps": 5, "buffer_size": 50,
        "replay_diag": {
            "tree": {"active_leaves": 64, "ess": 20.0, "ess_frac": 0.31,
                     "max_mean_ratio": 4.2, "frac_at_max": 0.05,
                     "priorities": {"count": 64, "p50": 0.5, "p95": 1.2,
                                    "p99": 2.0}},
            "shards": [{"active_leaves": 32, "ess_frac": 0.3,
                        "frac_at_max": 0.04},
                       {"active_leaves": 32, "ess_frac": 0.32,
                        "frac_at_max": 0.06}],
            "evictions": {"evicted": 40, "never_sampled": 10,
                          "never_sampled_frac": 0.25,
                          "mean_lifetime": 2.5, "mean_age_blocks": 20,
                          "interval": {"evicted": 8, "never_sampled": 2}},
            "lanes": {"total_lanes": 16, "active_lanes": 12,
                      "starved_frac": 0.25, "max_share": 0.2,
                      "unknown_frac": 0.0, "sampled_sequences": 64},
        }})
    assert "replay: tree active=64" in frame
    assert "NEVER-SAMPLED 25.0%" in frame
    assert "shard 1" in frame
    assert "12/16 active" in frame


# ---------------------------------------------------------------------------
# slow e2e slice: the replay_diag block lands end-to-end


@pytest.mark.slow
def test_e2e_replay_diag_block_and_kill_switch(tmp_path):
    from r2d2_tpu.runtime.orchestrator import train
    from tests.test_runtime import tiny_config

    # a SMALL ring (10 rows) so evictions happen inside the slice and
    # the never-sampled fraction is meaningfully nonzero
    cfg = tiny_config(tmp_path, **{
        "replay.capacity": 200, "replay.learning_starts": 60,
        "runtime.save_interval": 0,
        "runtime.log_interval": 1.0,
        "telemetry.replay_diag_interval": 5,
    })
    records = []
    stacks = train(cfg, max_training_steps=40, max_seconds=180,
                   actor_mode="thread", log_fn=records.append)
    assert stacks[0].learner.training_steps >= 40
    blocks = [r["replay_diag"] for r in records if r.get("replay_diag")]
    assert blocks, "no replay_diag block in any record"
    trees = [b["tree"] for b in blocks if b.get("tree")]
    assert trees and all(t["active_leaves"] > 0 for t in trees)
    assert all(0 < t["ess_frac"] <= 1.0 for t in trees)
    # the ring wrapped: evictions accumulated with a NONZERO
    # never-sampled fraction (10-row ring, 2 actors outrunning sampling)
    evs = [b["evictions"] for b in blocks if b.get("evictions")]
    assert evs and evs[-1]["evicted"] > 0
    assert evs[-1].get("never_sampled_frac", 0) > 0
    # lane composition spans the 2-worker ladder with global stamps
    lanes = [b["lanes"] for b in blocks if b.get("lanes")]
    assert lanes and lanes[-1]["total_lanes"] == 2
    assert lanes[-1]["unknown_frac"] == 0.0
    assert lanes[-1]["active_lanes"] >= 1

    # kill switch: same system, replay_diag_enabled=false → no block at
    # all (records byte-identical to the PR9 schema)
    cfg_off = tiny_config(tmp_path / "off", **{
        "replay.capacity": 200, "replay.learning_starts": 60,
        "runtime.save_interval": 0, "runtime.log_interval": 1.0,
        "telemetry.replay_diag_enabled": False,
    })
    records_off = []
    train(cfg_off, max_training_steps=10, max_seconds=120,
          actor_mode="thread", log_fn=records_off.append)
    assert records_off
    assert all("replay_diag" not in r for r in records_off)
