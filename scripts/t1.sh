#!/usr/bin/env bash
# Tier-1 gate — the EXACT command from ROADMAP.md ("Tier-1 verify"), so
# builders, CI, and the driver all run the same thing. Prints
# DOTS_PASSED=<n> (the driver's pass-count convention) and exits with
# pytest's status.
#
# Usage: scripts/t1.sh  (from the repo root or any subdirectory)

set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
