"""Benchmark: learner sequence-updates/sec/chip (BASELINE.md north star).

Measures the fused R2D2 learner step — prioritized sample from HBM replay +
full 55-step conv/LSTM unroll + value-rescaled double/dueling loss + Adam +
priority write-back, one XLA program — at the reference's training
configuration (batch 128 sequences, burn-in 40 / learning 10 / n-step 5,
84x84x4 frames, cnn_out 1024, LSTM 512, dueling on, double off;
/root/reference/config.py).

Measurements (VERDICT r2 #1/#3 + the rounds-3/4 kernels):
  1. obs-decode A/B at the base config: XLA gather vs the pallas VMEM kernel;
  1b. replay sample-gather A/B: the scalar-prefetch pallas row gather vs the
     XLA batched-dynamic-slice gather, inside the full fused step;
  2. the perf matrix {f32, bf16} x {steps_per_dispatch 1, 4, 16} on the
     default decode path — the reference's amp analog (config.py:35) and the
     host-dispatch amortization the reference cannot do (it pays a Ray RPC
     per step by construction, worker.py:303);
  2b. optional A/B cells, ordered by information value: the fused pallas
     LSTM scan (block_t sweep), the gather variant opposite the shipped
     default, space_to_depth, NHWC decode (default-skipped dead end), and
     the double-DQN unroll-fusion pair;
  3. an analytic model-FLOPs/s estimate against the chip's peak (MFU).

This file measures the LEARNER side only (synthetic replay, no actors).
The system-level number — process-mode vector actors feeding this learner,
env-steps/s and learner steps/s reported together — is
r2d2_tpu/tools/e2e_bench.py (also reachable as a soak phase:
``cli.soak --e2e-seconds=...``); artifact E2E_r06.json.

vs_baseline: the reference publishes NO numbers (BASELINE.json "published":
{}). Its learner logs 'training speed' in updates/s (worker.py:229); upstream
runs of this codebase on a desktop GPU train at ~5 updates/s = 640
sequence-updates/s (128-sequence batches). That figure is the documented
baseline estimate used here until a measured reference log is available.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Resilience (VERDICT r3 #1): the measurement runs under a supervisor in the
same file. The supervisor probes backend discovery in a SUBPROCESS with a
bounded timeout and retries with backoff (a wedged remote-TPU tunnel makes
`jax.devices()` HANG, not fail — observed rounds 1 and 3), then runs the
measurement itself as a child with an overall deadline. On persistent
backend failure it prefers THIS RUN's partial results — the child
checkpoints the matrix after every cell (emit_partial_or_stale, flagged
"partial": true) — and only then the last driver-grade measurement from
BENCH_CACHE.json with an explicit "stale": true flag; both exit 0, so a
wedged tunnel at driver time degrades the artifact instead of losing the
round's number (round 4: a mid-matrix wedge in an optional cell would
otherwise have discarded nine fresh cells). A fresh successful TPU
measurement rewrites the cache.

Env knobs (used by tests/test_bench_diag.py):
  R2D2_BENCH_SMOKE=1                 tiny config, xla-decode spd=1 only
  R2D2_BENCH_SIMULATE_DISPATCH_FAILURE=1  raise at first dispatch (diagnostics path)
  R2D2_BENCH_CHILD=1                 run the measurement directly (no supervisor)
  R2D2_BENCH_CACHE=path              last-good cache location (default: ./BENCH_CACHE.json)
  R2D2_BENCH_PROBE_TIMEOUT / _ATTEMPTS / _BACKOFF   discovery retry schedule
  R2D2_BENCH_CHILD_TIMEOUT           overall measurement deadline (s)
  R2D2_BENCH_FORCE_CACHE=1           cache even non-TPU results (tests)
  R2D2_BENCH_PARTIAL=path            mid-run cell snapshot (default: $TMPDIR)
  R2D2_BENCH_SIMULATE_HANG=1         wedge after the base matrix (tests)
"""

import dataclasses
import json
import os
import sys
import time

import numpy as np

REFERENCE_SEQ_UPDATES_PER_SEC = 640.0  # ~5 train steps/s * batch 128 (see above)

# Child exit code for DIAGNOSED backend failures (wedged tunnel, dispatch
# failure on a known-good program). The supervisor masks only this code
# (and signal deaths) with the stale cache — a genuine code crash stays a
# loud nonzero exit so regressions are never hidden behind last round's
# number.
BACKEND_FAILURE_RC = 42

BACKEND_GUIDANCE = (
    "  If this is the remote-TPU tunnel: a previously killed "
    "TPU-holding process can wedge the tunnel until the environment "
    "resets; retry later or run with JAX_PLATFORMS=cpu for a "
    "smoke-only number."
)

# Per-chip dense peak (bf16 matmul FLOP/s) by device_kind substring — the
# MFU denominator convention of jax-ml.github.io/scaling-book. f32 configs
# are reported against the same bf16 peak (stated in the output) since the
# MXU's native multiply precision is bf16.
PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),       # Trillium
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def init_backend_or_die():
    """Initialize the JAX backend up front with actionable diagnostics —
    round 1 died with a bare 'Unable to initialize backend' when the remote
    TPU tunnel was wedged by an earlier hard-killed process. A wedged
    tunnel can also make discovery HANG rather than fail (observed round
    3), so a watchdog prints the guidance to stderr while we wait — the
    driver's eventual timeout then leaves a diagnosis in the log tail."""
    import threading

    import jax

    watchdog = threading.Timer(90.0, lambda: print(
        "bench: backend discovery has been stuck for 90s — the remote-TPU "
        "tunnel is likely wedged by an earlier hard-killed process.\n"
        + BACKEND_GUIDANCE, file=sys.stderr, flush=True))
    watchdog.daemon = True
    watchdog.start()
    try:
        devs = jax.devices()
    except RuntimeError as e:
        print(
            "bench: JAX backend init FAILED.\n"
            f"  error: {e}\n"
            f"  JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '<unset>')!r}\n"
            + BACKEND_GUIDANCE,
            file=sys.stderr)
        sys.exit(BACKEND_FAILURE_RC)
    finally:
        watchdog.cancel()
    print(f"backend: {devs[0].platform} x{len(devs)} "
          f"({devs[0].device_kind})", file=sys.stderr)
    return devs


def peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for marker, peak in PEAK_FLOPS_BY_KIND:
        if marker in kind:
            return peak
    return 0.0  # unknown chip: MFU omitted


def model_flops_per_step(cfg, action_dim: int, use_double: bool) -> float:
    """Analytic model FLOPs for one train step (fwd + bwd ~= 3x fwd MACs*2),
    counting the conv torso, FC, LSTM, and head matmuls over the full
    (batch x seq_window) unroll. Elementwise/decode/Adam FLOPs are noise
    against these and are not counted.

    The math lives in telemetry/costmodel.py (ONE source for this count,
    the roofline tool, and the cost-regression gate), reconciled against
    XLA ``cost_analysis()`` there: the first conv's input gradient is
    never computed (obs needs no grad — XLA DCEs it), which the pre-PR9
    count here overstated by 5-7% at the reference shape."""
    from r2d2_tpu.telemetry.costmodel import model_flops_per_step as _mfps
    return _mfps(cfg, action_dim, use_double)


def make_synthetic_block(spec, rng):
    # shared with tools/soak.py so bench and soak can never construct
    # divergent reference-shaped data
    from r2d2_tpu.replay.synthetic import make_synthetic_block as _mk
    return _mk(spec, rng)


class FirstDispatchError(Exception):
    """First compile+dispatch of a known-good program failed — the backend
    (not the program) is the suspect."""


def _last_loss(metrics):
    """Scalar loss from single-step ({} of scalars) or multi-step ((K,))."""
    loss = np.asarray(metrics["loss"])
    return float(loss.reshape(-1)[-1])


def measure_path(step, ts, rs, label: str, steps_per_dispatch: int = 1,
                 n_timed: int = 30, diagnose_backend: bool = False):
    """Compile, warm up, and time one step function. Returns
    (train_steps_per_sec, ts, rs) — threading state through so all paths
    reuse the same filled replay ring.

    With diagnose_backend, a RuntimeError at the first compile+dispatch is
    wrapped in FirstDispatchError: the program is known-good, so the failure
    is the backend's (VERDICT r2 #5 — BENCH_r02 n=1 died with a raw
    traceback when the wedged tunnel surfaced at first dispatch, after
    init's jax.devices() guard had already passed)."""
    import jax

    t0 = time.time()
    try:
        if os.environ.get("R2D2_BENCH_SIMULATE_DISPATCH_FAILURE"):
            raise RuntimeError("simulated backend failure at first dispatch")
        ts, rs, m = step(ts, rs)
        jax.block_until_ready(m["loss"])
    except (RuntimeError, jax.errors.JaxRuntimeError) as e:
        if diagnose_backend:
            raise FirstDispatchError(str(e)) from e
        raise
    print(f"[{label}] compile + first step: {time.time()-t0:.1f}s "
          f"loss={_last_loss(m):.5f}", file=sys.stderr)

    for _ in range(3):  # warmup
        ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])

    # TWO independent timing windows, not one: a transient tunnel stall
    # inside a single window silently corrupts the cell (BENCH r4's
    # f32_spd4 read 245 seq/s, 34x under its real value, from exactly
    # this). A stall can only make a window SLOWER, never faster, so when
    # the windows disagree the faster one is the measurement; agreement
    # combines both for the tighter estimate.
    rates = []
    for _ in range(2):
        t0 = time.time()
        for _ in range(n_timed // 2):
            ts, rs, m = step(ts, rs)
        jax.block_until_ready(m["loss"])
        rates.append((n_timed // 2) * steps_per_dispatch / (time.time() - t0))
    if max(rates) > 1.3 * min(rates):
        steps_per_sec = max(rates)
        print(f"[{label}] timing windows disagree "
              f"({rates[0]:.2f} vs {rates[1]:.2f} steps/s — transient "
              "backend stall?); taking the faster window", file=sys.stderr)
    else:
        steps_per_sec = sum(rates) / 2
    print(f"[{label}] {steps_per_sec:.2f} train steps/s; "
          f"loss={_last_loss(m):.5f}", file=sys.stderr)
    return steps_per_sec, ts, rs


def run_bench() -> None:
    # Route any JAX_PLATFORMS request through jax.config BEFORE backend
    # discovery: with a wedged remote-TPU tunnel, the env var alone does not
    # stop the accelerator plugin from hanging discovery (it filters after
    # plugin init) — a JAX_PLATFORMS=cpu bench run must never touch it.
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    devs = init_backend_or_die()
    on_tpu = devs[0].platform not in ("cpu",)
    smoke = bool(os.environ.get("R2D2_BENCH_SMOKE"))

    import jax

    from r2d2_tpu.config import Config
    from r2d2_tpu.learner import (
        create_train_state, make_learner_step, make_multi_learner_step)
    from r2d2_tpu.models import init_network
    from r2d2_tpu.ops.pallas_kernels import resolve_pallas_obs_decode
    from r2d2_tpu.replay import ReplaySpec, replay_add, replay_init

    # reference-default training config; replay capacity trimmed to bound
    # bench setup time (25.6k steps of ring is plenty to sample 128 from)
    cfg = Config().replace(**{"replay.capacity": 25_600})
    if smoke:
        cfg = cfg.replace(**{
            "replay.capacity": 1_600, "replay.block_length": 400,
            "replay.batch_size": 8, "network.hidden_dim": 64,
            "network.cnn_out_dim": 64})
    spec = ReplaySpec.from_config(cfg)
    action_dim = 18  # full Atari action set

    net, _ = init_network(jax.random.PRNGKey(0), action_dim, cfg.network)
    ts = create_train_state(jax.random.PRNGKey(1), net, cfg.optim)
    rs = replay_init(spec)

    rng = np.random.default_rng(0)
    t0 = time.time()
    for _ in range(spec.num_blocks):
        rs = replay_add(spec, rs, make_synthetic_block(spec, rng))
    jax.block_until_ready(rs.tree)
    print(f"filled {spec.num_blocks} blocks in {time.time()-t0:.1f}s",
          file=sys.stderr)

    use_double = cfg.network.use_double
    flops_per_step = model_flops_per_step(cfg, action_dim, use_double)
    peak = peak_flops(devs[0].device_kind) if on_tpu else 0.0

    # static context for assemble_output — computed up front so every
    # checkpointed partial snapshot is self-contained
    from r2d2_tpu.ops.pallas_kernels import resolve_pallas_setting
    bf16_resolved = resolve_pallas_setting(cfg.network.bf16, "network.bf16")
    s2d_default = resolve_pallas_setting(cfg.network.space_to_depth,
                                         "network.space_to_depth")
    ctx = {
        "default_label": (f"{'bf16' if bf16_resolved else 'f32'}"
                          f"_spd{cfg.runtime.resolved_steps_per_dispatch()}"
                          f"{'_s2d' if s2d_default else ''}"),
        "batch_size": spec.batch_size,
        "flops_per_step": flops_per_step,
        "peak": peak,
        "platform": devs[0].platform,
        "device_kind": devs[0].device_kind,
        # what the default cell ACTUALLY measures on this backend: the
        # label string doesn't encode every knob (a flipped pallas_lstm
        # default still reads "bf16_spd16"), so the artifact spells the
        # resolved configuration out
        "defaults": {
            "bf16": bf16_resolved,
            "steps_per_dispatch": cfg.runtime.resolved_steps_per_dispatch(),
            "space_to_depth": s2d_default,
            "pallas_obs_decode": resolve_pallas_obs_decode(
                cfg.optim.pallas_obs_decode),
            "pallas_gather": spec.pallas_gather,
            "exact_gather": spec.exact_gather,
            "pallas_lstm": resolve_pallas_setting(
                cfg.network.pallas_lstm, "network.pallas_lstm"),
            "pallas_lstm_block": cfg.network.pallas_lstm_block,
        },
    }

    def build_step(use_pallas: bool, bf16: bool, spd: int, step_spec=None,
                   s2d: bool = False):
        opt = dataclasses.replace(
            cfg.optim, pallas_obs_decode="on" if use_pallas else "off")
        # s2d=True forces the rewrite on; otherwise the SHIPPED default
        # applies, so the matrix keeps describing the defaults if the
        # space_to_depth default ever flips
        netcfg = dataclasses.replace(
            cfg.network, bf16=bf16,
            space_to_depth="on" if s2d else cfg.network.space_to_depth)
        from r2d2_tpu.models import NetworkApply
        net_b = NetworkApply(action_dim, netcfg, cfg.env.frame_stack,
                             cfg.env.frame_height, cfg.env.frame_width)
        step_spec = step_spec or spec
        if spd == 1:
            return make_learner_step(net_b, step_spec, opt, use_double)
        return make_multi_learner_step(net_b, step_spec, opt, use_double, spd)

    results = {}
    matrix = {}
    # cell_status: a parallel per-cell map so a partial/stale artifact is
    # self-describing without PERF.md context — "not-run" (wedge before the
    # cell), "ok", "ok-reused", "carried" (resume pass kept a prior
    # measurement), "anomaly" (value kept but implausible — transient
    # tunnel stall or early block_until_ready), "mosaic-reject",
    # "failed:<Type>", or "skipped:<reason>". Bare null cells were
    # indistinguishable across those cases (VERDICT r4).
    cell_status = {}
    # R2D2_BENCH_SKIP: comma-separated substrings of optional-cell labels to
    # skip — the rerun lever when one cell's compile wedges the tunnel
    # (observed round 4: double_fused hung remote compile for >15 min)
    skip = [s for s in os.environ.get("R2D2_BENCH_SKIP", "").split(",") if s]

    def skipped(label):
        if cell_status.get(label) == "carried":
            return True
        if any(s in label for s in skip):
            print(f"[{label}] skipped via R2D2_BENCH_SKIP", file=sys.stderr)
            cell_status[label] = "skipped:R2D2_BENCH_SKIP"
            return True
        return False

    def record(label, seq_per_sec):
        """Record a measured cell, classifying implausible values so they
        never read as clean measurements (round-4 f32_spd4=245 lesson)."""
        matrix[label] = seq_per_sec
        st = "ok"
        base = matrix.get("f32_spd1")
        if base and seq_per_sec < 0.3 * base:
            st = "anomaly"
            print(f"[{label}] ANOMALY: {seq_per_sec:.1f} seq/s < 0.3x the "
                  f"f32_spd1 base ({base:.1f}) — transient tunnel stall "
                  "suspected; disregard this cell", file=sys.stderr)
        if peak:
            mfu = seq_per_sec / spec.batch_size * flops_per_step / peak
            if mfu > 0.9:
                st = "anomaly"
                print(f"[{label}] ANOMALY: implied MFU {mfu:.2f} — early "
                      "block_until_ready suspected (round-3 hazard); "
                      "disregard this cell", file=sys.stderr)
        cell_status[label] = st

    def record_fail(label, e):
        matrix[label] = None
        msg = str(e)
        cell_status[label] = ("mosaic-reject"
                              if "osaic" in msg or "osaic" in type(e).__name__
                              else f"failed:{type(e).__name__}")
        print(f"[{label}] FAILED: {type(e).__name__}: {e}", file=sys.stderr)

    def mark_skip(label, reason):
        # don't clobber a more specific status (R2D2_BENCH_SKIP, carried)
        if cell_status.get(label, "not-run") == "not-run":
            cell_status[label] = "skipped:" + reason

    def gate_reason():
        if smoke:
            return "smoke"
        if not on_tpu:
            return "needs-tpu"
        return "gated"

    # pre-seed every planned cell as None so a mid-run wedge reports the
    # never-reached cells in partial_missing instead of omitting them
    # (a partial artifact must not read as a complete matrix)
    # the gather A/B cell measures whichever side is NOT the default spec
    spec_pad = dataclasses.replace(spec, exact_gather=not spec.exact_gather)
    ab_label = ("bf16_spd16_exactgather" if spec_pad.exact_gather
                else "bf16_spd16_rowgather")
    # R2D2_BENCH_PLSTM_BT: comma-separated block_t values to sweep in the
    # fused-LSTM section (timesteps per kernel grid iteration; must divide
    # seq_window=55). Parsed here so every swept cell is pre-seeded below —
    # a wedge before the sweep must report them as not-run, not omit them.
    plstm_bts = [int(v) for v in os.environ.get(
        "R2D2_BENCH_PLSTM_BT", "1,5").split(",") if v]
    plstm_labels = ["bf16_spd16_plstm" if bt == 1
                    else f"bf16_spd16_plstm_bt{bt}" for bt in plstm_bts]
    if smoke:
        planned = ["f32_spd1"]
    else:
        planned = (["f32_spd1", "f32_spd4", "f32_spd16",
                    "bf16_spd1", "bf16_spd4", "bf16_spd16",
                    "bf16_spd16_s2d", ab_label, "bf16_spd16_nhwc"]
                   + plstm_labels
                   + ["bf16_spd16_double", "bf16_spd16_double_fused"])
    for label in planned:
        matrix[label] = None
        cell_status[label] = "not-run"

    # R2D2_BENCH_RESUME: the supervisor's only-missing-cells retry — after
    # a mid-run wedge whose backend probe then SUCCEEDS, the rerun child
    # seeds every already-measured cell from the partial snapshot
    # ("carried") and spends the fresh window on the missing cells only.
    if os.environ.get("R2D2_BENCH_RESUME"):
        try:
            with open(_partial_path()) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = {}
        prev_status = prev.get("cell_status") or {}
        for k, v in (prev.get("matrix") or {}).items():
            if (v is not None and k in matrix
                    and prev_status.get(k, "ok") in ("ok", "ok-reused",
                                                     "carried")):
                matrix[k] = v
                cell_status[k] = "carried"
                print(f"[{k}] carried from this run's partial snapshot "
                      "(resume pass)", file=sys.stderr)
        for k, v in (prev.get("results") or {}).items():
            if v is not None and k not in results:
                results[k] = v

    def checkpoint():
        # after every cell: snapshot what's measured so far so a later
        # wedge costs only the remaining cells (emit_partial_or_stale)
        try:
            tmp = _partial_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"results": results, "matrix": matrix, "ctx": ctx,
                           "cell_status": cell_status},
                          f)
            os.replace(tmp, _partial_path())
        except OSError as e:
            print(f"bench: partial checkpoint failed: {e}", file=sys.stderr)

    # --- 1. decode A/B at the base config (f32, spd=1) ------------------
    first = True
    for label, use_pallas in (("xla_decode", False), ("pallas_decode", True)):
        if results.get(label) is not None:   # resume pass carried it
            print(f"[{label}] carried from this run's partial snapshot",
                  file=sys.stderr)
            first = False
            continue
        if use_pallas and (not on_tpu or smoke):
            results[label] = None
            reason = ("smoke mode measures the xla path only" if smoke else
                      f"pallas needs a TPU backend (have {devs[0].platform})")
            print(f"[{label}] skipped: {reason}", file=sys.stderr)
            continue
        step = build_step(use_pallas, bf16=False, spd=1)
        try:
            sps, ts, rs = measure_path(step, ts, rs, label,
                                       diagnose_backend=first)
            results[label] = sps * spec.batch_size
        except FirstDispatchError as e:
            print(
                "bench: first compile+dispatch FAILED on a known-good "
                "program — the backend, not the program, is the suspect.\n"
                f"  error: {e}\n"
                f"  JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '<unset>')!r}\n"
                + BACKEND_GUIDANCE,
                file=sys.stderr)
            sys.exit(BACKEND_FAILURE_RC)
        except Exception as e:  # pallas lowering failure must not kill the bench
            if not use_pallas:
                raise
            results[label] = None
            print(f"[{label}] FAILED: {type(e).__name__}: {e}", file=sys.stderr)
        first = False

    # default decode path for the matrix (auto: pallas on TPU)
    default_pallas = (resolve_pallas_obs_decode(cfg.optim.pallas_obs_decode)
                      and results.get("pallas_decode") is not None)

    # --- 1b. sample-gather A/B (gather_rows_pallas vs the XLA gather) ----
    # Part 1 ran with spec.pallas_gather auto-resolved (pallas on TPU); one
    # extra measurement with the gather forced off isolates its effect on
    # the full fused step.
    if results.get("xla_gather") is not None:   # resume pass carried it
        print("[xla_gather] carried from this run's partial snapshot",
              file=sys.stderr)
    elif on_tpu and not smoke and spec.pallas_gather:
        spec_xla_gather = dataclasses.replace(spec, pallas_gather=False)
        step = build_step(default_pallas, bf16=False, spd=1,
                          step_spec=spec_xla_gather)
        sps, ts, rs = measure_path(step, ts, rs, "xla_gather")
        results["xla_gather"] = sps * spec.batch_size
        results["pallas_gather"] = (results["pallas_decode"] if default_pallas
                                    else results["xla_decode"])
    else:
        results["xla_gather"] = results["pallas_gather"] = None

    # --- 2. perf matrix {f32, bf16} x {steps_per_dispatch 1, 4, 16} -----
    checkpoint()
    combos = [(False, 1)] if smoke else [
        (False, 1), (False, 4), (False, 16),
        (True, 1), (True, 4), (True, 16)]
    for bf16, spd in combos:
        label = f"{'bf16' if bf16 else 'f32'}_spd{spd}"
        if cell_status.get(label) == "carried":
            print(f"[{label}] carried from this run's partial snapshot",
                  file=sys.stderr)
            continue
        if bf16 and not on_tpu:
            matrix[label] = None
            mark_skip(label, "needs-tpu")
            print(f"[{label}] skipped: bf16 matrix is a TPU measurement",
                  file=sys.stderr)
            continue
        if not bf16 and spd == 1:
            # identical configuration to the part-1 A/B winner — reuse the
            # measurement instead of paying another compile + timing window
            reused = (results["pallas_decode"] if default_pallas
                      else results["xla_decode"])
            matrix[label] = reused
            cell_status[label] = "ok-reused"
            checkpoint()
            print(f"[{label}] = {reused:.1f} seq/s (reused from part-1 A/B)",
                  file=sys.stderr)
            continue
        step = build_step(default_pallas, bf16, spd)
        sps, ts, rs = measure_path(step, ts, rs, label, steps_per_dispatch=spd)
        record(label, sps * spec.batch_size)
        checkpoint()
        if peak:
            mfu = sps * flops_per_step / peak
            print(f"[{label}] ~{sps * flops_per_step / 1e12:.1f} TFLOP/s "
                  f"model flops = {100*mfu:.1f}% of {peak/1e12:.0f} TFLOP/s "
                  "bf16 peak", file=sys.stderr)

    if os.environ.get("R2D2_BENCH_SIMULATE_HANG"):
        # test hook (test_bench_diag): wedge AFTER the base matrix so the
        # supervisor's partial fallback has cells to assemble
        print("bench: simulated mid-run hang", file=sys.stderr, flush=True)
        time.sleep(100_000)

    # --- 2b. fused-pallas-LSTM A/B at the bf16_spd16 policy -------------
    # network.pallas_lstm runs the 55-step recurrent chain as ONE pallas
    # kernel (Wh VMEM-resident, f32 scratch carries, custom-VJP backward —
    # ops/pallas_lstm.py) instead of a lax.scan while-loop, attacking the
    # profiled per-iteration overhead on the serial chain. Win -> flip the
    # default; Mosaic rejection -> documented dead end.
    # (plstm_bts / plstm_labels parsed up top so the sweep is pre-seeded)
    for bt, label in zip(plstm_bts, plstm_labels):
        if (on_tpu and not smoke and default_pallas
                and not skipped(label)):
            try:
                opt_default = dataclasses.replace(
                    cfg.optim, pallas_obs_decode="on")
                from r2d2_tpu.models import NetworkApply
                net_pl = NetworkApply(
                    action_dim, dataclasses.replace(
                        cfg.network, bf16=True, pallas_lstm="on",
                        pallas_lstm_block=bt),
                    cfg.env.frame_stack, cfg.env.frame_height,
                    cfg.env.frame_width)
                ts_pl = create_train_state(jax.random.PRNGKey(1), net_pl,
                                           cfg.optim)
                step = make_multi_learner_step(net_pl, spec, opt_default,
                                               use_double, 16)
                sps, _tspl, rs = measure_path(step, ts_pl, rs, label,
                                              steps_per_dispatch=16)
                record(label, sps * spec.batch_size)
            except Exception as e:   # never kill the bench for extra cells
                record_fail(label, e)
        elif cell_status.get(label) != "carried":
            matrix[label] = None
            mark_skip(label, gate_reason())
        checkpoint()

    # --- 2b2. exact-read pad-gather A/B at the bf16_spd16 policy ---------
    # replay.pallas_exact_gather pads stored frames (84x84 -> 96x128) and
    # DMAs only each sampled window (async copy) instead of the whole ring
    # row (~7.7x read amplification). It measured +4.2% and is now the TPU
    # default ("auto", BENCH r4) — so this cell measures the OTHER side
    # (exact_gather forced to the opposite of the default spec), keeping
    # the A/B in every artifact in case a chip generation shifts it.
    # Storage layout changes with the flag, so this cell builds its own
    # replay.
    if on_tpu and not smoke and not skipped(ab_label):
        try:
            rs_pad = replay_init(spec_pad)
            rng_pad = np.random.default_rng(0)
            for _ in range(spec_pad.num_blocks):
                rs_pad = replay_add(spec_pad, rs_pad,
                                    make_synthetic_block(spec_pad, rng_pad))
            jax.block_until_ready(rs_pad.tree)
            step = build_step(default_pallas, bf16=True, spd=16,
                              step_spec=spec_pad)
            ts_pg = create_train_state(jax.random.PRNGKey(1), net, cfg.optim)
            sps, _tspg, rs_pad = measure_path(step, ts_pg, rs_pad, ab_label,
                                              steps_per_dispatch=16)
            record(ab_label, sps * spec.batch_size)
            del rs_pad
        except Exception as e:   # never kill the bench for the extra cell
            record_fail(ab_label, e)
    elif cell_status.get(ab_label) != "carried":
        matrix[ab_label] = None
        mark_skip(ab_label, gate_reason())
    checkpoint()

    # --- 2b3. space_to_depth A/B at the bf16_spd16 policy (the current
    # shipped TPU default; compare against that cell specifically) --------
    # The exact first-conv rewrite (network.space_to_depth) targets the
    # MXU's input-lane underutilization on the 4-channel frame stack. The
    # knob changes the param layout so its default stays explicit
    # ('off'/'on'); this cell measures what flipping it would buy so the
    # default can follow measurement (params differ, so this uses a fresh
    # train state — the throughput comparison is unaffected).
    if on_tpu and not smoke and not skipped("bf16_spd16_s2d"):
        try:
            from r2d2_tpu.models import NetworkApply
            opt_default = dataclasses.replace(
                cfg.optim,
                pallas_obs_decode="on" if default_pallas else "off")
            s2d_cfg = dataclasses.replace(cfg.network, bf16=True,
                                          space_to_depth="on")
            s2d_net = NetworkApply(action_dim, s2d_cfg, cfg.env.frame_stack,
                                   cfg.env.frame_height, cfg.env.frame_width)
            # ONE net builds both the train state and the step, so their
            # param trees cannot drift
            ts_s2d = create_train_state(jax.random.PRNGKey(1), s2d_net,
                                        cfg.optim)
            step = make_multi_learner_step(s2d_net, spec, opt_default,
                                           use_double, 16)
            sps, _ts2, rs = measure_path(step, ts_s2d, rs, "bf16_spd16_s2d",
                                         steps_per_dispatch=16)
            record("bf16_spd16_s2d", sps * spec.batch_size)
        except Exception as e:   # never kill the bench for the extra cell
            record_fail("bf16_spd16_s2d", e)
    elif cell_status.get("bf16_spd16_s2d") != "carried":
        matrix["bf16_spd16_s2d"] = None
        mark_skip("bf16_spd16_s2d", gate_reason())
    checkpoint()

    # --- 2b4. NHWC-decode A/B at the bf16_spd16 policy -------------------
    # optim.pallas_decode_layout="nhwc" folds the post-decode layout
    # transpose (the ~1.6 ms/step HBM copy in the round-3 profile) into
    # the kernel's in-register relayout. Win -> flip the default; Mosaic
    # rejection -> documented dead end.
    # default-SKIPPED: four distinct Mosaic rejections settled this as a
    # dead end on the current stack (PERF.md), and its compile-helper
    # crash ("HTTP 500: tpu_compile_helper subprocess exit code 1") is
    # the suspected poisoner of the round-4 tunnel wedge. Re-enable with
    # R2D2_BENCH_NHWC=1 when the Mosaic version changes.
    if (on_tpu and not smoke and default_pallas
            and os.environ.get("R2D2_BENCH_NHWC")
            and not skipped("bf16_spd16_nhwc")):
        try:
            opt_nhwc = dataclasses.replace(
                cfg.optim, pallas_obs_decode="on",
                pallas_decode_layout="nhwc")
            from r2d2_tpu.models import NetworkApply
            net_n = NetworkApply(
                action_dim, dataclasses.replace(cfg.network, bf16=True),
                cfg.env.frame_stack, cfg.env.frame_height,
                cfg.env.frame_width)
            ts_n = create_train_state(jax.random.PRNGKey(1), net_n, cfg.optim)
            step = make_multi_learner_step(net_n, spec, opt_nhwc,
                                           use_double, 16)
            sps, _tsn, rs = measure_path(step, ts_n, rs, "bf16_spd16_nhwc",
                                         steps_per_dispatch=16)
            record("bf16_spd16_nhwc", sps * spec.batch_size)
        except Exception as e:   # never kill the bench for the extra cell
            record_fail("bf16_spd16_nhwc", e)
    elif cell_status.get("bf16_spd16_nhwc") != "carried":
        matrix["bf16_spd16_nhwc"] = None
        mark_skip("bf16_spd16_nhwc",
                  gate_reason() if (not on_tpu or smoke)
                  else "dead-end; set R2D2_BENCH_NHWC=1 to re-measure")
    checkpoint()

    # --- 2c. double-DQN unroll-fusion A/B at the bf16_spd16 policy -------
    # use_double=True pays a SECOND 55-step recurrent unroll; sequential
    # (two XLA while-loops) vs interleaved-in-one-scan
    # (optim.fused_double_unroll, models/network.py dual_sequence_q). The
    # default config keeps use_double off (reference parity), so this pair
    # measures the double-DQN configuration's wall and what the fusion buys
    # — flip the fused_double_unroll default when the _fused cell wins.
    if on_tpu and not smoke:
        from r2d2_tpu.models import NetworkApply
        for label, fused in (("bf16_spd16_double", "off"),
                             ("bf16_spd16_double_fused", "on")):
            if skipped(label):
                if cell_status.get(label) != "carried":
                    matrix[label] = None
                continue
            try:
                opt_d = dataclasses.replace(
                    cfg.optim,
                    pallas_obs_decode="on" if default_pallas else "off",
                    fused_double_unroll=fused)
                net_d = NetworkApply(
                    action_dim,
                    dataclasses.replace(cfg.network, bf16=True,
                                        use_double=True),
                    cfg.env.frame_stack, cfg.env.frame_height,
                    cfg.env.frame_width)
                ts_d = create_train_state(jax.random.PRNGKey(1), net_d,
                                          cfg.optim)
                step = make_multi_learner_step(net_d, spec, opt_d,
                                               use_double=True,
                                               steps_per_dispatch=16)
                sps, _tsd, rs = measure_path(step, ts_d, rs, label,
                                             steps_per_dispatch=16)
                record(label, sps * spec.batch_size)
            except Exception as e:   # never kill the bench for extra cells
                record_fail(label, e)
    else:
        for label in ("bf16_spd16_double", "bf16_spd16_double_fused"):
            if cell_status.get(label) != "carried":
                matrix[label] = None
                mark_skip(label, gate_reason())

    # --- report ----------------------------------------------------------
    # primary metric: what the SHIPPED defaults actually run — default
    # decode path, NetworkConfig.bf16, RuntimeConfig.steps_per_dispatch —
    # when that cell was measured; otherwise (smoke mode trims the matrix)
    # the best measured cell, reported under its own label so value and
    # measured_config always describe the same configuration. The full
    # matrix is attached so the defaults can be re-validated against the
    # measurements each round. matrix['f32_spd1'] is always populated (a
    # failed base measurement exits in part 1), so assemble_output never
    # returns None here. Assembly is shared with the supervisor's
    # partial-results fallback (assemble_output).
    print(json.dumps(assemble_output(results, matrix, ctx, cell_status)))


# The probe must route any JAX_PLATFORMS request through jax.config BEFORE
# discovery (same reason as run_bench's pin_platform call): the env var
# filters after plugin init, so a cpu-pinned probe would still hang on a
# wedged remote-TPU plugin.
_PROBE_SCRIPT = (
    "import sys; from r2d2_tpu.utils import pin_platform; pin_platform(); "
    "import jax; d = jax.devices(); "
    "print('probe-ok', d[0].platform, len(d), d[0].device_kind); "
    "sys.stdout.flush()")


def _terminate(proc) -> None:
    """SIGTERM, grace, then SIGKILL — a hard-killed TPU-holding process is
    itself a known tunnel-wedger (round 3), so give it a chance to unwind."""
    import subprocess
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def probe_backend(timeout: float, active=None) -> bool:
    """Run backend discovery in a subprocess so a wedged tunnel's HANG is
    bounded by `timeout` instead of stalling the bench forever. `active`
    (a dict) exposes the in-flight proc to the supervisor's signal handler."""
    import subprocess
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_SCRIPT],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    if active is not None:
        active["proc"] = proc
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        _terminate(proc)
        print(f"bench: backend probe hung past {timeout:.0f}s (wedged "
              "tunnel?)", file=sys.stderr, flush=True)
        return False
    ok = proc.returncode == 0 and "probe-ok" in out
    if not ok:
        tail = out.strip().splitlines()[-3:] if out.strip() else []
        print(f"bench: backend probe failed rc={proc.returncode}: "
              + " | ".join(tail), file=sys.stderr, flush=True)
    else:
        print(f"bench: backend probe ok: {out.strip().splitlines()[-1]}",
              file=sys.stderr, flush=True)
    return ok


def _cache_path() -> str:
    return os.environ.get(
        "R2D2_BENCH_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_CACHE.json"))


def _partial_path() -> str:
    import tempfile
    return os.environ.get(
        "R2D2_BENCH_PARTIAL",
        os.path.join(tempfile.gettempdir(), "r2d2_bench_partial.json"))


def _write_cache(result: dict) -> None:
    tmp = _cache_path() + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime()),
                   "output": result}, f, indent=1)
    os.replace(tmp, _cache_path())
    print(f"bench: cached last-good measurement to {_cache_path()}",
          file=sys.stderr)


def assemble_output(results: dict, matrix: dict, ctx: dict,
                    cell_status: dict = None):
    """Build the final JSON dict from measured cells + static context.
    Shared by the measurement child (full run) and the supervisor's
    partial-results fallback (emit_partial_or_stale), so a wedge in a LATE
    cell cannot discard the cells already measured this run. Returns None
    when no comparable cell exists yet.

    ``cell_status`` makes the matrix self-describing (per cell: "ok",
    "ok-reused", "carried", "anomaly", "mosaic-reject", "failed:<Type>",
    "skipped:<reason>", "not-run"); absent (pre-round-5 snapshots) it is
    synthesized from the values alone ("ok" / "unknown")."""
    if cell_status is None:
        cell_status = {k: ("ok" if v is not None else "unknown")
                       for k, v in matrix.items()}
    # anomalous values never elect the headline or best cell
    candidates = {k: v for k, v in matrix.items()
                  if v is not None and "_double" not in k
                  and cell_status.get(k) != "anomaly"}
    if not candidates:
        return None
    # _double cells are a different workload (a second unroll's FLOPs) —
    # comparable to each other, not to the default config's cells
    best_label = max(candidates, key=candidates.get)
    default_label = ctx["default_label"]
    # the default cell elects the headline only when its measurement is
    # clean — an anomaly-flagged default (round-4 f32_spd4 class) must not
    # become the artifact's value/vs_baseline/MFU
    measured_label = (default_label if default_label in candidates
                      else best_label)
    seq_updates = matrix[measured_label]

    def _r(key):
        v = results.get(key)
        return v and round(v, 1)

    out = {
        "metric": "learner_sequence_updates_per_sec_per_chip",
        "value": round(seq_updates, 1),
        "unit": "sequences/s",
        "vs_baseline": round(seq_updates / REFERENCE_SEQ_UPDATES_PER_SEC, 2),
        "measured_config": measured_label,
        "default_config": default_label,
        "best_config": best_label,
        "xla_decode": _r("xla_decode"),
        "pallas_decode": _r("pallas_decode"),
        "xla_gather": _r("xla_gather"),
        "pallas_gather": _r("pallas_gather"),
        "matrix": {k: v and round(v, 1) for k, v in matrix.items()},
        "cell_status": {k: cell_status.get(k, "unknown") for k in matrix},
        "platform": ctx["platform"],
        "device_kind": ctx["device_kind"],
    }
    if ctx.get("defaults"):
        out["resolved_defaults"] = ctx["defaults"]
    if ctx.get("peak"):
        steps_per_sec = seq_updates / ctx["batch_size"]
        out["model_tflops_per_sec"] = round(
            steps_per_sec * ctx["flops_per_step"] / 1e12, 1)
        out["mfu_vs_bf16_peak"] = round(
            steps_per_sec * ctx["flops_per_step"] / ctx["peak"], 4)
    return out


def emit_partial_or_stale(reason: str) -> None:
    """A mid-run wedge loses the rest of the matrix, not the cells already
    measured: prefer THIS RUN's checkpointed partial results over the
    previous run's cache; fall back to the stale cache (or rc=1) only when
    nothing measurable was checkpointed."""
    try:
        with open(_partial_path()) as f:
            snap = json.load(f)
        out = assemble_output(snap["results"], snap["matrix"], snap["ctx"],
                              snap.get("cell_status"))
    except (OSError, ValueError, KeyError):
        out = None
    if out is None:
        emit_stale_or_die(reason)
        return
    out["partial"] = True
    out["partial_reason"] = reason
    # "missing" = cells a rerun could still measure — deliberately skipped
    # cells (env-gated nhwc, R2D2_BENCH_SKIP) are not losses of this wedge
    snap_status = snap.get("cell_status") or {}
    out["partial_missing"] = sorted(
        k for k, v in snap["matrix"].items()
        if v is None and not snap_status.get(k, "").startswith("skipped:"))
    print("bench: emitting PARTIAL fresh measurement "
          f"(missing cells: {out['partial_missing']}) because: {reason}",
          file=sys.stderr)
    # fresh headline-grade numbers beat an older full run as the next
    # fallback; a partial missing the default cell does not
    cacheable = (out["platform"] == "tpu"
                 and out["measured_config"] == out["default_config"]
                 and not os.environ.get("R2D2_BENCH_SMOKE"))
    if cacheable or os.environ.get("R2D2_BENCH_FORCE_CACHE"):
        _write_cache(out)
    print(json.dumps(out))
    sys.exit(0)


def emit_stale_or_die(reason: str) -> None:
    """Persistent backend failure: emit the last-good cached measurement
    flagged stale (rc=0) so the round keeps a number, else rc=1."""
    try:
        with open(_cache_path()) as f:
            cache = json.load(f)
        out = cache["output"]
    except (OSError, KeyError, json.JSONDecodeError):
        print("bench: no last-good cache at "
              f"{_cache_path()!r} to fall back on.\n" + BACKEND_GUIDANCE,
              file=sys.stderr)
        sys.exit(1)
    out["stale"] = True
    out["stale_reason"] = reason
    out["stale_recorded_at"] = cache.get("recorded_at")
    if "cell_status" not in out and isinstance(out.get("matrix"), dict):
        # pre-round-5 cache: synthesize so the artifact stays self-describing
        out["cell_status"] = {k: ("ok" if v is not None else "unknown")
                              for k, v in out["matrix"].items()}
    print("bench: emitting LAST-GOOD measurement (stale=true, recorded "
          f"{cache.get('recorded_at')}) because: {reason}", file=sys.stderr)
    print(json.dumps(out))
    sys.exit(0)


def supervise() -> None:
    """Probe-with-retry, then run the measurement as a deadlined child;
    fall back to the stale cache on persistent backend failure. Only
    DIAGNOSED backend failures (BACKEND_FAILURE_RC, signal deaths,
    timeouts) are masked by the cache — a genuine crash stays nonzero."""
    import signal
    import subprocess
    attempts = int(os.environ.get("R2D2_BENCH_ATTEMPTS", "3"))
    probe_timeout = float(os.environ.get("R2D2_BENCH_PROBE_TIMEOUT", "120"))
    backoff = float(os.environ.get("R2D2_BENCH_BACKOFF", "45"))
    child_timeout = float(os.environ.get("R2D2_BENCH_CHILD_TIMEOUT", "2700"))

    # A driver-side timeout SIGTERMs the SUPERVISOR; without a handler the
    # in-flight probe or measurement child would be orphaned still holding
    # the TPU — the exact hard-kill tunnel-wedge this file exists to
    # prevent. Unwind whichever child is live and still leave a (stale)
    # number on stdout. Installed BEFORE the probe loop: on a wedged
    # tunnel the probe/backoff phase alone can outlast a driver timeout.
    active = {"proc": None}

    def _on_term(signum, frame):
        if active["proc"] is not None:
            _terminate(active["proc"])
        emit_partial_or_stale(f"supervisor received signal {signum} "
                              "(driver timeout?) — children unwound")
    prev_term = signal.signal(signal.SIGTERM, _on_term)

    def _echo(out: str) -> None:
        for ln in out.strip().splitlines():
            if ln.strip():
                print(ln, file=sys.stderr)

    try:
        for attempt in range(1, attempts + 1):
            if probe_backend(probe_timeout, active):
                break
            if attempt < attempts:
                print(f"bench: probe attempt {attempt}/{attempts} failed; "
                      f"retrying in {backoff:.0f}s", file=sys.stderr,
                      flush=True)
                time.sleep(backoff)
        else:
            emit_stale_or_die(
                f"backend discovery failed {attempts}x (timeout "
                f"{probe_timeout:.0f}s each) — remote-TPU tunnel wedged")
        active["proc"] = None

        try:                      # drop any previous run's partial snapshot
            os.unlink(_partial_path())
        except OSError:
            pass
        env = dict(os.environ, R2D2_BENCH_CHILD="1")
        proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                                env=env, stdout=subprocess.PIPE, text=True)
        active["proc"] = proc
        resumed = False
        while True:
            try:
                out, _ = proc.communicate(timeout=child_timeout)
                break
            except subprocess.TimeoutExpired:
                _terminate(proc)
                if (resumed or os.environ.get("R2D2_BENCH_NO_RESUME")
                        or not probe_backend(probe_timeout, active)):
                    emit_partial_or_stale(
                        f"measurement exceeded the {child_timeout:.0f}s "
                        "deadline (backend likely wedged mid-run)")
                # deadline hit but the backend still answers (a single cell
                # stalled, not a dead tunnel): spend ONE more window on the
                # missing cells only — the rerun child seeds measured cells
                # from the partial snapshot (R2D2_BENCH_RESUME)
                active["proc"] = None
                print("bench: child deadline hit but the backend probe "
                      "still answers — re-running missing cells only",
                      file=sys.stderr, flush=True)
                resumed = True
                proc = subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__)],
                    env=dict(env, R2D2_BENCH_RESUME="1"),
                    stdout=subprocess.PIPE, text=True)
                active["proc"] = proc
        active["proc"] = None
    finally:
        signal.signal(signal.SIGTERM, prev_term)

    if proc.returncode != 0:
        _echo(out)
        if proc.returncode == BACKEND_FAILURE_RC or proc.returncode < 0:
            emit_partial_or_stale(
                f"measurement child exited rc={proc.returncode} "
                "(diagnosed backend failure — diagnostics above)")
        print(f"bench: measurement child CRASHED rc={proc.returncode} — a "
              "code failure, NOT masking it with the stale cache",
              file=sys.stderr)
        sys.exit(proc.returncode)

    lines = [ln for ln in out.strip().splitlines() if ln.strip()]
    try:
        result = json.loads(lines[-1])
    except (IndexError, json.JSONDecodeError):
        _echo(out)
        emit_stale_or_die("measurement child emitted no JSON line")
    for ln in lines[:-1]:             # anything else must not pollute stdout
        print(ln, file=sys.stderr)

    cacheable = (result.get("platform") == "tpu"
                 and not os.environ.get("R2D2_BENCH_SMOKE")) or \
        bool(os.environ.get("R2D2_BENCH_FORCE_CACHE"))
    if cacheable:
        _write_cache(result)
    try:                          # completed run: the snapshot is obsolete
        os.unlink(_partial_path())
    except OSError:
        pass
    print(json.dumps(result))


if __name__ == "__main__":
    if os.environ.get("R2D2_BENCH_CHILD"):
        # The default SIGTERM disposition dies with no cleanup — from the
        # TPU runtime's view the same abrupt kill as SIGKILL (the known
        # tunnel-wedger). Raise SystemExit instead so atexit/JAX client
        # teardown runs when the supervisor unwinds us.
        import signal
        signal.signal(signal.SIGTERM, lambda s, f: sys.exit(143))
        if os.environ.get("R2D2_BENCH_SIMULATE_CRASH"):
            raise ValueError("simulated measurement-code crash")
        run_bench()
    else:
        supervise()
