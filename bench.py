"""Benchmark: learner sequence-updates/sec/chip (BASELINE.md north star).

Measures the fused R2D2 learner step — prioritized sample from HBM replay +
full 55-step conv/LSTM unroll + value-rescaled double/dueling loss + Adam +
priority write-back, one XLA program — at the reference's training
configuration (batch 128 sequences, burn-in 40 / learning 10 / n-step 5,
84x84x4 frames, cnn_out 1024, LSTM 512, dueling on, double off, f32;
/root/reference/config.py).

vs_baseline: the reference publishes NO numbers (BASELINE.json "published":
{}). Its learner logs 'training speed' in updates/s (worker.py:229); upstream
runs of this codebase on a desktop GPU train at ~5 updates/s = 640
sequence-updates/s (128-sequence batches). That figure is the documented
baseline estimate used here until a measured reference log is available.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

REFERENCE_SEQ_UPDATES_PER_SEC = 640.0  # ~5 train steps/s * batch 128 (see above)


def make_synthetic_block(spec, rng):
    from r2d2_tpu.replay.structs import Block
    S, L = spec.seqs_per_block, spec.learning
    burn = np.minimum(np.arange(S) * L, spec.burn_in).astype(np.int32)
    return Block(
        obs_row=rng.integers(0, 255, (spec.obs_row_len, spec.frame_height,
                                      spec.frame_width)).astype(np.uint8),
        last_action_row=rng.integers(0, 18, (spec.la_row_len,)).astype(np.int32),
        hidden=rng.normal(size=(S, 2, spec.hidden_dim)).astype(np.float32),
        action=rng.integers(0, 18, (S, L)).astype(np.int32),
        reward=rng.normal(size=(S, L)).astype(np.float32),
        gamma=np.full((S, L), 0.997**spec.forward, np.float32),
        priority=rng.uniform(0.1, 2.0, (S,)).astype(np.float32),
        burn_in_steps=burn,
        learning_steps=np.full((S,), L, np.int32),
        forward_steps=np.concatenate(
            [np.full((S - 1,), spec.forward), [1]]).astype(np.int32),
        seq_start=(burn[0] + L * np.arange(S)).astype(np.int32),
        num_sequences=np.asarray(S, np.int32),
        sum_reward=np.asarray(np.nan, np.float32),
    )


def main() -> None:
    import jax

    from r2d2_tpu.config import Config
    from r2d2_tpu.learner import create_train_state, make_learner_step
    from r2d2_tpu.models import init_network
    from r2d2_tpu.replay import ReplaySpec, replay_add, replay_init

    # reference-default training config; replay capacity trimmed to bound
    # bench setup time (25.6k steps of ring is plenty to sample 128 from)
    cfg = Config().replace(**{"replay.capacity": 25_600})
    spec = ReplaySpec.from_config(cfg)
    action_dim = 18  # full Atari action set

    net, _ = init_network(jax.random.PRNGKey(0), action_dim, cfg.network)
    ts = create_train_state(jax.random.PRNGKey(1), net, cfg.optim)
    rs = replay_init(spec)

    rng = np.random.default_rng(0)
    t0 = time.time()
    for _ in range(spec.num_blocks):
        rs = replay_add(spec, rs, make_synthetic_block(spec, rng))
    jax.block_until_ready(rs.tree)
    print(f"filled {spec.num_blocks} blocks in {time.time()-t0:.1f}s",
          file=sys.stderr)

    step = make_learner_step(net, spec, cfg.optim, cfg.network.use_double)

    t0 = time.time()
    ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])
    print(f"compile + first step: {time.time()-t0:.1f}s "
          f"loss={float(m['loss']):.5f}", file=sys.stderr)

    for _ in range(3):  # warmup
        ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])

    n_timed = 30
    t0 = time.time()
    for _ in range(n_timed):
        ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])
    dt = time.time() - t0

    steps_per_sec = n_timed / dt
    seq_updates = steps_per_sec * spec.batch_size
    print(f"{steps_per_sec:.2f} train steps/s; loss={float(m['loss']):.5f}",
          file=sys.stderr)
    print(json.dumps({
        "metric": "learner_sequence_updates_per_sec_per_chip",
        "value": round(seq_updates, 1),
        "unit": "sequences/s",
        "vs_baseline": round(seq_updates / REFERENCE_SEQ_UPDATES_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
