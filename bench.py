"""Benchmark: learner sequence-updates/sec/chip (BASELINE.md north star).

Measures the fused R2D2 learner step — prioritized sample from HBM replay +
full 55-step conv/LSTM unroll + value-rescaled double/dueling loss + Adam +
priority write-back, one XLA program — at the reference's training
configuration (batch 128 sequences, burn-in 40 / learning 10 / n-step 5,
84x84x4 frames, cnn_out 1024, LSTM 512, dueling on, double off, f32;
/root/reference/config.py).

vs_baseline: the reference publishes NO numbers (BASELINE.json "published":
{}). Its learner logs 'training speed' in updates/s (worker.py:229); upstream
runs of this codebase on a desktop GPU train at ~5 updates/s = 640
sequence-updates/s (128-sequence batches). That figure is the documented
baseline estimate used here until a measured reference log is available.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import dataclasses
import json
import os
import sys
import time

import numpy as np

REFERENCE_SEQ_UPDATES_PER_SEC = 640.0  # ~5 train steps/s * batch 128 (see above)


def init_backend_or_die():
    """Initialize the JAX backend up front with actionable diagnostics —
    round 1 died with a bare 'Unable to initialize backend' when the remote
    TPU tunnel was wedged by an earlier hard-killed process."""
    import jax

    try:
        devs = jax.devices()
    except RuntimeError as e:
        print(
            "bench: JAX backend init FAILED.\n"
            f"  error: {e}\n"
            f"  JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '<unset>')!r}\n"
            "  If this is the remote-TPU tunnel: a previously killed "
            "TPU-holding process can wedge the tunnel until the environment "
            "resets; retry later or run with JAX_PLATFORMS=cpu for a "
            "smoke-only number.",
            file=sys.stderr)
        sys.exit(1)
    print(f"backend: {devs[0].platform} x{len(devs)}", file=sys.stderr)
    return devs


def make_synthetic_block(spec, rng):
    from r2d2_tpu.replay.structs import Block
    S, L = spec.seqs_per_block, spec.learning
    burn = np.minimum(np.arange(S) * L, spec.burn_in).astype(np.int32)
    return Block(
        obs_row=rng.integers(0, 255, (spec.obs_row_len, spec.frame_height,
                                      spec.frame_width)).astype(np.uint8),
        last_action_row=rng.integers(0, 18, (spec.la_row_len,)).astype(np.int32),
        hidden=rng.normal(size=(S, 2, spec.hidden_dim)).astype(np.float32),
        action=rng.integers(0, 18, (S, L)).astype(np.int32),
        reward=rng.normal(size=(S, L)).astype(np.float32),
        gamma=np.full((S, L), 0.997**spec.forward, np.float32),
        priority=rng.uniform(0.1, 2.0, (S,)).astype(np.float32),
        burn_in_steps=burn,
        learning_steps=np.full((S,), L, np.int32),
        forward_steps=np.concatenate(
            [np.full((S - 1,), spec.forward), [1]]).astype(np.int32),
        seq_start=(burn[0] + L * np.arange(S)).astype(np.int32),
        num_sequences=np.asarray(S, np.int32),
        sum_reward=np.asarray(np.nan, np.float32),
    )


def measure_path(step, ts, rs, label: str, n_timed: int = 30):
    """Compile, warm up, and time one step function. Returns
    (seq_updates_per_sec, ts, rs) — threading state through so the two
    decode paths reuse the same filled replay ring."""
    import jax

    t0 = time.time()
    ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])
    print(f"[{label}] compile + first step: {time.time()-t0:.1f}s "
          f"loss={float(m['loss']):.5f}", file=sys.stderr)

    for _ in range(3):  # warmup
        ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])

    t0 = time.time()
    for _ in range(n_timed):
        ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])
    dt = time.time() - t0

    steps_per_sec = n_timed / dt
    print(f"[{label}] {steps_per_sec:.2f} train steps/s; "
          f"loss={float(m['loss']):.5f}", file=sys.stderr)
    return steps_per_sec, ts, rs


def main() -> None:
    devs = init_backend_or_die()
    on_tpu = devs[0].platform not in ("cpu",)

    import jax

    from r2d2_tpu.config import Config
    from r2d2_tpu.learner import create_train_state, make_learner_step
    from r2d2_tpu.models import init_network
    from r2d2_tpu.replay import ReplaySpec, replay_add, replay_init

    # reference-default training config; replay capacity trimmed to bound
    # bench setup time (25.6k steps of ring is plenty to sample 128 from)
    cfg = Config().replace(**{"replay.capacity": 25_600})
    spec = ReplaySpec.from_config(cfg)
    action_dim = 18  # full Atari action set

    net, _ = init_network(jax.random.PRNGKey(0), action_dim, cfg.network)
    ts = create_train_state(jax.random.PRNGKey(1), net, cfg.optim)
    rs = replay_init(spec)

    rng = np.random.default_rng(0)
    t0 = time.time()
    for _ in range(spec.num_blocks):
        rs = replay_add(spec, rs, make_synthetic_block(spec, rng))
    jax.block_until_ready(rs.tree)
    print(f"filled {spec.num_blocks} blocks in {time.time()-t0:.1f}s",
          file=sys.stderr)

    # A/B the two obs-decode paths (VERDICT r1 #5): XLA gather vs the fused
    # pallas VMEM kernel (ops/pallas_kernels.py). Pallas compiles on TPU only.
    results = {}
    for label, use_pallas in (("xla_decode", False), ("pallas_decode", True)):
        if use_pallas and not on_tpu:
            results[label] = None
            print(f"[{label}] skipped: pallas needs a TPU backend "
                  f"(have {devs[0].platform})", file=sys.stderr)
            continue
        opt = dataclasses.replace(cfg.optim, pallas_obs_decode=use_pallas)
        step = make_learner_step(net, spec, opt, cfg.network.use_double)
        try:
            sps, ts, rs = measure_path(step, ts, rs, label)
            results[label] = sps * spec.batch_size
        except Exception as e:  # pallas lowering failure must not kill the bench
            if not use_pallas:
                raise
            results[label] = None
            print(f"[{label}] FAILED: {type(e).__name__}: {e}", file=sys.stderr)

    # primary metric follows the config-default decode path, falling back to
    # the other path if the default one was skipped/failed on this backend
    default_label = ("pallas_decode" if cfg.optim.pallas_obs_decode
                     else "xla_decode")
    seq_updates = results[default_label]
    if seq_updates is None:
        fallback = "xla_decode" if default_label != "xla_decode" else "pallas_decode"
        seq_updates = results[fallback]
    print(json.dumps({
        "metric": "learner_sequence_updates_per_sec_per_chip",
        "value": round(seq_updates, 1),
        "unit": "sequences/s",
        "vs_baseline": round(seq_updates / REFERENCE_SEQ_UPDATES_PER_SEC, 2),
        "xla_decode": results["xla_decode"] and round(results["xla_decode"], 1),
        "pallas_decode": (results["pallas_decode"]
                          and round(results["pallas_decode"], 1)),
    }))


if __name__ == "__main__":
    main()
