#!/bin/bash
# Round-5 capture watchdog: wait for a healthy TPU tunnel, then run the
# queued measurement sequence in order (verify skill's post-wedge recipe):
#   1. chip_checks  — validate every pallas kernel's Mosaic lowering (~3 min)
#   2. bench.py     — full matrix incl. plstm block_t sweep 1/5/11 (~20 min)
#   3. r5_learn_tpu — on-chip learnability under shipped (padded) defaults
# Logs: r5_capture.log; artifacts: r5_chip_checks.log, r5_bench_out.json,
# r5_bench_err.log, r5_learn_out.json, r5_learn_err.log.
cd /root/repo || exit 1
LOG=r5_capture.log
ts() { date -u +%FT%TZ; }
probe() {
  # SIGTERM -> SystemExit so atexit/JAX teardown runs when timeout fires:
  # the default disposition is an abrupt kill, the documented tunnel-wedge
  # class (bench.py's measurement child installs the same handler)
  timeout -k 30 90 python -c "import signal, sys; signal.signal(signal.SIGTERM, lambda s, f: sys.exit(143)); from r2d2_tpu.utils.platform import pin_platform; pin_platform(); import jax; d=jax.devices(); assert d[0].platform=='tpu', d; import jax.numpy as jnp; (jnp.ones((8,128))@jnp.ones((128,8))).block_until_ready(); print('probe-ok', d[0].device_kind)" >> "$LOG" 2>&1
}
echo "$(ts) watchdog start (pid $$)" >> "$LOG"
while true; do
  if probe; then
    echo "$(ts) tunnel HEALTHY -> chip_checks" >> "$LOG"
    python -m r2d2_tpu.cli.chip_checks > r5_chip_checks.log 2>&1
    echo "$(ts) chip_checks rc=$?" >> "$LOG"
    echo "$(ts) bench start (plstm bt sweep 1,5,11)" >> "$LOG"
    R2D2_BENCH_CHILD_TIMEOUT=2700 R2D2_BENCH_PLSTM_BT=1,5,11 \
      python bench.py > r5_bench_out.json 2> r5_bench_err.log
    echo "$(ts) bench rc=$?" >> "$LOG"
    # measurement-driven default flips (plstm win / exact-gather revert):
    # rc=10 means config.py changed and parity tests passed -> re-run
    # bench so the headline cell measures the NEW defaults
    python r5_decide.py >> "$LOG" 2>&1
    if [ $? -eq 10 ]; then
      echo "$(ts) defaults flipped; re-running bench under new defaults" >> "$LOG"
      R2D2_BENCH_CHILD_TIMEOUT=2700 \
        python bench.py > r5_bench_flipped_out.json 2> r5_bench_flipped_err.log
      echo "$(ts) flipped bench rc=$?" >> "$LOG"
    fi
    if probe; then
      echo "$(ts) learnability start" >> "$LOG"
      # sync_train carries its own in-process deadline (graceful); the
      # outer timeout is a last resort only (SIGTERM, then SIGKILL +60s)
      timeout -k 60 4500 python r5_learn_tpu.py \
        > r5_learn_out.json 2> r5_learn_err.log
      echo "$(ts) learnability rc=$?" >> "$LOG"
    else
      echo "$(ts) tunnel wedged again after bench; skipping learnability" >> "$LOG"
    fi
    if probe; then
      echo "$(ts) soak start (30 min, reference scale)" >> "$LOG"
      timeout -k 60 3600 python -m r2d2_tpu.cli.soak --seconds=1800 \
        --save-dir=/tmp/r2d2_soak_r5 \
        > r5_soak_out.json 2> r5_soak_err.log
      echo "$(ts) soak rc=$?" >> "$LOG"
    else
      echo "$(ts) tunnel wedged; skipping soak" >> "$LOG"
    fi
    echo "$(ts) capture sequence COMPLETE" >> "$LOG"
    break
  fi
  echo "$(ts) still wedged; sleeping 480s" >> "$LOG"
  sleep 480
done
