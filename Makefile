# Developer entry points.
#
#   make t1    — the tier-1 gate: EXACTLY the ROADMAP.md verify command
#                (via scripts/t1.sh), preceded by a marker check that the
#                ingestion and chaos tests are collected in the fast
#                ('not slow') tier — a stray @pytest.mark.slow would
#                silently drop them from the gate.
#   make chaos — the fast-tier worker-health / fault-injection suite
#                (tests/test_chaos.py, 'chaos and not slow'); the
#                slow-marked chaos slices (real injected hangs/crash-loops
#                through process actors) run with the full tier or via
#                pytest -m chaos.
#   make telemetry — the fast-tier telemetry suite (tests/test_telemetry.py:
#                histogram percentiles/merge, span rings, board
#                aggregation, record schema stability, profiler capture
#                lifecycle); the slow-marked e2e slices run with the full
#                tier.
#   make learning — the fast-tier learning-diagnostics suite
#                (tests/test_learning_diag.py: device-vs-host histogram
#                parity, dQ reference agreement, staleness stamps through
#                shm/mp/ring-wrap, NaN forensics, record schema); the
#                slow e2e slice runs with the full tier.
#   make anakin — the fast-tier on-device acting suite
#                (tests/test_anakin.py: jitted-env parity, block-layout
#                parity with the host sink, replay-state identity, the
#                fused loop, kill switch); the slow gridworld
#                learnability slice runs with the full tier.
#   make anakin-sharded — the fast-tier sharded-anakin suite
#                (tests/test_anakin_sharded.py: dp=2 replay-state
#                identity vs the per-shard sequential reference,
#                per-shard RNG independence, global ε-ladder layout,
#                relaxed mesh validation, the composed loop + per-shard
#                telemetry block, the shard_imbalance rule); the slow
#                dp=2 gridworld learnability slice runs with the full
#                tier.
#   make sentinel — the fast-tier resource/compile/alerting suite
#                (tests/test_sentinel.py: rule-engine semantics, retrace
#                detection on a shape-churning jit, board RSS
#                aggregation, resource monitor + forensics dump, record
#                schema stability); the slow chaos-driven e2e slices
#                (injected hang → actor_stall alert) run with the full
#                tier.
#   make replaydiag — the fast-tier replay-observability suite
#                (tests/test_replay_diag.py: device-vs-host leaf-histogram
#                parity, sample-count ring across wrap + batched
#                overwrite, lane stamps through the queue transports and
#                the sharded anakin path, eviction lifetimes vs a
#                sequential reference, the new alert rules, kill-switch
#                record-schema stability); the slow e2e slice (populated
#                replay_diag block, nonzero never-sampled fraction) runs
#                with the full tier.
#   make fleet — the fast-tier fleet-observability suite
#                (tests/test_fleet.py: lockstep psum-row gauge math on
#                the emulated mesh (argmax/skew, kill-switch shape
#                identity), FleetAggregator merge parity vs per-rank
#                references, the four fleet alert rules incl.
#                once-per-breach edge semantics, host-row rotation,
#                trace merge + clock alignment on the checked-in
#                two-rank fixture, sentinel host-row/alert streams,
#                record-schema stability); the slow single-controller
#                lockstep e2e + two-process loopback straggler A/B run
#                with the full tier.
#   make serve — the fast-tier policy-serving suite (tests/test_serve.py:
#                micro-batcher deadline/fill semantics, state-cache
#                lease/evict/reconnect, local-vs-server action parity,
#                transport round-trips (in-proc + shm + socket), serving
#                record schema + the serve_* alert rules, kill-switch
#                schema stability, the sharded fleet: shard routing +
#                handoff, single-server parity, kill/adopt failover,
#                grow/shrink reslice, admission shed + brownout alert,
#                membership leases); the slow e2e slice (real actors
#                through the server into the learner) and the
#                server-kill/restart chaos drill run with the full tier.
#   make elastic — the fast-tier elastic-fleet suite
#                (tests/test_elastic.py: service-vs-in-mesh replay
#                parity, spill demote/promote round-trips + the >= 2x
#                capacity geometry, lane-routing provenance, the
#                socket rung, fan-out tree topology/stamp propagation
#                incl. the quant bundle, membership
#                lease/park/adopt/handoff, elastic supervision, the
#                join/leave chaos grammar, the replay_service block +
#                three fleet alert rules, the service-routed Learner);
#                the slow churn drill (leave 25% of a running fleet,
#                re-join it, zero learner stalls) runs with the full
#                tier.
#   make service-ingest — the fast-tier batched service data-plane
#                suite (tests/test_service_ingest.py: grouped-ingest
#                bit-parity with the sequential path incl. ring wrap /
#                mid-group spill demotion / lane routing, the AOT chunk
#                plan, windowed socket cumulative acks under
#                drop_ack@every chaos injection, spilled-page priority
#                write-backs, priority-ordered async prefetch, the
#                producer pump + run_replay_producer wiring, the new
#                fleet knobs' round-trip/validation, the ingest_backlog
#                rule); the slow sample-stager parity slice runs with
#                the full tier.
#   make quant — the fast-tier quantized-inference suite
#                (tests/test_quant.py: per-channel int8 round-trip
#                bounds, greedy-action agreement vs the f32 twin,
#                publish-time bundle round-trips through both weight
#                stores with staleness stamps, serve/local/anakin
#                switching through the one shared forward, the in-graph
#                probe + quant block + quant_divergence rule,
#                kill-switch schema stability, pre-PR14 config
#                round-trip); the slow int8 learnability slice runs
#                with the full tier.
#   make costmodel — the fast-tier cost-model/roofline suite
#                (tests/test_costmodel.py: XLA cost-table extraction
#                across step factories incl. a sharded emulated-mesh
#                program, named_scope presence in lowered HLO,
#                traceparse on the checked-in miniature trace, roofline
#                report + analytic golden file, the costs gate, record
#                schema stability under the kill switch).
#   make recovery — the fast-tier crash-recovery suite
#                (tests/test_recovery.py: snapshot round-trip bit-parity
#                (service shards with/without spill, the plain in-mesh
#                cut), the atomic manifest commit + torn-payload probe,
#                SnapshotWriter latest-wins, producer reconnect +
#                unacked-tail replay across a service bounce,
#                eager-connect construction failures + the dial ladder,
#                resume determinism on both learner paths, the
#                supervisor's breaker/clean-exit/resume-chain policies,
#                checkpoint retention GC, kill-switch record-schema
#                stability + inert alert rules); the slow SIGKILL drills
#                (tools/chaos.py --kill-learner / --kill-replay-service
#                end-to-end) run with the full tier.
#   make tracing — the fast-tier cross-plane tracing suite
#                (tests/test_tracing.py: hop-stamp propagation through
#                the in-proc/shm/socket serve rungs, the experience
#                lineage stamp through ring wrap + spill
#                demote/promote + snapshot restore, the trace record
#                block, kill-switch byte-identity of records and wire
#                frames, record-schema stability).
#   make tower — the fast-tier control-tower slice of the same file
#                (tests/test_tracing.py -m tower: the TowerCollector
#                join over synthesized plane streams, the derived
#                cross-plane signals, the four tower rules, clock-
#                anchor alignment, the offline-replay CLI).
#   make quality — the fast-tier policy-quality suite
#                (tests/test_quality.py: the Q-calibration join vs a
#                per-row python reference, QualityStats interval/eval
#                aggregation, shadow scoring that never mutates live
#                serving state, the gated canary promotion round-trip
#                (stage/refuse/promote/rollback + restart persistence),
#                kill-switch record-schema stability, pre-PR20 config
#                round-trips, the three quality alert rules + their
#                tower twins); the promotion drill itself
#                (tools/chaos.py --promotion) rides the e2e bench's
#                --promotion-ab evidence cell.
#   make regress — the regression gate: tools/regress.py compares the
#                tree's E2E_*/BENCH_* artifacts against BASELINE.json's
#                'bench' snapshot (per-metric noise tolerances) AND the
#                freshly recomputed XLA cost table against its 'costs'
#                snapshot (exact match — compute regressions fail even
#                on wall-clock-noisy hosts); exit 1 on any failure.
#   make costs — write the per-program XLA cost table to COSTS.json
#                (telemetry/costmodel.py, CPU-pinned 2-device mesh).
#   make roofline — generate the roofline report (JSON + table) into
#                ROOFLINE.json: per-component flops/bytes/arithmetic
#                intensity/%-of-peak + the serial-chain model
#                (tools/roofline.py; gate preset on CPU, reference
#                shape on TPU).

.PHONY: t1 chaos telemetry learning anakin anakin-sharded sentinel \
	replaydiag fleet serve quant elastic service-ingest costmodel \
	recovery tracing tower quality regress costs roofline \
	check-fast-markers

t1: check-fast-markers
	bash scripts/t1.sh

chaos: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
	    -m 'chaos and not slow' -p no:cacheprovider

telemetry: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py -q \
	    -m 'not slow' -p no:cacheprovider

learning: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_learning_diag.py -q \
	    -m 'not slow' -p no:cacheprovider

anakin: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_anakin.py -q \
	    -m 'not slow' -p no:cacheprovider

anakin-sharded: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_anakin_sharded.py -q \
	    -m 'not slow' -p no:cacheprovider

sentinel: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_sentinel.py -q \
	    -m 'not slow' -p no:cacheprovider

replaydiag: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_replay_diag.py -q \
	    -m 'not slow' -p no:cacheprovider

fleet: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q \
	    -m 'not slow' -p no:cacheprovider

serve: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_serve.py -q \
	    -m 'not slow' -p no:cacheprovider

quant: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_quant.py -q \
	    -m 'not slow' -p no:cacheprovider

elastic: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_elastic.py -q \
	    -m 'not slow' -p no:cacheprovider

service-ingest: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_service_ingest.py -q \
	    -m 'not slow' -p no:cacheprovider

costmodel: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_costmodel.py -q \
	    -m 'not slow' -p no:cacheprovider

recovery: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_recovery.py -q \
	    -m 'not slow' -p no:cacheprovider

tracing: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q \
	    -m 'not slow' -p no:cacheprovider

tower: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q \
	    -m 'tower and not slow' -p no:cacheprovider

quality: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_quality.py -q \
	    -m 'not slow' -p no:cacheprovider

regress:
	JAX_PLATFORMS=cpu python -m r2d2_tpu.tools.regress \
	    --baseline BASELINE.json --dir .

costs:
	JAX_PLATFORMS=cpu python -m r2d2_tpu.telemetry.costmodel \
	    --out COSTS.json

roofline:
	JAX_PLATFORMS=cpu python -m r2d2_tpu.tools.roofline \
	    --out ROOFLINE.json

# One guard per suite: module:marker:min-collected:label (marker spelled
# with underscores for spaces). A stray @pytest.mark.slow (or a marker
# typo) silently drops tests from the fast tier; the count floor catches
# it.
FAST_MARKER_CHECKS := \
	tests/test_ingest.py:not_slow:10:ingestion \
	tests/test_chaos.py:chaos_and_not_slow:12:chaos \
	tests/test_telemetry.py:not_slow:20:telemetry \
	tests/test_learning_diag.py:not_slow:12:learning-diagnostics \
	tests/test_anakin.py:not_slow:10:anakin \
	tests/test_anakin_sharded.py:not_slow:8:anakin-sharded \
	tests/test_sentinel.py:not_slow:20:sentinel \
	tests/test_replay_diag.py:not_slow:10:replay-diag \
	tests/test_fleet.py:not_slow:12:fleet \
	tests/test_serve.py:not_slow:40:serve \
	tests/test_quant.py:not_slow:14:quant \
	tests/test_elastic.py:not_slow:20:elastic \
	tests/test_service_ingest.py:not_slow:20:service-ingest \
	tests/test_costmodel.py:not_slow:10:cost-model \
	tests/test_recovery.py:not_slow:18:recovery \
	tests/test_tracing.py:not_slow:16:tracing \
	tests/test_tracing.py:tower_and_not_slow:5:tower \
	tests/test_quality.py:not_slow:14:quality

check-fast-markers:
	@for spec in $(FAST_MARKER_CHECKS); do \
	    mod=$${spec%%:*}; rest=$${spec#*:}; \
	    marker=$$(echo "$${rest%%:*}" | tr '_' ' '); rest=$${rest#*:}; \
	    min=$${rest%%:*}; label=$${rest#*:}; \
	    n=$$(JAX_PLATFORMS=cpu python -m pytest "$$mod" \
	        -m "$$marker" --collect-only -q -p no:cacheprovider 2>/dev/null \
	        | grep -c '::'); \
	    if [ "$$n" -ge "$$min" ]; then \
	        echo "fast-tier $$label tests collected: $$n"; \
	    else \
	        echo "ERROR: $$label tests missing from the '$$marker' tier ($$n collected)"; \
	        exit 1; \
	    fi; \
	done
