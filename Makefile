# Developer entry points.
#
#   make t1    — the tier-1 gate: EXACTLY the ROADMAP.md verify command
#                (via scripts/t1.sh), preceded by a marker check that the
#                ingestion and chaos tests are collected in the fast
#                ('not slow') tier — a stray @pytest.mark.slow would
#                silently drop them from the gate.
#   make chaos — the fast-tier worker-health / fault-injection suite
#                (tests/test_chaos.py, 'chaos and not slow'); the
#                slow-marked chaos slices (real injected hangs/crash-loops
#                through process actors) run with the full tier or via
#                pytest -m chaos.
#   make telemetry — the fast-tier telemetry suite (tests/test_telemetry.py:
#                histogram percentiles/merge, span rings, board
#                aggregation, record schema stability, profiler capture
#                lifecycle); the slow-marked e2e slices run with the full
#                tier.
#   make learning — the fast-tier learning-diagnostics suite
#                (tests/test_learning_diag.py: device-vs-host histogram
#                parity, dQ reference agreement, staleness stamps through
#                shm/mp/ring-wrap, NaN forensics, record schema); the
#                slow e2e slice runs with the full tier.

.PHONY: t1 chaos telemetry learning check-fast-markers

t1: check-fast-markers
	bash scripts/t1.sh

chaos: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
	    -m 'chaos and not slow' -p no:cacheprovider

telemetry: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py -q \
	    -m 'not slow' -p no:cacheprovider

learning: check-fast-markers
	JAX_PLATFORMS=cpu python -m pytest tests/test_learning_diag.py -q \
	    -m 'not slow' -p no:cacheprovider

check-fast-markers:
	@n=$$(JAX_PLATFORMS=cpu python -m pytest tests/test_ingest.py \
	    -m 'not slow' --collect-only -q -p no:cacheprovider 2>/dev/null \
	    | grep -c '::'); \
	if [ "$$n" -ge 10 ]; then \
	    echo "fast-tier ingestion tests collected: $$n"; \
	else \
	    echo "ERROR: ingestion tests missing from the 'not slow' tier ($$n collected)"; \
	    exit 1; \
	fi
	@n=$$(JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py \
	    -m 'chaos and not slow' --collect-only -q -p no:cacheprovider 2>/dev/null \
	    | grep -c '::'); \
	if [ "$$n" -ge 12 ]; then \
	    echo "fast-tier chaos tests collected: $$n"; \
	else \
	    echo "ERROR: chaos tests missing from the 'chaos and not slow' tier ($$n collected)"; \
	    exit 1; \
	fi
	@n=$$(JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py \
	    -m 'not slow' --collect-only -q -p no:cacheprovider 2>/dev/null \
	    | grep -c '::'); \
	if [ "$$n" -ge 20 ]; then \
	    echo "fast-tier telemetry tests collected: $$n"; \
	else \
	    echo "ERROR: telemetry tests missing from the 'not slow' tier ($$n collected)"; \
	    exit 1; \
	fi
	@n=$$(JAX_PLATFORMS=cpu python -m pytest tests/test_learning_diag.py \
	    -m 'not slow' --collect-only -q -p no:cacheprovider 2>/dev/null \
	    | grep -c '::'); \
	if [ "$$n" -ge 12 ]; then \
	    echo "fast-tier learning-diagnostics tests collected: $$n"; \
	else \
	    echo "ERROR: learning-diagnostics tests missing from the 'not slow' tier ($$n collected)"; \
	    exit 1; \
	fi
