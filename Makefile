# Developer entry points.
#
#   make t1    — the tier-1 gate: EXACTLY the ROADMAP.md verify command
#                (via scripts/t1.sh), preceded by a marker check that the
#                ingestion tests are collected in the fast ('not slow')
#                tier — a stray @pytest.mark.slow would silently drop them
#                from the gate.

.PHONY: t1 check-fast-markers

t1: check-fast-markers
	bash scripts/t1.sh

check-fast-markers:
	@n=$$(JAX_PLATFORMS=cpu python -m pytest tests/test_ingest.py \
	    -m 'not slow' --collect-only -q -p no:cacheprovider 2>/dev/null \
	    | grep -c '::'); \
	if [ "$$n" -ge 10 ]; then \
	    echo "fast-tier ingestion tests collected: $$n"; \
	else \
	    echo "ERROR: ingestion tests missing from the 'not slow' tier ($$n collected)"; \
	    exit 1; \
	fi
