"""On-chip learnability spot check (PERF.md "Measurements queued" #4).

Runs the learnability acceptance config (tests/test_learnability.py — the
CI stand-in for the reference's Boxing curve, /root/reference/README.md:38-40)
on the REAL chip under the shipped defaults — which now resolve to the
padded exact-read gather storage — with runtime.steps_per_dispatch=1 to
keep the calibrated collect:learn ratio (the round-3 run's setup).

Acceptance: every seed >= 2x random (40.0), mean >= 3x (60.0); round-3
margins were 76/77/70 (mean 74.3) vs 20.0 random.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tests"))

from test_learnability import (COLLECT_EPS, EVAL_SEEDS,  # noqa: E402
                               RANDOM_EXPECTATION, TRAIN_STEPS, learn_config)


def main() -> int:
    import jax
    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev.device_kind})", file=sys.stderr)
    if dev.platform != "tpu":
        print("not a TPU backend — this is the on-chip spot check; the CPU "
              "result is already CI-gated", file=sys.stderr)
        return 2
    cfg = learn_config("/tmp/r5_learn_tpu").replace(
        **{"runtime.steps_per_dispatch": 1})
    from r2d2_tpu.tools.sync_train import greedy_return, sync_train
    t0 = time.time()
    net, learner = sync_train(cfg, TRAIN_STEPS, COLLECT_EPS, seed=0,
                              deadline=t0 + 3000)
    returns = [float(greedy_return(net, learner.train_state.params,
                                   cfg.env, s)) for s in EVAL_SEEDS]
    mean = sum(returns) / len(returns)
    out = {"returns": returns, "mean": round(mean, 1),
           "random_expectation": RANDOM_EXPECTATION,
           "pass": (min(returns) >= 2 * RANDOM_EXPECTATION
                    and mean >= 3 * RANDOM_EXPECTATION),
           "exact_gather_default_resolved": bool(
               learner.spec.exact_gather),
           "train_s": round(time.time() - t0, 1),
           "device_kind": dev.device_kind}
    print(json.dumps(out))
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
