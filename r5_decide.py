"""Post-bench decision helper for the round-5 capture watchdog.

Reads the fresh bench artifact and applies the measurement-driven default
flips the round-4 verdict prescribes, so a healthy tunnel window is used
end-to-end without waiting for a human in the loop:

  1. If a fused-pallas-LSTM cell (bf16_spd16_plstm / _bt5 / _bt11) beats
     the bf16_spd16 headline by >2%, flip ``network.pallas_lstm`` to
     "auto" (and ``pallas_lstm_block`` to the winning block size) in
     config.py, run the fast LSTM parity tests, and exit 10 — the
     watchdog then re-runs bench.py so the headline cell measures the
     new default.
  2. If the headline (now measuring the padded exact-read gather default)
     came in BELOW the row-gather A/B cell, revert
     ``replay.pallas_exact_gather`` to "off" and exit 10 likewise.
  3. Otherwise exit 0 (defaults stand; nothing to re-measure).

Exit 1 = artifact unreadable/stale (no decision possible).
"""
import json
import re
import subprocess
import sys

CFG = "/root/repo/r2d2_tpu/config.py"


def _edit(pattern, repl):
    src = open(CFG).read()
    new, n = re.subn(pattern, repl, src, count=1)
    if n != 1:
        raise RuntimeError(f"config edit failed: {pattern!r}")
    open(CFG, "w").write(new)


def main() -> int:
    try:
        with open("/root/repo/r5_bench_out.json") as f:
            out = json.loads(f.read().strip().splitlines()[-1])
    except (OSError, ValueError, IndexError) as e:
        print(f"decide: no readable artifact ({e})", file=sys.stderr)
        return 1
    if out.get("stale"):
        print("decide: artifact is stale — no decision", file=sys.stderr)
        return 1
    matrix = out.get("matrix") or {}
    status = out.get("cell_status") or {}

    def val(label):
        v = matrix.get(label)
        return v if v is not None and status.get(label, "ok") in (
            "ok", "ok-reused", "carried") else None

    base = val("bf16_spd16")
    if base is None:
        print("decide: no clean headline cell — no decision",
              file=sys.stderr)
        return 1

    changed = []
    # --- 1. fused pallas LSTM ------------------------------------------
    plstm = {1: val("bf16_spd16_plstm"),
             5: val("bf16_spd16_plstm_bt5"),
             11: val("bf16_spd16_plstm_bt11")}
    plstm = {k: v for k, v in plstm.items() if v is not None}
    if plstm:
        bt, best = max(plstm.items(), key=lambda kv: kv[1])
        print(f"decide: plstm best = {best:.0f} (bt={bt}) vs base "
              f"{base:.0f}", file=sys.stderr)
        if best > 1.02 * base:
            _edit(r'pallas_lstm: str = "off"',
                  'pallas_lstm: str = "auto"')
            if bt != 1:
                _edit(r"pallas_lstm_block: int = 1",
                      f"pallas_lstm_block: int = {bt}")
            changed.append(f"pallas_lstm=auto block={bt} ({best:.0f} vs "
                           f"{base:.0f})")

    # --- 2. exact-gather confirmation ----------------------------------
    # the padded default costs 1.74x obs-ring HBM, so it must BEAT the
    # row gather by >1% to stay justified; only meaningful when the
    # headline actually measured padded storage
    resolved = out.get("resolved_defaults") or {}
    row = val("bf16_spd16_rowgather")
    if (row is not None and row >= 0.99 * base
            and resolved.get("exact_gather", True)):
        _edit(r'pallas_exact_gather: str = "auto"',
              'pallas_exact_gather: str = "off"')
        changed.append(f"pallas_exact_gather=off (rowgather {row:.0f} vs "
                       f"padded headline {base:.0f}: <1% win does not "
                       "justify 1.74x ring HBM)")

    if not changed:
        print("decide: defaults stand", file=sys.stderr)
        return 0
    print("decide: flipped ->", "; ".join(changed), file=sys.stderr)
    # gate the flip on the fast parity tests before re-spending the chip
    t = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_network.py",
         "tests/test_train_step.py", "-q", "-m", "not slow"],
        cwd="/root/repo", capture_output=True, text=True, timeout=1200)
    if t.returncode != 0:
        print("decide: parity tests FAILED after flip — reverting",
              file=sys.stderr)
        subprocess.run(["git", "checkout", "--", "r2d2_tpu/config.py"],
                       cwd="/root/repo")
        return 1
    return 10


if __name__ == "__main__":
    sys.exit(main())
