"""Disaggregated replay service (ISSUE 15 tentpole, plane a; ISSUE 16
batched/pipelined data plane).

The dp-sharded device replay (parallel/sharded.py) bound N replay rings
to N mesh shards inside ONE shard_map program — producers and consumers
were the same fused loop. This module generalizes that layout into N
ADDRESSABLE shards behind one :class:`ReplayService` interface: any
producer routes blocks by shard key (the same jitted
``replay_add``/``replay_add_many`` ring-writes), any consumer draws
prioritized sample batches (``replay_sample``) and writes priorities
back (``replay_update_priorities``) — so the replay plane no longer
assumes producers, consumers, and storage share a process, a mesh, or a
lifetime.

Capacity scales past the HBM budget through a host-RAM **spill tier**:
when a device ring-write overwrites a live block, the overwritten
block's host page is DEMOTED into an LRU page store instead of being
destroyed; pages are RE-PROMOTED into the samplable device ring at
sample time (``spill_promote_per_sample`` pages rotated per sample
call), so spilled experience cycles back through the prioritized tree
rather than being lost. With the spill tier cold (empty) the sample
path is exactly ``replay_sample`` on the device state — parity with the
in-mesh path is program identity, not a tolerance argument
(tests/test_elastic.py).

Routing policies:

  * ``"round_robin"`` — block k lands in shard ``k % num_shards``:
    EXACTLY the dp-sharded path's feeding order, which is what the
    service-vs-in-mesh parity test pins bit-for-bit.
  * ``"lane"`` — shard = ``block.lane % num_shards`` (the PR-10 ε-lane
    provenance stamp): a producer's blocks land in a shard determined
    by its lane identity, so shard contents are provenance-checkable
    (the churn drill's acceptance) and an elastic joiner adopting a
    slot's lane range adopts its replay routing with it. Unstamped
    blocks (lane −1) fall back to round-robin.

The batched data plane (ISSUE 16) removes the per-block dispatch tax at
every rung while keeping bit-parity with the sequential path:

  * **Grouped ingest** — :meth:`ReplayService.add_blocks` routes K
    blocks in arrival order (the round-robin counter advances exactly
    as K sequential :meth:`add_block` calls would), groups them by
    routed shard, and commits each per-shard group through the donated
    ``replay_add_many`` program in pow2 chunks AOT-precompiled at
    service start. Per-shard ring rows, spill demotions (order and
    LRU position), and lane/staleness stamps are bit-identical to the
    sequential adds (tests/test_service_ingest.py); a configured
    ``ingest_batch_blocks=1`` keeps the per-block loop byte-identical.
  * **Windowed socket rung** — one ``addw`` frame carries a stacked
    group; the producer keeps up to ``window`` unacked frames in
    flight with CUMULATIVE acks (an ack for seq confirms every frame
    ≤ seq), so a dropped ack is absorbed by the next one and
    ``flush`` (always acked) is the resync point.
  * **Priority-aware spill prefetch** — pages carry their stored leaf
    priorities; with ``spill_prefetch`` promotion pops the
    highest-priority page (max-heap) instead of the LRU end, and runs
    on a service-owned background thread kicked at write-back time so
    the sample path stops paying promotion latency inline.
  * **Spilled-page write-backs** (ROADMAP 4a) — a priority write-back
    whose sampled row was demoted since the sample routes to the
    page's stored priorities instead of being dropped as stale, so
    the spill tier holds the cold tail rather than random victims.

The transport ladder follows serve/transport.py's shape: in-proc
producers call :meth:`ReplayService.add_block` directly;
:class:`ReplayServiceServer` / :class:`RemoteReplayProducer` are the
cross-host socket rung (length-prefixed-pickle frames, one connection
per producer) feeding the same routing.
"""

import heapq
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from r2d2_tpu.replay.structs import Block, ReplaySpec, RingAccountant


def _host_block(block: Block) -> Block:
    """Materialize a block's leaves as host numpy arrays (the spill tier
    stores pages in host RAM; feeder-queue blocks already are numpy, so
    this is a cheap view in the common case)."""
    import jax
    return jax.tree_util.tree_map(lambda x: np.asarray(x), block)


class SpillTier:
    """Host-RAM LRU page store for blocks demoted from a device ring.

    A page is one block record (host numpy) plus its accounting meta.
    ``demote`` inserts at the MRU end and drops the LRU page when the
    tier is full (an ``eviction`` — that experience is now truly gone,
    like a pre-service ring overwrite); ``promote_next`` pops the LRU
    page for re-insertion into the device ring (a ``hit``: the page made
    it back into the samplable set). ``hit_rate`` is therefore the share
    of demoted pages that returned to the ring rather than falling off
    the end — the spill tier's usefulness gauge; ``thrash_frac`` (the
    per-interval eviction/demotion ratio in :meth:`take_interval`) is
    the ``spill_thrash`` alert's signal: near 1.0 the ring is turning
    over so fast the tier is a pure write-through loss.

    ISSUE 16: every page also carries its max stored leaf priority
    (``demote`` reads it from the page's ``block.priority`` — the raw
    |TD| record both add-time seeding and write-backs are expressed in).
    ``promote_best`` pops the highest-priority page via a lazy-deletion
    max-heap, and :meth:`write_back` lets a post-demotion priority
    write-back reach the page in place (ROADMAP 4a) — the re-seeded
    priorities take effect at promotion through the same ``replay_add``
    seeding as a fresh block. Eviction stays LRU in BOTH modes: the
    heap orders what comes back first, not what falls off the end."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._pages: "OrderedDict[int, tuple]" = OrderedDict()
        # page id -> max stored leaf priority; the heap holds
        # (-priority, id) with lazy deletion (stale ids skipped on pop)
        self._prio: Dict[int, float] = {}
        self._heap: List[Tuple[float, int]] = []
        self._next_id = 1          # 1-based so a page id is always truthy
        self.demotions = 0
        self.promotions = 0
        self.evictions = 0
        self.writebacks = 0
        self._interval = [0, 0, 0]   # demotions, promotions, evictions
        # per-tier telemetry (ISSUE 19 satellite, ROADMAP 4d): demotion
        # timestamp per resident page (monotonic) so a promotion's
        # time-in-tier lands in the shared 64-bucket latency histogram;
        # page_bytes is measured from the first demoted page (fixed
        # record shapes — every page weighs the same)
        self._demoted_at: Dict[int, float] = {}
        from r2d2_tpu.telemetry.histogram import NBUCKETS
        self._promo_lat = np.zeros(NBUCKETS, np.int64)
        self.page_bytes = 0

    @property
    def occupancy(self) -> int:
        return len(self._pages)

    def demote(self, block: Block, learning: int,
               weight_version: int) -> Optional[int]:
        """Insert one demoted page; returns its page id (the write-back
        routing token) or None when the tier is disabled (capacity 0 —
        the page is simply lost, the pre-service overwrite
        semantics)."""
        if self.capacity <= 0:
            return None
        pid = self._next_id
        self._next_id += 1
        self._pages[pid] = (block, int(learning), int(weight_version))
        prio = float(np.max(np.asarray(block.priority)))
        self._prio[pid] = prio
        heapq.heappush(self._heap, (-prio, pid))
        self.demotions += 1
        self._interval[0] += 1
        self._demoted_at[pid] = time.monotonic()
        if not self.page_bytes:
            self.page_bytes = sum(
                np.asarray(v).nbytes for v in _block_fields(block).values())
        if len(self._pages) > self.capacity:
            old_id, _ = self._pages.popitem(last=False)
            self._prio.pop(old_id, None)
            self._demoted_at.pop(old_id, None)
            self.evictions += 1
            self._interval[2] += 1
        return pid

    def promote_next(self) -> Optional[tuple]:
        """Pop the least-recently-demoted page for re-insertion into the
        device ring; None when the tier is empty."""
        if not self._pages:
            return None
        pid, page = self._pages.popitem(last=False)
        self._prio.pop(pid, None)
        self._note_promo(pid)
        self.promotions += 1
        self._interval[1] += 1
        return page

    def promote_best(self) -> Optional[tuple]:
        """Pop the HIGHEST-priority page (ISSUE 16 priority-aware
        promotion): lazy-deletion max-heap over the stored per-page
        priorities — evicted/promoted/re-written ids are skipped on
        pop. None when the tier is empty."""
        while self._heap:
            neg_prio, pid = heapq.heappop(self._heap)
            if self._prio.get(pid) != -neg_prio or pid not in self._pages:
                continue            # evicted, promoted, or re-prioritized
            page = self._pages.pop(pid)
            self._prio.pop(pid, None)
            self._note_promo(pid)
            self.promotions += 1
            self._interval[1] += 1
            return page
        return None

    def _note_promo(self, pid: int) -> None:
        t = self._demoted_at.pop(pid, None)
        if t is not None:
            from r2d2_tpu.telemetry.histogram import bucket_index
            self._promo_lat[bucket_index(time.monotonic() - t)] += 1

    def take_promotion_latency(self) -> Optional[dict]:
        """Interval time-in-tier summary for promoted pages (reset on
        read); None when nothing was promoted this interval."""
        from r2d2_tpu.telemetry.histogram import summarize
        s = summarize(self._promo_lat)
        self._promo_lat[:] = 0
        return s

    def write_back(self, page_id: int, seq: int, abs_td: float) -> bool:
        """Write one sequence's new |TD| priority into a spilled page
        (ROADMAP 4a): the page's ``block.priority[seq]`` is the raw-|TD|
        record ``replay_add`` seeds the tree from at promotion, so the
        write-back re-prioritizes the page exactly as a live-row
        write-back would have. False when the page is gone (evicted or
        already promoted) — the caller counts that as a dropped row."""
        page = self._pages.get(page_id)
        if page is None:
            return False
        block, learning, wv = page
        prio = np.array(np.asarray(block.priority), copy=True)
        if not 0 <= seq < prio.shape[0]:
            return False
        prio[seq] = abs_td
        self._pages[page_id] = (block.replace(priority=prio), learning, wv)
        new_max = float(np.max(prio))
        self._prio[page_id] = new_max
        heapq.heappush(self._heap, (-new_max, page_id))
        self.writebacks += 1
        return True

    @property
    def hit_rate(self) -> Optional[float]:
        """Cumulative promoted / (promoted + evicted) — None before any
        page has left the tier either way."""
        done = self.promotions + self.evictions
        return round(self.promotions / done, 4) if done else None

    def take_interval(self) -> dict:
        """Per-interval demotion/promotion/eviction deltas (reset on
        read) + the interval thrash fraction for the alert rule."""
        d, p, e = self._interval
        self._interval = [0, 0, 0]
        return {
            "demotions": d, "promotions": p, "evictions": e,
            "thrash_frac": (round(e / d, 4) if d else None),
        }


class ReplayShard:
    """One addressable replay shard: a device ring (the exact jitted
    add/sample/update programs of replay/device_replay.py), its
    RingAccountant, and — when spill is enabled — the host page shadow
    that makes demotion free (the overwritten block's page is already in
    host RAM; no device read-back)."""

    def __init__(self, spec: ReplaySpec, index: int,
                 spill_blocks: int = 0):
        from r2d2_tpu.replay.device_replay import replay_init
        self.spec = spec
        self.index = index
        self.state = replay_init(spec)
        self.ring = RingAccountant(spec.num_blocks)
        self.spill = SpillTier(spill_blocks)
        self._retain = spill_blocks > 0
        # host page per live ring slot (spill mode only): (block,
        # learning, weight_version), the demotion source
        self._resident: List[Optional[tuple]] = [None] * spec.num_blocks
        # spill page id the slot's LAST overwritten occupant demoted to
        # (the write-back routing table: a sampled row overwritten since
        # its snapshot lives at _demote_ids[row] if anywhere)
        self._demote_ids: List[Optional[int]] = [None] * spec.num_blocks

    def add(self, block: Block) -> int:
        """Ring-write one block (jitted replay_add); demotes the
        overwritten slot's page into the spill tier first. Returns the
        ring slot the block landed in."""
        from r2d2_tpu.replay.device_replay import replay_add
        learning = int(np.asarray(block.learning_steps).sum())
        wv = int(np.asarray(block.weight_version))
        trace = block.trace_ms
        if trace is not None:
            trace = int(np.asarray(trace))
        slot = self.ring.ptr
        if self._retain:
            block = _host_block(block)
            old = self._resident[slot]
            if old is not None and self.ring.slot_steps[slot] > 0:
                self._demote_ids[slot] = self.spill.demote(*old)
        # The device programs (and their AOT add_many avals) never see
        # the lineage leaf — it lives in the ring accountant's host
        # mirrors; the _resident page keeps the stamped block so spill
        # demote/promote and snapshots carry lineage for free.
        dev = block if trace is None else block.replace(trace_ms=None)
        self.state = replay_add(self.spec, self.state, dev)
        if trace is None:
            self.ring.advance(learning, wv)
        else:
            from r2d2_tpu.telemetry.tracing import now_ms
            self.ring.advance(learning, wv, trace_ms=trace,
                              ingest_ms=(now_ms() if trace >= 0 else -1))
        if self._retain:
            self._resident[slot] = (block, learning, wv)
        return slot

    def add_group(self, blocks: List[Block], get_exe,
                  max_chunk: int) -> Tuple[int, float, float]:
        """Commit a routed group through ``replay_add_many`` in chunks:
        ``max_chunk`` when enough blocks remain, else the largest pow2
        that fits (every size AOT-precompiled at service start; a chunk
        of 1 routes through :meth:`add` — program identity with the
        per-block path). Bit-parity with len(blocks) sequential adds
        holds because a chunk's ring rows ``(ptr + j) % n`` are DISTINCT
        (chunks never exceed num_blocks), so the per-slot demotion
        reads/writes and the spill tier's LRU insertion order are
        exactly the sequential ones, and ``replay_add_many`` is pinned
        bit-identical to sequential ``replay_add`` (PR 2,
        tests/test_service_ingest.py). Returns (dispatches, stage
        seconds, commit seconds) for the ingest telemetry."""
        import jax
        dispatches, stage_s, commit_s = 0, 0.0, 0.0
        n = self.spec.num_blocks
        i, total = 0, len(blocks)
        while i < total:
            rem = total - i
            k = max_chunk if rem >= max_chunk else 1 << (rem.bit_length() - 1)
            if k == 1:
                t0 = time.perf_counter()
                self.add(blocks[i])
                commit_s += time.perf_counter() - t0
                dispatches += 1
                i += 1
                continue
            chunk = blocks[i:i + k]
            t0 = time.perf_counter()
            if self._retain:
                chunk = [_host_block(b) for b in chunk]
            metas = []
            dev_chunk = []
            for b in chunk:
                t = b.trace_ms
                t = int(np.asarray(t)) if t is not None else None
                metas.append((int(np.asarray(b.learning_steps).sum()),
                              int(np.asarray(b.weight_version)), t))
                # strip the lineage leaf before stacking: the AOT
                # add_many avals are built traceless (see add())
                dev_chunk.append(b if t is None else b.replace(trace_ms=None))
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]), *dev_chunk)
            t1 = time.perf_counter()
            slots = [(self.ring.ptr + j) % n for j in range(k)]
            if self._retain:
                for slot in slots:
                    old = self._resident[slot]
                    if old is not None and self.ring.slot_steps[slot] > 0:
                        self._demote_ids[slot] = self.spill.demote(*old)
            self.state = get_exe(k)(self.state, stacked)
            for learning, wv, t in metas:
                if t is None:
                    self.ring.advance(learning, wv)
                else:
                    from r2d2_tpu.telemetry.tracing import now_ms
                    self.ring.advance(learning, wv, trace_ms=t,
                                      ingest_ms=(now_ms() if t >= 0 else -1))
            if self._retain:
                for slot, blk, (learning, wv, _t) in zip(slots, chunk, metas):
                    self._resident[slot] = (blk, learning, wv)
            t2 = time.perf_counter()
            stage_s += t1 - t0
            commit_s += t2 - t1
            dispatches += 1
            i += k
        return dispatches, stage_s, commit_s

    def promote(self, n: int, by_priority: bool = False) -> int:
        """Rotate up to ``n`` spilled pages back into the device ring
        (each re-entry demotes whatever it overwrites — the ring cycles
        through the spilled set). ``by_priority`` pops the
        highest-priority page (ISSUE 16 prefetch order) instead of the
        LRU end. Returns pages promoted."""
        done = 0
        for _ in range(max(n, 0)):
            page = (self.spill.promote_best() if by_priority
                    else self.spill.promote_next())
            if page is None:
                break
            self.add(page[0])
            done += 1
        return done

    def sample(self, key):
        from r2d2_tpu.replay.device_replay import replay_sample
        return replay_sample(self.spec, self.state, key)

    def update_priorities(self, idxes, td_errors) -> None:
        from r2d2_tpu.replay.device_replay import replay_update_priorities
        self.state = replay_update_priorities(self.spec, self.state,
                                              idxes, td_errors)

    @property
    def live_blocks(self) -> int:
        return sum(1 for s in self.ring.slot_steps if s > 0)

    @property
    def fill(self) -> float:
        cap = self.spec.num_blocks * self.spec.block_length
        return round(self.ring.buffer_steps / cap, 4) if cap else 0.0


_ROUTES = ("round_robin", "lane")


class ReplayService:
    """N addressable replay shards behind one producer/consumer
    interface, with the accountant facade the Learner's gate/metrics
    read (``buffer_steps`` / ``total_adds`` / ``live_versions``) so a
    service-backed learner needs no second accounting path."""

    def __init__(self, spec: ReplaySpec, num_shards: int,
                 spill_blocks: int = 0, route: str = "round_robin",
                 promote_per_sample: int = 1,
                 ingest_batch_blocks: int = 1,
                 spill_prefetch: bool = False,
                 tier_stats: bool = False):
        if num_shards < 1:
            raise ValueError(f"num_shards ({num_shards}) must be >= 1")
        if route not in _ROUTES:
            raise ValueError(f"route {route!r} must be one of {_ROUTES}")
        self.spec = spec
        self.num_shards = num_shards
        self.route = route
        self.promote_per_sample = promote_per_sample
        self.spill_prefetch = bool(spill_prefetch)
        # per-tier telemetry (ISSUE 19 satellite, ROADMAP 4d): gated so
        # legacy `replay_service` record blocks stay byte-identical
        self.tier_stats = bool(tier_stats)
        self.ingest_k = max(int(ingest_batch_blocks), 1)
        self.shards = [ReplayShard(spec, s, spill_blocks=spill_blocks)
                       for s in range(num_shards)]
        self._rr_add = 0
        self._rr_sample = 0
        self._lock = threading.Lock()   # socket drain thread vs learner
        # priority write-backs dropped by the staleness guard (a remote
        # producer's add landed between a sample and its write-back and
        # overwrote a sampled row) — surfaced in the telemetry block
        self.stale_writebacks = 0
        # ISSUE 16: write-back rows routed to spilled pages / dropped
        # because their page was already gone (evicted or promoted)
        self.spilled_writebacks = 0
        self.stale_rows_dropped = 0
        # grouped-ingest dispatch plane: AOT executables per chunk size,
        # compiled at service start so the first burst never pays a
        # mid-run XLA compile (the stager lesson, learner_loop PR 2)
        self._max_chunk = min(self.ingest_k, spec.num_blocks)
        self._add_many_cache: Dict[int, object] = {}
        if self.ingest_k > 1:
            for kb in self._aot_chunk_sizes():
                self._add_many_cache[kb] = self._compile_add_many(kb)
        # per-interval ingest counters: blocks, dispatches, stage s,
        # commit s (reset on interval_block read) + the backlog gauge
        self._ingest_iv = [0, 0, 0.0, 0.0]
        self._backlog = 0
        # async spill prefetch (ISSUE 16): shard indices awaiting a
        # priority-ordered promotion pass, drained by a lazy-started
        # background thread kicked at write-back time
        self._prefetch_pending: set = set()
        self._prefetch_event = threading.Event()
        self._prefetch_stop = threading.Event()
        self._prefetch_thread: Optional[threading.Thread] = None
        self._prefetch_iv = 0
        self._prefetch_popped = 0
        self._prefetch_done = 0

    # -- grouped-ingest dispatch plane (ISSUE 16) --

    def _aot_chunk_sizes(self) -> List[int]:
        """Every pow2 chunk below the configured group size PLUS the
        group size itself (the steady-state chunk under load) — the
        stager's bucket rule (learner_loop._aot_bucket_sizes) applied
        to the service's commit plane. Size 1 is excluded: it routes
        through the already-jitted per-block ``replay_add``."""
        sizes, kb = [], 2
        while kb < self._max_chunk:
            sizes.append(kb)
            kb *= 2
        if self._max_chunk > 1:
            sizes.append(self._max_chunk)
        return sizes

    def _compile_add_many(self, kb: int):
        """Lower + AOT-compile the donated add_many executable for chunk
        size ``kb``, deriving block avals from the authoritative record
        layout (empty_block_np) — the learner stager's one lowering
        recipe, aimed at the shard-sized spec."""
        import jax

        from r2d2_tpu.replay.device_replay import replay_add_many
        from r2d2_tpu.replay.structs import empty_block_np
        proto = empty_block_np(self.spec)
        blocks = Block(**{
            name: jax.ShapeDtypeStruct((kb,) + arr.shape, arr.dtype)
            for name, arr in proto.items()})
        state_avals = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self.shards[0].state)
        return replay_add_many.lower(self.spec, state_avals,
                                     blocks).compile()

    def _exe_for(self, k: int):
        exe = self._add_many_cache.get(k)
        if exe is None:     # defensive: an un-precompiled odd size
            exe = self._compile_add_many(k)
            self._add_many_cache[k] = exe
        return exe

    def aot_chunk_coverage(self) -> dict:
        """Precompiled chunk sizes vs the expected set — the compile
        observability hook (mirrors Learner.aot_coverage)."""
        expected = self._aot_chunk_sizes()
        return {"expected": expected,
                "compiled": sorted(self._add_many_cache),
                "complete": all(k in self._add_many_cache
                                for k in expected)}

    # -- producer side --

    def route_shard(self, block: Block) -> int:
        """The shard key: lane-provenance routing when configured and
        the block is stamped; the dp path's round-robin otherwise."""
        if self.route == "lane":
            lane = int(np.asarray(block.lane))
            if lane >= 0:
                return lane % self.num_shards
        shard = self._rr_add
        self._rr_add = (self._rr_add + 1) % self.num_shards
        return shard

    def add_block(self, block: Block) -> int:
        """Route + ring-write one block; returns the shard it landed in."""
        with self._lock:
            shard = self.route_shard(block)
            self.shards[shard].add(block)
            return shard

    def add_blocks(self, blocks: List[Block]) -> List[int]:
        """Route + commit a group of blocks. With ``ingest_batch_blocks``
        > 1 (ISSUE 16) the group is routed in arrival order (the
        round-robin counter advances exactly as sequential add_block
        calls would), grouped by shard, and each per-shard run commits
        through the AOT ``replay_add_many`` chunks — bit-identical
        contents, one dispatch per chunk instead of per block. At the
        default 1 this IS the sequential loop (byte-identity with
        PR 15). Returns the routed shard per block, in input order."""
        if self.ingest_k <= 1 or len(blocks) <= 1:
            return [self.add_block(b) for b in blocks]
        with self._lock:
            t0 = time.perf_counter()
            routed = [self.route_shard(b) for b in blocks]
            groups: "OrderedDict[int, List[Block]]" = OrderedDict()
            for shard, block in zip(routed, blocks):
                groups.setdefault(shard, []).append(block)
            stage_s = time.perf_counter() - t0
            dispatches, commit_s = 0, 0.0
            for shard, group in groups.items():
                d, s, c = self.shards[shard].add_group(
                    group, self._exe_for, self._max_chunk)
                dispatches += d
                stage_s += s
                commit_s += c
            self._ingest_iv[0] += len(blocks)
            self._ingest_iv[1] += dispatches
            self._ingest_iv[2] += stage_s
            self._ingest_iv[3] += commit_s
            return routed

    def note_backlog(self, queued_blocks: int) -> None:
        """Record the producer-side queue depth observed at the last
        drain — the ``ingest_backlog`` alert's gauge (negative = the
        transport can't report a depth; kept at 0)."""
        self._backlog = max(int(queued_blocks), 0)

    # -- consumer side --

    def sample(self, key) -> Tuple[object, int, int]:
        """One prioritized batch from the next non-empty shard
        (round-robin over shards, the dp learner's per-shard sampling
        order flattened). Spill promotion happens HERE, before the tree
        descent, so the returned ``idxes`` stay valid for the caller's
        priority write-back as long as no add interleaves — unless
        ``spill_prefetch`` moved promotion to the async write-back-time
        pass (ISSUE 16), in which case the sample path is exactly
        ``replay_sample``. Returns (SampleBatch, shard_index,
        adds_snapshot) — the snapshot is the write-back staleness
        token: the single-threaded in-proc loop never moves it, but a
        SOCKET producer's add (or an async promotion) can land between
        sample and write-back, and the guard in
        :meth:`update_priorities` uses it to refuse writing the old
        batch's priorities onto a row a new block just took."""
        with self._lock:
            for _ in range(self.num_shards):
                shard = self.shards[self._rr_sample]
                self._rr_sample = (self._rr_sample + 1) % self.num_shards
                if shard.ring.total_adds == 0:
                    continue
                if self.promote_per_sample > 0 and not self.spill_prefetch:
                    shard.promote(self.promote_per_sample)
                return (shard.sample(key), shard.index,
                        shard.ring.total_adds)
        raise RuntimeError("ReplayService.sample on an empty service — "
                           "gate on all_shards_nonempty first")

    def trace_lookup(self, shard: int, idxes) -> List[Tuple[int, int]]:
        """Lineage stamps for one sampled batch (ISSUE 19): the (emit_ms,
        ingest_ms) pair of every traced row's ring slot. Rows whose slot
        was never stamped (untraced run, stamp overwritten, promoted
        page) are simply absent — the trace is a sampled signal, not an
        accounting invariant."""
        sh = self.shards[shard]
        spb = self.spec.seqs_per_block
        out: List[Tuple[int, int]] = []
        with self._lock:
            ring = sh.ring
            for idx in np.asarray(idxes).reshape(-1):
                slot = int(idx) // spb
                if 0 <= slot < ring.num_blocks and ring.slot_trace[slot] >= 0:
                    out.append((int(ring.slot_trace[slot]),
                                int(ring.slot_ingest_ms[slot])))
        return out

    def _update_one(self, sh: ReplayShard, idxes, td_errors,
                    adds_snapshot: Optional[int]) -> None:
        """One write-back under the held lock, with the PR-14 staleness
        guard extended to route stale rows to spilled pages (ROADMAP
        4a): a sampled row overwritten since its snapshot was — with the
        spill tier on — demoted to a known page (``_demote_ids``), so
        its new |TD| is written into the page's stored priorities
        instead of being dropped; the remaining fresh rows are applied
        through the SAME-SHAPE program (stale positions padded with a
        duplicate of a fresh entry — an identical-value scatter, so the
        result is deterministic and no per-count recompile exists).
        Without the tier the PR-14 whole-batch drop is preserved
        exactly."""
        if adds_snapshot is not None:
            delta = sh.ring.total_adds - adds_snapshot
            if delta > 0:
                n = sh.spec.num_blocks
                if delta >= n:
                    self.stale_writebacks += 1
                    return      # the whole ring turned over
                ptr0 = adds_snapshot % n
                overwritten = {(ptr0 + j) % n for j in range(delta)}
                spb = sh.spec.seqs_per_block
                idxes_np = np.asarray(idxes)
                rows = idxes_np // spb
                stale = np.array([int(r) in overwritten for r in rows])
                if stale.any():
                    if not sh._retain:
                        self.stale_writebacks += 1
                        return
                    td_np = np.asarray(td_errors)
                    for i in np.nonzero(stale)[0]:
                        slot = int(rows[i])
                        seq = int(idxes_np[i]) % spb
                        pid = sh._demote_ids[slot]
                        if pid is not None and sh.spill.write_back(
                                pid, seq, abs(float(td_np[i]))):
                            self.spilled_writebacks += 1
                        else:
                            self.stale_rows_dropped += 1
                    fresh = np.nonzero(~stale)[0]
                    if fresh.size == 0:
                        return
                    sel = np.where(stale, fresh[0],
                                   np.arange(idxes_np.shape[0]))
                    sh.update_priorities(idxes_np[sel], td_np[sel])
                    return
        sh.update_priorities(idxes, td_errors)

    def update_priorities(self, shard: int, idxes, td_errors,
                          adds_snapshot: Optional[int] = None) -> None:
        """Write learner priorities back to ``shard``. With
        ``adds_snapshot`` (the token :meth:`sample` returned), rows
        overwritten by an add since the sample are guarded: dropped
        whole-batch without the spill tier (counted in
        ``stale_writebacks`` — the reference worker's ring-pointer
        staleness guard), routed to their spilled pages with it
        (``spilled_writebacks``; see :meth:`_update_one`)."""
        with self._lock:
            self._update_one(self.shards[shard], idxes, td_errors,
                             adds_snapshot)
        self._kick_prefetch(shard)

    def update_priorities_group(
            self, shard: int,
            entries: List[Tuple[object, object, Optional[int]]]) -> None:
        """Apply a batch of write-backs to ONE shard under a single lock
        acquisition (the service stager's grouped write-back path).
        Entries — (idxes, td_errors, adds_snapshot) — apply
        SEQUENTIALLY, each with its own snapshot guard: concatenating
        would change the update program's batch shape per group size
        (a recompile per count) and reorder guard decisions; grouping
        here buys the lock/dispatch locality, not a fused scatter."""
        with self._lock:
            sh = self.shards[shard]
            for idxes, td_errors, adds_snapshot in entries:
                self._update_one(sh, idxes, td_errors, adds_snapshot)
        self._kick_prefetch(shard)

    # -- async spill prefetch (ISSUE 16) --

    def _kick_prefetch(self, shard: int) -> None:
        """Queue a priority-ordered promotion pass for ``shard`` on the
        service-owned background thread (lazy-started). Called at
        write-back time — the natural moment: the learner just finished
        a batch, so promotion latency lands OFF the sample path."""
        if not self.spill_prefetch or self.promote_per_sample <= 0:
            return
        self._prefetch_pending.add(shard)
        if self._prefetch_thread is None:
            self._prefetch_thread = threading.Thread(
                target=self._prefetch_loop, daemon=True,
                name="replay-svc-prefetch")
            self._prefetch_thread.start()
        self._prefetch_event.set()

    def _prefetch_loop(self) -> None:
        while not self._prefetch_stop.is_set():
            if not self._prefetch_event.wait(timeout=0.25):
                continue
            self._prefetch_event.clear()
            while self._prefetch_pending and not self._prefetch_stop.is_set():
                shard = self._prefetch_pending.pop()
                self._prefetch_popped += 1
                with self._lock:
                    done = self.shards[shard].promote(
                        self.promote_per_sample, by_priority=True)
                    self._prefetch_iv += done
                self._prefetch_done += 1

    def drain_prefetch(self, timeout: float = 2.0) -> None:
        """Block until the queued prefetch passes have RUN — pending set
        empty AND no pass in flight (``_prefetch_done`` advances only
        after a popped shard's promotion finishes, so a popped-but-not-
        yet-promoted pass can't satisfy the drain). Test and shutdown
        hook; the thread itself is free-running."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self._prefetch_pending \
                    and self._prefetch_done >= self._prefetch_popped:
                return
            time.sleep(0.002)

    def close(self) -> None:
        """Stop the prefetch thread (idempotent; the shards themselves
        hold no threads)."""
        self._prefetch_stop.set()
        self._prefetch_event.set()
        if self._prefetch_thread is not None:
            self._prefetch_thread.join(timeout=2.0)
            self._prefetch_thread = None

    # -- crash-recovery plane (ISSUE 18): durable snapshot delegation --

    def snapshot_state(self, step: int, extra: Optional[dict] = None) -> dict:
        """Consistent host-side cut of every shard (replay/snapshot.py)
        — taken under the service lock at a commit boundary."""
        from r2d2_tpu.replay.snapshot import capture_service
        return capture_service(self, step, extra)

    def restore_state(self, snap: dict) -> None:
        """Load a captured cut back into this (freshly-built) service —
        bit-parity with the captured one."""
        from r2d2_tpu.replay.snapshot import restore_service
        restore_service(self, snap)

    # -- accountant facade (the Learner's ring contract) --

    @property
    def buffer_steps(self) -> int:
        return sum(s.ring.buffer_steps for s in self.shards)

    @property
    def total_adds(self) -> int:
        return sum(s.ring.total_adds for s in self.shards)

    @property
    def all_shards_nonempty(self) -> bool:
        """Per-shard training gate: sampling an empty tree yields NaN
        importance weights (the dp learner's same precondition)."""
        return all(s.ring.total_adds > 0 for s in self.shards)

    def live_versions(self) -> List[int]:
        out: List[int] = []
        for s in self.shards:
            out.extend(s.ring.live_versions())
        return out

    @property
    def live_blocks(self) -> int:
        """Blocks currently samplable OR held in spill — the service's
        effective capacity (the >= 2x-device-ring acceptance reads
        this)."""
        return sum(s.live_blocks + s.spill.occupancy for s in self.shards)

    @property
    def device_ring_blocks(self) -> int:
        return self.num_shards * self.spec.num_blocks

    @property
    def device_bytes(self) -> int:
        return self.num_shards * self.spec.device_ring_bytes

    # -- telemetry --

    def interval_block(self) -> dict:
        """The record's ``replay_service`` shard/spill sub-blocks
        (per-interval spill deltas reset on read). The ISSUE-16 keys —
        the ``ingest`` sub-block and the spill prefetch gauges — appear
        only when their planes are configured on, so a default-knob run
        keeps the PR-15 record byte-identical."""
        fills = [s.fill for s in self.shards]
        interval = {"demotions": 0, "promotions": 0, "evictions": 0,
                    "thrash_frac": None}
        demo = 0
        for s in self.shards:
            iv = s.spill.take_interval()
            interval["demotions"] += iv["demotions"]
            interval["promotions"] += iv["promotions"]
            interval["evictions"] += iv["evictions"]
            demo += iv["demotions"]
        if demo:
            interval["thrash_frac"] = round(
                interval["evictions"] / demo, 4)
        cap = sum(s.spill.capacity for s in self.shards)
        occ = sum(s.spill.occupancy for s in self.shards)
        hits = [s.spill.hit_rate for s in self.shards
                if s.spill.hit_rate is not None]
        spill = {
            "capacity": cap,
            "occupancy": occ,
            "occupancy_frac": (round(occ / cap, 4) if cap else 0.0),
            "hit_rate": (round(float(np.mean(hits)), 4)
                         if hits else None),
            **interval,
        }
        if self.spill_prefetch:
            spill["prefetch"] = True
            spill["prefetch_promotions"] = self._prefetch_iv
            self._prefetch_iv = 0
            spill["spilled_writebacks"] = self.spilled_writebacks
            spill["stale_rows_dropped"] = self.stale_rows_dropped
        if self.tier_stats:
            # ROADMAP 4(d): promotion latency (interval time-in-tier of
            # promoted pages) + bytes resident per tier
            lats = [s.spill.take_promotion_latency() for s in self.shards]
            lats = [l for l in lats if l is not None]
            merged = None
            if lats:
                merged = {
                    "count": sum(l["count"] for l in lats),
                    "p50_ms": round(float(np.median(
                        [l["p50_ms"] for l in lats])), 3),
                    "p95_ms": round(max(l["p95_ms"] for l in lats), 3),
                    "p99_ms": round(max(l["p99_ms"] for l in lats), 3),
                }
            spill["promotion_latency"] = merged
            page_b = next((s.spill.page_bytes for s in self.shards
                           if s.spill.page_bytes), 0)
            spill["tiers"] = {
                "device_bytes": self.device_bytes,
                "spill_bytes": occ * page_b,
                "spill_page_bytes": page_b,
            }
        out = {
            "shards": {
                "n": self.num_shards,
                "route": self.route,
                "fill": fills,
                "fill_min": min(fills),
                "fill_max": max(fills),
                "adds": [s.ring.total_adds for s in self.shards],
                "live_blocks": [s.live_blocks for s in self.shards],
                "stale_writebacks": self.stale_writebacks,
            },
            "spill": spill,
        }
        if self.ingest_k > 1:
            blocks, dispatches, stage_s, commit_s = self._ingest_iv
            self._ingest_iv = [0, 0, 0.0, 0.0]
            out["ingest"] = {
                "batch_blocks": self.ingest_k,
                "blocks": blocks,
                "dispatches": dispatches,
                "blocks_per_dispatch": (round(blocks / dispatches, 2)
                                        if dispatches else None),
                "stage_ms": round(stage_s * 1e3, 3),
                "commit_ms": round(commit_s * 1e3, 3),
                "backlog": self._backlog,
                "spilled_writebacks": self.spilled_writebacks,
                "stale_rows_dropped": self.stale_rows_dropped,
            }
        return out


# ---------------------------------------------------------------------------
# Socket rung: remote producers route blocks into the service over TCP —
# the serve/transport.py frame discipline applied to the experience path.


class ReplayServiceServer:
    """TCP listener feeding a ReplayService: one reader thread per
    producer connection. Two frame dialects share the wire:

      * ``("add", field_dict)`` — PR 15's per-block lockstep, acked
        ``("ack", shard)`` with the shard it landed in (producers can
        assert routing end-to-end);
      * ``("addw", seq, inflight, k, stacked_fields)`` — ISSUE 16's
        windowed rung: one frame carries a K-stacked group (leading
        axis K on every field), committed through
        :meth:`ReplayService.add_blocks` (the grouped dispatch plane)
        and acked ``("ackw", seq, k)`` — CUMULATIVE: an ack for seq
        confirms every frame ≤ seq on that connection (frames process
        in order), so a dropped ack is absorbed by the next one.
        ``("flushw", seq)`` is ALWAYS acked (never subject to the drop
        injection) — the producer's resync point.

    ``drop_ack_every`` > 0 drops every Nth DATA ack (the chaos
    grammar's ``drop_ack@every=N`` injection) to drill the cumulative
    semantics."""

    def __init__(self, service: ReplayService, host: str = "127.0.0.1",
                 port: int = 0, drop_ack_every: int = 0, telemetry=None):
        import socket

        from r2d2_tpu.serve.transport import recv_frame, send_frame
        from r2d2_tpu.telemetry.core import NULL_TELEMETRY
        self._recv_frame, self._send_frame = recv_frame, send_frame
        self.service = service
        # ISSUE 19: a standalone service host passes its process-local
        # Telemetry so ingest commits land as spans on the service
        # process's track in the cross-process Perfetto merge
        self.telemetry = telemetry or NULL_TELEMETRY
        self.drop_ack_every = int(drop_ack_every)
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.25)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._conns: list = []
        self.blocks_received = 0
        self.acks_dropped = 0
        self._stats_lock = threading.Lock()
        # per-interval socket gauges: frames, blocks, max in-flight
        # window occupancy observed (the producer stamps its depth into
        # every addw frame), acks dropped by injection
        self._socket_iv = [0, 0, 0, 0]
        self._data_frames = 0
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="replay-svc-accept")
        self._thread.start()

    def _accept_loop(self) -> None:
        import socket
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(None)
            self._conns.append(conn)
            threading.Thread(target=self._reader_loop, args=(conn,),
                             daemon=True, name="replay-svc-conn").start()

    def _note_frame(self, blocks: int, inflight: int) -> None:
        with self._stats_lock:
            self.blocks_received += blocks
            self._socket_iv[0] += 1
            self._socket_iv[1] += blocks
            self._socket_iv[2] = max(self._socket_iv[2], inflight)

    def _drop_this_ack(self) -> bool:
        if self.drop_ack_every <= 0:
            return False
        with self._stats_lock:
            self._data_frames += 1
            if self._data_frames % self.drop_ack_every == 0:
                self.acks_dropped += 1
                self._socket_iv[3] += 1
                return True
        return False

    def _reader_loop(self, conn) -> None:
        import pickle
        lock = threading.Lock()
        try:
            while not self._stop.is_set():
                frame = self._recv_frame(conn)
                kind = frame[0]
                if kind == "add":
                    _, payload = frame
                    block = Block(**{k: np.asarray(v)
                                     for k, v in payload.items()})
                    shard = self.service.add_block(block)
                    self._note_frame(1, 1)
                    self._send_frame(conn, ("ack", shard), lock)
                elif kind == "addw":
                    _, seq, inflight, k, fields = frame
                    blocks = [Block(**{name: np.asarray(v[i])
                                       for name, v in fields.items()})
                              for i in range(k)]
                    t0 = time.time() if self.telemetry.spans.enabled \
                        else 0.0
                    self.service.add_blocks(blocks)
                    if t0:
                        self.telemetry.record_span(
                            "ingest/commit", t0, time.time(), {"k": k})
                    self._note_frame(k, inflight)
                    if not self._drop_this_ack():
                        self._send_frame(conn, ("ackw", seq, k), lock)
                elif kind == "flushw":
                    _, seq = frame
                    self._send_frame(conn, ("ackw", seq, 0), lock)
        except (ConnectionError, OSError, EOFError, pickle.PickleError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def interval_stats(self) -> dict:
        """Per-interval socket gauges (reset on read) — merged into the
        record's ``replay_service.socket`` sub-block by the
        orchestrator."""
        with self._stats_lock:
            frames, blocks, window_max, dropped = self._socket_iv
            self._socket_iv = [0, 0, 0, 0]
        return {"frames": frames, "blocks": blocks,
                "window_max": window_max, "acks_dropped": dropped,
                "blocks_total": self.blocks_received}

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)


class RemoteReplayProducer:
    """Producer-side socket channel. ``add_block`` is PR 15's lockstep
    rung (one frame, one blocking ack — routing-assertable).
    ``add_blocks`` / ``add_stacked`` are the ISSUE-16 windowed rung: one
    ``addw`` frame per stacked group, up to ``window`` unacked frames in
    flight, cumulative acks reaped at the window bound (back-pressure)
    and on :meth:`flush`.

    Crash-recovery rung (ISSUE 18): the producer DIALS AT CONSTRUCTION
    (a dead address raises there, not at the first add a thousand steps
    later) with a bounded connect retry on the PR-3 backoff ladder
    (``min(base * 2^(attempt-1), max)``) so a producer rank may start
    before the service finishes binding. Each in-flight entry retains
    its serialized frame, so when the service socket dies mid-window the
    producer redials on the same ladder and REPLAYS the unacked tail in
    seq order — frames the dead service committed get re-acked
    cumulatively (server-side commits are ring overwrites, so a
    duplicate from a lost ack is benign), frames it never saw are
    simply delivered to the successor. A service bounce therefore costs
    the producer a counted reconnect, never a crash; what IS lost is
    whatever the service committed after its last snapshot — bounded by
    the snapshot interval, measured by the kill drill."""

    def __init__(self, host: str, port: int, dial_timeout: float = 2.0,
                 window: int = 1, connect_retries: int = 0,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 eager_connect: bool = True):
        self._addr = (host, port)
        self._dial_timeout = dial_timeout
        self.window = max(int(window), 1)
        self.connect_retries = max(int(connect_retries), 0)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._sock = None
        self._lock = threading.Lock()
        self._seq = 0
        # (seq, n_blocks, frame) — frame retained for tail replay; None
        # for flush probes (resync points are connection-local, dropped
        # at reconnect instead of replayed)
        self._inflight: "deque[Tuple[int, int, Optional[tuple]]]" = deque()
        self.frames_sent = 0
        self.blocks_acked = 0
        self.reconnects = 0
        self.blocks_resent = 0
        from r2d2_tpu.serve.transport import recv_frame, send_frame
        self._recv_frame, self._send_frame = recv_frame, send_frame
        if eager_connect:
            self._ensure()

    def _dial(self):
        """One connect attempt per ladder rung; the terminal failure
        re-raises the last refusal (ECONNREFUSED and friends) so a
        misaddressed producer fails with the real error."""
        import socket
        attempt = 0
        while True:
            try:
                s = socket.create_connection(self._addr,
                                             timeout=self._dial_timeout)
                break
            except OSError:
                attempt += 1
                if attempt > self.connect_retries:
                    raise
                time.sleep(min(self.backoff_base_s * (2 ** (attempt - 1)),
                               self.backoff_max_s))
        # Windowed frames interleave large data writes one way with
        # small cumulative acks the other; Nagle holding an ack
        # behind the peer's delayed ACK stalls the pipeline ~40 ms
        # per occurrence. Frames are whole sendall() calls, so
        # nothing is gained by coalescing.
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(self._dial_timeout)
        return s

    def _ensure(self):
        if self._sock is None:
            self._sock = self._dial()
        return self._sock

    def _drop_socket(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _recover(self, timeout: float):
        """Redial on the ladder and replay the unacked tail in seq
        order. Flush probes are dropped from the window first: the old
        connection's resync points have no meaning to the successor,
        and an unreplayed probe would pin the window open forever."""
        self._drop_socket()
        sock = self._ensure()
        sock.settimeout(timeout)
        self.reconnects += 1
        self._inflight = deque(e for e in self._inflight
                               if e[2] is not None)
        for _seq, k, frame in list(self._inflight):
            self._send_frame(sock, frame, self._lock)
            self.blocks_resent += k
        return sock

    def add_block(self, block: Block, timeout: float = 5.0) -> int:
        fields = _block_fields(block)
        frame = ("add", fields)
        try:
            sock = self._ensure()
            sock.settimeout(timeout)
            self._send_frame(sock, frame, self._lock)
            kind, shard = self._recv_frame(sock)
        except (ConnectionError, EOFError, OSError):
            # lockstep rung: nothing windowed is outstanding (any addw
            # tail replays first), so retry this one frame once
            sock = self._recover(timeout)
            self._send_frame(sock, frame, self._lock)
            kind, shard = self._recv_frame(sock)
        if kind != "ack":
            raise ConnectionError(f"unexpected reply kind {kind!r}")
        return int(shard)

    def add_blocks(self, blocks: List[Block], timeout: float = 5.0) -> None:
        """Ship a group of blocks as ONE windowed frame (fields stacked
        on a new leading axis)."""
        if not blocks:
            return
        fields = {name: np.stack([np.asarray(getattr(b, name))
                                  for b in blocks])
                  for name in blocks[0].__dataclass_fields__
                  if getattr(blocks[0], name) is not None}
        self._send_windowed(fields, len(blocks), timeout)

    def add_stacked(self, stacked: Block, k: int,
                    timeout: float = 5.0) -> None:
        """Ship an already-stacked group (leading axis ``k`` on every
        field — feeder.BlockQueue.drain_stacked's native layout, so the
        shm fast path reaches the wire without restacking)."""
        if k <= 0:
            return
        self._send_windowed(_block_fields(stacked), k, timeout)

    def _send_windowed(self, fields, k: int, timeout: float) -> None:
        self._seq += 1
        frame = ("addw", self._seq, len(self._inflight), k, fields)
        self._inflight.append((self._seq, k, frame))
        self.frames_sent += 1
        try:
            sock = self._ensure()
            sock.settimeout(timeout)
            self._send_frame(sock, frame, self._lock)
        except (ConnectionError, EOFError, OSError):
            sock = self._recover(timeout)   # replays the tail incl. this
        while len(self._inflight) >= self.window:
            self._await_ack(sock, timeout)

    def _await_ack(self, sock, timeout: float = 5.0) -> None:
        """Reap one cumulative ack: pops every in-flight frame ≤ the
        acked seq (a dropped ack is covered by the next). On a recv
        timeout a flush probe is sent once — the server always acks
        flushes, so a window stalled behind a dropped final ack
        self-heals instead of deadlocking. A dead socket recovers via
        tail replay and the reap resumes on the new connection."""
        import socket as _socket
        if self._sock is not None:
            # a _recover inside an earlier reap replaced the socket; the
            # caller's loop still holds the corpse — prefer the live one
            sock = self._sock
        try:
            try:
                frame = self._recv_frame(sock)
            except _socket.timeout:
                self._seq += 1
                self._send_frame(sock, ("flushw", self._seq), self._lock)
                self._inflight.append((self._seq, 0, None))
                frame = self._recv_frame(sock)
        except (ConnectionError, EOFError, OSError):
            sock = self._recover(timeout)
            if not self._inflight:
                return
            self._seq += 1
            probe = ("flushw", self._seq)
            self._send_frame(sock, probe, self._lock)
            self._inflight.append((self._seq, 0, None))
            frame = self._recv_frame(sock)
        kind, seq, _k = frame
        if kind != "ackw":
            raise ConnectionError(f"unexpected reply kind {kind!r}")
        while self._inflight and self._inflight[0][0] <= seq:
            _, nblocks, _frame = self._inflight.popleft()
            self.blocks_acked += nblocks

    def flush(self, timeout: float = 5.0) -> int:
        """Drain the in-flight window: one always-acked flush frame,
        then reap until empty. Returns cumulative blocks acked."""
        if self._sock is not None or self._inflight:
            try:
                sock = self._ensure()
                sock.settimeout(timeout)
                self._seq += 1
                self._send_frame(sock, ("flushw", self._seq), self._lock)
                self._inflight.append((self._seq, 0, None))
            except (ConnectionError, EOFError, OSError):
                sock = self._recover(timeout)
                if self._inflight:
                    self._seq += 1
                    self._send_frame(sock, ("flushw", self._seq),
                                     self._lock)
                    self._inflight.append((self._seq, 0, None))
            while self._inflight:
                self._await_ack(sock, timeout)
        return self.blocks_acked

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    def close(self) -> None:
        self._drop_socket()
        self._inflight.clear()


class ReplayProducerPump:
    """Producer-side emit pump: drains an actor fleet's BlockQueue in
    stacked groups (``drain_stacked`` — the shm transport stacks
    natively, mp/thread queues fall back to np.stack) and ships each
    group as one windowed frame through a :class:`RemoteReplayProducer`.
    This is the socket rung's feeder half for a producer-only host
    (parallel/multihost.run_replay_producer): the actors never learn
    that replay is remote — they emit into the same queue, the pump
    turns queue depth into frames."""

    def __init__(self, queue, producer: RemoteReplayProducer,
                 group: int = 8, idle_sleep_s: float = 0.002):
        self.queue = queue
        self.producer = producer
        self.group = max(int(group), 1)
        self.idle_sleep_s = idle_sleep_s
        self.blocks_sent = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def pump_once(self) -> int:
        """Drain up to one group and ship it; returns blocks shipped
        (0 = the queue was empty)."""
        stacked, k = self.queue.drain_stacked(self.group)
        if k == 0:
            return 0
        if k == 1 and self.producer.window <= 1:
            # degenerate shape: the lockstep rung's exact cadence
            import jax
            block = jax.tree_util.tree_map(lambda x: np.asarray(x)[0],
                                           stacked)
            self.producer.add_block(block)
        else:
            self.producer.add_stacked(stacked, k)
        self.blocks_sent += k
        return k

    def run(self, stop: Optional[threading.Event] = None,
            seconds: Optional[float] = None) -> int:
        """Pump until ``stop`` is set (and the queue is drained) or
        ``seconds`` elapse; flushes the window on exit. Returns blocks
        shipped."""
        stop = stop or self._stop
        deadline = (time.monotonic() + seconds) if seconds else None
        while True:
            n = self.pump_once()
            if deadline is not None and time.monotonic() >= deadline:
                break
            if n == 0:
                if stop.is_set():
                    break
                time.sleep(self.idle_sleep_s)
        self.producer.flush()
        return self.blocks_sent

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="replay-producer-pump")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def _block_fields(block: Block) -> Dict[str, np.ndarray]:
    """Block → {field: numpy} for the socket frame (flax PyTreeNodes
    expose their fields through __dataclass_fields__)."""
    return {name: np.asarray(getattr(block, name))
            for name in block.__dataclass_fields__
            if getattr(block, name) is not None}
