"""Disaggregated replay service (ISSUE 15 tentpole, plane a).

The dp-sharded device replay (parallel/sharded.py) bound N replay rings
to N mesh shards inside ONE shard_map program — producers and consumers
were the same fused loop. This module generalizes that layout into N
ADDRESSABLE shards behind one :class:`ReplayService` interface: any
producer routes blocks by shard key (the same jitted
``replay_add``/``replay_add_many`` ring-writes), any consumer draws
prioritized sample batches (``replay_sample``) and writes priorities
back (``replay_update_priorities``) — so the replay plane no longer
assumes producers, consumers, and storage share a process, a mesh, or a
lifetime.

Capacity scales past the HBM budget through a host-RAM **spill tier**:
when a device ring-write overwrites a live block, the overwritten
block's host page is DEMOTED into an LRU page store instead of being
destroyed; pages are RE-PROMOTED into the samplable device ring at
sample time (``spill_promote_per_sample`` pages rotated per sample
call), so spilled experience cycles back through the prioritized tree
rather than being lost. With the spill tier cold (empty) the sample
path is exactly ``replay_sample`` on the device state — parity with the
in-mesh path is program identity, not a tolerance argument
(tests/test_elastic.py).

Routing policies:

  * ``"round_robin"`` — block k lands in shard ``k % num_shards``:
    EXACTLY the dp-sharded path's feeding order, which is what the
    service-vs-in-mesh parity test pins bit-for-bit.
  * ``"lane"`` — shard = ``block.lane % num_shards`` (the PR-10 ε-lane
    provenance stamp): a producer's blocks land in a shard determined
    by its lane identity, so shard contents are provenance-checkable
    (the churn drill's acceptance) and an elastic joiner adopting a
    slot's lane range adopts its replay routing with it. Unstamped
    blocks (lane −1) fall back to round-robin.

The transport ladder follows serve/transport.py's shape: in-proc
producers call :meth:`ReplayService.add_block` directly;
:class:`ReplayServiceServer` / :class:`RemoteReplayProducer` are the
cross-host socket rung (length-prefixed-pickle frames, one connection
per producer) feeding the same routing.
"""

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from r2d2_tpu.replay.structs import Block, ReplaySpec, RingAccountant


def _host_block(block: Block) -> Block:
    """Materialize a block's leaves as host numpy arrays (the spill tier
    stores pages in host RAM; feeder-queue blocks already are numpy, so
    this is a cheap view in the common case)."""
    import jax
    return jax.tree_util.tree_map(lambda x: np.asarray(x), block)


class SpillTier:
    """Host-RAM LRU page store for blocks demoted from a device ring.

    A page is one block record (host numpy) plus its accounting meta.
    ``demote`` inserts at the MRU end and drops the LRU page when the
    tier is full (an ``eviction`` — that experience is now truly gone,
    like a pre-service ring overwrite); ``promote_next`` pops the LRU
    page for re-insertion into the device ring (a ``hit``: the page made
    it back into the samplable set). ``hit_rate`` is therefore the share
    of demoted pages that returned to the ring rather than falling off
    the end — the spill tier's usefulness gauge; ``thrash_frac`` (the
    per-interval eviction/demotion ratio in :meth:`take_interval`) is
    the ``spill_thrash`` alert's signal: near 1.0 the ring is turning
    over so fast the tier is a pure write-through loss."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._pages: "OrderedDict[int, tuple]" = OrderedDict()
        self._next_id = 0
        self.demotions = 0
        self.promotions = 0
        self.evictions = 0
        self._interval = [0, 0, 0]   # demotions, promotions, evictions

    @property
    def occupancy(self) -> int:
        return len(self._pages)

    def demote(self, block: Block, learning: int, weight_version: int) -> bool:
        """Insert one demoted page; returns False when the tier is
        disabled (capacity 0 — the page is simply lost, the pre-service
        overwrite semantics)."""
        if self.capacity <= 0:
            return False
        self._pages[self._next_id] = (block, int(learning),
                                      int(weight_version))
        self._next_id += 1
        self.demotions += 1
        self._interval[0] += 1
        if len(self._pages) > self.capacity:
            self._pages.popitem(last=False)
            self.evictions += 1
            self._interval[2] += 1
        return True

    def promote_next(self) -> Optional[tuple]:
        """Pop the least-recently-demoted page for re-insertion into the
        device ring; None when the tier is empty."""
        if not self._pages:
            return None
        _, page = self._pages.popitem(last=False)
        self.promotions += 1
        self._interval[1] += 1
        return page

    @property
    def hit_rate(self) -> Optional[float]:
        """Cumulative promoted / (promoted + evicted) — None before any
        page has left the tier either way."""
        done = self.promotions + self.evictions
        return round(self.promotions / done, 4) if done else None

    def take_interval(self) -> dict:
        """Per-interval demotion/promotion/eviction deltas (reset on
        read) + the interval thrash fraction for the alert rule."""
        d, p, e = self._interval
        self._interval = [0, 0, 0]
        return {
            "demotions": d, "promotions": p, "evictions": e,
            "thrash_frac": (round(e / d, 4) if d else None),
        }


class ReplayShard:
    """One addressable replay shard: a device ring (the exact jitted
    add/sample/update programs of replay/device_replay.py), its
    RingAccountant, and — when spill is enabled — the host page shadow
    that makes demotion free (the overwritten block's page is already in
    host RAM; no device read-back)."""

    def __init__(self, spec: ReplaySpec, index: int,
                 spill_blocks: int = 0):
        from r2d2_tpu.replay.device_replay import replay_init
        self.spec = spec
        self.index = index
        self.state = replay_init(spec)
        self.ring = RingAccountant(spec.num_blocks)
        self.spill = SpillTier(spill_blocks)
        self._retain = spill_blocks > 0
        # host page per live ring slot (spill mode only): (block,
        # learning, weight_version), the demotion source
        self._resident: List[Optional[tuple]] = [None] * spec.num_blocks

    def add(self, block: Block) -> int:
        """Ring-write one block (jitted replay_add); demotes the
        overwritten slot's page into the spill tier first. Returns the
        ring slot the block landed in."""
        from r2d2_tpu.replay.device_replay import replay_add
        learning = int(np.asarray(block.learning_steps).sum())
        wv = int(np.asarray(block.weight_version))
        slot = self.ring.ptr
        if self._retain:
            block = _host_block(block)
            old = self._resident[slot]
            if old is not None and self.ring.slot_steps[slot] > 0:
                self.spill.demote(*old)
        self.state = replay_add(self.spec, self.state, block)
        self.ring.advance(learning, wv)
        if self._retain:
            self._resident[slot] = (block, learning, wv)
        return slot

    def promote(self, n: int) -> int:
        """Rotate up to ``n`` spilled pages back into the device ring
        (each re-entry demotes whatever it overwrites — the ring cycles
        through the spilled set). Returns pages promoted."""
        done = 0
        for _ in range(max(n, 0)):
            page = self.spill.promote_next()
            if page is None:
                break
            self.add(page[0])
            done += 1
        return done

    def sample(self, key):
        from r2d2_tpu.replay.device_replay import replay_sample
        return replay_sample(self.spec, self.state, key)

    def update_priorities(self, idxes, td_errors) -> None:
        from r2d2_tpu.replay.device_replay import replay_update_priorities
        self.state = replay_update_priorities(self.spec, self.state,
                                              idxes, td_errors)

    @property
    def live_blocks(self) -> int:
        return sum(1 for s in self.ring.slot_steps if s > 0)

    @property
    def fill(self) -> float:
        cap = self.spec.num_blocks * self.spec.block_length
        return round(self.ring.buffer_steps / cap, 4) if cap else 0.0


_ROUTES = ("round_robin", "lane")


class ReplayService:
    """N addressable replay shards behind one producer/consumer
    interface, with the accountant facade the Learner's gate/metrics
    read (``buffer_steps`` / ``total_adds`` / ``live_versions``) so a
    service-backed learner needs no second accounting path."""

    def __init__(self, spec: ReplaySpec, num_shards: int,
                 spill_blocks: int = 0, route: str = "round_robin",
                 promote_per_sample: int = 1):
        if num_shards < 1:
            raise ValueError(f"num_shards ({num_shards}) must be >= 1")
        if route not in _ROUTES:
            raise ValueError(f"route {route!r} must be one of {_ROUTES}")
        self.spec = spec
        self.num_shards = num_shards
        self.route = route
        self.promote_per_sample = promote_per_sample
        self.shards = [ReplayShard(spec, s, spill_blocks=spill_blocks)
                       for s in range(num_shards)]
        self._rr_add = 0
        self._rr_sample = 0
        self._lock = threading.Lock()   # socket drain thread vs learner
        # priority write-backs dropped by the staleness guard (a remote
        # producer's add landed between a sample and its write-back and
        # overwrote a sampled row) — surfaced in the telemetry block
        self.stale_writebacks = 0

    # -- producer side --

    def route_shard(self, block: Block) -> int:
        """The shard key: lane-provenance routing when configured and
        the block is stamped; the dp path's round-robin otherwise."""
        if self.route == "lane":
            lane = int(np.asarray(block.lane))
            if lane >= 0:
                return lane % self.num_shards
        shard = self._rr_add
        self._rr_add = (self._rr_add + 1) % self.num_shards
        return shard

    def add_block(self, block: Block) -> int:
        """Route + ring-write one block; returns the shard it landed in."""
        with self._lock:
            shard = self.route_shard(block)
            self.shards[shard].add(block)
            return shard

    def add_blocks(self, blocks: List[Block]) -> List[int]:
        return [self.add_block(b) for b in blocks]

    # -- consumer side --

    def sample(self, key) -> Tuple[object, int, int]:
        """One prioritized batch from the next non-empty shard
        (round-robin over shards, the dp learner's per-shard sampling
        order flattened). Spill promotion happens HERE, before the tree
        descent, so the returned ``idxes`` stay valid for the caller's
        priority write-back as long as no add interleaves. Returns
        (SampleBatch, shard_index, adds_snapshot) — the snapshot is the
        write-back staleness token: the single-threaded in-proc loop
        never moves it, but a SOCKET producer's add can land between
        sample and write-back, and the guard in
        :meth:`update_priorities` uses it to refuse writing the old
        batch's priorities onto a row a new block just took."""
        with self._lock:
            for _ in range(self.num_shards):
                shard = self.shards[self._rr_sample]
                self._rr_sample = (self._rr_sample + 1) % self.num_shards
                if shard.ring.total_adds == 0:
                    continue
                if self.promote_per_sample > 0:
                    shard.promote(self.promote_per_sample)
                return (shard.sample(key), shard.index,
                        shard.ring.total_adds)
        raise RuntimeError("ReplayService.sample on an empty service — "
                           "gate on all_shards_nonempty first")

    def update_priorities(self, shard: int, idxes, td_errors,
                          adds_snapshot: Optional[int] = None) -> None:
        """Write learner priorities back to ``shard``. With
        ``adds_snapshot`` (the token :meth:`sample` returned), the
        write-back is DROPPED — counted in ``stale_writebacks`` — when
        any sampled row was overwritten by an add since the sample (the
        reference worker's ring-pointer staleness guard, needed here
        only when remote producers feed the service concurrently; the
        drop degrades one batch toward its pre-update priorities, the
        same accepted mode as the host path's backpressure drop)."""
        with self._lock:
            sh = self.shards[shard]
            if adds_snapshot is not None:
                delta = sh.ring.total_adds - adds_snapshot
                if delta > 0:
                    n = sh.spec.num_blocks
                    if delta >= n:
                        self.stale_writebacks += 1
                        return      # the whole ring turned over
                    ptr0 = adds_snapshot % n
                    overwritten = {(ptr0 + j) % n for j in range(delta)}
                    rows = np.asarray(idxes) // sh.spec.seqs_per_block
                    if any(int(r) in overwritten for r in rows):
                        self.stale_writebacks += 1
                        return
            sh.update_priorities(idxes, td_errors)

    # -- accountant facade (the Learner's ring contract) --

    @property
    def buffer_steps(self) -> int:
        return sum(s.ring.buffer_steps for s in self.shards)

    @property
    def total_adds(self) -> int:
        return sum(s.ring.total_adds for s in self.shards)

    @property
    def all_shards_nonempty(self) -> bool:
        """Per-shard training gate: sampling an empty tree yields NaN
        importance weights (the dp learner's same precondition)."""
        return all(s.ring.total_adds > 0 for s in self.shards)

    def live_versions(self) -> List[int]:
        out: List[int] = []
        for s in self.shards:
            out.extend(s.ring.live_versions())
        return out

    @property
    def live_blocks(self) -> int:
        """Blocks currently samplable OR held in spill — the service's
        effective capacity (the >= 2x-device-ring acceptance reads
        this)."""
        return sum(s.live_blocks + s.spill.occupancy for s in self.shards)

    @property
    def device_ring_blocks(self) -> int:
        return self.num_shards * self.spec.num_blocks

    @property
    def device_bytes(self) -> int:
        return self.num_shards * self.spec.device_ring_bytes

    # -- telemetry --

    def interval_block(self) -> dict:
        """The record's ``replay_service`` shard/spill sub-blocks
        (per-interval spill deltas reset on read)."""
        fills = [s.fill for s in self.shards]
        interval = {"demotions": 0, "promotions": 0, "evictions": 0,
                    "thrash_frac": None}
        demo = 0
        for s in self.shards:
            iv = s.spill.take_interval()
            interval["demotions"] += iv["demotions"]
            interval["promotions"] += iv["promotions"]
            interval["evictions"] += iv["evictions"]
            demo += iv["demotions"]
        if demo:
            interval["thrash_frac"] = round(
                interval["evictions"] / demo, 4)
        cap = sum(s.spill.capacity for s in self.shards)
        occ = sum(s.spill.occupancy for s in self.shards)
        hits = [s.spill.hit_rate for s in self.shards
                if s.spill.hit_rate is not None]
        return {
            "shards": {
                "n": self.num_shards,
                "route": self.route,
                "fill": fills,
                "fill_min": min(fills),
                "fill_max": max(fills),
                "adds": [s.ring.total_adds for s in self.shards],
                "live_blocks": [s.live_blocks for s in self.shards],
                "stale_writebacks": self.stale_writebacks,
            },
            "spill": {
                "capacity": cap,
                "occupancy": occ,
                "occupancy_frac": (round(occ / cap, 4) if cap else 0.0),
                "hit_rate": (round(float(np.mean(hits)), 4)
                             if hits else None),
                **interval,
            },
        }


# ---------------------------------------------------------------------------
# Socket rung: remote producers route blocks into the service over TCP —
# the serve/transport.py frame discipline applied to the experience path.


class ReplayServiceServer:
    """TCP listener feeding a ReplayService: one reader thread per
    producer connection; each ``("add", field_dict)`` frame is routed
    through :meth:`ReplayService.add_block` and acked with the shard it
    landed in (producers can assert routing end-to-end)."""

    def __init__(self, service: ReplayService, host: str = "127.0.0.1",
                 port: int = 0):
        import socket

        from r2d2_tpu.serve.transport import recv_frame, send_frame
        self._recv_frame, self._send_frame = recv_frame, send_frame
        self.service = service
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.25)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._conns: list = []
        self.blocks_received = 0
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="replay-svc-accept")
        self._thread.start()

    def _accept_loop(self) -> None:
        import socket
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            self._conns.append(conn)
            threading.Thread(target=self._reader_loop, args=(conn,),
                             daemon=True, name="replay-svc-conn").start()

    def _reader_loop(self, conn) -> None:
        import pickle
        lock = threading.Lock()
        try:
            while not self._stop.is_set():
                kind, payload = self._recv_frame(conn)
                if kind != "add":
                    continue
                block = Block(**{k: np.asarray(v)
                                 for k, v in payload.items()})
                shard = self.service.add_block(block)
                self.blocks_received += 1
                self._send_frame(conn, ("ack", shard), lock)
        except (ConnectionError, OSError, EOFError, pickle.PickleError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)


class RemoteReplayProducer:
    """Producer-side socket channel: ``add_block`` ships one block and
    returns the shard the service routed it to. Lazily (re)dials like
    serve/transport.SocketChannel."""

    def __init__(self, host: str, port: int, dial_timeout: float = 2.0):
        self._addr = (host, port)
        self._dial_timeout = dial_timeout
        self._sock = None
        self._lock = threading.Lock()
        from r2d2_tpu.serve.transport import recv_frame, send_frame
        self._recv_frame, self._send_frame = recv_frame, send_frame

    def _ensure(self):
        import socket
        if self._sock is None:
            s = socket.create_connection(self._addr,
                                         timeout=self._dial_timeout)
            s.settimeout(self._dial_timeout)
            self._sock = s
        return self._sock

    def add_block(self, block: Block, timeout: float = 5.0) -> int:
        fields = _block_fields(block)
        sock = self._ensure()
        sock.settimeout(timeout)
        self._send_frame(sock, ("add", fields), self._lock)
        kind, shard = self._recv_frame(sock)
        if kind != "ack":
            raise ConnectionError(f"unexpected reply kind {kind!r}")
        return int(shard)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


def _block_fields(block: Block) -> Dict[str, np.ndarray]:
    """Block → {field: numpy} for the socket frame (flax PyTreeNodes
    expose their fields through __dataclass_fields__)."""
    return {name: np.asarray(getattr(block, name))
            for name in block.__dataclass_fields__
            if getattr(block, name) is not None}
