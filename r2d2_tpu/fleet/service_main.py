"""Standalone ReplayService host (ISSUE 18, rung b).

PR 15 disaggregated replay into :class:`ReplayService` and PR 16 gave it
the windowed socket rung — but the service always lived INSIDE the
learner process, so "the replay service died" and "the learner died"
were the same failure. This module hosts the service + its socket
listener in a process of its own, which is what makes the restart drill
meaningful: kill THIS process mid-ingest and the producers' reconnect +
tail-replay (RemoteReplayProducer) plus the snapshot restore here must
put the fleet back together with at most one snapshot interval of loss.

Lifecycle:

  * start: build the service exactly the way the Learner does (equal
    device-ring slices per shard off ``ReplaySpec.from_config``), then
    — under ``runtime.resume``-style semantics — reload the durable
    shard snapshot (``replay/snapshot.py``) if one exists, so a
    restarted service comes back with its experience, not empty rings;
  * announce: re-register the listener's address with the fleet lease
    board (``announce_replay``, best-effort) so producers discovering
    through ``info`` dial the survivor;
  * run: periodic snapshots every ``runtime.snapshot_interval``
    COMMITTED BLOCKS (this process has no train-step clock; adds are
    its commit boundary), written through the same async
    :class:`SnapshotWriter` the learner uses;
  * stop (SIGTERM/SIGINT): final synchronous snapshot, close.

The pid is published to ``{save_dir}/replay_service.pid`` (the
--kill-replay-service drill's target).
"""

import logging
import os
import signal
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)


def _pid_path(save_dir: str) -> str:
    return os.path.join(save_dir or ".", "replay_service.pid")


class ReplayServiceHost:
    """One standalone service incarnation: service + socket listener +
    snapshot plane. ``player_idx`` namespaces the snapshot files, so a
    multiplayer deployment runs one host per player stack."""

    def __init__(self, cfg, player_idx: int = 0,
                 host: Optional[str] = None, port: Optional[int] = None):
        import dataclasses

        from r2d2_tpu.fleet.replay_service import (ReplayService,
                                                   ReplayServiceServer)
        from r2d2_tpu.replay.structs import ReplaySpec
        if cfg.fleet.replay_shards < 1:
            raise ValueError(
                "ReplayServiceHost requires fleet.replay_shards >= 1")
        self.cfg = cfg
        self.player_idx = player_idx
        # process identity + clock anchor (ISSUE 19): stamped at
        # construction, refined at lease announcement (the board echoes
        # its wall clock, giving a skew estimate good to ±RTT/2)
        from r2d2_tpu.telemetry.core import Telemetry
        from r2d2_tpu.telemetry.tracing import proc_header
        self.proc = proc_header("replay_service")
        self.telemetry = Telemetry.from_config(cfg, name="replay_service")
        spec = ReplaySpec.from_config(cfg)
        shard_spec = dataclasses.replace(
            spec, num_blocks=spec.num_blocks // cfg.fleet.replay_shards,
            replay_diag=False)
        self.service = ReplayService(
            shard_spec, cfg.fleet.replay_shards,
            spill_blocks=cfg.fleet.spill_blocks,
            route=cfg.fleet.replay_route,
            promote_per_sample=cfg.fleet.spill_promote_per_sample,
            ingest_batch_blocks=cfg.fleet.ingest_batch_blocks,
            spill_prefetch=cfg.fleet.spill_prefetch,
            tier_stats=(cfg.telemetry.enabled
                        and cfg.telemetry.replay_tiers_enabled))
        self.restored_blocks = 0
        self._snap_writer = None
        self._snap_adds = 0
        save_dir = cfg.runtime.save_dir or "."
        if cfg.runtime.snapshot_interval > 0:
            from r2d2_tpu.replay.snapshot import (SnapshotWriter,
                                                  load_snapshot)
            self._snap_writer = SnapshotWriter(save_dir, player_idx)
            snap = load_snapshot(save_dir, player_idx)
            if snap is not None and snap.get("kind") == "service":
                self.service.restore_state(snap)
                self.restored_blocks = self.service.total_adds
                self._snap_adds = self.service.total_adds
                log.warning(
                    "replay service restored %d committed blocks from "
                    "the step-%s snapshot", self.restored_blocks,
                    snap.get("step"))
        self.server = ReplayServiceServer(
            self.service,
            cfg.fleet.service_host if host is None else host,
            cfg.fleet.service_port if port is None else port,
            telemetry=self.telemetry)
        self.announced = self._announce()

    def _announce(self) -> bool:
        """Re-register with the fleet lease board (best-effort: the
        board lives in the orchestrator, which may itself be mid-restart
        — producers then fall back to their configured address and the
        reconnect ladder)."""
        cfg = self.cfg
        if cfg.fleet.lease_transport != "socket":
            return False
        try:
            from r2d2_tpu.fleet.membership import lease_call
            anchor_wall = time.time()
            reply = lease_call(
                cfg.fleet.lease_host, cfg.fleet.lease_port,
                "announce_replay", timeout_s=2.0,
                host=self.server.host, port=self.server.port,
                shards=cfg.fleet.replay_shards,
                step=self.service.total_adds,
                anchor_wall=anchor_wall)
            # ISSUE 19: re-anchor at the announcement instant and keep
            # the board's echo as the skew estimate (±RTT/2) — what the
            # tower join and the Perfetto merge align this plane on
            from r2d2_tpu.telemetry.tracing import proc_header
            self.proc = proc_header("replay_service")
            if reply.get("board_wall") is not None:
                self.proc["clock_anchor"]["offset_est"] = round(
                    anchor_wall - float(reply["board_wall"]), 6)
            return True
        except (OSError, RuntimeError) as e:
            log.info("replay service lease announcement skipped (%s)", e)
            return False

    def maybe_snapshot(self) -> bool:
        """Async snapshot when ``snapshot_interval`` blocks committed
        since the last one; returns True when one was submitted."""
        if self._snap_writer is None:
            return False
        interval = self.cfg.runtime.snapshot_interval
        adds = self.service.total_adds
        if adds - self._snap_adds < interval:
            return False
        t0 = time.time()
        self._snap_writer.submit(
            self.service.snapshot_state(adds))
        self.telemetry.record_span("recovery/snapshot_capture", t0,
                                   time.time(), {"adds": adds})
        self._snap_adds = adds
        return True

    def run(self, max_seconds: Optional[float] = None,
            stop: Optional[threading.Event] = None,
            poll_s: float = 0.1) -> None:
        """Serve until stopped/deadline: the listener threads do the
        ingest work; this loop drives the snapshot cadence and the
        periodic metrics rows (ISSUE 19: one
        ``service_metrics_p{player}.jsonl`` row per log interval, led by
        the process-identity header — the tower join's and the offline
        sentinel's view of this plane)."""
        import json
        stop = stop or threading.Event()
        deadline = time.time() + max_seconds if max_seconds else None
        save_dir = self.cfg.runtime.save_dir or "."
        metrics_path = os.path.join(
            save_dir, f"service_metrics_p{self.player_idx}.jsonl")
        os.makedirs(save_dir, exist_ok=True)
        open(metrics_path, "w").close()
        self.telemetry.start_drain(
            os.path.join(save_dir, "spans_replay_service.jsonl"))
        t0 = time.time()
        last_log = t0

        def write_row(final: bool = False) -> None:
            row = {"t": round(time.time() - t0, 1), "proc": self.proc,
                   "replay_service": {
                       **self.service.interval_block(),
                       "socket": self.server.interval_stats()}}
            if final:
                row["final"] = True
            with open(metrics_path, "a") as f:
                f.write(json.dumps(row) + "\n")

        try:
            while not stop.is_set():
                now = time.time()
                if deadline is not None and now >= deadline:
                    break
                self.maybe_snapshot()
                if now - last_log >= self.cfg.runtime.log_interval:
                    last_log = now
                    write_row()
                time.sleep(poll_s)
        finally:
            write_row(final=True)   # short runs still leave evidence

    def close(self) -> None:
        """Final synchronous snapshot (the process is exiting — nothing
        to protect from the write), then tear down."""
        if self._snap_writer is not None:
            try:
                self._snap_writer.write_now(
                    self.service.snapshot_state(self.service.total_adds))
            finally:
                self._snap_writer.stop()
        self.server.close()
        self.service.close()
        self.telemetry.close()


def run_replay_service(cfg, player_idx: int = 0,
                       max_seconds: Optional[float] = None) -> None:
    """Blocking entry: host the service, snapshot on cadence, write the
    final snapshot on SIGTERM/SIGINT or deadline."""
    host = ReplayServiceHost(cfg, player_idx)
    save_dir = cfg.runtime.save_dir or "."
    os.makedirs(save_dir, exist_ok=True)
    pid_file = _pid_path(save_dir)
    with open(pid_file, "w") as f:
        f.write(str(os.getpid()))
    stop = threading.Event()
    prev = {}
    if threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            stop.set()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):
                pass
    print(f"replay service: {host.server.host}:{host.server.port} "
          f"({cfg.fleet.replay_shards} shard(s), restored "
          f"{host.restored_blocks} block(s))", flush=True)
    try:
        host.run(max_seconds=max_seconds, stop=stop)
    finally:
        host.close()
        try:
            os.remove(pid_file)
        except OSError:
            pass
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass


def main(argv=None) -> None:
    import sys

    from r2d2_tpu.config import Config, parse_overrides
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    argv = list(sys.argv[1:] if argv is None else argv)
    player_idx, max_seconds, rest = 0, None, []
    for arg in argv:
        if arg.startswith("--player="):
            player_idx = int(arg.split("=", 1)[1])
        elif arg.startswith("--max-seconds="):
            max_seconds = float(arg.split("=", 1)[1])
        else:
            rest.append(arg)
    cfg = parse_overrides(Config(), rest)
    run_replay_service(cfg, player_idx, max_seconds=max_seconds)


if __name__ == "__main__":
    main()
