"""Elastic fleet membership (ISSUE 15 tentpole, plane c).

The actor fleet used to be FROZEN at startup: ``actor.num_actors``
workers, each permanently owning slot i's heartbeat row, lane range
``[i*k, (i+1)*k)``, ε-ladder slice, and replay routing. This module
makes the slot table a LEASED resource over the PR-3 heartbeat board:

  * a leaving (clean ``leave_actor``) or killed (``fleet.elastic``
    supervision policy) worker's slot PARKS — its lane range, ε slice,
    and routing key are preserved for re-adoption, and the learner keeps
    training on the remaining fleet;
  * a joining process LEASES a parked (or spare — ``fleet.max_slots`` >
    ``actor.num_actors``) slot mid-training and adopts exactly that
    slot's identity, so lane ranges can never overlap (the churn drill's
    acceptance) and the ε ladder stays fixed as the fleet churns;
  * a leased slot whose worker silently vanished (heartbeat stale past
    the orphan horizon with no supervision verdict) reads as ORPHANED —
    the ``orphaned_slot`` alert's signal, a leaked lease the operator
    must reap.

Leases are arbitrated by the ONE owning supervisor process (the
orchestrator) — joiners go through :meth:`FleetMembership.lease`, never
race on shared state — while LIVENESS stays on the shared-memory
heartbeat board the workers already publish to."""

import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

SLOT_FREE = "free"        # spare capacity, never yet leased
SLOT_ACTIVE = "active"    # leased to a live worker
SLOT_PARKED = "parked"    # left/killed; identity preserved for re-adoption


@dataclass(frozen=True)
class SlotLease:
    """What a joiner adopts: the slot's full identity. ``generation``
    counts adoptions of this slot (0 = the original startup worker), so
    respawn-vs-adoption is distinguishable in logs and tests."""

    slot: int
    generation: int
    lane_base: int
    lanes: int
    shard_key: int

    def lane_range(self):
        return range(self.lane_base, self.lane_base + self.lanes)


class FleetMembership:
    """The slot lease table. ``n_slots`` is the fleet's MAXIMUM width
    (``fleet.max_slots``, default the startup ``actor.num_actors``);
    slots [0, initial_active) start ACTIVE (the startup fleet), the rest
    FREE (spare capacity joiners can claim)."""

    def __init__(self, n_slots: int, envs_per_slot: int = 1,
                 initial_active: Optional[int] = None, num_shards: int = 1):
        self.n_slots = n_slots
        self.envs_per_slot = envs_per_slot
        self.num_shards = max(num_shards, 1)
        active = n_slots if initial_active is None else initial_active
        if not 0 <= active <= n_slots:
            raise ValueError(
                f"initial_active ({active}) must be in [0, {n_slots}]")
        self._state = [SLOT_ACTIVE] * active + \
            [SLOT_FREE] * (n_slots - active)
        self._generation = [0] * n_slots
        self._parked_at = [0.0] * n_slots
        self._park_reason: List[Optional[str]] = [None] * n_slots
        # cumulative churn counters for the telemetry block
        self.leaves = 0
        self.joins = 0

    # -- identity derivation (ONE place: the layout every spawner and
    # vector_lane_epsilons already agree on) --

    def lane_base(self, slot: int) -> int:
        return slot * self.envs_per_slot

    def shard_key(self, slot: int) -> int:
        """The slot's replay-routing key under lane routing: its first
        lane's shard (ReplayService route='lane' sends lane l to shard
        l % num_shards)."""
        return self.lane_base(slot) % self.num_shards

    def generation(self, slot: int) -> int:
        """Adoptions of this slot so far (0 = the startup worker)."""
        return self._generation[slot]

    def lease_of(self, slot: int) -> SlotLease:
        return SlotLease(slot=slot, generation=self._generation[slot],
                         lane_base=self.lane_base(slot),
                         lanes=self.envs_per_slot,
                         shard_key=self.shard_key(slot))

    # -- state machine --

    def state(self, slot: int) -> str:
        return self._state[slot]

    def park(self, slot: int, reason: str = "left") -> None:
        """A worker left or was killed: preserve the slot's identity for
        re-adoption. Idempotent (a leave followed by the supervisor
        observing the corpse must not double-count)."""
        if self._state[slot] == SLOT_PARKED:
            return
        self._state[slot] = SLOT_PARKED
        self._parked_at[slot] = time.time()
        self._park_reason[slot] = reason
        self.leaves += 1

    def lease(self, slot: Optional[int] = None) -> SlotLease:
        """Adopt a slot: the requested one (must be PARKED or FREE), or
        the longest-parked slot, or a FREE spare. Raises when the fleet
        is at full width with nothing parked."""
        if slot is None:
            parked = [(self._parked_at[s], s) for s in range(self.n_slots)
                      if self._state[s] == SLOT_PARKED]
            if parked:
                slot = min(parked)[1]
            else:
                free = [s for s in range(self.n_slots)
                        if self._state[s] == SLOT_FREE]
                if not free:
                    raise RuntimeError(
                        "no parked or free slot to lease — the fleet is "
                        "at full width; raise fleet.max_slots or leave a "
                        "worker first")
                slot = free[0]
        elif self._state[slot] == SLOT_ACTIVE:
            raise RuntimeError(
                f"slot {slot} is ACTIVE — a live worker holds its lease "
                "(leave it first, or lease a parked/free slot)")
        self._state[slot] = SLOT_ACTIVE
        self._generation[slot] += 1
        self._park_reason[slot] = None
        self.joins += 1
        return self.lease_of(slot)

    # -- views --

    def active_slots(self) -> List[int]:
        return [s for s in range(self.n_slots)
                if self._state[s] == SLOT_ACTIVE]

    def parked_slots(self) -> List[int]:
        return [s for s in range(self.n_slots)
                if self._state[s] == SLOT_PARKED]

    def assert_no_overlap(self) -> None:
        """Every active slot's lane range must be disjoint — the churn
        drill's structural acceptance. Lane ranges derive from the slot
        index, so overlap is impossible UNLESS a lease was duplicated;
        this asserts the lease table itself is consistent."""
        seen = set()
        for s in self.active_slots():
            lanes = set(self.lease_of(s).lane_range())
            if lanes & seen:
                raise AssertionError(
                    f"lane-range overlap at slot {s}: {sorted(lanes & seen)}")
            seen |= lanes

    def orphaned(self, heartbeat_ages, horizon_s: float) -> int:
        """Leased (ACTIVE) slots whose heartbeat went stale past the
        orphan horizon: the worker vanished without the supervisor
        parking the slot — a leaked lease (the ``orphaned_slot``
        signal). ``heartbeat_ages`` is the board's per-slot age array
        (may be shorter than n_slots on a legacy-sized board)."""
        if horizon_s <= 0 or heartbeat_ages is None:
            return 0
        count = 0
        for s in self.active_slots():
            if s < len(heartbeat_ages) and \
                    float(heartbeat_ages[s]) > horizon_s:
                count += 1
        return count

    def snapshot(self, heartbeat_ages=None,
                 orphan_horizon_s: float = 0.0) -> dict:
        """The record's ``membership`` sub-block."""
        return {
            "slots": self.n_slots,
            "active": len(self.active_slots()),
            "parked": len(self.parked_slots()),
            "free": sum(1 for s in self._state if s == SLOT_FREE),
            "joins": self.joins,
            "leaves": self.leaves,
            "orphaned": self.orphaned(heartbeat_ages, orphan_horizon_s),
        }


class MembershipServer:
    """The fleet lease API over TCP (ROADMAP 2c; gated on
    ``fleet.lease_transport == "socket"``): a fresh process —
    ``cli/join.py`` — dials the supervisor and asks it to admit an acting
    worker (the same :meth:`PlayerStack.join_actor` slot-adoption path
    the in-process join schedule uses) or to grow/shrink the serving
    fleet (ISSUE 17). Leases stay arbitrated by the ONE owning
    supervisor; this is a remote-procedure face on it, not a second
    arbiter.

    Wire discipline: the serving plane's length-prefixed pickle frames
    (serve/transport.py ``send_frame``/``recv_frame``) — one request
    dict ``{"op": ..., **kwargs}`` per frame, one reply dict
    ``{"ok": bool, ...}`` back. Connections are served concurrently;
    handlers run on the connection thread, so the callables passed in
    must be safe to call off the training thread (join_actor and the
    fleet grow/shrink are — they only touch supervisor-owned state)."""

    def __init__(self, handlers: Dict[str, Callable],
                 host: str = "127.0.0.1", port: int = 0):
        self._handlers = dict(handlers)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._accept = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="lease-accept")
        self._accept.start()

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.25)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="lease-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        from r2d2_tpu.serve.transport import recv_frame, send_frame
        lock = threading.Lock()
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                req = recv_frame(conn)
                op = req.get("op")
                handler = self._handlers.get(op)
                if handler is None:
                    reply = {"ok": False,
                             "error": f"unknown op {op!r} (have "
                                      f"{sorted(self._handlers)})"}
                else:
                    try:
                        kwargs = {k: v for k, v in req.items() if k != "op"}
                        reply = {"ok": True, **(handler(**kwargs) or {})}
                    except Exception as e:     # surfaces to the dialer
                        reply = {"ok": False, "error": str(e)}
                send_frame(conn, reply, lock)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept.join(timeout=2.0)


def lease_call(host: str, port: int, op: str, timeout_s: float = 10.0,
               **kwargs) -> dict:
    """One round-trip against a :class:`MembershipServer`: dial, send
    ``{"op": op, **kwargs}``, return the reply dict. Raises
    ``RuntimeError`` with the server's message when the op failed —
    callers never have to inspect ``ok`` themselves."""
    from r2d2_tpu.serve.transport import recv_frame, send_frame
    s = socket.create_connection((host, port), timeout=timeout_s)
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_frame(s, {"op": op, **kwargs}, threading.Lock())
        reply = recv_frame(s)
    finally:
        s.close()
    if not reply.get("ok"):
        raise RuntimeError(f"lease op {op!r} failed: "
                           f"{reply.get('error', 'unknown error')}")
    return reply
