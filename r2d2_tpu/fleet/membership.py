"""Elastic fleet membership (ISSUE 15 tentpole, plane c).

The actor fleet used to be FROZEN at startup: ``actor.num_actors``
workers, each permanently owning slot i's heartbeat row, lane range
``[i*k, (i+1)*k)``, ε-ladder slice, and replay routing. This module
makes the slot table a LEASED resource over the PR-3 heartbeat board:

  * a leaving (clean ``leave_actor``) or killed (``fleet.elastic``
    supervision policy) worker's slot PARKS — its lane range, ε slice,
    and routing key are preserved for re-adoption, and the learner keeps
    training on the remaining fleet;
  * a joining process LEASES a parked (or spare — ``fleet.max_slots`` >
    ``actor.num_actors``) slot mid-training and adopts exactly that
    slot's identity, so lane ranges can never overlap (the churn drill's
    acceptance) and the ε ladder stays fixed as the fleet churns;
  * a leased slot whose worker silently vanished (heartbeat stale past
    the orphan horizon with no supervision verdict) reads as ORPHANED —
    the ``orphaned_slot`` alert's signal, a leaked lease the operator
    must reap.

Leases are arbitrated by the ONE owning supervisor process (the
orchestrator) — joiners go through :meth:`FleetMembership.lease`, never
race on shared state — while LIVENESS stays on the shared-memory
heartbeat board the workers already publish to."""

import time
from dataclasses import dataclass
from typing import List, Optional

SLOT_FREE = "free"        # spare capacity, never yet leased
SLOT_ACTIVE = "active"    # leased to a live worker
SLOT_PARKED = "parked"    # left/killed; identity preserved for re-adoption


@dataclass(frozen=True)
class SlotLease:
    """What a joiner adopts: the slot's full identity. ``generation``
    counts adoptions of this slot (0 = the original startup worker), so
    respawn-vs-adoption is distinguishable in logs and tests."""

    slot: int
    generation: int
    lane_base: int
    lanes: int
    shard_key: int

    def lane_range(self):
        return range(self.lane_base, self.lane_base + self.lanes)


class FleetMembership:
    """The slot lease table. ``n_slots`` is the fleet's MAXIMUM width
    (``fleet.max_slots``, default the startup ``actor.num_actors``);
    slots [0, initial_active) start ACTIVE (the startup fleet), the rest
    FREE (spare capacity joiners can claim)."""

    def __init__(self, n_slots: int, envs_per_slot: int = 1,
                 initial_active: Optional[int] = None, num_shards: int = 1):
        self.n_slots = n_slots
        self.envs_per_slot = envs_per_slot
        self.num_shards = max(num_shards, 1)
        active = n_slots if initial_active is None else initial_active
        if not 0 <= active <= n_slots:
            raise ValueError(
                f"initial_active ({active}) must be in [0, {n_slots}]")
        self._state = [SLOT_ACTIVE] * active + \
            [SLOT_FREE] * (n_slots - active)
        self._generation = [0] * n_slots
        self._parked_at = [0.0] * n_slots
        self._park_reason: List[Optional[str]] = [None] * n_slots
        # cumulative churn counters for the telemetry block
        self.leaves = 0
        self.joins = 0

    # -- identity derivation (ONE place: the layout every spawner and
    # vector_lane_epsilons already agree on) --

    def lane_base(self, slot: int) -> int:
        return slot * self.envs_per_slot

    def shard_key(self, slot: int) -> int:
        """The slot's replay-routing key under lane routing: its first
        lane's shard (ReplayService route='lane' sends lane l to shard
        l % num_shards)."""
        return self.lane_base(slot) % self.num_shards

    def generation(self, slot: int) -> int:
        """Adoptions of this slot so far (0 = the startup worker)."""
        return self._generation[slot]

    def lease_of(self, slot: int) -> SlotLease:
        return SlotLease(slot=slot, generation=self._generation[slot],
                         lane_base=self.lane_base(slot),
                         lanes=self.envs_per_slot,
                         shard_key=self.shard_key(slot))

    # -- state machine --

    def state(self, slot: int) -> str:
        return self._state[slot]

    def park(self, slot: int, reason: str = "left") -> None:
        """A worker left or was killed: preserve the slot's identity for
        re-adoption. Idempotent (a leave followed by the supervisor
        observing the corpse must not double-count)."""
        if self._state[slot] == SLOT_PARKED:
            return
        self._state[slot] = SLOT_PARKED
        self._parked_at[slot] = time.time()
        self._park_reason[slot] = reason
        self.leaves += 1

    def lease(self, slot: Optional[int] = None) -> SlotLease:
        """Adopt a slot: the requested one (must be PARKED or FREE), or
        the longest-parked slot, or a FREE spare. Raises when the fleet
        is at full width with nothing parked."""
        if slot is None:
            parked = [(self._parked_at[s], s) for s in range(self.n_slots)
                      if self._state[s] == SLOT_PARKED]
            if parked:
                slot = min(parked)[1]
            else:
                free = [s for s in range(self.n_slots)
                        if self._state[s] == SLOT_FREE]
                if not free:
                    raise RuntimeError(
                        "no parked or free slot to lease — the fleet is "
                        "at full width; raise fleet.max_slots or leave a "
                        "worker first")
                slot = free[0]
        elif self._state[slot] == SLOT_ACTIVE:
            raise RuntimeError(
                f"slot {slot} is ACTIVE — a live worker holds its lease "
                "(leave it first, or lease a parked/free slot)")
        self._state[slot] = SLOT_ACTIVE
        self._generation[slot] += 1
        self._park_reason[slot] = None
        self.joins += 1
        return self.lease_of(slot)

    # -- views --

    def active_slots(self) -> List[int]:
        return [s for s in range(self.n_slots)
                if self._state[s] == SLOT_ACTIVE]

    def parked_slots(self) -> List[int]:
        return [s for s in range(self.n_slots)
                if self._state[s] == SLOT_PARKED]

    def assert_no_overlap(self) -> None:
        """Every active slot's lane range must be disjoint — the churn
        drill's structural acceptance. Lane ranges derive from the slot
        index, so overlap is impossible UNLESS a lease was duplicated;
        this asserts the lease table itself is consistent."""
        seen = set()
        for s in self.active_slots():
            lanes = set(self.lease_of(s).lane_range())
            if lanes & seen:
                raise AssertionError(
                    f"lane-range overlap at slot {s}: {sorted(lanes & seen)}")
            seen |= lanes

    def orphaned(self, heartbeat_ages, horizon_s: float) -> int:
        """Leased (ACTIVE) slots whose heartbeat went stale past the
        orphan horizon: the worker vanished without the supervisor
        parking the slot — a leaked lease (the ``orphaned_slot``
        signal). ``heartbeat_ages`` is the board's per-slot age array
        (may be shorter than n_slots on a legacy-sized board)."""
        if horizon_s <= 0 or heartbeat_ages is None:
            return 0
        count = 0
        for s in self.active_slots():
            if s < len(heartbeat_ages) and \
                    float(heartbeat_ages[s]) > horizon_s:
                count += 1
        return count

    def snapshot(self, heartbeat_ages=None,
                 orphan_horizon_s: float = 0.0) -> dict:
        """The record's ``membership`` sub-block."""
        return {
            "slots": self.n_slots,
            "active": len(self.active_slots()),
            "parked": len(self.parked_slots()),
            "free": sum(1 for s in self._state if s == SLOT_FREE),
            "joins": self.joins,
            "leaves": self.leaves,
            "orphaned": self.orphaned(heartbeat_ages, orphan_horizon_s),
        }
