"""Elastic fleet control plane (ISSUE 15, ROADMAP item 4).

Three planes that let producers (actors) and consumers (learners) scale
and churn independently of one another:

  * ``replay_service`` — the dp-sharded device replay generalized into N
    addressable shards behind one :class:`ReplayService` interface, with
    a host-RAM spill tier (LRU block pages demoted from the device ring,
    re-promoted into the samplable ring) so capacity scales past the HBM
    budget, and a socket rung so remote producers can route blocks in.
  * ``fanout`` — weight distribution as a relay tree: the learner
    publishes ONCE, intermediate relay nodes re-publish to their
    children, and actors read from leaf relays — replacing
    every-actor-polls-one-publisher. The stamped quant bundle (ISSUE 14)
    rides through unchanged.
  * ``membership`` — actors join/leave a RUNNING fleet: slots are leased,
    a leaving/killed actor's slot parks for re-adoption, and a joiner
    adopts a parked slot's lane range + ε-ladder slice + replay routing
    mid-training.
"""

from r2d2_tpu.fleet.fanout import FanoutTree, ShmFanout
from r2d2_tpu.fleet.promotion import (STATE_CANARY, STATE_IDLE,
                                      STATE_PROMOTED, STATE_REFUSED,
                                      STATE_ROLLED_BACK, PromotionManager,
                                      ShadowScorer)
from r2d2_tpu.fleet.membership import (SLOT_ACTIVE, SLOT_FREE, SLOT_PARKED,
                                       FleetMembership, MembershipServer,
                                       SlotLease, lease_call)
from r2d2_tpu.fleet.replay_service import (RemoteReplayProducer,
                                           ReplayProducerPump, ReplayShard,
                                           ReplayService, ReplayServiceServer,
                                           SpillTier)

__all__ = [
    "ReplayService", "ReplayShard", "SpillTier",
    "ReplayServiceServer", "RemoteReplayProducer", "ReplayProducerPump",
    "FanoutTree", "ShmFanout",
    "PromotionManager", "ShadowScorer",
    "STATE_IDLE", "STATE_CANARY", "STATE_PROMOTED", "STATE_REFUSED",
    "STATE_ROLLED_BACK",
    "FleetMembership", "SlotLease", "MembershipServer", "lease_call",
    "SLOT_FREE", "SLOT_ACTIVE", "SLOT_PARKED",
]
