"""Gated canary promotion + shadow scoring (ISSUE 20; ROADMAP item 2d).

The deployment lifecycle over the existing distribution plumbing — no new
transport, no new weight format:

  * ``ShadowScorer`` — the serve router's mirror sink
    (``RoutingChannel.set_mirror``): a sampled fraction of live requests
    is COPIED to a candidate server's own channel and the candidate's
    replies are scored for greedy-agreement / max-|ΔQ| divergence against
    the live replies. Mirroring only enqueues (bounded queue, drops
    counted); a worker thread pays the candidate's latency, so the live
    path sees O(sample-decision) overhead and candidate replies are never
    returned to clients. The candidate server owns its own state cache —
    live client state is untouched by construction.
  * ``PromotionManager`` — the state machine: ``stage()`` retains the
    currently-published bundle (root store value + stamp, persisted under
    ``{save_dir}/promotion/`` so rollback survives the process) and
    canary-publishes the candidate to a slice of the fan-out tree's leaf
    relays (PR-14); ``decide()`` applies the configurable gates (eval
    return ≥ live − tolerance, calibration drift and shadow divergence
    bounded, minimum shadow sample count); ``promote()`` is ONE root
    publish — the same path a training publish takes, so every consumer
    and serving slot adopts through unchanged plumbing; ``rollback()``
    re-publishes the retained previous bundle bit-identically.

Candidates arrive PREPARED (the PR-13 publish preparer has already built
the stamped quant bundle when quantization is on) — promotion moves
bundles, it never rebuilds them.
"""

import dataclasses
import json
import os
import pickle
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

STATE_IDLE = "idle"
STATE_CANARY = "canary"
STATE_PROMOTED = "promoted"
STATE_REFUSED = "refused"
STATE_ROLLED_BACK = "rolled_back"


def _greedy(reply) -> Optional[int]:
    """Greedy action under a reply: argmax of the carried q-vector when
    present (exploration-free — two policies with different ε must not
    read as divergence), the sampled action otherwise."""
    q = getattr(reply, "q", None)
    if q is not None:
        return int(np.argmax(np.asarray(q)))
    a = int(getattr(reply, "action", -1))
    return a if a >= 0 else None


class ShadowScorer:
    """Mirror sink for ``RoutingChannel.set_mirror``: samples live
    (request, reply) pairs into a bounded queue; ``process_pending()``
    (the worker loop, or tests/drills directly) replays request COPIES
    against the candidate channel and feeds greedy-agreement + max-|ΔQ|
    into ``QualityStats.on_shadow``. Only OK step replies score; the
    live ``reqs``/``replies`` objects are never written to."""

    def __init__(self, candidate_channel, stats=None, *,
                 sample_rate: float = 1.0, max_queue: int = 512,
                 timeout_s: float = 2.0, seed: int = 0):
        import random
        from r2d2_tpu.serve.transport import KIND_STEP, STATUS_OK
        self._kind_step = KIND_STEP
        self._status_ok = STATUS_OK
        self.candidate = candidate_channel
        self.stats = stats
        self.sample_rate = float(sample_rate)
        self.timeout_s = float(timeout_s)
        self._rng = random.Random(seed)
        self._q: deque = deque(maxlen=int(max_queue))
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (read by tests/the drill; stats carries the record)
        self.mirrored = 0
        self.scored = 0
        self.agreed = 0
        self.dropped = 0
        self.errors = 0

    # -- live-path side (must be cheap and exception-free) --

    def mirror(self, reqs: Sequence, replies: Dict[int, object]) -> None:
        pairs = []
        for r in reqs:
            if r.kind != self._kind_step:
                continue
            live = replies.get(r.req_id)
            if live is None or live.status != self._status_ok:
                continue
            if self._rng.random() >= self.sample_rate:
                continue
            pairs.append((r, live))
        if not pairs:
            return
        with self._lock:
            before = len(self._q)
            self._q.extend(pairs)
            lost = before + len(pairs) - len(self._q)
        if lost > 0:
            self.dropped += lost
            if self.stats is not None:
                self.stats.on_shadow(0, 0, dropped=lost)
        self.mirrored += len(pairs)
        self._wake.set()

    # -- candidate side --

    def process_pending(self) -> int:
        """Drain the queue against the candidate; returns pairs scored."""
        with self._lock:
            pairs = list(self._q)
            self._q.clear()
        if not pairs:
            return 0
        copies = [dataclasses.replace(r, reply_to="") for r, _live in pairs]
        try:
            cand = self.candidate.request_many(copies,
                                               timeout=self.timeout_s)
        except Exception:
            self.errors += 1
            return 0
        scored = agreed = 0
        dq_max = None
        for (req, live), copy in zip(pairs, copies):
            rep = cand.get(copy.req_id)
            if rep is None or rep.status != self._status_ok:
                continue
            g_live, g_cand = _greedy(live), _greedy(rep)
            if g_live is None or g_cand is None:
                continue
            scored += 1
            agreed += int(g_live == g_cand)
            if live.q is not None and rep.q is not None:
                dq = float(np.max(np.abs(
                    np.asarray(live.q, np.float32)
                    - np.asarray(rep.q, np.float32))))
                dq_max = dq if dq_max is None else max(dq_max, dq)
        if scored:
            self.scored += scored
            self.agreed += agreed
            if self.stats is not None:
                self.stats.on_shadow(scored, agreed, dq_max=dq_max)
        return scored

    def divergence(self) -> Optional[float]:
        """Cumulative greedy-disagreement fraction (None before any
        score) — the gate input when no QualityStats is attached."""
        return (1.0 - self.agreed / self.scored) if self.scored else None

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(0.1)
            self._wake.clear()
            try:
                self.process_pending()
            except Exception:
                self.errors += 1

    def start(self) -> "ShadowScorer":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="shadow-scorer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


class PromotionManager:
    """idle → canary → promoted | refused (+ rollback) over the root
    weight store and the optional fan-out tree. Thread-safe; ``block()``
    is the record's ``promotion`` sub-block (``age_s`` is non-None only
    while a canary is in flight — the ``promotion_stall`` rule's path)."""

    def __init__(self, fleet_cfg, store, *, fanout=None, stats=None,
                 save_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.time):
        self.cfg = fleet_cfg
        self.store = store
        self.fanout = fanout
        self.stats = stats
        self.clock = clock
        self._lock = threading.Lock()
        self.state = STATE_IDLE
        self.promotions = 0
        self.rollbacks = 0
        self.refusals = 0
        self.root_publishes = 0      # drill: promote == ONE root publish
        self._candidate = None
        self._candidate_stamp: Optional[int] = None
        self._retained = None        # (tree, stamp) of the pre-stage bundle
        self._staged_at: Optional[float] = None
        self._last_gates: Optional[dict] = None
        self._dir = (os.path.join(save_dir, "promotion")
                     if save_dir else None)
        if stats is not None:
            stats.set_promotion(self.block)
        if self._dir is not None:
            self._load_persisted()

    # -- persistence (one staged generation survives the process) --

    def _load_persisted(self) -> None:
        try:
            with open(os.path.join(self._dir, "state.json")) as f:
                st = json.load(f)
            self.state = st.get("state", STATE_IDLE)
            self.promotions = int(st.get("promotions", 0))
            self.rollbacks = int(st.get("rollbacks", 0))
            self.refusals = int(st.get("refusals", 0))
            self._candidate_stamp = st.get("candidate_stamp")
            self._staged_at = st.get("staged_at")
            with open(os.path.join(self._dir, "previous.pkl"), "rb") as f:
                prev = pickle.load(f)
            self._retained = (prev["tree"], int(prev["stamp"]))
        except (OSError, ValueError, KeyError, pickle.PickleError):
            pass                     # fresh dir / partial write: start idle

    def _persist(self) -> None:
        if self._dir is None:
            return
        try:
            os.makedirs(self._dir, exist_ok=True)
            if self._retained is not None:
                tmp = os.path.join(self._dir, ".previous.pkl.tmp")
                with open(tmp, "wb") as f:
                    pickle.dump({"tree": self._retained[0],
                                 "stamp": self._retained[1]}, f)
                os.replace(tmp, os.path.join(self._dir, "previous.pkl"))
            tmp = os.path.join(self._dir, ".state.json.tmp")
            with open(tmp, "w") as f:
                json.dump({"state": self.state,
                           "candidate_stamp": self._candidate_stamp,
                           "staged_at": self._staged_at,
                           "promotions": self.promotions,
                           "rollbacks": self.rollbacks,
                           "refusals": self.refusals,
                           "gates": self._last_gates}, f)
            os.replace(tmp, os.path.join(self._dir, "state.json"))
        except OSError:
            pass                     # persistence is best-effort

    # -- lifecycle --

    def stage(self, candidate_tree, *, stamp: Optional[int] = None) -> dict:
        """Retain the live bundle and canary-publish the candidate to
        ``promotion_canary_frac`` of the fan-out consumers. Returns the
        canary coverage (empty when no tree / no relays — the candidate
        then proves itself on shadow + eval alone)."""
        with self._lock:
            if self.state == STATE_CANARY:
                raise RuntimeError(
                    "a canary is already staged (stamp "
                    f"{self._candidate_stamp}) — promote, refuse, or "
                    "roll back first")
            live_tree = self.store.current("promotion")
            self._retained = (live_tree, int(self.store.publish_count))
            self._candidate = candidate_tree
            self._candidate_stamp = (int(stamp) if stamp is not None
                                     else int(self.store.publish_count) + 1)
            covered: List[int] = []
            if self.fanout is not None:
                covered = self.fanout.canary_publish(
                    candidate_tree, self._candidate_stamp,
                    frac=self.cfg.promotion_canary_frac)
            self.state = STATE_CANARY
            self._staged_at = self.clock()
            self._persist()
            return {"candidate_stamp": self._candidate_stamp,
                    "previous_stamp": self._retained[1],
                    "canary_consumers": covered}

    def decide(self, *, candidate_return: Optional[float] = None,
               live_return: Optional[float] = None,
               calibration_gap: Optional[float] = None,
               shadow_divergence: Optional[float] = None,
               shadow_requests: int = 0) -> Tuple[bool, dict]:
        """Apply the gates. Eval and shadow gates fail CLOSED (a missing
        signal refuses — a promotion must earn its evidence); the
        calibration gate fails open when no calibration stream exists
        (process-actor fleets have none) but bounds it when it does."""
        cfg = self.cfg
        gates = {}
        gates["eval_return"] = {
            "ok": (candidate_return is not None and live_return is not None
                   and candidate_return
                   >= live_return - cfg.promotion_return_tolerance),
            "candidate": candidate_return, "live": live_return,
            "tolerance": cfg.promotion_return_tolerance,
        }
        gates["calibration"] = {
            "ok": (calibration_gap is None
                   or abs(calibration_gap) <= cfg.promotion_calibration_bound),
            "gap": calibration_gap,
            "bound": cfg.promotion_calibration_bound,
        }
        gates["shadow"] = {
            "ok": (shadow_requests >= cfg.promotion_min_shadow
                   and shadow_divergence is not None
                   and shadow_divergence <= cfg.promotion_divergence_bound),
            "requests": int(shadow_requests),
            "min_requests": cfg.promotion_min_shadow,
            "divergence": shadow_divergence,
            "bound": cfg.promotion_divergence_bound,
        }
        ok = all(g["ok"] for g in gates.values())
        with self._lock:
            self._last_gates = gates
        return ok, gates

    def _publish(self, tree) -> None:
        self.store.publish(tree)
        self.root_publishes += 1
        if self.fanout is not None:
            self.fanout.clear_canary()
            self.fanout.on_publish()

    def promote(self) -> int:
        """Commit the staged candidate: ONE root publish; the fan-out
        tree re-pumps every relay (incl. the canary slice) from the
        root. Returns the promoted stamp."""
        with self._lock:
            if self.state != STATE_CANARY or self._candidate is None:
                raise RuntimeError("no staged candidate to promote")
            self._publish(self._candidate)
            stamp = self._candidate_stamp
            self._candidate = None
            self.state = STATE_PROMOTED
            self.promotions += 1
            self._staged_at = None
            self._persist()
            return stamp

    def refuse(self, gates: Optional[dict] = None) -> None:
        """Reject the staged candidate: clear the canary slice back to
        the root's bundle; the retained previous stays retained (the
        root was never touched, so nothing re-publishes)."""
        with self._lock:
            if self.state != STATE_CANARY:
                raise RuntimeError("no staged candidate to refuse")
            if self.fanout is not None:
                self.fanout.clear_canary()
            if gates is not None:
                self._last_gates = gates
            self._candidate = None
            self.state = STATE_REFUSED
            self.refusals += 1
            self._staged_at = None
            self._persist()

    def rollback(self) -> int:
        """One-command rollback: re-publish the retained previous bundle
        from the root (bit-identical — the tree was snapshotted, never
        rebuilt). Returns the restored bundle's original stamp."""
        with self._lock:
            if self._retained is None:
                raise RuntimeError(
                    "nothing retained to roll back to (no promotion was "
                    "staged from this save_dir)")
            tree, stamp = self._retained
            self._publish(tree)
            self._candidate = None
            self.state = STATE_ROLLED_BACK
            self.rollbacks += 1
            self._staged_at = None
            self._persist()
            return stamp

    def block(self) -> dict:
        with self._lock:
            age = (self.clock() - self._staged_at
                   if (self.state == STATE_CANARY
                       and self._staged_at is not None) else None)
            return {
                "state": self.state,
                "candidate_stamp": self._candidate_stamp,
                "previous_stamp": (self._retained[1]
                                   if self._retained is not None else None),
                "age_s": age,
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
                "refusals": self.refusals,
            }
