"""Weight fan-out tree (ISSUE 15 tentpole, plane b).

Every weight consumer used to poll the ONE learner-side
publisher/store — N readers per publication, which is fine at N=2 and a
scaling wall at fleet scale (the 2012.04210 bottleneck analysis: the
weight broadcast path saturates first). This module turns distribution
into a TREE: the learner publishes once to its root store, intermediate
RELAY nodes adopt and re-publish, and each consumer reads from its leaf
relay — the root sees at most ``degree`` readers no matter how wide the
fleet grows.

Two implementations share the topology math (:func:`tier_sizes`):

  * :class:`FanoutTree` — in-process relays over the thread-mode
    ``InProcWeightStore`` contract (poll/version per reader). Relays
    propagate on ``on_publish()`` (the learner's publish wrapper) and
    lazily on consumer polls once ``pull_interval_s`` elapses — with a
    nonzero interval the tree runs deliberately behind, which is what
    makes relay LAG a real, testable signal (the ``fanout_lag`` alert).
  * :class:`ShmFanout` — process-mode relays: each relay node is a
    WeightSubscriber on its parent's shm segment plus its OWN
    WeightPublisher segment; actor processes attach to their leaf
    relay's segment name through the unchanged actor_main plumbing.

Both carry the published tree OPAQUELY — the stamped quant bundle
(ISSUE 14: {f32, int8/bf16 twin, publish stamp}) rides through relays
unchanged, so staleness accounting and the quantized twins work at every
tree depth for free (stamp-propagation-tested)."""

import math
import threading
import time
from typing import Callable, List, Optional, Tuple

import jax


def tier_sizes(n_consumers: int, degree: int) -> List[int]:
    """Relay-node count per tier, LEAF tier first: ceil(n/d) leaf relays,
    then ceil(prev/d) above, until one tier holds <= degree nodes (those
    read the root directly). Empty for n_consumers <= degree — the root
    can serve the consumers itself, no relays needed."""
    if degree < 2:
        raise ValueError(f"fan-out degree ({degree}) must be >= 2")
    sizes: List[int] = []
    width = n_consumers
    while width > degree:
        width = math.ceil(width / degree)
        sizes.append(width)
    return sizes


class _Relay:
    """One in-process relay node: adopts (tree, version) from its
    upstream poll/version pair and serves them to per-reader consumers
    with the InProcWeightStore poll contract. The version is the ROOT
    publish count propagated verbatim — block staleness stamps measured
    against the learner's clock stay correct at any depth (a lagging
    relay's consumers stamp OLDER versions, which is the truth)."""

    def __init__(self, upstream_poll: Callable, upstream_version: Callable,
                 pull_interval_s: float = 0.0):
        self._up_poll = upstream_poll
        self._up_version = upstream_version
        self._pull_interval_s = pull_interval_s
        self._lock = threading.Lock()
        self._tree = None
        self._version = 0
        self._last_pull = 0.0
        self._readers = {}

    def pump(self) -> bool:
        """Adopt the upstream's current tree if it moved; returns True
        when fresh data was adopted."""
        with self._lock:
            fresh = self._up_poll()
            self._last_pull = time.monotonic()
            if fresh is None:
                return False
            self._tree = fresh
            self._version = int(self._up_version())
            return True

    def _maybe_pull(self) -> None:
        if self._pull_interval_s <= 0:
            return                # push-through: on_publish pumps
        if time.monotonic() - self._last_pull >= self._pull_interval_s:
            self.pump()

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def poll(self, reader_id):
        """Fresh tree for this reader, or None (unchanged / nothing
        adopted yet)."""
        self._maybe_pull()
        with self._lock:
            if self._tree is None or \
                    self._readers.get(reader_id) == self._version:
                return None
            self._readers[reader_id] = self._version
            return self._tree

    def current(self, reader_id=None):
        """The relay's current tree without the seen-version gate (the
        spawn-time read, mirroring InProcWeightStore.current); pumps
        first so a just-published tree is visible to a joiner."""
        self.pump()
        with self._lock:
            if reader_id is not None and self._tree is not None:
                self._readers[reader_id] = self._version
            return self._tree

    def adopt(self, tree, version: int) -> None:
        """Directly install (tree, version) on this relay, bypassing the
        upstream (the canary-promotion path, ISSUE 20): every consumer's
        seen-version resets so all of them re-poll the adopted tree. The
        override STICKS until the upstream actually re-publishes (pump
        only overwrites on fresh upstream data) or the tree's
        ``clear_canary`` re-adopts the root's bundle."""
        with self._lock:
            self._tree = tree
            self._version = int(version)
            self._readers.clear()

    def reader_version(self, reader_id) -> int:
        with self._lock:
            return self._readers.get(reader_id, 0)


class FanoutTree:
    """In-process relay tree over a root InProcWeightStore.

    ``endpoints(consumer)`` hands a consumer its leaf relay's
    (poll, version, current) closures — a drop-in for the store-direct
    closures the thread spawner builds; ``on_publish()`` propagates one
    publication root→leaves (called by the learner's publish wrapper
    when ``pull_interval_s`` is 0 — with a nonzero interval relays pull
    on their own clock instead and lag becomes visible)."""

    def __init__(self, store, n_consumers: int, degree: int,
                 pull_interval_s: float = 0.0):
        self.store = store
        self.degree = degree
        self.n_consumers = n_consumers
        self._pull_interval_s = pull_interval_s
        self.tiers: List[List[_Relay]] = []
        sizes = tier_sizes(n_consumers, degree)
        # build ROOT-ward tier first so each relay's upstream exists;
        # tier_sizes is leaf-first, so reverse for construction
        upstream_tier: Optional[List[_Relay]] = None
        for size in reversed(sizes):
            tier = []
            for j in range(size):
                if upstream_tier is None:
                    up_poll = (lambda _j=j:
                               self.store.poll(f"fanout-relay-{_j}"))
                    up_version = (lambda _j=j: self.store.reader_version(
                        f"fanout-relay-{_j}"))
                else:
                    parent = upstream_tier[j // degree]
                    up_poll = (lambda _p=parent, _j=j:
                               _p.poll(f"fanout-relay-{_j}"))
                    up_version = _make_version(parent)
                tier.append(_Relay(up_poll, up_version, pull_interval_s))
            self.tiers.append(tier)
            upstream_tier = tier
        # tiers is now root-ward first; leaves last (possibly empty —
        # degree >= n_consumers means consumers read the root directly)
        self.relays = [r for tier in self.tiers for r in tier]
        # canary slice (ISSUE 20): leaf relays currently serving a
        # candidate bundle instead of the root's (see canary_publish)
        self._canaried: List[_Relay] = []
        # initial propagation: relays adopt the store's construction
        # publication (tier order is root-ward, so one pass reaches the
        # leaves) — a consumer spawned before the first training publish
        # must still read params, exactly like a store-direct reader
        self.pump()

    @property
    def depth(self) -> int:
        """Relay tiers between the root store and the consumers."""
        return len(self.tiers)

    def _leaf_for(self, consumer: int) -> Optional[_Relay]:
        if not self.tiers:
            return None
        # leaf tier holds ceil(n_consumers/degree) relays, so
        # consumer // degree is always a valid leaf index
        return self.tiers[-1][consumer // self.degree]

    def endpoints(self, consumer: int) -> Tuple[Callable, Callable, Callable]:
        """(poll, version, current) closures for one consumer slot —
        exactly the shapes PlayerStack's thread spawner wires from the
        root store when no tree is configured."""
        leaf = self._leaf_for(consumer)
        if leaf is None:
            return ((lambda: self.store.poll(consumer)),
                    (lambda: self.store.reader_version(consumer)),
                    (lambda: self.store.current(reader_id=consumer)))
        return ((lambda: leaf.poll(consumer)),
                (lambda: leaf.reader_version(consumer)),
                (lambda: leaf.current(reader_id=consumer)))

    def on_publish(self) -> None:
        """Propagate the newest publication down every tier (root-ward
        tier first so leaves see it in the same pass). Skipped when
        relays pull on their own interval — then lag is the interval's."""
        if self._pull_interval_s > 0:
            return
        self.pump()

    def pump(self) -> None:
        for tier in self.tiers:
            for relay in tier:
                relay.pump()

    def canary_publish(self, tree, version: int,
                       frac: float = 0.25) -> List[int]:
        """Serve a CANDIDATE bundle to a slice of the fleet (ISSUE 20):
        adopt (tree, version) on enough leaf relays — taken from the
        high-slot end, the most-exploratory end of the ε ladder — to
        cover at least ``ceil(frac * n_consumers)`` consumers. Slice
        granularity is the leaf relay (all of a canaried relay's
        consumers get the candidate). Returns the covered consumer
        slots — empty when the tree has no relays (degree >=
        n_consumers: consumers read the root directly, which only a
        root publish may touch) or ``frac <= 0``."""
        if not self.tiers or frac <= 0:
            return []
        want = max(1, math.ceil(float(frac) * self.n_consumers))
        leaf_tier = self.tiers[-1]
        tree = jax.device_get(tree)
        covered: List[int] = []
        canaried: List[_Relay] = []
        for j in range(len(leaf_tier) - 1, -1, -1):
            canaried.append(leaf_tier[j])
            covered.extend(c for c in range(self.n_consumers)
                           if c // self.degree == j)
            if len(covered) >= want:
                break
        for relay in canaried:
            relay.adopt(tree, version)
        self._canaried = canaried
        return sorted(covered)

    def clear_canary(self) -> None:
        """Return every canaried relay to the ROOT's current bundle
        (explicit re-adoption: after a refused canary the root never
        re-published, so an upstream pump would return None forever and
        the candidate would stick)."""
        if not self._canaried:
            return
        current = self.store.current()
        version = int(self.store.publish_count)
        for relay in self._canaried:
            relay.adopt(current, version)
        self._canaried = []

    def stats(self) -> Optional[dict]:
        """The record's ``fanout`` sub-block: topology + the max relay
        lag in publications (root publish count − slowest relay's
        adopted count) — the ``fanout_lag`` alert's signal. A live
        canary's relays carry the CANDIDATE stamp (> root), clamped out
        of the lag so a canary never reads as negative lag."""
        root = int(self.store.publish_count)
        lags = [max(root - r.version, 0) for r in self.relays]
        out = {
            "degree": self.degree,
            "depth": self.depth,
            "relays": len(self.relays),
            "consumers": self.n_consumers,
            "max_lag": (max(lags) if lags else 0),
        }
        if self._canaried:
            # present only while a canary is live, so promotion-less
            # runs' records stay byte-identical to the PR-19 schema
            out["canary_relays"] = len(self._canaried)
        return out


def _make_version(parent: _Relay) -> Callable[[], int]:
    return lambda: parent.version


class _ShmNode:
    """One shm relay: subscriber on the parent segment + own publisher
    segment + the root publication count last adopted (for lag)."""

    __slots__ = ("sub", "pub", "parent", "adopted_root")

    def __init__(self, sub, pub, parent: Optional["_ShmNode"]):
        self.sub = sub
        self.pub = pub
        self.parent = parent
        self.adopted_root = 0


class ShmFanout:
    """Process-mode fan-out: relay nodes re-publish the root
    WeightPublisher's segment into their own shm segments; consumer
    slot i attaches to ``segment_for(i)`` through the unchanged
    WeightSubscriber/actor_main plumbing. Relays are pumped by the
    owning (learner) process — on every root publish and on the
    supervise cadence — one subscriber read + one publisher memcpy per
    relay per publication, in exchange for the root segment seeing
    ``degree`` readers instead of the whole fleet."""

    def __init__(self, root_name: str, template, n_consumers: int,
                 degree: int):
        from r2d2_tpu.runtime.weights import (WeightPublisher,
                                              WeightSubscriber)
        self.degree = degree
        self.n_consumers = n_consumers
        self._nodes: List[List[_ShmNode]] = []   # tiers, root-ward first
        sizes = tier_sizes(n_consumers, degree)
        init = jax.device_get(template)
        parent_tier: List[Optional[_ShmNode]] = [None]   # None = root
        parent_names: List[str] = [root_name]
        try:
            for size in reversed(sizes):
                tier = []
                for j in range(size):
                    # the tier above holds ceil(size/degree) segments
                    # (or just the root), so j // degree always lands
                    k = min(j // degree, len(parent_names) - 1)
                    tier.append(_ShmNode(
                        WeightSubscriber(parent_names[k], template),
                        WeightPublisher(init), parent_tier[k]))
                self._nodes.append(tier)
                parent_tier = tier
                parent_names = [n.pub.name for n in tier]
        except BaseException:
            self.close()
            raise
        self._leaf_names = parent_names

    @property
    def depth(self) -> int:
        return len(self._nodes)

    @property
    def relays(self) -> int:
        return sum(len(t) for t in self._nodes)

    def segment_for(self, consumer: int) -> str:
        """The shm segment name consumer slot ``consumer`` subscribes
        to — its leaf relay's, or the root's when no relays exist."""
        if not self._nodes:
            return self._leaf_names[0]
        leaves = self._leaf_names
        return leaves[min(consumer // self.degree, len(leaves) - 1)]

    def pump(self) -> None:
        """Propagate: each tier's subscribers poll their parents and
        re-publish fresh trees (root-ward tier first, so one pass moves
        a publication the full depth). Each node records the ROOT
        publication count it last adopted (tier 0's subscriber counts
        root publications directly; deeper nodes inherit their parent's
        adopted count at adoption) — the lag gauge the fanout_lag rule
        reads."""
        for tier in self._nodes:
            for node in tier:
                fresh = node.sub.poll()
                if fresh is not None:
                    node.pub.publish(fresh)
                    node.adopted_root = (node.sub.publish_count
                                         if node.parent is None
                                         else node.parent.adopted_root)

    def stats(self, root_publish_count: int) -> dict:
        lags = [root_publish_count - node.adopted_root
                for tier in self._nodes for node in tier]
        return {
            "degree": self.degree,
            "depth": self.depth,
            "relays": self.relays,
            "consumers": self.n_consumers,
            "max_lag": (max(lags) if lags else 0),
        }

    def close(self) -> None:
        for tier in self._nodes:
            for node in tier:
                try:
                    node.sub.close()
                except Exception:
                    pass
                try:
                    node.pub.close()
                except Exception:
                    pass
        self._nodes = []
