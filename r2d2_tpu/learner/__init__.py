"""Learner: the fused on-device R2D2 training step and its host-side driver."""

from r2d2_tpu.learner.train_step import (
    TrainState,
    create_train_state,
    make_learner_step,
    make_loss_fn,
    make_multi_learner_step,
)

__all__ = ["TrainState", "create_train_state", "make_learner_step",
           "make_loss_fn", "make_multi_learner_step"]
