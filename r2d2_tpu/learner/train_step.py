"""The fused R2D2 learner step — sample → unroll → loss → Adam → priority
write-back as ONE XLA program.

Reference semantics being reproduced (/root/reference/worker.py:308-381):
frame-stack reassembly + /255 (330-331), double-DQN action selection
(335-339), invertible value-rescaled n-step target (341,383-390), IS-weighted
0.5·MSE over ragged learning steps (344-346), mixed max/mean priority
(348-350,240-249), grad-clip(40) + Adam (361-364), periodic hard target sync
(375-377).

TPU-native deltas:
  * the reference pays a Ray RPC + numba tree walk to sample, a D2H sync to
    compute priorities, and an async RPC to write them back; here all three
    are jnp ops inside the jitted step — the learner never leaves the device;
  * two LSTM unrolls per step instead of three: because an LSTM output at t
    depends only on inputs ≤ t, the grad-enabled online unroll over the full
    window also provides the (stop-gradient) action-selection Q at t+n — the
    reference's separate no-grad online pass (worker.py:336) is a gather;
  * ragged sequence handling is gather indices + masks (ops/indexing.py), not
    pack/pad;
  * sample→train→update is atomic, so the ring staleness guard
    (worker.py:196-206) is unnecessary by construction;
  * torch.cuda.amp → bf16 compute policy in the network (no loss scaling
    needed: bf16 keeps f32's exponent range).
"""

from typing import Any, Dict, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from r2d2_tpu.config import OptimConfig
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.ops.indexing import (
    frame_stack_indices,
    learning_step_mask,
    online_q_positions,
    target_q_positions,
)
from r2d2_tpu.ops.priority import mixed_td_errors_masked
from r2d2_tpu.ops.sum_tree import tree_update
from r2d2_tpu.ops.value import inverse_value_rescale, value_rescale
from r2d2_tpu.replay.device_replay import replay_sample
from r2d2_tpu.replay.structs import ReplaySpec, ReplayState, SampleBatch


class TrainState(flax.struct.PyTreeNode):
    params: Any
    target_params: Any          # == params when use_double is off (unused)
    opt_state: Any
    step: jnp.ndarray           # () int32
    key: jax.Array


def make_optimizer(optim: OptimConfig) -> optax.GradientTransformation:
    """clip_grad_norm + Adam, matching torch Adam semantics
    (ref worker.py:268,363: lr=1e-4, eps=1e-3 added outside the sqrt)."""
    return optax.chain(
        optax.clip_by_global_norm(optim.grad_norm),
        optax.adam(optim.lr, eps=optim.adam_eps),
    )


def create_train_state(key: jax.Array, net: NetworkApply, optim: OptimConfig
                       ) -> TrainState:
    pkey, skey = jax.random.split(key)
    params = net.init(pkey)
    tx = make_optimizer(optim)
    return TrainState(
        params=params,
        target_params=jax.tree_util.tree_map(jnp.copy, params),
        opt_state=tx.init(params),
        step=jnp.zeros((), jnp.int32),
        key=skey,
    )


def _decode_inputs(net: NetworkApply, spec: ReplaySpec, batch: SampleBatch,
                   use_pallas: bool,
                   nhwc: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """THE storage→network decode (one place for every unroll path): uint8
    frame rows → stacked normalized obs (B,T,H,W,K) (fused pallas kernel on
    TPU, jnp gather elsewhere — ops/pallas_kernels.py; out_height/out_width
    strip any exact-gather storage tile padding), action indices → one-hot
    (-1 encodes the null action as zeros). Decodes directly into the network's compute
    dtype: under the bf16 policy this skips materializing the 4x-larger f32
    obs intermediate that XLA would cast at the conv boundary anyway
    (PERF.md profile: that transpose+cast copy was ~2.5 ms/step)."""
    from r2d2_tpu.ops.pallas_kernels import stack_frames
    with jax.named_scope("obs_decode"):
        stacked = stack_frames(batch.obs, spec.seq_window, spec.frame_stack,
                               use_pallas=use_pallas,
                               out_dtype=net.module.compute_dtype,
                               out_height=spec.frame_height,
                               out_width=spec.frame_width, nhwc=nhwc)
        last_action = jax.nn.one_hot(batch.last_action, net.action_dim,
                                     dtype=jnp.float32)
    return stacked, last_action


def _unrolled_q(net: NetworkApply, spec: ReplaySpec, params,
                batch: SampleBatch, use_pallas: bool = False,
                nhwc: bool = False) -> jnp.ndarray:
    """Decode (see _decode_inputs) and unroll the full window from the
    stored hidden state. Returns (B, T, A) f32 Q-values."""
    stacked, last_action = _decode_inputs(net, spec, batch, use_pallas, nhwc)
    q, _ = net.module.apply(params, stacked, last_action, batch.hidden)
    return q


def make_loss_fn(net: NetworkApply, spec: ReplaySpec, optim: OptimConfig,
                 use_double: bool):
    """Returns loss(params, target_params, batch) -> (loss, aux). Pure —
    shared by the single-chip jit, the shard_map path, and the tests."""

    from r2d2_tpu.ops.pallas_kernels import (
        resolve_pallas_obs_decode, resolve_pallas_setting)
    use_pallas = resolve_pallas_obs_decode(optim.pallas_obs_decode)
    layout = str(optim.pallas_decode_layout).lower()
    if layout not in ("planar", "nhwc"):
        raise ValueError("optim.pallas_decode_layout must be 'planar' or "
                         f"'nhwc'; got {optim.pallas_decode_layout!r}")
    nhwc = layout == "nhwc"
    # double-DQN only: interleave the two unrolls' recurrent chains in one
    # scan (two sequential while-loops cannot overlap — see
    # models/network.py dual_sequence_q); identical math, parity-tested
    fused_dual = use_double and resolve_pallas_setting(
        optim.fused_double_unroll, "optim.fused_double_unroll")

    def loss_fn(params, target_params, batch: SampleBatch):
        if fused_dual:
            from r2d2_tpu.models.network import dual_sequence_q
            stacked, last_action = _decode_inputs(net, spec, batch,
                                                  use_pallas, nhwc)
            q_online, q_target_all = dual_sequence_q(
                net, params, target_params, stacked, last_action,
                batch.hidden, batch.hidden)
        else:
            q_online = _unrolled_q(net, spec, params, batch, use_pallas,
                                   nhwc)

        # the target unroll stays on the non-fused double path below, so
        # it is computed BEFORE entering the loss scope — its ops keep
        # their torso/lstm/head component scopes un-nested
        if use_double and not fused_dual:
            q_target_all = _unrolled_q(net, spec, target_params, batch,
                                       use_pallas, nhwc)

        # "loss" component scope (ISSUE 9): everything below is gathers
        # + masked reductions over the unrolled Q — cheap, but
        # attributable (telemetry/traceparse.py) rather than landing in
        # the trace's unattributed bucket
        with jax.named_scope("loss"):
            tpos = target_q_positions(batch.burn_in_steps,
                                      batch.learning_steps,
                                      batch.forward_steps, spec.learning,
                                      spec.forward)
            opos = online_q_positions(batch.burn_in_steps, spec.learning)
            mask = learning_step_mask(batch.learning_steps, spec.learning)

            # --- bootstrap value at t+n (no grad; ref worker.py:335-339) ---
            q_online_tn = jax.lax.stop_gradient(
                jnp.take_along_axis(q_online, tpos[:, :, None], axis=1))
            if use_double:
                a_star = jnp.argmax(q_online_tn, axis=-1)           # (B,L)
                q_target_all = jax.lax.stop_gradient(q_target_all)
                q_target_tn = jnp.take_along_axis(
                    q_target_all, tpos[:, :, None], axis=1)
                q_next = jnp.take_along_axis(
                    q_target_tn, a_star[:, :, None], axis=2)[:, :, 0]
            else:
                q_next = jnp.max(q_online_tn, axis=-1)              # (B,L)
            q_next = jax.lax.stop_gradient(q_next)

            target = value_rescale(
                batch.reward + batch.gamma * inverse_value_rescale(
                    q_next, optim.value_rescale_eps),
                optim.value_rescale_eps)                            # (B,L)

            # --- online Q(s_t, a_t) over learning steps (worker.py:344) ---
            q_learn = jnp.take_along_axis(q_online, opos[:, :, None], axis=1)
            q_chosen = jnp.take_along_axis(
                q_learn, batch.action[:, :, None], axis=2)[:, :, 0]  # (B,L)

            td = (target - q_chosen) * mask
            num_valid = jnp.maximum(jnp.sum(mask), 1.0)
            # IS-weighted 0.5*MSE over valid steps (ref worker.py:168,346)
            loss = 0.5 * jnp.sum(batch.is_weights[:, None] * td**2) / num_valid

            priorities = mixed_td_errors_masked(jnp.abs(td), mask,
                                                optim.priority_eta)
        aux = {
            "priorities": priorities,
            "mean_abs_td": jnp.sum(jnp.abs(td)) / num_valid,
            "mean_q": jnp.sum(q_chosen * mask) / num_valid,
            # raw per-element views for the learning-diagnostics histograms
            # (telemetry/learning.py); DCE'd when no LearningDiag consumes
            # them, so the plain step's program is unchanged
            "abs_td": jnp.abs(td),
            "mask": mask,
            "q_chosen": q_chosen,
        }
        return loss, aux

    return loss_fn


def make_learner_step(net: NetworkApply, spec: ReplaySpec, optim: OptimConfig,
                      use_double: bool, jit: bool = True, diag=None,
                      rdiag=None):
    """Build the fused step:

        step(train_state, replay_state) -> (train_state, replay_state, metrics)

    Both states are donated: the optimizer state, params, replay rings and
    priority tree update in place in HBM.

    ``diag`` (telemetry.LearningDiag or None): fuse the learning-dynamics
    diagnostics into the same program — device-side |TD|/priority/Q
    histograms, per-group grad norms, the non-finite guard, sample
    staleness stamps, and (every ``diag.interval`` steps, under lax.cond
    so the steady-state path is untouched) target-parameter distance and
    the stored-state ΔQ check. None compiles the pre-diagnostics program
    byte-for-byte — the telemetry.learning_enabled kill switch.

    ``rdiag`` (telemetry.ReplayDiag or None): the replay-observability
    pillar (ISSUE 10) fused the same way — the per-slot sample-count
    increment + lane-composition bincount every step, and the sum-tree
    health snapshot / eviction-accumulator read under lax.cond every
    ``rdiag.interval`` steps. Same kill-switch contract
    (telemetry.replay_diag_enabled).
    """
    loss_fn = make_loss_fn(net, spec, optim, use_double)
    tx = make_optimizer(optim)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step(train_state: TrainState, replay_state: ReplayState):
        key, sample_base = jax.random.split(train_state.key)
        # fold_in(0) matches the dp-sharded step's per-shard key derivation,
        # so a dp=1 mesh reproduces the single-chip sample stream exactly
        # (tested in tests/test_parallel.py)
        sample_key = jax.random.fold_in(sample_base, 0)
        # nested-jit calls trace inline into this one program; the
        # component scope covers the window gather + stratified descent
        # (tree_sample carries its own nested sum_tree scope)
        with jax.named_scope("replay_sample"):
            batch = replay_sample(spec, replay_state, sample_key)

        (loss, aux), grads = grad_fn(
            train_state.params, train_state.target_params, batch)
        with jax.named_scope("optimizer"):
            updates, opt_state = tx.update(grads, train_state.opt_state,
                                           train_state.params)
            params = optax.apply_updates(train_state.params, updates)

        # priority write-back, atomic with the sample (no staleness window)
        tree = tree_update(
            spec.tree_layers, replay_state.tree, spec.prio_exponent,
            aux["priorities"], batch.idxes)
        replay_state = replay_state.replace(tree=tree)

        # hard target sync every target_net_update_interval (ref worker.py:375-377);
        # 1-based counter like the reference's post-increment check
        new_step = train_state.step + 1
        if use_double:
            sync = (new_step % optim.target_net_update_interval) == 0
            target_params = jax.tree_util.tree_map(
                lambda p, t: jnp.where(sync, p, t), params,
                train_state.target_params)
        else:
            target_params = train_state.target_params

        grad_norm = optax.global_norm(grads)
        metrics = {
            "loss": loss,
            "mean_abs_td": aux["mean_abs_td"],
            "mean_q": aux["mean_q"],
            "grad_norm": grad_norm,
        }
        if diag is not None:
            from r2d2_tpu.telemetry.learning import fused_diagnostics
            # pre-update params: consistent with the batch just trained on
            metrics.update(fused_diagnostics(
                net, spec, diag, new_step, train_state.params,
                train_state.target_params, batch, aux, grads, loss,
                grad_norm, replay_state=replay_state))
        if rdiag is not None:
            # replay-pathology pillar (ISSUE 10): sample-count ring +
            # lane bincount every step, tree-health snapshot on the
            # rdiag.interval cadence — after the priority write-back so
            # the snapshot reflects this step's tree
            from r2d2_tpu.telemetry.replaydiag import fused_replay_diag
            replay_state, rd = fused_replay_diag(
                spec, rdiag, new_step, replay_state, batch)
            metrics.update(rd)
        train_state = train_state.replace(
            params=params, target_params=target_params,
            opt_state=opt_state, step=new_step, key=key)
        return train_state, replay_state, metrics

    if jit:
        return jax.jit(step, donate_argnums=(0, 1))
    return step


def make_external_batch_step(net: NetworkApply, spec: ReplaySpec,
                             optim: OptimConfig, use_double: bool,
                             diag=None, rdiag=None):
    """Train step for host-placement replay (config replay.placement="host"):
    the batch is sampled by HostReplay on the CPU (native C++ sum tree) and
    fed across the host boundary, mirroring the reference's architecture
    (/root/reference/worker.py:299-306) minus Ray. Returns
    (train_state, metrics) — priorities in metrics["priorities"] go back to
    the host tree asynchronously, guarded by HostReplay's staleness check.

    Sharding-agnostic by design: under committed (device_put) inputs the
    compiled program follows THEIR shardings, which is how the tensor-
    parallel path reuses this exact step (parallel/tensor_parallel.py).
    """
    loss_fn = make_loss_fn(net, spec, optim, use_double)
    tx = make_optimizer(optim)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step(train_state: TrainState, batch: SampleBatch):
        (loss, aux), grads = grad_fn(
            train_state.params, train_state.target_params, batch)
        with jax.named_scope("optimizer"):
            updates, opt_state = tx.update(grads, train_state.opt_state,
                                           train_state.params)
            params = optax.apply_updates(train_state.params, updates)

        new_step = train_state.step + 1
        if use_double:
            sync = (new_step % optim.target_net_update_interval) == 0
            target_params = jax.tree_util.tree_map(
                lambda p, t: jnp.where(sync, p, t), params,
                train_state.target_params)
        else:
            target_params = train_state.target_params

        grad_norm = optax.global_norm(grads)
        metrics = {
            "loss": loss,
            "priorities": aux["priorities"],
            "mean_abs_td": aux["mean_abs_td"],
            "mean_q": aux["mean_q"],
            "grad_norm": grad_norm,
        }
        if diag is not None and batch.weight_version is not None:
            # host placement: histograms / grad norms / staleness / the
            # non-finite guard; ΔQ needs the device-resident ring context
            # and reports NaN here (replay_state=None)
            from r2d2_tpu.telemetry.learning import fused_diagnostics
            metrics.update(fused_diagnostics(
                net, spec, diag, new_step, train_state.params,
                train_state.target_params, batch, aux, grads, loss,
                grad_norm, replay_state=None))
        if rdiag is not None and batch.lane is not None and rdiag.lanes > 0:
            # host placement carries only the lane-composition half of the
            # replay pillar in-graph; sum-tree health / eviction lifetimes
            # come from the HostReplay numpy twin at the metrics flush
            from r2d2_tpu.telemetry.replaydiag import lane_counts
            metrics["rd/lane_counts"] = lane_counts(batch.lane, rdiag.lanes)
        train_state = train_state.replace(
            params=params, target_params=target_params,
            opt_state=opt_state, step=new_step, key=train_state.key)
        return train_state, metrics

    # Donation audit (ISSUE 6 satellite): train_state donated like every
    # step factory; the BATCH deliberately is not — the host loop reads
    # batch.idxes AFTER the step for the async priority write-back
    # (learner_loop._host_step_once), so donating it would hand the
    # write-back a dead buffer. The batch is also the prefetch thread's
    # fresh device_put each step, so there is no ring to alias in place.
    return jax.jit(step, donate_argnums=0)


def make_multi_learner_step(net: NetworkApply, spec: ReplaySpec,
                            optim: OptimConfig, use_double: bool,
                            steps_per_dispatch: int, diag=None, rdiag=None):
    """K fused steps per dispatch via lax.scan — one host round-trip buys K
    training steps.

    The reference pays a Ray RPC and a GPU sync per step by construction
    (/root/reference/worker.py:303,348); on TPU the remaining per-step cost
    is the host dispatch itself, which this amortizes. Semantics are
    identical to K calls of the single step (same RNG chain, same per-step
    target-sync schedule via the carried step counter); only the host-side
    observation points (weight publish, checkpoint) coarsen to dispatch
    boundaries. Returns stacked (K,) metrics per dispatch (the learning
    diagnostics' histograms stack to (K, 64), ΔQ to (K,) with NaN on the
    non-interval steps — the scanned cond predicate rides the carried
    step counter, so interval steps fire inside the scan too).
    """
    inner = make_learner_step(net, spec, optim, use_double, jit=False,
                              diag=diag, rdiag=rdiag)

    def multi_step(train_state: TrainState, replay_state: ReplayState):
        def body(carry, _):
            ts, rs = carry
            ts, rs, m = inner(ts, rs)
            return (ts, rs), m

        (train_state, replay_state), metrics = jax.lax.scan(
            body, (train_state, replay_state), None, length=steps_per_dispatch)
        return train_state, replay_state, metrics

    return jax.jit(multi_step, donate_argnums=(0, 1))
